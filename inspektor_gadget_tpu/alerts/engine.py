"""Per-node alert evaluation: SketchSummary harvests in, transitions out.

One AlertEngine serves one gadget run. Every harvest calls observe(),
which evaluates each rule (per key — container/mntns slot for
anomaly_score, the whole stream otherwise) and drives a hysteresis +
debounce state machine per (rule, key):

    idle --cond true (past cooldown)--> pending --held `for`--> firing
    firing --cond false (past `clear`)--> resolved --> idle

A pending that loses its condition before `for` elapses never FIRES —
that's the debounce: one noisy window cannot flap an alert — but the
surfaced pending is retracted with a resolved event so every consumer
(stream, sinks, stores) drops it. After resolve, `cooldown` suppresses
re-triggering. Hysteresis:
while pending/firing, a rule with a `clear` level stays active until the
value crosses IT, not the trigger threshold.

Transitions (never steady states) emit AlertEvents to the configured
sinks, the process-wide active-alert store, the stream callback (the
agent pushes them as EV_ALERT messages), the telemetry registry
(`ig_alerts_firing{rule,severity}` gauge + transition counters), and the
flight recorder as facts — a crash dump shows what was firing.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import Callable, Iterable

from ..telemetry import counter, gauge
from ..telemetry.tracing import RECORDER
from .rules import AlertRule, summary_fields
from .store import ACTIVE

_tm_firing = gauge("ig_alerts_firing",
                   "currently-firing alert keys per rule",
                   ("rule", "severity"))
_tm_transitions = counter("ig_alerts_transitions_total",
                          "alert state transitions",
                          ("rule", "transition"))
_tm_evals = counter("ig_alerts_evals_total",
                    "rule evaluations against harvested summaries",
                    ("rule",))

PENDING, FIRING, RESOLVED = "pending", "firing", "resolved"
_IDLE = "idle"


@dataclasses.dataclass
class AlertEvent:
    """One lifecycle transition of one (rule, key) alert."""

    rule: str
    severity: str
    kind: str
    transition: str          # pending | firing | resolved
    key: str = ""            # offending slot, e.g. "mntns:4026531840"
    value: float = 0.0       # the triggering evaluation value
    threshold: float = 0.0
    node: str = ""
    gadget: str = ""
    run_id: str = ""
    trace_id: str = ""
    epoch: int = 0
    ts: float = 0.0          # wall clock
    nodes: tuple[str, ...] = ()  # cluster fold-in (GrpcRuntime dedup)

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["nodes"] = list(self.nodes)
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "AlertEvent":
        kw = {f.name: d[f.name] for f in dataclasses.fields(cls)
              if f.name in d}
        kw["nodes"] = tuple(kw.get("nodes") or ())
        return cls(**kw)


class _KeyState:
    __slots__ = ("state", "since", "last_resolved", "value")

    def __init__(self):
        self.state = _IDLE
        self.since = 0.0          # when the current condition run began
        self.last_resolved = None  # monotonic ts of last resolve
        self.value = 0.0


class _RuleState:
    """Per-rule evaluation memory: baseline window + top-k membership."""

    __slots__ = ("keys", "baseline", "prev_topk")

    def __init__(self, window: int):
        self.keys: dict[str, _KeyState] = {}
        self.baseline: deque[float] = deque(maxlen=window)
        self.prev_topk: set[int] | None = None


def _cmp(op: str, value: float, threshold: float) -> bool:
    if op == ">":
        return value > threshold
    if op == ">=":
        return value >= threshold
    if op == "<":
        return value < threshold
    return value <= threshold


class AlertEngine:
    def __init__(self, rules: Iterable[AlertRule], *, node: str = "",
                 gadget: str = "", run_id: str = "", trace_id: str = "",
                 sinks: Iterable = (),
                 on_event: Callable[[dict], None] | None = None,
                 dry_run: bool = False):
        """dry_run: evaluate + emit return values only — no telemetry, no
        flight-recorder facts, no store updates, no sinks (the `alerts
        test` replay path)."""
        self.rules = list(rules)
        self.node = node
        self.gadget = gadget
        self.run_id = run_id
        self.trace_id = trace_id
        self.sinks = list(sinks)
        self.on_event = on_event
        self.dry_run = dry_run
        # harvests arrive from the run thread today; the lock keeps the
        # per-key state machines correct if a second caller ever observes
        # concurrently (e.g. an operator serving parallel sub-streams)
        self._mu = threading.Lock()
        self._rs = {r.id: _RuleState(r.window) for r in self.rules}
        if dry_run:
            class _Nop:
                def inc(self, n=1.0): pass
                def dec(self, n=1.0): pass
            nop = _Nop()
            self._m_eval = {r.id: nop for r in self.rules}
            self._m_fire = {r.id: nop for r in self.rules}
        else:
            self._m_eval = {r.id: _tm_evals.labels(rule=r.id)
                            for r in self.rules}
            self._m_fire = {r.id: _tm_firing.labels(rule=r.id,
                                                    severity=r.severity)
                            for r in self.rules}

    # -- evaluation ---------------------------------------------------------

    def _evaluate(self, rule: AlertRule, rs: _RuleState, summary,
                  fields: dict[str, float]) -> list[tuple[str, float, bool]]:
        """→ [(key, value, triggered)]. Baseline-window kinds push their
        observation AFTER evaluating, so the current epoch never dilutes
        its own baseline."""
        if rule.kind == "threshold":
            v = fields[rule.field]
            return [("", v, _cmp(rule.op, v, rule.threshold))]
        if rule.kind == "ratio":
            denom = fields[rule.denom]
            if not denom:
                # no data is not a ratio of 0 — an op:'<' rule must not
                # trip on the empty first harvest
                return [("", 0.0, False)]
            v = fields[rule.field] / denom
            return [("", v, _cmp(rule.op, v, rule.threshold))]
        if rule.kind == "entropy_jump":
            v = fields["entropy_bits"]
            base = rs.baseline
            delta = abs(v - sum(base) / len(base)) if base else 0.0
            trig = bool(base) and delta > rule.threshold
            base.append(v)
            return [("", delta, trig)]
        if rule.kind == "cardinality_spike":
            v = fields["distinct"]
            base = rs.baseline
            mean = sum(base) / len(base) if base else 0.0
            trig = (len(base) > 0 and v > rule.factor * mean
                    and v >= rule.threshold)
            base.append(v)
            return [("", v, trig)]
        if rule.kind == "quantile_shift":
            # latency regression vs the rolling baseline of the watched
            # percentile (p50/p90/p99/p999). A 0.0 reading means the
            # quantile plane is off or the window saw no events — that is
            # "no observation", so it neither triggers nor enters the
            # baseline (an idle window must not halve the baseline mean
            # and turn the first busy window into a false shift)
            v = fields[rule.field]
            base = rs.baseline
            mean = sum(base) / len(base) if base else 0.0
            trig = (len(base) > 0 and mean > 0.0
                    and v > rule.factor * mean and v >= rule.threshold)
            if v > 0.0:
                base.append(v)
            return [("", v, trig)]
        if rule.kind == "pipeline_lag":
            # pipeline health regression vs the rolling baseline of the
            # watched stage signal (host_lag/device_lag/starved_ratio).
            # Same idle-window immunity as quantile_shift: a 0.0 reading
            # means the health plane is off or the stage saw no traffic —
            # "no observation" neither triggers nor enters the baseline
            v = fields[rule.field]
            base = rs.baseline
            mean = sum(base) / len(base) if base else 0.0
            trig = (len(base) > 0 and mean > 0.0
                    and v > rule.factor * mean and v >= rule.threshold)
            if v > 0.0:
                base.append(v)
            return [("", v, trig)]
        if rule.kind == "accuracy_drift":
            # accuracy audit plane (ISSUE 19): the ANALYTIC bound is the
            # baseline — no rolling window. Fires when the worst
            # observed_err/bound ratio exceeds `factor` (and the optional
            # absolute floor). 0.0 means nothing was audited (plane off,
            # idle window, empty sample): "no observation" neither
            # triggers nor counts as recovery data — the quantile_shift
            # idle-window immunity
            v = fields["accuracy_ratio"]
            trig = v > 0.0 and v > rule.factor and v >= rule.threshold
            return [("", v, trig)]
        if rule.kind == "heavy_hitter_churn":
            hh = (summary.get("heavy_hitters") if isinstance(summary, dict)
                  else summary.heavy_hitters) or []
            cur = {int(k) for k, _ in hh}
            prev = rs.prev_topk
            rs.prev_topk = cur
            # an EMPTY previous top-k is no baseline, not 100% churn —
            # traffic first appearing must not read as turnover
            if not prev or not cur:
                return [("", 0.0, False)]
            jaccard_dist = 1.0 - len(prev & cur) / len(prev | cur)
            return [("", jaccard_dist, jaccard_dist > rule.threshold)]
        if rule.kind == "heavy_flow":
            # one state machine per DECODED key (invertible plane): the
            # counts are exact recoveries from merged sketch state, so a
            # firing names the offending flow itself — keys that stop
            # decoding resolve via the vanished-key sweep below. A
            # decode can recover tens of thousands of keys (every
            # count-1 singleton under capacity), so only keys that
            # TRIGGER — or already hold live state (hysteresis/`clear`
            # must keep seeing values below the trigger) — get a state
            # machine; everything else is skipped before allocation
            from .rules import decoded_pairs
            out = []
            for k, c in sorted(decoded_pairs(summary)):
                key = f"key:0x{k:08x}"
                trig = _cmp(rule.op, float(c), rule.threshold)
                if trig or key in rs.keys:
                    out.append((key, float(c), trig))
            return out
        # anomaly_score: one state machine per container slot
        anomaly = (summary.get("anomaly") if isinstance(summary, dict)
                   else summary.anomaly) or {}
        return [(f"mntns:{ns}", float(score),
                 _cmp(rule.op, float(score), rule.threshold))
                for ns, score in sorted(anomaly.items())]

    # -- state machine ------------------------------------------------------

    def observe(self, summary, now: float | None = None) -> list[AlertEvent]:
        """Evaluate every rule against one harvest; returns the emitted
        transitions. `now` is injectable (monotonic seconds) for tests."""
        if now is None:
            now = time.monotonic()
        with self._mu:
            return self._observe_locked(summary, now)

    def _observe_locked(self, summary, now: float) -> list[AlertEvent]:
        fields = summary_fields(summary)
        epoch = (summary.get("epoch", 0) if isinstance(summary, dict)
                 else summary.epoch)
        out: list[AlertEvent] = []
        for rule in self.rules:
            rs = self._rs[rule.id]
            self._m_eval[rule.id].inc()
            results = self._evaluate(rule, rs, summary, fields)
            seen_keys = set()
            for key, value, triggered in results:
                seen_keys.add(key)
                out.extend(self._step(rule, rs, key, value, triggered,
                                      now, epoch))
            # keys that vanished from the summary (container gone) resolve
            # unconditionally — hysteresis can't hold a slot that stopped
            # existing, a firing alert must not linger on it, and a
            # vanished PENDING must not keep its `since` frozen (a slot
            # reused later would fire instantly, bypassing the debounce)
            for key, ks in list(rs.keys.items()):
                if key not in seen_keys and ks.state in (PENDING, FIRING):
                    if ks.state == FIRING:
                        self._m_fire[rule.id].dec()
                    ks.state = _IDLE
                    ks.last_resolved = now
                    ev = AlertEvent(
                        rule=rule.id, severity=rule.severity,
                        kind=rule.kind, transition=RESOLVED, key=key,
                        value=ks.value, threshold=rule.threshold,
                        node=self.node, gadget=self.gadget,
                        run_id=self.run_id, trace_id=self.trace_id,
                        epoch=epoch, ts=time.time())
                    out.append(ev)
                    self._deliver(ev)
        return out

    def _step(self, rule: AlertRule, rs: _RuleState, key: str, value: float,
              triggered: bool, now: float, epoch: int) -> list[AlertEvent]:
        ks = rs.keys.setdefault(key, _KeyState())
        ks.value = value
        events: list[AlertEvent] = []

        def emit(transition: str):
            ev = AlertEvent(
                rule=rule.id, severity=rule.severity, kind=rule.kind,
                transition=transition, key=key, value=value,
                threshold=rule.threshold, node=self.node,
                gadget=self.gadget, run_id=self.run_id,
                trace_id=self.trace_id, epoch=epoch, ts=time.time())
            events.append(ev)
            self._deliver(ev)

        if ks.state == _IDLE:
            if triggered:
                if (rule.cooldown_s > 0 and ks.last_resolved is not None
                        and now - ks.last_resolved < rule.cooldown_s):
                    return events  # suppressed: still cooling down
                ks.state = PENDING
                ks.since = now
                emit(PENDING)
                if rule.for_s == 0:
                    ks.state = FIRING
                    self._m_fire[rule.id].inc()
                    emit(FIRING)
            return events
        if ks.state == PENDING:
            if not self._still_active(rule, value, triggered):
                # debounced: the alert never FIRES (that's the flap
                # suppression), but the surfaced pending must be
                # retracted everywhere it went — stream, sinks, stores —
                # or remote consumers show it active forever
                ks.state = _IDLE
                ks.last_resolved = now
                emit(RESOLVED)
                return events
            if now - ks.since >= rule.for_s:
                ks.state = FIRING
                self._m_fire[rule.id].inc()
                emit(FIRING)
            return events
        # FIRING
        if not self._still_active(rule, value, triggered):
            ks.state = _IDLE
            ks.last_resolved = now
            self._m_fire[rule.id].dec()
            emit(RESOLVED)
        return events

    def _still_active(self, rule: AlertRule, value: float,
                      triggered: bool) -> bool:
        """Hysteresis: an active alert with a `clear` level only releases
        once the value crosses IT (direction follows the trigger op)."""
        if triggered:
            return True
        if rule.clear is None:
            return False
        if rule.op in (">", ">="):
            return value > rule.clear
        return value < rule.clear

    def _deliver(self, ev: AlertEvent) -> None:
        if self.dry_run:
            return
        _tm_transitions.labels(rule=ev.rule, transition=ev.transition).inc()
        # flight-recorder fact per (rule, key): the crash dump's answer to
        # "what was firing when this process died"
        RECORDER.set_fact(
            f"alert:{ev.rule}:{ev.key or '*'}",
            {"state": ev.transition, "value": round(ev.value, 6),
             "severity": ev.severity, "ts": ev.ts, "node": self.node})
        ACTIVE.update(ev, scope="node")
        for sink in self.sinks:
            try:
                sink.emit(ev)
            except Exception as e:  # noqa: BLE001 — one sink must not kill the rest
                import logging
                logging.getLogger("ig-tpu.alerts").warning(
                    "alert sink %r failed: %r", type(sink).__name__, e)
        if self.on_event is not None:
            self.on_event(ev.to_dict())

    def close(self, now: float | None = None) -> list[AlertEvent]:
        """End-of-run teardown: every still-pending/firing key resolves.
        Without this, a stopped run would leave its alerts active forever
        in the process-global table, the ig_alerts_firing gauge, and —
        because the resolves ride the stream before it ends — the
        client-side cluster fold-in."""
        if now is None:
            now = time.monotonic()
        out: list[AlertEvent] = []
        with self._mu:
            for rule in self.rules:
                rs = self._rs[rule.id]
                for key, ks in rs.keys.items():
                    if ks.state not in (PENDING, FIRING):
                        continue
                    if ks.state == FIRING:
                        self._m_fire[rule.id].dec()
                    ks.state = _IDLE
                    ks.last_resolved = now
                    ev = AlertEvent(
                        rule=rule.id, severity=rule.severity,
                        kind=rule.kind, transition=RESOLVED, key=key,
                        value=ks.value, threshold=rule.threshold,
                        node=self.node, gadget=self.gadget,
                        run_id=self.run_id, trace_id=self.trace_id,
                        ts=time.time())
                    out.append(ev)
                    self._deliver(ev)
        return out

    # -- introspection ------------------------------------------------------

    def firing(self) -> list[tuple[str, str]]:
        return [(rid, key)
                for rid, rs in self._rs.items()
                for key, ks in rs.keys.items() if ks.state == FIRING]
