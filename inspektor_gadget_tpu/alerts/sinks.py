"""Pluggable alert delivery.

The sink API is deliberately tiny — `emit(AlertEvent)` — so operators can
bolt on pagers/webhooks without touching the engine. Two built-ins:

- LogSink: one log line per transition on the run's logger (which the
  agent multiplexes onto the client stream, so remote transitions show up
  client-side even without the typed EV_ALERT path).
- WebhookFileSink: appends each transition as one JSON line to a file —
  the webhook stand-in tests and air-gapped deployments assert against
  (O_APPEND single-write, same crash-safety stance as the perf ledger).
"""

from __future__ import annotations

import json
import logging
import os
from typing import Protocol, runtime_checkable

from .engine import AlertEvent

_SEV_LEVEL = {"info": logging.INFO, "warning": logging.WARNING,
              "critical": logging.ERROR}


@runtime_checkable
class AlertSink(Protocol):
    def emit(self, event: AlertEvent) -> None: ...


class LogSink:
    def __init__(self, logger: logging.Logger | None = None):
        self.logger = logger or logging.getLogger("ig-tpu.alerts")

    def emit(self, event: AlertEvent) -> None:
        self.logger.log(
            _SEV_LEVEL.get(event.severity, logging.WARNING),
            "alert %s %s%s: value=%.6g threshold=%.6g [%s]",
            event.rule, event.transition,
            f" key={event.key}" if event.key else "",
            event.value, event.threshold, event.severity)


class WebhookFileSink:
    """JSON-lines delivery to a file path (the test/webhook stand-in).

    Each transition is one `json.dumps` + single O_APPEND write, so
    concurrent engines can share a file without interleaving lines.
    """

    def __init__(self, path: str):
        self.path = path

    def emit(self, event: AlertEvent) -> None:
        line = json.dumps(event.to_dict(), separators=(",", ":")) + "\n"
        fd = os.open(self.path, os.O_WRONLY | os.O_CREAT | os.O_APPEND,
                     0o644)
        try:
            os.write(fd, line.encode())
        finally:
            os.close(fd)

    @staticmethod
    def read(path: str) -> list[dict]:
        """Read back a sink file, tolerating a crash-truncated tail."""
        out: list[dict] = []
        try:
            with open(path, encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        out.append(json.loads(line))
                    except json.JSONDecodeError:
                        break  # torn tail — everything before it is good
        except OSError:
            pass
        return out
