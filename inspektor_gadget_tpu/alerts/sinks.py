"""Pluggable alert delivery.

The sink API is deliberately tiny — `emit(AlertEvent)` — so operators can
bolt on pagers/webhooks without touching the engine. Two built-ins:

- LogSink: one log line per transition on the run's logger (which the
  agent multiplexes onto the client stream, so remote transitions show up
  client-side even without the typed EV_ALERT path).
- WebhookFileSink: appends each transition as one JSON line to a file —
  the webhook stand-in tests and air-gapped deployments assert against
  (the shared utils/journal.py append + torn-tail-read discipline).
"""

from __future__ import annotations

import logging
from typing import Protocol, runtime_checkable

from ..utils.journal import append_line, read_jsonl
from .engine import AlertEvent

_SEV_LEVEL = {"info": logging.INFO, "warning": logging.WARNING,
              "critical": logging.ERROR}


@runtime_checkable
class AlertSink(Protocol):
    def emit(self, event: AlertEvent) -> None: ...


class LogSink:
    def __init__(self, logger: logging.Logger | None = None):
        self.logger = logger or logging.getLogger("ig-tpu.alerts")

    def emit(self, event: AlertEvent) -> None:
        self.logger.log(
            _SEV_LEVEL.get(event.severity, logging.WARNING),
            "alert %s %s%s: value=%.6g threshold=%.6g [%s]",
            event.rule, event.transition,
            f" key={event.key}" if event.key else "",
            event.value, event.threshold, event.severity)


class WebhookFileSink:
    """JSON-lines delivery to a file path (the test/webhook stand-in).

    Each transition is one `json.dumps` + single O_APPEND write, so
    concurrent engines can share a file without interleaving lines.
    """

    def __init__(self, path: str):
        self.path = path

    def emit(self, event: AlertEvent) -> None:
        append_line(self.path, event.to_dict())

    @staticmethod
    def read(path: str) -> list[dict]:
        """Read back a sink file, tolerating a crash-truncated tail."""
        try:
            return read_jsonl(path, on_bad="stop").records
        except OSError:
            return []
