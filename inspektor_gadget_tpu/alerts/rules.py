"""Declarative detector rules over harvested SketchSummary fields.

A rule document (YAML when pyyaml is importable, JSON always) is either a
list of rule mappings or `{"rules": [...]}`:

    rules:
      - id: entropy-jump
        kind: entropy_jump        # vs the mean of the last `window` epochs
        threshold: 1.0            # jump size, bits
        window: 3
        for: 50ms                 # debounce: condition must hold this long
        cooldown: 5s              # re-trigger suppression after resolve
        severity: warning
      - id: drop-ratio
        kind: ratio               # field / denom vs threshold
        field: drops
        denom: events
        op: ">"
        threshold: 0.01
        clear: 0.005              # hysteresis clear level
      - id: hot-container
        kind: anomaly_score       # one state machine per mntns slot
        threshold: 0.8
        severity: critical
      - id: latency-regression
        kind: quantile_shift      # pX vs the mean of the last `window`
        field: p99                # p50 | p90 | p99 | p999 (default p99)
        factor: 2.0               # degradation multiple that trips it
        threshold: 1000           # optional absolute floor (value units, ns)
        for: 100ms
        severity: warning

Everything is validated at LOAD time (ref: the round-5 stance that
failures must be loud): unknown keys, unknown fields, non-numeric
thresholds, duplicate ids, and empty documents all raise RuleError with
the offending rule named — a bad rule file fails the run before the first
harvest ever evaluates.
"""

from __future__ import annotations

import dataclasses
import json

from ..params.validators import parse_duration

KINDS = ("threshold", "ratio", "entropy_jump", "cardinality_spike",
         "heavy_hitter_churn", "anomaly_score", "heavy_flow",
         "quantile_shift", "pipeline_lag", "accuracy_drift")
SEVERITIES = ("info", "warning", "critical")
OPS = (">", ">=", "<", "<=")

# numeric summary fields a threshold/ratio rule may reference; the single
# access point (summary_fields) keeps rules and the harvest shape in sync
SUMMARY_FIELDS = ("events", "drops", "distinct", "entropy_bits",
                  "hh_top_count", "hh_top_share", "hh_count", "anomaly_max",
                  "decoded_count", "p50", "p90", "p99", "p999")

# the percentiles a harvest's quantile block carries (operators/tpusketch
# harvest → summary.quantiles); the only fields quantile_shift may watch
QUANTILE_FIELDS = ("p50", "p90", "p99", "p999")

# the pipeline health block's flat numeric fields (ISSUE 18, harvest →
# summary.pipeline); the only fields pipeline_lag may watch. host_lag /
# device_lag are the stage watermarks in seconds, starved_ratio the
# starved / (starved + saturated) stager-tick fraction
PIPELINE_FIELDS = ("host_lag", "device_lag", "starved_ratio")


def decoded_pairs(summary) -> list[tuple[int, int]]:
    """The invertible plane's decoded (key32, exact count) pairs from a
    SketchSummary or its wire-decoded dict shape — one access point, like
    summary_fields. Empty when the plane is off."""
    rows = (summary.get("decoded") if isinstance(summary, dict)
            else getattr(summary, "decoded", None)) or []
    return [(int(k), int(c)) for k, c in rows]


def summary_fields(summary) -> dict[str, float]:
    """Flatten a SketchSummary (or its wire-decoded dict) into the numeric
    field map rules evaluate against — the one place field access lives."""
    if isinstance(summary, dict):  # wire shape (agent/wire.decode_summary)
        events = float(summary.get("events", 0))
        drops = float(summary.get("drops", 0))
        distinct = float(summary.get("distinct", 0.0))
        entropy = float(summary.get("entropy", summary.get("entropy_bits", 0.0)))
        hh = summary.get("heavy_hitters") or []
        anomaly = summary.get("anomaly") or {}
        quantiles = summary.get("quantiles") or {}
    else:
        events = float(summary.events)
        drops = float(summary.drops)
        distinct = float(summary.distinct)
        entropy = float(summary.entropy_bits)
        hh = summary.heavy_hitters or []
        anomaly = summary.anomaly or {}
        quantiles = getattr(summary, "quantiles", None) or {}
    if isinstance(summary, dict):
        pipeline = summary.get("pipeline") or {}
        accuracy = summary.get("accuracy") or {}
    else:
        pipeline = getattr(summary, "pipeline", None) or {}
        accuracy = getattr(summary, "accuracy", None) or {}
    top_count = float(hh[0][1]) if hh else 0.0
    return {
        "events": events,
        "drops": drops,
        "distinct": distinct,
        "entropy_bits": entropy,
        "hh_top_count": top_count,
        "hh_top_share": top_count / events if events > 0 else 0.0,
        "hh_count": float(len(hh)),
        "anomaly_max": max((float(v) for v in anomaly.values()), default=0.0),
        "decoded_count": float(len(decoded_pairs(summary))),
        # latency quantile plane: 0.0 when the plane is off or the window
        # was empty — quantile_shift treats 0 as "no observation"
        **{p: float(quantiles.get(p, 0.0)) for p in QUANTILE_FIELDS},
        # pipeline health plane: 0.0 when absent — pipeline_lag shares
        # quantile_shift's idle-window immunity (0 never enters the
        # rolling baseline)
        "host_lag": float(pipeline.get("host_lag_s", 0.0)),
        "device_lag": float(pipeline.get("device_lag_s", 0.0)),
        "starved_ratio": float(pipeline.get("starved_ratio", 0.0)),
        # accuracy audit plane (ISSUE 19): worst observed_err / analytic
        # bound across audited stats. 0.0 when the plane is off or
        # nothing was audited — accuracy_drift reads 0 as "no
        # observation" (idle-window immunity), never as zero error
        "accuracy_ratio": float(accuracy.get("ratio", 0.0)),
    }


class RuleError(ValueError):
    """A rule document failed validation; message names the rule."""


@dataclasses.dataclass(frozen=True)
class AlertRule:
    id: str
    kind: str
    severity: str = "warning"
    field: str = ""          # threshold numerator (kind-implied otherwise)
    denom: str = ""          # ratio denominator
    op: str = ">"
    threshold: float = 0.0
    clear: float | None = None  # hysteresis: stays active until past this
    window: int = 3          # baseline epochs (jump/spike/churn kinds)
    factor: float = 2.0      # spike multiple vs the baseline mean
    for_s: float = 0.0       # min-duration before pending → firing
    cooldown_s: float = 0.0  # re-trigger suppression after resolve

    def describe(self) -> str:
        if self.kind == "threshold":
            cond = f"{self.field} {self.op} {self.threshold:g}"
        elif self.kind == "ratio":
            cond = f"{self.field}/{self.denom} {self.op} {self.threshold:g}"
        elif self.kind == "entropy_jump":
            cond = (f"|entropy_bits - mean(last {self.window})| "
                    f"> {self.threshold:g}b")
        elif self.kind == "cardinality_spike":
            cond = f"distinct > {self.factor:g}x mean(last {self.window})"
        elif self.kind == "heavy_hitter_churn":
            cond = f"topk jaccard-dist > {self.threshold:g}"
        elif self.kind == "heavy_flow":
            cond = (f"decoded[key] {self.op} {self.threshold:g} "
                    "(invertible plane, exact counts)")
        elif self.kind == "quantile_shift":
            cond = (f"{self.field} > {self.factor:g}x mean(last "
                    f"{self.window}) (latency quantile plane)")
        elif self.kind == "pipeline_lag":
            cond = (f"{self.field} > {self.factor:g}x mean(last "
                    f"{self.window}) (pipeline health plane)")
        elif self.kind == "accuracy_drift":
            cond = (f"observed_err > {self.factor:g}x analytic bound "
                    "(accuracy audit plane)")
        else:  # anomaly_score
            cond = f"anomaly[mntns] {self.op} {self.threshold:g}"
        return (f"{self.id}: {cond} for {self.for_s:g}s "
                f"cooldown {self.cooldown_s:g}s [{self.severity}]")


_KNOWN_KEYS = {"id", "kind", "severity", "field", "denom", "op", "threshold",
               "clear", "window", "factor", "for", "cooldown"}

# kinds with an implied field: a rule may omit it, or restate it exactly
_IMPLIED_FIELD = {"entropy_jump": "entropy_bits",
                  "cardinality_spike": "distinct"}


def _num(raw: dict, key: str, rid: str, default: float) -> float:
    v = raw.get(key, default)
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        raise RuleError(
            f"rule {rid!r}: {key} must be a number, got {v!r}")
    return float(v)


def _dur(raw: dict, key: str, rid: str) -> float:
    v = raw.get(key, 0)
    if isinstance(v, (int, float)) and not isinstance(v, bool):
        v = float(v)
    elif isinstance(v, str):
        try:
            v = parse_duration(v)
        except ValueError as e:
            raise RuleError(f"rule {rid!r}: bad {key} duration: {e}") from None
    else:
        raise RuleError(f"rule {rid!r}: {key} must be a duration, got {v!r}")
    if v < 0:
        raise RuleError(f"rule {rid!r}: {key} must be >= 0")
    return v


def _parse_rule(raw: object, index: int) -> AlertRule:
    if not isinstance(raw, dict):
        raise RuleError(f"rule #{index}: expected a mapping, got {raw!r}")
    rid = raw.get("id")
    if not rid or not isinstance(rid, str):
        raise RuleError(f"rule #{index}: missing or non-string 'id'")
    unknown = sorted(set(raw) - _KNOWN_KEYS)
    if unknown:
        raise RuleError(
            f"rule {rid!r}: unknown key(s) {unknown} "
            f"(known: {sorted(_KNOWN_KEYS)})")
    kind = raw.get("kind", "threshold")
    if kind not in KINDS:
        raise RuleError(f"rule {rid!r}: unknown kind {kind!r} "
                        f"(one of {list(KINDS)})")
    severity = raw.get("severity", "warning")
    if severity not in SEVERITIES:
        raise RuleError(f"rule {rid!r}: unknown severity {severity!r} "
                        f"(one of {list(SEVERITIES)})")
    op = raw.get("op", ">")
    if op not in OPS:
        raise RuleError(f"rule {rid!r}: unknown op {op!r} (one of {list(OPS)})")

    field = raw.get("field", "")
    if kind in _IMPLIED_FIELD:
        implied = _IMPLIED_FIELD[kind]
        if field and field != implied:
            raise RuleError(f"rule {rid!r}: kind {kind!r} always evaluates "
                            f"{implied!r}; remove field={field!r}")
        field = implied
    elif kind in ("threshold", "ratio"):
        if not field:
            raise RuleError(f"rule {rid!r}: kind {kind!r} requires 'field'")
        if field not in SUMMARY_FIELDS:
            raise RuleError(f"rule {rid!r}: unknown summary field {field!r} "
                            f"(one of {list(SUMMARY_FIELDS)})")
    elif kind == "heavy_flow" and field:
        raise RuleError(f"rule {rid!r}: kind 'heavy_flow' evaluates the "
                        f"decoded key counts; remove field={field!r}")
    elif kind == "quantile_shift":
        field = field or "p99"
        if field not in QUANTILE_FIELDS:
            raise RuleError(
                f"rule {rid!r}: quantile_shift watches one of "
                f"{list(QUANTILE_FIELDS)} (the harvest quantile block), "
                f"got field={field!r}")
    elif kind == "pipeline_lag":
        field = field or "host_lag"
        if field not in PIPELINE_FIELDS:
            raise RuleError(
                f"rule {rid!r}: pipeline_lag watches one of "
                f"{list(PIPELINE_FIELDS)} (the harvest pipeline block), "
                f"got field={field!r}")
    elif kind == "accuracy_drift":
        if field and field != "accuracy_ratio":
            raise RuleError(
                f"rule {rid!r}: kind 'accuracy_drift' always evaluates "
                f"the worst observed_err/bound ratio; remove "
                f"field={field!r}")
        field = "accuracy_ratio"

    denom = raw.get("denom", "")
    if kind == "ratio":
        if not denom:
            raise RuleError(f"rule {rid!r}: kind 'ratio' requires 'denom'")
        if denom not in SUMMARY_FIELDS:
            raise RuleError(f"rule {rid!r}: unknown denom field {denom!r} "
                            f"(one of {list(SUMMARY_FIELDS)})")
    elif denom:
        raise RuleError(f"rule {rid!r}: 'denom' only applies to kind 'ratio'")

    # cardinality_spike / quantile_shift / pipeline_lag / accuracy_drift
    # trigger on `factor` x baseline (for accuracy_drift the analytic
    # bound IS the baseline); their threshold is an optional absolute
    # floor. Every other kind requires one.
    if "threshold" not in raw and kind not in ("cardinality_spike",
                                               "quantile_shift",
                                               "pipeline_lag",
                                               "accuracy_drift"):
        raise RuleError(f"rule {rid!r}: missing 'threshold'")
    threshold = _num(raw, "threshold", rid, 0.0)
    clear = None
    if "clear" in raw:
        clear = _num(raw, "clear", rid, 0.0)
    window = raw.get("window", 3)
    if isinstance(window, bool) or not isinstance(window, int) or window < 1:
        raise RuleError(f"rule {rid!r}: window must be an int >= 1, "
                        f"got {window!r}")
    factor = _num(raw, "factor", rid, 2.0)
    if factor <= 0:
        raise RuleError(f"rule {rid!r}: factor must be > 0")
    if kind == "heavy_hitter_churn" and not 0.0 <= threshold <= 1.0:
        raise RuleError(f"rule {rid!r}: churn threshold is a jaccard "
                        f"distance in [0, 1], got {threshold!r}")

    return AlertRule(
        id=rid, kind=kind, severity=severity, field=field, denom=denom,
        op=op, threshold=threshold, clear=clear, window=window,
        factor=factor, for_s=_dur(raw, "for", rid),
        cooldown_s=_dur(raw, "cooldown", rid),
    )


def _parse_doc(text: str, source: str) -> object:
    text = text.strip()
    if not text:
        raise RuleError(f"{source}: empty rule document")
    try:
        import yaml
        try:
            return yaml.safe_load(text)
        except yaml.YAMLError as e:
            raise RuleError(f"{source}: unparseable YAML/JSON: {e}") from None
    except ImportError:
        try:
            return json.loads(text)
        except json.JSONDecodeError as e:
            raise RuleError(f"{source}: unparseable JSON "
                            f"(pyyaml not installed): {e}") from None


def load_rules(text: str, source: str = "<rules>") -> list[AlertRule]:
    """Parse + validate a rule document; raises RuleError on anything off."""
    doc = _parse_doc(text, source)
    if isinstance(doc, dict):
        extra = sorted(set(doc) - {"rules"})
        if extra:
            raise RuleError(f"{source}: unknown top-level key(s) {extra} "
                            f"(expected 'rules')")
        doc = doc.get("rules")
    if doc is None or doc == []:
        raise RuleError(f"{source}: no rules defined")
    if not isinstance(doc, list):
        raise RuleError(f"{source}: expected a list of rules, got "
                        f"{type(doc).__name__}")
    rules = [_parse_rule(r, i) for i, r in enumerate(doc)]
    seen: dict[str, int] = {}
    for i, r in enumerate(rules):
        if r.id in seen:
            raise RuleError(f"{source}: duplicate rule id {r.id!r} "
                            f"(rules #{seen[r.id]} and #{i})")
        seen[r.id] = i
    return rules


def load_rules_file(path: str) -> list[AlertRule]:
    try:
        with open(path, encoding="utf-8") as f:
            text = f.read()
    except OSError as e:
        raise RuleError(f"cannot read rule file {path!r}: {e}") from None
    return load_rules(text, source=path)
