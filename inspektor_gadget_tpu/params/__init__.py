"""Self-describing param system (ref: pkg/params, ~1295 LoC).

One typed flag/config system shared by gadgets, operators, and runtimes:
ParamDesc describes a parameter (key, alias, default, validator, type hint,
value hint); ParamDescs materialize into Params holding live values; a
Collection maps prefixes ("operator.<name>.", "runtime.") to Params and
round-trips through a flat string map over the wire — the exact catalog/gRPC
contract of the reference (params.go:42-96; serialization in
pkg/gadget-service/service.go:112-131).
"""

from .params import (
    Param,
    ParamDesc,
    ParamDescs,
    Params,
    Collection,
    TypeHint,
    ValueHint,
    ParamError,
)
from .validators import (
    validate_int_range,
    validate_one_of,
    validate_duration,
    parse_duration,
)

__all__ = [
    "Param",
    "ParamDesc",
    "ParamDescs",
    "Params",
    "Collection",
    "TypeHint",
    "ValueHint",
    "ParamError",
    "validate_int_range",
    "validate_one_of",
    "validate_duration",
    "parse_duration",
]
