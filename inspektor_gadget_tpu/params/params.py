"""Param descriptors, live params, and prefixed collections.

Reference contract (pkg/params/params.go:42-96):
  ParamDesc{Key, Alias, Title, DefaultValue, Description, IsMandatory,
            Tags, Validator, TypeHint, ValueHint, PossibleValues}
  ParamDescs.ToParams() → Params; Params.CopyFromMap/CopyToMap(prefix);
  Collection keyed by prefix. Values travel as strings and are parsed at the
  typed getters, so the same descriptor drives CLI flags, catalogs, and the
  wire format.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Any, Callable, Iterable, Iterator, Mapping

from .validators import parse_duration


class ParamError(ValueError):
    pass


class TypeHint(str, enum.Enum):
    STRING = "string"
    BOOL = "bool"
    INT = "int"
    UINT = "uint"
    FLOAT = "float"
    DURATION = "duration"
    IP = "ip"


class ValueHint(str, enum.Enum):
    """Frontend hints so clients can inject environment defaults
    (ref: ValueHint usage in cmd/kubectl-gadget/main.go:64-65)."""

    NODE_NAME = "node-name"
    K8S_NAMESPACE = "k8s-namespace"
    K8S_PODNAME = "k8s-podname"
    K8S_CONTAINERNAME = "k8s-containername"
    CONTAINER_NAME = "container-name"
    FILE_PATH = "file-path"
    MESH_AXIS = "mesh-axis"


_TRUE = {"true", "1", "yes", "on"}
_FALSE = {"false", "0", "no", "off", ""}


@dataclasses.dataclass
class ParamDesc:
    key: str
    default: str = ""
    description: str = ""
    alias: str = ""
    title: str = ""
    is_mandatory: bool = False
    tags: tuple[str, ...] = ()
    validator: Callable[[str], None] | None = None
    type_hint: TypeHint = TypeHint.STRING
    value_hint: ValueHint | None = None
    possible_values: tuple[str, ...] = ()

    def to_param(self) -> "Param":
        return Param(desc=self, value=self.default)


class Param:
    def __init__(self, desc: ParamDesc, value: str):
        self.desc = desc
        self._value = value

    @property
    def key(self) -> str:
        return self.desc.key

    @property
    def value(self) -> str:
        return self._value

    def set(self, value: str) -> None:
        if not isinstance(value, str):
            value = _to_wire(value)
        self.validate(value)
        self._value = value

    def validate(self, value: str | None = None) -> None:
        v = self._value if value is None else value
        if self.desc.is_mandatory and v == "":
            raise ParamError(f"param {self.key!r} is mandatory")
        if self.desc.possible_values and v not in self.desc.possible_values:
            raise ParamError(
                f"param {self.key!r}: {v!r} not in {list(self.desc.possible_values)}"
            )
        if self.desc.validator is not None and v != "":
            try:
                self.desc.validator(v)
            except ValueError as e:
                raise ParamError(f"param {self.key!r}: {e}") from None
        if v != "":
            try:
                _parse_typed(v, self.desc.type_hint)
            except ValueError as e:
                raise ParamError(f"param {self.key!r}: {e}") from None

    # typed getters -------------------------------------------------------

    def as_string(self) -> str:
        return self._value

    def as_bool(self) -> bool:
        v = self._value.lower()
        if v in _TRUE:
            return True
        if v in _FALSE:
            return False
        raise ParamError(f"param {self.key!r}: {self._value!r} is not a bool")

    def as_int(self) -> int:
        return int(self._value or "0")

    def as_uint(self) -> int:
        v = int(self._value or "0")
        if v < 0:
            raise ParamError(f"param {self.key!r}: {v} is negative")
        return v

    def as_float(self) -> float:
        return float(self._value or "0")

    def as_duration(self) -> float:
        return parse_duration(self._value) if self._value else 0.0

    def get(self) -> Any:
        return _parse_typed(self._value, self.desc.type_hint)


def _parse_typed(value: str, hint: TypeHint) -> Any:
    if hint == TypeHint.BOOL:
        v = value.lower()
        if v in _TRUE:
            return True
        if v in _FALSE:
            return False
        raise ValueError(f"{value!r} is not a bool")
    if hint == TypeHint.INT:
        return int(value or "0")
    if hint == TypeHint.UINT:
        v = int(value or "0")
        if v < 0:
            raise ValueError(f"{v} is negative")
        return v
    if hint == TypeHint.FLOAT:
        return float(value or "0")
    if hint == TypeHint.DURATION:
        return parse_duration(value) if value else 0.0
    return value


def _to_wire(v: Any) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    return str(v)


class ParamDescs(list):
    """Ordered list of ParamDesc (ref: params.go ParamDescs)."""

    def to_params(self) -> "Params":
        return Params(self)

    def get(self, key: str) -> ParamDesc:
        for d in self:
            if d.key == key:
                return d
        raise KeyError(key)


class Params:
    def __init__(self, descs: Iterable[ParamDesc] = ()):  # noqa: D107
        self._params: dict[str, Param] = {}
        for d in descs:
            self.add(d.to_param())

    def add(self, param: Param) -> None:
        self._params[param.key] = param

    def get(self, key: str) -> Param:
        try:
            return self._params[key]
        except KeyError:
            raise KeyError(f"unknown param {key!r}") from None

    def __contains__(self, key: str) -> bool:
        return key in self._params

    def __iter__(self) -> Iterator[Param]:
        return iter(self._params.values())

    def __len__(self) -> int:
        return len(self._params)

    def set(self, key: str, value: Any) -> None:
        self.get(key).set(value)

    def validate(self) -> None:
        for p in self._params.values():
            p.validate()

    # wire format ---------------------------------------------------------

    def copy_from_map(self, m: Mapping[str, str], prefix: str = "") -> None:
        """Apply values whose keys carry `prefix` (ref: params.go CopyFromMap;
        used server-side in gadget-service/service.go:112-131)."""
        for k, v in m.items():
            if k.startswith(prefix):
                key = k[len(prefix):]
                if key in self._params:
                    self._params[key].set(v)

    def copy_to_map(self, m: dict[str, str] | None = None, prefix: str = "") -> dict[str, str]:
        if m is None:
            m = {}
        for p in self._params.values():
            m[prefix + p.key] = p.value
        return m

    def to_descs_json(self) -> list[dict]:
        """Catalog serialization so remote clients can render flags
        (ref: pkg/runtime/catalog.go)."""
        return [
            {
                "key": p.desc.key,
                "default": p.desc.default,
                "description": p.desc.description,
                "alias": p.desc.alias,
                "isMandatory": p.desc.is_mandatory,
                "typeHint": p.desc.type_hint.value,
                "valueHint": p.desc.value_hint.value if p.desc.value_hint else "",
                "possibleValues": list(p.desc.possible_values),
                "tags": list(p.desc.tags),
            }
            for p in self._params.values()
        ]


def descs_from_json(items: list[dict]) -> ParamDescs:
    descs = ParamDescs()
    for it in items:
        descs.append(
            ParamDesc(
                key=it["key"],
                default=it.get("default", ""),
                description=it.get("description", ""),
                alias=it.get("alias", ""),
                is_mandatory=it.get("isMandatory", False),
                type_hint=TypeHint(it.get("typeHint", "string")),
                value_hint=ValueHint(it["valueHint"]) if it.get("valueHint") else None,
                possible_values=tuple(it.get("possibleValues", ())),
                tags=tuple(it.get("tags", ())),
            )
        )
    return descs


class Collection(dict):
    """prefix → Params (ref: params.go Collection; prefixes like
    "operator.localmanager.", "runtime.", "gadget.")."""

    def copy_from_map(self, m: Mapping[str, str]) -> None:
        for prefix, params in self.items():
            params.copy_from_map(m, prefix)

    def copy_to_map(self) -> dict[str, str]:
        out: dict[str, str] = {}
        for prefix, params in self.items():
            params.copy_to_map(out, prefix)
        return out

    def validate(self) -> None:
        for params in self.values():
            params.validate()
