"""Param validators (ref: pkg/params/validators.go:23-112)."""

from __future__ import annotations

import re
from typing import Callable, Sequence

_DURATION_RE = re.compile(r"(\d+(?:\.\d+)?)(ns|us|µs|ms|s|m|h)")
_DURATION_UNITS = {
    "ns": 1e-9, "us": 1e-6, "µs": 1e-6, "ms": 1e-3, "s": 1.0, "m": 60.0, "h": 3600.0,
}


def parse_duration(s: str) -> float:
    """Parse Go-style duration strings ("1m30s", "500ms", plain seconds)."""
    s = s.strip()
    if not s:
        raise ValueError("empty duration")
    try:
        return float(s)
    except ValueError:
        pass
    pos, total = 0, 0.0
    for m in _DURATION_RE.finditer(s):
        if m.start() != pos:
            raise ValueError(f"invalid duration {s!r}")
        total += float(m.group(1)) * _DURATION_UNITS[m.group(2)]
        pos = m.end()
    if pos != len(s):
        raise ValueError(f"invalid duration {s!r}")
    return total


def validate_int_range(lo: int | None = None, hi: int | None = None) -> Callable[[str], None]:
    def check(value: str) -> None:
        try:
            v = int(value)
        except ValueError:
            raise ValueError(f"{value!r} is not an integer") from None
        if lo is not None and v < lo:
            raise ValueError(f"{v} below minimum {lo}")
        if hi is not None and v > hi:
            raise ValueError(f"{v} above maximum {hi}")
    return check


def validate_one_of(choices: Sequence[str]) -> Callable[[str], None]:
    def check(value: str) -> None:
        if value not in choices:
            raise ValueError(f"{value!r} not one of {list(choices)}")
    return check


def validate_duration(value: str) -> None:
    parse_duration(value)
