"""Pipeline health plane: per-stage lag watermarks, backpressure and
starvation accounting for the ingest hot path.

The BENCH_r04 starvation gap (device plane eats 2.6B ev/s/chip, one host
thread supplies 130M) was only visible in one-off `bench run` sessions;
a live fleet had no per-stage lag, occupancy, or starvation signal at
all. This module is the standing instrument: every tpusketch run (and
the perf harness) registers a `PipelineStats`, the staging layer and the
operator ingest loop feed it batch-grain observations, and every surface
the fleet already looks at — harvest summaries, DumpState, Prometheus,
doctor, `ig-tpu fleet lag`, the `pipeline_lag` alert kind — reads its
`snapshot()`.

Vocabulary (docs/observability.md "Pipeline health & backpressure"):

- **Watermark**: each batch carries its oldest-event timestamp and its
  pop timestamp (sources/batch.py `oldest_ts`/`pop_ts`, stamped once per
  batch — zero per-event cost). Host lag = pop − oldest event; device
  lag = dispatch − pop. The *watermark* of a stage is the lag of the
  most recently dispatched batch.
- **Starved tick**: the H2D stager found its next ring slot empty — the
  device had already drained everything in flight; the host is the
  bottleneck (the BENCH_r04 regime).
- **Saturated tick**: the slot was still occupied — the host is a full
  ring depth ahead and must block on `block_until_ready` (the stall
  seconds are measured); the device is the bottleneck.
- **starved_ratio** = starved / (starved + saturated).

Lag *distributions* eat the quantile plane's own dogfood: each stage
feeds a host-side DDSketch twin (`LagSketch`, same bucket math as
`ops/quantiles.py`, pure numpy — this module must not import jax) so
summaries carry p50/p99 lag per stage, not just the last watermark.
"""

from __future__ import annotations

import math
import threading

import numpy as np

from .registry import counter, gauge

_tm_stage_lag = gauge(
    "ig_pipeline_stage_lag_seconds",
    "Lag watermark of the most recent batch through a pipeline stage",
    ("stage", "lane"))
_tm_starved_ratio = gauge(
    "ig_pipeline_starved_ratio",
    "starved / (starved + saturated) stager ticks — 1.0 means the device "
    "always drained the ring before the host refilled it (host-bound)")
_tm_backpressure = counter(
    "ig_pipeline_backpressure_total",
    "Ticks a pipeline stage blocked on a full downstream ring",
    ("stage",))
_tm_occupancy = gauge(
    "ig_pipeline_occupancy",
    "Occupied slots in a pipeline stage's ring",
    ("stage", "lane"))


class LagSketch:
    """Host-twin DDSketch over a single stage's lag samples.

    Same bucket geometry as ops/quantiles.py `dd_init` defaults (alpha
    1%, 2048 buckets, min_value 1e-9 — spans ns..~30s), replicated in
    scalar math because telemetry must stay importable without jax;
    tests/test_pipeline_health.py pins parity against `dd_quantile_np`.
    One sample per *batch*, so the per-add cost is a log and an int
    increment, nothing per event.
    """

    __slots__ = ("alpha", "min_value", "counts", "zeros", "total",
                 "watermark", "_inv_log_gamma", "_offset", "_gamma")

    def __init__(self, alpha: float = 0.01, n_buckets: int = 2048,
                 min_value: float = 1e-9):
        self.alpha = alpha
        self.min_value = min_value
        self._gamma = (1.0 + alpha) / (1.0 - alpha)
        self._inv_log_gamma = 1.0 / math.log(self._gamma)
        self._offset = math.log(min_value) * self._inv_log_gamma
        self.counts = np.zeros(n_buckets, np.int64)
        self.zeros = 0
        self.total = 0
        self.watermark = 0.0

    def add(self, v: float) -> None:
        self.watermark = float(v)
        self.total += 1
        if v <= 0.0:
            self.zeros += 1
            return
        idx = math.ceil(math.log(max(v, self.min_value))
                        * self._inv_log_gamma - self._offset)
        self.counts[min(max(idx, 0), len(self.counts) - 1)] += 1

    def quantile(self, q: float) -> float:
        """Value at quantile q — the dd_quantile_np formula on this
        sketch's own lanes (0.0 inside the zero bucket / empty sketch:
        a lag gauge must never surface NaN)."""
        if self.total <= 0:
            return 0.0
        rank = q * max(self.total - 1.0, 0.0)
        if rank < self.zeros:
            return 0.0
        cum = self.zeros + np.cumsum(self.counts.astype(np.float64))
        bucket = int((cum <= rank).sum())
        bucket = min(bucket, len(self.counts) - 1)
        log_gamma = math.log(self._gamma)
        offset = math.log(self.min_value) / log_gamma
        return float(2.0 * math.exp((bucket + offset) * log_gamma)
                     / (self._gamma + 1.0))


class PipelineStats:
    """Per-run pipeline health accounting, fed batch-grain from the
    staging layer (starved/saturated/stall/occupancy) and the operator
    ingest loop (watermarks) — registered like SketchStatsSource so live
    surfaces (DumpState, doctor, fleet lag) can find it by run."""

    def __init__(self, run_id: str, gadget: str = ""):
        self.run_id = run_id
        self.gadget = gadget
        self._mu = threading.Lock()
        self._stages: dict[tuple[str, int], LagSketch] = {}
        self.starved = 0
        self.saturated = 0
        self.stall_s = 0.0
        self.rounds = 0
        self._backpressure: dict[str, int] = {}
        self._occupancy: dict[str, float] = {}
        self._occ_touched: set[tuple[str, str]] = set()

    # -- observations (hot path: one lock + O(1) work per batch) ------------

    def note_lag(self, stage: str, lag_s: float, lane: int = 0) -> None:
        lag_s = max(float(lag_s), 0.0)
        with self._mu:
            sk = self._stages.get((stage, lane))
            if sk is None:
                sk = self._stages[(stage, lane)] = LagSketch()
            sk.add(lag_s)
        _tm_stage_lag.labels(stage=stage, lane=str(lane)).set(lag_s)

    def note_host_lag(self, lag_s: float, lane: int = 0) -> None:
        """pop − oldest event: how stale a batch already was when the
        host popped it off the capture ring."""
        self.note_lag("pop", lag_s, lane)

    def note_device_lag(self, lag_s: float, lane: int = 0) -> None:
        """dispatch − pop: how long a popped batch waited for staging +
        the device update to pick it up."""
        self.note_lag("h2d", lag_s, lane)

    def note_starved(self, lane: int = 0) -> None:
        with self._mu:
            self.starved += 1
            ratio = self.starved / (self.starved + self.saturated)
        _tm_starved_ratio.set(ratio)

    def note_saturated(self, stall_s: float, lane: int = 0,
                       stage: str = "h2d") -> None:
        with self._mu:
            self.saturated += 1
            self.stall_s += max(float(stall_s), 0.0)
            self._backpressure[stage] = self._backpressure.get(stage, 0) + 1
            ratio = self.starved / (self.starved + self.saturated)
        _tm_starved_ratio.set(ratio)
        _tm_backpressure.labels(stage=stage).inc()

    def note_backpressure(self, stage: str, n: int = 1) -> None:
        with self._mu:
            self._backpressure[stage] = self._backpressure.get(stage, 0) + n
        _tm_backpressure.labels(stage=stage).inc(n)

    def note_occupancy(self, stage: str, occupied: float,
                       lane: int = 0) -> None:
        with self._mu:
            self._occupancy[f"{stage}:{lane}"] = float(occupied)
            self._occ_touched.add((stage, str(lane)))
        _tm_occupancy.labels(stage=stage, lane=str(lane)).set(occupied)

    def note_round(self) -> None:
        with self._mu:
            self.rounds += 1

    # -- reads --------------------------------------------------------------

    def snapshot(self) -> dict:
        """The `pipeline` block harvest summaries / DumpState carry —
        plain JSON-able dict, stable keys (alert summary_fields and the
        fleet lag table key into it)."""
        with self._mu:
            stages: dict[str, dict] = {}
            for (stage, lane), sk in sorted(self._stages.items()):
                row = stages.setdefault(stage, {
                    "watermark_s": 0.0, "p50_s": 0.0, "p99_s": 0.0,
                    "count": 0})
                # multi-lane stages report the worst lane's view: the
                # fleet cares about the laggiest lane, not the average
                row["watermark_s"] = max(row["watermark_s"], sk.watermark)
                row["p50_s"] = max(row["p50_s"], sk.quantile(0.50))
                row["p99_s"] = max(row["p99_s"], sk.quantile(0.99))
                row["count"] += sk.total
            ticks = self.starved + self.saturated
            return {
                "stages": stages,
                "host_lag_s": stages.get("pop", {}).get("watermark_s", 0.0),
                "device_lag_s": stages.get("h2d", {}).get("watermark_s", 0.0),
                "starved": self.starved,
                "saturated": self.saturated,
                "starved_ratio": (self.starved / ticks) if ticks else 0.0,
                "stall_s": self.stall_s,
                "backpressure": dict(self._backpressure),
                "occupancy": dict(self._occupancy),
                "rounds": self.rounds,
            }

    # -- lifecycle ----------------------------------------------------------

    def register(self) -> None:
        with _live_mu:
            _live[self.run_id] = self

    def unregister(self) -> None:
        """Drop out of the live registry and return every gauge this run
        touched exactly to baseline (the PR-9/PR-11 teardown-accounting
        discipline: a stopped run leaves no residue on shared gauges)."""
        with _live_mu:
            _live.pop(self.run_id, None)
        with self._mu:
            touched = list(self._stages.keys())
            occ = list(self._occ_touched)
        for stage, lane in touched:
            _tm_stage_lag.labels(stage=stage, lane=str(lane)).set(0.0)
        for stage, lane in occ:
            _tm_occupancy.labels(stage=stage, lane=lane).set(0.0)
        _tm_starved_ratio.set(0.0)


_live_mu = threading.Lock()
_live: dict[str, PipelineStats] = {}


def live_stats() -> list[PipelineStats]:
    with _live_mu:
        return list(_live.values())
