"""Distributed tracing plane: spans, context propagation, flight recorder.

Where registry.py answers "how much / how often", this module answers
"where did THIS run spend its time". One process-wide Tracer keeps a
bounded ring of finished spans; W3C-style trace/span IDs propagate
client → gRPC fan-out → agent → operator chain → device plane (the
`traceparent` header rides the RunGadget request, agent/wire.py carries
it in stream metadata), so one gadget run is one trace across every
process it touched. Export is Chrome trace-event JSON ("traceEvents"),
loadable in Perfetto / chrome://tracing via `ig-tpu debug trace export`.

On top of the same ring sits the flight recorder: the last N spans, log
records (utils/logger.py attaches a handler into it), errors, and facts
(probed platform, node name). It is served through the agent's DumpState
RPC, the `ig-tpu debug flight-record` verb, and dumped to a file on
SIGTERM / unhandled crash — a wedged or killed process leaves evidence.

Cost model: spans are batch/RPC/run-grain like the metrics plane — never
per event. An unsampled trace (head sampling, decided once at mint time)
propagates context but records nothing.
"""

from __future__ import annotations

import contextvars
import dataclasses
import json
import logging
import os
import random
import signal
import sys
import threading
import time
import traceback
import uuid
from collections import deque
from typing import Any, Callable, Iterable

from .registry import counter

TRACEPARENT = "traceparent"  # W3C header key, also the wire metadata key

_tm_spans = counter("ig_trace_spans_total", "spans recorded into the ring")
_tm_evicted = counter("ig_trace_spans_evicted_total",
                      "spans evicted from the bounded ring")
_tm_unsampled = counter("ig_trace_spans_unsampled_total",
                        "spans skipped by head sampling")


@dataclasses.dataclass(frozen=True)
class SpanContext:
    """Propagatable identity of a span (W3C trace-context shaped)."""

    trace_id: str            # 32 lowercase hex
    span_id: str             # 16 lowercase hex
    sampled: bool = True

    def to_traceparent(self) -> str:
        return f"00-{self.trace_id}-{self.span_id}-" \
               f"{'01' if self.sampled else '00'}"


def parse_traceparent(value: str) -> SpanContext | None:
    """'00-<32hex>-<16hex>-<2hex>' → SpanContext; None on malformed input
    (a bad peer header degrades to a fresh trace, never an error)."""
    if not isinstance(value, str):
        return None
    parts = value.split("-")
    if len(parts) != 4:
        return None
    _ver, trace_id, span_id, flags = parts
    if len(trace_id) != 32 or len(span_id) != 16:
        return None
    try:
        int(trace_id, 16), int(span_id, 16), int(flags, 16)
    except ValueError:
        return None
    return SpanContext(trace_id=trace_id, span_id=span_id,
                       sampled=bool(int(flags, 16) & 1))


@dataclasses.dataclass
class SpanRecord:
    """One finished span as retained in the ring / exported."""

    name: str
    trace_id: str
    span_id: str
    parent_id: str
    start: float             # epoch seconds (cross-process alignable)
    duration: float          # seconds
    node: str = ""
    thread: str = ""
    error: str = ""
    attrs: dict[str, Any] = dataclasses.field(default_factory=dict)


class Span:
    """Context-manager span. Entering sets it as the thread's current
    span (children parent to it implicitly); exiting records it into the
    tracer ring — unless the trace is unsampled, in which case only the
    context propagates."""

    __slots__ = ("_tracer", "name", "context", "parent_id", "attrs",
                 "_t0", "_start", "_token", "_ambient", "error")

    def __init__(self, tracer: "Tracer", name: str, context: SpanContext,
                 parent_id: str, attrs: dict[str, Any] | None,
                 ambient: bool = True):
        self._tracer = tracer
        self.name = name
        self.context = context
        self.parent_id = parent_id
        self.attrs = dict(attrs) if attrs else {}
        self.error = ""
        self._t0 = 0.0
        self._start = 0.0
        self._ambient = ambient
        self._token: contextvars.Token | None = None

    def set_attr(self, key: str, value: Any) -> "Span":
        self.attrs[key] = value
        return self

    def __enter__(self) -> "Span":
        self._start = time.time()
        self._t0 = time.perf_counter()
        if self._ambient:
            self._token = self._tracer._current.set(self.context)
        return self

    def __exit__(self, exc_type, exc, _tb) -> None:
        dur = time.perf_counter() - self._t0
        if self._token is not None:
            self._tracer._current.reset(self._token)
            self._token = None
        if exc is not None:
            self.error = f"{type(exc).__name__}: {exc}"
        if self.context.sampled:
            self._tracer._record(SpanRecord(
                name=self.name, trace_id=self.context.trace_id,
                span_id=self.context.span_id, parent_id=self.parent_id,
                start=self._start, duration=dur, node=self._tracer.node,
                thread=threading.current_thread().name,
                error=self.error, attrs=self.attrs))
        else:
            _tm_unsampled.inc()


def _new_trace_id() -> str:
    return uuid.uuid4().hex


def _new_span_id() -> str:
    return uuid.uuid4().hex[:16]


class Tracer:
    """Process-wide span store: bounded ring retention, head sampling,
    contextvar-based implicit parenting within a thread."""

    def __init__(self, capacity: int = 4096, sample_rate: float = 1.0,
                 node: str = ""):
        self.capacity = int(capacity)
        self.sample_rate = float(sample_rate)
        self.node = node
        self._ring: deque[SpanRecord] = deque(maxlen=self.capacity)
        self._mu = threading.Lock()
        self._current: contextvars.ContextVar[SpanContext | None] = \
            contextvars.ContextVar("ig_current_span", default=None)

    # -- span creation ------------------------------------------------------

    def span(self, name: str, parent: SpanContext | None = None,
             attrs: dict[str, Any] | None = None,
             ambient: bool = True) -> Span:
        """Open a span. Parent resolution: explicit `parent` wins, else the
        thread's current span, else a new trace is minted (head-sampled).
        ambient=False skips the current-span contextvar entirely — for
        spans held open across yields, where a generator resumed on a
        different worker thread could otherwise strand a dead span as
        that thread's ambient parent forever."""
        if parent is None:
            parent = self._current.get()
        if parent is None:
            sampled = random.random() < self.sample_rate
            ctx = SpanContext(_new_trace_id(), _new_span_id(), sampled)
            return Span(self, name, ctx, parent_id="", attrs=attrs,
                        ambient=ambient)
        ctx = SpanContext(parent.trace_id, _new_span_id(), parent.sampled)
        return Span(self, name, ctx, parent_id=parent.span_id, attrs=attrs,
                    ambient=ambient)

    def start_trace(self, name: str,
                    attrs: dict[str, Any] | None = None) -> Span:
        """Mint a root span with a fresh trace ID (ignores any current)."""
        sampled = random.random() < self.sample_rate
        ctx = SpanContext(_new_trace_id(), _new_span_id(), sampled)
        return Span(self, name, ctx, parent_id="", attrs=attrs)

    def current_context(self) -> SpanContext | None:
        return self._current.get()

    # -- ring ---------------------------------------------------------------

    def _record(self, rec: SpanRecord) -> None:
        with self._mu:
            if len(self._ring) == self.capacity:
                _tm_evicted.inc()
            self._ring.append(rec)
        _tm_spans.inc()

    def records(self, trace_id: str | None = None) -> list[SpanRecord]:
        with self._mu:
            recs = list(self._ring)
        if trace_id is not None:
            recs = [r for r in recs if r.trace_id == trace_id]
        return recs

    def export(self, trace_id: str | None = None) -> list[dict]:
        return [dataclasses.asdict(r) for r in self.records(trace_id)]

    def reset(self) -> None:
        with self._mu:
            self._ring.clear()


# ---------------------------------------------------------------------------
# Chrome trace-event export (Perfetto / chrome://tracing loadable)
# ---------------------------------------------------------------------------

def export_chrome(spans: Iterable[dict | SpanRecord],
                  trace_id: str | None = None) -> dict:
    """Span records (dicts or SpanRecords, local and/or fetched from
    agents) → Chrome trace-event JSON. Each node becomes a synthetic
    `pid` with a process_name metadata row; threads map to stable small
    `tid`s; spans are complete ("X") events with ts/dur in µs and span
    identity in args."""
    norm: list[dict] = []
    seen: set[str] = set()
    for s in spans:
        d = dataclasses.asdict(s) if isinstance(s, SpanRecord) else dict(s)
        if trace_id is not None and d.get("trace_id") != trace_id:
            continue
        sid = d.get("span_id", "")
        if sid and sid in seen:  # client + agent rings may overlap in-process
            continue
        seen.add(sid)
        norm.append(d)
    norm.sort(key=lambda d: d.get("start", 0.0))

    pids: dict[str, int] = {}
    tids: dict[tuple[int, str], int] = {}
    events: list[dict] = []
    for d in norm:
        proc = d.get("node") or "client"
        pid = pids.setdefault(proc, len(pids) + 1)
        tkey = (pid, d.get("thread") or "main")
        tid = tids.setdefault(tkey, len(tids) + 1)
        args = {"trace_id": d.get("trace_id", ""),
                "span_id": d.get("span_id", ""),
                "parent_id": d.get("parent_id", "")}
        if d.get("error"):
            args["error"] = d["error"]
        args.update(d.get("attrs") or {})
        events.append({
            "name": d.get("name", "?"), "ph": "X", "cat": "ig-tpu",
            "ts": round(d.get("start", 0.0) * 1e6, 3),
            "dur": round(d.get("duration", 0.0) * 1e6, 3),
            "pid": pid, "tid": tid, "args": args,
        })
    meta = [{"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
             "args": {"name": proc}} for proc, pid in pids.items()]
    meta += [{"name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
              "args": {"name": tname}}
             for (pid, tname), tid in tids.items()]
    return {"traceEvents": meta + events, "displayTimeUnit": "ms"}


def device_annotation(name: str):
    """jax.profiler.TraceAnnotation(name) when JAX is importable, so
    device-plane spans line up with XLA activity in the same profiler
    timeline; a no-op context manager otherwise."""
    try:
        from jax.profiler import TraceAnnotation
        return TraceAnnotation(name)
    except Exception:  # noqa: BLE001 — tracing must never require jax
        import contextlib
        return contextlib.nullcontext()


# ---------------------------------------------------------------------------
# Flight recorder
# ---------------------------------------------------------------------------

class FlightRecorder:
    """Crash-safe black box: last N spans (shared with the tracer ring),
    log records, errors, and facts. snapshot() is the DumpState payload;
    dump() writes it as JSON for post-mortem reads."""

    def __init__(self, tracer: Tracer, max_logs: int = 512,
                 max_errors: int = 128):
        self.tracer = tracer
        self._logs: deque[dict] = deque(maxlen=max_logs)
        self._errors: deque[dict] = deque(maxlen=max_errors)
        self._facts: dict[str, Any] = {}
        self._mu = threading.Lock()

    def record_log(self, entry: dict) -> None:
        with self._mu:
            self._logs.append(entry)

    def record_error(self, kind: str, msg: str, tb: str = "") -> None:
        with self._mu:
            self._errors.append({"ts": time.time(), "kind": kind,
                                 "msg": msg, "traceback": tb})

    def set_fact(self, key: str, value: Any) -> None:
        with self._mu:
            self._facts[key] = value

    def snapshot(self, max_spans: int = 512) -> dict:
        # slice BEFORE converting: asdict over the whole 4096-ring on
        # every DumpState/crash dump would be ~8x the needed work
        spans = [dataclasses.asdict(r)
                 for r in self.tracer.records()[-max_spans:]]
        with self._mu:
            return {
                "pid": os.getpid(),
                "node": self.tracer.node,
                "time": time.time(),
                "facts": dict(self._facts),
                "spans": spans,
                "logs": list(self._logs),
                "errors": list(self._errors),
            }

    def dump(self, path: str, max_spans: int = 512) -> str:
        """Write the snapshot to `path` (best-effort atomically); returns
        the path. Never raises — the dump runs from crash/signal context
        where a second failure must not mask the first."""
        try:
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(self.snapshot(max_spans), f, default=str)
            os.replace(tmp, path)
        except OSError as e:
            logging.getLogger("ig-tpu.tracing").warning(
                "flight-record dump to %s failed: %s", path, e)
        return path

    def clear(self) -> None:
        with self._mu:
            self._logs.clear()
            self._errors.clear()


def load_dump(path: str) -> tuple[dict | None, str]:
    """Post-mortem read of a flight-recorder dump: (snapshot, "") or
    (None, why). Routed through the shared utils/journal tolerant read —
    a dump truncated by the very crash it documents (or a leftover
    .tmp from an interrupted atomic write) is reported, never raised."""
    from ..utils.journal import read_json_file
    doc, err = read_json_file(path)
    if doc is None:
        # an interrupted atomic dump leaves <path>.tmp.<pid>; the newest
        # one is the best surviving evidence. A tmp can vanish between
        # glob and stat (the dumper's os.replace landing) — never raise
        # from a helper whose contract is reported-not-raised
        import glob

        def _mtime(p: str) -> float:
            try:
                return os.path.getmtime(p)
            except OSError:
                return 0.0

        tmps = sorted(glob.glob(f"{path}.tmp.*"), key=_mtime)
        if tmps:
            doc2, err2 = read_json_file(tmps[-1])
            if doc2 is not None:
                return doc2, f"recovered from {tmps[-1]} ({err})"
        return None, err
    return doc, ""


class FlightRecorderHandler(logging.Handler):
    """logging.Handler feeding the flight recorder. Picks up `run_id` /
    `trace_id` attrs (StreamLogger threads them onto remote records) so
    flight-recorded log lines correlate with spans."""

    def __init__(self, recorder: FlightRecorder):
        super().__init__(level=logging.DEBUG)
        self.recorder = recorder

    def emit(self, record: logging.LogRecord) -> None:
        try:
            entry = {
                "ts": record.created,
                "level": record.levelname,
                "logger": record.name,
                "msg": record.getMessage(),
                "run_id": getattr(record, "run_id", ""),
                "trace_id": getattr(record, "trace_id", ""),
            }
            self.recorder.record_log(entry)
            if record.levelno >= logging.ERROR:
                tb = ""
                if record.exc_info and record.exc_info[2] is not None:
                    tb = "".join(traceback.format_exception(
                        *record.exc_info))[-2000:]
                self.recorder.record_error("log", entry["msg"], tb)
        except Exception:  # noqa: BLE001 — logging must never take down the app
            self.handleError(record)


def install_crash_handlers(path: str, *,
                           recorder: "FlightRecorder | None" = None,
                           signals: tuple[int, ...] = (signal.SIGTERM,),
                           ) -> Callable[[], None]:
    """Dump the flight record to `path` on unhandled exceptions (main
    thread + threading.excepthook) and on the given signals, then chain
    to the previous handler. Returns an uninstall function (tests)."""
    rec = recorder if recorder is not None else RECORDER

    prev_hook = sys.excepthook

    def hook(tp, val, tb):
        rec.record_error(tp.__name__, str(val),
                         "".join(traceback.format_exception(tp, val, tb))[-4000:])
        rec.dump(path)
        prev_hook(tp, val, tb)

    sys.excepthook = hook

    prev_thook = threading.excepthook

    def thook(args):
        rec.record_error(
            args.exc_type.__name__, str(args.exc_value),
            "".join(traceback.format_exception(
                args.exc_type, args.exc_value, args.exc_traceback))[-4000:])
        rec.dump(path)
        prev_thook(args)

    threading.excepthook = thook

    prev_sig: dict[int, Any] = {}
    for sig in signals:
        def handler(signum, frame, _sig=sig):
            rec.record_error("signal", f"terminated by signal {signum}")
            rec.dump(path)
            prev = prev_sig.get(_sig)
            if callable(prev):
                prev(signum, frame)
            elif prev == signal.SIG_IGN:
                return  # the signal was a no-op before; keep it one
            else:
                raise SystemExit(128 + signum)
        try:
            prev_sig[sig] = signal.signal(sig, handler)
        except ValueError:  # not the main thread: excepthooks still work
            pass

    def uninstall() -> None:
        sys.excepthook = prev_hook
        threading.excepthook = prev_thook
        for sig, prev in prev_sig.items():
            try:
                signal.signal(sig, prev)
            except (ValueError, TypeError):
                pass

    return uninstall


# The process-wide tracer + flight recorder every layer shares, tunable
# via env (capacity bounds the black box; sample<1 head-samples traces).
TRACER = Tracer(
    capacity=int(os.environ.get("IG_TRACE_CAPACITY", "4096")),
    sample_rate=float(os.environ.get("IG_TRACE_SAMPLE", "1.0")),
)
RECORDER = FlightRecorder(TRACER)

# every process that touches telemetry keeps its recent ig-tpu.* log
# records in the flight recorder (the "ig-tpu" root logger is the
# ancestor of every component logger in this tree)
_root = logging.getLogger("ig-tpu")
if not any(isinstance(h, FlightRecorderHandler) for h in _root.handlers):
    _root.addHandler(FlightRecorderHandler(RECORDER))
