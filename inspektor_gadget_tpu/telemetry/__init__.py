"""Framework-wide telemetry plane (metrics registry + spans + exposition).

Usage:
    from ..telemetry import counter, gauge, histogram
    _events = counter("ig_source_events_total", "events popped", ("gadget",))
    _events.labels(gadget="trace/exec").inc(batch.count)

    with histogram("ig_op_enrich_seconds").time():
        ...

Exposed via telemetry/http.py (Prometheus text over --metrics-addr), the
`top metrics` gadget, and snapshot() embedded in bench/doctor JSON.
"""

from .registry import (  # noqa: F401
    DEFAULT_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    MetricFamily,
    REGISTRY,
    Registry,
    Span,
    counter,
    gauge,
    histogram,
    render_prometheus,
    snapshot,
)
from .http import MetricsServer, parse_addr  # noqa: F401
from .tracing import (  # noqa: F401
    RECORDER,
    TRACER,
    FlightRecorder,
    FlightRecorderHandler,
    SpanContext,
    SpanRecord,
    Tracer,
    device_annotation,
    export_chrome,
    install_crash_handlers,
    parse_traceparent,
)
