"""Prometheus exposition over a tiny stdlib HTTP endpoint.

The agent opts in with --metrics-addr (off by default — the reference's
otel-metrics-listen-address contract): GET /metrics renders the process
registry in text format 0.0.4, GET /healthz answers a JSON liveness
document (status/uptime/scrape count — what a probe or a human curl
wants to know: is it up, since when, is anyone scraping it).
ThreadingHTTPServer on a daemon thread; scrapes never touch the gRPC
workers.
"""

from __future__ import annotations

import json
import socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .registry import REGISTRY, Registry


def parse_addr(addr: str) -> tuple[str, int]:
    """'host:port', '[v6]:port', ':port', or bare 'port' → (host, port)."""
    host, sep, port = addr.rpartition(":")
    if not sep:
        host, port = "", addr
    if host.startswith("[") and host.endswith("]"):
        host = host[1:-1]  # bracketed IPv6 literal
    try:
        return host or "0.0.0.0", int(port)
    except ValueError:
        raise ValueError(f"bad metrics address {addr!r}: "
                         "expected host:port or :port") from None


class MetricsServer:
    def __init__(self, addr: str, registry: Registry | None = None):
        self.host, self.port = parse_addr(addr)
        self.registry = registry if registry is not None else REGISTRY
        self._server: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None
        self._started_at = 0.0
        self.scrapes = 0  # /metrics GETs served since start()

    def start(self) -> "MetricsServer":
        registry = self.registry
        server = self
        self._started_at = time.monotonic()

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — stdlib handler contract
                if self.path.split("?", 1)[0] == "/metrics":
                    server.scrapes += 1
                    body = registry.render_prometheus().encode()
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                elif self.path.split("?", 1)[0] == "/healthz":
                    body = (json.dumps({
                        "status": "ok",
                        "uptime": round(
                            time.monotonic() - server._started_at, 3),
                        "scrapes": server.scrapes,
                    }) + "\n").encode()
                    ctype = "application/json"
                else:
                    self.send_error(404)
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # scrapes must not spam stderr
                pass

        class Server(ThreadingHTTPServer):
            # stdlib default is AF_INET-only; honor IPv6 literals
            address_family = (socket.AF_INET6 if ":" in self.host
                              else socket.AF_INET)

        self._server = Server((self.host, self.port), Handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]  # resolve port 0
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True, name="metrics-http")
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
