"""Lock-cheap metrics registry: Counter, Gauge, Histogram + span timers.

The framework's self-observability plane (the role pkg/bpfstats + the
OpenTelemetry exporter play for the reference): every layer — sources,
operator chain, tpusketch device plane, agent streams, gRPC fan-out —
records into one process-wide registry, exposed three ways: Prometheus
text format over HTTP (telemetry/http.py), the `top metrics` interval
gadget, and `snapshot()` embedded in bench/doctor JSON output.

Cost model: all increments are batch-grain (per EventBatch / per RPC /
per tick, never per event), so the per-sample lock is microscopic next to
the work being measured. Histograms use fixed log-scale buckets so bucket
search is a bisect over a small static tuple and two same-width
histograms are mergeable bucket-by-bucket.
"""

from __future__ import annotations

import threading
import time
from bisect import bisect_left
from typing import Any, Callable, Iterator

# default latency buckets: log2-spaced, 1µs → ~16.8s (13 + overflow)
DEFAULT_BUCKETS: tuple[float, ...] = tuple(
    (1 << i) * 1e-6 for i in range(0, 26, 2))


def _label_key(label_names: tuple[str, ...], kw: dict[str, Any]) -> tuple[str, ...]:
    if set(kw) != set(label_names):
        raise ValueError(
            f"labels {sorted(kw)} != declared {sorted(label_names)}")
    return tuple(str(kw[n]) for n in label_names)


def _escape(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def format_labels(label_names: tuple[str, ...],
                  values: tuple[str, ...]) -> str:
    """Prometheus label block, '' when unlabeled."""
    if not label_names:
        return ""
    inner = ",".join(f'{n}="{_escape(v)}"'
                     for n, v in zip(label_names, values))
    return "{" + inner + "}"


class Counter:
    """Monotonic counter child. inc() only; never decreases."""

    __slots__ = ("_value", "_lock")

    def __init__(self):
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError("counters only go up")
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        return self._value


class Gauge:
    """Settable gauge child; set_function defers the read to scrape time
    (queue depths, ages — values that exist rather than accumulate)."""

    __slots__ = ("_value", "_fn", "_lock")

    def __init__(self):
        self._value = 0.0
        self._fn: Callable[[], float] | None = None
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)
            self._fn = None

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    def dec(self, n: float = 1.0) -> None:
        self.inc(-n)

    def set_function(self, fn: Callable[[], float]) -> None:
        with self._lock:
            self._fn = fn

    @property
    def value(self) -> float:
        fn = self._fn
        if fn is not None:
            try:
                return float(fn())
            except Exception:  # noqa: BLE001 — a dead callback reads as 0
                return 0.0
        return self._value


class Histogram:
    """Fixed log-scale-bucket histogram child.

    counts[i] = observations <= bounds[i]; counts[-1] is the +Inf
    overflow. Rendering emits Prometheus cumulative buckets, _sum, _count.
    """

    __slots__ = ("bounds", "_counts", "_sum", "_lock")

    def __init__(self, bounds: tuple[float, ...] = DEFAULT_BUCKETS):
        self.bounds = tuple(float(b) for b in bounds)
        if list(self.bounds) != sorted(set(self.bounds)):
            raise ValueError("histogram bounds must be strictly increasing")
        self._counts = [0] * (len(self.bounds) + 1)
        self._sum = 0.0
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        i = bisect_left(self.bounds, v)
        with self._lock:
            self._counts[i] += 1
            self._sum += v

    def time(self) -> "Span":
        return Span(self)

    @property
    def count(self) -> int:
        return sum(self._counts)

    @property
    def sum(self) -> float:
        return self._sum

    def buckets(self) -> list[tuple[float, int]]:
        """Cumulative (le, count) pairs ending with (+Inf, total)."""
        out = []
        acc = 0
        with self._lock:
            counts = list(self._counts)
        for b, c in zip(self.bounds, counts):
            acc += c
            out.append((b, acc))
        out.append((float("inf"), acc + counts[-1]))
        return out


class Span:
    """Context-manager timer feeding a Histogram (pipeline span)."""

    __slots__ = ("_hist", "_t0")

    def __init__(self, hist: Histogram):
        self._hist = hist
        self._t0 = 0.0

    def __enter__(self) -> "Span":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        self._hist.observe(time.perf_counter() - self._t0)


_KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class MetricFamily:
    """name + kind + label names → children keyed by label values."""

    def __init__(self, name: str, kind: str, help: str = "",
                 label_names: tuple[str, ...] = (),
                 buckets: tuple[float, ...] | None = None):
        self.name = name
        self.kind = kind
        self.help = help
        self.label_names = tuple(label_names)
        self._buckets = buckets
        self._children: dict[tuple[str, ...], Any] = {}
        self._lock = threading.Lock()
        if not label_names:
            self._children[()] = self._new_child()

    def _new_child(self):
        if self.kind == "histogram":
            return Histogram(self._buckets or DEFAULT_BUCKETS)
        return _KINDS[self.kind]()

    def labels(self, **kw: Any):
        key = _label_key(self.label_names, kw)
        child = self._children.get(key)
        if child is None:
            with self._lock:
                child = self._children.setdefault(key, self._new_child())
        return child

    # unlabeled families proxy the single child for ergonomic call sites
    def inc(self, n: float = 1.0) -> None:
        self._children[()].inc(n)

    def set(self, v: float) -> None:
        self._children[()].set(v)

    def dec(self, n: float = 1.0) -> None:
        self._children[()].dec(n)

    def set_function(self, fn: Callable[[], float]) -> None:
        self._children[()].set_function(fn)

    def observe(self, v: float) -> None:
        self._children[()].observe(v)

    def time(self) -> Span:
        return self._children[()].time()

    @property
    def value(self) -> float:
        return self._children[()].value

    @property
    def total(self) -> float:
        """Sum over every child's value — the label-agnostic read for
        counter/gauge families (e.g. pool hits across all device lanes)."""
        with self._lock:
            return sum(c.value for c in self._children.values())

    @property
    def count(self) -> int:
        return self._children[()].count

    @property
    def sum(self) -> float:
        return self._children[()].sum

    def buckets(self) -> list[tuple[float, int]]:
        return self._children[()].buckets()

    def children(self) -> list[tuple[tuple[str, ...], Any]]:
        with self._lock:
            return sorted(self._children.items())


class Registry:
    """Process-wide metric store. counter/gauge/histogram are
    get-or-create (idempotent across modules registering the same name);
    a name re-registered with a different kind or label set raises."""

    def __init__(self):
        self._families: dict[str, MetricFamily] = {}
        self._lock = threading.Lock()

    def _get_or_create(self, name: str, kind: str, help: str,
                       labels: tuple[str, ...],
                       buckets: tuple[float, ...] | None = None) -> MetricFamily:
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = MetricFamily(name, kind, help, labels, buckets)
                self._families[name] = fam
                return fam
        if fam.kind != kind or fam.label_names != tuple(labels):
            raise ValueError(
                f"metric {name!r} already registered as {fam.kind}"
                f"{fam.label_names}, not {kind}{tuple(labels)}")
        if (kind == "histogram" and buckets is not None
                and tuple(buckets) != (fam._buckets or DEFAULT_BUCKETS)):
            raise ValueError(
                f"histogram {name!r} already registered with buckets "
                f"{fam._buckets or DEFAULT_BUCKETS}, not {tuple(buckets)}")
        return fam

    def counter(self, name: str, help: str = "",
                labels: tuple[str, ...] = ()) -> MetricFamily:
        return self._get_or_create(name, "counter", help, labels)

    def gauge(self, name: str, help: str = "",
              labels: tuple[str, ...] = ()) -> MetricFamily:
        return self._get_or_create(name, "gauge", help, labels)

    def histogram(self, name: str, help: str = "",
                  labels: tuple[str, ...] = (),
                  buckets: tuple[float, ...] | None = None) -> MetricFamily:
        return self._get_or_create(name, "histogram", help, labels, buckets)

    def families(self) -> list[MetricFamily]:
        with self._lock:
            return [self._families[n] for n in sorted(self._families)]

    def reset(self) -> None:
        """Test helper: drop every family."""
        with self._lock:
            self._families.clear()

    # -- exposition ---------------------------------------------------------

    def samples(self) -> Iterator[tuple[str, str, str, float]]:
        """Flat (sample_name, kind, label_block, value) stream, sorted by
        family name then label values — the deterministic walk snapshot()
        and the renderers share. Histograms flatten to _bucket/_sum/_count."""
        for fam in self.families():
            for key, child in fam.children():
                lbl = format_labels(fam.label_names, key)
                if fam.kind == "histogram":
                    for le, acc in child.buckets():
                        le_s = "+Inf" if le == float("inf") else repr(le)
                        blk = format_labels(fam.label_names + ("le",),
                                            key + (le_s,))
                        yield f"{fam.name}_bucket", fam.kind, blk, float(acc)
                    yield f"{fam.name}_sum", fam.kind, lbl, child.sum
                    yield f"{fam.name}_count", fam.kind, lbl, float(child.count)
                else:
                    yield fam.name, fam.kind, lbl, child.value

    def snapshot(self) -> dict[str, float]:
        """Deterministic flat map 'name{labels}' → value (JSON-embeddable;
        bench.py / doctor.py ride this into their output records)."""
        return {f"{name}{lbl}": value
                for name, _kind, lbl, value in self.samples()}

    def render_prometheus(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        lines: list[str] = []
        last_family = None
        for name, kind, lbl, value in self.samples():
            fam_name = name
            for suffix in ("_bucket", "_sum", "_count"):
                if kind == "histogram" and name.endswith(suffix):
                    fam_name = name[: -len(suffix)]
                    break
            if fam_name != last_family:
                fam = self._families.get(fam_name)
                if fam is not None and fam.help:
                    lines.append(f"# HELP {fam_name} {fam.help}")
                lines.append(f"# TYPE {fam_name} {kind}")
                last_family = fam_name
            if value == int(value) and abs(value) < 2**53:
                lines.append(f"{name}{lbl} {int(value)}")
            else:
                lines.append(f"{name}{lbl} {value}")
        return "\n".join(lines) + "\n"


# The process-wide default registry and module-level conveniences every
# instrumented layer uses.
REGISTRY = Registry()


def counter(name: str, help: str = "",
            labels: tuple[str, ...] = ()) -> MetricFamily:
    return REGISTRY.counter(name, help, labels)


def gauge(name: str, help: str = "",
          labels: tuple[str, ...] = ()) -> MetricFamily:
    return REGISTRY.gauge(name, help, labels)


def histogram(name: str, help: str = "", labels: tuple[str, ...] = (),
              buckets: tuple[float, ...] | None = None) -> MetricFamily:
    return REGISTRY.histogram(name, help, labels, buckets)


def snapshot() -> dict[str, float]:
    return REGISTRY.snapshot()


def render_prometheus() -> str:
    return REGISTRY.render_prometheus()
