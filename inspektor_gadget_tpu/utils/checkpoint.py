"""Sketch-state checkpoint/resume.

The reference has no ML-style checkpointing (SURVEY §5: closest analogues
are pinned BPF maps surviving daemon restarts and traceloop's retrospective
rings). This framework carries real device state — sketch bundles and the
anomaly scorer — so agents checkpoint it: host-offload the pytree, write
one .npz (arrays) + .json (treedef/aux), resume after restart with merge
semantics intact (a resumed bundle keeps absorbing; two checkpoints merge
via bundle_merge exactly like live state).
"""

from __future__ import annotations

import json
import os
import threading
from pathlib import Path

import jax
import numpy as np


def save_pytree(path: str | Path, tree) -> None:
    """Atomic save: a crash mid-write (the exact scenario resume exists
    for) must never leave a torn .npz that poisons the next start — write
    to temp names, then rename both."""
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    arrays = {f"leaf_{i}": np.asarray(x) for i, x in enumerate(leaves)}
    # the treedef travels INSIDE the archive so the checkpoint is one file
    # + one rename — a crash can never pair a new .npz with stale metadata
    arrays["__treedef__"] = np.frombuffer(
        str(treedef).encode(), dtype=np.uint8)
    # unique temp names: concurrent savers of the same key (checkpointer
    # thread vs run-teardown, or two runs sharing a key) must each write
    # their own file — interleaved writes into one shared .tmp would
    # install a torn archive, the exact failure atomicity is for
    tag = f".{os.getpid()}.{threading.get_ident()}.tmp"
    tmp_npz = path.with_suffix(f".npz{tag}")
    with open(tmp_npz, "wb") as f:
        np.savez_compressed(f, **arrays)
    os.replace(tmp_npz, path.with_suffix(".npz"))
    # sidecar kept for human inspection only; load trusts the archive
    try:
        path.with_suffix(".json").write_text(json.dumps({
            "n_leaves": len(leaves),
            "treedef": str(treedef),
        }))
    except OSError:
        pass


def load_pytree(path: str | Path, like):
    """Restore into the structure of `like` (same config/shapes). The
    saved treedef string must match `like`'s — leaf count alone can't
    tell a bundle from a scorer with the same number of arrays, and a
    silent structure swap corrupts resumed state."""
    path = Path(path)
    saved_treedef = None
    with np.load(str(path.with_suffix(".npz"))) as z:
        if "__treedef__" in z.files:
            saved_treedef = bytes(z["__treedef__"]).decode()
        n = len([k for k in z.files if k.startswith("leaf_")])
        leaves = [z[f"leaf_{i}"] for i in range(n)]
    like_leaves, treedef = jax.tree_util.tree_flatten(like)
    if saved_treedef is None:  # legacy checkpoints: sidecar metadata
        meta_path = path.with_suffix(".json")
        if meta_path.exists():
            saved_treedef = json.loads(meta_path.read_text()).get("treedef")
    if saved_treedef is not None and saved_treedef != str(treedef):
        raise ValueError(
            f"checkpoint structure mismatch:\n  saved: {saved_treedef}\n"
            f"  expected: {treedef}")
    if len(leaves) != len(like_leaves):
        raise ValueError(
            f"checkpoint has {len(leaves)} leaves, expected {len(like_leaves)}")
    import jax.numpy as jnp
    restored = [jnp.asarray(a) for a in leaves]
    return jax.tree_util.tree_unflatten(treedef, restored)
