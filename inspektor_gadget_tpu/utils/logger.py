"""Leveled logger facade (ref: pkg/logger/logger.go, 191 LoC).

A thin contract over stdlib logging so components depend on the facade, not
a backend — the role the reference's Logger interface plays over logrus.
The gRPC transport encodes severity in the high bits of the event type
(agent/wire.py EV_LOG_SHIFT; ref grpc-runtime.go:326-328), so remote log
records multiplex into the event stream; StreamLogger is the server-side
adapter that does that encoding and threads run/trace IDs into the stream
header so client-side lines correlate with spans. Every ig-tpu.* record
also lands in the process flight recorder (telemetry/tracing.py attaches
its handler to the "ig-tpu" root logger).
"""

from __future__ import annotations

import logging
from typing import Callable

# severity levels mirroring the reference's (logrus) ordering
PANIC, FATAL, ERROR, WARN, INFO, DEBUG, TRACE = range(7)

_TO_STD = {
    PANIC: logging.CRITICAL, FATAL: logging.CRITICAL, ERROR: logging.ERROR,
    WARN: logging.WARNING, INFO: logging.INFO, DEBUG: logging.DEBUG,
    TRACE: logging.DEBUG,
}


def std_from_severity(sev: int) -> int:
    """Reference severity (wire type bits) → stdlib levelno. Exact
    inverse of severity_from_std: PANIC/FATAL→CRITICAL, ERROR→ERROR,
    WARN→WARNING, INFO→INFO, DEBUG/TRACE→DEBUG."""
    return min(logging.CRITICAL, max(logging.DEBUG, 60 - sev * 10))


def severity_from_std(levelno: int) -> int:
    """stdlib levelno → reference severity (for the wire's type bits)."""
    if levelno >= logging.CRITICAL:
        return FATAL
    if levelno >= logging.ERROR:
        return ERROR
    if levelno >= logging.WARNING:
        return WARN
    if levelno >= logging.INFO:
        return INFO
    return DEBUG


def get_logger(name: str = "ig-tpu", level: int = INFO) -> logging.Logger:
    """Get a component logger. The level is only applied to a logger that
    has never been configured (level NOTSET): setting it unconditionally
    made the LAST caller win across every component sharing the name —
    a tpusketch import could silence the agent mid-flight."""
    log = logging.getLogger(name)
    if log.level == logging.NOTSET:
        log.setLevel(_TO_STD[level])
    return log


class StreamLogger:
    """Adapter publishing log records into a gadget event stream with
    severity-in-type encoding (ref: pkg/gadget-service/logger.go). The
    stream header carries run_id/trace_id so the client can correlate a
    remote log line with the spans of the run that produced it."""

    def __init__(self, push: Callable[[int, dict, bytes], None],
                 shift: int = 16, run_id: str = "", trace_id: str = ""):
        self._push = push
        self._shift = shift
        self.run_id = run_id
        self.trace_id = trace_id

    def log(self, severity: int, msg: str) -> None:
        header: dict = {}
        if self.run_id:
            header["run_id"] = self.run_id
        if self.trace_id:
            header["trace_id"] = self.trace_id
        self._push(severity << self._shift, header,
                   msg.encode("utf-8", "replace"))

    def error(self, msg: str) -> None:
        self.log(ERROR, msg)

    def warn(self, msg: str) -> None:
        self.log(WARN, msg)

    def info(self, msg: str) -> None:
        self.log(INFO, msg)

    def debug(self, msg: str) -> None:
        self.log(DEBUG, msg)


class StreamLogHandler(logging.Handler):
    """stdlib handler forwarding a run's logger records into its event
    stream via a StreamLogger (attached per run by agent/service.py, so
    ctx.logger warnings reach the remote client)."""

    def __init__(self, stream_logger: StreamLogger,
                 level: int = logging.INFO):
        super().__init__(level=level)
        self._sl = stream_logger

    def emit(self, record: logging.LogRecord) -> None:
        try:
            self._sl.log(severity_from_std(record.levelno),
                         record.getMessage())
        except Exception:  # noqa: BLE001 — logging must never kill the stream
            self.handleError(record)
