"""Leveled logger facade (ref: pkg/logger/logger.go, 191 LoC).

A thin contract over stdlib logging so components depend on the facade, not
a backend — the role the reference's Logger interface plays over logrus.
The gRPC transport encodes severity in the high bits of the event type
(agent/wire.py EV_LOG_SHIFT; ref grpc-runtime.go:326-328), so remote log
records multiplex into the event stream, and StreamLogger here is the
server-side adapter that does that encoding.
"""

from __future__ import annotations

import logging
from typing import Callable

# severity levels mirroring the reference's (logrus) ordering
PANIC, FATAL, ERROR, WARN, INFO, DEBUG, TRACE = range(7)

_TO_STD = {
    PANIC: logging.CRITICAL, FATAL: logging.CRITICAL, ERROR: logging.ERROR,
    WARN: logging.WARNING, INFO: logging.INFO, DEBUG: logging.DEBUG,
    TRACE: logging.DEBUG,
}


def get_logger(name: str = "ig-tpu", level: int = INFO) -> logging.Logger:
    log = logging.getLogger(name)
    log.setLevel(_TO_STD[level])
    return log


class StreamLogger:
    """Adapter publishing log records into a gadget event stream with
    severity-in-type encoding (ref: pkg/gadget-service/logger.go)."""

    def __init__(self, push: Callable[[int, bytes], None], shift: int = 16):
        self._push = push
        self._shift = shift

    def log(self, severity: int, msg: str) -> None:
        self._push(severity << self._shift, msg.encode("utf-8", "replace"))

    def error(self, msg: str) -> None:
        self.log(ERROR, msg)

    def warn(self, msg: str) -> None:
        self.log(WARN, msg)

    def info(self, msg: str) -> None:
        self.log(INFO, msg)

    def debug(self, msg: str) -> None:
        self.log(DEBUG, msg)
