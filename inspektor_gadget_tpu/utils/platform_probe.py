"""Bounded-time accelerator acquisition (VERDICT hole #1).

The environment's PJRT plugin can hang *indefinitely* inside backend
init when the device tunnel is down — and it registers before env vars
are read, so only `jax.config.update("jax_platforms", ...)` before
first backend use avoids it (bench.py documents the same dance). Any
process that will touch the device plane (the agent, bench) therefore
asks this module FIRST: `acquire_platform("auto")` probes the backend
under a hard time bound and, on timeout or error, pins this process to
CPU with a logged + counted fallback instead of wedging at first use.

The probe itself runs in a subprocess (a hung in-process probe thread
would poison jax's backend-init lock for the whole process); a daemon
thread supervises it so even a wedged subprocess spawn can't block the
caller past `timeout`. The outcome lands in the telemetry registry, the
flight recorder, and doctor output.
"""

from __future__ import annotations

import dataclasses
import json
import os
import subprocess
import sys
import threading
import time
from typing import Callable

from ..telemetry.registry import counter, gauge
from ..telemetry.tracing import RECORDER, TRACER
from .logger import get_logger

DEFAULT_PROBE_TIMEOUT = float(os.environ.get("IG_PLATFORM_PROBE_TIMEOUT",
                                             "20"))
DEFAULT_PROBE_ATTEMPTS = int(os.environ.get("IG_PLATFORM_PROBE_ATTEMPTS",
                                            "3"))
DEFAULT_PROBE_HORIZON = float(os.environ.get("IG_PLATFORM_PROBE_HORIZON",
                                             "60"))

log = get_logger("ig-tpu.platform")

_tm_probes = counter("ig_platform_probe_total",
                     "device platform probes by outcome", ("outcome",))
_tm_fallbacks = counter("ig_platform_fallbacks_total",
                        "probe failures degraded to the CPU backend")
_tm_info = gauge("ig_platform_info", "acquired device platform (1=current)",
                 ("platform",))
_tm_degraded = gauge("ig_platform_degraded",
                     "1 when the process degraded to CPU after a failed "
                     "device probe")


@dataclasses.dataclass
class ProbeResult:
    ok: bool
    platform: str
    detail: str
    elapsed: float


# last acquire_platform outcome, for doctor/flight-record rendering
_last_acquire: dict | None = None
_mu = threading.Lock()


def last_acquire() -> dict | None:
    with _mu:
        return dict(_last_acquire) if _last_acquire else None


def _subprocess_probe(timeout: float) -> ProbeResult:
    """Touch the backend in a child process; the parent's timeout is the
    safety net a hanging PJRT init cannot escape."""
    code = ("import jax, json, sys; "
            "sys.stdout.write(json.dumps("
            "{'platform': jax.devices()[0].platform}))")
    t0 = time.perf_counter()
    try:
        p = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True, timeout=timeout)
    except subprocess.TimeoutExpired:
        return ProbeResult(False, "", f"probe timed out after {timeout:.0f}s",
                           time.perf_counter() - t0)
    except OSError as e:
        return ProbeResult(False, "", f"probe spawn failed: {e}",
                           time.perf_counter() - t0)
    elapsed = time.perf_counter() - t0
    if p.returncode != 0:
        tail = (p.stderr or p.stdout or "").strip().splitlines()[-2:]
        return ProbeResult(False, "", "probe rc=%d: %s"
                           % (p.returncode, " | ".join(tail)), elapsed)
    try:
        platform = json.loads(p.stdout.strip().splitlines()[-1])["platform"]
    except (ValueError, KeyError, IndexError):
        return ProbeResult(False, "", "probe produced no JSON", elapsed)
    return ProbeResult(True, platform, f"backend ok in {elapsed:.1f}s",
                       elapsed)


def probe_device_platform(
    timeout: float = DEFAULT_PROBE_TIMEOUT,
    probe_fn: Callable[[], ProbeResult] | None = None,
) -> ProbeResult:
    """Run the probe in a daemon thread and wait at most `timeout`. The
    thread bound holds even if `probe_fn` itself ignores deadlines (the
    regression the tests pin: an unreachable TPU must degrade within the
    timeout, never hang the caller)."""
    fn = probe_fn or (lambda: _subprocess_probe(timeout))
    box: list[ProbeResult] = []

    def run():
        try:
            box.append(fn())
        except Exception as e:  # noqa: BLE001 — a broken probe is a failed probe
            box.append(ProbeResult(False, "", f"probe raised: {e!r}", 0.0))

    t0 = time.perf_counter()
    t = threading.Thread(target=run, daemon=True, name="platform-probe")
    t.start()
    t.join(timeout)
    if not box:
        return ProbeResult(False, "", f"probe timed out after {timeout:.0f}s",
                           time.perf_counter() - t0)
    return box[0]


def _pin_cpu() -> None:
    try:
        import jax
        jax.config.update("jax_platforms", "cpu")
    except Exception as e:  # noqa: BLE001 — no jax at all is already "cpu"
        log.debug("could not pin jax to cpu: %r", e)


def acquire_platform(
    requested: str = "auto",
    timeout: float = DEFAULT_PROBE_TIMEOUT,
    probe_fn: Callable[[], ProbeResult] | None = None,
) -> dict:
    """Resolve `--platform auto|tpu|cpu` before first device use.

    cpu: pin to CPU, no probe. auto/tpu: bounded probe; an accelerator
    answer wins, a cpu answer just means no accelerator on this host,
    and a timeout/error degrades to CPU (logged, counted, recorded)
    instead of hanging forever at first device use.
    Returns {requested, platform, degraded, detail, elapsed}.
    """
    if requested not in ("auto", "tpu", "cpu"):
        raise ValueError(f"platform must be auto|tpu|cpu, not {requested!r}")
    with TRACER.span("platform/acquire", attrs={"requested": requested}):
        if requested == "cpu":
            _pin_cpu()
            out = {"requested": requested, "platform": "cpu",
                   "degraded": False, "detail": "cpu requested", "elapsed": 0.0}
            _tm_probes.labels(outcome="skipped").inc()
        else:
            res = probe_device_platform(timeout, probe_fn)
            if res.ok and res.platform != "cpu":
                _tm_probes.labels(outcome="ok").inc()
                out = {"requested": requested, "platform": res.platform,
                       "degraded": False, "detail": res.detail,
                       "elapsed": res.elapsed}
            elif res.ok:  # probe answered: this host has no accelerator
                _pin_cpu()
                degraded = requested == "tpu"
                _tm_probes.labels(outcome="cpu").inc()
                if degraded:
                    _tm_fallbacks.inc()
                    log.warning("tpu requested but probe found only cpu; "
                                "degrading to cpu (%s)", res.detail)
                out = {"requested": requested, "platform": "cpu",
                       "degraded": degraded, "detail": res.detail,
                       "elapsed": res.elapsed}
            else:  # timeout / crash: the hang-forever path, now bounded
                _pin_cpu()
                _tm_probes.labels(outcome="failed").inc()
                _tm_fallbacks.inc()
                log.warning("device probe failed (%s); degrading to cpu "
                            "instead of blocking at first device use",
                            res.detail)
                out = {"requested": requested, "platform": "cpu",
                       "degraded": True, "detail": res.detail,
                       "elapsed": res.elapsed}
    _tm_info.labels(platform=out["platform"]).set(1.0)
    _tm_degraded.set(1.0 if out["degraded"] else 0.0)
    RECORDER.set_fact("platform", out["platform"])
    RECORDER.set_fact("platform_probe", out)
    global _last_acquire
    with _mu:
        _last_acquire = out
    return out


def backoff_gaps(attempts: int, horizon: float) -> list[float]:
    """Sleep gaps between probe attempts: exponentially growing, summing
    to `horizon` (attempt 1 now, the rest spread so a short tunnel blip
    is retried quickly and a longer one still gets a late chance)."""
    n_gaps = max(attempts - 1, 0)
    if n_gaps == 0 or horizon <= 0:
        return [0.0] * n_gaps
    total = float((1 << n_gaps) - 1)  # 1 + 2 + 4 + ...
    return [horizon * (1 << i) / total for i in range(n_gaps)]


def acquire_platform_with_retry(
    requested: str = "auto",
    attempts: int | None = None,
    horizon: float | None = None,
    timeout: float = DEFAULT_PROBE_TIMEOUT,
    probe_fn: Callable[[], ProbeResult] | None = None,
    sleep: Callable[[float], None] = time.sleep,
) -> dict:
    """acquire_platform with N probe attempts spread over a backoff
    horizon (VERDICT next-round #2: one tunnel blip must not cost the
    round's number). Only probe failures (timeout/crash) are retried — a
    probe that *answers*, tpu or cpu, is authoritative. Returns the
    acquire_platform dict plus an `attempts` trail, so the whole
    acquisition story lands in PerfRecord provenance."""
    # clamp BOTH sources to >=1: an env-misconfigured 0 must degrade the
    # usual way, not skip the loop and crash on an unset result
    attempts = max(DEFAULT_PROBE_ATTEMPTS if attempts is None else attempts, 1)
    horizon = DEFAULT_PROBE_HORIZON if horizon is None else horizon
    if requested == "cpu":
        out = acquire_platform(requested, timeout, probe_fn)
        out["attempts"] = [{"attempt": 1, "ok": True, "platform": "cpu",
                            "detail": "cpu requested", "elapsed_s": 0.0}]
        return out
    gaps = backoff_gaps(attempts, horizon)
    trail: list[dict] = []
    res: ProbeResult | None = None
    for i in range(attempts):
        res = probe_device_platform(timeout, probe_fn)
        trail.append({"attempt": i + 1, "ok": res.ok,
                      "platform": res.platform, "detail": res.detail,
                      "elapsed_s": round(res.elapsed, 3)})
        if res.ok:
            break
        if i < attempts - 1:
            log.warning("platform probe attempt %d/%d failed (%s); "
                        "retrying in %.1fs", i + 1, attempts, res.detail,
                        gaps[i])
            sleep(gaps[i])
    # funnel the final outcome through acquire_platform so the usual
    # bookkeeping (pin-to-cpu, metrics, flight-recorder facts) applies
    out = acquire_platform(requested, timeout, probe_fn=lambda: res)
    out["attempts"] = trail
    RECORDER.set_fact("platform_probe_attempts", trail)
    return out
