"""Network-namespace helpers (ref: pkg/netnsenter, pkg/rawsock).

netns_enter runs a callable inside another process's network namespace —
the reference locks an OS thread and setns's it (netnsenter); Python 3.12's
os.setns plus a dedicated thread gives the same isolation. netns_fd_for_pid
hands the capture layer the fd that PacketSniffSource setns's before
opening its AF_PACKET socket (rawsock.go:40-76's OpenRawSock contract).
"""

from __future__ import annotations

import os
import threading
from typing import Any, Callable


def netns_fd_for_pid(pid: int) -> int:
    """Open /proc/<pid>/ns/net; caller owns the fd (the capture layer closes
    it on source destroy)."""
    return os.open(f"/proc/{pid}/ns/net", os.O_RDONLY)


def netns_enter(pid: int, fn: Callable[[], Any]) -> Any:
    """Run fn() on a thread joined to pid's netns; returns fn's result."""
    result: list[Any] = [None]
    error: list[BaseException | None] = [None]

    def body():
        fd = netns_fd_for_pid(pid)
        try:
            os.setns(fd, os.CLONE_NEWNET)
            result[0] = fn()
        except BaseException as e:  # propagate to caller
            error[0] = e
        finally:
            os.close(fd)

    t = threading.Thread(target=body)
    t.start()
    t.join()
    if error[0] is not None:
        raise error[0]
    return result[0]
