"""x86_64 syscall number ↔ name table.

The role libseccomp plays in the reference (advise/seccomp tracer.go Peek
converts the per-mntns syscall bitmap to names via libseccomp). A static
table keeps us dependency-free; covers the full classic x86_64 range used
by policy generation and traceloop decoding.
"""

from __future__ import annotations

_NAMES = {
    0: "read", 1: "write", 2: "open", 3: "close", 4: "stat", 5: "fstat",
    6: "lstat", 7: "poll", 8: "lseek", 9: "mmap", 10: "mprotect",
    11: "munmap", 12: "brk", 13: "rt_sigaction", 14: "rt_sigprocmask",
    15: "rt_sigreturn", 16: "ioctl", 17: "pread64", 18: "pwrite64",
    19: "readv", 20: "writev", 21: "access", 22: "pipe", 23: "select",
    24: "sched_yield", 25: "mremap", 26: "msync", 27: "mincore",
    28: "madvise", 29: "shmget", 30: "shmat", 31: "shmctl", 32: "dup",
    33: "dup2", 34: "pause", 35: "nanosleep", 36: "getitimer", 37: "alarm",
    38: "setitimer", 39: "getpid", 40: "sendfile", 41: "socket",
    42: "connect", 43: "accept", 44: "sendto", 45: "recvfrom",
    46: "sendmsg", 47: "recvmsg", 48: "shutdown", 49: "bind", 50: "listen",
    51: "getsockname", 52: "getpeername", 53: "socketpair", 54: "setsockopt",
    55: "getsockopt", 56: "clone", 57: "fork", 58: "vfork", 59: "execve",
    60: "exit", 61: "wait4", 62: "kill", 63: "uname", 64: "semget",
    65: "semop", 66: "semctl", 67: "shmdt", 68: "msgget", 69: "msgsnd",
    70: "msgrcv", 71: "msgctl", 72: "fcntl", 73: "flock", 74: "fsync",
    75: "fdatasync", 76: "truncate", 77: "ftruncate", 78: "getdents",
    79: "getcwd", 80: "chdir", 81: "fchdir", 82: "rename", 83: "mkdir",
    84: "rmdir", 85: "creat", 86: "link", 87: "unlink", 88: "symlink",
    89: "readlink", 90: "chmod", 91: "fchmod", 92: "chown", 93: "fchown",
    94: "lchown", 95: "umask", 96: "gettimeofday", 97: "getrlimit",
    98: "getrusage", 99: "sysinfo", 100: "times", 101: "ptrace",
    102: "getuid", 103: "syslog", 104: "getgid", 105: "setuid",
    106: "setgid", 107: "geteuid", 108: "getegid", 109: "setpgid",
    110: "getppid", 111: "getpgrp", 112: "setsid", 113: "setreuid",
    114: "setregid", 115: "getgroups", 116: "setgroups", 117: "setresuid",
    118: "getresuid", 119: "setresgid", 120: "getresgid", 121: "getpgid",
    122: "setfsuid", 123: "setfsgid", 124: "getsid", 125: "capget",
    126: "capset", 127: "rt_sigpending", 128: "rt_sigtimedwait",
    129: "rt_sigqueueinfo", 130: "rt_sigsuspend", 131: "sigaltstack",
    132: "utime", 133: "mknod", 135: "personality", 136: "ustat",
    137: "statfs", 138: "fstatfs", 139: "sysfs", 140: "getpriority",
    141: "setpriority", 142: "sched_setparam", 143: "sched_getparam",
    144: "sched_setscheduler", 145: "sched_getscheduler",
    146: "sched_get_priority_max", 147: "sched_get_priority_min",
    148: "sched_rr_get_interval", 149: "mlock", 150: "munlock",
    151: "mlockall", 152: "munlockall", 153: "vhangup", 154: "modify_ldt",
    155: "pivot_root", 157: "prctl", 158: "arch_prctl", 159: "adjtimex",
    160: "setrlimit", 161: "chroot", 162: "sync", 163: "acct",
    164: "settimeofday", 165: "mount", 166: "umount2", 167: "swapon",
    168: "swapoff", 169: "reboot", 170: "sethostname", 171: "setdomainname",
    172: "iopl", 173: "ioperm", 175: "init_module", 176: "delete_module",
    179: "quotactl", 186: "gettid", 187: "readahead", 188: "setxattr",
    189: "lsetxattr", 190: "fsetxattr", 191: "getxattr", 192: "lgetxattr",
    193: "fgetxattr", 194: "listxattr", 195: "llistxattr", 196: "flistxattr",
    197: "removexattr", 198: "lremovexattr", 199: "fremovexattr",
    200: "tkill", 201: "time", 202: "futex", 203: "sched_setaffinity",
    204: "sched_getaffinity", 206: "io_setup", 207: "io_destroy",
    208: "io_getevents", 209: "io_submit", 210: "io_cancel",
    213: "epoll_create", 216: "remap_file_pages", 217: "getdents64",
    218: "set_tid_address", 219: "restart_syscall", 220: "semtimedop",
    221: "fadvise64", 222: "timer_create", 223: "timer_settime",
    224: "timer_gettime", 225: "timer_getoverrun", 226: "timer_delete",
    227: "clock_settime", 228: "clock_gettime", 229: "clock_getres",
    230: "clock_nanosleep", 231: "exit_group", 232: "epoll_wait",
    233: "epoll_ctl", 234: "tgkill", 235: "utimes", 237: "mbind",
    238: "set_mempolicy", 239: "get_mempolicy", 240: "mq_open",
    241: "mq_unlink", 242: "mq_timedsend", 243: "mq_timedreceive",
    244: "mq_notify", 245: "mq_getsetattr", 246: "kexec_load",
    247: "waitid", 248: "add_key", 249: "request_key", 250: "keyctl",
    251: "ioprio_set", 252: "ioprio_get", 253: "inotify_init",
    254: "inotify_add_watch", 255: "inotify_rm_watch", 256: "migrate_pages",
    257: "openat", 258: "mkdirat", 259: "mknodat", 260: "fchownat",
    261: "futimesat", 262: "newfstatat", 263: "unlinkat", 264: "renameat",
    265: "linkat", 266: "symlinkat", 267: "readlinkat", 268: "fchmodat",
    269: "faccessat", 270: "pselect6", 271: "ppoll", 272: "unshare",
    273: "set_robust_list", 274: "get_robust_list", 275: "splice",
    276: "tee", 277: "sync_file_range", 278: "vmsplice", 279: "move_pages",
    280: "utimensat", 281: "epoll_pwait", 282: "signalfd", 283: "timerfd_create",
    284: "eventfd", 285: "fallocate", 286: "timerfd_settime",
    287: "timerfd_gettime", 288: "accept4", 289: "signalfd4", 290: "eventfd2",
    291: "epoll_create1", 292: "dup3", 293: "pipe2", 294: "inotify_init1",
    295: "preadv", 296: "pwritev", 297: "rt_tgsigqueueinfo",
    298: "perf_event_open", 299: "recvmmsg", 300: "fanotify_init",
    301: "fanotify_mark", 302: "prlimit64", 303: "name_to_handle_at",
    304: "open_by_handle_at", 305: "clock_adjtime", 306: "syncfs",
    307: "sendmmsg", 308: "setns", 309: "getcpu", 310: "process_vm_readv",
    311: "process_vm_writev", 312: "kcmp", 313: "finit_module",
    314: "sched_setattr", 315: "sched_getattr", 316: "renameat2",
    317: "seccomp", 318: "getrandom", 319: "memfd_create", 320: "kexec_file_load",
    321: "bpf", 322: "execveat", 323: "userfaultfd", 324: "membarrier",
    325: "mlock2", 326: "copy_file_range", 327: "preadv2", 328: "pwritev2",
    332: "statx", 333: "io_pgetevents", 334: "rseq",
    424: "pidfd_send_signal", 425: "io_uring_setup", 426: "io_uring_enter",
    427: "io_uring_register", 428: "open_tree", 429: "move_mount",
    430: "fsopen", 431: "fsconfig", 432: "fsmount", 433: "fspick",
    434: "pidfd_open", 435: "clone3", 436: "close_range", 437: "openat2",
    438: "pidfd_getfd", 439: "faccessat2", 440: "process_madvise",
    441: "epoll_pwait2", 442: "mount_setattr", 443: "quotactl_fd",
    444: "landlock_create_ruleset", 445: "landlock_add_rule",
    446: "landlock_restrict_self", 447: "memfd_secret",
    448: "process_mrelease",
}

_NUMBERS = {v: k for k, v in _NAMES.items()}


def syscall_name(nr: int) -> str:
    return _NAMES.get(nr, f"syscall_{nr}")


def syscall_number(name: str) -> int | None:
    return _NUMBERS.get(name)


def all_names() -> list[str]:
    return sorted(_NAMES.values())
