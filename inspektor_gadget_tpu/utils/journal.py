"""Shared append-only JSON-lines discipline: atomic appends, torn-tail
tolerant reads.

Three planes grew the same recovery logic independently — the perf
ledger (perf/ledger.py), the alert webhook-file sink (alerts/sinks.py),
and flight-recorder dump reads — and three copies of "skip/stop at the
crash-truncated tail" is a drift bug waiting to happen. This module is
the single owner; the capture plane's segment *index* and recording
manifests use it too (the binary segment framing itself lives in
capture/journal.py, built on the same append discipline).

Append contract: one record = one compact JSON line, written with a
single `os.write` on an O_APPEND fd — POSIX makes that atomic between
processes, so concurrent writers cannot interleave bytes. A rare short
write is completed in a loop or raised, never reported as success.

Read contract: a crash mid-append leaves at most one torn line at the
tail. Readers never fail the whole file for it — `on_bad="stop"` treats
the first unparseable line as the torn tail (everything before it is
good), `on_bad="skip"` reports and skips every unusable line (the
ledger's stance: interior corruption must not take the history down).
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Callable


@dataclasses.dataclass
class JsonlRead:
    records: list[dict]
    skipped: list[str]          # 'line N: why' for unusable lines


def append_line(path: str, obj: Any, *, mode: int = 0o644) -> None:
    """Serialize `obj` to ONE compact JSON line and append it atomically
    (single O_APPEND write; short writes completed or raised)."""
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    line = json.dumps(obj, sort_keys=True, separators=(",", ":")) + "\n"
    append_bytes(path, line.encode("utf-8"), mode=mode)


def append_bytes(path: str, buf: bytes, *, mode: int = 0o644) -> None:
    """The raw O_APPEND single-write discipline (capture segment frames
    reuse it for binary records)."""
    fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, mode)
    try:
        while buf:  # a short write must not report success on a torn tail
            n = os.write(fd, buf)
            if n <= 0:
                raise OSError(f"short write appending to {path}")
            buf = buf[n:]
    finally:
        os.close(fd)


def read_jsonl(path: str, *, on_bad: str = "stop",
               validate: Callable[[dict], str | None] | None = None
               ) -> JsonlRead:
    """All parseable records in append order, tolerating a torn tail.

    on_bad="stop": an unparseable line IS the torn tail — stop there
    (the webhook-sink stance). on_bad="skip": report and skip every
    unusable line, keep reading (the ledger stance). `validate` returns
    an error string for records that parse but are unusable; those are
    always skipped-and-reported, never fatal.
    """
    if on_bad not in ("stop", "skip"):
        raise ValueError(f"on_bad must be 'stop' or 'skip', got {on_bad!r}")
    records: list[dict] = []
    skipped: list[str] = []
    if not os.path.exists(path):
        return JsonlRead(records, skipped)
    with open(path, encoding="utf-8") as f:
        for i, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as e:
                skipped.append(f"line {i}: unparseable ({e.msg})")
                if on_bad == "stop":
                    break  # torn tail — everything before it is good
                continue
            if validate is not None:
                err = validate(rec)
                if err:
                    skipped.append(f"line {i}: invalid ({err})")
                    continue
            records.append(rec)
    return JsonlRead(records, skipped)


def read_json_file(path: str) -> tuple[dict | None, str]:
    """(document, "") or (None, why) for a whole-file JSON artifact that
    may be crash-truncated (flight-recorder dumps): unreadable or torn
    files are reported, never raised — a post-mortem read must not crash
    on the very evidence of the crash."""
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
    except OSError as e:
        return None, f"{path}: unreadable ({e.strerror or e})"
    except json.JSONDecodeError as e:
        return None, f"{path}: truncated or corrupt ({e.msg} at line {e.lineno})"
    if not isinstance(doc, dict):
        return None, f"{path}: not a JSON object"
    return doc, ""


__all__ = ["JsonlRead", "append_bytes", "append_line", "read_json_file",
           "read_jsonl"]
