"""k8sutil — the kube-API client abstraction (ref: pkg/k8sutil, 74 LoC:
a clientset constructor resolving in-cluster vs kubeconfig credentials).

Stdlib-only (urllib + ssl): resolves credentials the way client-go's
rest.InClusterConfig does — the mounted service-account token, CA cert and
KUBERNETES_SERVICE_HOST/PORT env — with explicit server/token/CA as the
out-of-cluster path. One `KubeClient` serves every consumer (pod informer,
node listing, deploy status checks) so the API plumbing lives in one
place instead of per-feature urllib calls.
"""

from __future__ import annotations

import json
import os
import ssl
import urllib.request

SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


class KubeClient:
    """Minimal typed facade over the apiserver REST API."""

    def __init__(self, server: str = "", token: str = "",
                 ca_cert: str = "", insecure: bool = False,
                 timeout: float = 5.0):
        self.server = server or self._in_cluster_server()
        self.token = token if token else self._read_sa("token")
        self.ca_cert = ca_cert or (
            f"{SA_DIR}/ca.crt" if os.path.exists(f"{SA_DIR}/ca.crt") else "")
        self.insecure = insecure
        self.timeout = timeout

    # -- credential resolution (rest.InClusterConfig contract) --------------

    @staticmethod
    def _in_cluster_server() -> str:
        host = os.environ.get("KUBERNETES_SERVICE_HOST", "")
        port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
        return f"https://{host}:{port}" if host else ""

    @staticmethod
    def _read_sa(name: str) -> str:
        try:
            with open(f"{SA_DIR}/{name}") as f:
                return f.read().strip()
        except OSError:
            return ""

    def available(self) -> bool:
        return bool(self.server)

    # -- transport ----------------------------------------------------------

    def _ssl_ctx(self):
        if not self.server.startswith("https"):
            return None
        if self.insecure:
            return ssl._create_unverified_context()  # noqa: S323
        if self.ca_cert:
            return ssl.create_default_context(cafile=self.ca_cert)
        return None

    def get(self, path: str) -> dict:
        req = urllib.request.Request(self.server + path)
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        with urllib.request.urlopen(req, timeout=self.timeout,
                                    context=self._ssl_ctx()) as resp:
            return json.load(resp)

    def send(self, path: str, body: dict, method: str = "PUT") -> dict:
        """Write a resource (PUT/PATCH/POST); returns the response body.
        The write half the Trace controller needs to park status/output on
        the resource (trace_controller.go's Status().Update role)."""
        data = json.dumps(body).encode()
        req = urllib.request.Request(self.server + path, data=data,
                                     method=method)
        req.add_header("Content-Type",
                       "application/merge-patch+json" if method == "PATCH"
                       else "application/json")
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        with urllib.request.urlopen(req, timeout=self.timeout,
                                    context=self._ssl_ctx()) as resp:
            raw = resp.read()
        return json.loads(raw) if raw else {}

    # -- typed helpers ------------------------------------------------------

    def list_pods(self, namespace: str = "", node_name: str = "",
                  label_selector: str = "") -> list[dict]:
        path = (f"/api/v1/namespaces/{namespace}/pods" if namespace
                else "/api/v1/pods")
        params = []
        if node_name:
            params.append(f"fieldSelector=spec.nodeName%3D{node_name}")
        if label_selector:
            params.append(f"labelSelector={label_selector}")
        if params:
            path += "?" + "&".join(params)
        return self.get(path).get("items", [])

    def list_services(self, namespace: str = "") -> list[dict]:
        path = (f"/api/v1/namespaces/{namespace}/services" if namespace
                else "/api/v1/services")
        return self.get(path).get("items", [])

    def list_nodes(self) -> list[dict]:
        return self.get("/api/v1/nodes").get("items", [])

    def daemonset_status(self, namespace: str, name: str) -> tuple[int, int]:
        """(desired, ready) — the rollout-wait check (deploy.go parity)."""
        obj = self.get(f"/apis/apps/v1/namespaces/{namespace}"
                       f"/daemonsets/{name}")
        status = obj.get("status", {})
        return (int(status.get("desiredNumberScheduled", 0)),
                int(status.get("numberReady", 0)))

    def node_names(self) -> list[str]:
        return [n.get("metadata", {}).get("name", "")
                for n in self.list_nodes()]


def pod_source_from_client(client: KubeClient, node_name: str = ""):
    """Adapt a KubeClient into the pod informer's PodSource shape (the
    client-go-free informer feed; see containers.podinformer)."""

    def list_pods() -> list[dict]:
        pods = []
        for item in client.list_pods(node_name=node_name):
            meta = item.get("metadata", {})
            spec = item.get("spec", {})
            status = item.get("status", {})
            ids = {
                cs.get("name"): cs.get("containerID", "").rpartition("//")[2]
                for cs in status.get("containerStatuses", ())
            }
            pods.append({
                "name": meta.get("name", ""),
                "namespace": meta.get("namespace", ""),
                "uid": meta.get("uid", ""),
                "node": spec.get("nodeName", ""),
                "labels": meta.get("labels", {}),
                "hostNetwork": spec.get("hostNetwork", False),
                "containers": [
                    {"name": c.get("name", ""),
                     "id": ids.get(c.get("name"), ""),
                     "image": c.get("image", "")}
                    for c in spec.get("containers", ())
                ],
            })
        return pods

    return list_pods
