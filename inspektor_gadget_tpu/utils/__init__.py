"""Shared utilities: syscall tables, netns helpers, logging."""
