"""Stream supervision: retry policy, error classes, and fleet health.

The fan-out runtime used to *isolate* node failures (record the error,
move on) but never *recover*: a dropped stream stayed dead for the rest
of the run. This module is the recovery half — the pieces GrpcRuntime
composes around each node stream:

  - RetryPolicy: capped exponential backoff with FULL jitter (AWS
    architecture-blog discipline: sleep = uniform(0, min(cap, base*2^n))
    so N reconnecting clients don't stampede the healing agent on the
    same tick), plus a per-attempt connect deadline and a "horizon"
    after which a still-unreachable node is *labeled* dead. Labeling is
    not giving up: the supervisor keeps retrying at the capped rate for
    as long as the run lives, so a partition that outlasts the horizon
    still heals (resurrection) — "dead" is an honest state, not a
    terminal one.
  - classify_error: retryable transport trouble vs fatal gadget errors.
    Retrying a broken gadget spec would loop forever on a determinist
    failure; giving up on a flaky network wastes a healthy node.
  - FleetHealth: the per-node state machine
    healthy | reconnecting | straggling | dead, with straggler
    detection keyed to the *fleet's* rolling inter-record p95 (a slow
    node is slow relative to its peers, not to a wall-clock constant)
    and an injectable clock so chaos tests can skew time.
  - NodeSupervisor: the retry loop itself — resume-first (re-attach to
    the still-running gadget at last_seq), restart-on-unknown-run (the
    agent was respawned; capture restarts), and seq-gap healing via the
    history plane's sealed-window backfill merge (the PR-6 algebra:
    everything is mergeable, so rejoin = fetch-and-merge, never
    re-stream from zero).
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Callable

from ..telemetry import counter, gauge

# -- telemetry (fleet plane) ------------------------------------------------

_tm_node_state = gauge(
    "ig_fleet_node_state",
    "per-node fleet health (1 for the node's current state)",
    ("node", "state"))
_tm_transitions = counter(
    "ig_fleet_transitions_total",
    "fleet health state transitions", ("node", "to"))
_tm_reconnects = counter(
    "ig_fleet_reconnects_total",
    "stream reconnect attempts per node", ("node",))
_tm_backfilled = counter(
    "ig_fleet_backfilled_records_total",
    "records recovered into merged state from sealed-window backfill "
    "after an outage", ("node",))

HEALTHY = "healthy"
RECONNECTING = "reconnecting"
STRAGGLING = "straggling"
DEAD = "dead"
STATES = (HEALTHY, RECONNECTING, STRAGGLING, DEAD)


# -- error classification ---------------------------------------------------

# gRPC status codes that mean "the transport or peer hiccupped, the same
# request can succeed later" (the reference's connection-level retries);
# everything else — and any error the gadget itself reported via
# EV_RESULT — is fatal: retrying re-runs a deterministic failure.
RETRYABLE_CODES = frozenset({
    "UNAVAILABLE", "DEADLINE_EXCEEDED", "ABORTED", "RESOURCE_EXHAUSTED",
    "UNKNOWN", "INTERNAL", "CANCELLED",
})

TRANSPORT = "transport"
FATAL = "fatal"


def classify_error(error: str | None, *, gadget_error: bool = False) -> str:
    """'transport' (retry with resume) or 'fatal' (record and stop).

    Client stream errors arrive as "CODE_NAME: details" strings
    (AgentClient formats grpc.RpcError that way); anything that doesn't
    parse to a known-retryable code — a gadget raising, a bad param, an
    unknown gadget — is fatal.
    """
    if gadget_error or not error:
        return FATAL
    code = error.split(":", 1)[0].strip()
    if code in RETRYABLE_CODES:
        return TRANSPORT
    # socket-level failures surfaced outside grpc status codes
    lowered = error.lower()
    if any(s in lowered for s in ("connection refused", "connection reset",
                                  "broken pipe", "unreachable", "timed out",
                                  "channel not ready", "eof")):
        return TRANSPORT
    return FATAL


# -- retry policy -----------------------------------------------------------

class RetryPolicy:
    """Capped exponential backoff with full jitter + attempt deadline.

    base/cap/horizon/attempt_deadline in seconds. `horizon` is how long
    a node may stay unreachable before being LABELED dead (retries
    continue at the capped rate — see module docstring). rng is
    injectable for deterministic tests.
    """

    def __init__(self, *, base: float = 0.2, cap: float = 3.0,
                 horizon: float = 30.0, attempt_deadline: float = 5.0,
                 rng: random.Random | None = None):
        if base <= 0 or cap < base:
            raise ValueError(f"retry base/cap out of range ({base}, {cap})")
        if horizon <= 0 or attempt_deadline <= 0:
            raise ValueError("retry horizon/attempt deadline must be > 0")
        self.base = float(base)
        self.cap = float(cap)
        self.horizon = float(horizon)
        self.attempt_deadline = float(attempt_deadline)
        self._rng = rng or random.Random()

    def ceiling(self, attempt: int) -> float:
        """Deterministic upper bound of the attempt-th sleep (attempt
        counts from 0)."""
        return min(self.cap, self.base * (2 ** min(attempt, 32)))

    def delay(self, attempt: int) -> float:
        """Full jitter: uniform over (0, ceiling]."""
        return self._rng.uniform(0.0, self.ceiling(attempt))


# -- fleet health -----------------------------------------------------------

class FleetHealth:
    """Per-node state machine over a shared fleet record cadence.

    A node is `straggling` when it has been silent for more than
    straggler_factor × the fleet's rolling inter-record p95 (floored at
    straggler_floor so a quiet-but-uniform fleet doesn't flap on µs
    cadences). observe() — a record arrived — heals any state back to
    healthy; the supervisor marks reconnecting/dead around stream
    outages. The clock is injectable (chaos tests skew it).
    """

    def __init__(self, nodes, *, clock: Callable[[], float] = time.monotonic,
                 straggler_factor: float = 4.0, straggler_floor: float = 1.0,
                 window: int = 256):
        self._clock = clock
        self.straggler_factor = float(straggler_factor)
        self.straggler_floor = float(straggler_floor)
        self._mu = threading.Lock()
        now = clock()
        self._state: dict[str, str] = {}
        self._last_seen: dict[str, float] = {n: now for n in nodes}
        self._intervals: list[float] = []
        self._window = int(window)
        self._finished: set[str] = set()
        for n in nodes:
            self._state[n] = HEALTHY
            self._export(n, HEALTHY)

    def _export(self, node: str, state: str) -> None:
        for s in STATES:
            _tm_node_state.labels(node=node, state=s).set(
                1.0 if s == state else 0.0)

    def _set_locked(self, node: str, state: str) -> None:
        if self._state.get(node) == state:
            return
        self._state[node] = state
        _tm_transitions.labels(node=node, to=state).inc()
        self._export(node, state)

    def mark(self, node: str, state: str) -> None:
        if state not in STATES:
            raise ValueError(f"unknown fleet state {state!r}")
        with self._mu:
            self._set_locked(node, state)

    def observe(self, node: str) -> None:
        """A record arrived from `node`: refresh cadence, heal state."""
        now = self._clock()
        with self._mu:
            last = self._last_seen.get(node, now)
            self._last_seen[node] = now
            dt = now - last
            if dt >= 0:  # a backwards clock skew must not poison the p95
                self._intervals.append(dt)
                if len(self._intervals) > self._window:
                    del self._intervals[: -self._window]
            self._set_locked(node, HEALTHY)

    def fleet_p95(self) -> float | None:
        with self._mu:
            if not self._intervals:
                return None
            s = sorted(self._intervals)
        return s[min(len(s) - 1, int(0.95 * len(s)))]

    def straggler_threshold(self) -> float:
        p95 = self.fleet_p95()
        if p95 is None:
            return float("inf")
        return max(self.straggler_factor * p95, self.straggler_floor)

    def finish(self, node: str) -> None:
        """The node's stream ended for good: silence is now expected,
        so straggler checks must leave its final state alone."""
        with self._mu:
            self._finished.add(node)

    def check_stragglers(self) -> list[str]:
        """Flag healthy-but-silent nodes; returns newly straggling."""
        thr = self.straggler_threshold()
        now = self._clock()
        flagged = []
        with self._mu:
            for node, st in self._state.items():
                if node in self._finished:
                    continue
                if st == HEALTHY and now - self._last_seen[node] > thr:
                    self._set_locked(node, STRAGGLING)
                    flagged.append(node)
        return flagged

    def get(self, node: str) -> str:
        with self._mu:
            return self._state.get(node, HEALTHY)

    def states(self) -> dict[str, str]:
        with self._mu:
            return dict(self._state)

    def silence(self, node: str) -> float:
        with self._mu:
            return self._clock() - self._last_seen.get(node, self._clock())


# -- the per-node supervision loop ------------------------------------------

class NodeSupervisor:
    """Run one node's stream to completion through chaos.

    attempt_fn(resume_from: int | None, run_id: str) -> dict is the
    blocking stream call (AgentClient.run_gadget with all handlers
    wired); it returns the client's accounting dict ({'error',
    'last_seq', 'records', 'gaps', 'dropped', 'unknown_run', 'resume',
    'result'}). The supervisor owns retries, resume bookkeeping, health
    transitions, and sealed-window backfill, and returns one merged
    accounting dict for the node's GadgetResult.
    """

    def __init__(self, node: str, client: Any, *, policy: RetryPolicy,
                 health: FleetHealth, run_id: str, gadget: str,
                 done: Callable[[], bool], logger=None,
                 backfill: bool = True,
                 clock: Callable[[], float] = time.monotonic,
                 wall_clock: Callable[[], float] = time.time):
        self.node = node
        self.client = client
        self.policy = policy
        self.health = health
        self.run_id = run_id
        self.gadget = gadget
        # the agent-assigned subscriber id, learned from attach/resume
        # acks: a resume must name WHICH subscriber is reconnecting, or
        # a shared run would resolve it to the wrong peer's stream
        self.sub_id = ""
        self._done = done
        self._log = logger
        self._backfill_enabled = backfill
        self._clock = clock
        self._wall = wall_clock

    # small seams the chaos tests poke through --------------------------

    def _sleep(self, seconds: float) -> None:
        deadline = self._clock() + seconds
        while not self._done() and self._clock() < deadline:
            time.sleep(min(0.05, max(0.0, deadline - self._clock())))

    def _wait_channel_ready(self) -> bool:
        """Per-attempt deadline: bound the connect wait so a blackholed
        peer consumes one backoff slot, not the whole run."""
        import grpc
        try:
            grpc.channel_ready_future(self.client.channel).result(
                timeout=self.policy.attempt_deadline)
            return True
        except Exception:  # noqa: BLE001 — timeout or terminal channel
            return False

    def _backfill(self, since_wall: float, until_wall: float,
                  out: dict) -> None:
        """Heal a seq gap from sealed windows: every window the node
        sealed during the outage is mergeable state (PR-6 algebra), so
        the gap's events rejoin the merged answer without re-streaming.
        Only windows already sealed are recoverable — the torn tail of
        a SIGKILLed store is dropped-and-accounted by the store reader,
        never silently resurrected."""
        if not self._backfill_enabled:
            return
        try:
            from ..history import decode_frames
            listing = self.client.list_windows(
                gadget=self.gadget, start_ts=since_wall, end_ts=until_wall)
            if not listing.get("windows"):
                return
            frames, _losses = self.client.fetch_windows(
                gadget=self.gadget, start_ts=since_wall, end_ts=until_wall)
            # THIS run's windows only: a concurrent run of the same
            # gadget seals into the same store, and merging its windows
            # here would smuggle another run's events into this result.
            # (An unknown-run restart reuses the run_id, so the dead
            # life's windows still match.)
            wins = [w for w in decode_frames(frames)
                    if not w.run_id or w.run_id == self.run_id]
        except Exception as e:  # noqa: BLE001 — backfill is best-effort
            if self._log:
                self._log.warning("[%s] backfill failed: %r", self.node, e)
            return
        events = sum(int(w.events) for w in wins)
        if wins:
            out["backfill"].extend(wins)
            out["backfilled"] += events
            _tm_backfilled.labels(node=self.node).inc(events)
            if self._log:
                self._log.info(
                    "[%s] backfilled %d sealed window(s), %d record(s) "
                    "covering the outage", self.node, len(wins), events)

    # the loop ----------------------------------------------------------

    def run(self, attempt_fn: Callable[[int | None, str], dict]) -> dict:
        out: dict[str, Any] = {
            "result": None, "error": None, "gaps": 0, "dropped": 0,
            "records": 0, "last_seq": 0, "reconnects": 0,
            "backfilled": 0, "backfill": [],
            # shared-run subscriber accounting, aggregated across
            # reconnect attempts (drop totals are cumulative per
            # subscriber, so max — not sum — across attempts)
            "sub_drops": 0, "evicted": False, "attach_refused": "",
            "attach": None,
        }
        resume_from: int | None = None
        attempt = 0                    # consecutive failed attempts
        outage_wall: float | None = None
        outage_mono: float | None = None

        while True:
            if attempt > 0:
                # reconnect path: fresh channel + bounded connect wait
                out["reconnects"] += 1
                _tm_reconnects.labels(node=self.node).inc()
                over_horizon = (outage_mono is not None and self._clock()
                                - outage_mono >= self.policy.horizon)
                self.health.mark(self.node,
                                 DEAD if over_horizon else RECONNECTING)
                try:
                    self.client.reconnect()
                except Exception as e:  # noqa: BLE001 — treat as failed dial
                    if self._log:
                        self._log.debug("[%s] redial failed: %r",
                                        self.node, e)
                if not self._wait_channel_ready():
                    if self._done():
                        break
                    if (outage_mono is not None and self._clock()
                            - outage_mono >= self.policy.horizon):
                        self.health.mark(self.node, DEAD)
                    self._sleep(self.policy.delay(attempt))
                    attempt += 1
                    continue

            res = attempt_fn(resume_from, self.run_id)
            out["gaps"] += int(res.get("gaps") or 0)
            out["dropped"] += int(res.get("dropped") or 0)
            out["records"] += int(res.get("records") or 0)
            out["sub_drops"] = max(out["sub_drops"],
                                   int(res.get("sub_drops") or 0))
            out["evicted"] = out["evicted"] or bool(res.get("evicted"))
            if res.get("attach_refused"):
                out["attach_refused"] = res["attach_refused"]
            if res.get("attach") is not None:
                out["attach"] = res["attach"]
                if res["attach"].get("sub_id"):
                    self.sub_id = res["attach"]["sub_id"]
            if res.get("last_seq"):
                out["last_seq"] = int(res["last_seq"])
            if res.get("result") is not None:
                out["result"] = res["result"]

            ack = res.get("resume") or {}
            if ack.get("sub_id"):
                self.sub_id = ack["sub_id"]
            was_reconnect = attempt > 0
            if int(res.get("records") or 0) > 0 or ack:
                # the attempt made real progress: later, unrelated
                # outages must start backoff from base again, not from
                # this outage's accumulated exponent
                attempt = 0
            if was_reconnect and ack and outage_wall is not None:
                # re-attached to the still-running gadget; anything the
                # replay ring could not cover is healed from sealed state
                if int(ack.get("missed") or 0) > 0:
                    self._backfill(outage_wall - 1.0, self._wall() + 1.0,
                                   out)
                outage_wall = outage_mono = None

            if res.get("unknown_run"):
                # the agent restarted underneath us: nothing to resume.
                # Recover what its previous life sealed to disk, then
                # restart capture fresh (rejoin = backfill-and-merge).
                since = (outage_wall - 1.0 if outage_wall is not None
                         else self._wall() - self.policy.horizon)
                self._backfill(since, self._wall() + 1.0, out)
                resume_from = None
                self.sub_id = ""  # the fresh run assigns a new identity
                # the respawned agent numbers its NEW life's stream from
                # seq 1: resuming (or gap-counting) against the dead
                # life's high seq would silently skip the new ring
                out["last_seq"] = 0
                outage_wall = outage_mono = None
                if self._done():
                    out["error"] = out["error"] or res.get("error")
                    break
                attempt += 1
                self._sleep(self.policy.delay(attempt))
                continue

            err = res.get("error")
            if not err:
                # clean stream end
                self.health.mark(self.node, HEALTHY)
                out["error"] = None
                break

            cls = classify_error(err, gadget_error=bool(res.get(
                "gadget_error")))
            if cls == FATAL:
                out["error"] = err
                self.health.mark(self.node, DEAD)
                break

            # retryable transport trouble: resume from where we stopped.
            # Always resume (even at last_seq 0) once a run request went
            # out — a fresh re-run against an agent whose previous life
            # still lingers would capture TWICE under one run_id; if the
            # run never actually started over there, the resume answers
            # unknown_run and we restart cleanly above.
            out["error"] = err  # kept only if we never recover
            if outage_mono is None:
                outage_mono = self._clock()
                outage_wall = self._wall()
            if self._done():
                break
            resume_from = int(out["last_seq"] or 0)
            attempt += 1
            self._sleep(self.policy.delay(attempt))

        # final label: a node that never healed ends dead with its last
        # error; a clean node ends healthy with error None
        if out["error"] is not None:
            self.health.mark(self.node, DEAD)
        return out


__all__ = [
    "DEAD", "FATAL", "FleetHealth", "HEALTHY", "NodeSupervisor",
    "RECONNECTING", "RETRYABLE_CODES", "RetryPolicy", "STATES",
    "STRAGGLING", "TRANSPORT", "classify_error",
]
