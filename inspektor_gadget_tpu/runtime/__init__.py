"""Runtimes: the lifecycle abstraction shared by local and remote execution
(ref: pkg/runtime/runtime.go:83-92 — Init, RunGadget, GetCatalog;
CombinedGadgetResult :42-47 for per-node results/errors).
"""

from .runtime import Runtime, GadgetResult, CombinedGadgetResult
from .local import LocalRuntime

__all__ = ["Runtime", "GadgetResult", "CombinedGadgetResult", "LocalRuntime",
           "GrpcRuntime"]


def __getattr__(name):
    # lazy: GrpcRuntime pulls in grpc only when used
    if name == "GrpcRuntime":
        from .grpc_runtime import GrpcRuntime
        return GrpcRuntime
    raise AttributeError(name)
