"""Local runtime: instantiate → install operators → wire handlers → run.

Reference contract: pkg/runtime/local/local.go:69-152 —
  NewInstance (:84) → operators.Instantiate (:100) →
  SetEventHandler(enrich-then-callback) (:108-110) →
  operatorInstances.PreGadgetRun (:126) → Run / RunWithResult (:133-146) →
  PostGadgetRun. Event flow per §3.1: source → enrich chain → parser
  callback → formatter.

TPU-first: the batch path is first-class — gadgets that emit EventBatches
get them enriched (enrich_batch) and forwarded to a batch callback; per-row
callbacks remain for display/JSON.
"""

from __future__ import annotations

from typing import Any, Callable

from ..gadgets.context import GadgetContext
from ..gadgets.interface import (
    EventHandlerArraySetter,
    BatchHandlerSetter,
    EventHandlerSetter,
    RunWithResult,
)
from ..operators.operators import install_operators
from .runtime import CombinedGadgetResult, GadgetResult, Runtime


class LocalRuntime(Runtime):
    name = "local"

    def __init__(self, node_name: str = "local"):
        self.node_name = node_name

    def run_gadget(
        self,
        ctx: GadgetContext,
        *,
        on_event: Callable[[Any], None] | None = None,
        on_event_array: Callable[[list], None] | None = None,
        on_batch: Callable[[Any], None] | None = None,
    ) -> CombinedGadgetResult:
        result = CombinedGadgetResult()
        try:
            res = self._run(ctx, on_event, on_event_array, on_batch)
            result[self.node_name] = GadgetResult(result=res)
        except Exception as e:  # per-node error isolation (runtime.go:42-79)
            ctx.logger.exception("gadget run failed")
            result[self.node_name] = GadgetResult(error=str(e))
        return result

    def _run(self, ctx, on_event, on_event_array, on_batch):
        from ..telemetry.tracing import TRACER
        # one span per local run: child of the agent's run span when this
        # runtime serves a gRPC request (ctx.extra carries the context),
        # a fresh trace for a standalone `ig-tpu <gadget>` run
        with TRACER.span(f"run/{ctx.desc.full_name}",
                         parent=ctx.extra.get("trace_ctx"),
                         attrs={"run_id": ctx.run_id,
                                "node": self.node_name}) as span:
            ctx.extra["trace_ctx"] = span.context
            # node identity for operators that stamp events (alerts)
            ctx.extra.setdefault("node", self.node_name)
            return self._run_traced(ctx, on_event, on_event_array, on_batch)

    def _run_traced(self, ctx, on_event, on_event_array, on_batch):
        gadget = ctx.desc.new_instance(ctx)
        from ..gadgets.interface import GadgetType
        if (ctx.desc.gadget_type in (GadgetType.PROFILE,
                                     GadgetType.START_STOP)
                and not isinstance(gadget, RunWithResult)):
            # a result-typed gadget without run_with_result would fall
            # through to run() and the caller would wait on a result
            # that never comes — fail loudly at wiring time instead
            raise TypeError(
                f"{ctx.desc.full_name} is registered as "
                f"{ctx.desc.gadget_type.value} but its gadget class "
                f"{type(gadget).__name__} does not implement "
                f"run_with_result")
        instances = install_operators(ctx, gadget, ctx.operator_params)

        if on_event is not None and isinstance(gadget, EventHandlerSetter):
            def handle(ev):
                instances.enrich(ev)
                on_event(ev)
            gadget.set_event_handler(handle)

        if on_event_array is not None and isinstance(gadget, EventHandlerArraySetter):
            def handle_array(evs):
                for ev in evs:
                    instances.enrich(ev)
                on_event_array(evs)
            gadget.set_event_handler_array(handle_array)

        if isinstance(gadget, BatchHandlerSetter):
            def handle_batch(batch):
                instances.enrich_batch(batch)
                if on_batch is not None:
                    on_batch(batch)
            gadget.set_batch_handler(handle_batch)

        if ctx.timeout > 0:
            import threading
            threading.Thread(
                target=ctx.wait_for_timeout_or_done, daemon=True
            ).start()

        instances.pre_gadget_run()
        try:
            if isinstance(gadget, RunWithResult):
                # the gadget collects until ctx timeout/cancel, then renders
                ctx.result = gadget.run_with_result(ctx)
            else:
                gadget.run(ctx)
        finally:
            instances.post_gadget_run()
        return ctx.result
