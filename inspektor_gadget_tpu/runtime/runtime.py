"""Runtime interface + combined results + catalog.

Reference contract: pkg/runtime/runtime.go (Runtime interface :83-92,
GadgetResult/CombinedGadgetResult :42-79 with per-node error isolation) and
pkg/runtime/catalog.go (serializable catalog of gadgets+operators+params so
remote clients can render flags for server-known gadgets).
"""

from __future__ import annotations

import dataclasses
from typing import Any

from ..gadgets import registry
from ..gadgets.context import GadgetContext
from ..operators import operators as op_registry
from ..params import ParamDescs, Params


@dataclasses.dataclass
class GadgetResult:
    result: Any = None
    error: str | None = None
    # stream accounting (filled by the supervised gRPC fan-out; the
    # local runtime leaves the defaults): seq gaps observed in transit,
    # reconnect attempts, records received, highest seq seen, events
    # recovered from sealed-window backfill, and the sealed windows
    # themselves so harvest merges can fold the healed state in.
    gaps: int = 0
    reconnects: int = 0
    records: int = 0
    last_seq: int = 0
    backfilled: int = 0
    backfill: list = dataclasses.field(default_factory=list)
    health: str = ""
    # shared-run subscriber accounting (next to the health fields so a
    # degraded answer is LABELED): records this node's subscriber queue
    # dropped under overload, whether it was evicted for stalling, a
    # typed admission-refusal reason (empty = admitted), and whether the
    # stream attached to an already-running shared gadget
    sub_drops: int = 0
    evicted: bool = False
    attach_refused: str = ""
    shared: bool = False


class CombinedGadgetResult(dict):
    """node → GadgetResult; partial failures stay per-node
    (ref: runtime.go:42-79). `health` carries each node's final fleet
    state (supervisor.FleetHealth) so a degraded answer is LABELED
    degraded instead of silently looking whole."""

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self.health: dict[str, str] = {}

    def first(self) -> Any:
        for r in self.values():
            if r.error is None:
                return r.result
        return None

    def errors(self) -> dict[str, str]:
        return {k: r.error for k, r in self.items() if r.error}

    def contributing(self) -> list[str]:
        """Nodes whose stream ended cleanly — the ones a harvest merge
        actually contains."""
        return [k for k, r in self.items() if r.error is None]

    @property
    def partial(self) -> bool:
        """True when any node failed or ended unhealthy: the merged
        answer does not cover the whole fleet."""
        if any(r.error for r in self.values()):
            return True
        return any(s not in ("", "healthy") for s in self.health.values())

    def overloaded(self) -> dict[str, str]:
        """node → overload label for nodes whose subscriber stream was
        degraded under fan-out (own-queue drops, eviction, or a refused
        admission) — a thinned answer is LABELED thinned, never silently
        complete-looking."""
        out: dict[str, str] = {}
        for node, r in self.items():
            if r.attach_refused:
                out[node] = f"refused ({r.attach_refused})"
            elif r.evicted:
                out[node] = f"evicted after {r.sub_drops} drop(s)"
            elif r.sub_drops:
                out[node] = f"{r.sub_drops} subscriber drop(s)"
        return out


class Runtime:
    name = ""

    def params(self) -> ParamDescs:
        return ParamDescs()

    def init(self, runtime_params: Params) -> None:
        pass

    def close(self) -> None:
        pass

    def run_gadget(self, ctx: GadgetContext) -> CombinedGadgetResult:
        raise NotImplementedError

    def get_catalog(self) -> dict:
        return build_catalog()


def build_catalog() -> dict:
    """Catalog from the live registries (ref: runtime/local/local.go:38-51
    builds its catalog from the gadget registry; serialization mirrors
    pkg/runtime/catalog.go)."""
    gadgets = []
    for desc in registry.get_all():
        cols = desc.columns()
        gadgets.append({
            "category": desc.category,
            "name": desc.name,
            "type": desc.gadget_type.value,
            "description": desc.description,
            "params": desc.params().to_params().to_descs_json(),
            "columns": [
                {"name": c.name, "width": c.width, "align": c.align,
                 "visible": c.visible, "description": c.description}
                for c in (cols.all() if cols else [])
            ],
        })
    ops = []
    for op in op_registry.get_all():
        ops.append({
            "name": op.name,
            "dependencies": op.dependencies(),
            "globalParams": op.global_params().to_params().to_descs_json(),
            "instanceParams": op.instance_params().to_params().to_descs_json(),
        })
    return {"gadgets": gadgets, "operators": ops}
