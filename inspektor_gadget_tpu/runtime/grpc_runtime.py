"""GrpcRuntime: client-side fan-out over all node agents.

Reference contract: pkg/runtime/grpc/grpc-runtime.go — RunGadget :185-239
spawns one goroutine + stream per gadget pod, node-filter param, per-node
error isolation in CombinedGadgetResult, interval snapshots merged via the
snapshot combiner (:196-207), one-shot events accumulated then flushed,
stop-request fan-out with a 30s result timeout (:336-353).

TPU-native addition: a "summary" output mode where nodes stream sketch
digests instead of raw events; the client merges digests (mergeable by
construction) — the low-bandwidth analogue of the psum path used when
nodes don't share a TPU slice.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable

from ..gadgets.context import GadgetContext
from ..gadgets.interface import GadgetType
from ..params import ParamDesc, ParamDescs, TypeHint, parse_duration
from ..snapshotcombiner import SnapshotCombiner
from ..telemetry import counter, gauge
from ..telemetry.tracing import TRACER
from .runtime import CombinedGadgetResult, GadgetResult, Runtime
from .supervisor import FleetHealth, NodeSupervisor, RetryPolicy, classify_error

STOP_RESULT_TIMEOUT = 30.0  # default; ref: grpc-runtime.go:347-353
                            # (runtime param stop-result-timeout overrides)

# fan-out telemetry: message-grain per node (a message carries a row array
# or batch); lag is read at SCRAPE time as the age of the node's last
# message — a node whose gauge grows while its peers' stay flat is stalled
# or unreachable (an on-message gauge would freeze at its last healthy
# value during exactly that outage)
_tm_node_events = counter("ig_runtime_node_events_total",
                          "rows received from each node's stream", ("node",))
_tm_node_errors = counter("ig_runtime_node_errors_total",
                          "per-node gadget-run errors by class "
                          "(transport = flaky network, retried with "
                          "resume; fatal = broken gadget, not retried)",
                          ("node", "class"))
_tm_seq_gaps = counter("ig_runtime_seq_gaps_total",
                       "stream messages lost in transit per node "
                       "(client-observed seq gaps, incl. resume-ring "
                       "overflow during outages)", ("node",))
_tm_node_lag = gauge("ig_runtime_node_stream_lag_seconds",
                     "seconds since each node's last stream message "
                     "(grows while a node is stalled)", ("node",))


def _validate_positive_duration(value: str) -> None:
    if parse_duration(value) <= 0:
        raise ValueError(f"duration {value!r} must be > 0")


class GrpcRuntime(Runtime):
    name = "grpc"

    def __init__(self, targets: dict[str, str], dialer_factory=None):
        """targets: node_name → grpc target (host:port or unix:///path).
        dialer_factory(node, target) -> Dialer lets fan-out reach agents
        with no routable address (exec tunnels — k8s-exec-dialer.go)."""
        self.targets = targets
        self.dialer_factory = dialer_factory
        self._clients: dict[str, Any] = {}

    def params(self) -> ParamDescs:
        from ..agent import wire
        from ..params.validators import validate_int_range, validate_one_of
        return ParamDescs([
            ParamDesc(key="node", default="",
                      description="restrict to one node"),
            ParamDesc(key="stop-result-timeout",
                      default=f"{STOP_RESULT_TIMEOUT:g}s",
                      type_hint=TypeHint.DURATION,
                      validator=_validate_positive_duration,
                      description="how long to wait for node results "
                                  "after the stop fan-out (ref: "
                                  "grpc-runtime.go:347-353)"),
            ParamDesc(key="supervise", default="true",
                      type_hint=TypeHint.BOOL,
                      description="supervise node streams: reconnect "
                                  "with resume on transport errors "
                                  "instead of abandoning the node"),
            ParamDesc(key="retry-base", default="200ms",
                      type_hint=TypeHint.DURATION,
                      validator=_validate_positive_duration,
                      description="reconnect backoff base (full-jitter "
                                  "exponential)"),
            ParamDesc(key="retry-cap", default="3s",
                      type_hint=TypeHint.DURATION,
                      validator=_validate_positive_duration,
                      description="reconnect backoff ceiling"),
            ParamDesc(key="retry-horizon", default="30s",
                      type_hint=TypeHint.DURATION,
                      validator=_validate_positive_duration,
                      description="outage length after which a node is "
                                  "labeled dead (retries continue at the "
                                  "capped rate; a later heal resurrects "
                                  "it)"),
            ParamDesc(key="attempt-deadline", default="5s",
                      type_hint=TypeHint.DURATION,
                      validator=_validate_positive_duration,
                      description="per-attempt connect deadline while "
                                  "reconnecting"),
            ParamDesc(key="resume-linger", default="10s",
                      type_hint=TypeHint.DURATION,
                      validator=_validate_positive_duration,
                      description="how long the agent keeps a "
                                  "disconnected run alive awaiting a "
                                  "resume"),
            ParamDesc(key="resume-ring", default="1024",
                      type_hint=TypeHint.INT,
                      validator=validate_int_range(lo=1),
                      description="outbound messages the agent retains "
                                  "for seq replay on resume"),
            ParamDesc(key="straggler-factor", default="4.0",
                      type_hint=TypeHint.FLOAT,
                      description="a node silent for more than this × "
                                  "the fleet's rolling inter-record p95 "
                                  "is marked straggling"),
            ParamDesc(key="straggler-floor", default="1s",
                      type_hint=TypeHint.DURATION,
                      validator=_validate_positive_duration,
                      description="minimum straggler silence threshold "
                                  "(no flapping on µs cadences)"),
            ParamDesc(key="backfill", default="true",
                      type_hint=TypeHint.BOOL,
                      description="heal seq gaps from the node's sealed "
                                  "history windows after an outage"),
            # shared-run multiplexing + overload protection: validated
            # LOUDLY here (the stop-result-timeout pattern) before the
            # first attach ever goes on the wire
            ParamDesc(key="share", default="false",
                      type_hint=TypeHint.BOOL,
                      description="share the gadget run: the first "
                                  "request for a (gadget, params, "
                                  "outputs) key starts the gadget, "
                                  "compatible requests attach as "
                                  "subscribers to the same pipeline"),
            ParamDesc(key="max-subscribers", default="16",
                      type_hint=TypeHint.INT,
                      validator=validate_int_range(lo=1),
                      description="admission cap on subscribers per "
                                  "shared run"),
            ParamDesc(key="sub-queue", default="1024",
                      type_hint=TypeHint.INT,
                      validator=validate_int_range(lo=1),
                      description="per-subscriber bounded delivery "
                                  "queue (messages); a slow consumer "
                                  "drops its own records, never its "
                                  "peers'"),
            ParamDesc(key="sub-budget", default="16384",
                      type_hint=TypeHint.INT,
                      validator=validate_int_range(lo=1),
                      description="per-run queued-capacity budget "
                                  "across all subscribers; low-priority "
                                  "admissions are refused first near "
                                  "the budget"),
            ParamDesc(key="drop-policy", default="drop-oldest",
                      validator=validate_one_of(wire.DROP_POLICIES),
                      description="which record a full subscriber "
                                  "queue sacrifices"),
            ParamDesc(key="priority", default="normal",
                      validator=validate_one_of(wire.PRIORITIES),
                      description="this subscriber's admission/"
                                  "protection class under overload"),
            ParamDesc(key="evict-after", default="10s",
                      type_hint=TypeHint.DURATION,
                      validator=_validate_positive_duration,
                      description="a subscriber stalled (queue full, "
                                  "client not draining) longer than "
                                  "this is evicted with a labeled "
                                  "terminal record"),
            ParamDesc(key="run-keepalive", default="10s",
                      type_hint=TypeHint.DURATION,
                      validator=_validate_positive_duration,
                      description="after the last subscriber detaches "
                                  "the gadget keeps running this long "
                                  "awaiting a re-attach (no capture "
                                  "thrash on dashboard churn)"),
        ])

    def _rp(self, ctx: GadgetContext, key: str):
        """Runtime param with default fallback: contexts built without
        this runtime's params (tests, older callers) get the documented
        defaults instead of KeyErrors."""
        if key in ctx.runtime_params:
            return ctx.runtime_params.get(key)
        return self.params().get(key).to_param()

    def _client(self, node: str):
        from ..agent.client import AgentClient
        if node not in self._clients:
            dialer = (self.dialer_factory(node, self.targets[node])
                      if self.dialer_factory else None)
            self._clients[node] = AgentClient(self.targets[node], node,
                                              dialer=dialer)
        return self._clients[node]

    def close(self) -> None:
        for c in self._clients.values():
            c.close()
        self._clients.clear()

    def get_catalog(self) -> dict:
        for node in self.targets:
            try:
                return self._client(node).get_catalog()
            except Exception:
                continue
        return super().get_catalog()

    # -- recording lifecycle fan-out (capture/) -----------------------------

    def _fanout_unary(self, fn, nodes=None) -> tuple[dict, dict]:
        """(per-node results, per-node errors) — the per-node isolation
        contract every fan-out verb follows (runtime.go:42-79)."""
        results: dict[str, dict] = {}
        errors: dict[str, str] = {}
        for node in (nodes or self.targets):
            try:
                results[node] = fn(self._client(node))
            except Exception as e:  # noqa: BLE001 — per-node isolation
                errors[node] = str(e)
        return results, errors

    def start_recording(self, recording_id: str,
                        opts: dict | None = None) -> tuple[dict, dict]:
        return self._fanout_unary(
            lambda c: c.start_recording(recording_id, opts=opts))

    def stop_recording(self, recording_id: str) -> tuple[dict, dict]:
        return self._fanout_unary(lambda c: c.stop_recording(recording_id))

    def list_recordings(self, recording_id: str = "") -> tuple[dict, dict]:
        return self._fanout_unary(lambda c: c.list_recordings(recording_id))

    def fetch_recording(self, recording_id: str, dest_dir: str) -> dict:
        """Pull every node's journals for one recording into a single
        client-side bundle:

            <dest_dir>/
              bundle.json          # which nodes, how much, what failed
              <node>/<journal>/... # each node's recording dir, verbatim

        Per-node errors are recorded in the bundle manifest, never
        fatal — a crashed node's journals are exactly the ones worth
        fetching from its peers."""
        import json
        import os
        import time as _time
        per_node: dict[str, dict] = {}
        errors: dict[str, str] = {}
        for node in self.targets:
            try:
                per_node[node] = self._client(node).fetch_recording(
                    recording_id, os.path.join(dest_dir, node))
            except Exception as e:  # noqa: BLE001 — per-node isolation
                errors[node] = str(e)
        bundle = {
            "schema": "ig-tpu/capture-bundle/v1",
            "recording_id": recording_id,
            "fetched_ts": _time.time(),
            "nodes": per_node,
            "errors": errors,
        }
        os.makedirs(dest_dir, exist_ok=True)
        with open(os.path.join(dest_dir, "bundle.json"), "w",
                  encoding="utf-8") as f:
            json.dump(bundle, f, sort_keys=True, indent=2)
        return bundle

    # -- sketch-history fan-out (history/) ----------------------------------

    def list_windows(self, **kw) -> tuple[dict, dict]:
        """Per-node sealed-window header rows overlapping a range/slice
        (kw: gadget, start_ts/end_ts, start_seq/end_seq, key)."""
        return self._fanout_unary(lambda c: c.list_windows(**kw))

    def fetch_windows(self, **kw) -> tuple[dict, dict]:
        """Per-node (frames, losses) for every matching window. The
        pull is index-guided: each node is first asked to LIST, and
        nodes with zero overlapping windows are never asked for bytes."""
        def pull(c):
            listing = c.list_windows(**kw)
            if not listing.get("windows"):
                return {"frames": [], "losses": listing.get("losses") or []}
            frames, losses = c.fetch_windows(**kw)
            return {"frames": frames, "losses": losses}
        return self._fanout_unary(pull)

    def query_history(self, *, key: str | None = None, top: int = 20,
                      pushdown: bool = True, topology=None, **kw) -> "Any":
        """The fleet-wide range query. Preferred path: QueryWindows
        PUSHDOWN — every agent folds the query node-side and ships ONE
        merged window, so wire cost is O(nodes) instead of O(windows).
        Agents that predate the RPC (UNIMPLEMENTED) fall back PER NODE
        to the PR-6 list+fetch pull, and the answer records which path
        each node took (`answer.paths`). Per-node errors are recorded
        in the answer, never fatal: a crashed node's peers still answer
        for their share.

        With `topology` (a fleet.Topology or a spec string — "auto",
        "auto:<fan_in>", or the declared zone grammar), the fold routes
        through the aggregation tier instead of one flat client loop:
        per-node summaries fold zone-by-zone up the merge tree
        (fleet.fold_tree), byte-identical to the flat fold by the merge
        algebra's associativity, with per-leaf path accounting and a
        flat re-fold of any subtree whose aggregator fails. The tree's
        shape accounting lands in `answer.fleet`."""
        import grpc as _grpc

        from ..history import (answer_query, decode_frames,
                               dedupe_compacted, level_counts)

        if topology is not None:
            return self._query_history_tree(
                topology, key=key, top=top, pushdown=pushdown, **kw)
        windows = []
        dropped: list[str] = []
        errors: dict[str, str] = {}
        paths: dict[str, str] = {}
        levels_total: dict[int, int] = {}

        def add_levels(levels: dict[int, int]) -> None:
            for lvl, n in levels.items():
                levels_total[lvl] = levels_total.get(lvl, 0) + n

        def add_losses(node: str, losses) -> None:
            for loss in losses or ():
                dropped.append(f"{node}: torn window tail "
                               f"({loss.get('reason', '?')}, "
                               f"{loss.get('dropped_bytes', 0)} bytes)")

        for node in self.targets:
            client = self._client(node)
            res = None
            if pushdown:
                try:
                    res = client.query_windows(key=key, **kw)
                except _grpc.RpcError as e:
                    if e.code() != _grpc.StatusCode.UNIMPLEMENTED:
                        errors[node] = f"{e.code().name}: {e.details()}"
                        paths[node] = "pushdown"
                        continue
                    # pre-pushdown agent: fall through to list+fetch
                except Exception as e:  # noqa: BLE001 — per-node isolation
                    errors[node] = str(e)
                    paths[node] = "pushdown"
                    continue
            if res is not None:
                paths[node] = "pushdown"
                if res["window"] is not None:
                    windows.append(res["window"])
                add_levels(res["levels"])
                for note in res["dropped"]:
                    dropped.append(f"{node}: {note}")
                add_losses(node, res["losses"])
                continue
            paths[node] = "fetch"
            try:
                listing = client.list_windows(key=key, **kw)
                if listing.get("windows"):
                    frames, losses = client.fetch_windows(key=key, **kw)
                else:
                    frames, losses = [], listing.get("losses") or []
                kept, notes = dedupe_compacted(decode_frames(frames))
                windows.extend(kept)
                add_levels(level_counts(kept))
                for note in notes:
                    dropped.append(f"{node}: {note}")
                add_losses(node, losses)
            except Exception as e:  # noqa: BLE001 — per-node isolation
                errors[node] = str(e)
        # determinism pin: fold in canonical window order, not reply
        # arrival order — the merge's label-map update is last-wins and
        # its geometry base is first-wins, so an unsorted fold would let
        # scheduling leak into the summary bytes (and break the tree
        # tier's byte-identity anchor)
        from ..fleet import canonical_order
        return answer_query(canonical_order(windows), key=key, top=top,
                            dropped=dropped, errors=errors,
                            levels=levels_total, paths=paths)

    def _query_history_tree(self, topology, *, key: str | None, top: int,
                            pushdown: bool, **kw) -> "Any":
        """query_history routed through the fleet aggregation tier."""
        import grpc as _grpc

        from ..fleet import flat_summary, fold_tree, parse_topology
        from ..fleet.topology import Topology
        from ..history import (answer_query, decode_frames,
                               dedupe_compacted, level_counts)
        if not isinstance(topology, Topology):
            topology = parse_topology(str(topology), self.targets)
        gadget = kw.get("gadget") or "fleet"

        def fetch_leaf(node: str) -> dict:
            """One leaf's share, reduced to the pushdown reply shape
            (ONE merged window + accounting). Pre-pushdown agents fall
            back to list+fetch and fold client-side to the same shape;
            unreachable agents raise (fold_tree isolates them)."""
            client = self._client(node)
            if pushdown:
                try:
                    return client.query_windows(key=key, **kw)
                except _grpc.RpcError as e:
                    if e.code() != _grpc.StatusCode.UNIMPLEMENTED:
                        raise RuntimeError(
                            f"{e.code().name}: {e.details()}") from e
                    # pre-pushdown agent: fall through to list+fetch
            listing = client.list_windows(key=key, **kw)
            if listing.get("windows"):
                frames, losses = client.fetch_windows(key=key, **kw)
            else:
                frames, losses = [], listing.get("losses") or []
            kept, notes = dedupe_compacted(decode_frames(frames))
            return {"node": node,
                    "window": flat_summary(kept, gadget=gadget, node=node),
                    "folded": True, "levels": level_counts(kept),
                    "torn": 0, "dropped": notes, "losses": losses}

        tf = fold_tree(topology, fetch_leaf, gadget=gadget)
        ans = answer_query(
            [tf.window] if tf.window is not None else [],
            key=key, top=top, dropped=tf.dropped, errors=tf.errors,
            levels=tf.levels, paths=tf.paths)
        # the root window answers as node "fleet"; report the leaves
        # that actually contributed, like the flat fold does
        ans.nodes = [n for n in sorted(tf.paths)
                     if tf.paths.get(n) != "unreachable"]
        ans.fleet = {
            "depth": tf.depth,
            "fan_in": topology.fan_in(),
            "aggregators": len(topology.aggregators()),
            "subtree_folds": tf.subtree_folds,
            "fallback": list(tf.fallback),
            "aggregate": tf.aggregate,
        }
        return ans

    # -- shared-run plane (subscribe-aware fan-out) --------------------------

    def list_runs(self, gadget: str = "") -> tuple[dict, dict]:
        """Per-node live shared-run rows (subscriber counts/classes,
        queue depths, drops, keepalive state) — the attach-by-key
        discovery surface `ig-tpu fleet runs` renders."""
        return self._fanout_unary(
            lambda c: {"runs": c.shared_runs(gadget=gadget)})

    def subscribe_summaries(
        self,
        *,
        gadget: str = "",
        run_id: str = "",
        on_summary: Callable[[str, dict], None] | None = None,
        on_alert: Callable[[str, dict], None] | None = None,
        on_window: Callable[[str, dict], None] | None = None,
        stop_event: threading.Event | None = None,
        priority: str = "low",
        queue: int = 256,
    ) -> dict:
        """The summary pub/sub tier: attach a cheap summary-only
        subscriber to every node's matching shared run — harvest
        summaries, alert transitions, and sealed-window announcements
        from ONE shared harvest, never the raw batches. Blocks until
        stop_event (or every stream ends); returns per-node accounting
        ({node: out-dict}; nodes with no matching run report an error
        entry, never raise)."""
        stop_event = stop_event or threading.Event()
        results: dict[str, dict] = {}
        results_mu = threading.Lock()

        def run_node(node: str):
            client = self._client(node)
            try:
                rid = run_id
                if not rid:
                    rows = client.shared_runs(gadget=gadget)
                    if not rows:
                        raise RuntimeError(
                            f"no live shared run for {gadget or '<any>'!r}")
                    rid = rows[0]["run_id"]
                out = client.run_gadget(
                    "", "", attach_to=rid,
                    subscriber={"tier": "summary", "priority": priority,
                                "queue": int(queue)},
                    on_summary=on_summary, on_alert=on_alert,
                    on_window=on_window, stop_event=stop_event)
            except Exception as e:  # noqa: BLE001 — per-node isolation
                out = {"error": str(e)}
            with results_mu:
                results[node] = out

        threads = [threading.Thread(target=run_node, args=(n,), daemon=True)
                   for n in self.targets]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return results

    def subscribe_query(
        self,
        *,
        query_id: str,
        gadget: str = "",
        run_id: str = "",
        on_answer: Callable[["Any", dict], None] | None = None,
        stop_event: threading.Event | None = None,
        priority: str = "low",
        queue: int = 256,
    ) -> dict:
        """Fleet fan-out for ONE standing query: attach a summary-tier
        subscriber to every node's matching shared run and fold each
        node's materialized answer (EV_QUERY) client-side — the same
        merge algebra QueryWindows replies fold with, so the fleet
        answer is exactly what an ad-hoc fleet query over the same
        coverage would compute. on_answer(answer, meta) fires on every
        node refresh with the latest per-node windows folded;
        meta carries per-node coverage digests and ticks. Blocks until
        stop_event; returns per-node stream accounting."""
        from ..history import answer_query
        from ..history.query import unpack_frames
        from ..history.window import decode_window

        stop_event = stop_event or threading.Event()
        latest: dict[str, tuple[dict, "Any"]] = {}
        latest_mu = threading.Lock()

        def on_query(node: str, qheader: dict, payload: bytes):
            if qheader.get("id") != query_id:
                return
            frames, dropped_bytes = unpack_frames(payload)
            if not frames:
                return
            win = decode_window(*frames[0])
            with latest_mu:
                latest[node] = (qheader, win)
                snap = sorted(latest.items())
            if on_answer is None:
                return
            answer = answer_query(
                [w for _, (_, w) in snap],
                key=(qheader.get("key") or None),
                top=int(qheader.get("top", 20)),
                dropped=([f"{node}: torn answer tail "
                          f"({dropped_bytes} bytes)"]
                         if dropped_bytes else None))
            meta = {
                "id": query_id,
                "from_node": node,
                "nodes": {n: {"tick": h.get("tick", 0),
                              "windows": h.get("windows", 0),
                              "coverage_digest":
                                  h.get("coverage_digest", "")}
                          for n, (h, _) in snap},
            }
            on_answer(answer, meta)

        results: dict[str, dict] = {}
        results_mu = threading.Lock()

        def run_node(node: str):
            client = self._client(node)
            try:
                rid = run_id
                if not rid:
                    rows = client.shared_runs(gadget=gadget)
                    if not rows:
                        raise RuntimeError(
                            f"no live shared run for {gadget or '<any>'!r}")
                    rid = rows[0]["run_id"]
                out = client.run_gadget(
                    "", "", attach_to=rid,
                    subscriber={"tier": "summary", "priority": priority,
                                "queue": int(queue)},
                    on_query=on_query, stop_event=stop_event)
            except Exception as e:  # noqa: BLE001 — per-node isolation
                out = {"error": str(e)}
            with results_mu:
                results[node] = out

        threads = [threading.Thread(target=run_node, args=(n,), daemon=True)
                   for n in self.targets]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return results

    def run_gadget(
        self,
        ctx: GadgetContext,
        *,
        on_event: Callable[[Any], None] | None = None,
        on_event_array: Callable[[list], None] | None = None,
        on_batch: Callable[[Any], None] | None = None,
        on_summary: Callable[[str, dict], None] | None = None,
        on_alert: Callable[[dict], None] | None = None,
    ) -> CombinedGadgetResult:
        # the client runtime mints the trace: one trace ID per gadget run,
        # propagated through every node's RunGadget request so client,
        # agent, operator, and device spans stitch into a single timeline
        with TRACER.span(f"client/run/{ctx.desc.full_name}",
                         parent=ctx.extra.get("trace_ctx"),
                         attrs={"run_id": ctx.run_id,
                                "gadget": ctx.desc.full_name}) as root:
            ctx.extra["trace_ctx"] = root.context
            return self._run_fanout(ctx, root, on_event, on_event_array,
                                    on_batch, on_summary, on_alert)

    def _run_fanout(
        self,
        ctx: GadgetContext,
        root_span,
        on_event: Callable[[Any], None] | None,
        on_event_array: Callable[[list], None] | None,
        on_batch: Callable[[Any], None] | None,
        on_summary: Callable[[str, dict], None] | None,
        on_alert: Callable[[dict], None] | None,
    ) -> CombinedGadgetResult:
        node_filter = ""
        if "node" in ctx.runtime_params:
            node_filter = ctx.runtime_params.get("node").as_string()
        nodes = [n for n in self.targets if not node_filter or n == node_filter]

        flat = ctx.gadget_params.copy_to_map(prefix="gadget.")
        flat.update(ctx.operator_params.copy_to_map())

        cols = ctx.columns
        is_interval = ctx.desc.gadget_type == GadgetType.TRACE_INTERVALS
        is_one_shot = ctx.desc.gadget_type == GadgetType.ONE_SHOT

        outputs = ["json"]
        if on_batch is not None:
            outputs.append("batch")
        if on_summary is not None:
            outputs.append("summary")
        if ctx.extra.get("output") == "json":
            outputs.append("result-json")  # server-side result rendering
        if is_one_shot and on_event_array is not None:
            # ask the agent to stream one-shot rows for client-side combining;
            # without this the agent renders result bytes per node as before
            outputs.append("combiner")

        # cadence derives from the gadget's own interval param, TTL = 2
        # ticks of that interval — the reference's parser.EnableSnapshots
        # (interval, ttl=2) contract (grpc-runtime.go:196-202)
        interval = 1.0
        if is_interval and "interval" in ctx.gadget_params:
            interval = ctx.gadget_params.get("interval").as_duration() or 1.0
        combiner = SnapshotCombiner(ttl_ticks=2) if is_interval else None
        # one-shot: accumulate every node's rows, flush once when all nodes
        # are done (ref: parser.EnableCombiner + Flush, grpc-runtime.go:204-207)
        one_shot_rows: list = []

        results = CombinedGadgetResult()
        results_mu = threading.Lock()
        stop_event = threading.Event()

        # supervision knobs (runtime params with documented defaults)
        supervise = self._rp(ctx, "supervise").as_bool()
        policy = RetryPolicy(
            base=self._rp(ctx, "retry-base").as_duration(),
            cap=max(self._rp(ctx, "retry-cap").as_duration(),
                    self._rp(ctx, "retry-base").as_duration()),
            horizon=self._rp(ctx, "retry-horizon").as_duration(),
            attempt_deadline=self._rp(ctx, "attempt-deadline").as_duration())
        resume_linger = self._rp(ctx, "resume-linger").as_duration()
        resume_ring = self._rp(ctx, "resume-ring").as_int()
        backfill = self._rp(ctx, "backfill").as_bool()
        stop_timeout = self._rp(ctx, "stop-result-timeout").as_duration()
        # shared-run / overload knobs (validated at the params layer —
        # a bad value never reaches the wire)
        share = self._rp(ctx, "share").as_bool()
        run_keepalive = self._rp(ctx, "run-keepalive").as_duration()
        max_subscribers = self._rp(ctx, "max-subscribers").as_int()
        sub_budget = self._rp(ctx, "sub-budget").as_int()
        subscriber_opts = {
            "priority": self._rp(ctx, "priority").as_string(),
            "drop_policy": self._rp(ctx, "drop-policy").as_string(),
            "queue": self._rp(ctx, "sub-queue").as_int(),
            "evict_after": self._rp(ctx, "evict-after").as_duration(),
        }
        health = FleetHealth(
            nodes,
            straggler_factor=self._rp(ctx, "straggler-factor").as_float(),
            straggler_floor=self._rp(ctx, "straggler-floor").as_duration(),
        )
        ctx.extra["fleet_health"] = health  # live view for embedders

        last_msg = {n: time.monotonic() for n in nodes}
        for n in nodes:
            # scrape-time age: keeps growing while the node is silent
            _tm_node_lag.labels(node=n).set_function(
                lambda n=n: time.monotonic() - last_msg[n])

        def _mark(node: str, events: int):
            last_msg[node] = time.monotonic()
            if events:
                _tm_node_events.labels(node=node).inc(events)

        def on_json(node: str, row: dict):
            _mark(node, 1)
            if on_event is not None and cols is not None:
                ev = cols.from_dict(row)
                ev.node = ev.node or node
                on_event(ev)

        def on_array(node: str, rows: list):
            _mark(node, len(rows))
            if cols is None:
                return
            evs = []
            for r in rows:
                ev = cols.from_dict(r)
                ev.node = ev.node or node
                evs.append(ev)
            if combiner is not None:
                combiner.add_snapshot(node, evs)
            elif is_one_shot:
                with results_mu:
                    one_shot_rows.extend(evs)
            elif on_event_array is not None:
                on_event_array(evs)

        # cluster-wide alert dedup: the same rule+key firing on N nodes
        # folds into ONE alert carrying the node list; resolved only when
        # the last node resolves (PSketch's priority-flow fan-in, here at
        # the client tier)
        from ..alerts import ClusterAlertAggregator
        aggregator = ClusterAlertAggregator(on_alert)

        def on_node_alert(node: str, alert: dict):
            _mark(node, 0)
            aggregator.observe(node, alert)

        def on_remote_log(n: str, sev: int, msg: str, header: dict):
            # remote run/trace IDs ride the record as attrs, so the
            # flight recorder can correlate the line with its spans
            from ..utils.logger import std_from_severity
            ctx.logger.log(std_from_severity(sev), "[%s] %s", n, msg,
                           extra={"run_id": header.get("run_id", ""),
                                  "trace_id": header.get("trace_id", "")})

        def run_node(node: str):
            # one child span per node stream; its context rides the run
            # request so the agent's server spans parent to it. The
            # supervisor owns reconnect/resume around the raw stream
            # call; per-node isolation (runtime.go:42-79) is the outer
            # except.
            with TRACER.span(f"client/node/{node}",
                             parent=root_span.context,
                             attrs={"node": node}) as nsp:
                client = self._client(node)
                run_id = f"{ctx.run_id}-{node}"

                def on_msg(_n: str, _seq: int, _t: int, node=node):
                    health.observe(node)

                sup = NodeSupervisor(
                    node, client, policy=policy, health=health,
                    run_id=run_id, gadget=ctx.desc.full_name,
                    done=lambda: ctx.done or stop_event.is_set(),
                    logger=ctx.logger, backfill=backfill)

                def attempt(resume_from, rid, node=node, nsp=nsp):
                    return client.run_gadget(
                        ctx.desc.category, ctx.desc.name, flat,
                        timeout=ctx.timeout, outputs=tuple(outputs),
                        on_json=on_json, on_array=on_array,
                        on_batch=(lambda n, b: on_batch(b)) if on_batch else None,
                        on_summary=on_summary,
                        on_alert=on_node_alert,
                        on_log=on_remote_log,
                        on_message=on_msg,
                        stop_event=stop_event,
                        trace_ctx=nsp.context,
                        run_id=rid,
                        resumable=supervise,
                        linger=resume_linger,
                        ring=resume_ring,
                        resume_from=resume_from,
                        # name WHICH subscriber is reconnecting: without
                        # the acked sub_id a shared run would resolve
                        # the resume onto a peer's stream
                        sub_id=sup.sub_id or None,
                        share=share,
                        keepalive=run_keepalive if share else None,
                        max_subscribers=max_subscribers if share else None,
                        sub_budget=sub_budget if share else None,
                        subscriber=subscriber_opts if share else None,
                    )

                try:
                    if supervise:
                        out = sup.run(attempt)
                    else:
                        out = attempt(None, run_id)
                        if out.get("error"):
                            health.mark(node, "dead")
                    with results_mu:
                        results[node] = GadgetResult(
                            result=out.get("result"),
                            error=out.get("error"),
                            gaps=int(out.get("gaps") or 0),
                            reconnects=int(out.get("reconnects") or 0),
                            records=int(out.get("records") or 0),
                            last_seq=int(out.get("last_seq") or 0),
                            backfilled=int(out.get("backfilled") or 0),
                            backfill=list(out.get("backfill") or ()),
                            health=health.get(node),
                            sub_drops=int(out.get("sub_drops") or 0),
                            evicted=bool(out.get("evicted")),
                            attach_refused=str(
                                out.get("attach_refused") or ""),
                            shared=bool((out.get("attach") or {}).get(
                                "shared")))
                        if out.get("error"):
                            _tm_node_errors.labels(
                                node=node,
                                **{"class": classify_error(
                                    out["error"],
                                    gadget_error=bool(
                                        out.get("gadget_error")))}).inc()
                        if out.get("gaps"):
                            _tm_seq_gaps.labels(node=node).inc(out["gaps"])
                            ctx.logger.warning(
                                "[%s] %d stream message(s) lost in transit "
                                "(%d healed from sealed windows)",
                                node, out["gaps"],
                                int(out.get("backfilled") or 0))
                except Exception as e:  # per-node isolation (runtime.go:42-79)
                    nsp.set_attr("error", str(e))
                    _tm_node_errors.labels(node=node, **{"class": "fatal"}).inc()
                    health.mark(node, "dead")
                    with results_mu:
                        results[node] = GadgetResult(error=str(e),
                                                     health="dead")
                finally:
                    # this node's supervision is over: its final health
                    # label is settled — the straggler monitor must not
                    # re-flag its post-run silence
                    health.finish(node)
                    # stream end reconciles this node's alerts: a dropped
                    # EV_ALERT 'resolved' (or a crashed node) must not
                    # wedge a cluster alert active forever
                    aggregator.node_done(node)

        threads = [threading.Thread(target=run_node, args=(n,), daemon=True)
                   for n in nodes]
        for t in threads:
            t.start()

        ticker_stop = threading.Event()
        if combiner is not None and on_event_array is not None:
            def tick_loop():
                while not ticker_stop.wait(interval):
                    on_event_array(combiner.get_snapshots())

            threading.Thread(target=tick_loop, daemon=True).start()

        # straggler monitor: a node silent for more than
        # straggler-factor × the fleet's rolling inter-record p95 is
        # flagged — slow relative to its PEERS, not to a wall-clock
        # constant (the fleet defines normal cadence). It stops the
        # moment the run starts winding down: silence during shutdown
        # is expected, and flagging it would mislabel a complete
        # answer as partial.
        straggle_stop = threading.Event()

        def straggle_loop():
            while not straggle_stop.wait(0.25):
                for flagged in health.check_stragglers():
                    ctx.logger.warning(
                        "[%s] straggling: silent for %.2fs (fleet p95 "
                        "threshold %.2fs)", flagged,
                        health.silence(flagged),
                        health.straggler_threshold())

        threading.Thread(target=straggle_loop, daemon=True).start()

        # all node streams finishing on their own (one-shot / run-with-result
        # gadgets) also ends the run — don't wait for a timeout that never fires
        def all_done_watch():
            for t in threads:
                t.join()
            ctx.cancel()

        threading.Thread(target=all_done_watch, daemon=True).start()

        # wait: context timeout/cancel then stop-fanout (ref: :336-353)
        ctx.wait_for_timeout_or_done()
        straggle_stop.set()
        stop_event.set()
        # ONE stop window shared by every node (not N× sequential joins:
        # a wide partition at stop time must not scale the wait with
        # fleet size)
        join_deadline = time.monotonic() + stop_timeout
        for t in threads:
            t.join(timeout=max(0.0, join_deadline - time.monotonic()))
        ticker_stop.set()
        # a stream wedged past the stop window must yield a LABELED dead
        # node, not a hang and not a silently missing key
        with results_mu:
            wedged = [n for n in nodes if n not in results]
            for n in wedged:
                results[n] = GadgetResult(
                    error=f"node stream still wedged {stop_timeout:.0f}s "
                          f"after stop fan-out", health="dead")
        for n in wedged:
            health.mark(n, "dead")
        if is_one_shot and on_event_array is not None:
            # flush even when empty so callers still see `[]` / a header,
            # matching the local path
            on_event_array(one_shot_rows)
        # final fleet-health labels ride the combined result so a partial
        # answer is LABELED partial (results.partial), never silently
        # full-looking
        results.health = health.states()
        if results.partial:
            degraded = {n: s for n, s in results.health.items()
                        if s != "healthy"}
            ctx.logger.warning(
                "partial result: %d/%d node(s) contributed (unhealthy: %s)",
                len(results.contributing()), len(nodes), degraded)
        overloaded = results.overloaded()
        if overloaded:
            ctx.logger.warning(
                "subscriber stream(s) degraded under fan-out: %s "
                "(drops are this client's own queue, peers unaffected)",
                overloaded)
        return results
