"""Hook installation on the host — the entrypoint's other half.

Reference contract: gadget-container/entrypoint.sh:83-142 detects the
container runtime and installs synchronous container-lifecycle hooks
before starting the daemon: crio-style OCI hook configs copied into the
host's hooks.d directories (each pointing at a tiny binary that reads the
OCI state from stdin and calls AddContainer/RemoveContainer over the
agent socket — hooks/oci/main.go:1-156, prestart.sh/poststop.sh), or an
NRI plugin registered in /etc/nri/conf.json (hooks/nri/main.go:1-148).
Fanotify needs no installation (the in-process watch).

Here the hook "binary" is this package itself: the installed config
invokes `ig-tpu-agent oci-hook <stage> --socket <sock>` (main.py), which
reads the OCI state JSON from stdin, enriches identity from the bundle's
config.json annotations (oci_annotations dialect resolvers), and calls
the agent's AddContainer/RemoveContainer — so a runtime-invoked hook
lands the container in the collection synchronously at creation, not at
the next poll tick.

All host paths are taken relative to `host_root` so deployments mount
the host filesystem at /host (as the reference's DaemonSet does) and
tests use a scratch directory.
"""

from __future__ import annotations

import dataclasses
import json
import os
import shlex
import stat
import sys
from pathlib import Path

# crio-style OCI hook config directories, in install-preference order
# (ref: entrypoint.sh:88-90)
OCI_HOOK_DIRS = ("etc/containers/oci/hooks.d",
                 "usr/share/containers/oci/hooks.d")
NRI_CONF = "etc/nri/conf.json"
NRI_BIN_DIR = "opt/nri/bin"

_PRESTART = "ig-tpu-prestart.json"
_POSTSTOP = "ig-tpu-poststop.json"
_NRI_PLUGIN = "ig-tpu-nri"

# a hook stalls the runtime's container-create path, so it must give up
# fast when the agent is unresponsive (never the 30s client default)
_HOOK_TIMEOUT = 3.0


@dataclasses.dataclass
class InstallResult:
    mode: str                    # oci | nri | fanotify | none
    installed: list[str]         # files written/updated on the host
    notes: list[str]
    degraded: bool = False       # mode is a fallback from a failed install


def detect_hook_mode(host_root: str = "/") -> str:
    """Runtime detection → hook mode (ref: entrypoint.sh:21-82 HOOK_MODE
    auto-detection): cri-o prefers OCI hook configs, containerd prefers
    NRI, docker/unknown fall back to the in-process fanotify watch."""
    root = Path(host_root)
    if (root / "run/crio/crio.sock").exists():
        return "oci"
    if (root / "run/containerd/containerd.sock").exists():
        return "nri"
    return "fanotify"


def _hook_command(socket: str) -> list[str]:
    """The command a runtime invokes; args[0] is the path per OCI spec."""
    return [sys.executable, "-m", "inspektor_gadget_tpu.agent.main",
            "oci-hook", "--socket", socket]


def _oci_hook_config(stage: str, cmd: list[str]) -> dict:
    # crio hook config schema 1.0.0 (ref: gadget-{prestart,poststop}.json)
    return {
        "version": "1.0.0",
        "hook": {
            "path": cmd[0],
            "args": [os.path.basename(cmd[0])] + cmd[1:] + ["--stage", stage],
        },
        "when": {"always": True},
        "stages": [stage],
    }


class HookInstaller:
    def __init__(self, host_root: str = "/",
                 agent_socket: str = "unix:///tmp/igtpu-agent.sock",
                 hook_cmd: list[str] | None = None):
        self.host_root = Path(host_root)
        self.agent_socket = agent_socket
        # the command the HOST runtime will exec; override when installing
        # from inside a container whose interpreter/package paths don't
        # exist on the host (the reference copies a self-contained binary)
        self.hook_cmd = hook_cmd

    def _cmd(self) -> list[str]:
        return self.hook_cmd or _hook_command(self.agent_socket)

    def _host_path_notes(self) -> list[str]:
        # hook.path is executed by the host runtime, not this container:
        # warn when the interpreter is visibly absent from the host view
        if self.hook_cmd or str(self.host_root) == "/":
            return []
        exe = self._cmd()[0]
        host_exe = self.host_root / exe.lstrip("/")
        if not host_exe.exists():
            return [f"WARNING: hook command {exe} does not exist under "
                    f"{self.host_root} — the host runtime cannot exec it; "
                    "pass hook_cmd with a host-valid command"]
        return []

    # -- install ------------------------------------------------------------

    def install(self, mode: str = "auto") -> InstallResult:
        if mode == "auto":
            mode = detect_hook_mode(str(self.host_root))
        if mode == "oci":
            return self._install_oci()
        if mode == "nri":
            return self._install_nri()
        if mode == "fanotify":
            return InstallResult("fanotify", [], [
                "no host installation needed: the runc fanotify watch "
                "runs in-process (runcfanotify parity)"])
        raise ValueError(f"unknown hook mode {mode!r}")

    def _install_oci(self) -> InstallResult:
        installed, notes = [], self._host_path_notes()
        cmd = self._cmd()
        for rel in OCI_HOOK_DIRS:
            d = self.host_root / rel
            try:
                d.mkdir(parents=True, exist_ok=True)
                for stage, fname in (("prestart", _PRESTART),
                                     ("poststop", _POSTSTOP)):
                    p = d / fname
                    p.write_text(json.dumps(
                        _oci_hook_config(stage, cmd), indent=2))
                    installed.append(str(p))
            except OSError as e:
                notes.append(f"{d}: {e}")
        if not installed:
            notes.append("couldn't install OCI hook configuration")
        return InstallResult("oci", installed, notes)

    def _install_nri(self) -> InstallResult:
        installed, notes = [], self._host_path_notes()
        try:
            # plugin "binary": a shim execing the hook client (ref installs
            # the nrigadget binary into /opt/nri/bin)
            bindir = self.host_root / NRI_BIN_DIR
            bindir.mkdir(parents=True, exist_ok=True)
            shim = bindir / _NRI_PLUGIN
            cmd = " ".join(shlex.quote(c) for c in self._cmd())
            shim.write_text(f"#!/bin/sh\nexec {cmd} --nri \"$@\"\n")
            shim.chmod(shim.stat().st_mode | stat.S_IXUSR | stat.S_IXGRP
                       | stat.S_IXOTH)
            installed.append(str(shim))
            # conf.json: append our plugin if a config exists, else create
            # it (ref: entrypoint.sh:106-119 jq append)
            conf_path = self.host_root / NRI_CONF
            conf_path.parent.mkdir(parents=True, exist_ok=True)
            conf = {"version": "0.1", "plugins": []}
            if conf_path.exists():
                try:
                    conf = json.loads(conf_path.read_text())
                except (OSError, ValueError) as e:
                    notes.append(f"existing {conf_path} unreadable ({e}); "
                                 "overwriting")
                    conf = {"version": "0.1", "plugins": []}
            plugins = conf.setdefault("plugins", [])
            if not any(isinstance(p, dict) and p.get("type") == _NRI_PLUGIN
                       for p in plugins):
                plugins.append({"type": _NRI_PLUGIN})
            conf_path.write_text(json.dumps(conf, indent=2))
            installed.append(str(conf_path))
        except OSError as e:
            # read-only host paths must not abort agent startup: degrade to
            # the in-process fanotify watch (same role, no install needed)
            notes.append(f"NRI install failed ({e}); falling back to the "
                         "in-process fanotify watch")
            return InstallResult("fanotify", installed, notes, degraded=True)
        return InstallResult("nri", installed, notes)

    # -- uninstall ----------------------------------------------------------

    def uninstall(self) -> list[str]:
        """Remove exactly what install() wrote (undeploy parity). Returns
        the removed paths; other plugins' NRI entries are preserved."""
        removed = []
        for rel in OCI_HOOK_DIRS:
            for fname in (_PRESTART, _POSTSTOP):
                p = self.host_root / rel / fname
                if p.exists():
                    p.unlink()
                    removed.append(str(p))
        shim = self.host_root / NRI_BIN_DIR / _NRI_PLUGIN
        if shim.exists():
            shim.unlink()
            removed.append(str(shim))
        conf_path = self.host_root / NRI_CONF
        if conf_path.exists():
            try:
                conf = json.loads(conf_path.read_text())
                plugins = conf.get("plugins", [])
                kept = [p for p in plugins
                        if not (isinstance(p, dict)
                                and p.get("type") == _NRI_PLUGIN)]
                if len(kept) != len(plugins):
                    conf["plugins"] = kept
                    conf_path.write_text(json.dumps(conf, indent=2))
                    removed.append(f"{conf_path} (plugin entry)")
            except (OSError, ValueError):
                pass
        return removed


# -- the hook invocation itself (what the runtime runs) ---------------------

def run_oci_hook(stage: str, socket: str, state_stream,
                 nri: bool = False) -> int:
    """Read the OCI state JSON from the runtime, resolve identity, call
    the agent (ref: hooks/oci/main.go — read state, gRPC AddContainer).
    NRI invocations carry the same state under an event wrapper."""
    from .client import AgentClient

    try:
        payload = json.load(state_stream)
    except ValueError as e:
        print(f"oci-hook: bad state JSON: {e}", file=sys.stderr)
        return 1
    if nri:
        # NRI v0.1 event wrapper; only container lifecycle events concern
        # us — sandbox/synchronize/unknown events must be ignored, not
        # added to the collection as workload containers
        nri_stage = {"StartContainer": "prestart",
                     "StopContainer": "poststop",
                     "RemoveContainer": "poststop"}.get(
                         payload.get("event", ""))
        if nri_stage is None:
            return 0
        stage = nri_stage
    cid = payload.get("id", "")
    pid = int(payload.get("pid", 0) or 0)
    if not cid:
        print("oci-hook: state has no container id", file=sys.stderr)
        return 1
    # A prestart hook that exits nonzero BLOCKS container creation on the
    # host (OCI hooks contract) — if the agent is down, degrade loudly on
    # stderr but let the container start (ref: the hook binaries dial with
    # a short timeout for the same reason).
    try:
        client = AgentClient(socket)
        if stage == "poststop":
            client.remove_container(cid, timeout=_HOOK_TIMEOUT)
            return 0
    except Exception as e:  # noqa: BLE001 — grpc.RpcError and transport
        print(f"oci-hook: agent unreachable ({e}); container proceeds "
              "untracked", file=sys.stderr)
        return 0
    # identity from the bundle's config.json annotations when present
    # (ref: hooks/oci/main.go reads the spec; dialect resolution here)
    name = pod = namespace = ""
    mntns = 0
    bundle = payload.get("bundle", "")
    if bundle:
        try:
            spec = json.loads((Path(bundle) / "config.json").read_text())
            from ..containers.oci_annotations import resolve_identity
            ident = resolve_identity(spec.get("annotations") or {})
            if ident is not None:
                name, pod, namespace = ident.name, ident.pod, ident.namespace
        except (OSError, ValueError):
            pass
    if pid:
        try:
            mntns = os.stat(f"/proc/{pid}/ns/mnt").st_ino
        except OSError:
            pass
    try:
        client.add_container({
            "id": cid, "name": name or cid[:12], "pid": pid, "mntns": mntns,
            "namespace": namespace, "pod": pod,
        }, timeout=_HOOK_TIMEOUT)
    except Exception as e:  # noqa: BLE001
        print(f"oci-hook: agent unreachable ({e}); container proceeds "
              "untracked", file=sys.stderr)
    return 0
