"""Agent server: the per-node gRPC service.

Reference contract (pkg/gadget-service/service.go): RunGadget :78-249 —
parse the run request, split the flat params map by prefix, build a
GadgetContext, pump events through a bounded 1024 buffer with drop-on-full
(:134-168), a sender goroutine forwards to the stream (:170-181), logs ride
the same stream with severity in the type bits (gadget-service/logger.go);
plus the container hooks service (gadgettracermanager.go AddContainer:151)
and a health service (daemon main.go:224-245).

gRPC methods are registered with generic handlers + identity serializers;
message bodies use wire.py framing.
"""

from __future__ import annotations

import collections
import json
import logging
import queue
import threading
import time
from concurrent import futures
from typing import Iterator

import grpc

from .. import all_gadgets  # noqa: F401
from ..containers import Container
from ..gadgets import GadgetContext
from ..gadgets import registry as gadget_registry
from ..gadgets.interface import GadgetType
from ..operators import operators as op_registry
from ..params import Collection
from ..runtime.local import LocalRuntime
from ..runtime.runtime import build_catalog
from ..telemetry import counter, gauge
from ..telemetry.tracing import RECORDER, TRACER
from ..utils.logger import StreamLogHandler, StreamLogger
from . import wire

EVENT_BUFFER = 1024  # ref: service.go:134 bounded buffer, drop-on-full

# resume plane defaults: how many outbound messages a detached run
# retains for ring replay, and how long a resumable run keeps running
# with no client attached before it cancels itself. Both are per-run
# overridable via the run request (`ring` / `linger`).
RESUME_RING = 1024
RESUME_LINGER = 10.0

log = logging.getLogger("ig-tpu.agent")


def handlers_for(gadget_type, outputs, on_event, on_event_array):
    """Gadget type → stream handler wiring for a RunGadget stream.

    Raises ValueError for a type this agent does not know how to serve:
    before this existed, an unknown type silently got no handlers and
    the client watched an empty stream end cleanly (VERDICT Weak #7 —
    the advise/traceloop mislabel rode exactly that hole)."""
    if gadget_type == GadgetType.TRACE:
        return on_event, None
    if gadget_type == GadgetType.TRACE_INTERVALS:
        return None, on_event_array
    if gadget_type == GadgetType.ONE_SHOT:
        return None, (on_event_array if "combiner" in outputs else None)
    if gadget_type in (GadgetType.PROFILE, GadgetType.START_STOP):
        # run-with-result gadgets: the final rendered bytes ride the
        # stream as EV_RESULT; no per-event handlers exist to wire
        return None, None
    raise ValueError(
        f"agent has no handler wiring for gadget type {gadget_type!r} "
        f"(outputs={sorted(outputs)}): refusing to serve a stream that "
        f"would silently carry no events")

# per-stream RPC telemetry (one lock touch per message, never per event —
# a message carries a whole batch/array)
_tm_rpc = counter("ig_agent_rpc_total", "agent RPCs served", ("method",))
_tm_stream_msgs = counter("ig_agent_stream_msgs_total",
                          "messages pushed onto RunGadget streams",
                          ("gadget",))
_tm_stream_dropped = counter("ig_agent_stream_dropped_total",
                             "stream messages dropped on backpressure",
                             ("gadget",))
_tm_stream_q = gauge("ig_agent_stream_queue_depth",
                     "RunGadget out-queue depth at last push (backpressure)",
                     ("gadget",))
_tm_active_runs = gauge("ig_agent_active_runs", "gadget runs in flight")
_tm_stream_resumes = counter("ig_agent_stream_resumes_total",
                             "RunGadget streams re-attached via resume",
                             ("gadget",))
_tm_detached_runs = gauge("ig_agent_detached_runs",
                          "resumable runs currently lingering with no "
                          "client attached")


class RunStream:
    """Per-run outbound stream state that survives client disconnects.

    The serving RPC generator used to own the queue and the seq counter,
    so a dropped connection destroyed both and the run with them. This
    object outlives any single RPC: every outbound message gets its seq
    here and lands in a bounded replay ring; an attached client also
    gets it on a live queue. When the client vanishes the run DETACHES
    (ring keeps filling) and lingers for `linger` seconds awaiting a
    `resume {run_id, last_seq}` re-attach, which replays ring messages
    with seq > last_seq — no duplicates by construction — and reports
    how many seqs overflowed the ring (`missed`, healed upstream by
    sealed-window backfill). Non-resumable runs keep the old semantics:
    disconnect cancels the run immediately.
    """

    def __init__(self, run_id: str, gadget: str, *, resumable: bool = False,
                 linger: float = RESUME_LINGER, ring_size: int = RESUME_RING):
        self.run_id = run_id
        self.gadget = gadget
        self.resumable = bool(resumable)
        self.linger = float(linger)
        self._mu = threading.Lock()
        self._ring: collections.deque = collections.deque(
            maxlen=max(int(ring_size), 1))
        self._q: queue.Queue | None = None
        self._gen = 0
        self.seq = 0
        self.dropped = 0
        self.done = False
        self.detached_at: float | None = None
        self.attaches = 0
        self._linger_timer: threading.Timer | None = None
        self.ctx = None  # the run's GadgetContext, set before first push
        self._m_msgs = _tm_stream_msgs.labels(gadget=gadget)
        self._m_dropped = _tm_stream_dropped.labels(gadget=gadget)
        self._m_qdepth = _tm_stream_q.labels(gadget=gadget)

    def is_attached(self) -> bool:
        with self._mu:
            return self._q is not None

    def owns(self, gen: int) -> bool:
        with self._mu:
            return self._gen == gen and self._q is not None

    def push(self, kind: int, header: dict, payload: bytes = b"",
             force: bool = False) -> None:
        """Stamp seq, retain in the ring, deliver to the live client if
        one is attached. `force` (trailers: EV_RESULT / EV_CONTROL_ACK)
        evicts the oldest queued message instead of dropping the new one
        — a full queue must not eat the run's result."""
        with self._mu:
            self.seq += 1
            msg = wire.encode_msg({**header, "seq": self.seq, "type": kind},
                                  payload)
            self._ring.append((self.seq, msg))
            self._m_msgs.inc()
            q = self._q
            if q is None:
                return
            try:
                q.put_nowait(msg)
                self._m_qdepth.set(q.qsize())
            except queue.Full:
                if not force:
                    self.dropped += 1  # ref: service.go:160-167 drop-on-full
                    self._m_dropped.inc()
                    return
                while True:
                    try:
                        q.put_nowait(msg)
                        return
                    except queue.Full:
                        try:
                            q.get_nowait()
                            self.dropped += 1
                            self._m_dropped.inc()
                        except queue.Empty:
                            pass

    def attach(self, last_seq: int) -> tuple[queue.Queue, int, dict]:
        """(Re-)attach a client that holds everything up to last_seq.
        Returns (live queue, attach generation, resume-ack dict)."""
        with self._mu:
            if self._linger_timer is not None:
                self._linger_timer.cancel()
                self._linger_timer = None
            if self.detached_at is not None:
                _tm_detached_runs.dec()
                self.detached_at = None
            replay = [(s, m) for s, m in self._ring if s > last_seq]
            if replay:
                missed = max(0, replay[0][0] - last_seq - 1)
            else:
                missed = max(0, self.seq - last_seq)
            q: queue.Queue = queue.Queue(
                maxsize=EVENT_BUFFER + len(replay) + 8)
            for _s, m in replay:
                q.put_nowait(m)
            if self.done:
                q.put_nowait(None)
            self._q = q
            self._gen += 1
            self.attaches += 1
            ack = {"run_id": self.run_id, "last_seq": int(last_seq),
                   "missed": int(missed), "replayed": len(replay),
                   "seq": self.seq, "attach": self.attaches}
            return q, self._gen, ack

    def detach(self, gen: int) -> None:
        """A serving RPC ended. Only the CURRENT attachment detaches (a
        generator superseded by a newer resume is a no-op). Resumable
        live runs linger awaiting a re-attach; everything else keeps the
        old cancel-on-disconnect contract."""
        ctx = None
        with self._mu:
            if gen != self._gen or self._q is None:
                return
            self._q = None
            if self.done:
                return
            self.detached_at = time.monotonic()
            _tm_detached_runs.inc()
            if self.resumable and self.linger > 0:
                t = threading.Timer(self.linger, self._linger_expired)
                t.daemon = True
                self._linger_timer = t
                t.start()
                return
            ctx = self.ctx
        if ctx is not None:
            ctx.cancel()

    def _linger_expired(self) -> None:
        with self._mu:
            if self._q is not None or self.done:
                return
            # cancel UNDER the lock: a resume attaching right now holds
            # the same lock in attach(), so it either lands before this
            # check (we return) or after the cancel (and sees the run
            # wind down with its trailer) — never a cancelled-under-
            # the-client limbo
            if self.ctx is not None:
                self.ctx.cancel()
        log.info("run %s (%s): no resume within %.1fs linger, cancelling",
                 self.run_id, self.gadget, self.linger)

    def finish(self) -> None:
        """The run ended: wake the attached client with the end-of-stream
        sentinel (never blocking — a gone client must not leak the run
        thread)."""
        with self._mu:
            self.done = True
            if self._linger_timer is not None:
                self._linger_timer.cancel()
                self._linger_timer = None
            if self.detached_at is not None:
                _tm_detached_runs.dec()
                self.detached_at = None
            q = self._q
            if q is None:
                return
            while True:
                try:
                    q.put_nowait(None)
                    return
                except queue.Full:
                    try:
                        q.get_nowait()
                        self.dropped += 1
                    except queue.Empty:
                        pass


class AgentServer:
    def __init__(self, node_name: str = "node"):
        self.node_name = node_name
        self.runtime = LocalRuntime(node_name=node_name)
        self._runs: dict[str, GadgetContext] = {}
        # run_id → RunStream: the resume plane's registry. Entries retire
        # a linger-window after the run ends so a client that dropped
        # right before completion can still re-attach for the tail.
        self._streams: dict[str, RunStream] = {}
        self._runs_mu = threading.Lock()
        # legacy CRD-path serving (ref: main.go:262-299 starts the Trace
        # controller inside the node daemon)
        from ..gadgets.trace_resource import TraceStore
        self.traces = TraceStore(node_name=node_name)
        self._ckpt_stop: threading.Event | None = None
        self.metrics_server = None  # set by serve(--metrics-addr)

    def start_checkpointer(self, directory: str,
                           interval: float = 30.0) -> None:
        """Periodic sketch-state checkpointing (role of pinned BPF maps
        surviving daemon restarts, pkg/gadgets/helpers.go:36): every live
        tpusketch bundle + scorer is host-offloaded to `directory` each
        interval; instances started after a restart merge it back in."""
        from ..operators import tpusketch
        tpusketch.set_checkpoint_dir(directory)
        self._ckpt_stop = threading.Event()
        stop = self._ckpt_stop

        def loop():
            while not stop.wait(interval):
                tpusketch.checkpoint_all()

        threading.Thread(target=loop, daemon=True,
                         name="sketch-checkpointer").start()

    def stop_checkpointer(self) -> None:
        if self._ckpt_stop is not None:
            self._ckpt_stop.set()
            self._ckpt_stop = None
            # final save: a clean SIGTERM must not drop the last interval's
            # counts for still-running gadget runs (their post_gadget_run
            # never fires — the stream threads die with the process)
            from ..operators import tpusketch
            tpusketch.checkpoint_all()

    # -- GadgetManager.GetCatalog ------------------------------------------

    def get_catalog(self, request: bytes, context) -> bytes:
        _tm_rpc.labels(method="GetCatalog").inc()
        catalog = build_catalog()
        catalog["node"] = self.node_name
        return wire.encode_msg({"catalog": catalog})

    # -- GadgetManager.RunGadget (bidi stream) ------------------------------

    def run_gadget(self, request_iterator: Iterator[bytes], context) -> Iterator[bytes]:
        _tm_rpc.labels(method="RunGadget").inc()
        first = next(request_iterator)
        header, _ = wire.decode_msg(first)
        # server span per RPC, parented to the client's fan-out span when
        # the request carries a traceparent (one trace end to end).
        # ambient=False: this span stays open across yields, and gRPC may
        # resume the generator on a different worker thread — an ambient
        # contextvar set here could strand a dead span as that thread's
        # parent; children parent via ctx.extra explicitly instead
        with TRACER.span("agent/RunGadget", parent=wire.extract_span(header),
                         attrs={"node": self.node_name},
                         ambient=False) as rpc_span:
            if header.get("resume"):
                yield from self._resume_stream(header["resume"],
                                               request_iterator, context)
            else:
                yield from self._run_gadget_traced(header, rpc_span,
                                                   request_iterator, context)

    def _resume_stream(self, resume: dict, request_iterator,
                       context) -> Iterator[bytes]:
        """Re-attach a reconnecting client to a still-running (or just-
        finished, still-lingering) gadget run: replay everything after
        last_seq from the ring, then continue live — capture never
        restarted. An unknown run_id (this agent was respawned, or the
        linger expired) answers with `unknown_run` so the client knows
        to restart fresh and heal the gap from sealed windows instead."""
        run_id = str(resume.get("run_id") or "")
        last_seq = int(resume.get("last_seq") or 0)
        with self._runs_mu:
            state = self._streams.get(run_id)
        if state is None:
            yield wire.encode_msg(
                {"error": f"unknown run {run_id!r} on {self.node_name}: "
                          f"nothing to resume",
                 "unknown_run": True, "node": self.node_name})
            return
        q, gen, ack = state.attach(last_seq)
        _tm_stream_resumes.labels(gadget=state.gadget).inc()
        log.info("run %s (%s): client re-attached at seq %d "
                 "(replayed %d, missed %d)", run_id, state.gadget,
                 last_seq, ack["replayed"], ack["missed"])
        yield wire.encode_msg({"type": wire.EV_RESUME_ACK,
                               "node": self.node_name, "resume": ack})
        threading.Thread(target=self._control_loop,
                         args=(request_iterator, state.ctx, state),
                         daemon=True).start()
        try:
            yield from self._serve_attached(state, q, gen, context)
        finally:
            state.detach(gen)

    @staticmethod
    def _control_loop(request_iterator, ctx, state) -> None:
        """Client stop requests cancel the run. Transport death is NOT a
        stop for resumable runs — the serving loop's detach starts the
        linger window instead; non-resumable runs keep the original
        cancel-on-disconnect contract."""
        try:
            for msg in request_iterator:
                h, _ = wire.decode_msg(msg)
                if h.get("stop"):
                    if ctx is not None:
                        ctx.cancel()
                    return
        except Exception:  # noqa: BLE001 — iterator died with the client
            if (state is None or not state.resumable) and ctx is not None:
                ctx.cancel()

    def _serve_attached(self, state: RunStream, q: queue.Queue, gen: int,
                        context) -> Iterator[bytes]:
        """Pump one attachment's queue onto the wire until end-of-run,
        client death, or takeover by a newer resume attachment."""
        while True:
            try:
                item = q.get(timeout=0.25)
            except queue.Empty:
                if not context.is_active():
                    return
                if not state.owns(gen):
                    return  # a newer resume took the stream over
                continue
            if item is None:
                return
            yield item
            if not context.is_active():
                return

    def _retire_stream(self, state: RunStream, after: float) -> None:
        def retire():
            with self._runs_mu:
                # identity-guarded: an unknown-run restart may have
                # re-registered the same run_id with a NEW stream state
                if self._streams.get(state.run_id) is state:
                    self._streams.pop(state.run_id, None)
        t = threading.Timer(max(after, 0.5), retire)
        t.daemon = True
        t.start()

    def _run_gadget_traced(self, header: dict, rpc_span, request_iterator,
                           context) -> Iterator[bytes]:
        run = header.get("run")
        if not run:
            yield wire.encode_msg({"error": "first message must be a run request"})
            return

        try:
            desc = gadget_registry.get(run["category"], run["name"])
        except KeyError as e:
            yield wire.encode_msg({"error": str(e)})
            return

        flat = run.get("params", {})
        gadget_params = desc.params().to_params()
        gadget_params.copy_from_map(flat, "gadget.")
        op_params = Collection({
            f"operator.{op.name}.": op.instance_params().to_params()
            for op in op_registry.get_all() if op.can_operate_on(desc)
        })
        op_params.copy_from_map(flat)

        outputs = set(run.get("output") or ["json"])
        ctx = GadgetContext(
            desc, gadget_params=gadget_params, operator_params=op_params,
            timeout=float(run.get("timeout") or 0),
            run_id=run.get("run_id") or None,
        )
        # run-with-result gadgets render server-side in the requested format
        ctx.extra["output"] = "json" if "result-json" in outputs else "columns"
        # per-RUN logger (child of the shared gadget logger, so records
        # still propagate to it and the flight recorder): the stream log
        # handler below must only see THIS run's records — attaching to
        # the shared logger would cross-stream concurrent runs' logs and,
        # with an in-process client, echo received lines back out forever.
        # Constructed directly, NOT via getLogger: the manager caches
        # named loggers forever, and one per run would leak unbounded in
        # a long-lived agent.
        run_logger = logging.Logger(f"ig-tpu.{desc.full_name}.{ctx.run_id}")
        run_logger.parent = logging.getLogger(f"ig-tpu.{desc.full_name}")
        ctx.logger = run_logger
        # resume plane: the client opts in per run; the stream state
        # below outlives this RPC so a reconnect can re-attach
        state = RunStream(
            ctx.run_id, desc.full_name,
            resumable=bool(run.get("resumable")),
            linger=float(run.get("linger") or RESUME_LINGER),
            ring_size=int(run.get("ring") or RESUME_RING))
        state.ctx = ctx
        with self._runs_mu:
            prev = self._streams.get(ctx.run_id)
            self._runs[ctx.run_id] = ctx
            self._streams[ctx.run_id] = state
        if prev is not None and not prev.done and prev.ctx is not None:
            # a client restarting under a reused run_id while the
            # previous life still lingers: two gadgets capturing under
            # one id would double-count — the new request supersedes
            log.warning("run %s (%s): superseded by a new run request; "
                        "cancelling the previous life",
                        ctx.run_id, desc.full_name)
            prev.ctx.cancel()
        _tm_active_runs.inc()
        # server span per run (child of the RPC span); operators and the
        # device plane parent their spans to this via ctx.extra —
        # ambient=False for the same cross-thread-generator reason.
        # The run span, registries, and log handler are unwound by the
        # RUN thread when the gadget actually ends — NOT when this RPC's
        # generator dies, because a resumable run outlives its first
        # connection by design.
        run_span = TRACER.span(f"agent/run/{desc.full_name}",
                               parent=rpc_span.context,
                               attrs={"run_id": ctx.run_id,
                                      "gadget": desc.full_name},
                               ambient=False)
        yield from self._run_gadget_stream(ctx, desc, outputs, state,
                                           run_span, request_iterator,
                                           context)

    def _run_gadget_stream(self, ctx, desc, outputs, state: RunStream,
                           run_span, request_iterator,
                           context) -> Iterator[bytes]:
        cleanup_mu = threading.Lock()
        cleanup_state = {"done": False, "handler": None}

        def run_cleanup():
            """Unwound exactly ONCE when the RUN ends (run thread,
            loud-failure path, or a setup crash) — never on a mere
            client disconnect: a resumable run outlives its first
            connection by design."""
            with cleanup_mu:
                if cleanup_state["done"]:
                    return
                cleanup_state["done"] = True
            ctx.cancel()
            if cleanup_state["handler"] is not None:
                ctx.logger.removeHandler(cleanup_state["handler"])
            with self._runs_mu:
                # identity-guarded: a superseding run request may have
                # re-registered this run_id with a NEW context/stream
                if self._runs.get(ctx.run_id) is ctx:
                    self._runs.pop(ctx.run_id, None)
            _tm_active_runs.dec()
            run_span.__exit__(None, None, None)
            # keep the stream state around one linger window so a client
            # that dropped right before the end can resume for the tail
            self._retire_stream(state, state.linger)

        try:
            yield from self._run_stream_setup_and_serve(
                ctx, desc, outputs, state, run_span, run_cleanup,
                cleanup_state, request_iterator, context)
        except GeneratorExit:
            # client disconnect mid-serve: the serving finally already
            # detached; the run itself lives on (or cancels via detach
            # for non-resumable runs) — no registry unwind here
            raise
        except BaseException:
            # setup (or serving) died before the run thread could take
            # ownership of cleanup: unwind so _runs/_streams and the
            # active-runs gauge cannot drift in a long-lived agent
            run_cleanup()
            state.finish()
            raise

    def _run_stream_setup_and_serve(self, ctx, desc, outputs,
                                    state: RunStream, run_span,
                                    run_cleanup, cleanup_state,
                                    request_iterator,
                                    context) -> Iterator[bytes]:
        push = state.push

        # run logs multiplex onto the same stream with severity in the
        # type bits; run/trace IDs ride the header so the client can
        # correlate a remote log line with this run's spans
        run_span.__enter__()
        ctx.extra["trace_ctx"] = run_span.context
        trace_ctx = ctx.extra.get("trace_ctx")
        stream_log = StreamLogger(
            push, shift=wire.EV_LOG_SHIFT, run_id=ctx.run_id,
            trace_id=trace_ctx.trace_id if trace_ctx is not None else "")
        log_handler = StreamLogHandler(stream_log)
        ctx.logger.addHandler(log_handler)
        cleanup_state["handler"] = log_handler

        cols = desc.columns()

        def row_dict(ev) -> dict:
            d = cols.to_dict(ev)
            d["node"] = self.node_name  # authoritative node identity
            return d

        def on_event(ev):
            if "json" in outputs:
                push(wire.EV_PAYLOAD_JSON, {"node": self.node_name},
                     json.dumps(row_dict(ev), default=str).encode())

        def on_event_array(evs):
            if "json" in outputs:
                payload = json.dumps(
                    [row_dict(e) for e in evs], default=str).encode()
                push(wire.EV_PAYLOAD_ARRAY, {"node": self.node_name}, payload)

        def on_batch(batch):
            if "batch" in outputs and batch.count:
                push(wire.EV_BATCH_NPZ, {"node": self.node_name,
                                         "drops": batch.drops},
                     wire.encode_batch(batch))

        if "summary" in outputs:
            def on_summary(summary):
                h, payload = wire.encode_summary(summary)
                push(wire.EV_SUMMARY, {"node": self.node_name, **h}, payload)
            ctx.extra["on_sketch_summary"] = on_summary

        # alert transitions ride the same stream as typed events whenever
        # the alerts operator is enabled for this run (rules set); the
        # client's GrpcRuntime folds them cluster-wide
        def on_alert_event(alert: dict):
            push(wire.EV_ALERT, {"node": self.node_name, "alert": alert})
        ctx.extra["on_alert_event"] = on_alert_event

        # control reader: client stop requests cancel the context
        threading.Thread(target=self._control_loop,
                         args=(request_iterator, ctx, state),
                         daemon=True).start()

        # resolve handler wiring BEFORE spawning the run thread so an
        # unknown gadget type fails the RPC loudly instead of vanishing
        # inside a daemon thread
        try:
            h_event, h_array = handlers_for(desc.gadget_type, outputs,
                                            on_event, on_event_array)
        except ValueError as e:
            log.error("RunGadget %s: %s", desc.full_name, e)
            # the error trailer goes through the ring like every other
            # trailer: a client that loses this connection and resumes
            # within the retire window must still see the failure, not
            # a clean empty end
            push(wire.EV_RESULT, {"error": str(e), "gadget_error": True},
                 force=True)
            run_cleanup()
            state.finish()
            q, gen, _ack = state.attach(0)
            try:
                yield from self._serve_attached(state, q, gen, context)
            finally:
                state.detach(gen)
            return

        def run_thread():
            try:
                res = self.runtime.run_gadget(
                    ctx,
                    on_event=h_event,
                    on_event_array=h_array,
                    on_batch=on_batch,
                )
                # trailers ride the same seq'd push path (force=True so a
                # full queue evicts data, never the result) — they live
                # in the ring too, so a resumed client still gets them
                node_res = res.get(self.node_name) if res else None
                if node_res is not None and node_res.error:
                    push(wire.EV_RESULT, {"error": node_res.error,
                                          "gadget_error": True}, force=True)
                elif node_res is not None and isinstance(node_res.result,
                                                         bytes):
                    push(wire.EV_RESULT, {}, node_res.result, force=True)
                if state.dropped:
                    push(wire.EV_CONTROL_ACK, {"dropped": state.dropped},
                         force=True)
            finally:
                run_cleanup()
                # end-of-stream sentinel; never blocks on a gone client
                state.finish()

        t = threading.Thread(target=run_thread, daemon=True)
        t.start()

        q, gen, _ack = state.attach(0)
        try:
            yield from self._serve_attached(state, q, gen, context)
        finally:
            state.detach(gen)

    # -- ContainerManager (hook-facing; ref: gadgettracermanager.go:151) ----

    def add_container(self, request: bytes, context) -> bytes:
        _tm_rpc.labels(method="AddContainer").inc()
        h, _ = wire.decode_msg(request)
        from ..operators.operators import ensure_initialized
        lm = ensure_initialized("localmanager")
        c = h.get("container", {})
        lm.cc.add_container(Container(
            id=c.get("id", ""), name=c.get("name", ""),
            pid=int(c.get("pid", 0)), mntns=int(c.get("mntns", 0)),
            netns=int(c.get("netns", 0)), namespace=c.get("namespace", ""),
            pod=c.get("pod", ""), labels=c.get("labels", {}),
        ))
        return wire.encode_msg({"ok": True, "count": len(lm.cc)})

    def remove_container(self, request: bytes, context) -> bytes:
        _tm_rpc.labels(method="RemoveContainer").inc()
        h, _ = wire.decode_msg(request)
        from ..operators.operators import get as get_op
        lm = get_op("localmanager")
        if lm.cc is not None:
            lm.cc.remove_container(h.get("container", {}).get("id", ""))
        return wire.encode_msg({"ok": True})

    # -- Trace-resource RPCs (ref: §3.5 — the CRD path served remotely) -----

    def apply_trace(self, request: bytes, context) -> bytes:
        _tm_rpc.labels(method="ApplyTrace").inc()
        h, _ = wire.decode_msg(request)
        try:
            return wire.encode_msg({"trace": self.traces.apply(h.get("trace", {}))})
        except Exception as e:
            return wire.encode_msg({"error": str(e)})

    def get_trace(self, request: bytes, context) -> bytes:
        _tm_rpc.labels(method="GetTrace").inc()
        h, _ = wire.decode_msg(request)
        doc = self.traces.get(h.get("name", ""))
        if doc is None:
            return wire.encode_msg({"error": f"trace {h.get('name')!r} not found"})
        return wire.encode_msg({"trace": doc})

    def list_traces(self, request: bytes, context) -> bytes:
        _tm_rpc.labels(method="ListTraces").inc()
        return wire.encode_msg({"traces": self.traces.list()})

    def delete_trace(self, request: bytes, context) -> bytes:
        _tm_rpc.labels(method="DeleteTrace").inc()
        h, _ = wire.decode_msg(request)
        return wire.encode_msg({"deleted": self.traces.delete(h.get("name", ""))})

    # -- capture/recording lifecycle RPCs (capture/) ------------------------

    def start_recording(self, request: bytes, context) -> bytes:
        """Arm the node-wide recording: every running and future gadget
        run on this agent tees its batches/summaries/alerts into
        journals under the recording directory until StopRecording."""
        _tm_rpc.labels(method="StartRecording").inc()
        h, _ = wire.decode_msg(request)
        from ..capture import RECORDINGS
        opts = {k: v for k, v in (h.get("opts") or {}).items()
                if k in ("max_segment_bytes", "max_segment_age",
                         "retention_bytes", "retention_segments")}
        rid = h.get("recording_id", "")
        existing = RECORDINGS.get(rid) if rid else None
        if existing is not None:
            # idempotent for fan-out retries and in-process agent fleets
            # sharing one manager: arming an armed recording is a no-op
            return wire.encode_msg({"ok": True, "recording_id": existing.id,
                                    "dir": existing.path, "already": True,
                                    "node": self.node_name})
        try:
            # always the manager's base area (--capture-dir): a client-
            # chosen base would be invisible to ListRecordings/Fetch,
            # which resolve under the same default
            rec = RECORDINGS.start(rid, **opts)
        except (ValueError, OSError) as e:
            return wire.encode_msg({"error": str(e)})
        return wire.encode_msg({"ok": True, "recording_id": rec.id,
                                "dir": rec.path, "node": self.node_name})

    def stop_recording(self, request: bytes, context) -> bytes:
        _tm_rpc.labels(method="StopRecording").inc()
        h, _ = wire.decode_msg(request)
        import os
        from ..capture import RECORDINGS
        from ..capture.manager import RECORDING_META
        rid = h.get("recording_id", "")
        try:
            meta = RECORDINGS.stop(rid)
        except KeyError as e:
            # a peer RPC in the same process (in-process fleet) may have
            # stopped it already: a sealed recording on disk is success,
            # a never-started id is the error
            try:
                done = os.path.join(RECORDINGS.recording_dir(rid),
                                    RECORDING_META)
            except ValueError as bad:
                return wire.encode_msg({"error": str(bad)})
            if rid and os.path.exists(done):
                return wire.encode_msg({"ok": True, "already": True,
                                        "node": self.node_name})
            return wire.encode_msg({"error": str(e)})
        return wire.encode_msg({"ok": True, "recording": meta,
                                "node": self.node_name})

    def list_recordings(self, request: bytes, context) -> bytes:
        """Active + on-disk recordings; with recording_id set, also the
        relative file list (the fetch fan-out's download manifest)."""
        _tm_rpc.labels(method="ListRecordings").inc()
        h, _ = wire.decode_msg(request)
        from ..capture import RECORDINGS
        msg: dict = {"node": self.node_name,
                     "recordings": RECORDINGS.list()}
        rid = h.get("recording_id", "")
        if rid:
            import os
            try:
                root = RECORDINGS.recording_dir(rid)
            except ValueError as e:
                msg["error"] = str(e)
                return wire.encode_msg(msg)
            files = []
            if os.path.isdir(root):
                for base, _dirs, names in os.walk(root):
                    for name in sorted(names):
                        p = os.path.join(base, name)
                        files.append({"path": os.path.relpath(p, root),
                                      "bytes": os.path.getsize(p)})
            else:
                msg["error"] = f"no recording {rid!r} on {self.node_name}"
            msg["files"] = sorted(files, key=lambda f: f["path"])
        return wire.encode_msg(msg)

    def fetch_segment(self, request: bytes, context) -> bytes:
        """Chunked download of one recording file (segments, manifests);
        stays under gRPC's 4 MiB default message cap via offset+limit."""
        _tm_rpc.labels(method="FetchSegment").inc()
        h, _ = wire.decode_msg(request)
        import os
        from ..capture import RECORDINGS
        rid = h.get("recording_id", "")
        rel = h.get("file", "")
        norm = os.path.normpath(rel)
        if not rid or not rel or norm.startswith("..") or \
                os.path.isabs(norm):
            return wire.encode_msg(
                {"error": f"bad fetch request ({rid!r}, {rel!r})"})
        try:
            path = os.path.join(RECORDINGS.recording_dir(rid), norm)
        except ValueError as e:
            return wire.encode_msg({"error": str(e)})
        offset = max(int(h.get("offset", 0)), 0)
        limit = min(max(int(h.get("limit", 1 << 20)), 1), 2 << 20)
        try:
            size = os.path.getsize(path)
            with open(path, "rb") as f:
                f.seek(offset)
                chunk = f.read(limit)
        except OSError as e:
            return wire.encode_msg({"error": f"{rel}: {e.strerror or e}"})
        return wire.encode_msg(
            {"ok": True, "file": rel, "offset": offset, "size": size,
             "eof": offset + len(chunk) >= size}, chunk)

    # -- sketch-history RPCs (history/): range-listing + chunked pulls ------

    @staticmethod
    def _window_range(h: dict) -> dict:
        """The (optional) range/slice filter every history RPC accepts —
        one parse, shared by ListWindows and FetchWindows."""
        return {
            "start_ts": float(h["start_ts"]) if h.get("start_ts") is not None else None,
            "end_ts": float(h["end_ts"]) if h.get("end_ts") is not None else None,
            "start_seq": int(h["start_seq"]) if h.get("start_seq") is not None else None,
            "end_seq": int(h["end_seq"]) if h.get("end_seq") is not None else None,
            "key": h.get("key") or None,
        }

    def list_windows(self, request: bytes, context) -> bytes:
        """Header rows of every sealed window overlapping the requested
        seq/ts range (and slice key) — the pruning half of a fleet-wide
        range query: the client decides which windows are worth pulling
        before any payload bytes move."""
        _tm_rpc.labels(method="ListWindows").inc()
        h, _ = wire.decode_msg(request)
        from ..history import HISTORY, validate_store_name
        gadget = h.get("gadget", "") or ""
        if gadget:
            try:
                validate_store_name(gadget.replace("/", "-"))
            except ValueError as e:
                return wire.encode_msg({"error": str(e)})
        losses: list = []
        try:
            # node=self.node_name: an agent serves only windows ITS runs
            # sealed — in-process fleets share one base area, and a
            # fan-out merging every node's windows from every node would
            # double-count
            rows = HISTORY.list_windows(gadget=gadget, losses=losses,
                                        node=self.node_name,
                                        **self._window_range(h))
        except (OSError, ValueError) as e:
            return wire.encode_msg({"error": str(e)})
        return wire.encode_msg({"ok": True, "node": self.node_name,
                                "windows": rows, "losses": losses})

    def fetch_windows(self, request: bytes, context) -> bytes:
        """Chunked download of matching windows' frames; every reply
        stays under the gRPC message cap via offset + max_bytes (the
        FetchSegment discipline applied to typed windows instead of raw
        files). Store names resolve server-side only — the one
        client-supplied path component (gadget) is traversal-guarded."""
        _tm_rpc.labels(method="FetchWindows").inc()
        h, _ = wire.decode_msg(request)
        from ..history import HISTORY, pack_frames, validate_store_name
        gadget = h.get("gadget", "") or ""
        if gadget:
            try:
                validate_store_name(gadget.replace("/", "-"))
            except ValueError as e:
                return wire.encode_msg({"error": str(e)})
        offset = max(int(h.get("offset", 0)), 0)
        max_bytes = min(max(int(h.get("max_bytes", 1 << 20)), 1), 2 << 20)
        losses: list = []
        picked: list[tuple[dict, bytes]] = []
        size = 0
        eof = True
        try:
            it = HISTORY.fetch_windows(gadget=gadget, losses=losses,
                                       node=self.node_name,
                                       **self._window_range(h))
            for i, (header, payload) in enumerate(it):
                if i < offset:
                    continue
                frame_size = len(payload) + 512  # header slack
                if picked and size + frame_size > max_bytes:
                    eof = False
                    break
                picked.append((header, payload))
                size += frame_size
        except (OSError, ValueError) as e:
            return wire.encode_msg({"error": str(e)})
        return wire.encode_msg(
            {"ok": True, "node": self.node_name, "count": len(picked),
             "offset": offset, "next_offset": offset + len(picked),
             "eof": eof,
             # every chunk rescans from frame 0, so only the FIRST chunk
             # reports torn-tail losses — the client concatenates reply
             # losses, and repeating them would multiply the accounting
             "losses": losses if offset == 0 else []},
            pack_frames(picked))

    # -- dump-state debug RPC (ref: gadgettracermanager.go DumpState :204) --

    def dump_state(self, request: bytes, context) -> bytes:
        _tm_rpc.labels(method="DumpState").inc()
        try:
            req, _ = wire.decode_msg(request)
        except (ValueError, json.JSONDecodeError):
            req = {}
        import sys
        frames = {}
        for tid, frame in sys._current_frames().items():
            stack = []
            f = frame
            while f is not None and len(stack) < 32:
                stack.append(f"{f.f_code.co_filename}:{f.f_lineno} {f.f_code.co_name}")
                f = f.f_back
            frames[str(tid)] = stack
        with self._runs_mu:
            runs = list(self._runs)
            stream_states = list(self._streams.values())
        # resume-plane view: every live (or lingering) run stream with
        # its attach state — `ig-tpu fleet health` reads this to tell a
        # serving run from one awaiting a resume
        now = time.monotonic()
        run_rows = [{
            "run_id": st.run_id, "gadget": st.gadget, "seq": st.seq,
            "resumable": st.resumable, "attached": st.is_attached(),
            "attaches": st.attaches, "done": st.done,
            "dropped": st.dropped,
            "detached_for": (round(now - st.detached_at, 3)
                             if st.detached_at is not None else 0.0),
        } for st in stream_states]
        # container set, as the reference's DumpState does
        # (gadgettracermanager.go:204-219 dumps containers + stacks)
        containers: list = []
        dump_error = ""
        try:
            from ..operators.operators import get as get_op
            lm = get_op("localmanager")
            if lm.cc is not None:
                containers = [
                    {"id": c.id, "name": c.name, "pid": c.pid,
                     "mntns": c.mntns, "namespace": c.namespace, "pod": c.pod,
                     "runtime": c.runtime}
                    for c in lm.cc.get_all()
                ]
        except Exception as e:
            dump_error = f"container dump failed: {e!r}"
        # the node's alert table rides the same debug dump, so a remote
        # `ig-tpu alerts list` can read every agent's active alerts
        from ..alerts import ACTIVE as active_alerts
        msg = {"threads": frames, "active_runs": runs,
               "runs": run_rows,
               "containers": containers,
               "alerts": active_alerts.all(),
               # CRD-path state rides the same debug dump (the reference's
               # daemon dumps its trace list alongside containers)
               "traces": [{"name": t["metadata"]["name"],
                           "gadget": t["spec"].get("gadget", ""),
                           "state": t["status"].get("state", ""),
                           "error": t["status"].get("operationError", "")}
                          for t in self.traces.list()]}
        if dump_error:
            msg["error"] = dump_error
        # the process flight recorder (recent spans/logs/errors/facts)
        # rides the same debug RPC, so a wedged agent can still be read;
        # max_spans lets trace export request the whole ring instead of
        # the 512-span debug default
        msg["flight_record"] = RECORDER.snapshot(
            max_spans=int(req.get("max_spans") or 512))
        return wire.encode_msg(msg)


def _traced_unary(name, behavior):
    """Open a server span per unary RPC, parented to the caller's span
    when the request header carries a traceparent."""
    def handler(request, context):
        parent = None
        try:
            h, _ = wire.decode_msg(request)
            parent = wire.extract_span(h)
        except (ValueError, KeyError, IndexError, UnicodeDecodeError,
                json.JSONDecodeError):
            parent = None
        with TRACER.span(f"agent/{name}", parent=parent):
            return behavior(request, context)
    return handler


def _method(behavior, kind, name=""):
    s, d = wire.identity_serializer, wire.identity_deserializer
    if kind == "unary":
        return grpc.unary_unary_rpc_method_handler(
            _traced_unary(name, behavior),
            request_deserializer=d, response_serializer=s)
    return grpc.stream_stream_rpc_method_handler(
        behavior, request_deserializer=d, response_serializer=s)


def serve(address: str = "unix:///tmp/igtpu-agent.sock",
          node_name: str = "node", max_workers: int = 8,
          checkpoint_dir: str = "",
          checkpoint_interval: float = 30.0,
          metrics_addr: str = "") -> tuple[grpc.Server, AgentServer]:
    """Start the agent (non-blocking); returns (grpc_server, agent).
    metrics_addr ('host:port', off by default) additionally serves the
    telemetry registry as Prometheus text on GET /metrics."""
    agent = AgentServer(node_name=node_name)
    # first agent in the process names the tracer/flight-recorder identity
    # (one agent per process in real deployments; in-process test fleets
    # share both, so keep the two first-wins-consistent — a last-wins
    # fact would contradict the span attribution)
    if not TRACER.node:
        TRACER.node = node_name
        RECORDER.set_fact("node", node_name)
    if metrics_addr:
        from ..telemetry import MetricsServer
        agent.metrics_server = MetricsServer(metrics_addr).start()
    if checkpoint_dir:
        agent.start_checkpointer(checkpoint_dir, checkpoint_interval)
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=max_workers))
    handlers = {
        "GetCatalog": _method(agent.get_catalog, "unary", "GetCatalog"),
        "RunGadget": _method(agent.run_gadget, "stream"),
        "AddContainer": _method(agent.add_container, "unary", "AddContainer"),
        "RemoveContainer": _method(agent.remove_container, "unary",
                                   "RemoveContainer"),
        "DumpState": _method(agent.dump_state, "unary", "DumpState"),
        "StartRecording": _method(agent.start_recording, "unary",
                                  "StartRecording"),
        "StopRecording": _method(agent.stop_recording, "unary",
                                 "StopRecording"),
        "ListRecordings": _method(agent.list_recordings, "unary",
                                  "ListRecordings"),
        "FetchSegment": _method(agent.fetch_segment, "unary", "FetchSegment"),
        "ListWindows": _method(agent.list_windows, "unary", "ListWindows"),
        "FetchWindows": _method(agent.fetch_windows, "unary",
                                "FetchWindows"),
        "ApplyTrace": _method(agent.apply_trace, "unary", "ApplyTrace"),
        "GetTrace": _method(agent.get_trace, "unary", "GetTrace"),
        "ListTraces": _method(agent.list_traces, "unary", "ListTraces"),
        "DeleteTrace": _method(agent.delete_trace, "unary", "DeleteTrace"),
    }
    server.add_generic_rpc_handlers((
        grpc.method_handlers_generic_handler("igtpu.GadgetManager", handlers),
    ))
    # standard health service analogue (ref: main.go:224-245)
    server.add_insecure_port(address)
    server.start()
    return server, agent
