"""Agent server: the per-node gRPC service.

Reference contract (pkg/gadget-service/service.go): RunGadget :78-249 —
parse the run request, split the flat params map by prefix, build a
GadgetContext, pump events through a bounded 1024 buffer with drop-on-full
(:134-168), a sender goroutine forwards to the stream (:170-181), logs ride
the same stream with severity in the type bits (gadget-service/logger.go);
plus the container hooks service (gadgettracermanager.go AddContainer:151)
and a health service (daemon main.go:224-245).

gRPC methods are registered with generic handlers + identity serializers;
message bodies use wire.py framing.

Shared-run plane (ISSUE 12): a run is a first-class shared resource —
SharedRun fans one gadget's stream out to N reference-counted
Subscribers, each with its own seq space, bounded queue, drop policy,
priority class, and evict-after stall window; admission control bounds
subscriber count and queued capacity (low priority refused first), and
the last detach starts a keepalive countdown instead of killing the
capture. See docs/robustness.md "Shared runs & overload".
"""

from __future__ import annotations

import collections
import json
import logging
import queue
import threading
import time
from concurrent import futures
from typing import Iterator

import grpc

from .. import all_gadgets  # noqa: F401
from ..containers import Container
from ..gadgets import GadgetContext
from ..gadgets import registry as gadget_registry
from ..gadgets.interface import GadgetType
from ..operators import operators as op_registry
from ..params import Collection
from ..runtime.local import LocalRuntime
from ..runtime.runtime import build_catalog
from ..telemetry import counter, gauge
from ..telemetry.tracing import RECORDER, TRACER
from ..utils.logger import StreamLogHandler, StreamLogger
from . import wire

EVENT_BUFFER = 1024  # ref: service.go:134 bounded buffer, drop-on-full

# resume plane defaults: how many outbound messages a detached run
# retains for ring replay, and how long a resumable run keeps running
# with no client attached before it cancels itself. Both are per-run
# overridable via the run request (`ring` / `linger`).
RESUME_RING = 1024
RESUME_LINGER = 10.0

# shared-run overload defaults (per-run / per-subscriber overridable via
# the run request — validated loudly both here and in the client params
# layer): bounded per-subscriber queues with an explicit drop policy, a
# per-run subscriber count + queued-capacity budget, and a stall window
# after which a wedged subscriber is EVICTED with a labeled terminal
# record instead of silently rotting.
SUB_QUEUE = EVENT_BUFFER
MAX_SUBSCRIBERS = 16
SUB_BUDGET = 16384              # total queued-message capacity per run
EVICT_AFTER = 10.0
DROP_POLICIES = wire.DROP_POLICIES
PRIORITIES = wire.PRIORITIES
TIERS = wire.TIERS
# admission headroom: the fraction of the run's subscriber budget a
# class may fill — low-priority admissions are refused FIRST as the run
# approaches saturation (PSketch-style priority classes under a fixed
# budget), so the important consumers stay whole.
ADMIT_HEADROOM = {"high": 1.0, "normal": 0.85, "low": 0.6}

log = logging.getLogger("ig-tpu.agent")


def handlers_for(gadget_type, outputs, on_event, on_event_array):
    """Gadget type → stream handler wiring for a RunGadget stream.

    Raises ValueError for a type this agent does not know how to serve:
    before this existed, an unknown type silently got no handlers and
    the client watched an empty stream end cleanly (VERDICT Weak #7 —
    the advise/traceloop mislabel rode exactly that hole)."""
    if gadget_type == GadgetType.TRACE:
        return on_event, None
    if gadget_type == GadgetType.TRACE_INTERVALS:
        return None, on_event_array
    if gadget_type == GadgetType.ONE_SHOT:
        return None, (on_event_array if "combiner" in outputs else None)
    if gadget_type in (GadgetType.PROFILE, GadgetType.START_STOP):
        # run-with-result gadgets: the final rendered bytes ride the
        # stream as EV_RESULT; no per-event handlers exist to wire
        return None, None
    raise ValueError(
        f"agent has no handler wiring for gadget type {gadget_type!r} "
        f"(outputs={sorted(outputs)}): refusing to serve a stream that "
        f"would silently carry no events")

# per-stream RPC telemetry (one lock touch per message, never per event —
# a message carries a whole batch/array)
_tm_rpc = counter("ig_agent_rpc_total", "agent RPCs served", ("method",))
_tm_stream_msgs = counter("ig_agent_stream_msgs_total",
                          "messages pushed onto RunGadget streams",
                          ("gadget",))
_tm_stream_dropped = counter("ig_agent_stream_dropped_total",
                             "stream messages dropped on backpressure",
                             ("gadget",))
_tm_stream_q = gauge("ig_agent_stream_queue_depth",
                     "RunGadget out-queue depth at last push (backpressure)",
                     ("gadget",))
_tm_active_runs = gauge("ig_agent_active_runs", "gadget runs in flight")
_tm_stream_resumes = counter("ig_agent_stream_resumes_total",
                             "RunGadget streams re-attached via resume",
                             ("gadget",))
_tm_detached_runs = gauge("ig_agent_detached_runs",
                          "resumable runs currently lingering with no "
                          "client attached")
# shared-run / overload-protection plane
_tm_run_subs = gauge("ig_agent_run_subscribers",
                     "live subscribers per shared gadget run", ("run",))
_tm_sub_drops = counter("ig_agent_subscriber_drops_total",
                        "records dropped by a slow subscriber's own "
                        "bounded queue (never stalls the gadget or its "
                        "peers)", ("run", "policy", "class"))
_tm_sub_evictions = counter("ig_agent_subscriber_evictions_total",
                            "subscribers evicted after stalling past "
                            "their evict-after window")
_tm_attach_refused = counter("ig_agent_attach_refused_total",
                             "shared-run attach admissions refused",
                             ("reason",))


def _validate_sub_opts(opts: dict) -> str | None:
    """Server-side guard on subscriber options: an unknown policy or
    class must refuse the attach loudly, never default silently."""
    policy = opts.get("drop_policy") or "drop-oldest"
    if policy not in DROP_POLICIES:
        return f"unknown drop policy {policy!r} (want {DROP_POLICIES})"
    priority = opts.get("priority") or "normal"
    if priority not in PRIORITIES:
        return f"unknown priority class {priority!r} (want {PRIORITIES})"
    tier = opts.get("tier") or "full"
    if tier not in TIERS:
        return f"unknown delivery tier {tier!r} (want {TIERS})"
    try:
        if opts.get("queue") is not None and int(opts["queue"]) < 1:
            return f"subscriber queue bound must be >= 1, got {opts['queue']}"
        if opts.get("evict_after") is not None and \
                float(opts["evict_after"]) <= 0:
            return f"evict_after must be > 0, got {opts['evict_after']}"
    except (TypeError, ValueError) as e:
        return f"bad subscriber option: {e}"
    return None


# kinds a summary-tier subscriber receives: harvest summaries, alert
# transitions, sealed-window announcements, and trailers/acks — never
# raw rows/batches or per-record logs. Cheap consumers ride one shared
# harvest without paying for the firehose.
_SUMMARY_KINDS = frozenset({
    wire.EV_SUMMARY, wire.EV_ALERT, wire.EV_WINDOW, wire.EV_RESULT,
    wire.EV_CONTROL_ACK, wire.EV_RESUME_ACK, wire.EV_DROP_NOTICE,
    wire.EV_ATTACH_ACK, wire.EV_QUERY,
})


class Subscriber:
    """One consumer of a SharedRun: own outbound seq counter, own
    bounded queue with a validated drop policy, own cursor into the
    run's shared replay ring.

    A slow subscriber drops ITS OWN records (accounted per drop in
    `ig_agent_subscriber_drops_total{run,policy,class}` and reported on
    the wire via EV_DROP_NOTICE) and never stalls the gadget or its
    peers; one stalled past `evict_after` is evicted with a labeled
    terminal record. All mutation happens under the owning SharedRun's
    lock.
    """

    def __init__(self, sub_id: str, run_id: str, gadget: str, *,
                 priority: str = "normal", policy: str = "drop-oldest",
                 queue_max: int = SUB_QUEUE,
                 evict_after: float = EVICT_AFTER, tier: str = "full",
                 stamp_ring: int = RESUME_RING):
        self.sub_id = sub_id
        self.run_id = run_id
        self.gadget = gadget
        self.priority = priority
        self.policy = policy
        self.queue_max = max(int(queue_max), 1)
        self.evict_after = float(evict_after)
        self.tier = tier
        self.seq = 0
        self.drops = 0                 # records this sub's queue dropped
        self._drops_unreported = 0     # not yet carried by a DROP_NOTICE
        self.evicted = False
        self.left = False              # permanently gone (stop/evict)
        self.done = False              # saw the end-of-stream sentinel
        self.attaches = 0
        self.cursor = 0                # highest ring index stamped
        self.stalled_since: float | None = None
        self.detached_since: float | None = None
        self._q: queue.Queue | None = None
        self._gen = 0
        # (seq, ring_index | None, encoded | None): the stamped tail for
        # resume replay — ring entries by index (re-encoded on demand),
        # sub-local control records (acks/notices) by encoded bytes
        self._stamps: collections.deque = collections.deque(
            maxlen=max(int(stamp_ring), 1))
        self._m_drops = _tm_sub_drops.labels(run=run_id, policy=policy,
                                             **{"class": priority})

    @property
    def attached(self) -> bool:
        return self._q is not None

    def wants(self, kind: int) -> bool:
        if self.tier != "summary":
            return True
        return (kind >> wire.EV_LOG_SHIFT) == 0 and kind in _SUMMARY_KINDS

    # delivery (run lock held) ------------------------------------------

    def deliver(self, index: int, kind: int, header: dict, payload: bytes,
                force: bool) -> None:
        if self.left or self.done:
            return
        if self._q is None:
            return  # detached: cursor lags, the shared ring keeps the tail
        if not self.wants(kind):
            self.cursor = index  # consumed by the tier filter, no seq
            return
        self.cursor = index
        self.seq += 1
        msg = wire.encode_msg({**header, "seq": self.seq, "type": kind},
                              payload)
        self._stamps.append((self.seq, index, None))
        self._put(msg, force)

    def deliver_local(self, kind: int, header: dict, payload: bytes = b"",
                      force: bool = False) -> None:
        """A sub-local control record (drop notice, eviction trailer):
        seq-stamped like everything else so client accounting stays
        exact, retained encoded for resume replay."""
        if self.done:
            return
        self.seq += 1
        msg = wire.encode_msg({**header, "seq": self.seq, "type": kind},
                              payload)
        self._stamps.append((self.seq, None, msg))
        self._put(msg, force)

    def _put(self, msg: bytes, force: bool) -> None:
        q = self._q
        if q is None:
            return
        try:
            q.put_nowait(msg)
            # hysteresis: a consumer is un-stalled when its queue has
            # genuinely drained, not when one slow read opened one slot
            # (that would reset the evict clock on every trickle)
            if self.stalled_since is not None \
                    and q.qsize() <= self.queue_max // 2:
                self.stalled_since = None
            return
        except queue.Full:
            if self.stalled_since is None:
                self.stalled_since = time.monotonic()
            if not force and self.policy == "drop-newest":
                # the new record is the casualty; the client sees a seq
                # gap and the next DROP_NOTICE carries the count
                self._record_drop()
                return
            # drop-oldest (and all trailers): evict queued records until
            # the new one fits — a full queue must not eat a result
            while True:
                try:
                    q.put_nowait(msg)
                    return
                except queue.Full:
                    try:
                        q.get_nowait()
                        self._record_drop()
                    except queue.Empty:
                        pass

    def _record_drop(self) -> None:
        self.drops += 1
        self._drops_unreported += 1
        self._m_drops.inc()

    def maybe_notice(self, node: str) -> None:
        """Lazily report accumulated drops once the queue has room again
        (run lock held): the notice itself must not thrash a full
        queue."""
        q = self._q
        if (self._drops_unreported <= 0 or q is None
                or q.qsize() >= self.queue_max - 1):
            return
        dropped, self._drops_unreported = self._drops_unreported, 0
        self.deliver_local(wire.EV_DROP_NOTICE, {
            "node": node, "sub_id": self.sub_id, "dropped": dropped,
            "drops_total": self.drops, "policy": self.policy,
            "class": self.priority})

    # attach plumbing (run lock held) -----------------------------------

    def attach_queue(self, replay: list[bytes], done: bool
                     ) -> tuple[queue.Queue, int]:
        q: queue.Queue = queue.Queue(
            maxsize=self.queue_max + len(replay) + 8)
        for m in replay:
            q.put_nowait(m)
        if done:
            q.put_nowait(None)
        self._q = q
        self._gen += 1
        self.attaches += 1
        self.stalled_since = None
        self.detached_since = None
        return q, self._gen

    def owns_locked(self, gen: int) -> bool:
        return self._gen == gen and self._q is not None

    def sentinel(self) -> None:
        """End-of-stream for this subscriber; never blocks."""
        self.done = True
        q = self._q
        if q is None:
            return
        while True:
            try:
                q.put_nowait(None)
                return
            except queue.Full:
                try:
                    q.get_nowait()
                    self._record_drop()
                except queue.Empty:
                    pass

    def row(self, now: float) -> dict:
        q = self._q
        return {
            "sub_id": self.sub_id, "priority": self.priority,
            "policy": self.policy, "tier": self.tier, "seq": self.seq,
            "drops": self.drops, "attached": self.attached,
            "attaches": self.attaches, "evicted": self.evicted,
            "left": self.left, "queue_depth": q.qsize() if q else 0,
            "queue_max": self.queue_max,
            "stalled_for": (round(now - self.stalled_since, 3)
                            if self.stalled_since is not None else 0.0),
        }


class SharedRun:
    """Per-run outbound state shared by N subscribers, outliving any
    single RPC (the PR-8 RunStream grown into a first-class shared
    resource).

    Every outbound message gets a run-level ring index and lands in ONE
    bounded replay ring; each attached subscriber stamps its OWN seq and
    gets the message on its OWN bounded queue (drop policy + priority
    class + evict-after — a slow consumer can only hurt itself). A
    disconnected subscriber detaches (the ring keeps the tail at its
    cursor) and resumes with `resume {run_id, last_seq[, sub_id]}` —
    replaying its stamped-but-lost tail with the ORIGINAL seqs, then
    catching up from the shared ring with fresh seqs: no duplicates by
    construction, ring overflow reported as `missed` (healed upstream by
    sealed-window backfill). When the last attached subscriber detaches
    the gadget keeps running for `keepalive` seconds awaiting a
    (re-)attach, so dashboard churn doesn't thrash capture setup;
    non-resumable, non-shared runs keep the original cancel-on-
    disconnect contract exactly.
    """

    def __init__(self, run_id: str, gadget: str, *, resumable: bool = False,
                 linger: float = RESUME_LINGER, ring_size: int = RESUME_RING,
                 shared: bool = False, share_key: str = "",
                 keepalive: float | None = None,
                 max_subscribers: int = MAX_SUBSCRIBERS,
                 sub_budget: int = SUB_BUDGET,
                 node: str = ""):
        self.run_id = run_id
        self.gadget = gadget
        self.node = node
        self.resumable = bool(resumable)
        self.shared = bool(shared)
        self.share_key = share_key
        self.linger = float(linger)
        # last detach starts this countdown before the gadget actually
        # stops (defaults to the resume linger for PR-8 compatibility)
        self.keepalive = float(keepalive if keepalive is not None
                               else linger)
        self.max_subscribers = max(int(max_subscribers), 1)
        self.sub_budget = max(int(sub_budget), 1)
        self._ring_size = max(int(ring_size), 1)
        self._mu = threading.Lock()
        # (index, kind, header, payload) — raw, encoded per subscriber
        self._ring: collections.deque = collections.deque(
            maxlen=self._ring_size)
        self.index = 0
        self._subs: dict[str, Subscriber] = {}
        self._order: list[str] = []     # attach order; [0] is primary
        self._next_sub = 0
        self.done = False
        self.detached_at: float | None = None
        self.attaches = 0
        self._keepalive_timer: threading.Timer | None = None
        self.ctx = None  # the run's GadgetContext, set before first push
        self._m_msgs = _tm_stream_msgs.labels(gadget=gadget)
        self._m_dropped = _tm_stream_dropped.labels(gadget=gadget)
        self._m_qdepth = _tm_stream_q.labels(gadget=gadget)
        self._m_subs = _tm_run_subs.labels(run=run_id)

    # -- introspection ------------------------------------------------------

    def is_attached(self) -> bool:
        with self._mu:
            return any(s.attached for s in self._subs.values())

    def owns(self, sub: Subscriber, gen: int) -> bool:
        with self._mu:
            return sub.owns_locked(gen)

    @property
    def seq(self) -> int:
        """Highest subscriber seq (DumpState/debug view; per-subscriber
        seqs are the wire truth)."""
        with self._mu:
            return max((s.seq for s in self._subs.values()), default=0)

    @property
    def dropped(self) -> int:
        with self._mu:
            return sum(s.drops for s in self._subs.values())

    def live_subscribers(self) -> int:
        with self._mu:
            return self._live_count_locked()

    def _live_count_locked(self) -> int:
        return sum(1 for s in self._subs.values() if not s.left)

    # -- admission ----------------------------------------------------------

    def admit(self, opts: dict) -> Subscriber | dict:
        """Admission-control a new subscriber; returns the Subscriber or
        a typed refusal dict {refused, reason, detail}. Low-priority
        admissions are refused first as the run nears its budget."""
        bad = _validate_sub_opts(opts)
        if bad is not None:
            _tm_attach_refused.labels(reason="bad-options").inc()
            return {"refused": True, "reason": "bad-options", "detail": bad}
        priority = opts.get("priority") or "normal"
        queue_max = int(opts.get("queue") or SUB_QUEUE)
        with self._mu:
            # expired ghosts must not crowd out live admissions; any
            # cancel-context the expiry returns is deliberately ignored
            # — a subscriber is being admitted right now, so the run
            # must keep living regardless of the ghosts' departure
            self._expire_stale_locked(time.monotonic())
            if self.done:
                _tm_attach_refused.labels(reason="run-done").inc()
                return {"refused": True, "reason": "run-done",
                        "detail": f"run {self.run_id} already ended"}
            if self._live_count_locked() >= self.max_subscribers:
                _tm_attach_refused.labels(reason="max-subscribers").inc()
                return {"refused": True, "reason": "max-subscribers",
                        "detail": f"run {self.run_id} already serves "
                                  f"{self.max_subscribers} subscriber(s)"}
            usage = sum(s.queue_max for s in self._subs.values()
                        if not s.left)
            headroom = ADMIT_HEADROOM.get(priority, 1.0)
            if usage + queue_max > self.sub_budget * headroom:
                _tm_attach_refused.labels(reason="memory-budget").inc()
                return {"refused": True, "reason": "memory-budget",
                        "detail": f"{priority} admission would put queued "
                                  f"capacity at {usage + queue_max} > "
                                  f"{headroom:.0%} of budget "
                                  f"{self.sub_budget}"}
            sub_id = str(opts.get("id") or "")
            if not sub_id or sub_id in self._subs:
                self._next_sub += 1
                sub_id = f"s{self._next_sub}"
            sub = Subscriber(
                sub_id, self.run_id, self.gadget, priority=priority,
                policy=opts.get("drop_policy") or "drop-oldest",
                queue_max=queue_max,
                evict_after=float(opts.get("evict_after") or EVICT_AFTER),
                tier=opts.get("tier") or "full",
                stamp_ring=self._ring_size)
            sub.cursor = self.index  # joins live; history via attach()
            self._subs[sub_id] = sub
            self._order.append(sub_id)
            self._m_subs.set(self._live_count_locked())
            return sub

    # -- delivery -----------------------------------------------------------

    def push(self, kind: int, header: dict, payload: bytes = b"",
             force: bool = False) -> None:
        """Retain one raw copy in the shared ring, fan out to every
        subscriber under its own seq/queue/policy. `force` (trailers:
        EV_RESULT / EV_CONTROL_ACK) evicts queued records instead of
        dropping the trailer — a full queue must not eat the result."""
        evict: list[Subscriber] = []
        with self._mu:
            self.index += 1
            self._ring.append((self.index, kind, dict(header), payload))
            self._m_msgs.inc()
            now = time.monotonic()
            depth = 0
            for sub in self._subs.values():
                before = sub.drops
                sub.deliver(self.index, kind, header, payload, force)
                if sub.drops > before:
                    self._m_dropped.inc(sub.drops - before)
                sub.maybe_notice(self.node)
                if sub._q is not None:
                    depth = max(depth, sub._q.qsize())
                if (sub.attached and not sub.left
                        and sub.stalled_since is not None
                        and now - sub.stalled_since > sub.evict_after):
                    evict.append(sub)
            self._m_qdepth.set(depth)
            stale_ctx = self._expire_stale_locked(now)
        if stale_ctx is not None:
            stale_ctx.cancel()
        for sub in evict:
            self.evict(sub, f"stalled > {sub.evict_after:g}s "
                            f"(queue full, client not draining)")

    def evict(self, sub: Subscriber, why: str) -> None:
        """A wedged subscriber gets a labeled terminal record and its
        stream ends; the gadget and its peers never notice."""
        with self._mu:
            if sub.left or sub.done:
                return
            sub.evicted = True
            sub.deliver_local(wire.EV_DROP_NOTICE, {
                "node": self.node, "sub_id": sub.sub_id, "evicted": True,
                "reason": why, "dropped": sub._drops_unreported,
                "drops_total": sub.drops, "policy": sub.policy,
                "class": sub.priority}, force=True)
            sub._drops_unreported = 0
            _tm_sub_evictions.inc()
        log.warning("run %s (%s): evicting subscriber %s (%s, %s): %s",
                    self.run_id, self.gadget, sub.sub_id, sub.priority,
                    sub.policy, why)
        self.leave(sub)

    # -- attach / detach / leave --------------------------------------------

    def attach_subscriber(self, sub: Subscriber, last_seq: int
                          ) -> tuple[queue.Queue, int, dict]:
        """(Re-)attach a subscriber that holds everything up to
        last_seq. Replays its stamped-but-lost tail with the ORIGINAL
        seqs, then catches up from the shared ring (fresh seqs); what
        fell off either ring is `missed` — no duplicates, no silent
        holes."""
        with self._mu:
            self._cancel_keepalive_locked()
            if self.detached_at is not None:
                _tm_detached_runs.dec()
                self.detached_at = None
            self.attaches += 1
            ring_by_index = {i: (k, h, p) for i, k, h, p in self._ring}
            replay: list[bytes] = []
            missed = 0
            # 1) stamped tail the client lost in transit
            stamped = [t for t in sub._stamps if t[0] > last_seq]
            if stamped:
                missed += max(0, stamped[0][0] - last_seq - 1)
            elif sub.seq > last_seq:
                missed += sub.seq - last_seq
            for s, idx, enc in stamped:
                if enc is not None:
                    replay.append(enc)
                elif idx in ring_by_index:
                    k, h, p = ring_by_index[idx]
                    replay.append(wire.encode_msg(
                        {**h, "seq": s, "type": k}, p))
                else:
                    missed += 1
            replayed = len(replay)
            # 2) catch-up: ring entries past this sub's cursor, stamped
            # fresh now (entries that already fell off are missed)
            if self._ring:
                first = self._ring[0][0]
                if first > sub.cursor + 1:
                    missed += first - sub.cursor - 1
                for i, k, h, p in self._ring:
                    if i <= sub.cursor or not sub.wants(k):
                        if i > sub.cursor:
                            sub.cursor = i
                        continue
                    sub.cursor = i
                    sub.seq += 1
                    replay.append(wire.encode_msg(
                        {**h, "seq": sub.seq, "type": k}, p))
                    sub._stamps.append((sub.seq, i, None))
                    replayed += 1
            elif self.index > sub.cursor:
                missed += self.index - sub.cursor
                sub.cursor = self.index
            q, gen = sub.attach_queue(replay, self.done)
            self._m_subs.set(self._live_count_locked())
            ack = {"run_id": self.run_id, "sub_id": sub.sub_id,
                   "last_seq": int(last_seq), "missed": int(missed),
                   "replayed": replayed, "seq": sub.seq,
                   "attach": sub.attaches,
                   "subscribers": self._live_count_locked(),
                   "shared": self.shared}
            return q, gen, ack

    def resume(self, sub_id: str, last_seq: int
               ) -> tuple[Subscriber, queue.Queue, int, dict] | None:
        """Resolve the subscriber a `resume` first-message addresses: by
        sub_id when given (the supervisor echoes the acked id); without
        one (PR-8 wire compat — resumes carried no subscriber identity)
        prefer a DETACHED live subscriber — a resume is by definition a
        reconnect, and picking an attached peer would hijack its
        stream. Returns None when nothing matches (answered upstream as
        unknown_run so the client restarts fresh, exactly the PR-8
        linger-expiry contract)."""
        with self._mu:
            sub = None
            if sub_id:
                sub = self._subs.get(sub_id)
            else:
                live = [self._subs[sid] for sid in self._order
                        if sid in self._subs
                        and not self._subs[sid].left]
                detached = [s for s in live if not s.attached]
                if detached:
                    sub = detached[0]
                elif live:
                    sub = live[0]
            if sub is None or sub.left:
                return None
        q, gen, ack = self.attach_subscriber(sub, last_seq)
        return sub, q, gen, ack

    def detach(self, sub: Subscriber, gen: int) -> None:
        """A serving RPC ended. Only the subscriber's CURRENT attachment
        detaches (a generator superseded by a newer resume is a no-op).
        Resumable/shared runs start the keepalive countdown when the
        LAST attached subscriber detaches; everything else keeps the old
        cancel-on-disconnect contract."""
        ctx = None
        with self._mu:
            if not sub.owns_locked(gen):
                return
            sub._q = None
            sub.stalled_since = None
            sub.detached_since = time.monotonic()
            if self.done:
                return
            if any(s.attached and not s.left
                   for s in self._subs.values()):
                return  # peers still live: nothing run-level to do
            if self.detached_at is None:
                # leave() may have marked the run detached already while
                # this subscriber's generator was still draining its
                # sentinel — one detachment, one gauge increment
                self.detached_at = time.monotonic()
                _tm_detached_runs.inc()
            if (self.resumable or self.shared) and self.keepalive > 0:
                self._arm_keepalive_locked()
                return
            ctx = self.ctx
        if ctx is not None:
            ctx.cancel()

    def leave(self, sub: Subscriber) -> None:
        """A subscriber is gone for good (stop request, eviction, or
        resume-window expiry): it stops receiving, its queue drains to
        the sentinel, and when the last live subscriber leaves the
        keepalive countdown (not an immediate stop) decides the gadget's
        fate."""
        with self._mu:
            ctx = self._leave_locked(sub)
        if ctx is not None:
            ctx.cancel()

    def _leave_locked(self, sub: Subscriber):
        """Core of leave(); returns a context to cancel AFTER the lock
        is released (or None)."""
        if sub.left:
            return None
        sub.left = True
        sub.sentinel()
        self._m_subs.set(self._live_count_locked())
        if self.done or self._live_count_locked() > 0:
            return None
        if self.detached_at is None:
            self.detached_at = time.monotonic()
            _tm_detached_runs.inc()
        if (self.resumable or self.shared) and self.keepalive > 0:
            self._arm_keepalive_locked()
            return None
        return self.ctx

    def _expire_stale_locked(self, now: float):
        """A subscriber detached longer than the resume window (the
        run's `linger`) is gone for good: without this, crash-
        disconnected dashboards would hold max-subscribers slots and
        budget capacity for the life of the run. Returns a context to
        cancel after the lock is released (or None)."""
        ctx = None
        for sub in self._subs.values():
            if (not sub.left and not sub.attached
                    and sub.detached_since is not None
                    and now - sub.detached_since > max(self.linger, 0.0)):
                log.info("run %s (%s): subscriber %s expired after %.1fs "
                         "detached with no resume", self.run_id,
                         self.gadget, sub.sub_id, now - sub.detached_since)
                ctx = self._leave_locked(sub) or ctx
        return ctx

    def _arm_keepalive_locked(self) -> None:
        self._cancel_keepalive_locked()
        t = threading.Timer(self.keepalive, self._keepalive_expired)
        t.daemon = True
        self._keepalive_timer = t
        t.start()

    def _cancel_keepalive_locked(self) -> None:
        if self._keepalive_timer is not None:
            self._keepalive_timer.cancel()
            self._keepalive_timer = None

    def _keepalive_expired(self) -> None:
        with self._mu:
            # a LEFT subscriber still draining its sentinel is not a
            # reason to keep the gadget alive — only live attachments
            if self.done or any(s.attached and not s.left
                                for s in self._subs.values()):
                return
            # cancel UNDER the lock: an attach landing right now holds
            # the same lock, so it either lands before this check (we
            # return) or after the cancel (and sees the run wind down
            # with its trailer) — never a cancelled-under-the-client
            # limbo
            if self.ctx is not None:
                self.ctx.cancel()
        log.info("run %s (%s): no (re-)attach within %.1fs keepalive, "
                 "cancelling", self.run_id, self.gadget, self.keepalive)

    def keepalive_remaining(self) -> float:
        """Seconds until the lingering run cancels itself (0 when a
        client is attached or the run ended)."""
        with self._mu:
            if self.done or self.detached_at is None \
                    or self._keepalive_timer is None:
                return 0.0
            return max(
                0.0, self.keepalive - (time.monotonic() - self.detached_at))

    def finish(self) -> None:
        """The run ended: wake every attached subscriber with the
        end-of-stream sentinel (never blocking — a gone client must not
        leak the run thread)."""
        with self._mu:
            self.done = True
            self._cancel_keepalive_locked()
            if self.detached_at is not None:
                _tm_detached_runs.dec()
                self.detached_at = None
            for sub in self._subs.values():
                sub.sentinel()
            self._m_subs.set(0)

    def subscriber_rows(self) -> list[dict]:
        now = time.monotonic()
        with self._mu:
            return [self._subs[sid].row(now) for sid in self._order
                    if sid in self._subs]


class AgentServer:
    def __init__(self, node_name: str = "node"):
        self.node_name = node_name
        self.runtime = LocalRuntime(node_name=node_name)
        self._runs: dict[str, GadgetContext] = {}
        # run_id → SharedRun: the resume/shared plane's registry. Entries
        # retire a keepalive-window after the run ends so a client that
        # dropped right before completion can still re-attach for the
        # tail.
        self._streams: dict[str, SharedRun] = {}
        # share_key → run_id: the first RunGadget request for a (gadget,
        # resolved-params) key starts the gadget; compatible requests
        # attach to the SAME running pipeline as subscribers.
        self._shared: dict[str, str] = {}
        self._runs_mu = threading.Lock()
        # legacy CRD-path serving (ref: main.go:262-299 starts the Trace
        # controller inside the node daemon)
        from ..gadgets.trace_resource import TraceStore
        self.traces = TraceStore(node_name=node_name)
        self._ckpt_stop: threading.Event | None = None
        self.metrics_server = None  # set by serve(--metrics-addr)

    def start_checkpointer(self, directory: str,
                           interval: float = 30.0) -> None:
        """Periodic sketch-state checkpointing (role of pinned BPF maps
        surviving daemon restarts, pkg/gadgets/helpers.go:36): every live
        tpusketch bundle + scorer is host-offloaded to `directory` each
        interval; instances started after a restart merge it back in."""
        from ..operators import tpusketch
        tpusketch.set_checkpoint_dir(directory)
        self._ckpt_stop = threading.Event()
        stop = self._ckpt_stop

        def loop():
            while not stop.wait(interval):
                tpusketch.checkpoint_all()

        threading.Thread(target=loop, daemon=True,
                         name="sketch-checkpointer").start()

    def stop_checkpointer(self) -> None:
        if self._ckpt_stop is not None:
            self._ckpt_stop.set()
            self._ckpt_stop = None
            # final save: a clean SIGTERM must not drop the last interval's
            # counts for still-running gadget runs (their post_gadget_run
            # never fires — the stream threads die with the process)
            from ..operators import tpusketch
            tpusketch.checkpoint_all()

    # -- GadgetManager.GetCatalog ------------------------------------------

    def get_catalog(self, request: bytes, context) -> bytes:
        _tm_rpc.labels(method="GetCatalog").inc()
        catalog = build_catalog()
        catalog["node"] = self.node_name
        return wire.encode_msg({"catalog": catalog})

    # -- GadgetManager.RunGadget (bidi stream) ------------------------------

    def run_gadget(self, request_iterator: Iterator[bytes], context) -> Iterator[bytes]:
        _tm_rpc.labels(method="RunGadget").inc()
        first = next(request_iterator)
        header, _ = wire.decode_msg(first)
        # server span per RPC, parented to the client's fan-out span when
        # the request carries a traceparent (one trace end to end).
        # ambient=False: this span stays open across yields, and gRPC may
        # resume the generator on a different worker thread — an ambient
        # contextvar set here could strand a dead span as that thread's
        # parent; children parent via ctx.extra explicitly instead
        with TRACER.span("agent/RunGadget", parent=wire.extract_span(header),
                         attrs={"node": self.node_name},
                         ambient=False) as rpc_span:
            if header.get("resume"):
                yield from self._resume_stream(header["resume"],
                                               request_iterator, context)
            elif header.get("attach"):
                yield from self._attach_stream(header["attach"],
                                               request_iterator, context)
            else:
                yield from self._run_gadget_traced(header, rpc_span,
                                                   request_iterator, context)

    def _resume_stream(self, resume: dict, request_iterator,
                       context) -> Iterator[bytes]:
        """Re-attach a reconnecting client to a still-running (or just-
        finished, still-lingering) gadget run: replay everything after
        last_seq from the ring, then continue live — capture never
        restarted. An unknown run_id (this agent was respawned, or the
        linger expired) answers with `unknown_run` so the client knows
        to restart fresh and heal the gap from sealed windows instead."""
        run_id = str(resume.get("run_id") or "")
        last_seq = int(resume.get("last_seq") or 0)
        sub_id = str(resume.get("sub_id") or "")
        with self._runs_mu:
            state = self._streams.get(run_id)
        if state is None:
            yield wire.encode_msg(
                {"error": f"unknown run {run_id!r} on {self.node_name}: "
                          f"nothing to resume",
                 "unknown_run": True, "node": self.node_name})
            return
        resolved = state.resume(sub_id, last_seq)
        if resolved is None:
            # the run lives but this subscriber is gone (left, evicted,
            # or expired): answer unknown_run — the PR-8 linger-expiry
            # contract — so the supervisor backfills and restarts fresh
            # (a share=true restart re-attaches as a NEW subscriber)
            yield wire.encode_msg(
                {"error": f"subscriber {sub_id or '<primary>'!r} no longer "
                          f"exists on run {run_id!r} on {self.node_name}: "
                          f"nothing to resume",
                 "unknown_run": True, "node": self.node_name})
            return
        sub, q, gen, ack = resolved
        _tm_stream_resumes.labels(gadget=state.gadget).inc()
        log.info("run %s (%s): subscriber %s re-attached at seq %d "
                 "(replayed %d, missed %d)", run_id, state.gadget,
                 sub.sub_id, last_seq, ack["replayed"], ack["missed"])
        yield wire.encode_msg({"type": wire.EV_RESUME_ACK,
                               "node": self.node_name, "resume": ack})
        threading.Thread(target=self._control_loop,
                         args=(request_iterator, state.ctx, state, sub),
                         daemon=True).start()
        try:
            yield from self._serve_attached(state, sub, q, gen, context)
        finally:
            state.detach(sub, gen)

    def _attach_stream(self, attach: dict, request_iterator,
                       context) -> Iterator[bytes]:
        """Attach a NEW subscriber to an already-running shared gadget,
        by run_id or by share key: admission-controlled (max-subscribers
        + per-run subscriber budget, low priority refused first), ACKed
        (or refused) with a typed EV_ATTACH_ACK. The subscriber rides
        its own seq space/queue/policy from the moment of admission."""
        run_id = str(attach.get("run_id") or "")
        key = str(attach.get("key") or "")
        with self._runs_mu:
            if not run_id and key:
                run_id = self._shared.get(key, "")
            state = self._streams.get(run_id) if run_id else None
        if state is None or state.done:
            yield wire.encode_msg(
                {"error": f"unknown run {run_id or key!r} on "
                          f"{self.node_name}: nothing to attach to",
                 "unknown_run": True, "node": self.node_name})
            return
        admitted = state.admit(attach)
        if isinstance(admitted, dict):  # typed refusal
            yield wire.encode_msg(
                {"type": wire.EV_ATTACH_ACK, "node": self.node_name,
                 "attach": {**admitted, "run_id": state.run_id},
                 "error": f"attach refused ({admitted['reason']}): "
                          f"{admitted['detail']}"})
            return
        sub = admitted
        q, gen, ack = state.attach_subscriber(sub, int(attach.get(
            "last_seq") or 0))
        log.info("run %s (%s): subscriber %s attached (%s, %s, tier=%s; "
                 "%d live)", state.run_id, state.gadget, sub.sub_id,
                 sub.priority, sub.policy, sub.tier, ack["subscribers"])
        yield wire.encode_msg({"type": wire.EV_ATTACH_ACK,
                               "node": self.node_name, "attach": ack})
        threading.Thread(target=self._control_loop,
                         args=(request_iterator, state.ctx, state, sub),
                         daemon=True).start()
        try:
            yield from self._serve_attached(state, sub, q, gen, context)
        finally:
            state.detach(sub, gen)

    @staticmethod
    def _control_loop(request_iterator, ctx, state, sub=None) -> None:
        """Client stop requests: on a SHARED run a subscriber's stop
        detaches that subscriber (last one out starts the keepalive
        countdown, the gadget never thrashes on dashboard churn); on a
        private run it cancels the gadget as before. `{"stop": "run"}`
        force-cancels a shared gadget. Transport death is NOT a stop for
        resumable/shared runs — the serving loop's detach starts the
        keepalive window instead; non-resumable runs keep the original
        cancel-on-disconnect contract."""
        try:
            for msg in request_iterator:
                h, _ = wire.decode_msg(msg)
                if h.get("stop"):
                    if (state is not None and state.shared
                            and sub is not None
                            and h.get("stop") != "run"):
                        state.leave(sub)
                    elif ctx is not None:
                        ctx.cancel()
                    return
        except Exception:  # noqa: BLE001 — iterator died with the client
            if (state is None or not (state.resumable or state.shared)) \
                    and ctx is not None:
                ctx.cancel()

    def _serve_attached(self, state: SharedRun, sub: Subscriber,
                        q: queue.Queue, gen: int,
                        context) -> Iterator[bytes]:
        """Pump one subscriber attachment's queue onto the wire until
        end-of-run, client death, eviction, or takeover by a newer
        resume attachment."""
        while True:
            try:
                item = q.get(timeout=0.25)
            except queue.Empty:
                if not context.is_active():
                    return
                if not state.owns(sub, gen):
                    return  # a newer resume took the stream over
                continue
            if item is None:
                return
            yield item
            if not context.is_active():
                return

    def _retire_stream(self, state: SharedRun, after: float) -> None:
        def retire():
            with self._runs_mu:
                # identity-guarded: an unknown-run restart may have
                # re-registered the same run_id with a NEW stream state
                if self._streams.get(state.run_id) is state:
                    self._streams.pop(state.run_id, None)
                if state.share_key and \
                        self._shared.get(state.share_key) == state.run_id:
                    self._shared.pop(state.share_key, None)
        t = threading.Timer(max(after, 0.5), retire)
        t.daemon = True
        t.start()

    @staticmethod
    def share_key(run: dict) -> str:
        """The shared-run identity: gadget + resolved flat params +
        requested outputs. Two requests with the same key drive the SAME
        capture/sketch pipeline; anything that would change what the
        gadget computes or emits forks the key."""
        return json.dumps([
            run.get("category", ""), run.get("name", ""),
            sorted((run.get("params") or {}).items()),
            sorted(set(run.get("output") or ["json"])),
        ], separators=(",", ":"))

    def _run_gadget_traced(self, header: dict, rpc_span, request_iterator,
                           context) -> Iterator[bytes]:
        run = header.get("run")
        if not run:
            yield wire.encode_msg({"error": "first message must be a run request"})
            return

        sub_opts = dict(run.get("subscriber") or {})
        bad = _validate_sub_opts(sub_opts)
        if bad is not None:
            yield wire.encode_msg({"error": bad})
            return

        if run.get("share"):
            key = self.share_key(run)
            with self._runs_mu:
                existing = self._streams.get(self._shared.get(key, ""))
            if existing is not None and not existing.done:
                # the gadget is already running for this exact request:
                # attach as a subscriber instead of paying for a second
                # capture + sketch + history pipeline
                yield from self._attach_stream(
                    {**sub_opts, "run_id": existing.run_id},
                    request_iterator, context)
                return

        try:
            desc = gadget_registry.get(run["category"], run["name"])
        except KeyError as e:
            yield wire.encode_msg({"error": str(e)})
            return

        flat = run.get("params", {})
        gadget_params = desc.params().to_params()
        gadget_params.copy_from_map(flat, "gadget.")
        op_params = Collection({
            f"operator.{op.name}.": op.instance_params().to_params()
            for op in op_registry.get_all() if op.can_operate_on(desc)
        })
        op_params.copy_from_map(flat)

        outputs = set(run.get("output") or ["json"])
        ctx = GadgetContext(
            desc, gadget_params=gadget_params, operator_params=op_params,
            timeout=float(run.get("timeout") or 0),
            run_id=run.get("run_id") or None,
        )
        # run-with-result gadgets render server-side in the requested format
        ctx.extra["output"] = "json" if "result-json" in outputs else "columns"
        # per-RUN logger (child of the shared gadget logger, so records
        # still propagate to it and the flight recorder): the stream log
        # handler below must only see THIS run's records — attaching to
        # the shared logger would cross-stream concurrent runs' logs and,
        # with an in-process client, echo received lines back out forever.
        # Constructed directly, NOT via getLogger: the manager caches
        # named loggers forever, and one per run would leak unbounded in
        # a long-lived agent.
        run_logger = logging.Logger(f"ig-tpu.{desc.full_name}.{ctx.run_id}")
        run_logger.parent = logging.getLogger(f"ig-tpu.{desc.full_name}")
        ctx.logger = run_logger
        # resume/shared plane: the client opts in per run; the stream
        # state below outlives this RPC so a reconnect can re-attach and
        # later compatible requests can subscribe
        share_key = self.share_key(run) if run.get("share") else ""
        state = SharedRun(
            ctx.run_id, desc.full_name,
            resumable=bool(run.get("resumable")),
            linger=float(run.get("linger") or RESUME_LINGER),
            ring_size=int(run.get("ring") or RESUME_RING),
            shared=bool(run.get("share")),
            share_key=share_key,
            keepalive=(float(run["keepalive"])
                       if run.get("keepalive") is not None else None),
            max_subscribers=int(run.get("max_subscribers")
                                or MAX_SUBSCRIBERS),
            sub_budget=int(run.get("sub_budget") or SUB_BUDGET),
            node=self.node_name)
        state.ctx = ctx
        primary = state.admit(sub_opts)
        if isinstance(primary, dict):  # refusal on the FIRST subscriber
            yield wire.encode_msg(
                {"type": wire.EV_ATTACH_ACK, "node": self.node_name,
                 "attach": {**primary, "run_id": ctx.run_id},
                 "error": f"attach refused ({primary['reason']}): "
                          f"{primary['detail']}"})
            return
        prev = None
        lost_to = ""
        with self._runs_mu:
            if share_key:
                # the AUTHORITATIVE share-key decision happens here,
                # under the registry lock: the early pre-ctx check is an
                # optimization, and two concurrent first-requests for
                # one key must not both start gadgets — first to
                # register wins, the loser attaches to it instead
                winner = self._streams.get(self._shared.get(share_key, ""))
                if winner is not None and not winner.done:
                    lost_to = winner.run_id
                else:
                    self._shared[share_key] = ctx.run_id
            if not lost_to:
                prev = self._streams.get(ctx.run_id)
                self._runs[ctx.run_id] = ctx
                self._streams[ctx.run_id] = state
        if lost_to:
            log.info("run %s (%s): lost the share-key race to %s; "
                     "attaching as a subscriber instead of starting a "
                     "second gadget", ctx.run_id, desc.full_name, lost_to)
            yield from self._attach_stream(
                {**sub_opts, "run_id": lost_to}, request_iterator, context)
            return
        if prev is not None and not prev.done and prev.ctx is not None:
            # a client restarting under a reused run_id while the
            # previous life still lingers: two gadgets capturing under
            # one id would double-count — the new request supersedes
            log.warning("run %s (%s): superseded by a new run request; "
                        "cancelling the previous life",
                        ctx.run_id, desc.full_name)
            prev.ctx.cancel()
        _tm_active_runs.inc()
        # server span per run (child of the RPC span); operators and the
        # device plane parent their spans to this via ctx.extra —
        # ambient=False for the same cross-thread-generator reason.
        # The run span, registries, and log handler are unwound by the
        # RUN thread when the gadget actually ends — NOT when this RPC's
        # generator dies, because a resumable run outlives its first
        # connection by design.
        run_span = TRACER.span(f"agent/run/{desc.full_name}",
                               parent=rpc_span.context,
                               attrs={"run_id": ctx.run_id,
                                      "gadget": desc.full_name},
                               ambient=False)
        yield from self._run_gadget_stream(ctx, desc, outputs, state,
                                           primary, run_span,
                                           request_iterator, context)

    def _run_gadget_stream(self, ctx, desc, outputs, state: SharedRun,
                           primary: Subscriber, run_span, request_iterator,
                           context) -> Iterator[bytes]:
        cleanup_mu = threading.Lock()
        cleanup_state = {"done": False, "handler": None}

        def run_cleanup():
            """Unwound exactly ONCE when the RUN ends (run thread,
            loud-failure path, or a setup crash) — never on a mere
            client disconnect: a resumable run outlives its first
            connection by design."""
            with cleanup_mu:
                if cleanup_state["done"]:
                    return
                cleanup_state["done"] = True
            ctx.cancel()
            if cleanup_state["handler"] is not None:
                ctx.logger.removeHandler(cleanup_state["handler"])
            with self._runs_mu:
                # identity-guarded: a superseding run request may have
                # re-registered this run_id with a NEW context/stream
                if self._runs.get(ctx.run_id) is ctx:
                    self._runs.pop(ctx.run_id, None)
            _tm_active_runs.dec()
            run_span.__exit__(None, None, None)
            # keep the stream state around one linger/keepalive window so
            # a client that dropped right before the end can resume for
            # the tail
            self._retire_stream(state, max(state.linger, state.keepalive))

        try:
            yield from self._run_stream_setup_and_serve(
                ctx, desc, outputs, state, primary, run_span, run_cleanup,
                cleanup_state, request_iterator, context)
        except GeneratorExit:
            # client disconnect mid-serve: the serving finally already
            # detached; the run itself lives on (or cancels via detach
            # for non-resumable runs) — no registry unwind here
            raise
        except BaseException:
            # setup (or serving) died before the run thread could take
            # ownership of cleanup: unwind so _runs/_streams and the
            # active-runs gauge cannot drift in a long-lived agent
            run_cleanup()
            state.finish()
            raise

    def _run_stream_setup_and_serve(self, ctx, desc, outputs,
                                    state: SharedRun, primary: Subscriber,
                                    run_span, run_cleanup, cleanup_state,
                                    request_iterator,
                                    context) -> Iterator[bytes]:
        push = state.push

        # run logs multiplex onto the same stream with severity in the
        # type bits; run/trace IDs ride the header so the client can
        # correlate a remote log line with this run's spans
        run_span.__enter__()
        ctx.extra["trace_ctx"] = run_span.context
        trace_ctx = ctx.extra.get("trace_ctx")
        stream_log = StreamLogger(
            push, shift=wire.EV_LOG_SHIFT, run_id=ctx.run_id,
            trace_id=trace_ctx.trace_id if trace_ctx is not None else "")
        log_handler = StreamLogHandler(stream_log)
        ctx.logger.addHandler(log_handler)
        cleanup_state["handler"] = log_handler

        cols = desc.columns()

        def row_dict(ev) -> dict:
            d = cols.to_dict(ev)
            d["node"] = self.node_name  # authoritative node identity
            return d

        def on_event(ev):
            if "json" in outputs:
                push(wire.EV_PAYLOAD_JSON, {"node": self.node_name},
                     json.dumps(row_dict(ev), default=str).encode())

        def on_event_array(evs):
            if "json" in outputs:
                payload = json.dumps(
                    [row_dict(e) for e in evs], default=str).encode()
                push(wire.EV_PAYLOAD_ARRAY, {"node": self.node_name}, payload)

        def on_batch(batch):
            if "batch" in outputs and batch.count:
                push(wire.EV_BATCH_NPZ, {"node": self.node_name,
                                         "drops": batch.drops},
                     wire.encode_batch(batch))

        if "summary" in outputs:
            def on_summary(summary):
                h, payload = wire.encode_summary(summary)
                push(wire.EV_SUMMARY, {"node": self.node_name, **h}, payload)
            ctx.extra["on_sketch_summary"] = on_summary

        # alert transitions ride the same stream as typed events whenever
        # the alerts operator is enabled for this run (rules set); the
        # client's GrpcRuntime folds them cluster-wide
        def on_alert_event(alert: dict):
            push(wire.EV_ALERT, {"node": self.node_name, "alert": alert})
        ctx.extra["on_alert_event"] = on_alert_event

        # sealed-window announcements ride the stream as header-only
        # EV_WINDOW records: summary-tier subscribers learn a window
        # exists (and can FetchWindows it) without the raw batches
        def on_window_sealed(win_header: dict):
            push(wire.EV_WINDOW, {"node": self.node_name,
                                  "window": win_header})
        ctx.extra["on_window_sealed"] = on_window_sealed

        # standing-query materialized answers ride the summary tier as
        # EV_QUERY records (header: query identity + coverage digest;
        # payload: one packed sealed window — the QueryWindows reply
        # frame shape, so subscribers reuse the same decode path)
        def on_query_answer(qheader: dict, qpayload: bytes):
            push(wire.EV_QUERY, {"node": self.node_name,
                                 "query": qheader}, qpayload)
        ctx.extra["on_query_answer"] = on_query_answer

        # control reader: client stop requests cancel the context (or
        # detach the subscriber on a shared run)
        threading.Thread(target=self._control_loop,
                         args=(request_iterator, ctx, state, primary),
                         daemon=True).start()

        # resolve handler wiring BEFORE spawning the run thread so an
        # unknown gadget type fails the RPC loudly instead of vanishing
        # inside a daemon thread
        try:
            h_event, h_array = handlers_for(desc.gadget_type, outputs,
                                            on_event, on_event_array)
        except ValueError as e:
            log.error("RunGadget %s: %s", desc.full_name, e)
            # the error trailer goes through the ring like every other
            # trailer: a client that loses this connection and resumes
            # within the retire window must still see the failure, not
            # a clean empty end
            push(wire.EV_RESULT, {"error": str(e), "gadget_error": True},
                 force=True)
            run_cleanup()
            state.finish()
            q, gen, _ack = state.attach_subscriber(primary, 0)
            try:
                yield from self._serve_attached(state, primary, q, gen,
                                                context)
            finally:
                state.detach(primary, gen)
            return

        def run_thread():
            try:
                res = self.runtime.run_gadget(
                    ctx,
                    on_event=h_event,
                    on_event_array=h_array,
                    on_batch=on_batch,
                )
                # trailers ride the same seq'd push path (force=True so a
                # full queue evicts data, never the result) — they live
                # in the ring too, so a resumed client still gets them
                node_res = res.get(self.node_name) if res else None
                if node_res is not None and node_res.error:
                    push(wire.EV_RESULT, {"error": node_res.error,
                                          "gadget_error": True}, force=True)
                elif node_res is not None and isinstance(node_res.result,
                                                         bytes):
                    push(wire.EV_RESULT, {}, node_res.result, force=True)
                if state.dropped:
                    push(wire.EV_CONTROL_ACK, {"dropped": state.dropped},
                         force=True)
            finally:
                run_cleanup()
                # end-of-stream sentinel; never blocks on a gone client
                state.finish()

        t = threading.Thread(target=run_thread, daemon=True)
        t.start()

        q, gen, _ack = state.attach_subscriber(primary, 0)
        try:
            yield from self._serve_attached(state, primary, q, gen, context)
        finally:
            state.detach(primary, gen)

    # -- ContainerManager (hook-facing; ref: gadgettracermanager.go:151) ----

    def add_container(self, request: bytes, context) -> bytes:
        _tm_rpc.labels(method="AddContainer").inc()
        h, _ = wire.decode_msg(request)
        from ..operators.operators import ensure_initialized
        lm = ensure_initialized("localmanager")
        c = h.get("container", {})
        lm.cc.add_container(Container(
            id=c.get("id", ""), name=c.get("name", ""),
            pid=int(c.get("pid", 0)), mntns=int(c.get("mntns", 0)),
            netns=int(c.get("netns", 0)), namespace=c.get("namespace", ""),
            pod=c.get("pod", ""), labels=c.get("labels", {}),
        ))
        return wire.encode_msg({"ok": True, "count": len(lm.cc)})

    def remove_container(self, request: bytes, context) -> bytes:
        _tm_rpc.labels(method="RemoveContainer").inc()
        h, _ = wire.decode_msg(request)
        from ..operators.operators import get as get_op
        lm = get_op("localmanager")
        if lm.cc is not None:
            lm.cc.remove_container(h.get("container", {}).get("id", ""))
        return wire.encode_msg({"ok": True})

    # -- Trace-resource RPCs (ref: §3.5 — the CRD path served remotely) -----

    def apply_trace(self, request: bytes, context) -> bytes:
        _tm_rpc.labels(method="ApplyTrace").inc()
        h, _ = wire.decode_msg(request)
        try:
            return wire.encode_msg({"trace": self.traces.apply(h.get("trace", {}))})
        except Exception as e:
            return wire.encode_msg({"error": str(e)})

    def get_trace(self, request: bytes, context) -> bytes:
        _tm_rpc.labels(method="GetTrace").inc()
        h, _ = wire.decode_msg(request)
        doc = self.traces.get(h.get("name", ""))
        if doc is None:
            return wire.encode_msg({"error": f"trace {h.get('name')!r} not found"})
        return wire.encode_msg({"trace": doc})

    def list_traces(self, request: bytes, context) -> bytes:
        _tm_rpc.labels(method="ListTraces").inc()
        return wire.encode_msg({"traces": self.traces.list()})

    def delete_trace(self, request: bytes, context) -> bytes:
        _tm_rpc.labels(method="DeleteTrace").inc()
        h, _ = wire.decode_msg(request)
        return wire.encode_msg({"deleted": self.traces.delete(h.get("name", ""))})

    # -- capture/recording lifecycle RPCs (capture/) ------------------------

    def start_recording(self, request: bytes, context) -> bytes:
        """Arm the node-wide recording: every running and future gadget
        run on this agent tees its batches/summaries/alerts into
        journals under the recording directory until StopRecording."""
        _tm_rpc.labels(method="StartRecording").inc()
        h, _ = wire.decode_msg(request)
        from ..capture import RECORDINGS
        opts = {k: v for k, v in (h.get("opts") or {}).items()
                if k in ("max_segment_bytes", "max_segment_age",
                         "retention_bytes", "retention_segments")}
        rid = h.get("recording_id", "")
        existing = RECORDINGS.get(rid) if rid else None
        if existing is not None:
            # idempotent for fan-out retries and in-process agent fleets
            # sharing one manager: arming an armed recording is a no-op
            return wire.encode_msg({"ok": True, "recording_id": existing.id,
                                    "dir": existing.path, "already": True,
                                    "node": self.node_name})
        try:
            # always the manager's base area (--capture-dir): a client-
            # chosen base would be invisible to ListRecordings/Fetch,
            # which resolve under the same default
            rec = RECORDINGS.start(rid, **opts)
        except (ValueError, OSError) as e:
            return wire.encode_msg({"error": str(e)})
        return wire.encode_msg({"ok": True, "recording_id": rec.id,
                                "dir": rec.path, "node": self.node_name})

    def stop_recording(self, request: bytes, context) -> bytes:
        _tm_rpc.labels(method="StopRecording").inc()
        h, _ = wire.decode_msg(request)
        import os
        from ..capture import RECORDINGS
        from ..capture.manager import RECORDING_META
        rid = h.get("recording_id", "")
        try:
            meta = RECORDINGS.stop(rid)
        except KeyError as e:
            # a peer RPC in the same process (in-process fleet) may have
            # stopped it already: a sealed recording on disk is success,
            # a never-started id is the error
            try:
                done = os.path.join(RECORDINGS.recording_dir(rid),
                                    RECORDING_META)
            except ValueError as bad:
                return wire.encode_msg({"error": str(bad)})
            if rid and os.path.exists(done):
                return wire.encode_msg({"ok": True, "already": True,
                                        "node": self.node_name})
            return wire.encode_msg({"error": str(e)})
        return wire.encode_msg({"ok": True, "recording": meta,
                                "node": self.node_name})

    def list_recordings(self, request: bytes, context) -> bytes:
        """Active + on-disk recordings; with recording_id set, also the
        relative file list (the fetch fan-out's download manifest)."""
        _tm_rpc.labels(method="ListRecordings").inc()
        h, _ = wire.decode_msg(request)
        from ..capture import RECORDINGS
        msg: dict = {"node": self.node_name,
                     "recordings": RECORDINGS.list()}
        rid = h.get("recording_id", "")
        if rid:
            import os
            try:
                root = RECORDINGS.recording_dir(rid)
            except ValueError as e:
                msg["error"] = str(e)
                return wire.encode_msg(msg)
            files = []
            if os.path.isdir(root):
                for base, _dirs, names in os.walk(root):
                    for name in sorted(names):
                        p = os.path.join(base, name)
                        files.append({"path": os.path.relpath(p, root),
                                      "bytes": os.path.getsize(p)})
            else:
                msg["error"] = f"no recording {rid!r} on {self.node_name}"
            msg["files"] = sorted(files, key=lambda f: f["path"])
        return wire.encode_msg(msg)

    def fetch_segment(self, request: bytes, context) -> bytes:
        """Chunked download of one recording file (segments, manifests);
        stays under gRPC's 4 MiB default message cap via offset+limit."""
        _tm_rpc.labels(method="FetchSegment").inc()
        h, _ = wire.decode_msg(request)
        import os
        from ..capture import RECORDINGS
        rid = h.get("recording_id", "")
        rel = h.get("file", "")
        norm = os.path.normpath(rel)
        if not rid or not rel or norm.startswith("..") or \
                os.path.isabs(norm):
            return wire.encode_msg(
                {"error": f"bad fetch request ({rid!r}, {rel!r})"})
        try:
            path = os.path.join(RECORDINGS.recording_dir(rid), norm)
        except ValueError as e:
            return wire.encode_msg({"error": str(e)})
        offset = max(int(h.get("offset", 0)), 0)
        limit = min(max(int(h.get("limit", 1 << 20)), 1), 2 << 20)
        try:
            size = os.path.getsize(path)
            with open(path, "rb") as f:
                f.seek(offset)
                chunk = f.read(limit)
        except OSError as e:
            return wire.encode_msg({"error": f"{rel}: {e.strerror or e}"})
        return wire.encode_msg(
            {"ok": True, "file": rel, "offset": offset, "size": size,
             "eof": offset + len(chunk) >= size}, chunk)

    # -- sketch-history RPCs (history/): range-listing + chunked pulls ------

    @staticmethod
    def _window_range(h: dict) -> dict:
        """The (optional) range/slice filter every history RPC accepts —
        one parse, shared by ListWindows and FetchWindows."""
        return {
            "start_ts": float(h["start_ts"]) if h.get("start_ts") is not None else None,
            "end_ts": float(h["end_ts"]) if h.get("end_ts") is not None else None,
            "start_seq": int(h["start_seq"]) if h.get("start_seq") is not None else None,
            "end_seq": int(h["end_seq"]) if h.get("end_seq") is not None else None,
            "key": h.get("key") or None,
        }

    def list_windows(self, request: bytes, context) -> bytes:
        """Header rows of every sealed window overlapping the requested
        seq/ts range (and slice key) — the pruning half of a fleet-wide
        range query: the client decides which windows are worth pulling
        before any payload bytes move."""
        _tm_rpc.labels(method="ListWindows").inc()
        h, _ = wire.decode_msg(request)
        from ..history import HISTORY, validate_store_name
        gadget = h.get("gadget", "") or ""
        if gadget:
            try:
                validate_store_name(gadget.replace("/", "-"))
            except ValueError as e:
                return wire.encode_msg({"error": str(e)})
        losses: list = []
        try:
            # node=self.node_name: an agent serves only windows ITS runs
            # sealed — in-process fleets share one base area, and a
            # fan-out merging every node's windows from every node would
            # double-count
            rows = HISTORY.list_windows(gadget=gadget, losses=losses,
                                        node=self.node_name,
                                        **self._window_range(h))
        except (OSError, ValueError) as e:
            return wire.encode_msg({"error": str(e)})
        return wire.encode_msg({"ok": True, "node": self.node_name,
                                "windows": rows, "losses": losses})

    def fetch_windows(self, request: bytes, context) -> bytes:
        """Chunked download of matching windows' frames; every reply
        stays under the gRPC message cap via offset + max_bytes (the
        FetchSegment discipline applied to typed windows instead of raw
        files). Store names resolve server-side only — the one
        client-supplied path component (gadget) is traversal-guarded."""
        _tm_rpc.labels(method="FetchWindows").inc()
        h, _ = wire.decode_msg(request)
        from ..history import HISTORY, pack_frames, validate_store_name
        gadget = h.get("gadget", "") or ""
        if gadget:
            try:
                validate_store_name(gadget.replace("/", "-"))
            except ValueError as e:
                return wire.encode_msg({"error": str(e)})
        try:
            # pagination contract: ANY offset is well-formed — one past
            # the last match (offset == N) or far beyond (offset > N)
            # returns an EMPTY ok reply with eof=true, never an error
            # (the client's drain loop lands on exactly N after a full
            # chunk, and a store shrunk by GC/compaction between chunks
            # can leave it beyond)
            offset = max(int(h.get("offset", 0)), 0)
            max_bytes = min(max(int(h.get("max_bytes", 1 << 20)), 1),
                            2 << 20)
        except (TypeError, ValueError) as e:
            return wire.encode_msg({"error": f"bad offset/max_bytes: {e}"})
        losses: list = []
        picked: list[tuple[dict, bytes]] = []
        size = 0
        eof = True
        try:
            it = HISTORY.fetch_windows(gadget=gadget, losses=losses,
                                       node=self.node_name,
                                       **self._window_range(h))
            for i, (header, payload) in enumerate(it):
                if i < offset:
                    continue
                frame_size = len(payload) + 512  # header slack
                if picked and size + frame_size > max_bytes:
                    eof = False
                    break
                picked.append((header, payload))
                size += frame_size
        except (OSError, ValueError) as e:
            return wire.encode_msg({"error": str(e)})
        return wire.encode_msg(
            {"ok": True, "node": self.node_name, "count": len(picked),
             "offset": offset, "next_offset": offset + len(picked),
             "eof": eof,
             # every chunk rescans from frame 0, so only the FIRST chunk
             # reports torn-tail losses — the client concatenates reply
             # losses, and repeating them would multiply the accounting
             "losses": losses if offset == 0 else []},
            pack_frames(picked))

    def query_windows(self, request: bytes, context) -> bytes:
        """Query pushdown (history/lifecycle plane): fold the
        (time-range, seq-range, key) query NODE-SIDE — prune, decode,
        dedupe across tiers, merge — and ship back ONE merged window
        plus accounting (windows folded, levels consulted, torn/dropped
        counts). Fleet-query wire cost becomes O(nodes) instead of
        O(windows): the raw windows never leave the node."""
        _tm_rpc.labels(method="QueryWindows").inc()
        h, _ = wire.decode_msg(request)
        from ..history import (HISTORY, decode_frames, dedupe_compacted,
                               encode_window, level_counts, merge_windows,
                               merged_to_sealed, pack_frames,
                               validate_store_name)
        gadget = h.get("gadget", "") or ""
        if gadget:
            try:
                validate_store_name(gadget.replace("/", "-"))
            except ValueError as e:
                return wire.encode_msg({"error": str(e)})
        losses: list = []
        try:
            frames = list(HISTORY.fetch_windows(
                gadget=gadget, losses=losses, node=self.node_name,
                **self._window_range(h)))
        except (OSError, ValueError) as e:
            return wire.encode_msg({"error": str(e)})
        kept, notes = dedupe_compacted(decode_frames(frames))
        merged = merge_windows(kept)
        levels = level_counts(kept)
        payload = b""
        if merged.windows:
            sw = merged_to_sealed(
                merged, gadget=gadget or kept[0].gadget,
                node=self.node_name, level=max(levels, default=0),
                window=0, run_id="query")
            payload = pack_frames([encode_window(sw)])
        return wire.encode_msg({
            "ok": True,
            "node": self.node_name,
            "folded": merged.windows,
            "levels": {str(k): v for k, v in sorted(levels.items())},
            "torn": len(losses),
            "dropped": list(merged.skipped) + notes,
            "losses": losses,
        }, payload)

    # -- dump-state debug RPC (ref: gadgettracermanager.go DumpState :204) --

    def dump_state(self, request: bytes, context) -> bytes:
        _tm_rpc.labels(method="DumpState").inc()
        try:
            req, _ = wire.decode_msg(request)
        except (ValueError, json.JSONDecodeError):
            req = {}
        import sys
        frames = {}
        for tid, frame in sys._current_frames().items():
            stack = []
            f = frame
            while f is not None and len(stack) < 32:
                stack.append(f"{f.f_code.co_filename}:{f.f_lineno} {f.f_code.co_name}")
                f = f.f_back
            frames[str(tid)] = stack
        with self._runs_mu:
            runs = list(self._runs)
            stream_states = list(self._streams.values())
        # resume/shared-plane view: every live (or lingering) run stream
        # with its attach + subscriber state — `ig-tpu fleet health` and
        # `ig-tpu fleet runs` read this to tell a serving run from one
        # awaiting a resume, and a saturated run from an idle one
        now = time.monotonic()
        run_rows = [{
            "run_id": st.run_id, "gadget": st.gadget, "seq": st.seq,
            "resumable": st.resumable, "attached": st.is_attached(),
            "attaches": st.attaches, "done": st.done,
            "dropped": st.dropped,
            "detached_for": (round(now - st.detached_at, 3)
                             if st.detached_at is not None else 0.0),
            "shared": st.shared,
            "subscribers": st.subscriber_rows(),
            "live_subscribers": st.live_subscribers(),
            "max_subscribers": st.max_subscribers,
            "sub_budget": st.sub_budget,
            "keepalive": st.keepalive,
            "keepalive_remaining": round(st.keepalive_remaining(), 3),
        } for st in stream_states]
        # container set, as the reference's DumpState does
        # (gadgettracermanager.go:204-219 dumps containers + stacks)
        containers: list = []
        dump_error = ""
        try:
            from ..operators.operators import get as get_op
            lm = get_op("localmanager")
            if lm.cc is not None:
                containers = [
                    {"id": c.id, "name": c.name, "pid": c.pid,
                     "mntns": c.mntns, "namespace": c.namespace, "pod": c.pod,
                     "runtime": c.runtime}
                    for c in lm.cc.get_all()
                ]
        except Exception as e:
            dump_error = f"container dump failed: {e!r}"
        # the node's history-tier footprint rides the debug dump too:
        # `ig-tpu history tiers --remote` and the doctor history_tiers
        # row read windows/bytes per compaction level + archive usage
        # without a store-walking RPC of their own
        history_tiers: dict = {}
        try:
            from ..history import HISTORY
            # TTL-cached: fleet health/runs/alerts all poll DumpState,
            # and the tier walk reads every store frame
            history_tiers = HISTORY.tier_stats(ttl=10.0)
        except Exception as e:  # noqa: BLE001 — debug dump stays best-effort
            history_tiers = {"error": repr(e)}
        # standing-query accounting rides the debug dump the same way:
        # one row per live query (coverage, refresh/publish counts,
        # cache hit/miss/invalidation) so `ig-tpu watch --table` and
        # `fleet queries` never need a store-walking RPC
        standing_queries: list = []
        try:
            from ..queries import live_stats
            standing_queries = live_stats()
        except Exception as e:  # noqa: BLE001 — debug dump stays best-effort
            standing_queries = [{"error": repr(e)}]
        # pipeline health (ISSUE 18): one row per live run — per-stage
        # lag watermarks/quantiles, starved ratio, backpressure — so
        # `ig-tpu fleet lag` and the doctor pipeline_health row read the
        # hot path's health without a dedicated RPC
        pipeline: list = []
        try:
            from ..telemetry.pipeline import live_stats as pipeline_stats
            pipeline = [{"run_id": ps.run_id, "gadget": ps.gadget,
                         **ps.snapshot()} for ps in pipeline_stats()]
        except Exception as e:  # noqa: BLE001 — debug dump stays best-effort
            pipeline = [{"error": repr(e)}]
        # accuracy audit plane (ISSUE 19): one row per audited run —
        # per-stat analytic bound vs observed error, sample size, drift
        # ratio — so `ig-tpu fleet accuracy` and the doctor accuracy row
        # read the envelope without a dedicated RPC
        accuracy: list = []
        try:
            from ..ops.accuracy import live_stats as accuracy_stats
            accuracy = [{"run_id": a.run_id, "gadget": a.gadget,
                         **a.snapshot()} for a in accuracy_stats()]
        except Exception as e:  # noqa: BLE001 — debug dump stays best-effort
            accuracy = [{"error": repr(e)}]
        # the node's alert table rides the same debug dump, so a remote
        # `ig-tpu alerts list` can read every agent's active alerts
        from ..alerts import ACTIVE as active_alerts
        msg = {"threads": frames, "active_runs": runs,
               "runs": run_rows,
               "containers": containers,
               "alerts": active_alerts.all(),
               "history_tiers": history_tiers,
               "standing_queries": standing_queries,
               "pipeline": pipeline,
               "accuracy": accuracy,
               # CRD-path state rides the same debug dump (the reference's
               # daemon dumps its trace list alongside containers)
               "traces": [{"name": t["metadata"]["name"],
                           "gadget": t["spec"].get("gadget", ""),
                           "state": t["status"].get("state", ""),
                           "error": t["status"].get("operationError", "")}
                          for t in self.traces.list()]}
        if dump_error:
            msg["error"] = dump_error
        # the process flight recorder (recent spans/logs/errors/facts)
        # rides the same debug RPC, so a wedged agent can still be read;
        # max_spans lets trace export request the whole ring instead of
        # the 512-span debug default
        msg["flight_record"] = RECORDER.snapshot(
            max_spans=int(req.get("max_spans") or 512))
        return wire.encode_msg(msg)


def _traced_unary(name, behavior):
    """Open a server span per unary RPC, parented to the caller's span
    when the request header carries a traceparent."""
    def handler(request, context):
        parent = None
        try:
            h, _ = wire.decode_msg(request)
            parent = wire.extract_span(h)
        except (ValueError, KeyError, IndexError, UnicodeDecodeError,
                json.JSONDecodeError):
            parent = None
        with TRACER.span(f"agent/{name}", parent=parent):
            return behavior(request, context)
    return handler


def _method(behavior, kind, name=""):
    s, d = wire.identity_serializer, wire.identity_deserializer
    if kind == "unary":
        return grpc.unary_unary_rpc_method_handler(
            _traced_unary(name, behavior),
            request_deserializer=d, response_serializer=s)
    return grpc.stream_stream_rpc_method_handler(
        behavior, request_deserializer=d, response_serializer=s)


def serve(address: str = "unix:///tmp/igtpu-agent.sock",
          node_name: str = "node", max_workers: int = 8,
          checkpoint_dir: str = "",
          checkpoint_interval: float = 30.0,
          metrics_addr: str = "") -> tuple[grpc.Server, AgentServer]:
    """Start the agent (non-blocking); returns (grpc_server, agent).
    metrics_addr ('host:port', off by default) additionally serves the
    telemetry registry as Prometheus text on GET /metrics."""
    agent = AgentServer(node_name=node_name)
    # first agent in the process names the tracer/flight-recorder identity
    # (one agent per process in real deployments; in-process test fleets
    # share both, so keep the two first-wins-consistent — a last-wins
    # fact would contradict the span attribution)
    if not TRACER.node:
        TRACER.node = node_name
        RECORDER.set_fact("node", node_name)
    if metrics_addr:
        from ..telemetry import MetricsServer
        agent.metrics_server = MetricsServer(metrics_addr).start()
    if checkpoint_dir:
        agent.start_checkpointer(checkpoint_dir, checkpoint_interval)
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=max_workers))
    handlers = {
        "GetCatalog": _method(agent.get_catalog, "unary", "GetCatalog"),
        "RunGadget": _method(agent.run_gadget, "stream"),
        "AddContainer": _method(agent.add_container, "unary", "AddContainer"),
        "RemoveContainer": _method(agent.remove_container, "unary",
                                   "RemoveContainer"),
        "DumpState": _method(agent.dump_state, "unary", "DumpState"),
        "StartRecording": _method(agent.start_recording, "unary",
                                  "StartRecording"),
        "StopRecording": _method(agent.stop_recording, "unary",
                                 "StopRecording"),
        "ListRecordings": _method(agent.list_recordings, "unary",
                                  "ListRecordings"),
        "FetchSegment": _method(agent.fetch_segment, "unary", "FetchSegment"),
        "ListWindows": _method(agent.list_windows, "unary", "ListWindows"),
        "FetchWindows": _method(agent.fetch_windows, "unary",
                                "FetchWindows"),
        "QueryWindows": _method(agent.query_windows, "unary",
                                "QueryWindows"),
        "ApplyTrace": _method(agent.apply_trace, "unary", "ApplyTrace"),
        "GetTrace": _method(agent.get_trace, "unary", "GetTrace"),
        "ListTraces": _method(agent.list_traces, "unary", "ListTraces"),
        "DeleteTrace": _method(agent.delete_trace, "unary", "DeleteTrace"),
    }
    server.add_generic_rpc_handlers((
        grpc.method_handlers_generic_handler("igtpu.GadgetManager", handlers),
    ))
    # standard health service analogue (ref: main.go:224-245)
    server.add_insecure_port(address)
    server.start()
    return server, agent
