"""Dialers: how an AgentClient reaches its agent.

Reference contract: pkg/runtime/grpc/k8s-exec-dialer.go:1-132 — the client
does not assume the agent is routable; it dials gRPC over the stdin/stdout
of a `kubectl exec` stream into the gadget pod. The seam here is the same:
a Dialer turns a target into a grpc.Channel. DirectDialer is the plain
host:port/unix path; ExecTunnelDialer bridges a local unix socket to a
subprocess's stdio (kubectl exec, ssh, or any stdio proxy), one subprocess
per gRPC connection, exactly as the reference spawns one exec stream per
dial.
"""

from __future__ import annotations

import logging
import os
import socket
import subprocess
import tempfile
import threading
import uuid

import grpc

log = logging.getLogger("ig-tpu.dialer")


class DirectDialer:
    """Plain target: host:port or unix:///path."""

    def dial(self, target: str) -> grpc.Channel:
        return grpc.insecure_channel(target)

    def close(self) -> None:
        pass


class ExecTunnelDialer:
    """gRPC over a subprocess's stdio (the k8s-exec-dialer analogue).

    argv is the tunnel command, e.g.
      ["kubectl", "exec", "-i", "-n", "ig-tpu", "pod/ig-tpu-agent-x",
       "--", "socat", "-", "UNIX-CONNECT:/run/igtpu-agent.sock"]
    Anything that relays its stdio to the agent's socket works (ssh, socat,
    a python bridge). The dialer listens on a private local unix socket;
    every connection gRPC opens spawns one tunnel subprocess and pumps
    bytes both ways.
    """

    def __init__(self, argv: list[str]):
        self.argv = list(argv)
        self._dir = tempfile.mkdtemp(prefix="igtpu-tunnel-")
        self._path = os.path.join(self._dir, f"{uuid.uuid4().hex[:8]}.sock")
        self._listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._listener.bind(self._path)
        self._listener.listen(8)
        self._closing = False
        self._procs: list[subprocess.Popen] = []
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               daemon=True)
        self._accept_thread.start()

    def dial(self, target: str) -> grpc.Channel:
        # the tunnel command embeds the real destination; `target` is kept
        # for logging/symmetry with DirectDialer
        return grpc.insecure_channel(f"unix://{self._path}")

    def _accept_loop(self) -> None:
        while not self._closing:
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            proc = subprocess.Popen(
                self.argv, stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                stderr=subprocess.DEVNULL, bufsize=0)
            self._procs.append(proc)
            threading.Thread(target=self._pump_out, args=(conn, proc),
                             daemon=True).start()
            threading.Thread(target=self._pump_in, args=(conn, proc),
                             daemon=True).start()

    @staticmethod
    def _pump_out(conn: socket.socket, proc: subprocess.Popen) -> None:
        """local socket → tunnel stdin"""
        try:
            while True:
                data = conn.recv(65536)
                if not data:
                    break
                # bufsize=0 → raw FileIO: write() may accept only part of
                # the chunk when the pipe is full; loop until drained or
                # the stream corrupts under backpressure
                mv = memoryview(data)
                while mv:
                    n = proc.stdin.write(mv)
                    if not n:
                        # would-block/zero write on a blocking pipe: the
                        # chunk can't be delivered intact — tear the tunnel
                        # down rather than resume mid-stream corrupted
                        raise OSError("tunnel stdin short write")
                    mv = mv[n:]
                proc.stdin.flush()
        except (OSError, ValueError, BrokenPipeError):
            pass
        finally:
            try:
                proc.stdin.close()
            except Exception as e:  # noqa: BLE001 — teardown best-effort
                log.debug("tunnel stdin close failed: %r", e)

    def _pump_in(self, conn: socket.socket, proc: subprocess.Popen) -> None:
        """tunnel stdout → local socket"""
        try:
            while True:
                # bufsize=0 → raw FileIO: read() returns as soon as any
                # bytes are available (partial reads are fine here)
                data = proc.stdout.read(65536)
                if not data:
                    break
                conn.sendall(data)
        except (OSError, ValueError, BrokenPipeError):
            pass
        finally:
            try:
                conn.shutdown(socket.SHUT_WR)
            except OSError:
                pass
            # stdout EOF means the tunnel exited (or is about to once its
            # stdin closes): reap it so reconnect churn over a long-lived
            # runtime doesn't accumulate zombies
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
                try:
                    proc.wait(timeout=2)
                except subprocess.TimeoutExpired:
                    pass
            try:
                self._procs.remove(proc)
            except ValueError:
                pass

    def close(self) -> None:
        self._closing = True
        try:
            self._listener.close()
        except OSError:
            pass
        for p in self._procs:
            try:
                p.kill()
                p.wait(timeout=2)
            except Exception as e:  # noqa: BLE001 — teardown best-effort
                log.debug("tunnel process reap failed: %r", e)
        try:
            os.unlink(self._path)
            os.rmdir(self._dir)
        except OSError:
            pass


def kubectl_exec_dialer(pod: str, namespace: str = "ig-tpu",
                        agent_socket: str = "/run/igtpu-agent.sock",
                        kubectl: str = "kubectl") -> ExecTunnelDialer:
    """The concrete kubectl-exec tunnel (k8s-exec-dialer.go parity)."""
    return ExecTunnelDialer([
        kubectl, "exec", "-i", "-n", namespace, pod, "--",
        "socat", "-", f"UNIX-CONNECT:{agent_socket}",
    ])
