"""ig-tpu-agent daemon + hook client subcommands.

Reference contract: gadget-container/gadgettracermanager/main.go — serve
mode starts the gRPC services on a unix socket (:247-299) with a liveness
probe subcommand (:224-245); the same binary doubles as the hook client
(add/remove-container, used by OCI/NRI hooks — hooks/oci/main.go).

Usage:
  python -m inspektor_gadget_tpu.agent.main serve --listen unix:///run/ig.sock
  python -m inspektor_gadget_tpu.agent.main liveness --target ...
  python -m inspektor_gadget_tpu.agent.main add-container --id c1 --pid 123 ...
  python -m inspektor_gadget_tpu.agent.main dump   # debug state (DumpState)
"""

from __future__ import annotations

import argparse
import signal
import sys
import time


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="ig-tpu-agent")
    sub = ap.add_subparsers(dest="cmd", required=True)

    sp = sub.add_parser("serve")
    sp.add_argument("--listen", default="unix:///tmp/igtpu-agent.sock")
    sp.add_argument("--node-name", default="node")
    sp.add_argument("--pod-manifest", default="",
                    help="JSON pod manifest to watch with the pod informer")
    sp.add_argument("--kube-api", default="",
                    help="apiserver URL for pod-informer discovery")
    sp.add_argument("--informer-interval", type=float, default=2.0)
    sp.add_argument("--checkpoint-dir", default="",
                    help="persist live sketch state here each interval; "
                         "resumed (merged) after restart")
    sp.add_argument("--checkpoint-interval", type=float, default=30.0)
    sp.add_argument("--capture-dir", default="",
                    help="base directory for capture recordings "
                         "(StartRecording RPC / ig-tpu record start); "
                         "default $IG_CAPTURE_DIR or ~/.ig-tpu/capture")
    sp.add_argument("--history-dir", default="",
                    help="base directory for the sealed-window sketch "
                         "history (tpusketch --history true; served via "
                         "ListWindows/FetchWindows); default "
                         "$IG_HISTORY_DIR or ~/.ig-tpu/history")
    from ..history.lifecycle import DEFAULT_SCHEDULE
    sp.add_argument("--history-compact", action="store_true",
                    help="run the tiered-history compaction engine in "
                         "the background: aged sealed windows merge into "
                         "coarser super-windows per --history-schedule")
    sp.add_argument("--history-schedule", default=DEFAULT_SCHEDULE,
                    help="resolution schedule res@horizon[,...]; the "
                         "last horizon must be inf (validated at startup)")
    sp.add_argument("--history-compact-interval", type=float, default=60.0,
                    help="seconds between background compaction passes")
    sp.add_argument("--history-archive-dir", default="",
                    help="offload fully-compacted cold history segments "
                         "to this archive root (manifest-driven "
                         "rehydration serves queries over them)")
    sp.add_argument("--history-archive-cache-bytes", type=int,
                    default=64 << 20,
                    help="rehydration cache budget (LRU by bytes)")
    sp.add_argument("--metrics-addr", default="",
                    help="serve Prometheus text metrics on host:port "
                         "(e.g. :9100); off by default")
    sp.add_argument("--platform", default="auto",
                    choices=("auto", "tpu", "cpu"),
                    help="device backend: auto probes under a hard "
                         "timeout and degrades to cpu instead of hanging "
                         "at first device use; cpu skips the probe")
    sp.add_argument("--probe-timeout", type=float, default=None,
                    help="seconds to wait for the device probe "
                         "(default $IG_PLATFORM_PROBE_TIMEOUT or 20)")
    sp.add_argument("--flight-record-path", default="",
                    help="dump the flight recorder (recent spans/logs/"
                         "errors) here on SIGTERM/crash; default "
                         "/tmp/igtpu-flight-<node>.json, 'off' disables")
    sp.add_argument("--watch-traces", action="store_true",
                    help="reconcile Trace resources off the kube API "
                         "(requires --kube-api; controller role of "
                         "gadget-container main.go:262-299)")
    sp.add_argument("--trace-namespace", default="ig-tpu")
    sp.add_argument("--no-doctor", action="store_true",
                    help="skip the capture-window probe at startup")
    sp.add_argument("--install-hooks", action="store_true",
                    help="install runtime hooks on the host before "
                         "serving, remove them on shutdown "
                         "(entrypoint.sh:83-142 parity)")
    sp.add_argument("--host-root", default="/",
                    help="host filesystem mount point for hook installs")
    sp.add_argument("--hook-mode", default="auto",
                    choices=("auto", "oci", "nri", "fanotify"))

    for name in ("liveness", "dump"):
        p = sub.add_parser(name)
        p.add_argument("--target", default="unix:///tmp/igtpu-agent.sock")

    acp = sub.add_parser("add-container")
    acp.add_argument("--target", default="unix:///tmp/igtpu-agent.sock")
    for f in ("id", "name", "namespace", "pod"):
        acp.add_argument(f"--{f}", default="")
    acp.add_argument("--pid", type=int, default=0)
    acp.add_argument("--mntns", type=int, default=0)

    rcp = sub.add_parser("remove-container")
    rcp.add_argument("--target", default="unix:///tmp/igtpu-agent.sock")
    rcp.add_argument("--id", required=True)

    # hook installation on the host (ref: entrypoint.sh:83-142) and the
    # hook invocation itself (ref: hooks/oci/main.go)
    ihp = sub.add_parser("install-hooks")
    ihp.add_argument("--host-root", default="/")
    ihp.add_argument("--mode", default="auto",
                     choices=("auto", "oci", "nri", "fanotify"))
    ihp.add_argument("--socket", default="unix:///tmp/igtpu-agent.sock")

    uhp = sub.add_parser("uninstall-hooks")
    uhp.add_argument("--host-root", default="/")

    ohp = sub.add_parser("oci-hook")
    ohp.add_argument("--socket", default="unix:///tmp/igtpu-agent.sock")
    ohp.add_argument("--stage", default="prestart",
                     choices=("prestart", "poststop"))
    ohp.add_argument("--nri", action="store_true",
                     help="payload is an NRI event wrapper, not OCI state")

    args = ap.parse_args(argv)

    if args.cmd == "install-hooks":
        from .hooks import HookInstaller
        res = HookInstaller(args.host_root, args.socket).install(args.mode)
        print(f"hook mode: {res.mode}")
        for p in res.installed:
            print(f"installed {p}")
        for n in res.notes:
            print(n)
        if res.mode == "fanotify":
            # nothing installable: the watch runs inside the serving agent
            # — only a success if that's what was asked for/detected, not
            # a silent degrade from a failed NRI install
            print("note: fanotify discovery runs in the serving agent "
                  "process (serve wires it), no host files needed")
            return 1 if res.degraded else 0
        return 0 if res.installed else 1
    if args.cmd == "uninstall-hooks":
        from .hooks import HookInstaller
        for p in HookInstaller(args.host_root).uninstall():
            print(f"removed {p}")
        return 0
    if args.cmd == "oci-hook":
        from .hooks import run_oci_hook
        return run_oci_hook(args.stage, args.socket, sys.stdin,
                            nri=args.nri)

    if args.cmd == "serve":
        if args.watch_traces and not args.kube_api:
            ap.error("--watch-traces requires --kube-api")
        # bounded device acquisition BEFORE first device use (VERDICT hole
        # #1: the PJRT plugin can hang forever in backend init) — a failed
        # or timed-out probe pins this process to CPU, logged + counted
        from ..utils.platform_probe import DEFAULT_PROBE_TIMEOUT, acquire_platform
        acq = acquire_platform(
            args.platform,
            timeout=(args.probe_timeout if args.probe_timeout is not None
                     else DEFAULT_PROBE_TIMEOUT))
        print(f"device platform: {acq['platform']}"
              + (f" (degraded: {acq['detail']})" if acq["degraded"] else ""),
              flush=True)
        # entrypoint-analogue environment probe (ref: entrypoint.sh:21-120
        # detects OS/kernel/runtime before starting the daemon): report
        # which capture windows work on this host so degraded gadgets are
        # known up front, not discovered mid-run
        if not args.no_doctor:
            from ..doctor import render_report
            print(render_report(), flush=True)
        return _serve_loop(args)

    from .client import AgentClient
    client = AgentClient(args.target)
    if args.cmd == "liveness":
        try:
            client.get_catalog(use_cache_on_error=False)
            print("ok")
            return 0
        except Exception as e:
            print(f"unhealthy: {e}", file=sys.stderr)
            return 1
    if args.cmd == "dump":
        import json
        print(json.dumps(client.dump_state(), indent=2))
        return 0
    if args.cmd == "add-container":
        print(client.add_container({
            "id": args.id, "name": args.name, "pid": args.pid,
            "mntns": args.mntns, "namespace": args.namespace, "pod": args.pod,
        }))
        return 0
    if args.cmd == "remove-container":
        print(client.remove_container(args.id))
        return 0
    return 2


def _serve_loop(args) -> int:
    from ..telemetry.tracing import RECORDER, install_crash_handlers
    from .service import serve
    # crash-safe black box: unhandled exceptions (any thread) dump the
    # flight recorder, and the SIGTERM/SIGINT path below dumps it too —
    # a wedged or killed agent leaves evidence of what it was doing
    flight_path = args.flight_record_path or \
        f"/tmp/igtpu-flight-{args.node_name}.json"
    if flight_path != "off":
        install_crash_handlers(flight_path, signals=())
    if args.capture_dir:
        from ..capture import RECORDINGS
        RECORDINGS.set_base_dir(args.capture_dir)
    if args.history_dir:
        from ..history import HISTORY
        HISTORY.set_base_dir(args.history_dir)
    if args.history_archive_dir:
        from ..history import HISTORY
        HISTORY.set_archive(args.history_archive_dir,
                            args.history_archive_cache_bytes)
    compactor = None
    if args.history_compact:
        # schedule validated LOUDLY before the agent serves: a bad
        # retention policy must fail startup, not eat history later
        from ..history import CompactionEngine
        compactor = CompactionEngine(args.history_schedule)
        compactor.start_background(args.history_compact_interval)
    # bind BEFORE installing hooks: a prestart config pointing at a socket
    # nobody serves stalls every container creation on the host
    server, _agent = serve(args.listen, node_name=args.node_name,
                           checkpoint_dir=args.checkpoint_dir,
                           checkpoint_interval=args.checkpoint_interval,
                           metrics_addr=args.metrics_addr)
    if _agent.metrics_server is not None:
        print(f"metrics on http://{_agent.metrics_server.host}:"
              f"{_agent.metrics_server.port}/metrics", flush=True)
    installer = None
    watcher = None
    try:
        if args.watch_traces and args.kube_api:
            from ..gadgets.trace_resource import TraceWatcher
            from ..utils.k8s import KubeClient
            watcher = TraceWatcher(
                KubeClient(server=args.kube_api), _agent.traces,
                namespace=args.trace_namespace,
                interval=args.informer_interval)
            watcher.start()
        if args.install_hooks:
            from .hooks import HookInstaller
            installer = HookInstaller(args.host_root, args.listen)
            res = installer.install(args.hook_mode)
            print(f"hook mode: {res.mode} "
                  f"({len(res.installed)} files installed)", flush=True)
            if res.mode == "fanotify":
                # nothing on the host invokes us: run the in-process runc
                # fanotify watch so container tracking still works (ref:
                # entrypoint.sh fanotify hook mode → the daemon's own
                # watch, runcfanotify.go)
                from ..containers import with_fanotify_discovery
                from ..operators.operators import ensure_initialized
                with_fanotify_discovery()(
                    ensure_initialized("localmanager").cc)
        if args.kube_api:
            # IP→pod/service enrichment off the same apiserver
            # (ref: kubeipresolver.go:62-156 inventory cache)
            from ..operators.operators import get as get_operator
            from ..utils.k8s import KubeClient
            get_operator("kubeipresolver").use_kube_client(
                KubeClient(server=args.kube_api))
        if args.pod_manifest or args.kube_api:
            # pod-informer discovery feeding the localmanager collection
            # (ref: WithPodInformer wired in main.go's serve path)
            from ..containers import (
                file_pod_source, kube_api_pod_source, with_pod_informer,
            )
            from ..operators.operators import ensure_initialized
            lm = ensure_initialized("localmanager")
            src = (file_pod_source(args.pod_manifest) if args.pod_manifest
                   else kube_api_pod_source(args.kube_api,
                                            node_name=args.node_name))
            with_pod_informer(src, node_name=args.node_name,
                              interval=args.informer_interval)(lm.cc)
        print(f"ig-tpu-agent listening on {args.listen}", flush=True)
        stop = [False]

        def on_sig(signum, *_):
            if flight_path != "off":
                RECORDER.record_error("signal",
                                      f"agent stopping on signal {signum}")
                RECORDER.dump(flight_path)
            stop[0] = True
        signal.signal(signal.SIGTERM, on_sig)
        signal.signal(signal.SIGINT, on_sig)
        while not stop[0]:
            time.sleep(0.2)
    finally:
        # uninstall while still serving, then stop: containers created in
        # the grace window must not invoke hooks against a dead socket —
        # and stop unconditionally, else a failed informer/install leaves
        # non-daemon gRPC workers keeping a dead agent alive
        if watcher is not None:
            watcher.stop()
        if _agent.metrics_server is not None:
            _agent.metrics_server.stop()
        _agent.stop_checkpointer()
        # seal any armed recordings: a clean SIGTERM must not leave
        # unsealed journals for the torn-tail reader to account
        from ..capture import RECORDINGS
        RECORDINGS.stop_all()
        # same for history stores: close seals active window segments
        if compactor is not None:
            compactor.stop()
        from ..history import HISTORY
        HISTORY.close_all()
        if installer is not None:
            installer.uninstall()
        server.stop(grace=2.0)
    return 0


if __name__ == "__main__":
    sys.exit(main())
