"""Per-node agent + distributed control plane.

Reference architecture (SURVEY §2.3/§2.5): a per-node daemon
(pkg/gadgettracermanager) exposes gRPC services over unix sockets — the
legacy container hooks API (AddContainer/RemoveContainer/ReceiveStream) and
the modern GadgetManager (GetInfo + RunGadget bidirectional stream,
gadgettracermanager/api proto:121-140); the client runtime fans out one
stream per node and merges client-side.

TPU-native redesign: gRPC remains the control plane (catalog, params, run
lifecycle, logs) and a row/JSON event path for display; the *aggregation*
path ships fixed-size sketch summaries (or nothing at all when nodes share
a TPU slice — then the merge is a psum over ICI, parallel/cluster.py, and
the agent only coordinates epochs).
"""

from .stream import GadgetStream
from .service import AgentServer, serve
from .client import AgentClient

__all__ = ["GadgetStream", "AgentServer", "serve", "AgentClient"]
