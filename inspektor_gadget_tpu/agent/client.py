"""AgentClient: one node's gRPC connection.

Reference contract: pkg/runtime/grpc — dial (k8s-exec tunnel there, plain
grpc target here), GetCatalog with client-side cache fallback
(grpc-runtime.go:62-91), RunGadget stream with seq-gap detection
(:312-314) and a stop request + bounded result wait (:336-353).
"""

from __future__ import annotations

import json
import logging
import os
import queue
import threading
from pathlib import Path
from typing import Any, Callable, Iterator

import grpc

from . import wire

CONNECT_TIMEOUT = 30.0      # ref: grpc-runtime.go:45-52
RESULT_TIMEOUT = 30.0

CATALOG_CACHE = Path.home() / ".ig-tpu" / "catalog.json"

# the shared subscriber-option vocabulary lives in wire.py (one home for
# client, agent, and params layer): the client refuses a bad attach
# BEFORE it goes on the wire, the agent refuses it again server-side —
# loud both ways, silent nowhere
DROP_POLICIES = wire.DROP_POLICIES
PRIORITIES = wire.PRIORITIES
TIERS = wire.TIERS


def _validate_subscriber_opts(opts: dict) -> None:
    """Raise ValueError on malformed subscriber options (the params
    layer applies the same vocabulary to the runtime flags)."""
    unknown = set(opts) - {"id", "priority", "drop_policy", "queue",
                           "evict_after", "tier"}
    if unknown:
        raise ValueError(f"unknown subscriber option(s) {sorted(unknown)}")
    if opts.get("drop_policy") is not None \
            and opts["drop_policy"] not in DROP_POLICIES:
        raise ValueError(f"drop_policy must be one of {DROP_POLICIES}, "
                         f"got {opts['drop_policy']!r}")
    if opts.get("priority") is not None \
            and opts["priority"] not in PRIORITIES:
        raise ValueError(f"priority must be one of {PRIORITIES}, "
                         f"got {opts['priority']!r}")
    if opts.get("tier") is not None and opts["tier"] not in TIERS:
        raise ValueError(f"tier must be one of {TIERS}, "
                         f"got {opts['tier']!r}")
    if opts.get("queue") is not None and int(opts["queue"]) < 1:
        raise ValueError(f"subscriber queue bound must be >= 1, "
                         f"got {opts['queue']}")
    if opts.get("evict_after") is not None \
            and float(opts["evict_after"]) <= 0:
        raise ValueError(f"evict_after must be > 0, "
                         f"got {opts['evict_after']}")


class AgentClient:
    def __init__(self, target: str, node_name: str = "", dialer=None,
                 rpc_deadline: float | None = None):
        """dialer: how to reach the agent (default DirectDialer). An
        ExecTunnelDialer reaches agents with no routable address by
        tunneling over a subprocess's stdio — the reference's
        k8s-exec-dialer contract (k8s-exec-dialer.go:1-132).

        rpc_deadline bounds every unary RPC (catalog, dump_state,
        list/fetch, recording lifecycle): an unresponsive agent fails the
        call with DEADLINE_EXCEEDED instead of wedging the caller.
        Default $IG_RPC_DEADLINE or 30s."""
        from .dialer import DirectDialer
        self.target = target
        self.node_name = node_name or target
        self.dialer = dialer or DirectDialer()
        if rpc_deadline is None:
            rpc_deadline = float(os.environ.get("IG_RPC_DEADLINE",
                                                CONNECT_TIMEOUT))
        if rpc_deadline <= 0:
            raise ValueError(f"rpc_deadline must be > 0, got {rpc_deadline}")
        self.rpc_deadline = rpc_deadline
        self.channel = self.dialer.dial(target)

    def close(self) -> None:
        self.channel.close()
        self.dialer.close()

    def reconnect(self) -> None:
        """Tear down the (possibly wedged) channel and dial a fresh one.
        The supervisor calls this between retry attempts so a channel
        stuck in TRANSIENT_FAILURE backoff doesn't slow the resume."""
        try:
            self.channel.close()
        except Exception as e:  # noqa: BLE001 — a dead channel may refuse close
            logging.getLogger("ig-tpu.client").debug(
                "channel close before redial failed: %r", e)
        self.channel = self.dialer.dial(self.target)

    # -- catalog ------------------------------------------------------------

    def get_catalog(self, use_cache_on_error: bool = True) -> dict:
        method = self.channel.unary_unary(
            "/igtpu.GadgetManager/GetCatalog",
            request_serializer=wire.identity_serializer,
            response_deserializer=wire.identity_deserializer,
        )
        try:
            reply = method(wire.encode_msg({}), timeout=self.rpc_deadline)
            header, _ = wire.decode_msg(reply)
            catalog = header["catalog"]
            try:  # cache for offline flag rendering (ref: catalog cache)
                CATALOG_CACHE.parent.mkdir(parents=True, exist_ok=True)
                CATALOG_CACHE.write_text(json.dumps(catalog))
            except OSError:
                pass
            return catalog
        except grpc.RpcError:
            if use_cache_on_error and CATALOG_CACHE.exists():
                return json.loads(CATALOG_CACHE.read_text())
            raise

    # -- run ----------------------------------------------------------------

    def run_gadget(
        self,
        category: str,
        name: str,
        params: dict[str, str] | None = None,
        *,
        timeout: float = 0.0,
        outputs: tuple[str, ...] = ("json",),
        on_json: Callable[[str, dict], None] | None = None,
        on_array: Callable[[str, list], None] | None = None,
        on_batch: Callable[[str, Any], None] | None = None,
        on_summary: Callable[[str, dict], None] | None = None,
        on_alert: Callable[[str, dict], None] | None = None,
        on_log: Callable[[str, int, str, dict], None] | None = None,
        on_message: Callable[[str, int, int], None] | None = None,
        on_window: Callable[[str, dict], None] | None = None,
        on_query: Callable[[str, dict, bytes], None] | None = None,
        stop_event: threading.Event | None = None,
        trace_ctx=None,
        run_id: str | None = None,
        resumable: bool = False,
        linger: float | None = None,
        ring: int | None = None,
        resume_from: int | None = None,
        share: bool = False,
        keepalive: float | None = None,
        max_subscribers: int | None = None,
        sub_budget: int | None = None,
        subscriber: dict | None = None,
        attach_to: str | None = None,
        sub_id: str | None = None,
    ) -> dict:
        """Blocking run; returns {'result': bytes|None, 'error': str|None,
        'gaps': int, 'dropped': int, 'records': int, 'last_seq': int,
        'resume': dict|None, 'unknown_run': bool, 'gadget_error': bool}.
        trace_ctx (a telemetry SpanContext) rides the run request as a
        traceparent so the agent's server spans join the caller's trace;
        on_log receives (node, severity, msg, header) — the header
        carries the remote run_id/trace_id; on_message(node, seq, type)
        fires for every seq-bearing stream message (supervision's
        record-cadence hook).

        resumable=True asks the agent to keep the run alive for `linger`
        seconds after a disconnect, retaining the last `ring` messages
        for replay; resume_from re-attaches to an existing run (run_id
        required) and receives messages after that seq — the agent
        answers with an EV_RESUME_ACK (surfaced as out['resume']) or
        `unknown_run` when it has nothing to resume (it restarted).

        Shared runs: share=True makes the run a first-class shared
        resource — the first request for a (gadget, params, outputs) key
        starts the gadget, compatible requests attach as SUBSCRIBERS to
        the same pipeline (out['attach'] carries the typed ack; a
        refused admission surfaces out['attach_refused']). `subscriber`
        ({id, priority, drop_policy, queue, evict_after, tier}) shapes
        this consumer's delivery: a slow subscriber drops its OWN
        records (EV_DROP_NOTICE → out['sub_drops']) and one stalled past
        evict_after is EVICTED (out['evicted'] + labeled terminal
        record) — never stalling the gadget or its peers. keepalive /
        max_subscribers / sub_budget are run-level (first request wins).
        attach_to joins an existing run by run_id WITHOUT a run request
        (tier='summary' subscribers get harvest summaries, alerts, and
        sealed-window announcements only — on_window receives the
        announcements). resume with sub_id re-attaches one subscriber."""
        method = self.channel.stream_stream(
            "/igtpu.GadgetManager/RunGadget",
            request_serializer=wire.identity_serializer,
            response_deserializer=wire.identity_deserializer,
        )
        ctrl_q: queue.Queue = queue.Queue()
        sub_opts = dict(subscriber or {})
        if sub_opts:
            _validate_subscriber_opts(sub_opts)
        if resume_from is not None:
            if not run_id:
                raise ValueError("resume_from requires run_id")
            resume_msg = {"run_id": run_id, "last_seq": int(resume_from)}
            if sub_id:
                resume_msg["sub_id"] = sub_id
            first_msg = {"resume": resume_msg}
        elif attach_to is not None:
            first_msg = {"attach": {**sub_opts, "run_id": attach_to}}
        else:
            run: dict = {
                "category": category, "name": name, "params": params or {},
                "timeout": timeout, "output": list(outputs),
            }
            if run_id:
                run["run_id"] = run_id
            if resumable:
                run["resumable"] = True
                if linger is not None:
                    run["linger"] = float(linger)
                if ring is not None:
                    run["ring"] = int(ring)
            if share:
                run["share"] = True
            if keepalive is not None:
                run["keepalive"] = float(keepalive)
            if max_subscribers is not None:
                run["max_subscribers"] = int(max_subscribers)
            if sub_budget is not None:
                run["sub_budget"] = int(sub_budget)
            if sub_opts:
                run["subscriber"] = sub_opts
            first_msg = {"run": run}
        ctrl_q.put(wire.encode_msg(wire.inject_span(first_msg, trace_ctx)))

        def requests() -> Iterator[bytes]:
            while True:
                item = ctrl_q.get()
                if item is None:
                    return
                yield item

        if stop_event is not None:
            def stopper():
                stop_event.wait()
                ctrl_q.put(wire.encode_msg({"stop": True}))
                ctrl_q.put(None)
            threading.Thread(target=stopper, daemon=True).start()

        out = {"result": None, "error": None, "gaps": 0, "dropped": 0,
               "records": 0, "last_seq": int(resume_from or 0),
               "resume": None, "unknown_run": False, "gadget_error": False,
               "attach": None, "attach_refused": "", "sub_drops": 0,
               "drop_notices": 0, "evicted": False}
        # resuming: seq numbering continues from what we already hold, so
        # gap detection spans the outage — a replay ring that overflowed
        # shows up as a gap here (and as `missed` in the resume ack)
        last_seq = int(resume_from or 0)
        call = method(requests(), timeout=None if timeout == 0 else timeout + RESULT_TIMEOUT)
        try:
            for msg in call:
                header, payload = wire.decode_msg(msg)
                seq = header.get("seq", 0)
                if seq and last_seq and seq != last_seq + 1:
                    out["gaps"] += seq - last_seq - 1  # ref: seq-gap :312-314
                if seq:
                    last_seq = seq
                    out["last_seq"] = seq
                    out["records"] += 1
                    if on_message is not None:
                        on_message(self.node_name, seq,
                                   header.get("type", 0))
                t = header.get("type", 0)
                sev = t >> wire.EV_LOG_SHIFT
                if sev:
                    if on_log:
                        on_log(self.node_name, sev,
                               payload.decode("utf-8", "replace"), header)
                elif t == wire.EV_PAYLOAD_JSON:
                    if on_json:
                        on_json(self.node_name, json.loads(payload))
                elif t == wire.EV_PAYLOAD_ARRAY:
                    if on_array:
                        on_array(self.node_name, json.loads(payload))
                elif t == wire.EV_BATCH_NPZ:
                    if on_batch:
                        on_batch(self.node_name, wire.decode_batch(payload))
                elif t == wire.EV_SUMMARY:
                    if on_summary:
                        on_summary(self.node_name, wire.decode_summary(header, payload))
                elif t == wire.EV_ALERT:
                    if on_alert:
                        on_alert(self.node_name, header.get("alert", {}))
                elif t == wire.EV_RESULT:
                    out["error"] = header.get("error")
                    out["result"] = payload or None
                    if header.get("error"):
                        out["gadget_error"] = True
                elif t == wire.EV_CONTROL_ACK:
                    out["dropped"] = header.get("dropped", 0)
                elif t == wire.EV_RESUME_ACK:
                    out["resume"] = header.get("resume", {})
                elif t == wire.EV_ATTACH_ACK:
                    a = header.get("attach", {})
                    out["attach"] = a
                    if a.get("refused"):
                        # typed admission refusal: deterministic — the
                        # supervisor must surface it, never retry it
                        out["attach_refused"] = a.get("reason", "refused")
                        out["error"] = header.get("error") or \
                            f"attach refused ({out['attach_refused']})"
                        out["gadget_error"] = True
                elif t == wire.EV_DROP_NOTICE:
                    # this subscriber's own overload accounting: its
                    # bounded queue dropped records (policy/class in the
                    # header); evicted=True is the labeled terminal
                    # record of a stalled subscriber
                    out["drop_notices"] += 1
                    out["sub_drops"] = max(
                        out["sub_drops"], int(header.get("drops_total", 0)))
                    if header.get("evicted"):
                        out["evicted"] = True
                        out["error"] = (f"subscriber evicted: "
                                        f"{header.get('reason', '?')}")
                        out["gadget_error"] = True
                elif t == wire.EV_WINDOW:
                    if on_window:
                        on_window(self.node_name,
                                  header.get("window", {}))
                elif t == wire.EV_QUERY:
                    # standing-query materialized answer: header is the
                    # query identity + coverage, payload the packed
                    # sealed window (QueryWindows reply frame shape)
                    if on_query:
                        on_query(self.node_name,
                                 header.get("query", {}), payload)
                elif "error" in header:
                    out["error"] = header["error"]
                    if header.get("unknown_run"):
                        out["unknown_run"] = True
                    else:
                        # run-setup refusals (unknown gadget, bad params)
                        # are deterministic — retrying replays the failure
                        out["gadget_error"] = True
        except grpc.RpcError as e:
            if e.code() != grpc.StatusCode.CANCELLED:
                out["error"] = f"{e.code().name}: {e.details()}"
        finally:
            ctrl_q.put(None)
        return out

    # -- container hooks (ref: hooks/oci/main.go) ---------------------------

    def add_container(self, container: dict,
                      timeout: float = CONNECT_TIMEOUT) -> dict:
        method = self.channel.unary_unary(
            "/igtpu.GadgetManager/AddContainer",
            request_serializer=wire.identity_serializer,
            response_deserializer=wire.identity_deserializer,
        )
        h, _ = wire.decode_msg(method(wire.encode_msg({"container": container}),
                                      timeout=timeout))
        return h

    def remove_container(self, container_id: str,
                         timeout: float = CONNECT_TIMEOUT) -> dict:
        method = self.channel.unary_unary(
            "/igtpu.GadgetManager/RemoveContainer",
            request_serializer=wire.identity_serializer,
            response_deserializer=wire.identity_deserializer,
        )
        h, _ = wire.decode_msg(method(
            wire.encode_msg({"container": {"id": container_id}}),
            timeout=timeout))
        return h

    def dump_state(self, max_spans: int = 0) -> dict:
        method = self.channel.unary_unary(
            "/igtpu.GadgetManager/DumpState",
            request_serializer=wire.identity_serializer,
            response_deserializer=wire.identity_deserializer,
        )
        req = {"max_spans": max_spans} if max_spans else {}
        h, _ = wire.decode_msg(method(wire.encode_msg(req),
                                      timeout=self.rpc_deadline))
        return h

    def shared_runs(self, gadget: str = "") -> list[dict]:
        """Live shared runs on this node (DumpState `runs` rows filtered
        to shared + not-done), the attach-by-key discovery surface: each
        row carries run_id, subscriber rows, queue depths, drops, and
        keepalive state."""
        rows = self.dump_state().get("runs") or []
        return [r for r in rows
                if r.get("shared") and not r.get("done")
                and (not gadget or r.get("gadget") == gadget)]

    def flight_record(self, max_spans: int = 0) -> dict:
        """The agent's flight recorder (recent spans/logs/errors/facts),
        served via DumpState. max_spans>512 pulls deeper into the span
        ring (trace export wants all of it)."""
        return self.dump_state(max_spans=max_spans).get("flight_record", {})

    # -- capture/recording lifecycle (capture/) -----------------------------

    def start_recording(self, recording_id: str, *,
                        opts: dict | None = None) -> dict:
        """Journals land under the AGENT's capture area (--capture-dir)
        — the same base ListRecordings/FetchSegment resolve against."""
        return self._unary("StartRecording",
                           {"recording_id": recording_id,
                            "opts": opts or {}})

    def stop_recording(self, recording_id: str) -> dict:
        return self._unary("StopRecording", {"recording_id": recording_id})

    def list_recordings(self, recording_id: str = "") -> dict:
        return self._unary("ListRecordings",
                           {"recording_id": recording_id})

    def fetch_file(self, recording_id: str, rel_path: str,
                   dest_path: str, *, chunk: int = 1 << 20) -> int:
        """Download one recording file in chunks; returns bytes written.
        The chunked unary keeps every message under gRPC's 4 MiB cap."""
        method = self.channel.unary_unary(
            "/igtpu.GadgetManager/FetchSegment",
            request_serializer=wire.identity_serializer,
            response_deserializer=wire.identity_deserializer,
        )
        os.makedirs(os.path.dirname(dest_path) or ".", exist_ok=True)
        written = 0
        with open(dest_path, "wb") as f:
            offset = 0
            while True:
                reply = method(wire.encode_msg(
                    {"recording_id": recording_id, "file": rel_path,
                     "offset": offset, "limit": chunk}),
                    timeout=self.rpc_deadline)
                h, payload = wire.decode_msg(reply)
                if h.get("error"):
                    raise RuntimeError(h["error"])
                f.write(payload)
                written += len(payload)
                offset += len(payload)
                if h.get("eof") or not payload:
                    break
        return written

    def fetch_recording(self, recording_id: str, dest_dir: str) -> dict:
        """Pull every file of one recording into dest_dir (mirroring the
        node's relative layout); returns {files, bytes}. The server's
        listing is NOT trusted: an absolute or ..-escaping relative path
        from a compromised agent must not write outside dest_dir
        (zip-slip), so such entries are refused loudly."""
        listing = self.list_recordings(recording_id)
        files = listing.get("files") or []
        total = 0
        for item in files:
            rel = os.path.normpath(item["path"])
            if os.path.isabs(rel) or rel.startswith(".."):
                raise RuntimeError(
                    f"{self.node_name}: refusing listed path {item['path']!r}"
                    " escaping the bundle directory")
            total += self.fetch_file(recording_id, item["path"],
                                     os.path.join(dest_dir, rel))
        return {"files": len(files), "bytes": total}

    # -- sketch-history plane (history/) ------------------------------------

    def list_windows(self, *, gadget: str = "",
                     start_ts: float | None = None,
                     end_ts: float | None = None,
                     start_seq: int | None = None,
                     end_seq: int | None = None,
                     key: str | None = None) -> dict:
        """Header rows of this node's sealed windows overlapping the
        range/slice — the cheap pruning pass before fetch_windows."""
        return self._unary("ListWindows", {
            "gadget": gadget, "start_ts": start_ts, "end_ts": end_ts,
            "start_seq": start_seq, "end_seq": end_seq, "key": key})

    def fetch_windows(self, *, gadget: str = "",
                      start_ts: float | None = None,
                      end_ts: float | None = None,
                      start_seq: int | None = None,
                      end_seq: int | None = None,
                      key: str | None = None,
                      chunk_bytes: int = 1 << 20
                      ) -> tuple[list[tuple[dict, bytes]], list[dict]]:
        """Pull every matching window's (header, payload) frame in
        chunks under the gRPC cap; returns (frames, torn-tail losses).
        A truncated reply tail is dropped-and-accounted client-side with
        the same rule a torn segment gets."""
        from ..history import unpack_frames
        method = self.channel.unary_unary(
            "/igtpu.GadgetManager/FetchWindows",
            request_serializer=wire.identity_serializer,
            response_deserializer=wire.identity_deserializer,
        )
        frames: list[tuple[dict, bytes]] = []
        losses: list[dict] = []
        offset = 0
        while True:
            reply = method(wire.encode_msg({
                "gadget": gadget, "start_ts": start_ts, "end_ts": end_ts,
                "start_seq": start_seq, "end_seq": end_seq, "key": key,
                "offset": offset, "max_bytes": chunk_bytes}),
                timeout=self.rpc_deadline)
            h, payload = wire.decode_msg(reply)
            if h.get("error"):
                raise RuntimeError(h["error"])
            got, dropped = unpack_frames(payload)
            frames.extend(got)
            losses.extend(h.get("losses") or [])
            if dropped:
                losses.append({"store": "<fetch>", "segment": "<reply>",
                               "offset": offset, "dropped_bytes": dropped,
                               "reason": "truncated fetch reply"})
            if h.get("eof") or not h.get("count"):
                return frames, losses
            offset = int(h.get("next_offset", offset + len(got)))

    def query_windows(self, *, gadget: str = "",
                      start_ts: float | None = None,
                      end_ts: float | None = None,
                      start_seq: int | None = None,
                      end_seq: int | None = None,
                      key: str | None = None) -> dict:
        """Query pushdown: the agent folds the range/slice query
        node-side and returns ONE merged window plus accounting —
        fleet-query wire cost O(nodes), not O(windows). Raises
        grpc.RpcError UNIMPLEMENTED against pre-pushdown agents (the
        runtime falls back to list+fetch per node) and RuntimeError on
        a typed server-side refusal."""
        from ..history import decode_frames, unpack_frames
        method = self.channel.unary_unary(
            "/igtpu.GadgetManager/QueryWindows",
            request_serializer=wire.identity_serializer,
            response_deserializer=wire.identity_deserializer,
        )
        reply = method(wire.encode_msg({
            "gadget": gadget, "start_ts": start_ts, "end_ts": end_ts,
            "start_seq": start_seq, "end_seq": end_seq, "key": key}),
            timeout=self.rpc_deadline)
        h, payload = wire.decode_msg(reply)
        if h.get("error"):
            raise RuntimeError(h["error"])
        frames, dropped_bytes = unpack_frames(payload)
        wins = decode_frames(frames)
        losses = list(h.get("losses") or [])
        if dropped_bytes:
            losses.append({"store": "<query>", "segment": "<reply>",
                           "offset": 0, "dropped_bytes": dropped_bytes,
                           "reason": "truncated query reply"})
        return {
            "node": h.get("node", self.node_name),
            "window": wins[0] if wins else None,
            "folded": int(h.get("folded", 0)),
            "levels": {int(k): int(v)
                       for k, v in (h.get("levels") or {}).items()},
            "torn": int(h.get("torn", 0)),
            "dropped": list(h.get("dropped") or []),
            "losses": losses,
        }

    # -- Trace resources (ref: utils/trace.go:340-848 CreateTrace/
    #    SetTraceOperation/getTraceListFromOptions, over agent RPCs) --------

    def _unary(self, name: str, msg: dict) -> dict:
        method = self.channel.unary_unary(
            f"/igtpu.GadgetManager/{name}",
            request_serializer=wire.identity_serializer,
            response_deserializer=wire.identity_deserializer,
        )
        # per-RPC deadline: an unresponsive agent fails this call with
        # DEADLINE_EXCEEDED instead of hanging dump_state/list_windows
        h, _ = wire.decode_msg(method(wire.encode_msg(msg),
                                      timeout=self.rpc_deadline))
        if h.get("error"):
            raise RuntimeError(h["error"])
        return h

    def apply_trace(self, doc: dict) -> dict:
        return self._unary("ApplyTrace", {"trace": doc})["trace"]

    def get_trace(self, name: str) -> dict:
        return self._unary("GetTrace", {"name": name})["trace"]

    def list_traces(self) -> list[dict]:
        return self._unary("ListTraces", {})["traces"]

    def delete_trace(self, name: str) -> bool:
        return self._unary("DeleteTrace", {"name": name})["deleted"]
