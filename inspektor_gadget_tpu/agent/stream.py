"""GadgetStream: pubsub with replay history and loss markers.

Reference contract: pkg/gadgettracermanager/stream/stream.go — 100-line
replay history for late subscribers (:22), 250-cap subscriber channels
(:23), an EventLost marker when a subscriber overruns, publish never
blocks.
"""

from __future__ import annotations

import collections
import threading
from typing import Any

HISTORY_SIZE = 100      # ref: stream.go:22
SUBSCRIBER_CAP = 250    # ref: stream.go:23

LOST_MARKER = {"__lost__": True}


class _Subscriber:
    def __init__(self):
        self.queue: collections.deque = collections.deque()
        self.cond = threading.Condition()
        self.lost = False
        self.closed = False


class GadgetStream:
    def __init__(self):
        self._mu = threading.Lock()
        self._history: collections.deque = collections.deque(maxlen=HISTORY_SIZE)
        self._subs: dict[object, _Subscriber] = {}

    def publish(self, item: Any) -> None:
        with self._mu:
            self._history.append(item)
            subs = list(self._subs.values())
        for s in subs:
            with s.cond:
                if len(s.queue) >= SUBSCRIBER_CAP:
                    if not s.lost:
                        s.lost = True
                        s.queue.append(LOST_MARKER)
                    continue
                s.lost = False
                s.queue.append(item)
                s.cond.notify()

    def subscribe(self, key: object, replay: bool = True) -> _Subscriber:
        s = _Subscriber()
        with self._mu:
            if replay:
                s.queue.extend(self._history)
            self._subs[key] = s
        return s

    def unsubscribe(self, key: object) -> None:
        with self._mu:
            s = self._subs.pop(key, None)
        if s is not None:
            with s.cond:
                s.closed = True
                s.cond.notify()

    @staticmethod
    def next_item(sub: _Subscriber, timeout: float = 1.0):
        """Blocking pop; returns (item, ok)."""
        with sub.cond:
            if not sub.queue and not sub.closed:
                sub.cond.wait(timeout)
            if sub.queue:
                return sub.queue.popleft(), True
            return None, not sub.closed
