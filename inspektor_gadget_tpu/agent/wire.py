"""Wire format for the agent's gRPC streams.

Reference contract: GadgetEvent{type, seq, payload} with log severity
encoded in the high bits of type (gadgettracermanager/api proto:114-119;
decode grpc-runtime.go:326-328); params travel as a flat string map
(service.go:112-131). Messages here are JSON headers with optional binary
numpy payloads — schema-stable, dependency-light, and the gRPC methods use
identity (de)serializers so the transport stays grpc-framed bytes. An
ig.proto documenting the service shapes lives alongside for protoc users.
"""

from __future__ import annotations

import io
import json

import numpy as np

from ..telemetry.tracing import TRACEPARENT, SpanContext, parse_traceparent

# event types (ref: api consts; log severity rides the high bits)
EV_PAYLOAD_JSON = 1     # one event row as JSON
EV_PAYLOAD_ARRAY = 2    # array-of-rows JSON (interval gadgets)
EV_RESULT = 3           # final result bytes (RunWithResult)
EV_BATCH_NPZ = 4        # columnar EventBatch as npz
EV_SUMMARY = 5          # sketch summary (mergeable state digest)
EV_CONTROL_ACK = 6
EV_ALERT = 7            # alert lifecycle transition (alerts/engine.py)
EV_JOURNAL_MARK = 8     # capture-journal lifecycle marker (capture/)
EV_WINDOW = 9           # sealed sketch window (history/) — mergeable state
EV_RESUME_ACK = 10      # resume re-attach acknowledgment (carries the
                        # replay start + how many seqs the ring lost)
EV_DROP_NOTICE = 11     # per-subscriber overload accounting: a slow
                        # consumer's own queue dropped records (policy/
                        # class/count in the header; evicted=True is the
                        # labeled terminal record of a stalled subscriber)
EV_ATTACH_ACK = 12      # shared-run attach acknowledgment OR typed
                        # admission refusal (attach.refused + reason)
EV_QUERY = 13           # standing-query materialized answer (queries/):
                        # header carries the query identity + coverage
                        # digest, payload is one packed sealed window —
                        # the same frame shape as a QueryWindows reply
EV_LOG_SHIFT = 16       # type >> 16 = severity when nonzero

# The one registry every EV_* wire id must appear in. Stream decoding,
# the capture journal, and docs all key off these numbers, so a silent
# collision (two planes hand-assigning the same id) corrupts decode far
# from the assignment; tools/check_wire_ids.py (tier-1 via
# tests/test_wire_ids.py) fails the suite on an unregistered constant, a
# duplicate id, or an id that would collide with the severity bits.
WIRE_EVENT_IDS: dict[str, int] = {
    "EV_PAYLOAD_JSON": EV_PAYLOAD_JSON,
    "EV_PAYLOAD_ARRAY": EV_PAYLOAD_ARRAY,
    "EV_RESULT": EV_RESULT,
    "EV_BATCH_NPZ": EV_BATCH_NPZ,
    "EV_SUMMARY": EV_SUMMARY,
    "EV_CONTROL_ACK": EV_CONTROL_ACK,
    "EV_ALERT": EV_ALERT,
    "EV_JOURNAL_MARK": EV_JOURNAL_MARK,
    "EV_WINDOW": EV_WINDOW,
    "EV_RESUME_ACK": EV_RESUME_ACK,
    "EV_DROP_NOTICE": EV_DROP_NOTICE,
    "EV_ATTACH_ACK": EV_ATTACH_ACK,
    "EV_QUERY": EV_QUERY,
}


# Fleet aggregation tier (fleet/): the accounting header an aggregator
# republishes alongside its ONE merged summary window. The proto mirror
# is the FleetAggregate message in ig.proto — tests/test_proto.py pins
# field-name drift between these constants and the proto text.
FLEET_AGGREGATE_SCHEMA = "ig-tpu/fleet-aggregate/v1"
FLEET_AGGREGATE_FIELDS = ("schema", "aggregator", "gadget", "children",
                          "folded", "missing", "skipped", "approx",
                          "digest")


# Shared-run subscriber vocabulary — ONE home for the values the client
# validates before the wire, the agent re-validates server-side, and the
# runtime params layer offers as one_of choices (three call sites, one
# truth; like the EV_* registry above).
DROP_POLICIES = ("drop-oldest", "drop-newest")
PRIORITIES = ("high", "normal", "low")
TIERS = ("full", "summary")


def encode_msg(header: dict, payload: bytes = b"") -> bytes:
    h = json.dumps(header, separators=(",", ":")).encode()
    return len(h).to_bytes(4, "big") + h + payload


def decode_msg(data: bytes) -> tuple[dict, bytes]:
    n = int.from_bytes(data[:4], "big")
    header = json.loads(data[4:4 + n])
    return header, data[4 + n:]


def encode_batch(batch) -> bytes:
    buf = io.BytesIO()
    arrays = dict(batch.cols)
    if batch.comm is not None:
        arrays["__comm__"] = batch.comm
    np.savez(buf, **{k: v[: batch.count] if v.ndim == 1 else v[: batch.count]
                     for k, v in arrays.items()})
    return buf.getvalue()


def decode_batch(payload: bytes):
    from ..sources.batch import EventBatch

    with np.load(io.BytesIO(payload)) as z:
        cols = {k: z[k] for k in z.files if k != "__comm__"}
        comm = z["__comm__"] if "__comm__" in z.files else None
    n = len(next(iter(cols.values()))) if cols else 0
    return EventBatch(cols=cols, count=n, comm=comm)


def encode_summary(summary) -> tuple[dict, bytes]:
    """SketchSummary → (header, payload)."""
    header = {
        "events": summary.events, "drops": summary.drops,
        "distinct": summary.distinct, "entropy": summary.entropy_bits,
        "epoch": summary.epoch,
        "anomaly": summary.anomaly,
        "names": {str(k): v for k, v in (summary.names or {}).items()},
    }
    # invertible-plane / candidate-ring accounting (ISSUE 15): only when
    # present, so pre-plane consumers see byte-identical headers. The
    # decoded lists are CAPPED here (count-descending, so the cap keeps
    # the heaviest): the in-process summary carries the full recovery
    # for the local alert engine, but a JSON header must stay bounded —
    # summary.inv.recovered reports the uncapped total either way.
    if getattr(summary, "approx", False):
        header["approx"] = True
    for field, cap in (("decoded", 256), ("decoded_only", 64)):
        rows = getattr(summary, field, None)
        if rows:
            header[field] = [[int(k), int(c)] for k, c in rows[:cap]]
    # the quantile block (ISSUE 16), pipeline health block (ISSUE 18)
    # and accuracy block (ISSUE 19) ride the same only-when-present
    # rule: plane-off summaries keep byte-identical headers
    for field in ("inv", "classes", "quantiles", "pipeline", "accuracy"):
        v = getattr(summary, field, None)
        if v is not None:
            header[field] = v
    arr = np.asarray(summary.heavy_hitters, dtype=np.int64)
    buf = io.BytesIO()
    np.save(buf, arr)
    return header, buf.getvalue()


def decode_summary(header: dict, payload: bytes) -> dict:
    hh = np.load(io.BytesIO(payload)) if payload else np.zeros((0, 2), np.int64)
    out = dict(header)
    out["heavy_hitters"] = [(int(k), int(c)) for k, c in hh]
    out["names"] = {int(k): v for k, v in (header.get("names") or {}).items()}
    return out


def inject_span(header: dict, ctx: SpanContext | None) -> dict:
    """Carry span context in message metadata (the W3C traceparent string
    rides the JSON header, so agent and client stitch one trace)."""
    if ctx is not None:
        header[TRACEPARENT] = ctx.to_traceparent()
    return header


def extract_span(header: dict) -> SpanContext | None:
    return parse_traceparent(header.get(TRACEPARENT, ""))


def identity_serializer(b: bytes) -> bytes:
    return b


def identity_deserializer(b: bytes) -> bytes:
    return b
