"""Device meshes, shardings, and cluster-wide sketch merges.

The reference's distributed plane is a goroutine-per-pod gRPC fan-out with
client-side JSON merging (pkg/runtime/grpc/grpc-runtime.go:185-239,
pkg/snapshotcombiner). The TPU-native redesign keeps gRPC as control plane
only (see agent/) and moves aggregation onto the mesh: every node's sketch
state lives on its chips; a cluster merge is one psum/pmax/all_gather over
the 'node' axis riding ICI — the snapshotcombiner's ticker merge becomes an
epoch-keyed all-reduce.
"""

from .mesh import make_mesh, node_axis, MeshSpec
from .ring import ring_psum, ring_psum_chunked
from .cluster import (
    cluster_sketch_step,
    cluster_merge,
    make_cluster_step,
    ClusterState,
    cluster_init,
)
from .flash_attention import flash_attention
from .moe import make_ep_moe, moe_apply, moe_init, moe_pspecs
from .pipeline import (
    make_pp_forward,
    make_pp_train_step,
    pp_block_init,
    pp_pspecs,
    pp_reference,
)

__all__ = [
    "make_mesh", "node_axis", "MeshSpec",
    "cluster_sketch_step", "cluster_merge", "make_cluster_step",
    "ClusterState", "cluster_init",
    "ring_psum", "ring_psum_chunked",
    "flash_attention",
    "make_ep_moe", "moe_apply", "moe_init", "moe_pspecs",
    "make_pp_forward", "make_pp_train_step", "pp_block_init", "pp_pspecs",
    "pp_reference",
]
