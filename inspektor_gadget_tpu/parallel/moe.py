"""Expert parallelism: Switch-style top-1 routed mixture-of-experts.

The reference has no model parallelism of any kind (SURVEY.md §2.5 — its
distributed dimension is per-node fan-out only); expert parallelism is part
of this build's first-class TPU distributed plane, next to DP×TP
(parallel/cluster.py), sequence parallelism (models/seqmodel.py) and
pipeline parallelism (parallel/pipeline.py). The scorer families stay
small, but the routing/dispatch machinery is the real thing: the same
all_to_all schedule a production MoE uses, so the framework scales scorer
capacity by adding experts without growing per-token FLOPs.

TPU-first choices:
- Dense dispatch/combine einsums (one-hot matmuls) instead of scatter —
  static shapes, MXU-friendly, no data-dependent control flow under jit.
- Top-1 (Switch) routing with a fixed per-expert capacity; over-capacity
  tokens get a zero expert output (the caller's residual connection, as in
  models/seqmodel.py blocks, is what carries them through) — the standard
  bounded-memory trade, matching the framework's drop-accounting
  philosophy (every hop bounded, losses observable: the router reports a
  drop fraction).
- Expert parallelism via two `lax.all_to_all` hops over an 'expert' mesh
  axis inside shard_map: tokens→owning expert, expert outputs→token owner.
  With E experts over n ranks each device holds E/n expert FFNs; dispatch
  rides ICI.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from .compat import shard_map

EXPERT_AXIS = "expert"


def moe_init(key, n_experts: int, d_model: int, d_ff: int) -> dict:
    """Router + stacked expert FFN params (experts on the leading axis, so
    sharding over the expert mesh axis is a single P('expert') spec)."""
    kg, k1, k2 = jax.random.split(key, 3)
    s1 = (2.0 / (d_model + d_ff)) ** 0.5
    return {
        "gate": jax.random.normal(kg, (d_model, n_experts), jnp.float32) * 0.02,
        "w1": jax.random.normal(k1, (n_experts, d_model, d_ff), jnp.float32) * s1,
        "b1": jnp.zeros((n_experts, d_ff), jnp.float32),
        "w2": jax.random.normal(k2, (n_experts, d_ff, d_model), jnp.float32) * s1,
        "b2": jnp.zeros((n_experts, d_model), jnp.float32),
    }


def moe_pspecs(expert_axis: str = EXPERT_AXIS) -> dict:
    """PartitionSpecs matching moe_init: experts sharded, router replicated."""
    return {
        "gate": P(),
        "w1": P(expert_axis), "b1": P(expert_axis),
        "w2": P(expert_axis), "b2": P(expert_axis),
    }


def _route(x: jnp.ndarray, gate_w: jnp.ndarray, capacity: int):
    """Top-1 routing → (dispatch [T,E,C], combine [T,E,C], aux) with static
    shapes. aux = (load-balance loss term, dropped-token fraction)."""
    t = x.shape[0]
    logits = x.astype(jnp.float32) @ gate_w
    probs = jax.nn.softmax(logits, axis=-1)              # [T, E]
    expert = jnp.argmax(probs, axis=-1)                  # [T]
    n_e = gate_w.shape[1]
    onehot = jax.nn.one_hot(expert, n_e, dtype=jnp.float32)
    gate = (probs * onehot).sum(-1)                      # chosen-expert prob
    # position of each token within its expert's capacity (exclusive cumsum)
    pos = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot    # [T, E]
    slot = pos.sum(-1)                                   # [T]
    keep = (slot < capacity).astype(jnp.float32)
    dispatch = (onehot * keep[:, None])[:, :, None] * jax.nn.one_hot(
        jnp.clip(slot, 0, capacity - 1).astype(jnp.int32), capacity,
        dtype=jnp.float32)[:, None, :]                   # [T, E, C]
    combine = dispatch * gate[:, None, None]
    # Switch load-balance loss: E * sum_e(frac_tokens_e * mean_prob_e)
    frac = onehot.mean(0)
    balance = n_e * jnp.sum(frac * probs.mean(0))
    dropped = 1.0 - keep.mean() if t else jnp.float32(0.0)
    return dispatch, combine, (balance, dropped)


def _expert_ffn(w1, b1, w2, b2, h):
    """Apply stacked expert FFNs: h [E, C, d] → [E, C, d] (bf16 matmuls)."""
    z = jnp.einsum("ecd,edf->ecf", h.astype(jnp.bfloat16),
                   w1.astype(jnp.bfloat16)) + b1[:, None, :].astype(jnp.bfloat16)
    z = jax.nn.gelu(z)
    out = jnp.einsum("ecf,efd->ecd", z, w2.astype(jnp.bfloat16))
    return out.astype(jnp.float32) + b2[:, None, :]


def moe_apply(params: dict, x: jnp.ndarray,
              capacity_factor: float = 2.0) -> tuple[jnp.ndarray, tuple]:
    """Single-device reference MoE: x [T, d] → ([T, d], aux). All experts
    local; the EP path must produce identical outputs (tests enforce it),
    so both are the same moe_ff code path."""
    return moe_ff(params, x, capacity_factor)


def moe_ff(params: dict, x: jnp.ndarray, capacity_factor: float = 2.0,
           axis_name: str | None = None,
           axis_size: int = 1) -> tuple[jnp.ndarray, tuple]:
    """Routed FF usable as a drop-in for a dense FF block: x [T, d] →
    (y [T, d], (balance_loss, drop_frac)). With `axis_name` set (inside
    shard_map over the expert axis), experts are sharded and dispatch takes
    the two all_to_all hops; otherwise all experts are local. This is the
    building block models embed (models/seqmodel.py MoE layers);
    make_ep_moe wraps it as a standalone jitted fn."""
    t = x.shape[0]
    n_experts = params["gate"].shape[1]  # gate is replicated, global width
    if axis_name and params["w1"].shape[0] * axis_size != n_experts:
        raise ValueError(
            f"expert shard {params['w1'].shape[0]} × axis {axis_size} != "
            f"gate width {n_experts}")
    capacity = max(1, int(t / n_experts * capacity_factor))
    dispatch, combine, (bal, drop) = _route(x, params["gate"], capacity)
    h = jnp.einsum("tec,td->ecd", dispatch, x.astype(jnp.float32))
    if axis_name:
        h = lax.all_to_all(h, axis_name, split_axis=0, concat_axis=1,
                           tiled=True)
    out = _expert_ffn(params["w1"], params["b1"], params["w2"], params["b2"], h)
    if axis_name:
        out = lax.all_to_all(out, axis_name, split_axis=1, concat_axis=0,
                             tiled=True)
    y = jnp.einsum("tec,ecd->td", combine, out).astype(x.dtype)
    return y, (bal, drop)


def make_ep_moe(mesh: Mesh, n_experts: int, capacity_factor: float = 2.0,
                axis: str = EXPERT_AXIS):
    """Build the expert-parallel MoE: tokens [T, d] sharded over `axis`,
    experts sharded over `axis` (E/n per device), two all_to_all hops.

    Returns a jitted fn(params, x) → (y, (balance_loss, drop_frac)).
    """
    n = mesh.shape[axis]
    if n_experts % n:
        raise ValueError(f"n_experts={n_experts} not divisible by mesh axis {n}")

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(moe_pspecs(axis), P(axis)),
        out_specs=(P(axis), (P(), P())))
    def ep(params, x):
        # local dispatch over ALL experts, then two all_to_all hops:
        # tokens → owning expert shard, expert outputs → token owner
        y, (bal, drop) = moe_ff(params, x, capacity_factor,
                                axis_name=axis, axis_size=n)
        return y, (lax.pmean(bal, axis), lax.pmean(drop, axis))

    return jax.jit(ep)
