"""Ring collectives for sketch merging.

Why a ring here: `jax.lax.psum` is the right default for the ≤ ~1.3MB
sketch bundles (XLA already emits near-optimal all-reduces on ICI). But
cross-slice merges of *wide* CMS tables (depth × 2^20+ counters for
long-horizon retention) are bandwidth-bound on DCN, and a hand-rolled ring
lets the runtime overlap each hop with the next ingest step and chunk the
table so per-hop messages stay under the DCN sweet spot — the same reason
ring attention passes KV blocks hop-by-hop instead of all-gathering them.

ring_psum: N-1 ppermute hops, each adding the neighbor's shard-sum;
ring_psum_chunked: the bidirectional variant splitting the table into
per-hop chunks (reduce-scatter + all-gather schedule).
Both are exact (integer tables: addition is associative; order-safe).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .compat import axis_size


def ring_psum(x: jnp.ndarray, axis_name: str) -> jnp.ndarray:
    """All-reduce via N-1 ring hops of the full tensor (exact for ints)."""
    n = axis_size(axis_name)
    if n == 1:
        return x
    idx = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def body(i, carry):
        acc, buf = carry
        buf = jax.lax.ppermute(buf, axis_name, perm)
        return acc + buf, buf

    acc, _ = jax.lax.fori_loop(0, n - 1, body, (x, x))
    del idx
    return acc


def ring_psum_chunked(x: jnp.ndarray, axis_name: str) -> jnp.ndarray:
    """Reduce-scatter + all-gather ring schedule (bandwidth-optimal
    2(N-1)/N of the naive ring): the tensor is split into N chunks; each
    rank reduces one chunk over N-1 hops, then the reduced chunks ride
    N-1 more hops to every rank."""
    n = axis_size(axis_name)
    if n == 1:
        return x
    orig_shape = x.shape
    flat = x.reshape(-1)
    pad = (-flat.shape[0]) % n
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros(pad, flat.dtype)])
    chunks = flat.reshape(n, -1)
    rank = jax.lax.axis_index(axis_name)
    send_next = [(i, (i + 1) % n) for i in range(n)]

    # reduce-scatter: after step s, rank r holds the partial sum of chunk
    # (r - s) mod n accumulated over s+1 ranks
    def rs_body(s, state):
        chunks, send = state
        recv = jax.lax.ppermute(send, axis_name, send_next)
        idx = (rank - s - 1) % n
        updated = jax.lax.dynamic_index_in_dim(chunks, idx, 0, keepdims=False) + recv
        chunks = jax.lax.dynamic_update_index_in_dim(chunks, updated, idx, 0)
        return chunks, updated

    first_send = jax.lax.dynamic_index_in_dim(chunks, rank % n, 0, keepdims=False)
    chunks, _ = jax.lax.fori_loop(0, n - 1, rs_body, (chunks, first_send))

    # all-gather: circulate each fully reduced chunk
    def ag_body(s, state):
        chunks, send = state
        recv = jax.lax.ppermute(send, axis_name, send_next)
        idx = (rank - s) % n
        chunks = jax.lax.dynamic_update_index_in_dim(chunks, recv, idx, 0)
        return chunks, recv

    own = jax.lax.dynamic_index_in_dim(chunks, (rank + 1) % n, 0, keepdims=False)
    chunks, _ = jax.lax.fori_loop(0, n - 1, ag_body, (chunks, own))

    out = chunks.reshape(-1)
    if pad:
        out = out[:-pad]
    return out.reshape(orig_shape)
