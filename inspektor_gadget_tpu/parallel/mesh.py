"""Mesh construction and sharding specs.

Axes:
  node   data-parallel over event streams (one shard per node/chip group) —
         sketch updates are per-node, merges are collectives over this axis.
  model  tensor-parallel axis for the autoencoder matmuls (used when the
         slice has more chips than event streams).

Within one pod slice both axes ride ICI; across slices the node axis maps
onto DCN — mirroring the reference's node-local (unix socket) vs cluster
(kubectl-exec gRPC) split (pkg/gadgettracermanager main.go:66-67 vs
pkg/runtime/grpc/k8s-exec-dialer.go).
"""

from __future__ import annotations

import dataclasses

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

NODE_AXIS = "node"
MODEL_AXIS = "model"


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    n_nodes: int
    n_model: int = 1


def node_axis() -> str:
    return NODE_AXIS


def make_mesh(n_nodes: int | None = None, n_model: int = 1,
              devices=None) -> Mesh:
    """Build a (node, model) mesh. Defaults: all local devices on the node
    axis. On a real multi-host slice, pass jax.devices() after
    jax.distributed.initialize()."""
    if devices is None:
        devices = jax.devices()
    if n_nodes is None:
        n_nodes = len(devices) // n_model
    devs = np.asarray(devices[: n_nodes * n_model]).reshape(n_nodes, n_model)
    return Mesh(devs, (NODE_AXIS, MODEL_AXIS))


def ingest_mesh(chips: int, devices=None) -> Mesh:
    """The (node)-only mesh the sharded ingest plane runs on (ISSUE 14):
    `chips` local devices, one SketchBundle replica each, collectives only
    at harvest. A 1-chip mesh is legal for the perf harness's scale-point
    sweep; the operator short-circuits chips=1 to the unsharded path."""
    if devices is None:
        devices = jax.local_devices()
    if chips < 1:
        raise ValueError(f"chips must be >= 1, got {chips}")
    if chips > len(devices):
        raise ValueError(
            f"chips={chips} exceeds the {len(devices)} local device(s)")
    return Mesh(np.asarray(devices[:chips]), (NODE_AXIS,))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Event batches shard over the node axis (leading dim = node)."""
    return NamedSharding(mesh, P(NODE_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
