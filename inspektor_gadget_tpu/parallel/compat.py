"""jax version-drift shims for the distributed plane (ISSUE 14 satellite).

The parallel/ and models/ SPMD code was written against the newer jax
surface (`jax.shard_map` with `check_vma`, `pltpu.CompilerParams`,
`lax.pcast`); the pinned 0.4.x toolchain still spells those
`jax.experimental.shard_map.shard_map` with `check_rep`,
`pltpu.TPUCompilerParams`, and has no varying-manual-axes cast at all.
This module is the ONE place that drift is resolved — every call site
imports from here, so the next jax bump is a one-file change (and the
29 tier-1 failures the drift caused stay cured on both sides of it).

Resolution is at call time, not import time, so a monkeypatched or
upgraded jax is picked up without reloading this module.
"""

from __future__ import annotations

import inspect
from typing import Any, Callable

import jax
from jax import lax


def _resolve_shard_map() -> Callable:
    impl = getattr(jax, "shard_map", None)
    if impl is None:  # 0.4.x spelling
        from jax.experimental.shard_map import shard_map as impl
    return impl


def shard_map(f: Callable | None = None, *, mesh, in_specs, out_specs,
              check_vma: bool | None = None, **kw) -> Callable:
    """`jax.shard_map` on every supported jax.

    Accepts the NEW keyword surface (`check_vma`); on a jax whose
    shard_map still takes `check_rep`, the flag is translated (they mean
    the same thing: verify the per-shard replication/varying typing).
    Usable directly or as a decorator factory (``functools.partial``
    style), mirroring both existing call-site shapes.
    """
    impl = _resolve_shard_map()
    kwargs: dict[str, Any] = dict(mesh=mesh, in_specs=in_specs,
                                  out_specs=out_specs, **kw)
    if check_vma is not None:
        params = inspect.signature(impl).parameters
        key = "check_vma" if "check_vma" in params else "check_rep"
        kwargs[key] = check_vma
    if f is None:
        return lambda g: impl(g, **kwargs)
    return impl(f, **kwargs)


def axis_size(axis_name) -> int:
    """``lax.axis_size`` where it exists; on 0.4.x the size comes off the
    tracing axis frame (``jax.core.axis_frame``) — a static Python int in
    both spellings, so ring schedules can build their permutation lists."""
    fn = getattr(lax, "axis_size", None)
    if fn is not None:
        return fn(axis_name)
    frame = jax.core.axis_frame(axis_name)
    return frame if isinstance(frame, int) else frame.size


def pcast_varying(x, axis_names):
    """``lax.pcast(x, axis_names, to="varying")`` where it exists,
    ``lax.pvary`` on the intermediate spelling, identity on 0.4.x —
    where shard_map has no varying-manual-axes type system, every
    per-shard value already IS varying and the cast has nothing to do."""
    pcast = getattr(lax, "pcast", None)
    if pcast is not None:
        return pcast(x, axis_names, to="varying")
    pvary = getattr(lax, "pvary", None)
    if pvary is not None:
        return pvary(x, axis_names)
    return x


def tpu_compiler_params(**kw):
    """``pltpu.CompilerParams`` across the TPUCompilerParams rename."""
    from jax.experimental.pallas import tpu as pltpu
    cls = getattr(pltpu, "CompilerParams", None)
    if cls is None:
        cls = pltpu.TPUCompilerParams
    return cls(**kw)


def drift_notes() -> dict[str, str]:
    """What this jax calls each shimmed symbol — doctor/debug surface and
    the version note skipped tests cite."""
    impl = _resolve_shard_map()
    params = inspect.signature(impl).parameters
    from jax.experimental.pallas import tpu as pltpu
    return {
        "jax": jax.__version__,
        "shard_map": ("jax.shard_map" if getattr(jax, "shard_map", None)
                      else "jax.experimental.shard_map.shard_map"),
        "check_flag": "check_vma" if "check_vma" in params else "check_rep",
        "compiler_params": ("CompilerParams"
                            if hasattr(pltpu, "CompilerParams")
                            else "TPUCompilerParams"),
        "varying_cast": ("lax.pcast" if hasattr(lax, "pcast")
                         else "lax.pvary" if hasattr(lax, "pvary")
                         else "none (pre-vma jax: no-op)"),
    }
