"""Sequence/context parallelism: ring attention + Ulysses all-to-all.

The reference has no long-context machinery (SURVEY.md §5: its unbounded-
stream analogue is snapshot/TTL merging). In the TPU build, long *event
sequences* are first-class model inputs: the sequence anomaly scorer
(models/seqmodel.py) attends over windows of 10^4-10^6 syscall tokens per
container, far beyond one chip's activation memory. This module provides
the three standard TPU-native attention layouts for that regime:

- ``blockwise_attention``: single-chip flash-style streaming softmax over
  KV chunks via ``lax.scan`` — O(T·chunk) memory instead of O(T^2).
- ``ring_attention``: sequence sharded over a mesh axis; KV blocks rotate
  hop-by-hop with ``lax.ppermute`` while each device accumulates its
  queries' partial softmax (running max / denominator / numerator). The
  per-hop message is one KV block, so the collective rides ICI neighbor
  links and overlaps with the block matmul.
- ``ulysses_attention``: ``lax.all_to_all`` re-shards sequence ↔ heads so
  each device runs *full* attention for a head subset — cheaper than the
  ring when heads ≥ devices and T fits after the head split.

All accumulate in float32 regardless of input dtype (bf16 inputs stay bf16
through the matmuls feeding the MXU; the softmax state is f32).

Inner functions are written for use under ``jax.shard_map`` with a mesh
axis carrying the sequence dimension; `make_*` helpers wrap them.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .compat import axis_size, pcast_varying, shard_map

_NEG = jnp.float32(-1e30)  # finite "-inf": keeps exp() exact-zero without NaNs


def _block_update(q, k, v, o, m, l, pos_q, pos_k, causal: bool, scale):
    """One streaming-softmax accumulation step.

    q: [B,H,Tq,D]; k,v: [B,H,Tk,D]; o: [B,H,Tq,D] f32; m,l: [B,H,Tq] f32.
    Returns updated (o, m, l). Fully-masked rows are harmless: scores are
    -1e30, so the incoming block contributes exp(-1e30 - m_new) = 0.
    """
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        mask = pos_q[:, None] >= pos_k[None, :]
        s = jnp.where(mask, s, _NEG)
    m_new = jnp.maximum(m, s.max(axis=-1))
    p = jnp.exp(s - m_new[..., None])
    corr = jnp.exp(m - m_new)
    l_new = l * corr + p.sum(axis=-1)
    o_new = o * corr[..., None] + jnp.einsum(
        "bhqk,bhkd->bhqd", p, v.astype(jnp.float32),
        preferred_element_type=jnp.float32)
    return o_new, m_new, l_new


def _finish(o, l, dtype):
    return (o / jnp.maximum(l, 1e-30)[..., None]).astype(dtype)


def full_attention(q, k, v, causal: bool = True,
                   scale: Optional[float] = None) -> jnp.ndarray:
    """Materialized-scores reference. Layout [B, T, H, D]."""
    scale = scale or q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        t = q.shape[1]
        s = jnp.where(jnp.tril(jnp.ones((t, t), bool)), s, _NEG)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32),
                      preferred_element_type=jnp.float32).astype(q.dtype)


def blockwise_attention(q, k, v, causal: bool = True, chunk: int = 128,
                        scale: Optional[float] = None) -> jnp.ndarray:
    """Single-device flash-style attention: lax.scan over KV chunks.

    Layout [B, T, H, D]; T must be divisible by `chunk`. Memory is
    O(B·H·T·D + B·H·T·chunk) — the full [T,T] score matrix never exists.
    """
    b, t, h, d = q.shape
    scale = scale or d ** -0.5
    qt = q.transpose(0, 2, 1, 3)  # [B,H,T,D]
    kt = k.transpose(0, 2, 1, 3).reshape(b, h, t // chunk, chunk, d)
    vt = v.transpose(0, 2, 1, 3).reshape(b, h, t // chunk, chunk, d)
    pos_q = jnp.arange(t)
    o0 = jnp.zeros((b, h, t, d), jnp.float32)
    m0 = jnp.full((b, h, t), _NEG)
    l0 = jnp.zeros((b, h, t), jnp.float32)

    def step(carry, inp):
        o, m, l = carry
        (kc, vc, ci) = inp
        pos_k = ci * chunk + jnp.arange(chunk)
        o, m, l = _block_update(qt, kc, vc, o, m, l, pos_q, pos_k,
                                causal, scale)
        return (o, m, l), None

    (o, _, l), _ = lax.scan(
        step, (o0, m0, l0),
        (kt.transpose(2, 0, 1, 3, 4), vt.transpose(2, 0, 1, 3, 4),
         jnp.arange(t // chunk)))
    return _finish(o, l, q.dtype).transpose(0, 2, 1, 3)


def ring_attention(q, k, v, axis_name: str, causal: bool = True,
                   scale: Optional[float] = None) -> jnp.ndarray:
    """Ring attention over a sharded sequence (call under shard_map).

    q/k/v hold this device's sequence shard, layout [B, T_local, H, D];
    global position of local row i is ``rank * T_local + i``. KV blocks
    rotate rank → rank+1 each hop (N hops total); queries never move.
    Exact: produces bitwise the softmax of the full sequence up to f32
    accumulation order.
    """
    n = axis_size(axis_name)
    rank = lax.axis_index(axis_name)
    b, t, h, d = q.shape
    scale = scale or d ** -0.5
    qt = q.transpose(0, 2, 1, 3)
    pos_q = rank * t + jnp.arange(t)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def body(s, carry):
        o, m, l, kb, vb = carry
        src = (rank - s) % n  # which rank's block we currently hold
        pos_k = src * t + jnp.arange(t)
        o, m, l = _block_update(qt, kb, vb, o, m, l, pos_q, pos_k,
                                causal, scale)
        kb = lax.ppermute(kb, axis_name, perm)
        vb = lax.ppermute(vb, axis_name, perm)
        return o, m, l, kb, vb

    # accumulators start replicated but the loop makes them device-varying;
    # pvary tells shard_map's vma type system up front
    vary = lambda x: pcast_varying(x, (axis_name,))
    o0 = vary(jnp.zeros((b, h, t, d), jnp.float32))
    m0 = vary(jnp.full((b, h, t), _NEG))
    l0 = vary(jnp.zeros((b, h, t), jnp.float32))
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    o, _, l, _, _ = lax.fori_loop(0, n, body, (o0, m0, l0, kt, vt))
    return _finish(o, l, q.dtype).transpose(0, 2, 1, 3)


def ulysses_attention(q, k, v, axis_name: str, causal: bool = True,
                      scale: Optional[float] = None) -> jnp.ndarray:
    """All-to-all (DeepSpeed-Ulysses style) sequence parallelism.

    Under shard_map with sequence sharded [B, T_local, H, D]: one
    all_to_all re-shards to [B, T_global, H_local, D], full (flash-free)
    attention runs per local head subset, and a second all_to_all restores
    sequence sharding. H must be divisible by the axis size. Two
    all-to-alls move 2·B·T_local·H·D elements — less than the ring's
    rotating KV when heads are plentiful and N is small.
    """
    h = q.shape[2]
    n = axis_size(axis_name)
    assert h % n == 0, f"heads {h} not divisible by axis size {n}"
    a2a = functools.partial(lax.all_to_all, axis_name=axis_name,
                            split_axis=2, concat_axis=1, tiled=True)
    qg, kg, vg = a2a(q), a2a(k), a2a(v)  # [B, T_glob, H_loc, D]
    og = full_attention(qg, kg, vg, causal=causal, scale=scale)
    return lax.all_to_all(og, axis_name=axis_name, split_axis=1,
                          concat_axis=2, tiled=True)


def make_ring_attention(mesh: Mesh, axis: str = "seq", causal: bool = True,
                        impl: str = "ring"):
    """Wrap the sharded attention for direct [B, T, H, D] arrays: shards T
    over `axis`, runs the chosen impl, returns the same layout."""
    inner = {"ring": ring_attention, "ulysses": ulysses_attention}[impl]

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(None, axis), P(None, axis), P(None, axis)),
        out_specs=P(None, axis))
    def fn(q, k, v):
        return inner(q, k, v, axis, causal=causal)

    return jax.jit(fn)
