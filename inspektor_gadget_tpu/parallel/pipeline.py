"""Pipeline parallelism: GPipe microbatch schedule over a 'stage' mesh axis.

The reference has no model parallelism (SURVEY.md §2.5); this completes the
framework's distributed plane (dp/tp/sp/ep/pp) so deep scorer stacks can be
sliced layer-wise across chips when a model no longer fits (or batches are
latency-bound) on one.

Design — idiomatic XLA, no host control flow:
- The model is S identical residual blocks; params are stacked on a leading
  stage axis and sharded P('stage'), so each device holds exactly its
  block(s). Layer-stacking + scan is the standard JAX pipelining shape.
- Inside shard_map, a single `lax.scan` runs S + M - 1 ticks (M =
  microbatches). Each tick: stage 0 injects the next microbatch, every
  stage applies its block, then one `lax.ppermute` hop shifts activations
  to the next stage — the classic bubble-fill/drain schedule with static
  shapes throughout.
- The last stage accumulates outputs; a masked psum broadcasts the result
  (tiny shapes here; a production variant would reduce_scatter).
- The whole schedule is differentiable: `make_pp_train_step` grads through
  the scan; each stage ends up with grads only for its own (sharded) block
  params, while the replicated head is trained outside shard_map.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from .compat import pcast_varying, shard_map

STAGE_AXIS = "stage"


def pp_block_init(key, n_stages: int, d_model: int, d_ff: int) -> dict:
    """S stacked residual MLP blocks: leading axis = pipeline stage."""
    k1, k2 = jax.random.split(key)
    s = (2.0 / (d_model + d_ff)) ** 0.5
    return {
        "w1": jax.random.normal(k1, (n_stages, d_model, d_ff), jnp.float32) * s,
        "b1": jnp.zeros((n_stages, d_ff), jnp.float32),
        "w2": jax.random.normal(k2, (n_stages, d_ff, d_model), jnp.float32) * s,
        "b2": jnp.zeros((n_stages, d_model), jnp.float32),
    }


def pp_pspecs(axis: str = STAGE_AXIS) -> dict:
    return {"w1": P(axis), "b1": P(axis), "w2": P(axis), "b2": P(axis)}


def _block(p, x):
    """One residual MLP block; p carries a leading local-stage axis of 1."""
    w1, b1 = p["w1"][0], p["b1"][0]
    w2, b2 = p["w2"][0], p["b2"][0]
    h = jax.nn.gelu(x.astype(jnp.bfloat16) @ w1.astype(jnp.bfloat16) + b1.astype(jnp.bfloat16))
    return x + (h @ w2.astype(jnp.bfloat16)).astype(jnp.float32) + b2


def pp_reference(params: dict, x: jnp.ndarray) -> jnp.ndarray:
    """Sequential single-device forward (ground truth for the pipeline)."""
    def body(h, p):
        return _block(jax.tree.map(lambda a: a[None], p), h), None
    out, _ = lax.scan(body, x, params)
    return out


def make_pp_forward(mesh: Mesh, axis: str = STAGE_AXIS):
    """Pipelined forward: x [M, mb, d] (microbatches, replicated in),
    result [M, mb, d] (replicated out)."""
    s = mesh.shape[axis]
    perm = [(i, (i + 1) % s) for i in range(s)]

    # check_vma=False: the scan carry's varying-type bookkeeping differs
    # between the 0.4 check_rep checker and the new vma one; the schedule
    # itself is checked by the numerics tests (pp_forward == sequential)
    @functools.partial(shard_map, mesh=mesh,
                       in_specs=(pp_pspecs(axis), P()), out_specs=P(),
                       check_vma=False)
    def fwd(params, x):
        stage = lax.axis_index(axis)
        m, mb, d = x.shape

        def tick(carry, t):
            act, outbuf = carry
            inj = lax.dynamic_index_in_dim(x, jnp.clip(t, 0, m - 1), 0,
                                           keepdims=False)
            act = jnp.where(stage == 0, inj, act)
            out = _block(params, act)
            oidx = t - (s - 1)
            write = (stage == s - 1) & (oidx >= 0)
            outbuf = lax.dynamic_update_index_in_dim(
                outbuf,
                jnp.where(write, out, lax.dynamic_index_in_dim(
                    outbuf, jnp.clip(oidx, 0, m - 1), 0, keepdims=False)),
                jnp.clip(oidx, 0, m - 1), 0)
            act = lax.ppermute(out, axis, perm)
            return (act, outbuf), None

        init = jax.tree.map(
            lambda a: pcast_varying(a, (axis,)),
            (jnp.zeros((mb, d), jnp.float32), jnp.zeros_like(x)))
        (_, outbuf), _ = lax.scan(tick, init, jnp.arange(m + s - 1))
        # only the last stage holds real outputs; broadcast via masked psum
        return lax.psum(jnp.where(stage == s - 1, outbuf, 0.0), axis)

    return jax.jit(fwd)


def make_pp_train_step(mesh: Mesh, lr: float = 1e-3, axis: str = STAGE_AXIS):
    """Jitted pipeline-parallel train step on (stacked blocks + replicated
    linear head): MSE to targets, SGD update. Grads for block params stay
    stage-local (they are sharded); the head runs on the replicated
    pipeline output outside shard_map, so its grad needs no reduction."""
    fwd_inner = make_pp_forward(mesh, axis)

    def loss_fn(params, head, x, y):
        h = fwd_inner(params, x)
        pred = h @ head
        return jnp.mean((pred - y) ** 2)

    @jax.jit
    def step(params, head, x, y):
        loss, (gp, gh) = jax.value_and_grad(loss_fn, argnums=(0, 1))(
            params, head, x, y)
        params = jax.tree.map(lambda p, g: p - lr * g, params, gp)
        head = head - lr * gh
        return params, head, loss

    return step
