"""Multi-host distributed initialization + mesh topology.

Reference: the distributed backend is per-node gRPC fan-out over
kubectl-exec tunnels (SURVEY §2.5). The TPU-native backend is JAX
collectives: jax.distributed.initialize joins every host's chips into one
global device set; meshes then span hosts, with the 'node' axis laid out so
its collectives ride ICI inside a pod slice and DCN only across slices
(make_multihost_mesh orders devices slice-major for exactly that reason).

Division of labor with the gRPC plane (agent/): gRPC = control (catalog,
run lifecycle, logs, row streams for display); XLA collectives = the
aggregation data plane (psum/pmax sketch merges, pmean grads). A cluster
where every node has TPU chips runs merges entirely over ICI/DCN; nodes
without chips fall back to gRPC sketch-summary streaming — same merge
semantics (sketches are mergeable either way).
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh

from .mesh import MODEL_AXIS, NODE_AXIS


def init_distributed(coordinator_address: str | None = None,
                     num_processes: int | None = None,
                     process_id: int | None = None) -> None:
    """Join the jax.distributed world (multi-host). No-op when single-host
    or already initialized."""
    if num_processes is None or num_processes <= 1:
        return
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except RuntimeError:
        pass  # already initialized


def make_multihost_mesh(n_model: int = 1) -> Mesh:
    """Global mesh over every process's devices, slice-major so the node
    axis's psum stays on ICI within a slice and crosses DCN once per slice
    pair (scaling-book layout recipe)."""
    devices = sorted(
        jax.devices(),
        key=lambda d: (getattr(d, "slice_index", 0) or 0, d.process_index, d.id),
    )
    n = len(devices) // n_model
    mesh_devices = np.asarray(devices[: n * n_model]).reshape(n, n_model)
    return Mesh(mesh_devices, (NODE_AXIS, MODEL_AXIS))


def local_node_index() -> int:
    return jax.process_index()


def world_size() -> int:
    return jax.process_count()
