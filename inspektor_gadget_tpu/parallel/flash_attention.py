"""Pallas TPU flash attention — the fused hot path for long windows.

The blockwise path (ring_attention.blockwise_attention) already avoids the
[T,T] score matrix, but XLA still round-trips each chunk's partial products
through HBM between scan steps. This kernel fuses the whole streaming
softmax into VMEM: scores, renormalization and the accumulator never leave
the core — the standard flash schedule mapped onto the MXU.

Schedule: grid (B·H, T/bq, T/bk) with the KV dimension 'arbitrary'
(sequential) so the (m, l, acc) scratch carries across KV steps; K/V
stream through VMEM one block per step, so VMEM use is O(block²) no matter
how long the window — T=64k compiles in the same footprint as T=2k. The
causal upper triangle costs nothing: masked-out KV blocks skip via pl.when.

Layout matches the rest of the attention plane: [B, T, H, D]. The wrapper
folds (B, H) into the grid, pads D to the 128-lane boundary and T to the
block size (zero-padding is exact: padded D contributes 0 to q·k, padded K
positions are masked, padded Q rows are sliced off).

Used as the `attn="flash"` backend of models/seqmodel.py; under sequence
parallelism it composes with the Ulysses all-to-all (head-sharded full
windows). On non-TPU backends it runs in Pallas interpret mode, so tests
exercise the same code path everywhere.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .compat import tpu_compiler_params

_NEG = -1e30  # finite "-inf": keeps exp() exact-zero without NaNs


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr, *,
                  block: int, t_real: int, causal: bool, scale: float,
                  n_kv: int):
    qi = pl.program_id(1)
    kj = pl.program_id(2)

    @pl.when(kj == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    active = (kj <= qi) if causal else (kj >= 0)

    @pl.when(active)
    def _update():
        q = q_ref[0].astype(jnp.float32) * scale       # (bq, d)
        k = k_ref[0].astype(jnp.float32)               # (bk, d)
        v = v_ref[0].astype(jnp.float32)
        s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
        pos_q = qi * block + lax.broadcasted_iota(jnp.int32, s.shape, 0)
        pos_k = kj * block + lax.broadcasted_iota(jnp.int32, s.shape, 1)
        keep = pos_k < t_real                          # T padding
        if causal:
            keep = keep & (pos_q >= pos_k)
        s = jnp.where(keep, s, _NEG)
        m_prev = m_scr[:, 0]
        m_new = jnp.maximum(m_prev, s.max(axis=-1))
        p = jnp.exp(s - m_new[:, None])
        corr = jnp.exp(m_prev - m_new)
        m_scr[...] = m_new[:, None]
        l_scr[...] = (l_scr[:, 0] * corr + p.sum(axis=-1))[:, None]
        acc_scr[...] = acc_scr[...] * corr[:, None] + lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(kj == n_kv - 1)
    def _finish():
        o_ref[0] = (acc_scr[...]
                    / jnp.maximum(l_scr[:, 0], 1e-30)[:, None]
                    ).astype(o_ref.dtype)


def flash_attention(q, k, v, causal: bool = True, block: int = 128,
                    scale: Optional[float] = None,
                    interpret: Optional[bool] = None) -> jnp.ndarray:
    """Fused attention, layout [B, T, H, D] (matches full/blockwise/ring).
    Any T and D: both are padded to hardware boundaries internally.

    Differentiable: the forward pass is the fused Pallas kernel; the
    backward pass recomputes attention per query block under
    jax.checkpoint (see _recompute_ref) — the standard flash training
    trade: scores are recomputed at transpose time, never stored, so
    backward memory is O(chunk·T), not O(T²)."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _flash(q, k, v, causal, block, scale, interpret)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, causal: bool, block: int, scale: float,
           interpret: bool) -> jnp.ndarray:
    b, t, h, d = q.shape
    t_pad = -t % block
    d_pad = -d % 128

    def fold(x):
        x = x.transpose(0, 2, 1, 3).reshape(b * h, t, d)
        return jnp.pad(x, ((0, 0), (0, t_pad), (0, d_pad)))

    qf, kf, vf = fold(q), fold(k), fold(v)
    tp, dp = t + t_pad, d + d_pad
    n_kv = tp // block
    kernel = functools.partial(_flash_kernel, block=block, t_real=t,
                               causal=causal, scale=scale, n_kv=n_kv)
    out = pl.pallas_call(
        kernel,
        grid=(b * h, tp // block, n_kv),
        in_specs=[
            pl.BlockSpec((1, block, dp), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, block, dp), lambda bh, i, j: (bh, j, 0)),
            pl.BlockSpec((1, block, dp), lambda bh, i, j: (bh, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block, dp), lambda bh, i, j: (bh, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, tp, dp), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block, 1), jnp.float32),    # running max m
            pltpu.VMEM((block, 1), jnp.float32),    # running denom l
            pltpu.VMEM((block, dp), jnp.float32),   # output accumulator
        ],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qf, kf, vf)
    return out[:, :t, :d].reshape(b, h, t, d).transpose(0, 2, 1, 3)


def _recompute_ref(q, k, v, causal: bool, scale: float, chunk: int = 128):
    """Differentiable recompute target for the backward pass: attention
    computed independently per query block under jax.checkpoint, mapped
    with lax.map. Memory truly stays sub-quadratic in the backward:
    checkpoint keeps each block's [chunk, T] scores out of the residuals
    (recomputed at transpose time), and lax.map's transpose ACCUMULATES
    dk/dv across blocks in a carry — nothing is stacked per step, unlike
    vjp through a scan-with-carried-output (which would stack O(T²/chunk)
    residuals). Any T: q is padded to the chunk boundary; padded rows are
    sliced off so their cotangents are zero."""
    b, t, h, d = q.shape
    t_pad = -t % chunk
    nb = (t + t_pad) // chunk
    qt = jnp.pad(q, ((0, 0), (0, t_pad), (0, 0), (0, 0))
                 ).transpose(0, 2, 1, 3)                     # [B,H,Tp,D]
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    qblocks = qt.reshape(b, h, nb, chunk, d).transpose(2, 0, 1, 3, 4)
    pos_k = jnp.arange(t)

    @jax.checkpoint
    def body(args):
        qblk, i = args                                       # [B,H,chunk,D]
        s = jnp.einsum("bhqd,bhkd->bhqk", qblk, kt,
                       preferred_element_type=jnp.float32) * scale
        if causal:
            pos_q = i * chunk + jnp.arange(chunk)
            s = jnp.where(pos_q[:, None] >= pos_k[None, :], s, _NEG)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bhqk,bhkd->bhqd", p, vt.astype(jnp.float32),
                          preferred_element_type=jnp.float32)

    out = lax.map(body, (qblocks, jnp.arange(nb)))           # [nb,B,H,chunk,D]
    out = out.transpose(1, 2, 0, 3, 4).reshape(b, h, t + t_pad, d)
    return out[:, :, :t].transpose(0, 2, 1, 3).astype(q.dtype)


def _flash_fwd(q, k, v, causal, block, scale, interpret):
    return _flash(q, k, v, causal, block, scale, interpret), (q, k, v)


def _flash_bwd(causal, block, scale, interpret, res, g):
    q, k, v = res
    _, vjp = jax.vjp(lambda q_, k_, v_: _recompute_ref(q_, k_, v_, causal,
                                                       scale), q, k, v)
    return vjp(g)


_flash.defvjp(_flash_fwd, _flash_bwd)
