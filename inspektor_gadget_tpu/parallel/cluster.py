"""Cluster-wide sketch pipeline: per-node updates + collective merges.

This is the distributed heart of the framework — the TPU equivalent of the
reference's fan-out/merge runtime (pkg/runtime/grpc/grpc-runtime.go:185-239
merging one JSON stream per node; pkg/snapshotcombiner's TTL ticker merge).

Design: each mesh 'node' shard holds its own SketchBundle (sketch arrays are
*sharded* over the node axis — state lives where events land). One jitted
`cluster_step` under shard_map:
  1. absorbs that node's event batch into its local bundle,
  2. trains the shared autoencoder data-parallel (pmean grads),
  3. computes the *merged* cluster view (psum CMS/entropy, pmax HLL,
     all_gather+rerank top-k) — returned as a replicated summary without
     ever moving raw events off-node.

The merged view is recomputed per harvest tick, not per batch — matching the
reference's interval semantics (snapshotcombiner ticker) while keeping the
hot path collective-free.
"""

from __future__ import annotations

import flax.struct
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.autoencoder import (
    AnomalyScorer,
    ae_param_pspecs,
    ae_train_step,
    ae_train_step_tp,
    normalize_counts,
)
from ..ops.countmin import cms_psum
from ..ops.entropy import entropy_psum
from ..ops.hll import hll_pmax
from ..ops.invertible import inv_psum
from ..ops.quantiles import dd_psum
from ..ops.sketches import SketchBundle, bundle_init, bundle_update
from ..ops.topk import topk_gather_merge
from .compat import shard_map
from .mesh import MODEL_AXIS, NODE_AXIS


@flax.struct.dataclass
class ClusterState:
    """Per-node bundles (sharded over 'node') + replicated scorer."""

    bundle: SketchBundle
    scorer: AnomalyScorer


def scorer_pspecs(scorer: AnomalyScorer, model_axis: str = MODEL_AXIS):
    """PartitionSpec tree for the scorer: Megatron row/col sharding on the
    params and matching sharding on Adam's mu/nu (same inner structure)."""
    pp = ae_param_pspecs(model_axis)

    def for_path(path, _leaf):
        keys = [k.key for k in path
                if isinstance(k, jax.tree_util.DictKey)]
        for layer in ("enc1", "enc2", "dec1", "dec2"):
            if layer in keys:
                return pp[layer]["w" if "w" in keys else "b"]
        return P()

    return AnomalyScorer(
        params=jax.tree_util.tree_map_with_path(for_path, scorer.params),
        opt_state=jax.tree_util.tree_map_with_path(for_path, scorer.opt_state),
        steps=P(),
        config=scorer.config,
    )


def cluster_init(mesh: Mesh, scorer: AnomalyScorer, **bundle_kw) -> ClusterState:
    """Materialize state with the right shardings: bundle arrays get a
    leading node-axis dim (one bundle per node); the scorer replicates on a
    1-D mesh and tensor-shards over the 'model' axis on a 2-D mesh."""
    n = mesh.shape[NODE_AXIS]
    tp = mesh.shape.get(MODEL_AXIS, 1) > 1

    def stack(x):
        return jax.device_put(
            jnp.broadcast_to(x, (n,) + x.shape),
            NamedSharding(mesh, P(NODE_AXIS)),
        )

    bundle = jax.tree.map(stack, bundle_init(**bundle_kw))
    if tp:
        specs = scorer_pspecs(scorer)
        shardings = jax.tree.map(
            lambda s: NamedSharding(mesh, s), specs,
            is_leaf=lambda x: isinstance(x, P))
        scorer = jax.device_put(scorer, shardings)
    else:
        scorer = jax.device_put(scorer, NamedSharding(mesh, P()))
    return ClusterState(bundle=bundle, scorer=scorer)


def cluster_sketch_step(
    state: ClusterState,
    hh_keys: jnp.ndarray,      # (n_nodes, batch) uint32
    distinct_keys: jnp.ndarray,
    dist_keys: jnp.ndarray,
    mask: jnp.ndarray,         # (n_nodes, batch) bool
    ae_batch: jnp.ndarray,     # (n_nodes, rows, input_dim) float32 counts
    use_tp: bool = False,
) -> tuple[ClusterState, jnp.ndarray]:
    """Per-node shard body (runs under shard_map; leading node dim is 1)."""
    bundle = jax.tree.map(lambda x: x[0], state.bundle)
    bundle = bundle_update(bundle, hh_keys[0], distinct_keys[0], dist_keys[0], mask[0])
    x = normalize_counts(ae_batch[0])
    if use_tp:
        scorer, loss = ae_train_step_tp(
            state.scorer, x, dp_axis=NODE_AXIS, model_axis=MODEL_AXIS)
    else:
        scorer, loss = ae_train_step(state.scorer, x, axis_name=NODE_AXIS)
    bundle = jax.tree.map(lambda x: x[None], bundle)
    return ClusterState(bundle=bundle, scorer=scorer), loss


def cluster_merge(bundle: SketchBundle) -> SketchBundle:
    """Collective merge of per-node bundles into the cluster view (runs
    under shard_map over the node axis). CMS/entropy psum, HLL pmax, top-k
    all_gather + re-rank vs the merged CMS, invertible lanes psum (the
    whole point of the invertible plane: decode runs on THIS state),
    DDSketch quantile row psum (cluster-wide latency distribution)."""
    local = jax.tree.map(lambda x: x[0], bundle)
    cms = cms_psum(local.cms, NODE_AXIS)
    merged = SketchBundle(
        cms=cms,
        hll=hll_pmax(local.hll, NODE_AXIS),
        entropy=entropy_psum(local.entropy, NODE_AXIS),
        topk=topk_gather_merge(local.topk, cms, NODE_AXIS),
        events=jax.lax.psum(local.events, NODE_AXIS),
        drops=jax.lax.psum(local.drops, NODE_AXIS),
        inv=(inv_psum(local.inv, NODE_AXIS)
             if local.inv is not None else None),
        quantiles=(dd_psum(local.quantiles, NODE_AXIS)
                   if local.quantiles is not None else None),
    )
    return merged


def _specs_like(tree, spec):
    """PartitionSpec pytree with `spec` at every array leaf of `tree`."""
    return jax.tree.map(lambda _: spec, tree)


def make_cluster_step(mesh: Mesh, state: ClusterState):
    """Jitted SPMD pair: (step, merge).

    step(state, hh, distinct, dist, mask, ae_batch) -> (state, loss)
      per-node sketch update + DP autoencoder train; no cross-node
      collectives except the grad pmean.
    merge(bundle_sharded) -> replicated cluster SketchBundle
      the harvest-tick collective (snapshotcombiner analogue).
    """
    use_tp = mesh.shape.get(MODEL_AXIS, 1) > 1
    state_specs = ClusterState(
        bundle=_specs_like(state.bundle, P(NODE_AXIS)),
        scorer=(scorer_pspecs(state.scorer) if use_tp
                else _specs_like(state.scorer, P())),
    )
    batch_spec = P(NODE_AXIS)

    import functools
    step = jax.jit(
        shard_map(
            functools.partial(cluster_sketch_step, use_tp=use_tp),
            mesh=mesh,
            in_specs=(state_specs, batch_spec, batch_spec, batch_spec,
                      batch_spec, batch_spec),
            out_specs=(state_specs, P()),
            check_vma=False,
        ),
        donate_argnums=0,
    )

    merge = jax.jit(
        shard_map(
            cluster_merge,
            mesh=mesh,
            in_specs=(_specs_like(state.bundle, P(NODE_AXIS)),),
            out_specs=_specs_like(jax.tree.map(lambda x: x[0], state.bundle), P()),
            check_vma=False,
        )
    )
    return step, merge
