"""Capture operator — tees the live pipeline into durable journals.

Rides every gadget run like tpusketch does, but stays a no-op until
armed: either the run itself sets `--capture-dir` (a run-scoped journal)
or a node-wide recording is active (RecordingManager — the agent's
StartRecording RPC / `ig-tpu record start`). When armed, the instance
appends to each destination journal:

- every decoded EventBatch that leaves the enrich chain (EV_BATCH_NPZ,
  the same npz framing the agent streams),
- every harvested sketch summary with its determinism digest
  (EV_SUMMARY — these double as the replay plane's harvest boundaries),
- every alert transition (EV_ALERT — the recorded ground truth the
  replay e2e compares against),
- lifecycle marks (EV_JOURNAL_MARK).

Replay runs set ctx.extra["replay"]; the operator refuses to re-record
them (a replay teeing into an active recording would recurse the
journal into itself).
"""

from __future__ import annotations

from typing import Any

from ..agent import wire
from ..gadgets.context import GadgetContext
from ..gadgets.interface import GadgetDesc
from ..params import ParamDesc, ParamDescs, Params, TypeHint
from ..utils.logger import get_logger
from .journal import (
    DEFAULT_RETENTION_BYTES,
    DEFAULT_SEGMENT_AGE,
    DEFAULT_SEGMENT_BYTES,
    JournalWriter,
    build_manifest,
    summary_digest,
    summary_to_dict,
)
from ..operators.operators import Operator, OperatorInstance, register
from .manager import RECORDINGS

log = get_logger("ig-tpu.capture")


def _resolved_params(ctx: GadgetContext) -> dict[str, str]:
    """The run's resolved flat param map — the manifest provenance a
    replay reconstructs its operator chain from."""
    flat = ctx.gadget_params.copy_to_map(prefix="gadget.")
    flat.update(ctx.operator_params.copy_to_map())
    return flat


class Capture(Operator):
    name = "capture"

    def dependencies(self) -> list[str]:
        return []

    def can_operate_on(self, desc: GadgetDesc) -> bool:
        return True  # any batch-emitting gadget can be recorded

    def instance_params(self) -> ParamDescs:
        return ParamDescs([
            ParamDesc(key="dir", default="",
                      description="record this run into a journal under "
                                  "this directory (independent of node-"
                                  "wide recordings)"),
            ParamDesc(key="max-segment-bytes",
                      default=str(DEFAULT_SEGMENT_BYTES),
                      type_hint=TypeHint.INT,
                      description="rotate the active segment at this size"),
            ParamDesc(key="max-segment-age", default=f"{DEFAULT_SEGMENT_AGE}s",
                      type_hint=TypeHint.DURATION,
                      description="rotate the active segment at this age"),
            ParamDesc(key="retention-bytes",
                      default=str(DEFAULT_RETENTION_BYTES),
                      type_hint=TypeHint.INT,
                      description="GC oldest sealed segments beyond this "
                                  "total size (0 = unlimited)"),
            ParamDesc(key="retention-segments", default="0",
                      type_hint=TypeHint.INT,
                      description="GC oldest sealed segments beyond this "
                                  "count (0 = unlimited)"),
            ParamDesc(key="summaries", default="true",
                      type_hint=TypeHint.BOOL,
                      description="record harvested sketch summaries"),
            ParamDesc(key="alerts", default="true", type_hint=TypeHint.BOOL,
                      description="record alert transitions"),
        ])

    def instantiate(self, ctx: GadgetContext, gadget: Any,
                    instance_params: Params) -> "CaptureInstance":
        return CaptureInstance(self, ctx, instance_params)


class CaptureInstance(OperatorInstance):
    def __init__(self, op: Capture, ctx: GadgetContext, params: Params):
        super().__init__(op.name)
        self.ctx = ctx
        self._run_writer: JournalWriter | None = None
        self._replay = bool(ctx.extra.get("replay"))
        p = params
        self._opts = {
            "max_segment_bytes": (p.get("max-segment-bytes").as_int()
                                  if "max-segment-bytes" in p
                                  else DEFAULT_SEGMENT_BYTES),
            "max_segment_age": (p.get("max-segment-age").as_duration()
                                if "max-segment-age" in p
                                else DEFAULT_SEGMENT_AGE),
            "retention_bytes": (p.get("retention-bytes").as_int()
                                if "retention-bytes" in p
                                else DEFAULT_RETENTION_BYTES),
            "retention_segments": (p.get("retention-segments").as_int()
                                   if "retention-segments" in p else 0),
        }
        self._want_summaries = (p.get("summaries").as_bool()
                                if "summaries" in p else True)
        self._want_alerts = (p.get("alerts").as_bool()
                             if "alerts" in p else True)
        run_dir = p.get("dir").as_string() if "dir" in p else ""
        self._node = ctx.extra.get("node", "") or ""
        self._params = _resolved_params(ctx)  # once, not per batch
        if run_dir and not self._replay:
            import os
            self._run_writer = JournalWriter(
                os.path.join(run_dir, f"{ctx.desc.full_name.replace('/', '-')}"
                                      f"-{ctx.run_id}"),
                manifest=build_manifest(
                    journal_id=ctx.run_id, node=self._node,
                    gadget=ctx.desc.full_name, run_id=ctx.run_id,
                    params=self._params),
                **self._opts)
            self._run_writer.mark("run-start", gadget=ctx.desc.full_name,
                                  run_id=ctx.run_id)
        # chain into the summary path. The alerts operator DEPENDS on
        # capture (alertsop.dependencies), so capture instantiates first
        # and its hook sits innermost: the engine evaluates each harvest
        # before this hook records it, and — because teardown runs in
        # reverse — the engine's end-of-run resolves still find these
        # writers open
        if self._want_summaries and not self._replay:
            prev = ctx.extra.get("on_sketch_summary")

            def hook(summary):
                self._record_summary(summary)
                if prev is not None:
                    prev(summary)

            ctx.extra["on_sketch_summary"] = hook
        if self._want_alerts and not self._replay:
            prev_alert = ctx.extra.get("on_alert_event")

            def alert_hook(alert: dict):
                self._record_alert(alert)
                if prev_alert is not None:
                    prev_alert(alert)

            ctx.extra["on_alert_event"] = alert_hook

    # -- destinations -------------------------------------------------------

    def _writers(self) -> list[JournalWriter]:
        writers = []
        if self._run_writer is not None:
            writers.append(self._run_writer)
        if not self._replay:
            for rec in RECORDINGS.active():
                try:
                    writers.append(rec.writer_for(
                        node=self._node, gadget=self.ctx.desc.full_name,
                        run_id=self.ctx.run_id, params=self._params))
                except (OSError, ValueError) as e:
                    log.warning("recording %s: journal open failed: %r",
                                rec.id, e)
        return writers

    @staticmethod
    def _append(writers: list[JournalWriter], ev_type: int, header: dict,
                payload: bytes = b"") -> None:
        for w in writers:
            try:
                w.append(ev_type, header, payload)
            except (OSError, ValueError) as e:
                log.warning("capture append to %s failed: %r", w.path, e)

    # -- the tee points -----------------------------------------------------

    def enrich_batch(self, batch: Any) -> None:
        if self._replay or batch.count == 0:
            return
        writers = self._writers()
        if not writers:
            return
        self._append(writers, wire.EV_BATCH_NPZ,
                     {"count": batch.count, "drops": batch.drops,
                      "batch_seq": batch.seq},
                     wire.encode_batch(batch))

    def _record_summary(self, summary) -> None:
        writers = self._writers()
        if not writers:
            return
        header, payload = wire.encode_summary(summary)
        header["digest"] = summary_digest(summary_to_dict(summary))
        self._append(writers, wire.EV_SUMMARY, header, payload)

    def _record_alert(self, alert: dict) -> None:
        writers = self._writers()
        if not writers:
            return
        self._append(writers, wire.EV_ALERT, {"alert": alert})

    def post_gadget_run(self) -> None:
        if self._run_writer is not None:
            self._run_writer.mark("run-end", run_id=self.ctx.run_id)
            self._run_writer.close()
            self._run_writer = None
        if not self._replay:
            for rec in RECORDINGS.active():
                rec.release(node=self._node, run_id=self.ctx.run_id)


register(Capture())
