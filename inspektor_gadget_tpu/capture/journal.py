"""Segmented on-disk event journal — the durable record half of the
capture/replay plane.

The reference keeps per-container overwritable syscall rings (traceloop)
so an incident can be inspected after the fact; this journal is the
framework-native durable analogue: typed wire records (the same EV_*
types the agent streams — batches, summaries, alerts, marks) framed into
append-only segment files that a crash can tear only at the very tail.

Layout of one journal directory:

    <journal>/
      manifest.json        # provenance: who/what/where recorded this
      index.jsonl          # one line per SEALED segment (seq/ts ranges)
      seg-00000001.igj     # frames; the highest-numbered file is active
      seg-00000002.igj

Frame format (all little-endian):

    u32 length  | u32 crc32(zpayload) | zpayload
    zpayload = zlib.compress(wire.encode_msg(header, payload))
    header carries at least {"type": EV_*, "seq": n, "ts": epoch-seconds}

Each frame is written with ONE O_APPEND write (utils/journal.py
append_bytes — short writes completed or raised), so concurrent writers
cannot interleave and a crash mid-write leaves exactly one torn frame at
the segment tail. Readers drop the torn tail and account the loss (the
perf-ledger stance applied to binary records): a truncated length
prefix, a frame shorter than its length, a CRC mismatch, or an
undecompressable payload all end that segment's read — everything before
is good, everything after is counted as dropped bytes.

Rotation seals the active segment (its seq/ts range goes into
index.jsonl) when it exceeds max_segment_bytes or max_segment_age;
retention GC then deletes the oldest sealed segments beyond
retention_bytes/retention_segments. The active segment is never GC'd.
"""

from __future__ import annotations

import dataclasses
import glob
import hashlib
import json
import os
import threading
import time
import zlib
from typing import Any, Callable, Iterator

from ..agent import wire
from ..telemetry import counter, gauge
from ..utils.journal import append_bytes, append_line, read_json_file, read_jsonl

JOURNAL_SCHEMA = "ig-tpu/capture-journal/v1"
MANIFEST = "manifest.json"
INDEX = "index.jsonl"
SEG_PREFIX = "seg-"
SEG_SUFFIX = ".igj"
FRAME_HEADER = 8  # u32 length + u32 crc32

DEFAULT_SEGMENT_BYTES = 4 << 20
DEFAULT_SEGMENT_AGE = 60.0
DEFAULT_RETENTION_BYTES = 256 << 20
DEFAULT_RETENTION_SEGMENTS = 0  # 0 = unlimited count (bytes still bound)

@dataclasses.dataclass(frozen=True)
class JournalMetrics:
    """The counter family one journal plane accounts into. The capture
    plane owns ig_capture_*; the sketch-history store (history/store.py)
    reuses the whole writer/reader machinery but must not launder its
    window traffic through capture's counters, so it passes its own."""
    records: Any    # counter("...", labels=("type",))
    bytes: Any      # counter
    drops: Any      # counter("...", labels=("reason",))
    gc: Any         # counter
    active: Any     # gauge


CAPTURE_METRICS = JournalMetrics(
    records=counter("ig_capture_records_total",
                    "records appended to capture journals", ("type",)),
    bytes=counter("ig_capture_bytes_total",
                  "bytes appended to capture journals"),
    drops=counter("ig_capture_drops_total",
                  "capture records lost (torn tails on reopen, failed "
                  "appends)", ("reason",)),
    gc=counter("ig_capture_gc_total",
               "sealed segments deleted by retention GC"),
    active=gauge("ig_capture_active_journals", "open journal writers"),
)

def capture_base_dir(path: str | None = None) -> str:
    """The node-wide default recording area: $IG_CAPTURE_DIR, else
    ~/.ig-tpu/capture (agents override with --capture-dir)."""
    return (path or os.environ.get("IG_CAPTURE_DIR")
            or os.path.join(os.path.expanduser("~"), ".ig-tpu", "capture"))


def is_journal(path: str) -> bool:
    return os.path.isfile(os.path.join(path, MANIFEST))


def _seg_name(n: int) -> str:
    return f"{SEG_PREFIX}{n:08d}{SEG_SUFFIX}"


def _seg_number(name: str) -> int:
    return int(os.path.basename(name)[len(SEG_PREFIX):-len(SEG_SUFFIX)])


def _list_segments(path: str) -> list[str]:
    return sorted(glob.glob(os.path.join(path, f"{SEG_PREFIX}*{SEG_SUFFIX}")),
                  key=_seg_number)


def build_manifest(*, journal_id: str = "", node: str = "", gadget: str = "",
                   run_id: str = "", params: dict[str, str] | None = None,
                   extra: dict | None = None) -> dict:
    """Provenance block every journal carries: git sha, node id, gadget
    id, resolved params, and the platform/degraded outcome of the PR-2
    probe — a journal read months later still answers 'what produced
    this' without trusting surrounding prose."""
    from ..perf.provenance import git_provenance, host_fingerprint
    from ..utils.platform_probe import last_acquire
    sha, dirty = git_provenance()
    acq = last_acquire() or {}
    return {
        "schema": JOURNAL_SCHEMA,
        "journal_id": journal_id,
        "node": node,
        "gadget": gadget,
        "run_id": run_id,
        "created_ts": time.time(),
        "git_sha": sha,
        "git_dirty": dirty,
        "host": host_fingerprint(),
        "platform": acq.get("platform", "unprobed"),
        "degraded": bool(acq.get("degraded", False)),
        "params": dict(params or {}),
        **(extra or {}),
    }


@dataclasses.dataclass
class SegmentLoss:
    """Loss accounting for one segment's torn tail."""
    segment: str
    offset: int          # byte offset the read stopped at
    dropped_bytes: int
    reason: str


class JournalWriter:
    """Appender for one journal directory. Thread-safe: rotation and the
    frame write happen under one lock (the O_APPEND write itself is
    atomic, but seq assignment and size accounting are not)."""

    def __init__(self, path: str, *,
                 manifest: dict | None = None,
                 max_segment_bytes: int = DEFAULT_SEGMENT_BYTES,
                 max_segment_age: float = DEFAULT_SEGMENT_AGE,
                 retention_bytes: int = DEFAULT_RETENTION_BYTES,
                 retention_segments: int = DEFAULT_RETENTION_SEGMENTS,
                 clock: Callable[[], float] = time.time,
                 metrics: JournalMetrics = CAPTURE_METRICS):
        self.path = path
        self._m = metrics
        self.max_segment_bytes = max(int(max_segment_bytes), 1 << 12)
        self.max_segment_age = float(max_segment_age)
        self.retention_bytes = int(retention_bytes)
        self.retention_segments = int(retention_segments)
        self._clock = clock
        self._mu = threading.Lock()
        self._closed = False
        os.makedirs(path, exist_ok=True)
        mpath = os.path.join(path, MANIFEST)
        if os.path.exists(mpath):
            # reopening an existing journal (crash recovery / resumed
            # recording): continue after the last good record, and account
            # the torn tail the previous writer may have left
            doc, err = read_json_file(mpath)
            self.manifest = doc or build_manifest()
            if err:
                self._m.drops.labels(reason="manifest").inc()
            self._recover()
        else:
            self.manifest = manifest or build_manifest()
            tmp = f"{mpath}.tmp.{os.getpid()}"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(self.manifest, f, sort_keys=True)
            os.replace(tmp, mpath)
            self._seg_n = 1
            self._seg_bytes = 0
            self._seg_records = 0
            self._seg_opened = self._clock()
            self._seg_first_seq = None
            self._seg_first_ts = None
            self._seq = 0
            self._last_ts = 0.0
        self._m.active.inc()

    # -- recovery -----------------------------------------------------------

    def _recover(self) -> None:
        segs = _list_segments(self.path)
        self._seq = 0
        self._last_ts = 0.0
        sealed: set[str] = set()
        ipath = os.path.join(self.path, INDEX)
        idx = read_jsonl(ipath, on_bad="stop")
        if idx.skipped:
            # a crash mid-seal tore an index line; repair NOW (atomic
            # rewrite of the good rows) — otherwise every seal row this
            # writer appends lands after the tear and stays invisible to
            # on_bad="stop" readers forever
            tmp = f"{ipath}.tmp.{os.getpid()}"
            with open(tmp, "w", encoding="utf-8") as f:
                for row in idx.records:
                    f.write(json.dumps(row, sort_keys=True,
                                       separators=(",", ":")) + "\n")
            os.replace(tmp, ipath)
            self._m.drops.labels(reason="index").inc()
        for line in idx.records:
            self._seq = max(self._seq, int(line.get("last_seq", 0)))
            self._last_ts = max(self._last_ts,
                                float(line.get("last_ts") or 0.0))
            sealed.add(str(line.get("file", "")))
        tail = segs[-1] if segs else None
        if tail is not None and os.path.basename(tail) not in sealed:
            # an UNSEALED tail (crash mid-segment): resume it after
            # dropping any torn frame on disk, so the next append starts
            # on a clean boundary instead of extending junk
            records, loss = scan_segment(tail)
            if loss is not None:
                with open(tail, "r+b") as f:
                    f.truncate(loss.offset)
                self._m.drops.labels(reason="torn_tail").inc()
            self._seg_n = _seg_number(tail)
            self._seg_bytes = os.path.getsize(tail)
            self._seg_records = len(records)
            if records:
                self._seq = max(self._seq,
                                int(records[-1][0].get("seq", 0)))
                self._last_ts = max(self._last_ts,
                                    float(records[-1][0].get("ts", 0.0)))
            self._seg_first_seq = (int(records[0][0].get("seq", 0))
                                   if records else None)
            self._seg_first_ts = (float(records[0][0].get("ts", 0.0))
                                  if records else None)
        else:
            # fresh journal, or the tail is already SEALED (clean close,
            # or crash between seal and next append): appending into a
            # sealed file would silently invalidate its index row, so
            # start the next segment instead
            self._seg_n = _seg_number(tail) + 1 if tail is not None else 1
            self._seg_bytes = 0
            self._seg_records = 0
            self._seg_first_seq = None
            self._seg_first_ts = None
        self._seg_opened = self._clock()

    # -- append -------------------------------------------------------------

    def append(self, ev_type: int, header: dict | None = None,
               payload: bytes = b"", ts: float | None = None) -> int:
        """Frame + append one typed record; returns its seq. One
        O_APPEND write; never partially applied from the reader's view
        (a torn write is dropped at read time, not half-decoded)."""
        with self._mu:
            if self._closed:
                raise ValueError(f"journal {self.path} is closed")
            self._maybe_rotate_locked()
            self._seq += 1
            seq = self._seq
            now = self._clock() if ts is None else float(ts)
            h = {**(header or {}), "type": ev_type, "seq": seq, "ts": now}
            zpayload = zlib.compress(wire.encode_msg(h, payload), 1)
            frame = (len(zpayload).to_bytes(4, "little")
                     + (zlib.crc32(zpayload) & 0xFFFFFFFF).to_bytes(4, "little")
                     + zpayload)
            try:
                append_bytes(self._active_path(), frame)
            except OSError:
                self._seq -= 1
                self._m.drops.labels(reason="append").inc()
                raise
            if self._seg_first_seq is None:
                self._seg_first_seq = seq
                self._seg_first_ts = now
            self._seg_bytes += len(frame)
            self._seg_records += 1
            self._last_ts = now
            self._m.records.labels(type=str(ev_type)).inc()
            self._m.bytes.inc(len(frame))
            return seq

    def mark(self, mark: str, **fields) -> int:
        """Append an EV_JOURNAL_MARK lifecycle record (recording
        start/stop, rotation causes, replay anchors)."""
        return self.append(wire.EV_JOURNAL_MARK, {"mark": mark, **fields})

    def _active_path(self) -> str:
        return os.path.join(self.path, _seg_name(self._seg_n))

    # -- rotation + retention ----------------------------------------------

    def _maybe_rotate_locked(self) -> None:
        if self._seg_records == 0:
            self._seg_opened = self._clock()
            return
        aged = (self.max_segment_age > 0
                and self._clock() - self._seg_opened >= self.max_segment_age)
        if self._seg_bytes < self.max_segment_bytes and not aged:
            return
        self._seal_locked()
        self._gc_locked()

    def _index_extra_locked(self) -> dict:
        """Subclass hook: extra fields merged into the seal row of the
        segment being sealed (the history store adds the subpopulation
        keys its windows carry, so range queries can skip whole segments
        by slice key). Called under _mu; must also reset any per-segment
        accumulation it maintains."""
        return {}

    def _seal_locked(self) -> None:
        append_line(os.path.join(self.path, INDEX), {
            "file": _seg_name(self._seg_n),
            "records": self._seg_records,
            "bytes": self._seg_bytes,
            "first_seq": self._seg_first_seq,
            "last_seq": self._seq,
            "first_ts": self._seg_first_ts,
            "last_ts": self._last_ts,
            "sealed_ts": self._clock(),
            **self._index_extra_locked(),
        })
        self._seg_n += 1
        self._seg_bytes = 0
        self._seg_records = 0
        self._seg_opened = self._clock()
        self._seg_first_seq = None
        self._seg_first_ts = None

    def _gc_locked(self) -> None:
        """Delete the oldest sealed segments beyond the retention bounds.
        The active segment and the index rows of surviving segments are
        untouched; GC'd rows stay in the index flagged nowhere — readers
        treat a missing sealed file as GC'd history, not corruption."""
        sealed = []
        total = self._seg_bytes
        for s in _list_segments(self.path):
            if _seg_number(s) >= self._seg_n:
                continue
            try:
                # a compaction/archive pass (history stores share this
                # writer machinery) may GC a sealed segment between the
                # listing and the stat — treat it as already gone
                total += os.path.getsize(s)
            except OSError:
                continue
            sealed.append(s)
        removed = 0
        for s in sealed:
            over_bytes = (self.retention_bytes > 0
                          and total > self.retention_bytes)
            over_count = (self.retention_segments > 0
                          and len(sealed) - removed > self.retention_segments)
            if not over_bytes and not over_count:
                break
            try:
                size = os.path.getsize(s)
                os.remove(s)
            except OSError:
                break  # a racing reader on a shared FS: stop, retry next GC
            total -= size
            removed += 1
            self._m.gc.inc()

    # -- lifecycle ----------------------------------------------------------

    def rotate(self) -> None:
        """Force-seal the active segment (tests; recording stop)."""
        with self._mu:
            if self._seg_records:
                self._seal_locked()
                self._gc_locked()

    def stats(self) -> dict:
        with self._mu:
            return {
                "path": self.path,
                "next_seq": self._seq,
                "active_segment": _seg_name(self._seg_n),
                "active_bytes": self._seg_bytes,
                "active_records": self._seg_records,
                "segments": len(_list_segments(self.path)),
            }

    def close(self) -> dict:
        """Seal the tail, finalize the manifest (closed_ts + totals);
        idempotent. Returns the final stats."""
        with self._mu:
            if self._closed:
                return {"path": self.path, "closed": True}
            if self._seg_records:
                self._seal_locked()
            self._closed = True
        self._m.active.dec()
        mpath = os.path.join(self.path, MANIFEST)
        doc, _err = read_json_file(mpath)
        doc = doc or dict(self.manifest)
        doc["closed_ts"] = self._clock()
        doc["last_seq"] = self._seq
        tmp = f"{mpath}.tmp.{os.getpid()}"
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(doc, f, sort_keys=True)
            os.replace(tmp, mpath)
        except OSError:
            self._m.drops.labels(reason="manifest").inc()
        return {"path": self.path, "records": self._seq,
                "segments": len(_list_segments(self.path))}


# ---------------------------------------------------------------------------
# Reading
# ---------------------------------------------------------------------------

def _frame_at(data: bytes, off: int) -> tuple[int, bytes, str]:
    """(end, zpayload, reason) for the frame starting at `off` — the ONE
    owner of the frame layout every reader (scan, digest, stats) walks
    with; a non-empty reason marks the torn tail."""
    n = len(data)
    if n - off < FRAME_HEADER:
        return 0, b"", "truncated frame header"
    length = int.from_bytes(data[off:off + 4], "little")
    crc = int.from_bytes(data[off + 4:off + 8], "little")
    end = off + FRAME_HEADER + length
    if length == 0 or end > n:
        return 0, b"", (f"frame shorter than its length prefix "
                        f"({length} bytes)")
    zpayload = data[off + FRAME_HEADER:end]
    if (zlib.crc32(zpayload) & 0xFFFFFFFF) != crc:
        return 0, b"", "crc mismatch"
    return end, zpayload, ""


def _decode_frame(zpayload: bytes) -> tuple[dict, bytes] | None:
    try:
        return wire.decode_msg(zlib.decompress(zpayload))
    except (zlib.error, ValueError, KeyError, json.JSONDecodeError):
        return None


def scan_segment(path: str) -> tuple[list[tuple[dict, bytes]],
                                     SegmentLoss | None]:
    """Decode every whole frame of one segment file. Returns (records,
    loss): records are (header, payload) pairs; loss is the torn tail
    (None when the file ends exactly on a frame boundary)."""
    records: list[tuple[dict, bytes]] = []
    try:
        data = open(path, "rb").read()
    except OSError as e:
        return records, SegmentLoss(os.path.basename(path), 0, 0,
                                    f"unreadable: {e.strerror or e}")
    off = 0
    n = len(data)
    while off < n:
        end, zpayload, reason = _frame_at(data, off)
        if reason:
            return records, SegmentLoss(
                os.path.basename(path), off, n - off, reason)
        decoded = _decode_frame(zpayload)
        if decoded is None:
            return records, SegmentLoss(
                os.path.basename(path), off, n - off, "undecodable frame")
        records.append(decoded)
        off = end
    return records, None


class JournalReader:
    """Range-capable reader over one journal directory. The index lets
    seq/time range reads skip whole sealed segments; the (possibly torn)
    active segment is always scanned directly."""

    def __init__(self, path: str, *,
                 metrics: JournalMetrics = CAPTURE_METRICS):
        if not is_journal(path):
            raise FileNotFoundError(f"{path}: not a capture journal "
                                    f"(no {MANIFEST})")
        self.path = path
        self._m = metrics
        doc, err = read_json_file(os.path.join(path, MANIFEST))
        self.manifest: dict = doc or {}
        self.manifest_error = err
        idx = read_jsonl(os.path.join(path, INDEX), on_bad="stop")
        self.index = idx.records
        self.index_skipped = idx.skipped
        self.losses: list[SegmentLoss] = []
        self.missing_segments: list[str] = []   # GC'd sealed history

    def _segment_files(self) -> list[str]:
        return _list_segments(self.path)

    def _index_row(self, name: str) -> dict | None:
        for row in self.index:
            if row.get("file") == name:
                return row
        return None

    def records(self, *, start_seq: int | None = None,
                end_seq: int | None = None,
                start_ts: float | None = None,
                end_ts: float | None = None,
                types: tuple[int, ...] | None = None
                ) -> Iterator[tuple[dict, bytes]]:
        """Yield (header, payload) in seq order, restricted to the given
        seq/ts range and record types. Loss accounting accumulates in
        self.losses as segments are scanned."""
        self.losses = []
        self.missing_segments = []
        present = {os.path.basename(p) for p in self._segment_files()}
        for row in self.index:
            if row.get("file") not in present:
                self.missing_segments.append(row.get("file", "?"))
        for seg in self._segment_files():
            row = self._index_row(os.path.basename(seg))
            if row is not None:
                # sealed segment: the index bounds let range reads skip it
                if start_seq is not None and row.get("last_seq") is not None \
                        and row["last_seq"] < start_seq:
                    continue
                if end_seq is not None and row.get("first_seq") is not None \
                        and row["first_seq"] > end_seq:
                    continue
                if start_ts is not None and row.get("last_ts") is not None \
                        and row["last_ts"] < start_ts:
                    continue
                if end_ts is not None and row.get("first_ts") is not None \
                        and row["first_ts"] > end_ts:
                    continue
            records, loss = scan_segment(seg)
            if loss is not None:
                self.losses.append(loss)
                self._m.drops.labels(reason="torn_tail").inc()
            for header, payload in records:
                seq = header.get("seq", 0)
                ts = header.get("ts", 0.0)
                if start_seq is not None and seq < start_seq:
                    continue
                if end_seq is not None and seq > end_seq:
                    continue
                if start_ts is not None and ts < start_ts:
                    continue
                if end_ts is not None and ts > end_ts:
                    continue
                if types is not None and header.get("type") not in types:
                    continue
                yield header, payload

    def stats(self) -> dict:
        """One inspection pass over every segment: counts by type,
        seq/ts bounds, losses, AND the content digest — computed in the
        same walk, so inspecting a multi-GiB bundle reads each segment
        exactly once."""
        by_type: dict[str, int] = {}
        first_seq = last_seq = None
        first_ts = last_ts = None
        total = 0
        losses: list[SegmentLoss] = []
        h = hashlib.sha256()
        for seg in self._segment_files():
            try:
                data = open(seg, "rb").read()
            except OSError as e:
                losses.append(SegmentLoss(os.path.basename(seg), 0, 0,
                                          f"unreadable: {e.strerror or e}"))
                continue
            off = 0
            while off < len(data):
                end, zpayload, reason = _frame_at(data, off)
                decoded = None if reason else _decode_frame(zpayload)
                if reason or decoded is None:
                    losses.append(SegmentLoss(
                        os.path.basename(seg), off, len(data) - off,
                        reason or "undecodable frame"))
                    break
                h.update(data[off:off + FRAME_HEADER])
                header, _payload = decoded
                total += 1
                t = str(header.get("type", 0))
                by_type[t] = by_type.get(t, 0) + 1
                seq = header.get("seq", 0)
                ts = header.get("ts", 0.0)
                first_seq = seq if first_seq is None else min(first_seq, seq)
                last_seq = seq if last_seq is None else max(last_seq, seq)
                first_ts = ts if first_ts is None else min(first_ts, ts)
                last_ts = ts if last_ts is None else max(last_ts, ts)
                off = end
        self.losses = losses
        present = {os.path.basename(p) for p in self._segment_files()}
        self.missing_segments = [row.get("file", "?") for row in self.index
                                 if row.get("file") not in present]
        return {
            "path": self.path,
            "records": total,
            "by_type": by_type,
            "first_seq": first_seq, "last_seq": last_seq,
            "first_ts": first_ts, "last_ts": last_ts,
            "segments": len(present),
            "gc_missing_segments": list(self.missing_segments),
            "losses": [dataclasses.asdict(loss) for loss in losses],
            "digest": h.hexdigest(),
        }

    def digest(self) -> str:
        """Content digest of every surviving frame (in order), cheap and
        stable: sha256 over each frame's (length, crc) header. Identifies
        replay inputs in PerfRecord provenance and verifies a fetched
        bundle matches the node's journal. Walks frames through the same
        _frame_at the decoder uses — a layout change cannot silently
        diverge the digest from what decodes."""
        h = hashlib.sha256()
        for seg in self._segment_files():
            try:
                data = open(seg, "rb").read()
            except OSError:
                continue
            off = 0
            while off < len(data):
                end, zpayload, reason = _frame_at(data, off)
                if reason or _decode_frame(zpayload) is None:
                    break  # same stop rule as scan_segment/stats
                h.update(data[off:off + FRAME_HEADER])
                off = end
        return h.hexdigest()


def dir_stats(path: str) -> tuple[int, int]:
    """(segment files, total bytes of ALL files) under a capture tree —
    the one helper the doctor row and top/recordings share, keyed off
    this module's format constants so a layout change can't silently
    zero their reports."""
    segments = 0
    total = 0
    for root, _dirs, files in os.walk(path):
        for f in files:
            try:
                total += os.path.getsize(os.path.join(root, f))
            except OSError:
                continue
            if f.startswith(SEG_PREFIX) and f.endswith(SEG_SUFFIX):
                segments += 1
    return segments, total


def summary_digest(summary: dict) -> str:
    """Canonical digest of one harvested summary — the unit the replay
    determinism contract is asserted over. Excludes `names` (label
    sampling resolves through the live gadget's vocab, which a replay
    does not have) and `anomaly` model scores' dict ordering is
    canonicalized by sort_keys."""
    doc = {
        "events": int(summary.get("events", 0)),
        "drops": int(summary.get("drops", 0)),
        "distinct": float(summary.get("distinct", 0.0)),
        "entropy": float(summary.get("entropy",
                                     summary.get("entropy_bits", 0.0))),
        "epoch": int(summary.get("epoch", 0)),
        "heavy_hitters": [[int(k), int(c)]
                          for k, c in (summary.get("heavy_hitters") or [])],
    }
    anomaly = summary.get("anomaly")
    if anomaly:
        doc["anomaly"] = {str(k): float(v) for k, v in anomaly.items()}
    blob = json.dumps(doc, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()


def summary_to_dict(summary: Any) -> dict:
    """SketchSummary (or its wire dict) → the canonical journal/digest
    dict shape (the wire decode_summary shape)."""
    if isinstance(summary, dict):
        return summary
    return {
        "events": summary.events,
        "drops": summary.drops,
        "distinct": summary.distinct,
        "entropy": summary.entropy_bits,
        "epoch": summary.epoch,
        "anomaly": summary.anomaly,
        "names": {str(k): v for k, v in (summary.names or {}).items()},
        "heavy_hitters": [(int(k), int(c)) for k, c in summary.heavy_hitters],
    }


__all__ = ["CAPTURE_METRICS", "DEFAULT_RETENTION_BYTES",
           "DEFAULT_SEGMENT_AGE", "DEFAULT_SEGMENT_BYTES", "INDEX",
           "JOURNAL_SCHEMA", "JournalMetrics", "JournalReader",
           "JournalWriter", "MANIFEST", "SegmentLoss", "build_manifest",
           "capture_base_dir", "dir_stats", "is_journal", "scan_segment",
           "summary_digest", "summary_to_dict"]
