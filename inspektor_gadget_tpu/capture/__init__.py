"""Capture/replay plane: durable segmented event journals, a
deterministic replay source, and the cluster-wide recording lifecycle.

The live pipeline is live-or-lost once a batch leaves the operator
chain; this package closes the gap the way production trace tooling
does — record the typed stream durably (journal.py), manage node-wide
recordings (manager.py, armed by the capture operator riding every run),
and re-drive any journal through the real operator chain on an
injectable clock (replay.py) so a bug seen on a node replays on a
laptop, the bench harness gets reproducible input, and `alerts test`
dry-runs rules against real recorded traffic.
"""

from .journal import (
    JOURNAL_SCHEMA,
    JournalReader,
    JournalWriter,
    SegmentLoss,
    build_manifest,
    capture_base_dir,
    is_journal,
    summary_digest,
    summary_to_dict,
)
from .manager import RECORDINGS, Recording, RecordingManager
from .replay import (
    ReplayClock,
    ReplayResult,
    ReplaySource,
    iter_journals,
    replay_journal,
)

__all__ = [
    "JOURNAL_SCHEMA", "JournalReader", "JournalWriter", "RECORDINGS",
    "Recording", "RecordingManager", "ReplayClock", "ReplayResult",
    "ReplaySource", "SegmentLoss", "build_manifest", "capture_base_dir",
    "is_journal", "iter_journals", "replay_journal", "summary_digest",
    "summary_to_dict",
]
