"""Deterministic replay: re-drive a recorded journal through the REAL
operator chain (enrich → tpusketch → alerts) on an injectable clock.

The journal's EV_BATCH_NPZ records are the input stream; its EV_SUMMARY
records are the harvest boundaries (replay disables the sketch plane's
wall-clock auto-harvest and harvests exactly where the original run
did, so the device math folds the same batches into the same epochs);
its EV_ALERT records are the recorded ground truth replayed transitions
are compared against. The alert engine runs on a ReplayClock driven by
recorded timestamps — debounce (`for`), cooldown, and hysteresis
decisions reproduce exactly, at recorded pace (`speed=1`), accelerated
(`speed=10`), or as fast as the machine goes (`speed=0`).

Determinism contract (asserted in tests and by `ig-tpu replay
--verify`): same journal → byte-identical summary digest sequence, and
the identical (rule, key, transition, epoch) alert sequence.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Iterator

from ..agent import wire
from ..gadgets.context import GadgetContext
from ..gadgets.interface import GadgetDesc, GadgetType
from ..params import Collection, ParamDescs
from ..utils.logger import get_logger
from .journal import JournalReader, summary_digest, summary_to_dict

log = get_logger("ig-tpu.replay")


class ReplayClock:
    """Recorded-timeline clock: now() is seconds since the journal's
    first record, advanced only by the records themselves. Injected into
    the alert engine so time-based decisions replay identically no
    matter how fast the wall clock runs."""

    def __init__(self):
        self._epoch: float | None = None
        self._now = 0.0

    def advance_to(self, ts: float) -> None:
        if self._epoch is None:
            self._epoch = ts
        self._now = max(self._now, ts - self._epoch)

    def now(self) -> float:
        return self._now


class ReplaySource:
    """Source-interface adapter over a journal's recorded batches — what
    `bench run --replay` feeds the perf harness so stage numbers are
    reproducible input-for-input. Batches are decoded once up front;
    generate()/pop() hands them out in recorded order (cycling when
    `cycle`, the harness mode: a fixed input sequence per pass)."""

    def __init__(self, journal: "str | JournalReader", *, cycle: bool = False):
        reader = (journal if isinstance(journal, JournalReader)
                  else JournalReader(journal))
        self.reader = reader
        self.batches = [wire.decode_batch(payload)
                        for header, payload in reader.records(
                            types=(wire.EV_BATCH_NPZ,))]
        self.digest = reader.digest()
        self.cycle = cycle
        self._i = 0
        self._seq = 0

    def __len__(self) -> int:
        return len(self.batches)

    def start(self) -> None:  # interface parity
        pass

    def stop(self) -> None:
        pass

    def close(self) -> None:
        pass

    def generate(self, n: int | None = None):
        if not self.batches:
            raise ValueError(f"{self.reader.path}: journal carries no "
                             "EV_BATCH_NPZ records to replay")
        if self._i >= len(self.batches):
            if not self.cycle:
                from ..sources.batch import EventBatch
                return EventBatch.alloc(0, with_comm=False)
            self._i = 0
        b = self.batches[self._i]
        self._i += 1
        b.seq = self._seq
        self._seq += b.count
        return b

    pop = generate

    def reset(self) -> None:
        """Rewind to the first recorded batch (the harness warms up on
        recorded data, then measures the sequence from the start)."""
        self._i = 0
        self._seq = 0

    def exhausted(self) -> bool:
        return not self.cycle and self._i >= len(self.batches)

    def drops(self) -> int:
        return 0

    def vocab_lookup(self, key_hash: int) -> str:
        return ""


@dataclasses.dataclass
class ReplayResult:
    journal: str
    records: int
    batches: int
    events: int
    summaries: list[dict]
    digests: list[str]              # replayed harvest digests, in order
    recorded_digests: list[str]     # digests the original run journaled
    alerts: list[dict]              # replayed transitions (wire dict shape)
    recorded_alerts: list[dict]     # transitions the original run journaled
    losses: list[dict]
    manifest: dict

    @property
    def digests_match(self) -> bool:
        # the recorded run may have journaled digests replay can't have
        # produced (records past a torn tail never replay) — compare the
        # common prefix only when loss was accounted, exactly otherwise
        if self.losses:
            n = len(self.digests)
            return self.recorded_digests[:n] == self.digests
        return self.recorded_digests == self.digests

    @staticmethod
    def _transition_key(a: dict) -> tuple:
        return (a.get("rule", ""), a.get("key", ""),
                a.get("transition", ""), a.get("epoch", 0))

    @property
    def alerts_match(self) -> bool:
        got = [self._transition_key(a) for a in self.alerts]
        want = [self._transition_key(a) for a in self.recorded_alerts]
        if self.losses:
            return want[:len(got)] == got or got[:len(want)] == want
        return got == want


class _ReplayGadget:
    """Internal batch gadget that walks the journal: batches feed the
    operator chain, summary records trigger the live sketch instance's
    harvest at exactly the recorded boundaries."""

    def __init__(self, ctx: GadgetContext, reader: JournalReader,
                 clock: ReplayClock, speed: float,
                 collect: "ReplayResult"):
        self.ctx = ctx
        self.reader = reader
        self.clock = clock
        self.speed = speed
        self.collect = collect
        self._batch_handler: Callable[[Any], None] | None = None

    def set_batch_handler(self, handler: Callable[[Any], None]) -> None:
        self._batch_handler = handler

    def _sketch_instance(self):
        from ..operators import tpusketch
        for inst in tpusketch.live_instances():
            if inst.ctx.run_id == self.ctx.run_id:
                return inst
        return None

    def run(self, ctx: GadgetContext) -> None:
        prev_ts: float | None = None
        for header, payload in self.reader.records():
            if ctx.done:
                break
            self.collect.records += 1
            ts = float(header.get("ts", 0.0))
            if self.speed > 0 and prev_ts is not None and ts > prev_ts:
                if ctx.sleep_or_done((ts - prev_ts) / self.speed):
                    break
            prev_ts = ts
            self.clock.advance_to(ts)
            t = header.get("type")
            if t == wire.EV_BATCH_NPZ:
                batch = wire.decode_batch(payload)
                batch.drops = int(header.get("drops", 0))
                batch.seq = int(header.get("batch_seq", 0))
                self.collect.batches += 1
                self.collect.events += batch.count
                if self._batch_handler is not None and batch.count:
                    self._batch_handler(batch)
            elif t == wire.EV_SUMMARY:
                if header.get("digest"):
                    self.collect.recorded_digests.append(header["digest"])
                inst = self._sketch_instance()
                if inst is not None and getattr(inst, "enabled", False):
                    inst.harvest()  # flows through alerts + our collector
            elif t == wire.EV_ALERT:
                self.collect.recorded_alerts.append(
                    dict(header.get("alert") or {}))
            # EV_JOURNAL_MARK and anything unknown: position-only records
        self.collect.losses = [dataclasses.asdict(loss)
                               for loss in self.reader.losses]


class _ReplayDesc(GadgetDesc):
    """Deliberately NOT registered: replay is a verb, not a catalog
    gadget (registering it would drift docs/gadgets.md and the doctor
    report with an entry no capture window backs)."""

    name = "journal"
    category = "replay"
    gadget_type = GadgetType.TRACE
    description = "internal journal replay driver"
    event_cls = None

    def __init__(self, reader: JournalReader, clock: ReplayClock,
                 speed: float, collect: ReplayResult):
        self._reader = reader
        self._clock = clock
        self._speed = speed
        self._collect = collect

    def params(self) -> ParamDescs:
        return ParamDescs()

    def new_instance(self, ctx: GadgetContext) -> _ReplayGadget:
        return _ReplayGadget(ctx, self._reader, self._clock, self._speed,
                             self._collect)


# params a replay must not inherit from the recorded run: capture would
# recurse the journal into itself, the webhook file would double-append,
# and the wall-clock harvest interval would fight the recorded
# boundaries (EV_SUMMARY records drive harvests instead)
_STRIP_PARAM_PREFIXES = ("operator.capture.",)
_STRIP_PARAMS = ("operator.alerts.webhook-file",)
_FORCE_PARAMS = {"operator.tpusketch.harvest-interval": "1h"}


def _replay_op_params(manifest: dict, desc: GadgetDesc,
                      overrides: dict[str, str] | None) -> Collection:
    """Reconstruct the recorded run's operator chain from the manifest's
    resolved params (the provenance contract), minus the self-referential
    bits, plus caller overrides."""
    from ..operators import operators as op_registry
    flat = {k: v for k, v in (manifest.get("params") or {}).items()
            if not any(k.startswith(p) for p in _STRIP_PARAM_PREFIXES)
            and k not in _STRIP_PARAMS}
    flat.update(_FORCE_PARAMS)
    flat.update(overrides or {})
    col = Collection({
        f"operator.{op.name}.": op.instance_params().to_params()
        for op in op_registry.get_all() if op.can_operate_on(desc)
    })
    col.copy_from_map(flat)
    return col


def replay_journal(path: str, *, speed: float = 0.0,
                   rules: str | None = None,
                   rules_file: str | None = None,
                   param_overrides: dict[str, str] | None = None,
                   dry_run_alerts: bool = False,
                   on_summary: Callable[[dict], None] | None = None,
                   on_alert: Callable[[dict], None] | None = None,
                   timeout: float = 0.0) -> ReplayResult:
    """Replay one journal through the real operator chain; returns the
    ReplayResult with the determinism evidence (digests + transitions,
    recorded and replayed). `rules`/`rules_file` replace the recorded
    alert rules (the `alerts test --journal` path); `speed` 0 = as fast
    as possible, 1 = recorded pace."""
    import inspektor_gadget_tpu.all_gadgets  # noqa: F401 — operators register
    from ..runtime.local import LocalRuntime

    reader = JournalReader(path)
    clock = ReplayClock()
    collect = ReplayResult(
        journal=path, records=0, batches=0, events=0, summaries=[],
        digests=[], recorded_digests=[], alerts=[], recorded_alerts=[],
        losses=[], manifest=reader.manifest)
    desc = _ReplayDesc(reader, clock, speed, collect)

    overrides = dict(param_overrides or {})
    if rules is not None:
        overrides["operator.alerts.rules"] = rules
        overrides["operator.alerts.rules-file"] = ""
    if rules_file is not None:
        overrides["operator.alerts.rules-file"] = rules_file
        overrides["operator.alerts.rules"] = ""

    def collect_summary(summary):
        d = summary_to_dict(summary)
        collect.summaries.append(d)
        collect.digests.append(summary_digest(d))
        if on_summary is not None:
            on_summary(d)

    def collect_alert(alert: dict):
        collect.alerts.append(dict(alert))
        if on_alert is not None:
            on_alert(dict(alert))

    ctx = GadgetContext(
        desc,
        operator_params=_replay_op_params(reader.manifest, desc, overrides),
        timeout=timeout,
        extra={
            "replay": True,
            "alerts_clock": clock.now,
            "alerts_dry_run": dry_run_alerts,
            "on_sketch_summary": collect_summary,
            "on_alert_event": collect_alert,
            "node": reader.manifest.get("node", "") or "replay",
            # windows resealed during replay keep the RECORDED gadget
            # identity, so their content digests reproduce the live run's
            "history_gadget": reader.manifest.get("gadget", "") or None,
        },
    )
    result = LocalRuntime(node_name="replay").run_gadget(ctx)
    errs = result.errors()
    if errs:
        raise RuntimeError(f"replay of {path} failed: {errs}")
    return collect


def iter_journals(path: str) -> Iterator[str]:
    """Yield journal directories under `path`: the path itself when it is
    a journal, else every immediate child journal (a recording dir or a
    fetched bundle node dir), else every node's journals one level down
    (a fetched bundle root)."""
    import os

    from .journal import is_journal
    if is_journal(path):
        yield path
        return
    found = False
    for name in sorted(os.listdir(path)) if os.path.isdir(path) else []:
        child = os.path.join(path, name)
        if is_journal(child):
            found = True
            yield child
    if found:
        return
    for name in sorted(os.listdir(path)) if os.path.isdir(path) else []:
        child = os.path.join(path, name)
        if os.path.isdir(child):
            for j in sorted(os.listdir(child)):
                jpath = os.path.join(child, j)
                if is_journal(jpath):
                    yield jpath


__all__ = ["ReplayClock", "ReplayResult", "ReplaySource", "iter_journals",
           "replay_journal"]
