"""RecordingManager — the node-wide recording lifecycle.

The capture operator rides every gadget run (like tpusketch), but a
journal is only written when something armed it: either the run itself
(`--capture-dir` on the operator) or a node-wide *recording* started
here — by the agent's StartRecording RPC, by `ig-tpu record start`
against a local process, or programmatically in tests. A recording is a
directory `<base>/<recording-id>/` that accumulates one journal per
(gadget run) teeing into it:

    <base>/<recording-id>/
      recording.json             # id, started/stopped, per-journal stats
      <node>--<run_id>/          # one capture journal per recorded run
        manifest.json  index.jsonl  seg-*.igj

StopRecording seals every journal and finalizes recording.json; the
GrpcRuntime's fetch fan-out then pulls each node's recording directory
into one client-side bundle. The process-wide singleton (RECORDINGS)
plays the role tpusketch's checkpoint-dir global plays for sketch state.
"""

from __future__ import annotations

import json
import os
import threading
import time

from ..utils.journal import read_json_file
from .journal import JournalReader, JournalWriter, build_manifest, capture_base_dir, is_journal

RECORDING_META = "recording.json"


def validate_recording_id(recording_id: str) -> str:
    """One id check every path-resolving entry point shares: the agent's
    recording RPCs resolve `<base>/<id>` for ids a CLIENT sent, so a
    separator, a '..' component, or an absolute id would escape the
    capture area entirely (os.path.join discards the base on an absolute
    component). Raises ValueError; returns the id for chaining."""
    if (not recording_id
            or recording_id != os.path.basename(recording_id)
            or recording_id in (".", "..")):
        raise ValueError(f"bad recording id {recording_id!r}")
    return recording_id


class Recording:
    def __init__(self, recording_id: str, path: str, opts: dict):
        self.id = recording_id
        self.path = path
        self.opts = dict(opts)
        self.started_ts = time.time()
        self._writers: dict[str, JournalWriter] = {}   # journal key → writer
        self._mu = threading.Lock()

    def writer_for(self, *, node: str, gadget: str, run_id: str,
                   params: dict[str, str] | None = None) -> JournalWriter:
        """The (lazily-opened) journal for one recorded run."""
        key = f"{node or 'local'}--{run_id}"
        with self._mu:
            w = self._writers.get(key)
            if w is None:
                w = JournalWriter(
                    os.path.join(self.path, key),
                    manifest=build_manifest(
                        journal_id=f"{self.id}/{key}", node=node,
                        gadget=gadget, run_id=run_id, params=params,
                        extra={"recording_id": self.id}),
                    **{k: v for k, v in self.opts.items()
                       if k in ("max_segment_bytes", "max_segment_age",
                                "retention_bytes", "retention_segments")},
                )
                w.mark("recording-start", recording=self.id, node=node,
                       gadget=gadget, run_id=run_id)
                self._writers[key] = w
        return w

    def release(self, *, node: str, run_id: str) -> None:
        """A recorded run finished: seal and close its journal."""
        key = f"{node or 'local'}--{run_id}"
        with self._mu:
            w = self._writers.pop(key, None)
        if w is not None:
            w.mark("run-end", recording=self.id, run_id=run_id)
            w.close()

    def stop(self) -> dict:
        with self._mu:
            writers = list(self._writers.items())
            self._writers.clear()
        journals = {}
        for key, w in writers:
            w.mark("recording-stop", recording=self.id)
            journals[key] = w.close()
        meta = {
            "id": self.id,
            "started_ts": self.started_ts,
            "stopped_ts": time.time(),
            "journals": sorted(
                d for d in os.listdir(self.path)
                if os.path.isdir(os.path.join(self.path, d))),
            "opts": self.opts,
        }
        tmp = os.path.join(self.path, f"{RECORDING_META}.tmp.{os.getpid()}")
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(meta, f, sort_keys=True)
        os.replace(tmp, os.path.join(self.path, RECORDING_META))
        return meta

    def describe(self) -> dict:
        with self._mu:
            open_journals = {k: w.stats() for k, w in self._writers.items()}
        return {"id": self.id, "path": self.path, "state": "recording",
                "started_ts": self.started_ts,
                "open_journals": open_journals}


class RecordingManager:
    def __init__(self):
        self._mu = threading.Lock()
        self._active: dict[str, Recording] = {}
        self._base: str | None = None

    # -- configuration ------------------------------------------------------

    def set_base_dir(self, path: str | None) -> None:
        """Agent --capture-dir / test override of the default area."""
        with self._mu:
            self._base = path or None

    def base_dir(self) -> str:
        with self._mu:
            return capture_base_dir(self._base)

    # -- lifecycle ----------------------------------------------------------

    def start(self, recording_id: str, *, base_dir: str | None = None,
              **opts) -> Recording:
        path = self.recording_dir(validate_recording_id(recording_id),
                                  base_dir)
        with self._mu:
            if recording_id in self._active:
                raise ValueError(f"recording {recording_id!r} already active")
            if os.path.exists(os.path.join(path, RECORDING_META)):
                raise ValueError(
                    f"recording {recording_id!r} already exists at {path}")
            os.makedirs(path, exist_ok=True)
            rec = Recording(recording_id, path, opts)
            self._active[recording_id] = rec
        return rec

    def stop(self, recording_id: str) -> dict:
        with self._mu:
            rec = self._active.pop(recording_id, None)
        if rec is None:
            raise KeyError(f"recording {recording_id!r} is not active")
        return rec.stop()

    def stop_all(self) -> list[dict]:
        with self._mu:
            recs = list(self._active.values())
            self._active.clear()
        return [r.stop() for r in recs]

    def active(self) -> list[Recording]:
        with self._mu:
            return list(self._active.values())

    def get(self, recording_id: str) -> Recording | None:
        with self._mu:
            return self._active.get(recording_id)

    def recording_dir(self, recording_id: str,
                      base_dir: str | None = None) -> str:
        """Resolve `<base>/<id>` for a VALIDATED id — the RPC layer hands
        client-supplied ids straight here, so the check is not optional."""
        return os.path.join(base_dir or self.base_dir(),
                            validate_recording_id(recording_id))

    # -- inspection ---------------------------------------------------------

    def list(self, base_dir: str | None = None) -> list[dict]:
        """Active recordings plus finished ones found on disk."""
        out = [r.describe() for r in self.active()]
        seen = {r["id"] for r in out}
        base = base_dir or self.base_dir()
        if os.path.isdir(base):
            for name in sorted(os.listdir(base)):
                if name in seen:
                    continue
                meta, _err = read_json_file(
                    os.path.join(base, name, RECORDING_META))
                if meta is not None:
                    out.append({**meta, "path": os.path.join(base, name),
                                "state": "stopped"})
        return out

    def inspect(self, recording_id: str,
                base_dir: str | None = None) -> dict:
        """Per-journal stats of one (active or stopped) recording."""
        path = self.recording_dir(recording_id, base_dir)
        if not os.path.isdir(path):
            raise FileNotFoundError(f"no recording at {path}")
        journals = {}
        for name in sorted(os.listdir(path)):
            jpath = os.path.join(path, name)
            if is_journal(jpath):
                journals[name] = JournalReader(jpath).stats()
        meta, _err = read_json_file(os.path.join(path, RECORDING_META))
        state = ("recording" if self.get(recording_id) is not None
                 else "stopped" if meta is not None else "unknown")
        return {"id": recording_id, "path": path, "state": state,
                "meta": meta, "journals": journals}


# the process-wide singleton every capture operator instance consults
RECORDINGS = RecordingManager()

__all__ = ["RECORDINGS", "RECORDING_META", "Recording", "RecordingManager"]
