"""Environment doctor — the entrypoint's capability-probe role.

Reference contract: gadget-container/entrypoint.sh:21-120 detects the OS,
kernel, container runtime and BPF mount state before starting the daemon,
and picks the hook installation mechanism accordingly. This build has seven
heterogeneous capture windows instead of one BPF substrate, so the doctor
probes each window (fanotify, perf_event_open, /dev/kmsg, ptrace,
sock_diag, netlink proc-connector, AF_PACKET, mountinfo, procfs) and maps
every registered gadget to real / degraded / unavailable — run at agent
start (agent/main.py) and on demand via `ig-tpu doctor`.

Probes are cheap, side-effect-free, and never raise: each returns
(ok, detail) so a broken window degrades the report, not the process.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import socket

from .telemetry import gauge

# probed platform facts as registry gauges: scrapes and embedded snapshots
# (bench.py, cmd_doctor --output json) carry degraded/unavailable windows
# as data, not hand-assembled prose
_tm_window_ok = gauge("ig_doctor_window_ok",
                      "capture window probe result (1 ok, 0 down)",
                      ("window",))
_tm_gadget_status = gauge("ig_doctor_gadgets",
                          "registered gadgets per doctor status",
                          ("status",))


@dataclasses.dataclass
class Window:
    name: str
    ok: bool
    detail: str


# ---------------------------------------------------------------------------
# Window probes
# ---------------------------------------------------------------------------

def _probe_native_lib() -> Window:
    try:
        from .sources.bridge import native_available
        if native_available():
            return Window("native_lib", True, "libigcapture.so loaded")
        from .sources import bridge
        return Window("native_lib", False, bridge._lib_err or "build failed")
    except Exception as e:  # noqa: BLE001
        return Window("native_lib", False, repr(e))


def _probe_native_toolchain() -> Window:
    """Build-plane row (ISSUE 10 satellite): can this host COMPILE the
    native capture library from source? The tier-1 native-build smoke
    test (tests/test_native_build.py) keys off the same facts — a missing
    toolchain skips the build tier there and degrades this row here, so
    the skip is visible in the doctor instead of silent."""
    try:
        import shutil
        from pathlib import Path
        cxx = os.environ.get("CXX") or "g++"
        have_cxx = shutil.which(cxx)
        have_make = shutil.which("make")
        so = (Path(__file__).resolve().parent / "native"
              / "libigcapture.so")
        built = "lib built" if so.exists() else "lib not built yet"
        if have_cxx and have_make:
            return Window("native_toolchain", True,
                          f"{cxx}+make present ({built})")
        missing = " ".join(n for n, ok in ((cxx, have_cxx),
                                           ("make", have_make)) if not ok)
        return Window("native_toolchain", False,
                      f"missing {missing} — native-build smoke tier "
                      f"skips; a prebuilt .so still loads ({built})")
    except Exception as e:  # noqa: BLE001
        return Window("native_toolchain", False, repr(e))


def _probe_fanotify() -> Window:
    try:
        from .sources.bridge import _load
        lib = _load()
        if lib is None:
            return Window("fanotify", False, "native lib unavailable")
        ok = bool(lib.ig_fanotify_supported())
        return Window("fanotify", ok,
                      "fanotify_init ok" if ok else
                      "fanotify_init failed (needs CAP_SYS_ADMIN)")
    except Exception as e:  # noqa: BLE001
        return Window("fanotify", False, repr(e))


def _probe_perf() -> Window:
    try:
        from .sources.bridge import _load
        lib = _load()
        if lib is None:
            return Window("perf", False, "native lib unavailable")
        ok = bool(lib.ig_perf_supported())
        if ok:
            return Window("perf", True, "perf_event_open ok")
        para = "?"
        try:
            para = open("/proc/sys/kernel/perf_event_paranoid").read().strip()
        except OSError:
            pass
        return Window("perf", False,
                      f"perf_event_open failed (perf_event_paranoid={para})")
    except Exception as e:  # noqa: BLE001
        return Window("perf", False, repr(e))


def _probe_kmsg() -> Window:
    try:
        fd = os.open("/dev/kmsg", os.O_RDONLY | os.O_NONBLOCK)
        try:
            try:
                os.read(fd, 8192)
            except BlockingIOError:
                pass  # readable, just no backlog
        finally:
            os.close(fd)
        return Window("kmsg", True, "/dev/kmsg readable")
    except OSError as e:
        return Window("kmsg", False, f"/dev/kmsg: {e.strerror}")


def _probe_ptrace() -> Window:
    scope = "?"
    try:
        scope = open("/proc/sys/kernel/yama/ptrace_scope").read().strip()
    except OSError:
        scope = "absent"
    if os.geteuid() == 0 and scope != "3":
        return Window("ptrace", True, f"root, yama scope {scope}")
    if scope == "0":
        return Window("ptrace", True, f"yama scope 0 (same-uid attach)")
    return Window("ptrace", False,
                  f"euid {os.geteuid()}, yama scope {scope}")


def _probe_sock_diag() -> Window:
    NETLINK_SOCK_DIAG = 4
    try:
        s = socket.socket(socket.AF_NETLINK, socket.SOCK_RAW, NETLINK_SOCK_DIAG)
        s.close()
        return Window("sock_diag", True, "NETLINK_SOCK_DIAG socket ok")
    except OSError as e:
        return Window("sock_diag", False, f"netlink: {e.strerror}")


def _probe_netlink_proc() -> Window:
    # proc connector needs CAP_NET_ADMIN to bind the CN_IDX_PROC group
    NETLINK_CONNECTOR = 11
    CN_IDX_PROC = 1
    try:
        s = socket.socket(socket.AF_NETLINK, socket.SOCK_DGRAM,
                          NETLINK_CONNECTOR)
        try:
            # nl_pid 0: kernel auto-assigns a free port — binding the
            # literal pid collides (EADDRINUSE) when this process already
            # holds a proc-connector socket (agent with a live exec source)
            s.bind((0, CN_IDX_PROC))
        finally:
            s.close()
        return Window("netlink_proc", True, "proc connector bind ok")
    except OSError as e:
        return Window("netlink_proc", False, f"proc connector: {e.strerror}")


def _probe_af_packet() -> Window:
    try:
        s = socket.socket(socket.AF_PACKET, socket.SOCK_RAW, 0)
        s.close()
        return Window("af_packet", True, "raw packet socket ok")
    except OSError as e:
        return Window("af_packet", False,
                      f"AF_PACKET: {e.strerror} (needs CAP_NET_RAW)")


def _probe_audit() -> Window:
    # host-wide audit window: NETLINK_AUDIT + READLOG multicast
    # (CAP_AUDIT_READ; kernel >= 3.16)
    try:
        from .sources.bridge import audit_supported
        ok = audit_supported()
        return Window("audit", ok,
                      "NETLINK_AUDIT readlog multicast ok" if ok else
                      "audit readlog unavailable (needs CAP_AUDIT_READ)")
    except Exception as e:  # noqa: BLE001
        return Window("audit", False, repr(e))


def _probe_captrace() -> Window:
    # cap_capable tracepoint (tracefs, kernel >= 6.7) — capable.bpf.c's
    # exact hook point, no BPF
    try:
        from .sources.bridge import captrace_supported
        ok = captrace_supported()
        return Window("captrace", ok,
                      "cap_capable tracepoint ok" if ok else
                      "cap_capable tracepoint unavailable "
                      "(tracefs or kernel < 6.7)")
    except Exception as e:  # noqa: BLE001
        return Window("captrace", False, repr(e))


def _probe_sockstate() -> Window:
    # inet_sock_set_state tracepoint — event-driven trace/tcp
    try:
        from .sources.bridge import sockstate_supported
        ok = sockstate_supported()
        return Window("sockstate", ok,
                      "inet_sock_set_state tracepoint ok" if ok else
                      "inet_sock_set_state unavailable (tracefs)")
    except Exception as e:  # noqa: BLE001
        return Window("sockstate", False, repr(e))


def _probe_sigtrace() -> Window:
    # signal_generate tracepoint — full sigsnoop parity
    try:
        from .sources.bridge import sigtrace_supported
        ok = sigtrace_supported()
        return Window("sigtrace", ok,
                      "signal_generate tracepoint ok" if ok else
                      "signal_generate unavailable (tracefs)")
    except Exception as e:  # noqa: BLE001
        return Window("sigtrace", False, repr(e))


def _probe_fstrace() -> Window:
    # raw_syscalls tracepoints with in-kernel id filter (host-wide fsslower)
    try:
        from .sources.bridge import fstrace_supported
        ok = fstrace_supported()
        return Window("fstrace", ok,
                      "raw_syscalls tracepoints ok" if ok else
                      "raw_syscalls tracepoints unavailable (tracefs)")
    except Exception as e:  # noqa: BLE001
        return Window("fstrace", False, repr(e))


def _probe_tcpinfo() -> Window:
    # top/tcp byte counters: sock_diag ext INET_DIAG_INFO (kernel >= 4.1)
    try:
        from .sources.bridge import tcpinfo_supported
        ok = tcpinfo_supported()
        return Window("tcpinfo", ok,
                      "sock_diag INET_DIAG_INFO byte counters ok" if ok else
                      "INET_DIAG_INFO dump failed (kernel < 4.1?)")
    except Exception as e:  # noqa: BLE001
        return Window("tcpinfo", False, repr(e))


def _probe_blktrace() -> Window:
    try:
        from .sources.bridge import blktrace_supported
        ok = blktrace_supported()
        return Window("blktrace", ok,
                      "tracefs block events readable" if ok else
                      "tracefs block events unavailable (mount tracefs)")
    except Exception as e:  # noqa: BLE001
        return Window("blktrace", False, repr(e))


def _probe_container_runtime() -> Window:
    """Runtime-availability row: can the container discovery/enrichment
    chain reach a real runtime (docker / containerd / CRI)? The real-
    runtime integration tier (tests/test_real_runtime.py) keys off the
    same sockets this probe checks."""
    try:
        from .containers.runtime_client import detect_runtime_client
        client = detect_runtime_client()
        if client is None:
            return Window("container_runtime", False,
                          "no runtime reachable (docker/containerd/CRI "
                          "sockets absent)")
        name = type(client).__name__.removesuffix("Client").lower()
        closer = getattr(client, "close", None)
        if closer is not None:
            closer()
        return Window("container_runtime", True, f"{name} reachable")
    except Exception as e:  # noqa: BLE001
        return Window("container_runtime", False, repr(e))


def _probe_capture_dir() -> Window:
    """Capture-plane row: is the recording area writable, and how much
    does it already hold? A node that cannot journal loses its replay
    evidence exactly when an incident makes it wanted."""
    try:
        import tempfile

        from .capture import capture_base_dir
        from .capture.journal import dir_stats
        base = capture_base_dir()
        os.makedirs(base, exist_ok=True)
        with tempfile.NamedTemporaryFile(dir=base, prefix=".doctor-"):
            pass
        segments, usage = dir_stats(base)
        try:
            st = os.statvfs(base)
            free = st.f_bavail * st.f_frsize
            free_s = f", {free / (1 << 30):.1f} GiB free"
        except OSError:
            free_s = ""
        return Window("capture_dir", True,
                      f"{base} writable ({usage / (1 << 20):.1f} MiB in "
                      f"{segments} segment(s){free_s})")
    except OSError as e:
        return Window("capture_dir", False,
                      f"capture dir unwritable: {e.strerror or e}")
    except Exception as e:  # noqa: BLE001
        return Window("capture_dir", False, repr(e))


def _probe_history_dir() -> Window:
    """History-plane row: is the sealed-window store area writable, and
    how much does it already hold? A node that cannot seal windows
    answers live queries only — the 2pm incident stays unanswerable at
    3pm, which is exactly what the history plane exists to fix."""
    try:
        import tempfile

        from .capture.journal import dir_stats
        from .history import history_base_dir
        base = history_base_dir()
        os.makedirs(base, exist_ok=True)
        with tempfile.NamedTemporaryFile(dir=base, prefix=".doctor-"):
            pass
        segments, usage = dir_stats(base)
        try:
            st = os.statvfs(base)
            free = st.f_bavail * st.f_frsize
            free_s = f", {free / (1 << 30):.1f} GiB free"
        except OSError:
            free_s = ""
        return Window("history_dir", True,
                      f"{base} writable ({usage / (1 << 20):.1f} MiB in "
                      f"{segments} segment(s){free_s})")
    except OSError as e:
        return Window("history_dir", False,
                      f"history dir unwritable: {e.strerror or e}")
    except Exception as e:  # noqa: BLE001
        return Window("history_dir", False, repr(e))


def _probe_history_tiers() -> Window:
    """Tier-plane row: how the node's history footprint is distributed
    across compaction levels and the archive tier. An empty store is
    fine (nothing sealed yet); the row fails only when the tier walk
    itself breaks — a store you cannot account is a retention policy
    you cannot trust."""
    try:
        from .history import HISTORY
        tiers = HISTORY.tier_stats()
        levels = tiers.get("levels") or {}
        if not tiers.get("stores"):
            return Window("history_tiers", True,
                          "no history stores yet (nothing sealed)")
        lvl_s = ", ".join(
            f"L{lvl}: {row['windows']}w/{row['bytes'] / (1 << 20):.1f}MiB"
            for lvl, row in levels.items()) or "no windows"
        arch = tiers.get("archived") or {}
        detail = (f"{tiers['stores']} store(s), {lvl_s}")
        if arch.get("segments"):
            cache = tiers.get("archive_cache") or {}
            detail += (f"; archived {arch['segments']} segment(s)/"
                       f"{arch['bytes'] / (1 << 20):.1f}MiB "
                       f"(cache {cache.get('hits', 0)}h/"
                       f"{cache.get('misses', 0)}m)")
        return Window("history_tiers", True, detail)
    except Exception as e:  # noqa: BLE001
        return Window("history_tiers", False, repr(e))


def _probe_standing_queries() -> Window:
    """Standing-query-plane row: which continuous queries are live in
    this process, how fresh their materialized answers are, and whether
    the result cache is earning its bytes. No registered queries is
    fine (the plane is opt-in); the row fails only when reading the
    live registry itself breaks."""
    try:
        from .queries import live_stats
        rows = live_stats()
        if not rows:
            return Window("standing_queries", True,
                          "no standing queries registered (opt-in via "
                          "the 'standing-queries' param)")
        cache = rows[0].get("cache") or {}
        per_q = ", ".join(
            f"{r['id']}: {r['windows']}w/{r['range_s']:g}s "
            f"({r['refreshed']} refreshes)"
            for r in rows)
        detail = (f"{len(rows)} quer{'y' if len(rows) == 1 else 'ies'} — "
                  f"{per_q}; cache {cache.get('hits', 0)}h/"
                  f"{cache.get('misses', 0)}m/"
                  f"{cache.get('invalidations', 0)}i, "
                  f"{cache.get('bytes', 0) / (1 << 10):.1f}KiB")
        return Window("standing_queries", True, detail)
    except Exception as e:  # noqa: BLE001
        return Window("standing_queries", False, repr(e))


def _probe_each_agent(probe_one):
    """The shared skeleton of the fleet-facing doctor rows: probe every
    locally-registered agent concurrently under a bounded deadline (the
    row costs one deadline, not one per agent) with per-node isolation.
    Returns (targets, [(node, result, error)])."""
    from .cli.deploy import local_targets
    targets = local_targets()
    if not targets:
        return targets, []
    from concurrent.futures import ThreadPoolExecutor

    from .agent.client import AgentClient

    def probe(item):
        node, target = item
        client = None
        try:
            client = AgentClient(target, node, rpc_deadline=2.0)
            return node, probe_one(client), None
        except Exception as e:  # noqa: BLE001 — per-node isolation
            return node, None, str(e)
        finally:
            if client is not None:
                client.close()

    with ThreadPoolExecutor(max_workers=min(len(targets), 16)) as ex:
        return targets, list(ex.map(probe, targets.items()))


def _probe_fleet_health() -> Window:
    """Fleet-plane row: are the locally-registered agents (deploy
    --local) reachable under a bounded deadline? No local fleet is fine
    — single-node mode — but a registered agent that doesn't answer is
    exactly the kind of silent rot the chaos runtime exists to surface
    (`ig-tpu fleet health` gives the per-run detail)."""
    try:
        targets, probed = _probe_each_agent(
            lambda c: c.get_catalog(use_cache_on_error=False))
        if not targets:
            return Window("fleet_health", True,
                          "no local fleet registered (single-node mode)")
        down = sorted(n for n, _res, err in probed if err)
        if down:
            return Window("fleet_health", False,
                          f"{len(down)}/{len(targets)} agent(s) "
                          f"unreachable: {', '.join(down)} "
                          f"(expected during fleet bring-up)")
        return Window("fleet_health", True,
                      f"{len(targets)} local agent(s) reachable")
    except Exception as e:  # noqa: BLE001
        return Window("fleet_health", False, repr(e))


def _probe_shared_runs() -> Window:
    """Shared-run plane row: how many shared gadget runs and live
    subscribers the local fleet is serving, and whether any subscriber
    is being shed (drops/evictions). No fleet (or no shared runs) is
    fine; an unreadable agent fails the row — an overloaded node you
    cannot see is the outage in waiting (`ig-tpu fleet runs` gives the
    per-run detail)."""
    try:
        targets, probed = _probe_each_agent(lambda c: c.shared_runs())
        if not targets:
            return Window("shared_runs", True,
                          "no local fleet registered (single-node mode)")
        down = sorted(n for n, _res, err in probed if err)
        if down:
            return Window("shared_runs", False,
                          f"{len(down)}/{len(targets)} agent(s) "
                          f"unreadable: {', '.join(down)}")
        runs = [r for _n, rows, _e in probed for r in rows or []]
        subs = sum(r.get("live_subscribers", 0) for r in runs)
        drops = sum(s.get("drops", 0) for r in runs
                    for s in (r.get("subscribers") or []))
        evicted = sum(1 for r in runs
                      for s in (r.get("subscribers") or [])
                      if s.get("evicted"))
        detail = (f"{len(runs)} shared run(s), {subs} live "
                  f"subscriber(s) across {len(targets)} agent(s)")
        if drops or evicted:
            detail += (f"; shedding: {drops} drop(s), {evicted} "
                       f"eviction(s) — see `ig-tpu fleet runs`")
        return Window("shared_runs", True, detail)
    except Exception as e:  # noqa: BLE001
        return Window("shared_runs", False, repr(e))


def _probe_device_topology() -> Window:
    """Device-plane topology row (ISSUE 14): how many local chips the
    sharded ingest plane can lane across, the (node) mesh shape it would
    build, and whether `shard-ingest` is eligible (>= 2 devices).
    Enumerating devices initializes the jax backend, so this probe only
    READS a backend some other plane already paid to bring up — the
    doctor must never be the thing that hangs on TPU acquisition (that
    is the platform probe's bounded job). Merely having the jax MODULE
    imported is not enough (the CLI imports it loading the operator
    registry, long before any backend touch), so the gate is the
    xla_bridge backend cache itself."""
    try:
        import sys
        initialized = False
        if "jax" in sys.modules:
            try:
                from jax._src import xla_bridge
                initialized = bool(getattr(xla_bridge, "_backends", None))
            except Exception:  # lint: allow-silent-except — internal-API probe; an unknown jax layout just reads as "not initialized", the safe answer
                initialized = False
        if not initialized:
            return Window("device_topology", True,
                          "jax backend not initialized in this process — "
                          "topology unprobed (run a gadget or bench "
                          "first)")
        import jax
        devs = jax.local_devices()
        n = len(devs)
        plat = devs[0].platform if devs else "none"
        eligible = ("shard-ingest eligible" if n >= 2
                    else "shard-ingest needs >= 2 devices")
        return Window("device_topology", True,
                      f"{n} local {plat} device(s), ingest mesh "
                      f"(node={n}); {eligible}")
    except Exception as e:  # noqa: BLE001
        return Window("device_topology", False, repr(e))


def _probe_pipeline_health() -> Window:
    """Pipeline-health-plane row (ISSUE 18): which gadget runs in this
    process carry live per-stage lag accounting, their worst-stage lag
    watermark, and the starved ratio (1.0 = host-bound, the BENCH_r04
    regime; 0.0 = device-bound). No live runs is fine — the plane rides
    every tpusketch run automatically, so an idle process simply has
    nothing to report; the row fails only when reading the registry
    breaks (`ig-tpu fleet lag` gives the per-node detail)."""
    try:
        from .telemetry.pipeline import live_stats
        rows = live_stats()
        if not rows:
            return Window("pipeline_health", True,
                          "no live instrumented runs (the plane rides "
                          "every tpusketch run)")
        per_run = []
        for ps in rows:
            snap = ps.snapshot()
            worst = max((r["watermark_s"]
                         for r in snap["stages"].values()), default=0.0)
            per_run.append(
                f"{ps.run_id[:8]}: lag {worst * 1e3:.1f}ms, "
                f"starved {snap['starved_ratio'] * 100:.0f}%")
        return Window("pipeline_health", True,
                      f"{len(rows)} instrumented run(s) — "
                      + ", ".join(per_run))
    except Exception as e:  # noqa: BLE001
        return Window("pipeline_health", False, repr(e))


def _probe_accuracy() -> Window:
    """Accuracy-audit-plane row (ISSUE 19): which gadget runs in this
    process carry a live shadow-sample audit, their sample fill, and the
    worst observed-error/analytic-bound ratio (> 1.0 means an estimate
    drifted past its envelope — the accuracy_drift alert's trigger).
    No audited runs is fine — the plane is opt-in (audit-sample > 0);
    analytic bounds still ride every answer. The row fails only when
    reading the registry breaks (`ig-tpu fleet accuracy` has detail)."""
    try:
        from .ops.accuracy import live_stats
        rows = live_stats()
        if not rows:
            return Window("accuracy", True,
                          "no audited runs (audit plane is opt-in: "
                          "audit-sample > 0; analytic bounds always ride "
                          "answers)")
        per_run = []
        for a in rows:
            snap = a.snapshot()
            per_run.append(
                f"{a.run_id[:8]}: sample {snap['sample_size']}, "
                f"fed {snap['samples_fed']}, ratio {snap['ratio']:.2f}")
        return Window("accuracy", True,
                      f"{len(rows)} audited run(s) — " + ", ".join(per_run))
    except Exception as e:  # noqa: BLE001
        return Window("accuracy", False, repr(e))


def _probe_fleet_topology() -> Window:
    """Fleet-aggregation-tier row (ISSUE 20): can this process build a
    merge tree over the deployed fleet, and what shape would it fold
    through — leaves, depth, fan-in, and the wire frames one merged
    query costs vs the flat fold. No deployed fleet is fine (the tier
    is a query-time choice); the row fails only when the deploy state
    names agents the topology builder refuses (the loud TopologyError
    an `ig-tpu query --topology` would hit)."""
    try:
        from .cli.deploy import local_targets
        from .fleet import auto_topology
        targets = local_targets()
        if not targets:
            return Window("fleet_topology", True,
                          "no deployed fleet (topology is a query-time "
                          "choice: ig-tpu query --topology auto)")
        topo = auto_topology(list(targets))
        return Window(
            "fleet_topology", True,
            f"{len(topo.leaves())} agent(s) → depth {topo.depth()}, "
            f"fan-in {topo.fan_in()}, {len(topo.aggregators())} "
            f"aggregator(s); {topo.edges() + 1} window frame(s)/query "
            f"vs {len(topo.leaves())} flat")
    except Exception as e:  # noqa: BLE001
        return Window("fleet_topology", False, repr(e))


def _probe_mountinfo() -> Window:
    try:
        with open("/proc/self/mountinfo") as f:
            f.readline()
        return Window("mountinfo", True, "/proc/self/mountinfo readable")
    except OSError as e:
        return Window("mountinfo", False, f"mountinfo: {e.strerror}")


def _probe_procfs() -> Window:
    try:
        os.listdir("/proc")
        with open("/proc/self/stat"):
            pass
        return Window("procfs", True, "/proc readable")
    except OSError as e:
        return Window("procfs", False, f"/proc: {e.strerror}")


_PROBES = (
    _probe_native_lib, _probe_native_toolchain, _probe_fanotify,
    _probe_perf, _probe_kmsg,
    _probe_ptrace, _probe_sock_diag, _probe_netlink_proc, _probe_af_packet,
    _probe_mountinfo, _probe_procfs, _probe_blktrace, _probe_tcpinfo,
    _probe_audit, _probe_captrace, _probe_fstrace, _probe_sockstate,
    _probe_sigtrace, _probe_container_runtime, _probe_capture_dir,
    _probe_history_dir, _probe_history_tiers, _probe_standing_queries,
    _probe_fleet_health, _probe_shared_runs, _probe_device_topology,
    _probe_pipeline_health, _probe_accuracy, _probe_fleet_topology,
)


def probe_windows() -> dict[str, Window]:
    """Probe every capture window once; returns {name: Window}."""
    out: dict[str, Window] = {}
    for probe in _PROBES:
        w = probe()
        out[w.name] = w
        _tm_window_ok.labels(window=w.name).set(1.0 if w.ok else 0.0)
    return out


# ---------------------------------------------------------------------------
# Per-gadget status
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class GadgetStatus:
    category: str
    name: str
    status: str          # real | degraded | unavailable | synthetic-only
    window: str          # primary window name ("" for synthetic-only)
    note: str


def _source_windows() -> dict[int, tuple[str, str, str]]:
    """native_kind → (primary window, degraded-fallback window, note)."""
    from .sources import bridge as B
    return {
        B.SRC_PROC_EXEC: ("netlink_proc", "", ""),
        B.SRC_PROC_TCP: ("procfs", "", ""),
        B.SRC_FANOTIFY_EXEC: ("fanotify", "", ""),
        B.SRC_FANOTIFY_OPEN: ("fanotify", "", ""),
        B.SRC_FANOTIFY_RUNC: ("fanotify", "", ""),
        B.SRC_MOUNTINFO: ("mountinfo", "", ""),
        B.SRC_SOCK_DIAG: ("sock_diag", "procfs", "procfs scan fallback"),
        B.SRC_KMSG_OOM: ("kmsg", "", ""),
        B.SRC_PTRACE: ("ptrace", "", "needs --command/--pid or container filter"),
        B.SRC_PERF_CPU: ("perf", "procfs", "procfs stat-delta fallback"),
        B.SRC_PKT_DNS: ("af_packet", "", ""),
        B.SRC_PKT_SNI: ("af_packet", "", ""),
        B.SRC_PKT_FLOW: ("af_packet", "", ""),
    }


# Gadgets that don't route through SourceTraceGadget.native_kind (procfs
# drain loops, the perf sampler, self-observation) declare their windows
# here: (primary window, degraded fallback, note).
_GADGET_WINDOWS: dict[tuple[str, str], tuple[str, str, str]] = {
    ("profile", "cpu"): ("perf", "procfs",
                         "49Hz callchains; procfs stat-delta fallback"),
    ("profile", "block-io"): ("blktrace", "procfs",
                              "per-IO tracefs latency; diskstats fallback"),
    ("top", "file"): ("fanotify", "procfs",
                      "per-(pid,file) fanotify rows with filenames; "
                      "per-process /proc/<pid>/io fallback"),
    ("top", "tcp"): ("tcpinfo", "procfs",
                     "per-connection INET_DIAG_INFO byte deltas; "
                     "connection-churn fallback"),
    ("top", "block-io"): ("procfs", "", "/proc/diskstats deltas"),
    ("top", "sketch"): ("native_lib", "", "capture-plane self-observation"),
    ("top", "recordings"): ("capture_dir", "",
                            "recording lifecycle + journal disk usage"),
    ("top", "windows"): ("history_dir", "",
                         "sealed-window store contents + freshness"),
    ("top", "self"): ("native_lib", "", "native source self-stats"),
    ("snapshot", "process"): ("procfs", "", "procfs collector"),
    ("snapshot", "socket"): ("procfs", "", "procfs collector"),
    ("advise", "network-policy"): ("af_packet", "",
                                   "synthesizes from trace/network events"),
    # host-wide audit windows with the ptrace per-target flavour as the
    # labeled fallback (ref: capable.bpf.c / audit-seccomp.bpf.c are
    # system-wide kprobes)
    ("trace", "capabilities"): ("captrace", "audit|ptrace",
                                "cap_capable tracepoint (every check, "
                                "allow+deny verdicts); audit EPERM-rule "
                                "fallback is denial-only; ptrace flavour "
                                "per-target"),
    ("audit", "seccomp"): ("audit", "ptrace",
                           "host-wide AUDIT_SECCOMP records; ptrace "
                           "per-target flavour also sees RET_ERRNO"),
    ("trace", "fsslower"): ("fstrace", "ptrace",
                            "host-wide raw_syscalls entry/exit latency "
                            "with in-kernel fs-syscall filter; ptrace "
                            "flavour per-target"),
    ("trace", "tcp"): ("sockstate", "procfs",
                       "event-driven inet_sock_set_state transitions "
                       "(no scan window); /proc diff scanner fallback"),
    ("trace", "tcpconnect"): ("sockstate", "procfs",
                              "connect-only view of the state-transition "
                              "stream; /proc diff scanner fallback"),
    ("trace", "signal"): ("sigtrace", "netlink_proc",
                          "signal_generate tracepoint (every signal, "
                          "sender+target); netlink-exit fatal-signal "
                          "fallback; ptrace flavour per-target"),
}


def gadget_report(windows: dict[str, Window] | None = None) -> list[GadgetStatus]:
    """Status of every registered gadget given the probed windows."""
    from . import all_gadgets  # noqa: F401 — ensure registry is populated
    from .gadgets import registry as gadget_registry

    if windows is None:
        windows = probe_windows()
    native_ok = windows["native_lib"].ok
    src_map = _source_windows()
    out: list[GadgetStatus] = []

    for desc in gadget_registry.get_all():
        # interrogate the gadget class for its native source kind without
        # instantiating a run: new_instance needs a context, so read the
        # class attribute off a probe instance when cheap, else the class
        g_cls = _gadget_class(desc)
        native_kind = getattr(g_cls, "native_kind", None) if g_cls else None
        # the explicit table wins over the class source kind: gadgets that
        # pick their window at runtime (audit vs ptrace) declare both here
        if (desc.category, desc.name) in _GADGET_WINDOWS:
            window, fallback, note = _GADGET_WINDOWS[desc.category, desc.name]
            if native_kind is not None and not native_ok:
                # both flavours run through the capture library; a probe-ok
                # kernel window doesn't help if the lib can't load
                out.append(GadgetStatus(desc.category, desc.name,
                                        "unavailable", window,
                                        windows["native_lib"].detail))
            elif windows.get(window) and windows[window].ok:
                out.append(GadgetStatus(desc.category, desc.name, "real",
                                        window, note))
            else:
                # "a|b" fallback chains: first probing-ok window wins
                fb_ok = next((f for f in fallback.split("|")
                              if f and windows.get(f) and windows[f].ok),
                             "") if fallback else ""
                if fb_ok:
                    out.append(GadgetStatus(
                        desc.category, desc.name, "degraded", fb_ok,
                        f"{window} unavailable "
                        f"({windows[window].detail}); {note}"))
                else:
                    out.append(GadgetStatus(desc.category, desc.name,
                                            "unavailable", window,
                                            windows[window].detail))
            continue
        if native_kind is None:
            out.append(GadgetStatus(desc.category, desc.name, "synthetic-only",
                                    "", "no native window for this gadget"))
            continue
        window, fallback, note = src_map.get(native_kind, ("", "", ""))
        if not native_ok:
            out.append(GadgetStatus(desc.category, desc.name, "unavailable",
                                    window, windows["native_lib"].detail))
            continue
        if window and windows.get(window) and windows[window].ok:
            out.append(GadgetStatus(desc.category, desc.name, "real",
                                    window, note))
        elif fallback and windows.get(fallback) and windows[fallback].ok:
            out.append(GadgetStatus(
                desc.category, desc.name, "degraded", fallback,
                f"{window} unavailable ({windows[window].detail}); {note}"))
        else:
            detail = windows[window].detail if window in windows else "unknown"
            out.append(GadgetStatus(desc.category, desc.name, "unavailable",
                                    window, detail))
    out.sort(key=lambda g: (g.category, g.name))
    counts: dict[str, int] = {}
    for g in out:
        counts[g.status] = counts.get(g.status, 0) + 1
    for status in ("real", "degraded", "unavailable", "synthetic-only"):
        _tm_gadget_status.labels(status=status).set(counts.get(status, 0))
    return out


def _gadget_class(desc):
    """Best-effort extraction of the gadget implementation class from a
    descriptor's new_instance closure (gadget classes carry native_kind as
    a class attribute; descriptors don't)."""
    fn = getattr(desc, "new_instance", None)
    if fn is None:
        return None
    func = getattr(fn, "__func__", fn)
    # _register-built descs close over gadget_cls; hand-written descs
    # reference the class in code constants or globals
    closure = getattr(func, "__closure__", None)
    if closure:
        for cell in closure:
            v = cell.cell_contents
            if isinstance(v, type):
                return v
    import inspect
    try:
        src_names = func.__code__.co_names
        module = inspect.getmodule(func)
        for nm in src_names:
            v = getattr(module, nm, None)
            if isinstance(v, type) and hasattr(v, "native_kind"):
                return v
    except Exception as e:  # noqa: BLE001
        logging.getLogger("ig-tpu.doctor").debug(
            "gadget class extraction failed for %s: %r", desc.name, e)
    return None


# ---------------------------------------------------------------------------
# Rendering
# ---------------------------------------------------------------------------

def render_report(windows: dict[str, Window] | None = None,
                  gadgets: list[GadgetStatus] | None = None) -> str:
    if windows is None:
        windows = probe_windows()
    if gadgets is None:
        gadgets = gadget_report(windows)
    lines = ["CAPTURE WINDOWS"]
    for w in windows.values():
        mark = "ok " if w.ok else "NO "
        lines.append(f"  {mark} {w.name:<14s} {w.detail}")
    lines.append("")
    lines.append("GADGETS")
    for g in gadgets:
        label = f"{g.category}/{g.name}"
        lines.append(f"  {g.status:<15s} {label:<28s} "
                     f"{g.window:<13s} {g.note}")
    counts: dict[str, int] = {}
    for g in gadgets:
        counts[g.status] = counts.get(g.status, 0) + 1
    lines.append("")
    # device-plane acquisition outcome (set by acquire_platform — the
    # agent probes at startup; standalone doctor shows "unprobed")
    from .utils.platform_probe import last_acquire
    acq = last_acquire()
    if acq is not None:
        mark = "degraded " if acq["degraded"] else ""
        lines.append(f"PLATFORM {mark}{acq['platform']} ({acq['detail']})")
    else:
        lines.append("PLATFORM unprobed (agents probe at startup; "
                     "see --platform)")
    lines.append("")
    lines.append("SUMMARY " + "  ".join(
        f"{k}={v}" for k, v in sorted(counts.items())))
    return "\n".join(lines)
