"""Declarative JSON event matching for black-box tests.

Ref: integration/helpers.go — parseMultiJSONOutput:31, parseJSONArrayOutput:53,
ExpectEntriesToMatch:150 (each expected entry must appear among the parsed,
normalized entries), ExpectEntriesInArrayToMatch:160 (line-per-array form used
by interval gadgets), BuildCommonData:178. Normalization zeroes fields the
test cannot predict (pids, timestamps, node names) so exact-equality
subset matching works.
"""

from __future__ import annotations

import json
from typing import Callable, Iterable


Normalize = Callable[[dict], None]


def parse_multi_json(output: str, normalize: Normalize | None = None) -> list[dict]:
    """One JSON object per line (streaming gadget `-o json` output)."""
    entries = []
    for line in output.splitlines():
        line = line.strip()
        if not line:
            continue
        entry = json.loads(line)
        if normalize is not None:
            normalize(entry)
        entries.append(entry)
    return entries


def parse_json_array(output: str, normalize: Normalize | None = None) -> list[dict]:
    """A single JSON array (one-shot snapshot gadgets), or one array per
    line (interval gadgets re-emitting each tick)."""
    output = output.strip()
    entries: list[dict] = []
    if output.startswith("["):
        arrays = [json.loads(ln) for ln in output.splitlines() if ln.strip()]
    else:
        arrays = [json.loads(output)]
    for arr in arrays:
        for entry in arr:
            if normalize is not None:
                normalize(entry)
            entries.append(entry)
    return entries


def _subset_match(expected: dict, got: dict) -> bool:
    return all(got.get(k) == v for k, v in expected.items())


def _expect(entries: list[dict], expected: Iterable[dict]) -> None:
    for exp in expected:
        if not any(_subset_match(exp, e) for e in entries):
            sample = json.dumps(entries[:5], indent=1, default=str)
            raise AssertionError(
                f"no entry matches {json.dumps(exp, default=str)};\n"
                f"got {len(entries)} entries, first 5:\n{sample}")


def expect_entries_to_match(output: str, normalize: Normalize | None,
                            *expected: dict) -> None:
    """Every expected entry appears in the line-per-event output."""
    _expect(parse_multi_json(output, normalize), expected)


def expect_entries_in_array_to_match(output: str, normalize: Normalize | None,
                                     *expected: dict) -> None:
    """Every expected entry appears in the JSON-array output."""
    _expect(parse_json_array(output, normalize), expected)


def expect_all_entries_to_match(output: str, normalize: Normalize | None,
                                expected: dict) -> None:
    """Every emitted entry matches the expected subset (negative-filter
    tests: e.g. everything carries the requested container name)."""
    entries = parse_multi_json(output, normalize)
    if not entries:
        raise AssertionError("no entries emitted")
    for e in entries:
        if not _subset_match(expected, e):
            raise AssertionError(
                f"entry {json.dumps(e, default=str)} does not match "
                f"{json.dumps(expected, default=str)}")


def build_common_data(node: str = "", namespace: str = "",
                      pod: str = "", container: str = "") -> dict:
    """CommonData subset for expectations (ref: helpers.go:178-189,
    pkg/types/types.go:73-120)."""
    d: dict = {}
    if node:
        d["node"] = node
    if namespace:
        d["namespace"] = namespace
    if pod:
        d["pod"] = pod
    if container:
        d["container"] = container
    return d
