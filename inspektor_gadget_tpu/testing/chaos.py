"""Fault injection for fleet chaos tests.

Three chaos primitives the soak tier composes (ROADMAP: "chaos-hardened
100-node soak"; the container black-box and alerts e2e tiers both caught
real bugs — this tier exists to catch the distributed ones):

  - ChaosProxy: a TCP relay between an AgentClient and its agent with
    injectable faults — connection cut (close every live connection
    once; new ones pass), latency/slow-drip (per-chunk delay), and
    partition with heal (refuse or blackhole new connections AND kill
    live ones until heal()). The client dials the proxy's listen
    address; the proxy dials the real agent (tcp host:port or a unix
    socket path), so no agent code knows it is being tortured.
  - AgentProcess: a real `ig-tpu-agent serve` subprocess with SIGKILL /
    respawn — the crash-restart driver. Respawning reuses the same
    listen address and state dirs, so a resume attempt against the new
    process exercises the unknown-run → backfill-and-restart path.
  - SkewClock: an injectable monotonic clock with a settable offset, for
    testing that health/straggler logic tolerates clock skew.
  - SubscriberChurn: attach/hold/detach cycles against one SHARED gadget
    run (some rounds leaving by proxy cut) — dashboard-client churn as a
    first-class fault for the shared-run multiplexing plane.

Nothing here is test-framework-specific: `ig-tpu` users can point the
proxy at a production agent to rehearse failure drills.
"""

from __future__ import annotations

import logging
import os
import signal
import socket
import subprocess
import sys
import threading
import time

log = logging.getLogger("ig-tpu.chaos")

_CHUNK = 65536


class ChaosProxy:
    """TCP proxy with injectable faults between a client and one agent.

    backend: "host:port" or a unix socket path ("/tmp/x.sock" or
    "unix:///tmp/x.sock"). Counters (connections_total, cuts_total,
    bytes_up/bytes_down) let tests assert the faults actually happened.
    """

    def __init__(self, backend: str, listen_host: str = "127.0.0.1"):
        self.backend = backend
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((listen_host, 0))
        self._listener.listen(16)
        self.listen_host, self.listen_port = self._listener.getsockname()
        self._mu = threading.Lock()
        self._conns: list[tuple[socket.socket, socket.socket | None]] = []
        self._closing = False
        self._partitioned: str | None = None  # None | "refuse" | "blackhole"
        self.latency = 0.0
        self.connections_total = 0
        self.refused_total = 0
        self.cuts_total = 0
        self.bytes_up = 0
        self.bytes_down = 0
        threading.Thread(target=self._accept_loop, daemon=True).start()

    @property
    def target(self) -> str:
        """The grpc target clients should dial."""
        return f"{self.listen_host}:{self.listen_port}"

    # -- fault controls -----------------------------------------------------

    def cut(self) -> None:
        """Sever every live connection once; new connections pass."""
        with self._mu:
            conns, self._conns = self._conns, []
            self.cuts_total += 1
        for pair in conns:
            self._close_pair(pair)

    def partition(self, mode: str = "refuse") -> None:
        """Isolate the agent until heal(): live connections die now;
        new ones are refused (fails fast — connection reset) or
        blackholed (accepted, never relayed — the connect 'succeeds'
        but gRPC channel readiness never does, exercising the
        per-attempt deadline)."""
        if mode not in ("refuse", "blackhole"):
            raise ValueError(f"unknown partition mode {mode!r}")
        with self._mu:
            self._partitioned = mode
        self.cut()

    def heal(self) -> None:
        """End the partition and clear injected latency."""
        with self._mu:
            self._partitioned = None
            self.latency = 0.0

    def set_latency(self, seconds: float) -> None:
        """Delay every relayed chunk (slow node, not a dead one)."""
        with self._mu:
            self.latency = max(0.0, float(seconds))

    # -- plumbing -----------------------------------------------------------

    def _dial_backend(self) -> socket.socket:
        b = self.backend
        if b.startswith("unix://"):
            b = b[len("unix://"):]
        if b.startswith("/") or b.startswith("@"):
            s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            s.connect(b)
            return s
        host, port = b.rsplit(":", 1)
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.connect((host or "127.0.0.1", int(port)))
        return s

    def _accept_loop(self) -> None:
        while not self._closing:
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            with self._mu:
                mode = self._partitioned
                self.connections_total += 1
            if mode == "refuse":
                self.refused_total += 1
                try:
                    conn.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                                    b"\x01\x00\x00\x00\x00\x00\x00\x00")
                    conn.close()  # RST-ish: the dial fails fast
                except OSError:
                    pass
                continue
            if mode == "blackhole":
                # hold the socket open but never relay: the TCP connect
                # succeeds, the HTTP/2 handshake never answers
                with self._mu:
                    self._conns.append((conn, None))
                continue
            try:
                backend = self._dial_backend()
            except OSError as e:
                log.debug("chaos backend dial failed: %r", e)
                try:
                    conn.close()
                except OSError:
                    pass
                continue
            pair = (conn, backend)
            with self._mu:
                self._conns.append(pair)
            threading.Thread(target=self._pump, args=(conn, backend, "up"),
                             daemon=True).start()
            threading.Thread(target=self._pump, args=(backend, conn, "down"),
                             daemon=True).start()

    def _pump(self, src: socket.socket, dst: socket.socket,
              direction: str) -> None:
        try:
            while True:
                data = src.recv(_CHUNK)
                if not data:
                    break
                delay = self.latency
                if delay > 0:
                    time.sleep(delay)
                dst.sendall(data)
                with self._mu:
                    if direction == "up":
                        self.bytes_up += len(data)
                    else:
                        self.bytes_down += len(data)
        except OSError:
            pass
        finally:
            for s in (src, dst):
                try:
                    s.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                try:
                    s.close()
                except OSError:
                    pass

    @staticmethod
    def _close_pair(pair) -> None:
        for s in pair:
            if s is None:
                continue
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                s.close()
            except OSError:
                pass

    def close(self) -> None:
        self._closing = True
        try:
            self._listener.close()
        except OSError:
            pass
        with self._mu:
            conns, self._conns = self._conns, []
        for pair in conns:
            self._close_pair(pair)


class AgentProcess:
    """A real agent subprocess with SIGKILL/respawn — the crash driver.

    The listen address and state dirs (history/capture/checkpoint)
    survive the kill, so the respawned agent serves the previous life's
    sealed windows: exactly what resume-with-backfill needs.
    """

    def __init__(self, node: str, listen: str, *, history_dir: str = "",
                 capture_dir: str = "", checkpoint_dir: str = "",
                 extra_args: tuple[str, ...] = (),
                 env: dict[str, str] | None = None):
        self.node = node
        self.listen = listen
        self.history_dir = history_dir
        self.capture_dir = capture_dir
        self.checkpoint_dir = checkpoint_dir
        self.extra_args = tuple(extra_args)
        self.env = dict(os.environ)
        # agents probe their own platform; chaos fleets pin CPU so a
        # respawn never hangs in device acquisition (VERDICT Weak #1)
        self.env["JAX_PLATFORMS"] = "cpu"
        # the package may be running from a source checkout that is not
        # installed: make `-m inspektor_gadget_tpu...` resolvable in the
        # child regardless of its cwd
        pkg_parent = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        pkg_root = os.path.dirname(pkg_parent)
        existing = self.env.get("PYTHONPATH", "")
        if pkg_root not in existing.split(os.pathsep):
            self.env["PYTHONPATH"] = (pkg_root + (os.pathsep + existing
                                                  if existing else ""))
        if env:
            self.env.update(env)
        self.proc: subprocess.Popen | None = None
        self.spawns = 0

    def _argv(self) -> list[str]:
        argv = [sys.executable, "-m", "inspektor_gadget_tpu.agent.main",
                "serve", "--listen", self.listen,
                "--node-name", self.node,
                "--platform", "cpu", "--no-doctor",
                "--flight-record-path", "off"]
        if self.history_dir:
            argv += ["--history-dir", self.history_dir]
        if self.capture_dir:
            argv += ["--capture-dir", self.capture_dir]
        if self.checkpoint_dir:
            argv += ["--checkpoint-dir", self.checkpoint_dir]
        argv += list(self.extra_args)
        return argv

    def start(self, wait: bool = True, timeout: float = 90.0) -> None:
        if self.proc is not None and self.proc.poll() is None:
            raise RuntimeError(f"agent {self.node} already running")
        self.proc = subprocess.Popen(
            self._argv(), env=self.env,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        self.spawns += 1
        if wait:
            self.wait_ready(timeout)

    def wait_ready(self, timeout: float = 90.0) -> None:
        """Poll the catalog RPC until the agent answers (liveness
        contract, agent/main.py `liveness`)."""
        from ..agent.client import AgentClient
        deadline = time.monotonic() + timeout
        last: Exception | None = None
        while time.monotonic() < deadline:
            if self.proc is not None and self.proc.poll() is not None:
                raise RuntimeError(
                    f"agent {self.node} exited rc={self.proc.returncode} "
                    f"before becoming ready")
            try:
                c = AgentClient(self.listen, self.node, rpc_deadline=2.0)
                try:
                    c.get_catalog(use_cache_on_error=False)
                    return
                finally:
                    c.close()
            except Exception as e:  # noqa: BLE001 — not up yet
                last = e
                time.sleep(0.2)
        raise TimeoutError(
            f"agent {self.node} not ready after {timeout}s: {last!r}")

    def kill(self, sig: int = signal.SIGKILL) -> None:
        """SIGKILL by default: no SIGTERM grace, no seals, no goodbyes —
        the crash the journal/history torn-tail disciplines exist for."""
        if self.proc is None:
            return
        try:
            self.proc.send_signal(sig)
        except ProcessLookupError:
            pass
        try:
            self.proc.wait(timeout=30)
        except subprocess.TimeoutExpired:
            self.proc.kill()
            self.proc.wait(timeout=10)

    def respawn(self, wait: bool = True, timeout: float = 90.0) -> None:
        """Kill-if-alive then start fresh on the same address/dirs."""
        if self.proc is not None and self.proc.poll() is None:
            self.kill()
        # a unix socket path must be unlinked or the rebind fails
        if self.listen.startswith("unix://"):
            try:
                os.unlink(self.listen[len("unix://"):])
            except OSError:
                pass
        self.start(wait=wait, timeout=timeout)

    def stop(self) -> None:
        if self.proc is None:
            return
        if self.proc.poll() is None:
            try:
                self.proc.terminate()
                self.proc.wait(timeout=10)
            except (subprocess.TimeoutExpired, ProcessLookupError):
                self.kill()


class SubscriberChurn:
    """Attach/hold/detach churn against one SHARED gadget run — the
    fan-out analogue of connection chaos (dashboard clients coming and
    going, some of them dying mid-stream).

    Each round attaches a fresh subscriber to `run_id` on `target`
    (optionally dialing through a ChaosProxy), pumps records for `hold`
    seconds, then leaves — cleanly via a stop request, or rudely via
    `proxy.cut()` when `cut=True`. Counters (rounds, records, acks,
    cuts, errors) let tests assert the churn really happened; the
    invariants (no leaked queues/threads/runs, unaffected peers) are the
    test's to check.
    """

    def __init__(self, target: str, run_id: str, *, node: str = "",
                 proxy: "ChaosProxy | None" = None,
                 subscriber: dict | None = None):
        self.target = target
        self.run_id = run_id
        self.node = node or "churn"
        self.proxy = proxy
        self.subscriber = dict(subscriber or {})
        self.rounds = 0
        self.cuts = 0
        self.records = 0
        self.acks = 0
        self.errors: list[str] = []

    def round(self, hold: float = 0.5, cut: bool = False) -> dict:
        """One attach/hold/leave cycle; returns the client's accounting
        dict. cut=True severs the proxy mid-hold instead of stopping."""
        from ..agent.client import AgentClient
        stop = threading.Event()
        holder: dict = {}
        client = AgentClient(self.target, self.node)

        def pump():
            holder["out"] = client.run_gadget(
                "", "", attach_to=self.run_id,
                subscriber=dict(self.subscriber),
                on_message=lambda *_: None, stop_event=stop)

        t = threading.Thread(target=pump, daemon=True)
        t.start()
        time.sleep(max(hold, 0.0))
        if cut and self.proxy is not None:
            self.proxy.cut()
            self.cuts += 1
            stop.set()  # unblock the stopper thread; the stream is gone
        else:
            stop.set()
        t.join(timeout=30.0)
        client.close()
        out = holder.get("out") or {"error": "churn round never returned"}
        self.rounds += 1
        self.records += int(out.get("records") or 0)
        if out.get("attach"):
            self.acks += 1
        # a cut round's transport error is the injected fault, not a
        # failure of the run under test
        if out.get("error") and not cut:
            self.errors.append(str(out["error"]))
        return out

    def run(self, rounds: int, *, hold: float = 0.5,
            cut_every: int = 0) -> None:
        """`rounds` cycles; every cut_every-th (1-based) leaves by
        proxy cut instead of a clean stop (0 = never cut)."""
        for i in range(1, rounds + 1):
            self.round(hold=hold,
                       cut=bool(cut_every and i % cut_every == 0))


class SkewClock:
    """A monotonic clock with injectable skew (FleetHealth's `clock`
    seam): skew(+5) jumps time forward five seconds for every consumer
    of this clock — the fleet-health equivalent of a VM pause or an NTP
    step."""

    def __init__(self, base=time.monotonic):
        self._base = base
        self.offset = 0.0

    def __call__(self) -> float:
        return self._base() + self.offset

    def skew(self, seconds: float) -> None:
        self.offset += float(seconds)


__all__ = ["AgentProcess", "ChaosProxy", "SkewClock", "SubscriberChurn"]
