"""Test steps: ordered commands with start/stop + guaranteed cleanup.

Semantics mirror integration/teststeps.go:64-113 — non-cleanup steps run
in order (start-and-stop steps are started and left running), started
steps are stopped in reverse order after an optional settle delay, and
cleanup steps ALWAYS run last, even when an earlier step failed.
Command matches integration/command.go: a subprocess with expected-string
/ expected-regexp / expected-fn verification, SIGINT-based stop for
streaming gadgets, and a `cleanup` flag.
"""

from __future__ import annotations

import io
import re
import signal
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Protocol, Sequence


class StepError(AssertionError):
    pass


class TestStep(Protocol):
    def run(self) -> None: ...
    def start(self) -> None: ...
    def stop(self) -> None: ...
    @property
    def is_cleanup(self) -> bool: ...
    @property
    def is_start_and_stop(self) -> bool: ...
    @property
    def running(self) -> bool: ...


@dataclass
class Command:
    """A subprocess step (ref: integration/command.go Command struct)."""

    name: str
    cmd: Sequence[str]
    expected_string: str | None = None
    expected_regexp: str | None = None
    expected_output_fn: Callable[[str], None] | None = None
    cleanup: bool = False
    start_and_stop: bool = False
    timeout: float = 120.0
    # SIGINT grace before SIGKILL on stop (streaming gadgets exit cleanly
    # on interrupt, like execsnoop-style Ctrl^C in the reference)
    stop_grace: float = 10.0

    # stop() waits up to this long for the process to produce its first
    # output before sending SIGINT (slow jax-importing startups would
    # otherwise be interrupted before their signal handler exists)
    ready_timeout: float = 60.0

    stdout: str = field(default="", init=False)
    stderr: str = field(default="", init=False)
    returncode: int | None = field(default=None, init=False)
    _proc: subprocess.Popen | None = field(default=None, init=False)
    _started: bool = field(default=False, init=False)
    _out_buf: io.StringIO = field(default_factory=io.StringIO, init=False)
    _ready: threading.Event = field(default_factory=threading.Event, init=False)
    _reader: threading.Thread | None = field(default=None, init=False)

    @property
    def is_cleanup(self) -> bool:
        return self.cleanup

    @property
    def is_start_and_stop(self) -> bool:
        return self.start_and_stop

    @property
    def running(self) -> bool:
        return self._started

    def run(self) -> None:
        r = subprocess.run(list(self.cmd), capture_output=True, text=True,
                           timeout=self.timeout)
        self.stdout, self.stderr, self.returncode = r.stdout, r.stderr, r.returncode
        if not self.cleanup and r.returncode != 0:
            raise StepError(
                f"step {self.name!r} exited {r.returncode}:\n{r.stderr}")
        self._verify()

    def start(self) -> None:
        self._proc = subprocess.Popen(
            list(self.cmd), stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True)
        self._started = True

        # drain stdout continuously: signals readiness and prevents the
        # pipe buffer from blocking long-running streams
        def drain():
            for line in self._proc.stdout:
                self._out_buf.write(line)
                self._ready.set()
            self._ready.set()

        self._reader = threading.Thread(target=drain, daemon=True)
        self._reader.start()

        def drain_err():
            self._err_text = self._proc.stderr.read()

        self._err_text = ""
        self._err_reader = threading.Thread(target=drain_err, daemon=True)
        self._err_reader.start()

    def stop(self) -> None:
        if self._proc is None:
            raise StepError(f"step {self.name!r} was never started")
        self._ready.wait(self.ready_timeout)
        self._proc.send_signal(signal.SIGINT)
        try:
            self._proc.wait(timeout=self.stop_grace)
        except subprocess.TimeoutExpired:
            self._proc.kill()
            self._proc.wait()
        self._reader.join(timeout=5.0)
        self._err_reader.join(timeout=5.0)
        self.stdout = self._out_buf.getvalue()
        self.stderr = self._err_text
        self.returncode = self._proc.returncode
        self._started = False
        # SIGINT exit (-2 or 0 after handler) is the expected stop path
        if self.returncode not in (0, -signal.SIGINT, 130):
            raise StepError(
                f"step {self.name!r} exited {self.returncode} on stop:\n{err}")
        self._verify()

    def _verify(self) -> None:
        if self.expected_string is not None and self.stdout != self.expected_string:
            raise StepError(
                f"step {self.name!r}: output mismatch\n"
                f"expected: {self.expected_string!r}\ngot: {self.stdout!r}")
        if self.expected_regexp is not None and not re.search(
                self.expected_regexp, self.stdout, re.MULTILINE):
            raise StepError(
                f"step {self.name!r}: regexp {self.expected_regexp!r} "
                f"not found in output:\n{self.stdout}")
        if self.expected_output_fn is not None:
            self.expected_output_fn(self.stdout)

    def kill(self) -> None:
        if self._proc is not None and self._proc.poll() is None:
            self._proc.kill()
            self._proc.wait()
        self._started = False


@dataclass
class FuncStep:
    """An in-process step (workload generation, assertions between steps)."""

    name: str
    fn: Callable[[], None]
    cleanup: bool = False

    _running: bool = field(default=False, init=False)

    @property
    def is_cleanup(self) -> bool:
        return self.cleanup

    @property
    def is_start_and_stop(self) -> bool:
        return False

    @property
    def running(self) -> bool:
        return self._running

    def run(self) -> None:
        self.fn()

    def start(self) -> None:  # pragma: no cover — FuncStep is never S&S
        self.run()

    def stop(self) -> None:  # pragma: no cover
        pass


def ig_cli(*args: str) -> list[str]:
    """Command line for the framework CLI (the built-binary analogue)."""
    return [sys.executable, "-m", "inspektor_gadget_tpu.cli.main", *args]


def run_test_steps(steps: Sequence[TestStep], *,
                   step_wait: float = 1.0,
                   before_cleanup: Callable[[], None] | None = None) -> None:
    """Run steps with the reference's ordering + cleanup guarantees
    (teststeps.go:64-113): start-and-stop steps are started inline, left
    running while later steps execute, then stopped in reverse order after
    `step_wait` seconds; cleanup steps run unconditionally at the end."""
    started: list[TestStep] = []
    first_error: BaseException | None = None
    try:
        for step in steps:
            if step.is_cleanup:
                continue
            if step.is_start_and_stop:
                step.start()
                started.append(step)
            else:
                step.run()
        if started:
            time.sleep(step_wait)
        for step in reversed(started):
            if step.running:
                step.stop()
                started.remove(step)
    except BaseException as e:  # noqa: BLE001 — re-raised after cleanup
        first_error = e
    finally:
        for step in reversed(started):
            if step.running and isinstance(step, Command):
                step.kill()
        if before_cleanup is not None:
            before_cleanup()
        for step in steps:
            if step.is_cleanup:
                try:
                    step.run()
                except Exception as e:  # noqa: BLE001
                    if first_error is None:
                        first_error = e
        if first_error is not None:
            raise first_error
