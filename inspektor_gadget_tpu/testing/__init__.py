"""Integration-test step framework.

Python analogue of the reference's cluster-integration tier
(integration/teststeps.go:26-113, integration/command.go, and the JSON
expectation helpers in integration/helpers.go:31-176): tests are lists of
steps — subprocess commands, workload generators, cleanup steps — run in
order with start-and-stop semantics, guaranteed cleanup, and declarative
output matching against normalized JSON events.
"""

from .chaos import AgentProcess, ChaosProxy, SkewClock
from .steps import Command, FuncStep, TestStep, run_test_steps
from .match import (
    build_common_data,
    expect_all_entries_to_match,
    expect_entries_in_array_to_match,
    expect_entries_to_match,
    parse_json_array,
    parse_multi_json,
)

__all__ = [
    "AgentProcess",
    "ChaosProxy",
    "SkewClock",
    "Command",
    "FuncStep",
    "TestStep",
    "run_test_steps",
    "build_common_data",
    "expect_all_entries_to_match",
    "expect_entries_in_array_to_match",
    "expect_entries_to_match",
    "parse_json_array",
    "parse_multi_json",
]
