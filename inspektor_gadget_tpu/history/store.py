"""HistoryStore — the per-node sealed-window sketch store.

Live sketch state is cumulative and volatile; this store is where the
tpusketch operator seals one window of it at each boundary, giving the
node a durable, range-readable history. The on-disk format IS the PR-5
journal format (capture/journal.py): every sealed window is one
EV_WINDOW frame appended with a single O_APPEND write, CRC-framed, so a
node killed mid-seal leaves exactly one torn window at the active
segment's tail — dropped-and-accounted on read, never half-decoded.
Size/age rotation seals segments into index.jsonl; retention GC deletes
the oldest sealed segments and never the active one; the manifest
stamps the same provenance (git sha, resolved params, platform/degraded
probe outcome) a capture journal carries.

The history-specific additions on top of the journal machinery:

- index rows carry the union of subpopulation keys and the window count
  of the segment they seal, so range queries with a ``--key`` filter
  skip whole segments without decoding them;
- history traffic accounts into its own ``ig_history_*`` counters, not
  the capture plane's;
- one store directory per (node, gadget) identity under the base area
  (``--history-dir`` / $IG_HISTORY_DIR / ~/.ig-tpu/history), so
  concurrent runs of one gadget share a window timeline the way they
  share a checkpoint key — and in-process agent fleets (tests, the
  deploy --local path) never interleave two nodes' windows in one
  journal.

Layout:

    <base>/[<node>--]<gadget-key>/
      manifest.json   index.jsonl   seg-*.igj   # EV_WINDOW frames
"""

from __future__ import annotations

import os
import threading
from typing import Iterator

from ..agent import wire
from ..capture.journal import (
    JournalMetrics,
    JournalReader,
    JournalWriter,
    _seg_name,
    build_manifest,
    is_journal,
)
from ..telemetry import counter, gauge
from ..utils.logger import get_logger
from .window import SealedWindow, encode_window, header_overlaps

HISTORY_SCHEMA = "ig-tpu/sketch-history/v1"

DEFAULT_SEGMENT_BYTES = 8 << 20
DEFAULT_SEGMENT_AGE = 300.0
DEFAULT_RETENTION_BYTES = 512 << 20
DEFAULT_RETENTION_SEGMENTS = 0

log = get_logger("ig-tpu.history")

HISTORY_METRICS = JournalMetrics(
    records=counter("ig_history_windows_total",
                    "sealed sketch windows appended to history stores",
                    ("type",)),
    bytes=counter("ig_history_bytes_total",
                  "bytes appended to history stores"),
    drops=counter("ig_history_drops_total",
                  "history windows lost (torn tails, failed appends)",
                  ("reason",)),
    gc=counter("ig_history_gc_total",
               "sealed history segments deleted by retention GC"),
    active=gauge("ig_history_active_stores", "open history store writers"),
)


def history_base_dir(path: str | None = None) -> str:
    """The node-wide window area: $IG_HISTORY_DIR, else
    ~/.ig-tpu/history (agents override with --history-dir)."""
    return (path or os.environ.get("IG_HISTORY_DIR")
            or os.path.join(os.path.expanduser("~"), ".ig-tpu", "history"))


def validate_store_name(name: str) -> str:
    """Store (gadget-key) names resolve under the base dir from
    client-supplied RPC fields — same escape surface as recording ids,
    same check."""
    if (not name or name != os.path.basename(name)
            or name in (".", "..")):
        raise ValueError(f"bad history store name {name!r}")
    return name


class _WindowJournal(JournalWriter):
    """JournalWriter that accumulates, per active segment, the union of
    subpopulation keys and the window count, sealing both into the
    segment's index row (the Hydra-style pruning index).

    The outer _win_mu serializes append+key-accounting against rotation
    and close: without it, a concurrent run sharing this writer could
    seal the segment's index row between another run's frame landing
    and its keys being recorded — and a missing key prunes that window
    out of every ``--key`` query."""

    def __init__(self, *args, **kwargs):
        self._win_mu = threading.Lock()
        self._seg_keys: set[str] = set()
        self._seg_windows = 0
        super().__init__(*args, **kwargs)

    def _index_extra_locked(self) -> dict:
        row = {"keys": sorted(self._seg_keys),
               "windows": self._seg_windows}
        self._seg_keys = set()
        self._seg_windows = 0
        return row

    def append_window_frame(self, header: dict, payload: bytes,
                            keys: list[str], ts: float | None) -> int:
        with self._win_mu:
            # rotation inside append() seals the PREVIOUS segment first
            # (this frame hasn't landed yet, so its keys belong to the
            # fresh segment the accounting below annotates)
            seq = self.append(wire.EV_WINDOW, header, payload, ts=ts)
            self._seg_keys.update(keys)
            self._seg_windows += 1
            return seq

    def rotate(self) -> None:
        with self._win_mu:
            super().rotate()

    def sync(self) -> None:
        """fsync the active segment — the compaction engine's durability
        barrier: a super-window frame must survive a crash BEFORE any of
        its source segments is GC'd, or coverage is lost."""
        with self._win_mu, self._mu:
            try:
                fd = os.open(self._active_path(), os.O_RDONLY)
            except OSError:
                return  # nothing appended yet: nothing to make durable
            try:
                os.fsync(fd)
            finally:
                os.close(fd)

    def remove_segments(self, names: list[str], *,
                        count_gc: bool = False
                        ) -> tuple[int, int]:
        """Delete sealed segments by name under the writer lock — the
        one door compaction/archive GC and retention GC share, so the
        two can never double-free a file or race the active segment
        (which is refused here unconditionally). Returns
        (removed, bytes_freed); missing files are skipped, not errors
        (a concurrent retention pass may have won the race)."""
        removed, freed = 0, 0
        with self._win_mu, self._mu:
            active = _seg_name(self._seg_n)
            for name in names:
                if not name or name == active \
                        or name != os.path.basename(name):
                    continue
                path = os.path.join(self.path, name)
                try:
                    size = os.path.getsize(path)
                    os.remove(path)
                except OSError:
                    continue
                removed += 1
                freed += size
                if count_gc:
                    self._m.gc.inc()
        return removed, freed

    def close(self) -> dict:
        with self._win_mu:
            return super().close()


class HistoryStore:
    """Process-wide singleton (HISTORY) the tpusketch operator seals
    into — the role RECORDINGS plays for the capture plane."""

    def __init__(self):
        self._mu = threading.Lock()
        self._base: str | None = None
        self._writers: dict[tuple[str, str], _WindowJournal] = {}
        # archive tiers are a property of a history AREA (base dir), not
        # of the process: one tier per base, so a run pointing at its
        # own --history-dir cannot rewire another area's rehydration
        self._archives: dict[str, "object"] = {}

    # -- configuration ------------------------------------------------------

    def set_base_dir(self, path: str | None) -> None:
        """Agent --history-dir / test override of the default area."""
        with self._mu:
            self._base = path or None

    def base_dir(self) -> str:
        with self._mu:
            return history_base_dir(self._base)

    def configured(self) -> bool:
        """True when an explicit base was set (agent flag / operator
        param) — sealing stays off until someone opts the node in, like
        recording stays off until armed."""
        with self._mu:
            return self._base is not None

    def set_archive(self, archive_dir: str | None,
                    cache_bytes: int | None = None,
                    base_dir: str | None = None) -> None:
        """Configure (or clear) the archive tier for ONE history area
        (base_dir; default the current base): a FilesystemArchive
        rooted at archive_dir, with the rehydration cache under that
        area (bounded LRU by cache_bytes). Agents opt in via
        --history-archive-dir / operator history-archive-dir."""
        base = os.path.abspath(history_base_dir(base_dir)
                               if base_dir else self.base_dir())
        if not archive_dir:
            with self._mu:
                self._archives.pop(base, None)
            return
        from .archive import ArchiveTier, FilesystemArchive
        tier = ArchiveTier(
            FilesystemArchive(archive_dir),
            cache_dir=os.path.join(base, ".archive-cache"),
            cache_bytes=cache_bytes or (64 << 20))
        with self._mu:
            self._archives[base] = tier

    def archive(self, base_dir: str | None = None):
        """The ArchiveTier configured for one history area (default
        the current base), or None."""
        base = os.path.abspath(base_dir or self.base_dir())
        with self._mu:
            return self._archives.get(base)

    # -- writing ------------------------------------------------------------

    def writer_for(self, gadget: str, *, node: str = "", run_id: str = "",
                   params: dict[str, str] | None = None,
                   base_dir: str | None = None,
                   max_segment_bytes: int = DEFAULT_SEGMENT_BYTES,
                   max_segment_age: float = DEFAULT_SEGMENT_AGE,
                   retention_bytes: int = DEFAULT_RETENTION_BYTES,
                   retention_segments: int = DEFAULT_RETENTION_SEGMENTS,
                   ) -> _WindowJournal:
        """The (lazily opened, reopen-tolerant) window journal for one
        (node, gadget) identity. Reopening an existing store recovers
        the PR-5 way: torn tail truncated and accounted, seq continues."""
        gadget_key = validate_store_name(gadget.replace("/", "-"))
        key_name = (validate_store_name(f"{node}--{gadget_key}") if node
                    else gadget_key)
        base = base_dir or self.base_dir()
        key = (base, key_name)
        with self._mu:
            w = self._writers.get(key)
            if w is None:
                manifest = build_manifest(
                    journal_id=key_name, node=node, gadget=gadget,
                    run_id=run_id, params=params,
                    extra={"schema": HISTORY_SCHEMA})
                w = _WindowJournal(
                    os.path.join(base, key_name),
                    manifest=manifest,
                    max_segment_bytes=max_segment_bytes,
                    max_segment_age=max_segment_age,
                    retention_bytes=retention_bytes,
                    retention_segments=retention_segments,
                    metrics=HISTORY_METRICS)
                self._writers[key] = w
        return w

    def writer_for_dir(self, store_dir: str) -> _WindowJournal:
        """The (cached) writer for an existing store directory — the
        compaction engine resolves stores by path, not identity. The
        (node, gadget) identity is recovered from the directory name,
        so the engine and a live sealer of the same store share ONE
        writer (and its lock)."""
        base = os.path.dirname(os.path.abspath(store_dir))
        name = os.path.basename(os.path.abspath(store_dir))
        node, sep, gadget_key = name.partition("--")
        if not sep:
            node, gadget_key = "", name
        return self.writer_for(gadget_key, node=node, base_dir=base)

    def append_window(self, win: SealedWindow, *,
                      writer: _WindowJournal) -> int:
        """Seal one window: ONE frame, ONE O_APPEND write. Returns the
        store seq; on failure the loss is counted, logged, and re-raised
        (the caller decides whether a failed seal stops the run — the
        operator logs and continues, like a failed checkpoint)."""
        header, payload = encode_window(win)
        seq = writer.append_window_frame(header, payload, win.slice_keys,
                                         win.end_ts or None)
        win.seq = seq
        return seq

    def release(self, writer: _WindowJournal) -> None:
        """A run using this store ended: force-seal the active segment
        so its windows get index rows (fan-out pruning), but keep the
        writer open for the next run of the same identity."""
        writer.rotate()

    def close_all(self) -> None:
        with self._mu:
            writers = list(self._writers.values())
            self._writers.clear()
        for w in writers:
            w.close()

    # -- reading ------------------------------------------------------------

    def store_dirs(self, base_dir: str | None = None) -> list[str]:
        base = base_dir or self.base_dir()
        out = []
        if os.path.isdir(base):
            for name in sorted(os.listdir(base)):
                p = os.path.join(base, name)
                if is_journal(p):
                    out.append(p)
        return out

    def list_windows(self, *, base_dir: str | None = None,
                     gadget: str = "", node: str = "",
                     start_ts: float | None = None,
                     end_ts: float | None = None,
                     start_seq: int | None = None,
                     end_seq: int | None = None,
                     key: str | None = None,
                     losses: list | None = None) -> list[dict]:
        """Window HEADER rows across this node's stores, oldest first,
        restricted to the range/slice. Torn tails are accounted into
        `losses` when a list is passed. No payload bytes leave this
        call, but the scan still inflates whole frames to read headers
        — a header-only side index is the known optimization when store
        sizes grow (the next arc's perf pass owns it)."""
        out: list[dict] = []
        for h, _payload in self._iter_frames(
                base_dir=base_dir, gadget=gadget, node=node,
                start_ts=start_ts,
                end_ts=end_ts, start_seq=start_seq, end_seq=end_seq,
                key=key, losses=losses, with_payload=False):
            out.append(h)
        return out

    def fetch_windows(self, *, base_dir: str | None = None,
                      gadget: str = "", node: str = "",
                      start_ts: float | None = None,
                      end_ts: float | None = None,
                      start_seq: int | None = None,
                      end_seq: int | None = None,
                      key: str | None = None,
                      losses: list | None = None
                      ) -> Iterator[tuple[dict, bytes]]:
        """(header, payload) pairs for every matching window."""
        return self._iter_frames(
            base_dir=base_dir, gadget=gadget, node=node, start_ts=start_ts,
            end_ts=end_ts, start_seq=start_seq, end_seq=end_seq,
            key=key, losses=losses, with_payload=True)

    def _iter_frames(self, *, base_dir, gadget, start_ts, end_ts,
                     start_seq, end_seq, key, losses,
                     with_payload, node="") -> Iterator[tuple[dict, bytes]]:
        # gadget filtering matches each window header's exact gadget id
        # (store dir names are node-qualified); the basename check only
        # prunes stores that cannot match
        want_suffix = gadget.replace("/", "-") if gadget else ""
        for store in self.store_dirs(base_dir):
            base_name = os.path.basename(store)
            if want_suffix and not (
                    base_name == want_suffix
                    or base_name.endswith(f"--{want_suffix}")):
                continue
            try:
                reader = JournalReader(store, metrics=HISTORY_METRICS)
            except FileNotFoundError:
                continue
            # the per-segment index rows carry the union of slice keys:
            # a --key query skips sealed segments that never saw it
            skip_files = set()
            if key:
                for row in reader.index:
                    if "keys" in row and key not in (row.get("keys") or []):
                        skip_files.add(row.get("file"))
            # the frame ts is the window's END ts, so the reader-level
            # start_ts filter is safe (end < start cannot overlap) but an
            # end_ts filter is NOT: a window straddling the range end has
            # frame ts > end_ts yet overlaps. The end bound is applied
            # only by header_overlaps below, on start_ts.
            for header, payload in reader.records(
                    start_seq=start_seq, end_seq=end_seq,
                    start_ts=start_ts,
                    types=(wire.EV_WINDOW,)):
                if skip_files and self._seg_of(reader, header) in skip_files:
                    continue
                if gadget and header.get("gadget") != gadget:
                    continue
                if node and header.get("node") != node:
                    # an agent serves only the windows ITS runs sealed —
                    # in-process fleets (tests, deploy --local) share one
                    # base area, and a fan-out that got every node's
                    # windows from every node would double-count merges
                    continue
                if not header_overlaps(header, start_ts=start_ts,
                                       end_ts=end_ts, start_seq=start_seq,
                                       end_seq=end_seq, key=key):
                    continue
                yield header, (payload if with_payload else b"")
            if losses is not None and reader.losses:
                for loss in reader.losses:
                    losses.append({"store": os.path.basename(store),
                                   **loss.__dict__})
            # archive tier: ranges overlapping offloaded segments
            # rehydrate through the manifest (digest-verified; a
            # corrupted object lands in `losses`, never in the fold)
            arch = self.archive(os.path.dirname(store))
            if arch is not None:
                for header, payload in arch.frames_for_range(
                        store, start_ts=start_ts, end_ts=end_ts,
                        start_seq=start_seq, end_seq=end_seq, key=key,
                        losses=losses):
                    if gadget and header.get("gadget") != gadget:
                        continue
                    if node and header.get("node") != node:
                        continue
                    if not header_overlaps(
                            header, start_ts=start_ts, end_ts=end_ts,
                            start_seq=start_seq, end_seq=end_seq, key=key):
                        continue
                    yield header, (payload if with_payload else b"")

    @staticmethod
    def _seg_of(reader: JournalReader, header: dict) -> str | None:
        seq = header.get("seq")
        for row in reader.index:
            first, last = row.get("first_seq"), row.get("last_seq")
            if first is not None and last is not None \
                    and first <= seq <= last:
                return row.get("file")
        return None

    def stats(self, base_dir: str | None = None) -> dict:
        """Per-store window counts + disk usage (doctor / top windows /
        `ig-tpu history tiers`), broken down per compaction level and
        per tier: each level reports windows, payload bytes, and its
        oldest/newest window timestamps, so "how much resolution do I
        still have for last Tuesday" reads straight off the store."""
        from ..capture.journal import dir_stats
        base = base_dir or self.base_dir()
        arch = self.archive(base)
        stores = {}
        for store in self.store_dirs(base):
            reader = JournalReader(store, metrics=HISTORY_METRICS)
            windows = 0
            levels: dict[int, dict] = {}
            for header, payload in reader.records(
                    types=(wire.EV_WINDOW,)):
                windows += 1
                lvl = int(header.get("level", 0))
                row = levels.setdefault(
                    lvl, {"windows": 0, "bytes": 0,
                          "oldest_ts": None, "newest_ts": None,
                          "source_windows": 0})
                row["windows"] += 1
                row["bytes"] += len(payload)
                start = float(header.get("start_ts", 0.0))
                end = float(header.get("end_ts", 0.0))
                row["oldest_ts"] = (start if row["oldest_ts"] is None
                                    else min(row["oldest_ts"], start))
                row["newest_ts"] = (end if row["newest_ts"] is None
                                    else max(row["newest_ts"], end))
                row["source_windows"] += (
                    len(header.get("compacted_from") or []) or 1)
            stores[os.path.basename(store)] = {
                "path": store,
                "windows": windows,
                "levels": {str(k): v for k, v in sorted(levels.items())},
                "segments": len(reader._segment_files()),
                "losses": [loss.__dict__ for loss in reader.losses],
                "archive": (arch.stats(store) if arch is not None
                            else None),
            }
        segments, total_bytes = dir_stats(base) if os.path.isdir(base) \
            else (0, 0)
        return {"base": base, "stores": stores,
                "segments": segments, "bytes": total_bytes}

    def tier_stats(self, base_dir: str | None = None, *,
                   ttl: float = 0.0) -> dict:
        """The fleet-facing tier summary (DumpState / doctor
        history_tiers): windows+bytes per level across every store,
        plus the archive tier's footprint and cache health. The walk
        decodes every store frame, so hot polled surfaces (DumpState —
        fleet health/runs/alerts all ride it) pass a ttl and reuse the
        last answer instead of re-scanning a possibly-large store on
        every poll."""
        import time as _time
        base = os.path.abspath(base_dir or self.base_dir())
        if ttl > 0:
            with self._mu:
                cached = getattr(self, "_tier_cache", None)
            if cached is not None and cached[0] == base \
                    and _time.monotonic() - cached[1] < ttl:
                return cached[2]
        full = self.stats(base_dir)
        by_level: dict[str, dict] = {}
        archived = {"segments": 0, "bytes": 0, "windows": 0}
        cache = None
        for srow in full["stores"].values():
            for lvl, row in (srow.get("levels") or {}).items():
                agg = by_level.setdefault(
                    lvl, {"windows": 0, "bytes": 0,
                          "oldest_ts": None, "newest_ts": None})
                agg["windows"] += row["windows"]
                agg["bytes"] += row["bytes"]
                for k, fn in (("oldest_ts", min), ("newest_ts", max)):
                    if row[k] is not None:
                        agg[k] = (row[k] if agg[k] is None
                                  else fn(agg[k], row[k]))
            a = srow.get("archive")
            if a:
                archived["segments"] += a["segments"]
                archived["bytes"] += a["bytes"]
                archived["windows"] += a["windows"]
                cache = a["cache"]
        out = {"base": full["base"], "stores": len(full["stores"]),
               "bytes": full["bytes"], "levels": by_level,
               "archived": archived, "archive_cache": cache}
        with self._mu:
            self._tier_cache = (base, _time.monotonic(), out)
        return out


# the process-wide singleton the tpusketch operator seals into
HISTORY = HistoryStore()

__all__ = ["DEFAULT_RETENTION_BYTES", "DEFAULT_SEGMENT_AGE",
           "DEFAULT_SEGMENT_BYTES", "HISTORY", "HISTORY_METRICS",
           "HISTORY_SCHEMA", "HistoryStore", "history_base_dir",
           "validate_store_name"]
