"""SealedWindow: the unit of the sketch-history plane.

"Sketch Disaggregation Across Time and Space" (arxiv 2503.13515) rests
on one property this module makes concrete: mergeable sketches sealed
per time window can be stored cheaply per node and merged lazily at
query time — count-min tables and entropy buckets add, HLL registers
max, top-k candidate lists union-and-requery — so "cardinality of
tenant X, 2–3pm, across nodes" is a client-side fold over whichever
sealed windows overlap the range, with zero coordination at ingest.

One sealed window carries:

- the window's GLOBAL sketch state (count-min table, HLL registers,
  entropy buckets, top-k candidates) for whole-traffic range queries;
- Hydra-style subpopulation slices (arxiv 2208.04927): for each
  bounded-cardinality slice key observed in the window (``mntns:<ns>``,
  ``kind:<syscall>``, and the ``mntns:<ns>|kind:<k>`` cross product), a
  small host-side HLL + entropy-bucket vector + exact truncated
  heavy-hitter table, so per-pod × per-syscall × time questions answer
  from sealed state without replaying raw events;
- a content digest over the decoded state (arrays hashed by value, wall
  timestamps excluded) — the determinism anchor: replaying the same
  PR-5 capture journal reseals byte-identical digests.

Encoding is the agent wire idiom: JSON header + one npz payload, framed
into history segments by history/store.py with the PR-5 journal
disciplines.
"""

from __future__ import annotations

import dataclasses
import hashlib
import io
import json
from typing import Iterable

import numpy as np

# the host-side murmur3 twin lives in ONE place (ops.hashing.fmix32_np,
# bit-identical to the device fmix32) so slice sketches and the
# invertible decode can never fork their hash family
from ..ops.hashing import fmix32_np as _fmix32_np

WINDOW_SCHEMA = "ig-tpu/sketch-window/v1"

# slice-plane geometry: small on purpose — a window carries up to
# max-slices of these, and the store holds hours of windows
SLICE_HLL_P = 8            # 256 one-byte registers per slice
SLICE_ENT_LOG2_WIDTH = 6   # 64 buckets per slice
SLICE_HH_K = 32            # exact truncated heavy-hitter table per slice




@dataclasses.dataclass
class SliceSketch:
    """One subpopulation's per-window state (host-side, numpy-only)."""

    events: int = 0
    hll: np.ndarray = dataclasses.field(
        default_factory=lambda: np.zeros(1 << SLICE_HLL_P, dtype=np.uint8))
    ent: np.ndarray = dataclasses.field(
        default_factory=lambda: np.zeros(1 << SLICE_ENT_LOG2_WIDTH,
                                         dtype=np.int64))
    hh: dict[int, int] = dataclasses.field(default_factory=dict)

    def update(self, hh_keys: np.ndarray, distinct_keys: np.ndarray,
               dist_keys: np.ndarray) -> None:
        self.events += len(hh_keys)
        # HLL scatter-max over leading-zero ranks (numpy twin of ops.hll)
        h = _fmix32_np(distinct_keys.astype(np.uint32))
        p = SLICE_HLL_P
        idx = (h >> np.uint32(32 - p)).astype(np.int64)
        rest = ((h << np.uint32(p)) | np.uint32((1 << p) - 1)).astype(np.uint32)
        # rank = leading zeros + 1 = 32 - floor(log2(rest)); rest is never
        # 0 (low p bits are padded with ones), and float64 is exact below
        # 2^32, so the vectorized log2 is the exact clz
        rank = (np.uint32(32) - np.floor(np.log2(
            rest.astype(np.float64))).astype(np.uint32)).astype(np.uint8)
        rank = np.minimum(rank, np.uint8(32 - p + 1))
        np.maximum.at(self.hll, idx, rank)
        # entropy buckets over the distribution stream
        eh = _fmix32_np(dist_keys.astype(np.uint32))
        eidx = (eh >> np.uint32(32 - SLICE_ENT_LOG2_WIDTH)).astype(np.int64)
        np.add.at(self.ent, eidx, 1)
        # exact heavy-hitter counts (truncated to SLICE_HH_K at seal)
        uniq, counts = np.unique(hh_keys.astype(np.uint32),
                                 return_counts=True)
        for k, c in zip(uniq.tolist(), counts.tolist()):
            if k:
                self.hh[k] = self.hh.get(k, 0) + c

    def sealed_hh(self) -> list[tuple[int, int]]:
        return sorted(self.hh.items(), key=lambda kv: -kv[1])[:SLICE_HH_K]


def slice_hll_estimate(registers: np.ndarray) -> float:
    """Standard HLL estimate over one (or a max-merged stack of) slice
    register vector(s) — numpy twin of ops.hll.hll_estimate."""
    m = registers.shape[-1]
    regs = registers.astype(np.float64)
    alpha = 0.7213 / (1 + 1.079 / m) if m > 64 else \
        {16: 0.673, 32: 0.697, 64: 0.709}.get(m, 0.7213 / (1 + 1.079 / m))
    raw = alpha * m * m / np.sum(np.exp2(-regs))
    zeros = float(np.sum(registers == 0))
    if raw <= 2.5 * m and zeros > 0:
        return float(m * np.log(m / max(zeros, 1.0)))
    return float(raw)


def entropy_bits(counts: np.ndarray) -> float:
    """Shannon entropy (bits) of one bucket-count vector."""
    c = counts.astype(np.float64)
    n = c.sum()
    if n <= 0:
        return 0.0
    nz = c[c > 0]
    return float(np.log2(n) - np.sum(nz * np.log2(nz)) / n)


@dataclasses.dataclass
class SealedWindow:
    """One decoded window. Arrays mirror the device bundle's per-window
    state; slices carry the Hydra-lite subpopulation sketches."""

    gadget: str
    node: str
    run_id: str
    window: int                    # per-run window ordinal, 1-based
    start_ts: float
    end_ts: float
    events: int
    drops: int
    cms: np.ndarray                # (depth, width) int32
    hll: np.ndarray                # (m,) int32 — device HLL registers
    ent: np.ndarray                # (w,) float32 — entropy buckets
    topk_keys: np.ndarray          # (k,) uint32
    topk_counts: np.ndarray        # (k,) int64
    slices: dict[str, dict]        # key → {events, hll, ent, hh}
    names: dict[int, str] = dataclasses.field(default_factory=dict)
    slices_dropped: int = 0        # subpopulations over the per-window cap
    seq: int = 0                   # store seq once appended
    digest: str = ""
    # -- tier plane (history/lifecycle.py) --------------------------------
    # level 0 = sealed at native resolution by the operator; level N>0 =
    # a super-window the compaction engine merged from aged level-(N-1)
    # windows per the resolution schedule. compacted_from is the sealed
    # provenance list: one row per source window ({digest, seq, window,
    # run_id, start_ts, end_ts, level}) so coverage is auditable and a
    # crash between super-window append and source GC is deduplicatable
    # at query time (the source's digest is in exactly one list).
    level: int = 0
    compacted_from: list[dict] = dataclasses.field(default_factory=list)
    # -- invertible heavy-key plane (ISSUE 15) ----------------------------
    # Per-window deltas of the bundle's invertible lanes (count int32,
    # keysum/fpsum uint32, all (rows, buckets)); None for configs without
    # the plane, and absent fields never enter the digest — pre-ISSUE-15
    # window digests are unchanged. Merge is elementwise add (wrap is
    # the algebra), so decoding a MERGED range recovers the range's
    # heavy keys exactly like live merged state does.
    inv_count: np.ndarray | None = None
    inv_keysum: np.ndarray | None = None
    inv_fpsum: np.ndarray | None = None
    # -- latency quantile plane (ISSUE 16) --------------------------------
    # Per-window DDSketch delta: bucket counts plus the zero/total
    # accounting, all exact integer subtractions of cumulative state.
    # alpha/min_value pin the bucket boundaries — two windows merge only
    # when they agree (different alpha = different log base = adding
    # apples to oranges). None (the default) for plane-off configs, and
    # absent fields never enter the digest — pre-plane window digests
    # are byte-identical.
    qt_counts: np.ndarray | None = None
    qt_zeros: int = 0
    qt_total: int = 0
    qt_alpha: float = 0.01
    qt_min_value: float = 1.0
    # -- accuracy audit plane (ISSUE 19) ----------------------------------
    # `approx` is the TopK candidate-ring overflow flag, finally carried
    # past the seal boundary (it used to be dropped here — the satellite
    # bugfix): True means some window of this state overflowed its
    # candidate ring, so merged top-k answers are approximate. It enters
    # the digest only when True, keeping every pre-existing digest
    # byte-identical. rs_keys/rs_weights are the per-window deterministic
    # bottom-k shadow-sample delta (ops/accuracy.ShadowSample lanes;
    # priorities recompute from keys, so they are never persisted):
    # None = plane off (absent from digest/encoding), empty = plane on
    # but nothing sampled this window.
    approx: bool = False
    rs_keys: np.ndarray | None = None
    rs_weights: np.ndarray | None = None
    rs_capacity: int = 0

    @property
    def slice_keys(self) -> list[str]:
        return sorted(self.slices)


def window_digest(win: SealedWindow) -> str:
    """Content digest of one sealed window: sha256 over the canonical
    JSON of the decoded state with every array hashed by VALUE. Wall
    timestamps are excluded — a deterministic replay reproduces the
    same device math at a different wall time, and the contract is
    byte-identical digests for byte-identical state."""
    def arr(a: np.ndarray) -> str:
        return hashlib.sha256(np.ascontiguousarray(a).tobytes()).hexdigest()

    doc = {
        "schema": WINDOW_SCHEMA,
        "gadget": win.gadget,
        "window": int(win.window),
        "events": int(win.events),
        "drops": int(win.drops),
        "slices_dropped": int(win.slices_dropped),
        # resolution identity: the same merged state at a different tier
        # is a different window (compacted_from stays OUT — provenance
        # lists are trimmed/audited without changing state identity).
        # Level 0 omits the field so pre-tier digests stay reproducible.
        **({"level": int(win.level)} if win.level else {}),
        # invertible plane: present only when sealed with it, so digests
        # of plane-off configs (and all pre-plane history) are unchanged
        **({"inv_count": arr(win.inv_count),
            "inv_keysum": arr(win.inv_keysum),
            "inv_fpsum": arr(win.inv_fpsum)}
           if win.inv_count is not None else {}),
        # quantile plane: same conditional discipline — plane-off
        # windows digest exactly as before ISSUE 16
        **({"qt_counts": arr(win.qt_counts),
            "qt_zeros": int(win.qt_zeros),
            "qt_total": int(win.qt_total),
            "qt_alpha": float(win.qt_alpha),
            "qt_min_value": float(win.qt_min_value)}
           if win.qt_counts is not None else {}),
        # accuracy plane: approx enters only when True and the shadow
        # lanes only when the audit plane sealed them — plane-off (and
        # all pre-ISSUE-19) digests are byte-identical
        **({"approx": True} if win.approx else {}),
        **({"rs_keys": arr(win.rs_keys),
            "rs_weights": arr(win.rs_weights),
            "rs_capacity": int(win.rs_capacity)}
           if win.rs_keys is not None else {}),
        "cms": arr(win.cms),
        "hll": arr(win.hll),
        "ent": arr(win.ent),
        "topk_keys": arr(win.topk_keys),
        "topk_counts": arr(win.topk_counts),
        "slices": {
            key: {
                "events": int(s["events"]),
                "hll": arr(s["hll"]),
                "ent": arr(s["ent"]),
                "hh": [[int(k), int(c)] for k, c in s["hh"]],
            }
            for key, s in sorted(win.slices.items())
        },
    }
    blob = json.dumps(doc, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()


def encode_window(win: SealedWindow) -> tuple[dict, bytes]:
    """SealedWindow → (frame header, npz payload). The header carries
    everything a ListWindows reply needs (range pruning, slice keys,
    digest) so listing never ships payload bytes."""
    arrays: dict[str, np.ndarray] = {
        "cms": win.cms,
        "hll": win.hll,
        "ent": win.ent,
        "topk_keys": win.topk_keys,
        "topk_counts": win.topk_counts,
    }
    if win.inv_count is not None:
        arrays["inv_count"] = win.inv_count
        arrays["inv_keysum"] = win.inv_keysum
        arrays["inv_fpsum"] = win.inv_fpsum
    if win.qt_counts is not None:
        arrays["qt_counts"] = win.qt_counts
    if win.rs_keys is not None:
        arrays["rs_keys"] = win.rs_keys
        arrays["rs_weights"] = win.rs_weights
    skeys = win.slice_keys
    if skeys:
        arrays["slice_events"] = np.array(
            [win.slices[k]["events"] for k in skeys], dtype=np.int64)
        arrays["slice_hll"] = np.stack(
            [win.slices[k]["hll"] for k in skeys]).astype(np.uint8)
        arrays["slice_ent"] = np.stack(
            [win.slices[k]["ent"] for k in skeys]).astype(np.int64)
        hh_keys = np.zeros((len(skeys), SLICE_HH_K), dtype=np.uint32)
        hh_counts = np.zeros((len(skeys), SLICE_HH_K), dtype=np.int64)
        for i, k in enumerate(skeys):
            pairs = win.slices[k]["hh"][:SLICE_HH_K]
            for j, (key32, c) in enumerate(pairs):
                hh_keys[i, j] = key32
                hh_counts[i, j] = c
        arrays["slice_hh_keys"] = hh_keys
        arrays["slice_hh_counts"] = hh_counts
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    header = {
        "schema": WINDOW_SCHEMA,
        "gadget": win.gadget,
        "node": win.node,
        "run_id": win.run_id,
        "window": int(win.window),
        "start_ts": float(win.start_ts),
        "end_ts": float(win.end_ts),
        "events": int(win.events),
        "drops": int(win.drops),
        "slices_dropped": int(win.slices_dropped),
        "keys": skeys,
        "names": {str(k): v for k, v in (win.names or {}).items()},
        "digest": win.digest or window_digest(win),
    }
    if win.level:
        header["level"] = int(win.level)
    if win.compacted_from:
        header["compacted_from"] = list(win.compacted_from)
    if win.qt_counts is not None:
        # scalar accounting + bucket-boundary identity ride the header
        # (range listings can report quantile coverage without payload
        # bytes); plane-off headers carry none of these keys, so the
        # pre-plane wire bytes are unchanged
        header["qt_zeros"] = int(win.qt_zeros)
        header["qt_total"] = int(win.qt_total)
        header["qt_alpha"] = float(win.qt_alpha)
        header["qt_min_value"] = float(win.qt_min_value)
    # accuracy plane headers ride only when carried, so plane-off wire
    # bytes (and the approx-false common case) are unchanged
    if win.approx:
        header["approx"] = True
    if win.rs_keys is not None:
        header["rs_capacity"] = int(win.rs_capacity)
    return header, buf.getvalue()


def decode_window(header: dict, payload: bytes) -> SealedWindow:
    with np.load(io.BytesIO(payload)) as z:
        arrays = {k: z[k] for k in z.files}
    skeys = list(header.get("keys") or [])
    slices: dict[str, dict] = {}
    if skeys and "slice_events" in arrays:
        for i, key in enumerate(skeys):
            hh_k = arrays["slice_hh_keys"][i]
            hh_c = arrays["slice_hh_counts"][i]
            slices[key] = {
                "events": int(arrays["slice_events"][i]),
                "hll": arrays["slice_hll"][i],
                "ent": arrays["slice_ent"][i],
                "hh": [(int(k), int(c)) for k, c in zip(hh_k, hh_c) if k],
            }
    return SealedWindow(
        gadget=header.get("gadget", ""),
        node=header.get("node", ""),
        run_id=header.get("run_id", ""),
        window=int(header.get("window", 0)),
        start_ts=float(header.get("start_ts", 0.0)),
        end_ts=float(header.get("end_ts", 0.0)),
        events=int(header.get("events", 0)),
        drops=int(header.get("drops", 0)),
        cms=arrays["cms"],
        hll=arrays["hll"],
        ent=arrays["ent"],
        topk_keys=arrays["topk_keys"],
        topk_counts=arrays["topk_counts"],
        slices=slices,
        names={int(k): v for k, v in (header.get("names") or {}).items()},
        slices_dropped=int(header.get("slices_dropped", 0)),
        seq=int(header.get("seq", 0)),
        digest=header.get("digest", ""),
        level=int(header.get("level", 0)),
        compacted_from=list(header.get("compacted_from") or []),
        inv_count=arrays.get("inv_count"),
        inv_keysum=arrays.get("inv_keysum"),
        inv_fpsum=arrays.get("inv_fpsum"),
        qt_counts=arrays.get("qt_counts"),
        qt_zeros=int(header.get("qt_zeros", 0)),
        qt_total=int(header.get("qt_total", 0)),
        qt_alpha=float(header.get("qt_alpha", 0.01)),
        qt_min_value=float(header.get("qt_min_value", 1.0)),
        approx=bool(header.get("approx", False)),
        rs_keys=arrays.get("rs_keys"),
        rs_weights=arrays.get("rs_weights"),
        rs_capacity=int(header.get("rs_capacity", 0)),
    )


def header_overlaps(header: dict, *, start_ts: float | None = None,
                    end_ts: float | None = None,
                    start_seq: int | None = None,
                    end_seq: int | None = None,
                    key: str | None = None) -> bool:
    """Does one ListWindows header row overlap the requested range/slice?
    The ONE overlap rule the agent RPC, the store's local reads, and the
    fan-out client all share — three copies would drift."""
    if start_ts is not None and float(header.get("end_ts", 0.0)) < start_ts:
        return False
    if end_ts is not None and float(header.get("start_ts", 0.0)) > end_ts:
        return False
    seq = int(header.get("seq", 0))
    if start_seq is not None and seq and seq < start_seq:
        return False
    if end_seq is not None and seq and seq > end_seq:
        return False
    if key and key not in (header.get("keys") or []):
        return False
    return True


# ---------------------------------------------------------------------------
# Merge algebra (query time)
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class MergedWindows:
    """Lazy-merged view over N sealed windows — the disaggregation
    paper's query-side fold. All fields are plain host state so answers
    render without device round-trips."""

    windows: int
    nodes: list[str]
    start_ts: float
    end_ts: float
    events: int
    drops: int
    cms: np.ndarray | None
    hll: np.ndarray | None
    ent: np.ndarray | None
    candidates: dict[int, int]       # key32 → summed top-k estimate
    slices: dict[str, dict]
    names: dict[int, str]
    skipped: list[str]               # windows dropped from the merge (why)
    # invertible plane fold (elementwise add); None when any folded
    # window lacked the plane or disagreed on geometry — the answer then
    # says so (skipped note) instead of decoding partial coverage
    inv_count: np.ndarray | None = None
    inv_keysum: np.ndarray | None = None
    inv_fpsum: np.ndarray | None = None
    # DDSketch fold (bucket-wise add); None when any folded window
    # lacked the plane or pinned different bucket boundaries
    # (alpha/min_value) — partial quantile coverage must not read as
    # total, so the answer drops the plane WITH a skipped note
    qt_counts: np.ndarray | None = None
    qt_zeros: int = 0
    qt_total: int = 0
    qt_alpha: float = 0.01
    qt_min_value: float = 1.0
    # accuracy plane: approx ORs over every consulted window (ANY
    # overflowed window taints the merged top-k — no coverage rule can
    # un-taint it); the shadow sample folds under the qt total-coverage
    # rule (merge is exact only while every window carries a matching
    # capacity)
    approx: bool = False
    rs: "object | None" = None       # ops.accuracy.ShadowSample

    def accuracy(self, heavy: list[tuple[int, int]] | None = None) -> dict | None:
        """The accuracy block for this merged range: analytic envelopes
        always (geometry is read off the merged arrays), observed error
        when the shadow plane folded with total coverage. None only for
        an empty merge (no geometry to derive bounds from)."""
        if self.cms is None or self.windows <= 0:
            return None
        from ..ops.accuracy import accuracy_block
        depth, width = self.cms.shape
        hh = heavy if heavy is not None else self.heavy_hitters(20)
        return accuracy_block(
            events=float(self.events),
            depth=int(depth), width=int(width),
            hll_p=int(np.log2(max(self.hll.shape[0], 2))),
            ent_log2_width=int(np.log2(max(self.ent.shape[0], 2))),
            distinct=self.distinct(),
            entropy_bits=self.entropy_bits(),
            hh_keys=np.array([k for k, _ in hh], np.uint32),
            hh_counts=np.array([c for _, c in hh], np.int64),
            qt_alpha=(float(self.qt_alpha) if self.qt_counts is not None
                      else None),
            shadow=self.rs,
        )

    def quantile(self, q) -> float | np.ndarray:
        """Value at quantile q over the merged range (<= alpha relative
        error — dd_merge is lossless, so the merged read is exactly the
        read of the union stream). NaN when the plane is absent."""
        if self.qt_counts is None:
            return float("nan") if np.ndim(q) == 0 else np.full(
                np.shape(q), np.nan)
        from ..ops.quantiles import dd_quantile_np
        out = dd_quantile_np(self.qt_counts, self.qt_zeros, self.qt_total,
                             q, alpha=self.qt_alpha,
                             min_value=self.qt_min_value)
        return float(out) if np.ndim(q) == 0 else out

    def quantile_answer(self) -> dict | None:
        """The standard quantile block (summary/CLI shape), or None when
        the plane is absent from the merged range."""
        if self.qt_counts is None:
            return None
        ps = self.quantile([0.50, 0.90, 0.99, 0.999])
        ps = np.nan_to_num(np.asarray(ps), nan=0.0)
        return {"p50": float(ps[0]), "p90": float(ps[1]),
                "p99": float(ps[2]), "p999": float(ps[3]),
                "zeros": int(self.qt_zeros), "total": int(self.qt_total),
                "underflow": int(self.qt_counts[0]),
                "alpha": float(self.qt_alpha)}

    def histogram_log2(self, n_slots: int = 32) -> np.ndarray | None:
        """biolatency-style log2 re-binning of the merged DDSketch row
        (ASCII render input): slot k counts values in [2^k, 2^(k+1)) of
        the lane's raw unit (ns for latency sources). None when the
        plane is absent."""
        if self.qt_counts is None:
            return None
        from ..ops.quantiles import dd_histogram_log2_np
        return dd_histogram_log2_np(self.qt_counts, alpha=self.qt_alpha,
                                    min_value=self.qt_min_value,
                                    n_slots=n_slots, unit_scale=1.0)

    def heavy_flows(self, top: int = 0,
                    min_count: int = 1) -> list[tuple[int, int]]:
        """Decode the merged invertible plane → exact (key32, count)
        pairs for the merged range, recovered from state alone (no
        candidate ring). Empty when the plane is absent/incomplete."""
        if self.inv_count is None:
            return []
        from ..ops.invertible import inv_decode
        dec = inv_decode((self.inv_count, self.inv_keysum,
                          self.inv_fpsum), min_count=min_count)
        return dec.keys[:top] if top else dec.keys

    def heavy_flow_decode(self):
        """Full decode result (keys + completeness accounting), or None
        when the plane is absent."""
        if self.inv_count is None:
            return None
        from ..ops.invertible import inv_decode
        return inv_decode((self.inv_count, self.inv_keysum,
                           self.inv_fpsum))

    def distinct(self) -> float:
        if self.hll is None:
            return 0.0
        return slice_hll_estimate(self.hll)

    def entropy_bits(self) -> float:
        if self.ent is None:
            return 0.0
        return entropy_bits(self.ent)

    def heavy_hitters(self, k: int = 20) -> list[tuple[int, int]]:
        # (-count, key) like merged_to_sealed: a stable -count sort
        # would break ties by dict insertion order, making the rendered
        # top-k depend on fold shape (flat vs incremental)
        order = sorted(self.candidates.items(),
                       key=lambda kv: (-kv[1], kv[0]))
        return [(key, int(c)) for key, c in order[:k] if key][:k]

    def slice_answer(self, key: str) -> dict | None:
        s = self.slices.get(key)
        if s is None:
            return None
        return {
            "key": key,
            "events": int(s["events"]),
            "distinct": slice_hll_estimate(s["hll"]),
            "entropy_bits": entropy_bits(s["ent"]),
            "heavy_hitters": sorted(
                s["hh"].items(),
                key=lambda kv: (-kv[1], kv[0]))[:SLICE_HH_K],
        }


def merge_windows(windows: Iterable[SealedWindow]) -> MergedWindows:
    """Fold sealed windows into one answer: CMS/entropy add, HLL max,
    top-k candidates union with summed per-window estimates, slices
    merge key-wise with the same algebra. Windows whose sketch geometry
    disagrees with the first window's are skipped AND reported — a
    silent shape coercion would corrupt every estimate downstream."""
    out = MergedWindows(windows=0, nodes=[], start_ts=0.0, end_ts=0.0,
                        events=0, drops=0, cms=None, hll=None, ent=None,
                        candidates={}, slices={}, names={}, skipped=[])
    inv_dropped = False
    qt_dropped = False
    rs_dropped = False

    def qt_matches(win: SealedWindow) -> bool:
        return (win.qt_counts.shape == out.qt_counts.shape
                and float(win.qt_alpha) == float(out.qt_alpha)
                and float(win.qt_min_value) == float(out.qt_min_value))

    def rs_of(win: SealedWindow):
        from ..ops.accuracy import ShadowSample
        return ShadowSample(win.rs_capacity, win.rs_keys, win.rs_weights)

    for win in windows:
        if out.cms is not None and (
                win.cms.shape != out.cms.shape
                or win.hll.shape != out.hll.shape
                or win.ent.shape != out.ent.shape):
            out.skipped.append(
                f"{win.node}/{win.gadget} window {win.window}: sketch "
                f"geometry {win.cms.shape}/{win.hll.shape}/{win.ent.shape} "
                "differs from the merge base")
            continue
        if out.cms is None:
            out.cms = win.cms.astype(np.int64).copy()
            out.hll = win.hll.copy()
            out.ent = win.ent.astype(np.float64).copy()
            out.start_ts, out.end_ts = win.start_ts, win.end_ts
            if win.inv_count is not None:
                out.inv_count = win.inv_count.astype(np.int64).copy()
                out.inv_keysum = win.inv_keysum.astype(np.uint32).copy()
                out.inv_fpsum = win.inv_fpsum.astype(np.uint32).copy()
            if win.qt_counts is not None:
                out.qt_counts = win.qt_counts.astype(np.int64).copy()
                out.qt_zeros = int(win.qt_zeros)
                out.qt_total = int(win.qt_total)
                out.qt_alpha = float(win.qt_alpha)
                out.qt_min_value = float(win.qt_min_value)
            if win.rs_keys is not None:
                out.rs = rs_of(win)
        else:
            out.cms += win.cms.astype(np.int64)
            np.maximum(out.hll, win.hll, out=out.hll)
            out.ent += win.ent.astype(np.float64)
            out.start_ts = min(out.start_ts, win.start_ts)
            out.end_ts = max(out.end_ts, win.end_ts)
        # invertible plane: fold while EVERY window carries a matching
        # geometry; one window without it (or shaped differently) makes
        # decode-of-the-range meaningless, so the plane is dropped from
        # the answer WITH a note — partial coverage must not decode as
        # if it were total
        if out.windows > 0:
            if win.inv_count is None:
                if out.inv_count is not None and not inv_dropped:
                    inv_dropped = True
                    out.skipped.append(
                        f"{win.node}/{win.gadget} window {win.window}: no "
                        "invertible plane — heavy-flow decode disabled "
                        "for this range (partial coverage would lie)")
                out.inv_count = out.inv_keysum = out.inv_fpsum = None
            elif out.inv_count is not None:
                if win.inv_count.shape != out.inv_count.shape:
                    inv_dropped = True
                    out.skipped.append(
                        f"{win.node}/{win.gadget} window {win.window}: "
                        f"invertible geometry {win.inv_count.shape} "
                        "differs from the merge base — heavy-flow decode "
                        "disabled for this range")
                    out.inv_count = out.inv_keysum = out.inv_fpsum = None
                else:
                    out.inv_count += win.inv_count.astype(np.int64)
                    out.inv_keysum += win.inv_keysum.astype(np.uint32)
                    out.inv_fpsum += win.inv_fpsum.astype(np.uint32)
            elif not inv_dropped and win.inv_count is not None:
                inv_dropped = True
                out.skipped.append(
                    f"{win.node}/{win.gadget} window {win.window}: "
                    "invertible plane present but an earlier window "
                    "lacked it — heavy-flow decode disabled for this "
                    "range")
        # quantile plane: same total-coverage rule as the invertible
        # fold — bucket counts add only while EVERY window carries the
        # plane with the SAME bucket boundaries (alpha/min_value pin the
        # log base); anything else drops the plane from the answer WITH
        # a note, because a partial or mixed-base fold would render
        # confident-looking but wrong percentiles
        if out.windows > 0:
            if win.qt_counts is None:
                if out.qt_counts is not None and not qt_dropped:
                    qt_dropped = True
                    out.skipped.append(
                        f"{win.node}/{win.gadget} window {win.window}: no "
                        "quantile plane — latency quantiles disabled for "
                        "this range (partial coverage would lie)")
                out.qt_counts = None
            elif out.qt_counts is not None:
                if not qt_matches(win):
                    qt_dropped = True
                    out.skipped.append(
                        f"{win.node}/{win.gadget} window {win.window}: "
                        f"quantile geometry {win.qt_counts.shape}/"
                        f"alpha={win.qt_alpha}/min={win.qt_min_value} "
                        "differs from the merge base — latency quantiles "
                        "disabled for this range")
                    out.qt_counts = None
                else:
                    out.qt_counts += win.qt_counts.astype(np.int64)
                    out.qt_zeros += int(win.qt_zeros)
                    out.qt_total += int(win.qt_total)
            elif not qt_dropped:
                qt_dropped = True
                out.skipped.append(
                    f"{win.node}/{win.gadget} window {win.window}: "
                    "quantile plane present but an earlier window lacked "
                    "it — latency quantiles disabled for this range")
        # shadow-sample plane: the qt total-coverage rule — a ground
        # truth over part of the range must not audit answers over all
        # of it, so one window without the plane (or with a different
        # capacity) drops the observed-error audit WITH a note; the
        # analytic envelopes survive regardless (geometry still merges)
        if out.windows > 0:
            if win.rs_keys is None:
                if out.rs is not None and not rs_dropped:
                    rs_dropped = True
                    out.skipped.append(
                        f"{win.node}/{win.gadget} window {win.window}: no "
                        "shadow sample — observed-error audit disabled "
                        "for this range (partial ground truth would lie)")
                out.rs = None
            elif out.rs is not None:
                if int(win.rs_capacity) != int(out.rs.capacity):
                    rs_dropped = True
                    out.skipped.append(
                        f"{win.node}/{win.gadget} window {win.window}: "
                        f"shadow capacity {win.rs_capacity} differs from "
                        f"the merge base {out.rs.capacity} — "
                        "observed-error audit disabled for this range")
                    out.rs = None
                else:
                    out.rs = out.rs.merge(rs_of(win))
            elif not rs_dropped:
                rs_dropped = True
                out.skipped.append(
                    f"{win.node}/{win.gadget} window {win.window}: "
                    "shadow sample present but an earlier window lacked "
                    "it — observed-error audit disabled for this range")
        # candidate-overflow taint ORs unconditionally: one overflowed
        # window makes the merged top-k approximate no matter how many
        # clean windows join it (the seal-boundary bugfix)
        out.approx = out.approx or bool(win.approx)
        out.windows += 1
        if win.node and win.node not in out.nodes:
            out.nodes.append(win.node)
        out.events += int(win.events)
        out.drops += int(win.drops)
        for key, c in zip(win.topk_keys.tolist(), win.topk_counts.tolist()):
            if key:
                out.candidates[key] = out.candidates.get(key, 0) + int(c)
        out.names.update(win.names or {})
        for skey, s in win.slices.items():
            dst = out.slices.get(skey)
            if dst is None:
                out.slices[skey] = {
                    "events": int(s["events"]),
                    "hll": np.array(s["hll"], dtype=np.uint8, copy=True),
                    "ent": s["ent"].astype(np.int64).copy(),
                    "hh": dict(s["hh"]),
                }
                continue
            if dst["hll"].shape != s["hll"].shape or \
                    dst["ent"].shape != s["ent"].shape:
                out.skipped.append(
                    f"{win.node}/{win.gadget} window {win.window}: slice "
                    f"{skey!r} geometry differs from the merge base")
                continue
            dst["events"] += int(s["events"])
            np.maximum(dst["hll"], s["hll"], out=dst["hll"])
            dst["ent"] += s["ent"].astype(np.int64)
            for k, c in s["hh"]:
                dst["hh"][k] = dst["hh"].get(k, 0) + c
    return out


def provenance_row(win: SealedWindow) -> dict:
    """One compacted_from entry: enough to audit that the source's
    seq/ts coverage landed in exactly one super-window, and to dedup a
    source that survived a crash between super-window append and GC."""
    return {"digest": win.digest, "seq": int(win.seq),
            "window": int(win.window), "run_id": win.run_id,
            "start_ts": float(win.start_ts), "end_ts": float(win.end_ts),
            "level": int(win.level)}


def merged_to_sealed(merged: MergedWindows, *, gadget: str, node: str,
                     level: int = 0, window: int = 0, run_id: str = "",
                     compacted_from: list[dict] | None = None,
                     ) -> SealedWindow:
    """MergedWindows → one SealedWindow — the shape both the compaction
    engine (a super-window per time bucket) and the QueryWindows
    pushdown reply (one merged window per node) seal a fold into. The
    candidate union is kept WHOLE (bounded by windows × top-k), so the
    additive planes and top-k estimates survive re-merging downstream
    with no extra truncation error at this boundary."""
    # tie-break by key, not just estimate: a stable -count sort would
    # leak dict insertion order into the sealed bytes, making the digest
    # depend on fold SHAPE (flat left-fold vs the standing-query plane's
    # pairwise incremental fold). (-count, key) is a pure function of
    # the candidate multiset, so every fold shape seals byte-identically.
    cand = sorted(merged.candidates.items(), key=lambda kv: (-kv[1], kv[0]))
    slices: dict[str, dict] = {}
    for skey, s in merged.slices.items():
        slices[skey] = {
            "events": int(s["events"]),
            "hll": s["hll"],
            "ent": s["ent"],
            "hh": sorted(s["hh"].items(), key=lambda kv: (-kv[1], kv[0])),
        }
    win = SealedWindow(
        gadget=gadget, node=node, run_id=run_id, window=int(window),
        start_ts=float(merged.start_ts), end_ts=float(merged.end_ts),
        events=int(merged.events), drops=int(merged.drops),
        cms=(merged.cms if merged.cms is not None
             else np.zeros((1, 1), np.int64)),
        hll=(merged.hll if merged.hll is not None
             else np.zeros(1, np.int32)),
        ent=(merged.ent if merged.ent is not None
             else np.zeros(1, np.float64)),
        topk_keys=np.array([k for k, _ in cand], dtype=np.uint32),
        topk_counts=np.array([c for _, c in cand], dtype=np.int64),
        slices=slices,
        names=dict(merged.names),
        level=int(level),
        compacted_from=list(compacted_from or []),
        # the count lane stays int64 on the compaction/pushdown write
        # path: a super-window can cover an unbounded range, and an
        # int32 downcast past 2^31 would wrap consistently with the
        # mod-2^32 key-sum/fingerprint lanes — decoding to a plausible
        # but WRONG "exact" count. int64 counts decode exactly (only
        # the sum lanes are modular); merge_windows already folds mixed
        # int32 (operator-sealed deltas) and int64 windows in int64.
        inv_count=(merged.inv_count if merged.inv_count is not None
                   else None),
        inv_keysum=(merged.inv_keysum if merged.inv_keysum is not None
                    else None),
        inv_fpsum=(merged.inv_fpsum if merged.inv_fpsum is not None
                   else None),
        # the quantile fold rides the same int64 write path: a
        # super-window's bucket counts can exceed int32 over an
        # unbounded range; merge_windows folds mixed int32/int64 in
        # int64 already
        qt_counts=(merged.qt_counts if merged.qt_counts is not None
                   else None),
        qt_zeros=int(merged.qt_zeros),
        qt_total=int(merged.qt_total),
        qt_alpha=float(merged.qt_alpha),
        qt_min_value=float(merged.qt_min_value),
        # accuracy plane survives re-sealing (compaction, pushdown,
        # standing-query folds): the taint flag rides through, and the
        # merged shadow — itself bit-identical to a single-pass sample
        # of the union stream — re-seals as this window's lanes
        approx=bool(merged.approx),
        rs_keys=(merged.rs.keys if merged.rs is not None else None),
        rs_weights=(merged.rs.weights if merged.rs is not None else None),
        rs_capacity=(int(merged.rs.capacity) if merged.rs is not None
                     else 0),
    )
    win.digest = window_digest(win)
    return win


__all__ = ["MergedWindows", "SLICE_ENT_LOG2_WIDTH", "SLICE_HH_K",
           "SLICE_HLL_P", "SealedWindow", "SliceSketch", "WINDOW_SCHEMA",
           "decode_window", "encode_window", "entropy_bits",
           "header_overlaps", "merge_windows", "merged_to_sealed",
           "provenance_row", "slice_hll_estimate", "window_digest"]
