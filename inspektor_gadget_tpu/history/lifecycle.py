"""Tiered history lifecycle: time-decayed compaction of sealed windows.

The PR-6 history plane seals windows at ONE resolution forever — fine
for hours, wrong for months: store size grows without bound and every
fleet query pays O(windows). Following the resolution-over-time idea in
"Sketch Disaggregation Across Time and Space" (arxiv 2503.13515) — old
data keeps answering queries, just at coarser resolution — retention
becomes a *policy*: a resolution schedule like

    1m@24h,10m@7d,1h@inf

reads "keep native (~1m) windows for 24h; older than that, merge into
10m super-windows; older than 7d, into 1h super-windows; the last level
is kept forever (or until the archive tier offloads it)". Each entry is
``<resolution>@<horizon>``; both sides are Go-style durations (plus a
``d`` day suffix), the final horizon must be ``inf``/``∞``.

The CompactionEngine walks a store's SEALED segments (the active one is
never touched) against the schedule and, per aged source window, folds
it into the super-window of its target-level time bucket via the
existing merge algebra — CMS/entropy add, HLL max, candidate union,
slice-key union — so compaction adds NO error beyond the coarser time
resolution itself (the sketches are homomorphic). Crash discipline is
the journal's, extended one step:

1. every super-window is ONE appended frame (CRC'd, O_APPEND) through
   the store's own writer, carrying a ``compacted_from`` provenance
   list (one {digest, seq, ts-range} row per source window);
2. the active segment is fsync'd, then force-rotated so super-windows
   get their own index row;
3. ONLY then are the source segments deleted (under the writer lock,
   never the active segment).

A SIGKILL anywhere in that sequence loses no coverage: sources survive
until step 3, and a query that sees both a super-window and its
not-yet-GC'd sources dedups by digest (history/query.py
dedupe_compacted) — exactly-once by construction. The next compaction
pass recognizes covered sources and finishes the GC without re-merging.
"""

from __future__ import annotations

import dataclasses
import math
import os
import re
import threading
import time
from typing import Callable

from ..params.validators import parse_duration
from ..telemetry import counter
from ..utils.logger import get_logger

log = get_logger("ig-tpu.history.lifecycle")

DEFAULT_SCHEDULE = "1m@24h,10m@7d,1h@inf"

_tm_compactions = counter(
    "ig_history_compactions_total",
    "compaction passes that rewrote aged windows into super-windows "
    "(or finished a crashed pass's source GC)")
_tm_compacted = counter(
    "ig_history_compacted_windows_total",
    "source windows folded into coarser super-windows, by target level",
    ("level",))
_tm_reclaimed = counter(
    "ig_history_compaction_reclaimed_bytes_total",
    "bytes of source segments deleted after their super-windows became "
    "durable")

_INF = ("inf", "infinite", "∞")
_DAYS = re.compile(r"^(\d+(?:\.\d+)?)d(.*)$")


def _parse_span(s: str) -> float:
    """Duration grammar of the schedule: parse_duration plus a leading
    ``<n>d`` day term (retention policies speak in days) and ``inf``."""
    s = s.strip()
    if s.lower() in _INF:
        return math.inf
    total = 0.0
    m = _DAYS.match(s)
    if m:
        total += float(m.group(1)) * 86400.0
        s = m.group(2)
        if not s:
            return total
    return total + parse_duration(s)


@dataclasses.dataclass(frozen=True)
class ScheduleLevel:
    """One tier: windows live at `resolution` until `horizon` old."""
    resolution: float    # target super-window length, seconds
    horizon: float       # age past which this level compacts upward


def parse_schedule(spec: str) -> list[ScheduleLevel]:
    """``res@horizon[,res@horizon...]`` → validated levels. Loud on
    every malformation: this is the params-layer validator, and a bad
    retention policy must fail the run before the first seal, not eat
    history later."""
    entries = [e.strip() for e in (spec or "").split(",") if e.strip()]
    if not entries:
        raise ValueError(f"empty resolution schedule {spec!r}")
    levels: list[ScheduleLevel] = []
    for i, entry in enumerate(entries):
        res_s, sep, hor_s = entry.partition("@")
        if not sep or not res_s.strip() or not hor_s.strip():
            raise ValueError(
                f"schedule entry {entry!r} is not <resolution>@<horizon>")
        try:
            res = _parse_span(res_s)
            hor = _parse_span(hor_s)
        except ValueError as e:
            raise ValueError(f"schedule entry {entry!r}: {e}") from None
        if not math.isfinite(res) or res <= 0:
            raise ValueError(
                f"schedule entry {entry!r}: resolution must be a finite "
                "positive duration")
        if hor <= 0:
            raise ValueError(
                f"schedule entry {entry!r}: horizon must be > 0")
        levels.append(ScheduleLevel(resolution=res, horizon=hor))
    for a, b in zip(levels, levels[1:]):
        if b.resolution <= a.resolution:
            raise ValueError(
                f"schedule {spec!r}: resolutions must strictly coarsen "
                f"({b.resolution:g}s after {a.resolution:g}s)")
        if b.horizon <= a.horizon:
            raise ValueError(
                f"schedule {spec!r}: horizons must strictly grow "
                f"({b.horizon:g}s after {a.horizon:g}s)")
    if math.isfinite(levels[-1].horizon):
        raise ValueError(
            f"schedule {spec!r}: the last horizon must be inf — data "
            "either lives forever at the coarsest level or moves to the "
            "archive tier, it never silently vanishes")
    for lvl in levels[:-1]:
        if not math.isfinite(lvl.horizon):
            raise ValueError(
                f"schedule {spec!r}: only the last horizon may be inf")
    return levels


def validate_schedule(value: str) -> None:
    """ParamDesc validator shim (raises ValueError, returns nothing)."""
    parse_schedule(value)


class CompactionEngine:
    """Background compactor for history stores. One engine serves any
    number of stores; a per-store lock serializes passes against each
    other, and every mutation of store files goes through the store's
    own _WindowJournal writer lock so compaction coexists with the
    active sealer and with retention GC (which runs under that same
    lock inside append)."""

    def __init__(self, schedule: str | list[ScheduleLevel]
                 = DEFAULT_SCHEDULE, *,
                 store=None, clock: Callable[[], float] = time.time):
        self.schedule = (parse_schedule(schedule)
                         if isinstance(schedule, str) else list(schedule))
        self._store = store
        self.clock = clock
        self._mu = threading.Lock()
        self._locks: dict[str, threading.Lock] = {}
        self._last_pass: dict[str, float] = {}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # test-only crash-injection point: called after super-windows are
        # durable (fsync + rotate) and BEFORE source GC — the widest
        # window in which a SIGKILL leaves both tiers on disk
        self._before_gc: Callable[[], None] | None = None

    @property
    def store(self):
        if self._store is None:
            from .store import HISTORY
            self._store = HISTORY
        return self._store

    def _lock_for(self, store_dir: str) -> threading.Lock:
        with self._mu:
            return self._locks.setdefault(os.path.abspath(store_dir),
                                          threading.Lock())

    # -- one pass over one store -------------------------------------------

    def compact_store(self, store_dir: str) -> dict:
        """Fold every fully-aged sealed segment's windows into coarser
        super-windows, then GC the sources. Returns the pass stats. A
        sealed segment is compactable only when EVERY window in it is
        past its level's horizon (and below the final level) or already
        covered by a durable super-window — partial segments wait, so a
        source segment is deleted exactly once and only whole."""
        from ..agent import wire
        from ..capture.journal import JournalReader, scan_segment
        from .store import HISTORY_METRICS
        from .window import (decode_window, encode_window, merge_windows,
                             merged_to_sealed, provenance_row)
        stats = {"store": os.path.basename(store_dir), "source_windows": 0,
                 "super_windows": 0, "segments_deleted": 0,
                 "bytes_reclaimed": 0, "levels": {}}
        final = len(self.schedule) - 1
        if final < 1:
            return stats  # single-level schedule: nothing ever compacts
        with self._lock_for(store_dir):
            reader = JournalReader(store_dir, metrics=HISTORY_METRICS)
            sealed = {str(row.get("file", "")) for row in reader.index}
            # digests already covered by a durable super-window anywhere
            # in the store (crash recovery: their sources just need GC)
            covered: set[str] = set()
            for header, _p in reader.records(types=(wire.EV_WINDOW,)):
                for row in header.get("compacted_from") or []:
                    if row.get("digest"):
                        covered.add(row["digest"])
            now = self.clock()
            candidates: list[tuple[str, list]] = []  # (segname, to_merge)
            for seg in reader._segment_files():
                name = os.path.basename(seg)
                if name not in sealed:
                    continue  # the active segment is NEVER compacted
                records, loss = scan_segment(seg)
                if loss is not None or not records:
                    continue  # torn sealed segment: readers account it
                to_merge = []
                eligible = True
                for h, p in records:
                    if h.get("type") != wire.EV_WINDOW:
                        eligible = False
                        break
                    if h.get("digest") in covered:
                        continue  # already folded by a crashed pass
                    lvl = int(h.get("level", 0))
                    if lvl >= final:
                        eligible = False  # coarsest tier: archive's job
                        break
                    horizon = self.schedule[min(lvl, final)].horizon
                    if now - float(h.get("end_ts", 0.0)) <= horizon:
                        eligible = False  # still inside its level's life
                        break
                    to_merge.append((h, p))
                if eligible:
                    candidates.append((name, to_merge))
            if not candidates:
                return stats
            writer = self.store.writer_for_dir(store_dir)
            # bucket by (target level, time bucket, sketch geometry):
            # geometry rides the key so merge_windows never has to skip
            # a window inside a bucket — a skipped window would lose
            # coverage when its segment is GC'd
            buckets: dict[tuple, list] = {}
            for _name, to_merge in candidates:
                for h, p in to_merge:
                    win = decode_window(h, p)
                    win.seq = int(h.get("seq", 0))
                    tgt = min(win.level + 1, final)
                    res = self.schedule[tgt].resolution
                    bucket = math.floor(win.start_ts / res)
                    geom = (win.cms.shape, win.hll.shape, win.ent.shape)
                    buckets.setdefault((tgt, bucket, geom), []).append(win)
            folded: set[str] = set()   # digests durably merged this pass
            for (tgt, bucket, _geom), wins in sorted(
                    buckets.items(), key=lambda kv: kv[0][:2]):
                merged = merge_windows(wins)
                if merged.skipped:
                    # the bucket key covers the MAIN sketch geometry but
                    # a slice plane can still mismatch (windows sealed
                    # by a build with different slice constants). A
                    # partial merge would silently drop that slice's
                    # coverage when the sources are GC'd — leave the
                    # whole bucket at its current level and report.
                    for note in merged.skipped:
                        log.warning("compaction skipped a bucket: %s",
                                    note)
                    stats["skipped_buckets"] = \
                        stats.get("skipped_buckets", 0) + 1
                    continue
                sw = merged_to_sealed(
                    merged, gadget=wins[0].gadget, node=wins[0].node,
                    level=tgt, window=bucket, run_id="compaction",
                    compacted_from=[provenance_row(w) for w in wins])
                header, payload = encode_window(sw)
                writer.append_window_frame(header, payload, sw.slice_keys,
                                           sw.end_ts or None)
                stats["super_windows"] += 1
                stats["source_windows"] += len(wins)
                stats["levels"][tgt] = stats["levels"].get(tgt, 0) + 1
                _tm_compacted.labels(level=str(tgt)).inc(len(wins))
                folded.update(w.digest for w in wins if w.digest)
            # durability barrier: the super-window frames (and their
            # index row) must survive a crash BEFORE any source vanishes
            writer.sync()
            writer.rotate()
            if self._before_gc is not None:
                self._before_gc()
            # a segment is deletable only when EVERY window it holds is
            # now covered: previously covered, or folded into a durable
            # super-window this pass (a skipped bucket keeps its
            # sources' segments whole)
            deletable = [
                name for name, to_merge in candidates
                if all(h.get("digest") in folded for h, _p in to_merge)]
            deleted, freed = writer.remove_segments(deletable)
            stats["segments_deleted"] = deleted
            stats["bytes_reclaimed"] = freed
            _tm_reclaimed.inc(freed)
            _tm_compactions.inc()
            log.info("compacted %s: %d window(s) -> %d super-window(s), "
                     "%d segment(s) GC'd, %d bytes reclaimed",
                     stats["store"], stats["source_windows"],
                     stats["super_windows"], deleted, freed)
            return stats

    def compact_all(self, base_dir: str | None = None) -> list[dict]:
        """One pass over every store under the base area."""
        out = []
        for store_dir in self.store.store_dirs(base_dir):
            try:
                out.append(self.compact_store(store_dir))
            except (OSError, ValueError) as e:  # per-store isolation
                log.warning("compaction pass failed for %s: %r",
                            store_dir, e)
                out.append({"store": os.path.basename(store_dir),
                            "error": str(e)})
        return out

    def maybe_compact(self, store_dir: str,
                      min_interval: float = 30.0) -> dict | None:
        """Seal-path hook: run a pass at most every min_interval
        (wall-gated on monotonic time — the aging clock may be a
        replay/sim clock and must not gate pass cadence)."""
        key = os.path.abspath(store_dir)
        now = time.monotonic()
        with self._mu:
            last = self._last_pass.get(key, -math.inf)
            if now - last < min_interval:
                return None
            self._last_pass[key] = now
        return self.compact_store(store_dir)

    # -- background loop ----------------------------------------------------

    def start_background(self, interval: float = 60.0,
                         base_dir: str | None = None) -> None:
        """Agent-side background compactor; idempotent."""
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()

        def loop():
            while not self._stop.wait(interval):
                self.compact_all(base_dir)

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="ig-history-compactor")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
        self._thread = None


__all__ = ["CompactionEngine", "DEFAULT_SCHEDULE", "ScheduleLevel",
           "parse_schedule", "validate_schedule"]
