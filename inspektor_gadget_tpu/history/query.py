"""Historical range queries over sealed windows.

The query side of the disaggregation design: windows were sealed
per-node with zero coordination; answering "cardinality of tenant X,
2–3pm, across nodes" is (1) prune — only windows whose [start_ts,
end_ts] overlap the range and whose key set contains the slice, (2)
pull — fetch just those windows' frames, (3) fold — the merge algebra
in history/window.py. This module owns (3) plus the frame packing the
FetchWindows RPC ships pulled windows in.

Error bounds are the constituent sketches' (documented in
docs/observability.md): CMS overestimates by ≤ N·e/width per row-min,
HLL standard error ≈ 1.04/√m, entropy biased down slightly by bucket
collisions; merging sealed windows adds NO further error (the sketches
are homomorphic: update-then-merge ≡ merge-then-update).
"""

from __future__ import annotations

import dataclasses
import zlib
from typing import Iterable

from ..agent import wire
from .window import SealedWindow, decode_window, merge_windows

# one packed frame = u32 length | u32 crc32(zpayload) | zpayload — the
# exact journal segment framing, so a fetched byte stream tolerates a
# truncated tail the same way a segment file does
_FRAME_HEADER = 8


def pack_frames(frames: Iterable[tuple[dict, bytes]]) -> bytes:
    out = bytearray()
    for header, payload in frames:
        zp = zlib.compress(wire.encode_msg(header, payload), 1)
        out += len(zp).to_bytes(4, "little")
        out += (zlib.crc32(zp) & 0xFFFFFFFF).to_bytes(4, "little")
        out += zp
    return bytes(out)


def unpack_frames(data: bytes) -> tuple[list[tuple[dict, bytes]], int]:
    """(frames, dropped_bytes): a short/undecodable tail is dropped and
    accounted, never half-decoded — the torn-window read contract."""
    from ..capture.journal import _decode_frame, _frame_at
    frames: list[tuple[dict, bytes]] = []
    off, n = 0, len(data)
    while off < n:
        end, zpayload, reason = _frame_at(data, off)
        decoded = None if reason else _decode_frame(zpayload)
        if reason or decoded is None:
            return frames, n - off
        frames.append(decoded)
        off = end
    return frames, 0


@dataclasses.dataclass
class QueryAnswer:
    """One rendered range-query result (ig-tpu query's output shape)."""

    windows: int
    nodes: list[str]
    start_ts: float
    end_ts: float
    events: int
    drops: int
    distinct: float
    entropy_bits: float
    heavy_hitters: list[tuple[int, int, str]]   # (key32, count, label)
    slices: dict[str, dict]
    dropped_windows: list[str]      # merges refused (geometry) + torn tails
    errors: dict[str, str]          # per-node fetch errors (never fatal)
    # tier accounting (history/lifecycle.py): windows folded per
    # compaction level — a nonzero level>0 count means part of this
    # answer came from compacted (coarser-resolution) super-windows,
    # and the CLI says so rather than surprising the user with
    # resolution loss. paths records HOW each node answered:
    # "pushdown" (QueryWindows folded node-side), "fetch" (list+fetch
    # fallback for old agents), or "local".
    levels: dict[int, int] = dataclasses.field(default_factory=dict)
    paths: dict[str, str] = dataclasses.field(default_factory=dict)
    # invertible-plane decode of the merged range (ISSUE 15): exact
    # (key32, count, label) rows recovered from merged state alone, the
    # subset of them the candidate ring missed (decoded_only — the
    # observable win over tracked candidates), and the decode's
    # completeness accounting; all empty/None when the range's windows
    # don't (all) carry the plane
    heavy_flows: list[tuple[int, int, str]] = dataclasses.field(
        default_factory=list)
    decoded_only: list[tuple[int, int, str]] = dataclasses.field(
        default_factory=list)
    inv: dict | None = None
    # latency quantile plane (ISSUE 16): {p50, p90, p99, p999, zeros,
    # total, underflow, alpha} read off the merged DDSketch fold, plus
    # the log2 histogram render input; both None when the range's
    # windows don't (all) carry the plane with one bucket geometry
    quantiles: dict | None = None
    histogram: list[int] | None = None
    # accuracy audit plane (ISSUE 19): the per-stat error envelope —
    # analytic bounds ALWAYS (derived client-side from the merged
    # geometry + observed mass, so even plane-off history answers carry
    # them), observed error only when every consulted window carried the
    # shadow sample. `approx` is the candidate-overflow taint: True
    # when ANY consulted window overflowed its top-k candidate ring.
    accuracy: dict | None = None
    approx: bool = False
    # fleet aggregation tier (ISSUE 20): present only when the query was
    # routed through a merge tree — {depth, fan_in, subtree_folds,
    # fallback: [aggregator ids answered flat], aggregate: the root
    # FleetAggregate accounting header}. The answer numbers themselves
    # are byte-identical to the flat fold's (that is the tier's
    # contract); this block records HOW the tree answered.
    fleet: dict | None = None

    def compacted_windows(self) -> int:
        """How many folded windows were coarser than native resolution."""
        return sum(n for lvl, n in self.levels.items() if lvl > 0)

    def to_dict(self) -> dict:
        return {
            "windows": self.windows,
            "nodes": self.nodes,
            "start_ts": self.start_ts,
            "end_ts": self.end_ts,
            "events": self.events,
            "drops": self.drops,
            "distinct": self.distinct,
            "entropy_bits": self.entropy_bits,
            "heavy_hitters": [
                {"key": f"0x{k:08x}", "count": c, "label": label}
                for k, c, label in self.heavy_hitters],
            "heavy_flows": [
                {"key": f"0x{k:08x}", "count": c, "label": label}
                for k, c, label in self.heavy_flows],
            "decoded_only": [
                {"key": f"0x{k:08x}", "count": c, "label": label}
                for k, c, label in self.decoded_only],
            "inv": self.inv,
            "quantiles": self.quantiles,
            "histogram": self.histogram,
            "slices": self.slices,
            "dropped_windows": self.dropped_windows,
            "errors": self.errors,
            "levels": {str(k): v for k, v in sorted(self.levels.items())},
            "compacted_windows": self.compacted_windows(),
            "paths": dict(self.paths),
            "accuracy": self.accuracy,
            "approx": self.approx,
            "fleet": self.fleet,
        }


def dedupe_compacted(windows: Iterable[SealedWindow]
                     ) -> tuple[list[SealedWindow], list[str]]:
    """Exactly-once coverage across tiers: drop (1) any window whose
    digest a present super-window's compacted_from lists — a crash
    between super-window append and source GC leaves both on disk, and
    merging both would double-count — and (2) exact duplicate digests.
    Returns (kept, notes); every drop is reported, never silent."""
    wins = list(windows)
    # dedup is PER NODE: a tier ladder lives inside one node's store,
    # and two nodes ingesting identical traffic legitimately seal
    # byte-identical (same-digest) windows that must BOTH fold
    covered: dict[tuple[str, str], str] = {}
    for w in wins:
        for row in w.compacted_from:
            d = row.get("digest")
            if d:
                covered[(w.node, d)] = \
                    f"{w.node}/{w.gadget} L{w.level} super-window"
    kept: list[SealedWindow] = []
    notes: list[str] = []
    seen: set[tuple[str, str]] = set()
    for w in wins:
        who = f"{w.node}/{w.gadget} window {w.window} (L{w.level})"
        if w.digest and (w.node, w.digest) in covered:
            notes.append(f"{who}: superseded by "
                         f"{covered[(w.node, w.digest)]} "
                         "(compaction source not yet GC'd)")
            continue
        if w.digest and (w.node, w.digest) in seen:
            notes.append(f"{who}: duplicate digest, folded once")
            continue
        if w.digest:
            seen.add((w.node, w.digest))
        kept.append(w)
    return kept, notes


def level_counts(windows: Iterable[SealedWindow]) -> dict[int, int]:
    """Windows folded per compaction level — the consultation
    accounting a query answer carries so resolution loss is visible."""
    out: dict[int, int] = {}
    for w in windows:
        out[w.level] = out.get(w.level, 0) + 1
    return out


def answer_query(windows: Iterable[SealedWindow], *,
                 key: str | None = None, top: int = 20,
                 dropped: list[str] | None = None,
                 errors: dict[str, str] | None = None,
                 levels: dict[int, int] | None = None,
                 paths: dict[str, str] | None = None) -> QueryAnswer:
    """Fold sealed windows into one QueryAnswer. With `key`, the global
    numbers still cover the whole merged traffic and `slices` is
    restricted to that one subpopulation; without it, every observed
    slice is answered. Windows covered by a present super-window are
    deduped (exactly-once across tiers) before the fold; `levels`
    overrides the per-level accounting when the caller already folded
    node-side (pushdown) and holds better counts than the one merged
    window per node left here."""
    kept, dedup_notes = dedupe_compacted(windows)
    merged = merge_windows(kept)
    labels = merged.names
    hh = [(k, c, labels.get(k, f"0x{k:08x}"))
          for k, c in merged.heavy_hitters(top)]
    # invertible plane: decode the merged range (exact counts, no
    # per-key storage) and report what the candidate ring missed
    flows: list[tuple[int, int, str]] = []
    decoded_only: list[tuple[int, int, str]] = []
    inv_info = None
    dec = merged.heavy_flow_decode()
    if dec is not None:
        flows = [(k, c, labels.get(k, f"0x{k:08x}"))
                 for k, c in dec.top(top)]
        ring = set(merged.candidates)
        decoded_only = [(k, c, labels.get(k, f"0x{k:08x}"))
                        for k, c in dec.keys if k not in ring][:top]
        inv_info = {"recovered": dec.recovered,
                    "complete": dec.complete,
                    "residual_events": dec.residual_events}
    # quantile plane: one read off the merged fold — dd_merge is
    # lossless, so this equals the read of the union stream
    qt_out = merged.quantile_answer()
    hist = merged.histogram_log2()
    slices: dict[str, dict] = {}
    for skey in ([key] if key else sorted(merged.slices)):
        ans = merged.slice_answer(skey)
        if ans is None:
            continue
        ans["heavy_hitters"] = [
            {"key": f"0x{k:08x}", "count": c,
             "label": labels.get(k, f"0x{k:08x}")}
            for k, c in ans["heavy_hitters"][:top]]
        slices[skey] = ans
    return QueryAnswer(
        # `windows` reports how many sealed windows the answer
        # CONSULTED: under pushdown the caller's per-node accounting
        # (levels) holds that number — the one merged window per node
        # that reached this fold would under-report it
        windows=(sum(levels.values()) if levels is not None
                 else merged.windows),
        nodes=merged.nodes,
        start_ts=merged.start_ts,
        end_ts=merged.end_ts,
        events=merged.events,
        drops=merged.drops,
        distinct=merged.distinct(),
        entropy_bits=merged.entropy_bits(),
        heavy_hitters=hh,
        slices=slices,
        dropped_windows=(list(merged.skipped) + dedup_notes
                         + list(dropped or [])),
        errors=dict(errors or {}),
        levels=dict(levels) if levels is not None else level_counts(kept),
        paths=dict(paths or {}),
        heavy_flows=flows,
        decoded_only=decoded_only,
        inv=inv_info,
        quantiles=qt_out,
        histogram=(hist.tolist() if hist is not None else None),
        accuracy=merged.accuracy(heavy=[(k, c) for k, c, _ in hh]),
        approx=bool(merged.approx),
    )


def decode_frames(frames: Iterable[tuple[dict, bytes]]
                  ) -> list[SealedWindow]:
    return [decode_window(h, p) for h, p in frames]


__all__ = ["QueryAnswer", "answer_query", "decode_frames",
           "dedupe_compacted", "level_counts", "pack_frames",
           "unpack_frames"]
