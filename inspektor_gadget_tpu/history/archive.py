"""Archive tier: cold history segments offloaded to an object store.

The compaction schedule bounds *resolution*; this tier bounds *local
disk*: fully-compacted segments (every window at the schedule's final
level) are offloaded whole to an object-store-shaped backend and
recorded in a per-store manifest (``archive.jsonl``), so retention
becomes a policy instead of a disk size ("Sketchy With a Chance of
Adoption", arxiv 2012.06001: telemetry that cannot bound its own
footprint does not survive production).

The ``ArchiveBackend`` protocol (put/get/list/delete) is the subsystem
boundary — the filesystem implementation below is what ships today; an
S3/GCS one slots in without touching the store, the query plane, or the
manifest format. Queries overlapping an archived range rehydrate the
segment through the manifest into a bounded local cache (LRU by bytes,
hit/miss counted) and verify the content digest on the way back in: a
corrupted or truncated archive object is REPORTED into the query's loss
accounting and never merged.

Manifest row (one JSON line per offloaded segment):

    {"object", "file", "bytes", "digest", "level", "windows",
     "first_seq", "last_seq", "first_ts", "last_ts", "keys",
     "archived_ts"}

The seq/ts ranges and slice-key union make the manifest a pruning
index: a query that doesn't overlap an archived range never touches
the backend.
"""

from __future__ import annotations

import hashlib
import os
import threading
import time
from typing import Callable, Iterator, Protocol

from ..telemetry import counter, gauge
from ..utils.journal import append_line, read_jsonl
from ..utils.logger import get_logger

log = get_logger("ig-tpu.history.archive")

ARCHIVE_MANIFEST = "archive.jsonl"
ARCHIVE_SCHEMA = "ig-tpu/history-archive/v1"

_tm_archived = counter(
    "ig_history_archived_segments_total",
    "cold (fully-compacted) history segments offloaded to the archive "
    "backend")
_tm_archived_bytes = counter(
    "ig_history_archive_bytes_total",
    "bytes offloaded to the archive backend")
_tm_rehydrations = counter(
    "ig_history_rehydrations_total",
    "archived-segment reads by local-cache outcome", ("result",))
_tm_archive_errors = counter(
    "ig_history_archive_errors_total",
    "archive objects refused (digest mismatch, unreadable backend, "
    "torn manifest rows)", ("reason",))
_tm_cache_bytes = gauge(
    "ig_history_archive_cache_bytes",
    "bytes currently held in the rehydration cache")


def _digest(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


class ArchiveBackend(Protocol):
    """Object-store shape the archive tier writes through. Names are
    ``<store>/<segment>`` keys; implementations own their own atomicity
    (put must never leave a half-object readable under the name)."""

    def put(self, name: str, data: bytes) -> None: ...
    def get(self, name: str) -> bytes: ...
    def list(self, prefix: str = "") -> list[str]: ...
    def delete(self, name: str) -> None: ...


class FilesystemArchive:
    """The shipping ArchiveBackend: objects are files under one root,
    written atomically (tmp + rename). The interface — not this class —
    is the subsystem boundary."""

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)

    def _path(self, name: str) -> str:
        # object names come from manifest rows a compromised agent could
        # have written: same traversal guard as every other
        # client-supplied path component
        norm = os.path.normpath(name)
        if not norm or os.path.isabs(norm) or norm.startswith(".."):
            raise ValueError(f"bad archive object name {name!r}")
        return os.path.join(self.root, norm)

    def put(self, name: str, data: bytes) -> None:
        path = self._path(name)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def get(self, name: str) -> bytes:
        with open(self._path(name), "rb") as f:
            return f.read()

    def list(self, prefix: str = "") -> list[str]:
        out = []
        for root, _dirs, files in os.walk(self.root):
            for f in files:
                rel = os.path.relpath(os.path.join(root, f), self.root)
                if rel.startswith(prefix):
                    out.append(rel)
        return sorted(out)

    def delete(self, name: str) -> None:
        os.remove(self._path(name))


class ArchiveTier:
    """Manifest-driven offload + rehydration for history stores."""

    def __init__(self, backend: ArchiveBackend, *, cache_dir: str,
                 cache_bytes: int = 64 << 20,
                 clock: Callable[[], float] = time.time):
        self.backend = backend
        self.cache_dir = os.path.abspath(cache_dir)
        self.cache_bytes = int(cache_bytes)
        self.clock = clock
        self._mu = threading.Lock()
        # LRU by bytes over the cache dir: path → size, oldest first
        self._lru: dict[str, int] = {}
        self._lru_loaded = False
        self.hits = 0
        self.misses = 0

    # -- offload ------------------------------------------------------------

    def archive_store(self, store_dir: str, *, min_level: int,
                      writer=None) -> dict:
        """Offload every sealed segment whose windows are ALL at
        min_level or beyond. The object is durable in the backend and
        its manifest row appended BEFORE the local segment is deleted
        (under the writer lock when one is passed) — a crash between
        the two leaves both copies, and reads prefer the local one."""
        from ..agent import wire
        from ..capture.journal import JournalReader, scan_segment
        from .store import HISTORY_METRICS
        stats = {"store": os.path.basename(store_dir), "segments": 0,
                 "bytes": 0, "windows": 0}
        reader = JournalReader(store_dir, metrics=HISTORY_METRICS)
        sealed = {str(row.get("file", "")) for row in reader.index}
        already = {row.get("file") for row in self.manifest_rows(store_dir)}
        for seg in reader._segment_files():
            name = os.path.basename(seg)
            if name not in sealed or name in already:
                continue
            records, loss = scan_segment(seg)
            if loss is not None or not records:
                continue
            if any(h.get("type") != wire.EV_WINDOW
                   or int(h.get("level", 0)) < min_level
                   for h, _p in records):
                continue
            try:
                with open(seg, "rb") as f:
                    data = f.read()
            except OSError:
                continue
            obj = f"{os.path.basename(store_dir)}/{name}"
            keys: set[str] = set()
            for h, _p in records:
                keys.update(h.get("keys") or [])
            row = {
                "schema": ARCHIVE_SCHEMA,
                "object": obj,
                "file": name,
                "bytes": len(data),
                "digest": _digest(data),
                "level": max(int(h.get("level", 0)) for h, _p in records),
                "windows": len(records),
                "first_seq": min(int(h.get("seq", 0)) for h, _p in records),
                "last_seq": max(int(h.get("seq", 0)) for h, _p in records),
                "first_ts": min(float(h.get("start_ts", 0.0))
                                for h, _p in records),
                "last_ts": max(float(h.get("end_ts", 0.0))
                               for h, _p in records),
                "keys": sorted(keys),
                "archived_ts": self.clock(),
            }
            self.backend.put(obj, data)
            append_line(os.path.join(store_dir, ARCHIVE_MANIFEST), row)
            if writer is not None:
                writer.remove_segments([name], count_gc=False)
            else:
                try:
                    os.remove(seg)
                except OSError:
                    pass
            stats["segments"] += 1
            stats["bytes"] += len(data)
            stats["windows"] += len(records)
            _tm_archived.inc()
            _tm_archived_bytes.inc(len(data))
        if stats["segments"]:
            log.info("archived %s: %d segment(s), %d window(s), %d bytes",
                     stats["store"], stats["segments"], stats["windows"],
                     stats["bytes"])
        return stats

    # -- manifest + rehydration --------------------------------------------

    def manifest_rows(self, store_dir: str) -> list[dict]:
        path = os.path.join(store_dir, ARCHIVE_MANIFEST)
        res = read_jsonl(path, on_bad="stop")
        if res.skipped:
            # a crash/ENOSPC tore a manifest line; repair NOW (atomic
            # rewrite of the good rows — the journal index's _recover
            # discipline) so rows appended after the tear don't stay
            # invisible to on_bad="stop" readers forever. The torn
            # row's object survives in the backend under a listable
            # name; only its index line is lost, and that loss is
            # counted.
            import json
            _tm_archive_errors.labels(reason="manifest").inc()
            tmp = f"{path}.tmp.{os.getpid()}"
            try:
                with open(tmp, "w", encoding="utf-8") as f:
                    for row in res.records:
                        f.write(json.dumps(row, sort_keys=True,
                                           separators=(",", ":")) + "\n")
                os.replace(tmp, path)
            except OSError as e:
                log.warning("archive manifest repair failed for %s: %r",
                            store_dir, e)
        return res.records

    def _cache_path(self, store_dir: str, name: str) -> str:
        return os.path.join(self.cache_dir,
                            os.path.basename(store_dir), name)

    def _load_lru_locked(self) -> None:
        if self._lru_loaded:
            return
        entries = []
        for root, _dirs, files in os.walk(self.cache_dir):
            for f in files:
                p = os.path.join(root, f)
                try:
                    st = os.stat(p)
                except OSError:
                    continue
                entries.append((st.st_mtime, p, st.st_size))
        for _mt, p, size in sorted(entries):
            self._lru[p] = size
        self._lru_loaded = True
        _tm_cache_bytes.set(sum(self._lru.values()))

    def _touch_locked(self, path: str, size: int) -> None:
        self._lru.pop(path, None)
        self._lru[path] = size       # dict order = LRU order, newest last
        used = sum(self._lru.values())
        # evict oldest beyond the budget — never the entry just touched
        # (a single over-budget object would otherwise thrash forever)
        for old in list(self._lru):
            if used <= self.cache_bytes or old == path:
                break
            used -= self._lru.pop(old)
            try:
                os.remove(old)
            except OSError:
                pass
        _tm_cache_bytes.set(sum(self._lru.values()))

    def rehydrate(self, store_dir: str, row: dict,
                  losses: list | None = None) -> str | None:
        """One archived segment back onto local disk (cache), digest-
        verified. Returns the cached path, or None with the refusal
        accounted — a corrupted archive object is reported, never
        merged."""
        name = str(row.get("file", ""))
        cpath = self._cache_path(store_dir, name)
        with self._mu:
            self._load_lru_locked()
            if os.path.isfile(cpath):
                self.hits += 1
                _tm_rehydrations.labels(result="hit").inc()
                self._touch_locked(cpath, os.path.getsize(cpath))
                return cpath
        self.misses += 1
        _tm_rehydrations.labels(result="miss").inc()
        try:
            data = self.backend.get(str(row.get("object", "")))
        except (OSError, ValueError) as e:
            _tm_archive_errors.labels(reason="get").inc()
            if losses is not None:
                losses.append({"store": os.path.basename(store_dir),
                               "segment": name, "offset": 0,
                               "dropped_bytes": int(row.get("bytes", 0)),
                               "reason": f"archive get failed: {e}"})
            return None
        if _digest(data) != row.get("digest"):
            _tm_archive_errors.labels(reason="digest").inc()
            if losses is not None:
                losses.append({"store": os.path.basename(store_dir),
                               "segment": name, "offset": 0,
                               "dropped_bytes": len(data),
                               "reason": "archive object digest mismatch "
                                         "(corrupted; refused)"})
            return None
        os.makedirs(os.path.dirname(cpath), exist_ok=True)
        tmp = f"{cpath}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, cpath)
        with self._mu:
            self._load_lru_locked()
            self._touch_locked(cpath, len(data))
        return cpath

    def frames_for_range(self, store_dir: str, *,
                         start_ts: float | None = None,
                         end_ts: float | None = None,
                         start_seq: int | None = None,
                         end_seq: int | None = None,
                         key: str | None = None,
                         losses: list | None = None
                         ) -> Iterator[tuple[dict, bytes]]:
        """EV_WINDOW frames of archived segments overlapping the range,
        rehydrated through the manifest. Manifest ranges prune before
        any backend traffic; segments still present locally are skipped
        (the store scan already served them)."""
        from ..agent import wire
        from ..capture.journal import scan_segment
        for row in self.manifest_rows(store_dir):
            name = str(row.get("file", ""))
            if not name or os.path.isfile(os.path.join(store_dir, name)):
                continue
            if start_ts is not None and float(row.get("last_ts") or 0.0) \
                    < start_ts:
                continue
            if end_ts is not None and float(row.get("first_ts") or 0.0) \
                    > end_ts:
                continue
            if start_seq is not None and int(row.get("last_seq") or 0) \
                    < start_seq:
                continue
            if end_seq is not None and int(row.get("first_seq") or 0) \
                    > end_seq:
                continue
            if key and (row.get("keys") is not None
                        and key not in row["keys"]):
                continue
            cpath = self.rehydrate(store_dir, row, losses)
            if cpath is None:
                continue
            records, loss = scan_segment(cpath)
            if loss is not None and losses is not None:
                losses.append({"store": os.path.basename(store_dir),
                               **loss.__dict__})
            for header, payload in records:
                if header.get("type") != wire.EV_WINDOW:
                    continue
                yield header, payload

    def stats(self, store_dir: str) -> dict:
        rows = self.manifest_rows(store_dir)
        rows = [r for r in rows
                if not os.path.isfile(os.path.join(store_dir,
                                                   str(r.get("file", ""))))]
        with self._mu:
            self._load_lru_locked()
            cache_used = sum(self._lru.values())
        return {
            "segments": len(rows),
            "bytes": sum(int(r.get("bytes", 0)) for r in rows),
            "windows": sum(int(r.get("windows", 0)) for r in rows),
            "cache": {"bytes": cache_used, "budget": self.cache_bytes,
                      "hits": self.hits, "misses": self.misses},
        }


__all__ = ["ARCHIVE_MANIFEST", "ARCHIVE_SCHEMA", "ArchiveBackend",
           "ArchiveTier", "FilesystemArchive"]
