"""Sketch history plane: time-windowed sketch store, fleet-wide range
queries, and subpopulation slices.

Live harvests render and vanish; checkpoints exist only for resume.
This package makes sketch state queryable across time and space
(arxiv 2503.13515, 2208.04927): the tpusketch operator seals one
mergeable window per boundary into a per-node store built on the PR-5
journal disciplines (window.py + store.py), agents serve
ListWindows/FetchWindows, and the query plane (query.py) merges
index-overlapping windows client-side — `ig-tpu query` answers
"cardinality of tenant X, 2–3pm, across nodes" from sealed state.
"""

from .archive import (
    ARCHIVE_MANIFEST,
    ARCHIVE_SCHEMA,
    ArchiveBackend,
    ArchiveTier,
    FilesystemArchive,
)
from .lifecycle import (
    DEFAULT_SCHEDULE,
    CompactionEngine,
    ScheduleLevel,
    parse_schedule,
    validate_schedule,
)
from .query import (
    QueryAnswer,
    answer_query,
    decode_frames,
    dedupe_compacted,
    level_counts,
    pack_frames,
    unpack_frames,
)
from .store import (
    HISTORY,
    HISTORY_METRICS,
    HISTORY_SCHEMA,
    HistoryStore,
    history_base_dir,
    validate_store_name,
)
from .window import (
    MergedWindows,
    SealedWindow,
    SliceSketch,
    WINDOW_SCHEMA,
    decode_window,
    encode_window,
    header_overlaps,
    merge_windows,
    merged_to_sealed,
    provenance_row,
    window_digest,
)

__all__ = [
    "ARCHIVE_MANIFEST", "ARCHIVE_SCHEMA", "ArchiveBackend", "ArchiveTier",
    "CompactionEngine", "DEFAULT_SCHEDULE", "FilesystemArchive", "HISTORY",
    "HISTORY_METRICS", "HISTORY_SCHEMA", "HistoryStore", "MergedWindows",
    "QueryAnswer", "ScheduleLevel", "SealedWindow", "SliceSketch",
    "WINDOW_SCHEMA", "answer_query", "decode_frames", "decode_window",
    "dedupe_compacted", "encode_window", "header_overlaps",
    "history_base_dir", "level_counts", "merge_windows", "merged_to_sealed",
    "pack_frames", "parse_schedule", "provenance_row", "unpack_frames",
    "validate_schedule", "validate_store_name", "window_digest",
]
