"""Fleet aggregation tier: aggregation as topology, not a client loop.

Up to PR 16, "fleet-wide" meant the querying client pulled one merged
summary per node over gRPC and folded them in Python — O(N) frames into
one process, fine at 4 nodes and wrong at 400. The merge algebra is
associative and commutative on every plane (CMS/entropy/DDSketch/
invertible lanes add, HLL registers max, top-k candidates union-and-sum
— history/window.py), so the fold can move onto the topology itself:

- `topology.py` — the tree spec (node → zone → fleet, declared via a
  compact grammar or auto-balanced to O(log N) fan-in) with loud typed
  validation: every agent exactly once, no empty zones, no id reuse.
- `aggregator.py` — the `AggregatorNode` role plus `fold_tree`: each
  aggregator folds its children's summary windows through the SAME
  merge algebra (`merge_windows` → `merged_to_sealed`, identical
  total-coverage refusal rules for the qt/inv/accuracy planes) and
  republishes ONE sealed window upward; the client queries the root.
  `flat_summary`/`canonical_order` pin the byte-identity anchor: any
  fold shape over the same leaf windows seals the same bytes.
- `collective.py` — the DCN path for chip-bearing hosts in one
  multihost slice: per-host lanes harvest over ICI, then one
  psum/pmax crossing DCN per slice (parallel/cluster.cluster_merge
  under a `make_multihost_mesh` mesh).
- `sim.py` — the in-process ~100-agent chaos/scale harness (churn,
  partition, skew) the scale proof and `perf/fleet_bench.py` drive.
"""

from .aggregator import (
    AggregatorNode,
    TreeFold,
    canonical_order,
    flat_summary,
    fold_tree,
)
from .collective import fleet_collective_merge, make_fleet_merge
from .topology import (
    Topology,
    TopologyError,
    TreeNode,
    auto_topology,
    parse_topology,
)

__all__ = [
    "AggregatorNode", "Topology", "TopologyError", "TreeFold", "TreeNode",
    "auto_topology", "canonical_order", "flat_summary",
    "fleet_collective_merge", "fold_tree", "make_fleet_merge",
    "parse_topology",
]
