"""DCN collective merge: the aggregation tier for chip-bearing hosts.

When the "fleet" is one multihost TPU slice (or several), the merge
tree does not need gRPC hops at all — the PR-11 sharded harvest already
leaves one fused SketchBundle per chip, and `cluster_merge` is a single
collective over the node axis. `make_multihost_mesh` orders devices
slice-major (slice_index, process_index, id), so the psum/pmax tree
rides ICI within each slice and crosses DCN once per slice pair — the
fleet-merged bundle materializes ON DEVICE and the invertible decode
runs on the *merged* state (arxiv 1910.10441's network-wide recovery,
arxiv 2503.13515's disaggregation across space).

Bit-identity contract: every lane the collective folds is integer
arithmetic — CMS/entropy/DDSketch/invertible counts psum (int lanes;
the mod-2^32 key-sum/fingerprint lanes wrap identically under any
association), HLL registers pmax, top-k all_gather in mesh order — so
the CPU-simulated multi-process merge is bit-identical to the same
merge on one process, and to the host-side flat fold of the equivalent
sealed windows. tests/test_fleet_collective.py pins the first two;
TPU verification of the DCN crossing rides the standing hardware-probe
item (a degraded/cpu run may not read as a TPU result).
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.sketches import SketchBundle
from ..parallel.cluster import cluster_merge
from ..parallel.compat import shard_map
from ..parallel.mesh import NODE_AXIS


def fleet_collective_merge(bundle: SketchBundle) -> SketchBundle:
    """The shard_map body: per-node bundles (leading node-axis dim) →
    ONE replicated fleet bundle. Exactly `cluster_merge` — the tier
    reuses the PR-11 harvest algebra verbatim so the on-device fold and
    the host-side window fold cannot drift apart."""
    return cluster_merge(bundle)


def make_fleet_merge(mesh: Mesh):
    """Jitted collective merge over `mesh`'s node axis.

    merge(stacked_bundle) -> replicated fleet SketchBundle, where
    `stacked_bundle` has a leading node-axis dim sharded over the mesh
    (one bundle row per chip/host lane). On a `make_multihost_mesh`
    mesh the reduction crosses DCN once per slice; on a single-host
    mesh it is the PR-11 harvest unchanged."""

    def specs_like(tree, spec):
        return jax.tree.map(lambda _: spec, tree)

    def merge_fn(stacked: SketchBundle) -> SketchBundle:
        in_specs = (specs_like(stacked, P(NODE_AXIS)),)
        out_specs = specs_like(
            jax.tree.map(lambda x: x[0], stacked), P())
        return jax.jit(shard_map(
            fleet_collective_merge, mesh=mesh, in_specs=in_specs,
            out_specs=out_specs, check_vma=False))(stacked)

    return merge_fn


def shard_over_nodes(mesh: Mesh, stacked: SketchBundle) -> SketchBundle:
    """Place a host-stacked bundle (leading dim = node count) onto the
    mesh's node axis — the single-process analogue of each host calling
    `jax.make_array_from_process_local_data` on its own rows."""
    sharding = NamedSharding(mesh, P(NODE_AXIS))
    return jax.tree.map(lambda x: jax.device_put(x, sharding), stacked)


def bundle_digest(bundle: SketchBundle) -> str:
    """sha256 over every plane's raw bytes in field order — the
    bit-identity witness two processes (or two fold shapes) compare.
    Optional planes hash their presence flag so plane-off and plane-on
    bundles can never collide."""
    import hashlib

    import numpy as np

    h = hashlib.sha256()
    for leaf in jax.tree.leaves(bundle):
        arr = np.asarray(leaf)
        h.update(str(arr.dtype).encode())
        h.update(str(arr.shape).encode())
        h.update(arr.tobytes())
    h.update(str(jax.tree.structure(bundle)).encode())
    return h.hexdigest()


__all__ = ["bundle_digest", "fleet_collective_merge", "make_fleet_merge",
           "shard_over_nodes"]
