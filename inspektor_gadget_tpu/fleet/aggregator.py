"""The aggregation-tier fold: AggregatorNode + fold_tree.

One rule makes the whole tier trustworthy: every tier folds child
summaries through the SAME algebra the flat client-side fold uses —
`merge_windows` (total-coverage refusal for the invertible/quantile/
shadow planes, unconditional approx-taint OR, geometry-skip with a
note) sealed by `merged_to_sealed` (canonical (-count, key) candidate
order, int64 count lanes). Because that algebra is associative and
commutative on every plane, any fold SHAPE over the same leaf windows
seals byte-identical summaries — a zone folding its four nodes and the
root folding the zones produces exactly the bytes of one flat fold over
all leaves. `flat_summary` is that anchor; order is pinned twice so
reply ARRIVAL order can never leak into the sealed bytes (the last-wins
label-map update and the merge-base choice are the two order-sensitive
spots): leaf-set folds sort by `canonical_order` (node id), and every
aggregator folds its children in TOPOLOGY order — which for the
auto-balanced tree equals canonical leaf order at every tier, making
even the digest-exempt label map identical to the flat fold's.

Failure is accounted, never fatal: an unreachable leaf becomes an
`errors` row with path ``unreachable``; an unreachable or mid-fold-
crashed aggregator trips the fallback counter and its subtree is
re-folded flat from the leaves (path ``flat-fallback``), with a
`folded`-leaf guard making double-counting structurally impossible —
each leaf's summary enters the fold exactly once per query no matter
how many re-folds the chaos causes.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Iterable

from ..agent import wire
from ..history.window import (
    SealedWindow,
    merge_windows,
    merged_to_sealed,
)
from ..telemetry import counter, gauge
from .topology import Topology, TreeNode

# live-fold depth: set while a tree fold is in flight, back to 0 when it
# returns — the scrape answers "is an aggregation running, how tall"
_tm_depth = gauge("ig_fleet_merge_depth",
                  "depth of the merge tree currently being folded "
                  "(0 = no tree fold in flight)")
_tm_folds = counter("ig_fleet_subtree_folds_total",
                    "aggregator subtree folds by result (ok = sealed "
                    "and republished, failed = fold crashed and the "
                    "subtree fell back flat)", ("result",))
_tm_fallback = counter("ig_fleet_fallback_total",
                       "subtrees answered by the flat per-leaf fold "
                       "because their aggregator was unreachable or "
                       "crashed mid-fold")


def canonical_order(windows: Iterable[SealedWindow]) -> list[SealedWindow]:
    """The fold order both the flat path and the tree pin: sorted by
    (node, level, window, seq, digest) — a pure function of the window
    set, so reply arrival order cannot reach the merge (where the
    label-map last-wins update and the merge-base geometry choice would
    otherwise leak it into the sealed bytes)."""
    return sorted(windows, key=lambda w: (w.node, int(w.level),
                                          int(w.window), int(w.seq),
                                          w.digest))


def flat_summary(windows: Iterable[SealedWindow], *, gadget: str = "fleet",
                 node: str = "fleet") -> SealedWindow | None:
    """ONE flat fold over every window, sealed — the byte-identity
    anchor the tree-merged summary is asserted against."""
    ws = canonical_order(windows)
    if not ws:
        return None
    return merged_to_sealed(merge_windows(ws), gadget=gadget, node=node)


class AggregatorNode:
    """The aggregator role: holds the latest summary window per child
    (fed by the PR-9 summary pub/sub or a fetch sweep), folds them on
    demand, republishes ONE sealed window + a FleetAggregate accounting
    header (wire.FLEET_AGGREGATE_FIELDS — the proto-documented shape).

    Stateless across publishes by design: `publish()` re-folds the
    current child set from scratch, so a crash mid-fold loses nothing
    but the attempt — the next publish over the same child summaries
    seals identical bytes, and a child observed twice simply replaces
    its previous summary (exactly-once per publish by construction)."""

    def __init__(self, id: str, children: Iterable[str], *,
                 gadget: str = "fleet"):
        self.id = id
        self.children = list(children)
        self.gadget = gadget
        self._latest: dict[str, SealedWindow] = {}

    def observe(self, child: str, window: SealedWindow) -> None:
        if child not in self.children:
            raise ValueError(f"{child!r} is not a child of aggregator "
                             f"{self.id!r} ({', '.join(self.children)})")
        self._latest[child] = window

    def discard(self, child: str) -> None:
        """Drop a departed child's summary (churn): its contribution
        leaves the next publish instead of going stale-forever."""
        self._latest.pop(child, None)

    def publish(self) -> tuple[SealedWindow | None, dict]:
        """(sealed merged window or None, FleetAggregate accounting)."""
        # fold in TOPOLOGY child order, not observation order: the
        # children list is fixed at construction, so reply arrival can
        # never leak into the sealed bytes — and for auto-balanced
        # trees child order IS canonical leaf order at every tier,
        # which is what keeps the republished summary byte-identical
        # to the flat fold (label map included)
        ws = [self._latest[c] for c in self.children
              if c in self._latest]
        missing = [c for c in self.children if c not in self._latest]
        if not ws:
            _tm_folds.labels(result="ok").inc()
            return None, self._aggregate(None, 0, missing, [])
        try:
            merged = merge_windows(ws)
            sealed = merged_to_sealed(merged, gadget=self.gadget,
                                      node=self.id)
        except Exception:
            _tm_folds.labels(result="failed").inc()
            raise
        _tm_folds.labels(result="ok").inc()
        return sealed, self._aggregate(sealed, len(ws), missing,
                                       list(merged.skipped))

    def _aggregate(self, sealed: SealedWindow | None, folded: int,
                   missing: list[str], skipped: list[str]) -> dict:
        return {
            "schema": wire.FLEET_AGGREGATE_SCHEMA,
            "aggregator": self.id,
            "gadget": self.gadget,
            "children": list(self.children),
            "folded": folded,
            "missing": missing,
            "skipped": skipped,
            "approx": bool(sealed.approx) if sealed is not None else False,
            "digest": sealed.digest if sealed is not None else "",
        }


@dataclasses.dataclass
class TreeFold:
    """One tree-routed fleet fold: the root summary plus the exact
    accounting the flat fold produces (levels/dropped/errors/paths), so
    `answer_query` renders either path identically."""

    window: SealedWindow | None
    levels: dict[int, int]
    dropped: list[str]
    errors: dict[str, str]
    paths: dict[str, str]          # per leaf: tree | flat-fallback |
                                   # unreachable
    fallback: list[str]            # aggregator ids answered flat
    depth: int
    subtree_folds: int
    aggregate: dict                # root FleetAggregate accounting


def fold_tree(topology: Topology,
              fetch_leaf: Callable[[str], dict], *,
              fetch_subtree: Callable[[TreeNode], dict] | None = None,
              gadget: str = "fleet") -> TreeFold:
    """Fold the fleet through `topology`.

    `fetch_leaf(node_id)` returns the per-agent summary dict the
    QueryWindows pushdown reply decodes to — ``{"window":
    SealedWindow|None, "levels": {level: n}, "dropped": [note],
    "losses": [loss]}`` — and raises on an unreachable agent.

    `fetch_subtree(tree_node)`, when given, asks a deployed
    AggregatorNode for its whole subtree in one hop (same reply shape);
    when it raises — the aggregator is partitioned away or crashed
    mid-fold — that subtree falls back to the flat per-leaf fold, the
    fallback counter trips, and the re-fold starts from zero folded
    leaves (the `folded` guard: a leaf summary enters this query's fold
    exactly once, crash-and-refold included)."""
    levels: dict[int, int] = {}
    dropped: list[str] = []
    errors: dict[str, str] = {}
    paths: dict[str, str] = {}
    fallback: list[str] = []
    # exactly-once core: one fetch and one accounting pass per leaf per
    # query, cached — a crash-and-refold reuses the cached summary
    # instead of re-fetching (no double-count) or re-accounting
    folded: set[str] = set()
    leaf_cache: dict[str, SealedWindow | None] = {}
    counts = {"subtree_folds": 0}

    def account(who: str, res: dict) -> None:
        for lvl, n in (res.get("levels") or {}).items():
            levels[int(lvl)] = levels.get(int(lvl), 0) + int(n)
        for note in res.get("dropped") or ():
            dropped.append(f"{who}: {note}")
        for loss in res.get("losses") or ():
            dropped.append(f"{who}: torn window tail "
                           f"({loss.get('reason', '?')}, "
                           f"{loss.get('dropped_bytes', 0)} bytes)")

    def fetch_one(leaf: str, path: str) -> SealedWindow | None:
        if leaf in folded:
            if leaf not in leaf_cache:
                return None  # consumed by a remote subtree reply
            if paths.get(leaf) != "unreachable":
                paths[leaf] = path  # a refold relabels how it answered
            return leaf_cache[leaf]
        folded.add(leaf)
        try:
            res = fetch_leaf(leaf)
        except Exception as e:  # noqa: BLE001 — per-node isolation
            errors[leaf] = str(e)
            paths[leaf] = "unreachable"
            leaf_cache[leaf] = None
            return None
        paths[leaf] = path
        account(leaf, res)
        leaf_cache[leaf] = res.get("window")
        return leaf_cache[leaf]

    def flat_fold(node: TreeNode) -> SealedWindow | None:
        """The fallback: fold this subtree's leaves with no
        intermediate tiers — exactly the pre-tree client loop (cached
        summaries are reused, so a refold never re-counts a leaf)."""
        ws = [w for leaf in Topology(node).leaves()
              if (w := fetch_one(leaf, "flat-fallback")) is not None]
        if not ws:
            return None
        merged = merge_windows(canonical_order(ws))
        for note in merged.skipped:
            dropped.append(f"{node.id}: {note}")
        return merged_to_sealed(merged, gadget=gadget, node=node.id)

    def fold(node: TreeNode) -> SealedWindow | None:
        if node.is_leaf:
            return fetch_one(node.id, "tree")
        if fetch_subtree is not None:
            try:
                res = fetch_subtree(node)
            except Exception as e:  # noqa: BLE001 — subtree isolation
                _tm_fallback.inc()
                fallback.append(node.id)
                dropped.append(f"{node.id}: aggregator unreachable "
                               f"({e}) — subtree re-folded flat")
                return flat_fold(node)
            counts["subtree_folds"] += 1
            account(node.id, res)
            for leaf in Topology(node).leaves():
                if leaf not in folded:
                    folded.add(leaf)
                    paths[leaf] = "tree"
            return res.get("window")
        # client-driven tier: this process performs the aggregator's
        # fold — same algebra, same seal, same accounting. Children
        # merge in TOPOLOGY order (deterministic; for auto trees equal
        # to canonical leaf order at every tier) — sorting by node id
        # here would mis-order a promoted remainder chunk, whose id
        # carries a different depth label than its siblings
        ws = [w for c in node.children if (w := fold(c)) is not None]
        if not ws:
            return None
        try:
            merged = merge_windows(ws)
            # a refusal at THIS tier (geometry mismatch, partial plane
            # coverage) must reach the answer's dropped_windows — the
            # sealed window it produces carries no trace of it, and
            # answer_query only re-merges what it is handed
            for note in merged.skipped:
                dropped.append(f"{node.id}: {note}")
            sealed = merged_to_sealed(merged, gadget=gadget,
                                      node=node.id)
        except Exception as e:  # noqa: BLE001 — crash mid-fold
            _tm_folds.labels(result="failed").inc()
            _tm_fallback.inc()
            fallback.append(node.id)
            dropped.append(f"{node.id}: aggregator fold crashed ({e}) — "
                           "subtree re-folded flat")
            return flat_fold(node)
        _tm_folds.labels(result="ok").inc()
        counts["subtree_folds"] += 1
        return sealed

    depth = topology.depth()
    _tm_depth.set(float(depth))
    try:
        root_win = fold(topology.root)
    finally:
        _tm_depth.set(0.0)
    aggregate = {
        "schema": wire.FLEET_AGGREGATE_SCHEMA,
        "aggregator": topology.root.id,
        "gadget": gadget,
        "children": [c.id for c in topology.root.children],
        "folded": sum(levels.values()),
        "missing": sorted(errors),
        "skipped": list(dropped),
        "approx": bool(root_win.approx) if root_win is not None else False,
        "digest": root_win.digest if root_win is not None else "",
    }
    return TreeFold(window=root_win, levels=levels, dropped=dropped,
                    errors=errors, paths=paths, fallback=fallback,
                    depth=depth, subtree_folds=counts["subtree_folds"],
                    aggregate=aggregate)


__all__ = ["AggregatorNode", "TreeFold", "canonical_order",
           "flat_summary", "fold_tree"]
