"""In-process fleet simulator: ~100 agents of sealed-window state with
fault injection, no subprocesses.

The PR-8 chaos tier (testing/chaos.py) tortures REAL agent processes —
right for transport/resume bugs, too heavy for 100 nodes in tier-1. The
scale proof needs the opposite trade: each agent is just its QueryWindows
pushdown reply (one merged sealed window + level/drop accounting), so a
hundred of them fit in one process and the faults under test are the
DISTRIBUTED ones — partition (fetch raises), churn (roster changes
between queries), clock skew (per-agent ts offsets), aggregator crash
(a subtree fold raises mid-query). `fetches` counts every leaf pull, so
exactly-once accounting is a direct assertion: one query folds each
reachable leaf exactly once, no matter how many subtree re-folds the
injected chaos causes.
"""

from __future__ import annotations

import zlib

import numpy as np

from ..history.window import SealedWindow, window_digest
from .aggregator import AggregatorNode, canonical_order, flat_summary
from .topology import Topology, TreeNode, parse_topology

GADGET = "trace/exec"


def make_window(node: str, seed: int, *, gadget: str = GADGET,
                window: int = 1, width: int = 64, inv: bool = False,
                qt: bool = False, rs: bool = False, approx: bool = False,
                slices: bool = True, skew: float = 0.0) -> SealedWindow:
    """One synthetic sealed window, deterministic in (node, seed): every
    plane the merge algebra folds, each one optional so plane-on/off
    matrices and geometry-mismatch refusals are one kwarg away."""
    # crc32, not hash(): Python string hashing is salted per process,
    # and the sim's windows must be reproducible across runs
    rng = np.random.default_rng([seed, zlib.crc32(node.encode())])
    keys = rng.integers(1, 500, 256, dtype=np.uint32)
    sl = {}
    if slices:
        from ..history.window import SliceSketch
        s = SliceSketch()
        s.update(keys, keys, keys)
        sl[f"mntns:{seed % 2}"] = {"events": s.events, "hll": s.hll,
                                   "ent": s.ent, "hh": s.sealed_hh()}
    w = SealedWindow(
        gadget=gadget, node=node, run_id="r", window=window,
        start_ts=1000.0 + window + skew, end_ts=1001.0 + window + skew,
        events=len(keys), drops=seed % 3,
        cms=rng.integers(0, 9, (4, width)).astype(np.int32),
        hll=rng.integers(0, 5, 256).astype(np.int32),
        ent=rng.integers(0, 9, 64).astype(np.float32),
        topk_keys=rng.integers(1, 500, 8, dtype=np.uint32),
        topk_counts=np.sort(rng.integers(1, 99, 8))[::-1].astype(np.int64),
        slices=sl, names={int(keys[0]): f"comm-{node}"},
        approx=approx)
    if inv:
        w.inv_count = rng.integers(0, 50, (2, 32)).astype(np.int32)
        w.inv_keysum = rng.integers(0, 2**31, (2, 32)).astype(np.uint32)
        w.inv_fpsum = rng.integers(0, 2**31, (2, 32)).astype(np.uint32)
    if qt:
        w.qt_counts = rng.integers(0, 30, 128).astype(np.int64)
        w.qt_zeros = int(seed % 5)
        w.qt_total = int(w.qt_counts.sum()) + w.qt_zeros
    if rs:
        w.rs_capacity = 16
        w.rs_keys = rng.integers(1, 500, 16, dtype=np.uint64)
        w.rs_weights = np.ones(16, np.float64)
    w.digest = window_digest(w)
    return w


class SimAgent:
    """One simulated agent: its windows plus the pushdown reply shape
    (`query_windows`-compatible dict) `fold_tree`'s fetch_leaf expects."""

    def __init__(self, node: str, seed: int, *, n_windows: int = 2,
                 skew: float = 0.0, **plane_kw):
        self.node = node
        self.seed = seed
        self.skew = skew
        self.plane_kw = dict(plane_kw)
        self.windows = [
            make_window(node, seed + i, window=i + 1, skew=skew,
                        **plane_kw)
            for i in range(n_windows)
        ]

    def summary(self) -> dict:
        """The per-agent pushdown reply: ONE merged sealed window (the
        agent folds its own windows server-side) + level accounting —
        byte-identical to what client.query_windows decodes."""
        win = flat_summary(self.windows, gadget=self.windows[0].gadget,
                           node=self.node)
        return {"node": self.node, "window": win, "folded": True,
                "levels": {0: len(self.windows)}, "torn": 0,
                "dropped": [], "losses": []}


class SimFleet:
    """N simulated agents + the fault controls the scale proof drives.

    fetch_leaf is the seam: it raises ConnectionError for partitioned or
    churned-out agents and counts every successful pull in `fetches`
    (the exactly-once witness). `flat_reference()` is the byte-identity
    anchor — the flat fold over currently-reachable agents' windows.
    """

    def __init__(self, n: int, *, seed: int = 0, n_windows: int = 2,
                 **plane_kw):
        self.seed = seed
        self.n_windows = n_windows
        self.plane_kw = dict(plane_kw)
        self.agents: dict[str, SimAgent] = {}
        self.partitioned: set[str] = set()
        self.fetches: dict[str, int] = {}
        self.spawned = 0
        for _ in range(n):
            self.spawn()

    # -- roster / fault controls ------------------------------------------

    def spawn(self, *, skew: float = 0.0) -> str:
        """Churn-in: a fresh agent joins the roster (new node id — a
        respawned agent is a new fleet member as far as the tree is
        concerned; rebuild the topology after churn)."""
        node = f"n{self.spawned:03d}"
        self.spawned += 1
        self.agents[node] = SimAgent(node, self.seed + self.spawned,
                                     n_windows=self.n_windows, skew=skew,
                                     **self.plane_kw)
        return node

    def kill(self, node: str) -> None:
        """Churn-out: the agent leaves the roster entirely (vs
        partition(), where it stays a target but stops answering)."""
        self.agents.pop(node, None)
        self.partitioned.discard(node)

    def partition(self, *nodes: str) -> None:
        self.partitioned.update(nodes)

    def heal(self, *nodes: str) -> None:
        if nodes:
            self.partitioned.difference_update(nodes)
        else:
            self.partitioned.clear()

    def skew(self, node: str, seconds: float) -> None:
        """Re-seal `node`'s windows with a clock offset (the SkewClock
        fault, applied to sealed history: its timestamps disagree with
        the fleet's but its sketch planes still fold)."""
        a = self.agents[node]
        self.agents[node] = SimAgent(node, a.seed,
                                     n_windows=self.n_windows,
                                     skew=a.skew + seconds,
                                     **self.plane_kw)

    # -- the fold seams ----------------------------------------------------

    def nodes(self) -> list[str]:
        return sorted(self.agents)

    def fetch_leaf(self, node: str) -> dict:
        if node not in self.agents:
            raise ConnectionError(f"agent {node} gone (churned out)")
        if node in self.partitioned:
            raise ConnectionError(f"agent {node} unreachable (partition)")
        self.fetches[node] = self.fetches.get(node, 0) + 1
        return self.agents[node].summary()

    def make_fetch_subtree(self, *, fail: set[str] | None = None,
                           gadget: str = GADGET):
        """A server-side aggregator tier: each fetch_subtree call plays
        the deployed AggregatorNode for that subtree (fold children via
        this same fleet, one reply up). Ids in `fail` raise — the
        crashed/partitioned-aggregator fault."""
        fail = set(fail or ())

        def fetch_subtree(tree_node: TreeNode) -> dict:
            if tree_node.id in fail:
                raise ConnectionError(
                    f"aggregator {tree_node.id} unreachable")
            agg = AggregatorNode(
                tree_node.id,
                [c.id for c in tree_node.children], gadget=gadget)
            levels: dict[int, int] = {}
            dropped: list[str] = []
            for child in tree_node.children:
                if child.is_leaf:
                    try:
                        res = self.fetch_leaf(child.id)
                    except Exception:
                        continue  # the aggregator's own missing-child row
                else:
                    res = fetch_subtree(child)
                if res.get("window") is not None:
                    agg.observe(child.id, res["window"])
                for lvl, n in (res.get("levels") or {}).items():
                    levels[int(lvl)] = levels.get(int(lvl), 0) + int(n)
                dropped.extend(res.get("dropped") or ())
            win, acct = agg.publish()
            dropped.extend(f"{tree_node.id}: child {c} missing"
                           for c in acct["missing"])
            # no node prefix: fold_tree's accounting prefixes the
            # replying aggregator's id when it ingests this reply
            dropped.extend(acct["skipped"])
            return {"node": tree_node.id, "window": win, "folded": True,
                    "levels": levels, "torn": 0, "dropped": dropped,
                    "losses": [], "aggregate": acct}

        return fetch_subtree

    def reachable_windows(self) -> list[SealedWindow]:
        return canonical_order(
            w for node, a in self.agents.items()
            if node not in self.partitioned for w in a.windows)

    def flat_reference(self, *, gadget: str = GADGET) -> SealedWindow | None:
        """What the pre-tree client loop would seal: per-agent pushdown
        summaries folded flat in canonical node order."""
        summaries = []
        for node in self.nodes():
            if node in self.partitioned:
                continue
            win = self.agents[node].summary()["window"]
            if win is not None:
                summaries.append(win)
        return flat_summary(summaries, gadget=gadget)

    def topology(self, spec: str = "auto") -> Topology:
        return parse_topology(spec, self.nodes())


__all__ = ["GADGET", "SimAgent", "SimFleet", "make_window"]
