"""Merge-tree topology: which aggregator folds which agents.

A topology is a rooted tree whose leaves are agent node names and whose
interior nodes are aggregators. Two ways to get one:

- **Declared** (`parse_topology`): a compact grammar mapping zones to
  members, one assignment per `;`-separated clause::

      zone-a=n0,n1;zone-b=n2,n3            # root → {zone-a, zone-b}
      dc1/rack-a=n0,n1;dc1/rack-b=n2;dc2=n3  # nested via '/' paths

  Every `/`-separated path segment names an aggregator under the
  implicit root (``fleet``); the clause's members become that
  aggregator's leaf children. Validation is loud and typed
  (`TopologyError`): every known agent appears exactly once, no agent
  is invented, no clause is empty, no aggregator id collides with an
  agent name.

- **Auto-balanced** (`auto_topology`): leaves sorted by node id are
  grouped into contiguous runs of `fan_in`, then the groups are grouped
  again until one root remains — depth is O(log_fan_in N). Contiguity
  over the SORTED ids is deliberate: it keeps the tree's leaf order
  equal to the flat fold's canonical order, which is what makes the
  tree-merged summary byte-identical to the flat client-side fold
  (see aggregator.py).

The spec string accepted everywhere a topology param appears:
``auto`` (fan-in 4), ``auto:<fan_in>``, or the declared grammar above.
"""

from __future__ import annotations

import dataclasses
from typing import Iterable

DEFAULT_FAN_IN = 4
ROOT_ID = "fleet"


class TopologyError(ValueError):
    """A topology spec that cannot be trusted to fold the whole fleet
    exactly once — raised instead of silently dropping or double-
    counting agents."""


@dataclasses.dataclass(frozen=True)
class TreeNode:
    """One topology vertex: a leaf (agent, no children) or an
    aggregator (folds its children's summaries)."""

    id: str
    children: tuple["TreeNode", ...] = ()

    @property
    def is_leaf(self) -> bool:
        return not self.children


@dataclasses.dataclass(frozen=True)
class Topology:
    """A validated merge tree. `leaves()` is the exactly-once agent
    set; `depth()`/`fan_in()` are the shape facts the doctor row, the
    CLI, and the perf ledger report."""

    root: TreeNode

    def leaves(self) -> list[str]:
        out: list[str] = []

        def walk(n: TreeNode) -> None:
            if n.is_leaf:
                out.append(n.id)
                return
            for c in n.children:
                walk(c)

        walk(self.root)
        return out

    def aggregators(self) -> list[TreeNode]:
        out: list[TreeNode] = []

        def walk(n: TreeNode) -> None:
            if n.is_leaf:
                return
            out.append(n)
            for c in n.children:
                walk(c)

        walk(self.root)
        return out

    def depth(self) -> int:
        """Edges on the longest root→leaf path (a root folding leaves
        directly has depth 1)."""

        def walk(n: TreeNode) -> int:
            if n.is_leaf:
                return 0
            return 1 + max(walk(c) for c in n.children)

        return walk(self.root)

    def fan_in(self) -> int:
        """Largest child count any aggregator folds — the per-link load
        bound the tree exists to enforce."""
        return max((len(a.children) for a in self.aggregators()),
                   default=0)

    def edges(self) -> int:
        """Parent←child summary hops per merged query: every child
        (leaf or aggregator) ships ONE sealed window to its parent."""
        return sum(len(a.children) for a in self.aggregators())

    def to_dict(self) -> dict:
        def walk(n: TreeNode):
            if n.is_leaf:
                return n.id
            return {n.id: [walk(c) for c in n.children]}

        return {"root": walk(self.root), "leaves": len(self.leaves()),
                "aggregators": len(self.aggregators()),
                "depth": self.depth(), "fan_in": self.fan_in(),
                "edges": self.edges()}


def _validate(topo: Topology, nodes: Iterable[str]) -> Topology:
    known = list(nodes)
    leaves = topo.leaves()
    seen: set[str] = set()
    for leaf in leaves:
        if leaf in seen:
            raise TopologyError(
                f"agent {leaf!r} assigned twice — a tree that folds a "
                "node's summary into two subtrees double-counts it")
        seen.add(leaf)
    unknown = sorted(seen - set(known))
    if unknown:
        raise TopologyError(
            f"unknown agent(s) {', '.join(unknown)} — topology names "
            f"must come from the target set ({', '.join(sorted(known))})")
    missing = sorted(set(known) - seen)
    if missing:
        raise TopologyError(
            f"agent(s) {', '.join(missing)} not placed in any zone — a "
            "fleet query through this tree would silently omit them")
    agg_ids = [a.id for a in topo.aggregators()]
    dup_agg = sorted({a for a in agg_ids if agg_ids.count(a) > 1})
    if dup_agg:
        raise TopologyError(
            f"aggregator id(s) {', '.join(dup_agg)} reused — per-node "
            "path accounting needs unique ids")
    clash = sorted(set(agg_ids) & seen)
    if clash:
        raise TopologyError(
            f"aggregator id(s) {', '.join(clash)} collide with agent "
            "names — accounting rows would be ambiguous")
    return topo


def auto_topology(nodes: Iterable[str], fan_in: int = DEFAULT_FAN_IN
                  ) -> Topology:
    """Balance sorted leaves into a fan_in-ary tree: contiguous runs of
    `fan_in` children per aggregator, repeated until one root remains.
    A run of one is promoted, not wrapped — no single-child aggregator
    ever exists (it would add a hop and fold nothing)."""
    if fan_in < 2:
        raise TopologyError(f"fan-in must be >= 2, got {fan_in} — a "
                            "1-ary tree is a linked list of folds")
    names = sorted(nodes)
    if not names:
        raise TopologyError("no agents to build a topology over")
    level: list[TreeNode] = [TreeNode(t) for t in names]
    depth = 0
    while len(level) > 1:
        depth += 1
        nxt: list[TreeNode] = []
        for i in range(0, len(level), fan_in):
            chunk = level[i:i + fan_in]
            if len(chunk) == 1:
                nxt.append(chunk[0])
                continue
            last = len(level) <= fan_in
            # zero-padded chunk index so id order matches chunk order
            # wherever accounting rows get sorted for display
            nxt.append(TreeNode(
                ROOT_ID if last else f"agg{depth}-{i // fan_in:03d}",
                tuple(chunk)))
        level = nxt
    root = level[0]
    if root.is_leaf:
        # single-agent fleet: the root still aggregates (folds one)
        root = TreeNode(ROOT_ID, (root,))
    return _validate(Topology(root), names)


def _parse_declared(spec: str, nodes: Iterable[str]) -> Topology:
    # paths["dc1/rack-a"] = [members...]; tree assembled per segment
    clauses = [c.strip() for c in spec.split(";") if c.strip()]
    if not clauses:
        raise TopologyError("empty topology spec")
    assigned: list[tuple[tuple[str, ...], list[str]]] = []
    for clause in clauses:
        if "=" not in clause:
            raise TopologyError(
                f"bad clause {clause!r} — expected zone[/zone...]=n1,n2")
        path_s, members_s = clause.split("=", 1)
        path = tuple(p.strip() for p in path_s.split("/"))
        if not all(path):
            raise TopologyError(f"bad zone path {path_s!r} in {clause!r}")
        members = [m.strip() for m in members_s.split(",") if m.strip()]
        if not members:
            raise TopologyError(
                f"zone {path_s!r} has no members — an empty zone folds "
                "nothing and hides a misspelled assignment")
        assigned.append((path, members))

    # nested dict of aggregators: {zone: ({subzone: ...}, [leaf, ...])}
    def new_level() -> tuple[dict, list]:
        return ({}, [])

    tree = new_level()
    for path, members in assigned:
        cur = tree
        for seg in path:
            cur = cur[0].setdefault(seg, new_level())
        cur[1].extend(members)

    def build(name: str, level: tuple[dict, list]) -> TreeNode:
        subs, members = level
        children = [build(seg, lv) for seg, lv in subs.items()]
        children.extend(TreeNode(m) for m in members)
        return TreeNode(name, tuple(children))

    return _validate(Topology(build(ROOT_ID, tree)), nodes)


def parse_topology(spec: str, nodes: Iterable[str]) -> Topology:
    """Spec string → validated Topology. ``auto``/``auto:<fan_in>``
    balances over the target set; anything else is the declared zone
    grammar. All failures are TopologyError with the reason."""
    spec = (spec or "auto").strip()
    if spec == "auto" or spec.startswith("auto:"):
        fan_in = DEFAULT_FAN_IN
        if spec.startswith("auto:"):
            try:
                fan_in = int(spec.split(":", 1)[1])
            except ValueError:
                raise TopologyError(
                    f"bad auto fan-in in {spec!r} — expected auto:<int>")
        return auto_topology(nodes, fan_in=fan_in)
    return _parse_declared(spec, nodes)


__all__ = ["DEFAULT_FAN_IN", "ROOT_ID", "Topology", "TopologyError",
           "TreeNode", "auto_topology", "parse_topology"]
