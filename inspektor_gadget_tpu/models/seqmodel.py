"""Sequence anomaly scorer: a causal transformer LM over syscall tokens.

Third scorer family next to the autoencoder (autoencoder.py) and VAE
(vae.py). Where those score per-container *distributions* (bag of
syscalls), this one scores *order*: the model is trained online as a
next-token LM over each container's recent event-key sequence, and the
anomaly score is the mean next-token negative log-likelihood — a container
doing familiar things in an unfamiliar order lights up here and nowhere
else. Reference analogue: the `advise seccomp-profile` gadget's per-
container syscall recording (reference pkg/gadget-collection/gadgets/
advise/seccomp/gadget.go:582) — which only captures the *set*; this is
the TPU-native upgrade to full sequence likelihood.

TPU-first choices: bf16 matmuls (MXU), f32 softmax/layernorm state,
sinusoidal positions (no learned table → any window length, and under
sequence parallelism each shard derives its global positions locally),
attention backend selectable per call: 'full' (short windows),
'blockwise' (long windows, one chip), 'flash' (Pallas fused kernel with a
blockwise-recompute custom_vjp — fastest long-window path for scoring and
training, parallel/flash_attention.py), 'ring' / 'ulysses' (windows
sharded over a mesh axis — parallel/ring_attention.py).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..parallel.compat import shard_map

from ..parallel.flash_attention import flash_attention
from ..parallel.ring_attention import (
    blockwise_attention, full_attention, ring_attention, ulysses_attention,
)

# backends rejected by training entry points (currently none: 'flash'
# carries a custom_vjp — fused forward, blockwise-recompute backward)
_SCORE_ONLY_ATTN: frozenset = frozenset()


def _check_trainable_attn(attn: str) -> None:
    if attn in _SCORE_ONLY_ATTN:
        raise ValueError(
            f"attn={attn!r} is a score-only backend; train with 'full', "
            "'blockwise', 'ring' or 'ulysses'")


@dataclasses.dataclass(frozen=True)
class SeqConfig:
    vocab: int = 512          # syscall/key token space (key % vocab)
    d_model: int = 128
    n_heads: int = 4
    n_layers: int = 2
    d_ff: int = 256
    lr: float = 1e-3
    dtype: Any = jnp.bfloat16
    # MoE FF (0 = dense): scorer capacity scales by adding experts without
    # growing per-token FLOPs; experts shard over an 'expert' mesh axis via
    # make_ep_train_step (parallel/moe.py all_to_all dispatch)
    n_experts: int = 0
    capacity_factor: float = 2.0
    balance_coef: float = 0.01


@dataclasses.dataclass
class SeqScorer:
    params: dict
    opt_state: Any
    steps: int
    config: SeqConfig


def _optimizer(cfg: SeqConfig):
    return optax.adamw(cfg.lr)


def seq_init(cfg: SeqConfig = SeqConfig(), seed: int = 0) -> SeqScorer:
    k = jax.random.PRNGKey(seed)
    keys = iter(jax.random.split(k, 4 + 8 * cfg.n_layers))

    def dense(fi, fo):
        return {
            "w": (jax.random.normal(next(keys), (fi, fo), jnp.float32)
                  * (2.0 / (fi + fo)) ** 0.5),
            "b": jnp.zeros((fo,), jnp.float32),
        }

    d, f = cfg.d_model, cfg.d_ff
    layers = []
    for _ in range(cfg.n_layers):
        layer = {
            "ln1": {"g": jnp.ones((d,)), "b": jnp.zeros((d,))},
            "qkv": dense(d, 3 * d),
            "out": dense(d, d),
            "ln2": {"g": jnp.ones((d,)), "b": jnp.zeros((d,))},
        }
        if cfg.n_experts:
            from ..parallel.moe import moe_init
            layer["moe"] = moe_init(next(keys), cfg.n_experts, d, f)
        else:
            layer["ff1"] = dense(d, f)
            layer["ff2"] = dense(f, d)
        layers.append(layer)
    params = {
        "embed": jax.random.normal(next(keys), (cfg.vocab, d)) * 0.02,
        "layers": layers,
        "lnf": {"g": jnp.ones((d,)), "b": jnp.zeros((d,))},
        "unembed": dense(d, cfg.vocab),
    }
    return SeqScorer(params=params, opt_state=_optimizer(cfg).init(params),
                     steps=0, config=cfg)


def _ln(x, p):
    x32 = x.astype(jnp.float32)
    mu = x32.mean(-1, keepdims=True)
    var = x32.var(-1, keepdims=True)
    return ((x32 - mu) * lax.rsqrt(var + 1e-6) * p["g"] + p["b"]).astype(x.dtype)


def _dense(x, p):
    return x @ p["w"].astype(x.dtype) + p["b"].astype(x.dtype)


def _sincos_positions(pos, d):
    """Sinusoidal encoding for explicit (possibly shard-offset) positions."""
    half = d // 2
    freqs = jnp.exp(-jnp.arange(half) * (jnp.log(10000.0) / max(half - 1, 1)))
    ang = pos[:, None].astype(jnp.float32) * freqs[None, :]
    return jnp.concatenate([jnp.sin(ang), jnp.cos(ang)], axis=-1)


def _attend(q, k, v, cfg, attn: str, axis_name: str | None):
    if attn == "full":
        return full_attention(q, k, v, causal=True)
    if attn == "flash":
        return flash_attention(q, k, v, causal=True)
    if attn == "blockwise":
        t = q.shape[1]
        chunk = next(c for c in range(min(128, t), 0, -1) if t % c == 0)
        return blockwise_attention(q, k, v, causal=True, chunk=chunk)
    if attn == "ring":
        return ring_attention(q, k, v, axis_name, causal=True)
    if attn == "ulysses":
        return ulysses_attention(q, k, v, axis_name, causal=True)
    raise ValueError(f"unknown attention impl {attn!r}")


def _seq_apply_aux(params: dict, tokens: jnp.ndarray, cfg: SeqConfig,
                   attn: str = "full", axis_name: str | None = None,
                   pos_offset: jnp.ndarray | int = 0,
                   ep_axis: str | None = None,
                   ep_size: int = 1) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(logits [B,T,vocab], moe balance loss) — internal; ep_axis routes MoE
    layers through the expert-parallel all_to_all path inside shard_map."""
    b, t = tokens.shape
    d, h = cfg.d_model, cfg.n_heads
    pos = pos_offset + jnp.arange(t)
    x = (params["embed"][tokens] + _sincos_positions(pos, d)).astype(cfg.dtype)
    balance = jnp.float32(0.0)
    for lp in params["layers"]:
        y = _ln(x, lp["ln1"])
        qkv = _dense(y, lp["qkv"]).reshape(b, t, 3, h, d // h)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        a = _attend(q, k, v, cfg, attn, axis_name).reshape(b, t, d)
        x = x + _dense(a, lp["out"])
        y = _ln(x, lp["ln2"])
        if "moe" in lp:
            from ..parallel.moe import moe_ff
            ff, (bal, _) = moe_ff(lp["moe"], y.reshape(b * t, d),
                                  cfg.capacity_factor, axis_name=ep_axis,
                                  axis_size=ep_size)
            x = x + ff.reshape(b, t, d)
            balance = balance + bal
        else:
            x = x + _dense(jax.nn.gelu(_dense(y, lp["ff1"])), lp["ff2"])
    x = _ln(x, params["lnf"])
    return _dense(x, params["unembed"]).astype(jnp.float32), balance


def seq_apply(params: dict, tokens: jnp.ndarray, cfg: SeqConfig,
              attn: str = "full", axis_name: str | None = None,
              pos_offset: jnp.ndarray | int = 0) -> jnp.ndarray:
    """Logits [B, T, vocab] for token ids [B, T] (int32).

    Under sequence parallelism, `tokens` is the local shard and
    `pos_offset` the global index of its first column.
    """
    return _seq_apply_aux(params, tokens, cfg, attn, axis_name, pos_offset)[0]


def _token_nll(logits: jnp.ndarray, targets: jnp.ndarray,
               mask: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Per-sequence (sum NLL, count) over masked next-token targets."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    nll = nll * mask
    return nll.sum(axis=-1), mask.sum(axis=-1)


def seq_loss(params: dict, tokens: jnp.ndarray, cfg: SeqConfig,
             attn: str = "full") -> jnp.ndarray:
    logits, bal = _seq_apply_aux(params, tokens[:, :-1], cfg, attn=attn)
    mask = (tokens[:, 1:] >= 0).astype(jnp.float32)
    s, c = _token_nll(logits, jnp.maximum(tokens[:, 1:], 0), mask)
    return s.sum() / jnp.maximum(c.sum(), 1.0) + cfg.balance_coef * bal


@functools.partial(jax.jit, static_argnames=("cfg", "attn"), donate_argnums=(0, 1))
def _train_step(params, opt_state, tokens, cfg: SeqConfig, attn: str):
    loss, grads = jax.value_and_grad(seq_loss)(params, tokens, cfg, attn)
    updates, opt_state = _optimizer(cfg).update(grads, opt_state, params)
    return optax.apply_updates(params, updates), opt_state, loss


def seq_train_step(scorer: SeqScorer, tokens: jnp.ndarray,
                   attn: str = "full") -> tuple[SeqScorer, jnp.ndarray]:
    _check_trainable_attn(attn)
    p, o, loss = _train_step(scorer.params, scorer.opt_state, tokens,
                             scorer.config, attn)
    return SeqScorer(params=p, opt_state=o, steps=scorer.steps + 1,
                     config=scorer.config), loss


@functools.partial(jax.jit, static_argnames=("cfg", "attn"))
def _score(params, tokens, cfg: SeqConfig, attn: str):
    logits = seq_apply(params, tokens[:, :-1], cfg, attn=attn)
    mask = (tokens[:, 1:] >= 0).astype(jnp.float32)
    s, c = _token_nll(logits, jnp.maximum(tokens[:, 1:], 0), mask)
    return s / jnp.maximum(c, 1.0)


def seq_score(scorer: SeqScorer, tokens: jnp.ndarray,
              attn: str = "full") -> jnp.ndarray:
    """Mean next-token NLL per sequence — the anomaly score. Padding is
    marked with negative token ids."""
    return _score(scorer.params, tokens, scorer.config, attn)


# --- sequence-parallel training (long windows sharded over a mesh axis) ----

def _sp_loss_local(params, tok_local, rank, n, cfg, attn, axis_name):
    """Local-shard loss body under shard_map. Next-token targets cross the
    shard boundary: each rank fetches the *first* token of the next rank's
    shard via one ppermute hop; the final global position has no target."""
    b, t = tok_local.shape
    logits = seq_apply(params, tok_local, cfg, attn=attn,
                       axis_name=axis_name, pos_offset=rank * t)
    nxt_first = lax.ppermute(tok_local[:, 0], axis_name,
                             [(i, (i - 1) % n) for i in range(n)])
    targets = jnp.concatenate([tok_local[:, 1:], nxt_first[:, None]], axis=1)
    mask = (targets >= 0).astype(jnp.float32)
    mask = mask.at[:, -1].set(jnp.where(rank == n - 1, 0.0, mask[:, -1]))
    s, c = _token_nll(logits, jnp.maximum(targets, 0), mask)
    return (lax.psum(s.sum(), axis_name),
            lax.psum(c.sum(), axis_name))


def make_sp_train_step(mesh: Mesh, cfg: SeqConfig, attn: str = "ring",
                       axis: str = "seq"):
    """Build a jitted sequence-parallel train step: tokens [B, T_global]
    sharded over `axis`, params replicated, grads psum-reduced."""
    _check_trainable_attn(attn)
    n = mesh.shape[axis]
    opt = _optimizer(cfg)

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(), P(), P(None, axis)),
        out_specs=(P(), P(), P()))
    def step(params, opt_state, tokens):
        rank = lax.axis_index(axis)

        def loss_fn(p):
            s, c = _sp_loss_local(p, tokens, rank, n, cfg, attn, axis)
            return s / jnp.maximum(c, 1.0)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        # loss_fn is already the *global* loss (psum'd numerator/denominator),
        # so each rank's grad holds only its local terms: sum, don't average.
        grads = jax.tree.map(lambda g: lax.psum(g, axis), grads)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    return jax.jit(step, donate_argnums=(0, 1))


# --- expert-parallel training (MoE layers sharded over an 'expert' axis) ---

def seq_param_pspecs(params: dict, ep_axis: str):
    """PartitionSpecs for a MoE seq model: expert FFN stacks sharded on
    their leading expert dim, everything else (embed, attention, gate,
    norms) replicated — the standard DP+EP-on-one-axis layout."""
    return jax.tree_util.tree_map_with_path(
        lambda path, _leaf: P(ep_axis) if _is_expert_path(path) else P(),
        params)


def _is_expert_path(path) -> bool:
    keys = [getattr(p, "key", getattr(p, "name", "")) for p in path]
    return "moe" in keys and keys[-1] != "gate"


def make_ep_train_step(mesh: Mesh, cfg: SeqConfig, scorer: SeqScorer,
                       attn: str = "full", axis: str = "expert"):
    """Build a jitted expert-parallel train step for a MoE seq scorer:
    token batches [B, T] sharded over `axis` (data parallel), MoE expert
    stacks sharded over the same axis (expert parallel — the layers take
    the all_to_all dispatch path), dense params replicated with psum'd
    grads. Expert grads need no reduction: the all_to_all backprop already
    delivers every rank's contribution to the owning shard. `scorer` is
    only used as the tree template for partition specs."""
    _check_trainable_attn(attn)
    if not cfg.n_experts:
        raise ValueError("make_ep_train_step requires cfg.n_experts > 0")
    n = mesh.shape[axis]
    if cfg.n_experts % n:
        raise ValueError(f"n_experts={cfg.n_experts} not divisible by {n}")
    opt = _optimizer(cfg)

    pspecs = seq_param_pspecs(scorer.params, axis)
    # optimizer state embeds copies of the param tree per moment; the same
    # path rule shards expert moments and replicates the rest + scalars
    ospecs = seq_param_pspecs(scorer.opt_state, axis)

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(pspecs, ospecs, P(axis)),
        out_specs=(pspecs, ospecs, P()))
    def step(params, opt_state, tokens):
        def loss_fn(p):
            logits, bal = _seq_apply_aux(
                p, tokens[:, :-1], cfg, attn=attn, ep_axis=axis, ep_size=n)
            mask = (tokens[:, 1:] >= 0).astype(jnp.float32)
            s, c = _token_nll(logits, jnp.maximum(tokens[:, 1:], 0), mask)
            nll = (lax.psum(s.sum(), axis)
                   / jnp.maximum(lax.psum(c.sum(), axis), 1.0))
            return nll + cfg.balance_coef * lax.pmean(bal, axis)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        # replicated leaves: sum local grad terms across ranks; expert
        # shards: already complete on their owner (see docstring)
        grads = jax.tree_util.tree_map_with_path(
            lambda path, g: g if _is_expert_path(path) else lax.psum(g, axis),
            grads)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    return jax.jit(step, donate_argnums=(0, 1))


def tokens_from_keys(keys: np.ndarray, vocab: int) -> np.ndarray:
    """Map raw event keys (any uint width) onto the LM token space."""
    return (keys.astype(np.uint64) % np.uint64(vocab)).astype(np.int32)
