"""Autoencoder anomaly scorer over per-container event distributions.

Input: L1-normalized, log-scaled count vectors (e.g. the 2^12-bucket syscall
distribution from the entropy sketch, per container). A 3-layer MLP
autoencoder reconstructs the vector; per-row MSE is the anomaly score.
Online training: Adam on streaming mini-batches; weights replicate across
the mesh, gradients psum over the 'node' axis (pure DP — the vectors are
tiny; the matmuls batch onto the MXU in bf16).

TPU notes: params kept in f32, activations cast to bf16 for the matmuls;
hidden sizes padded to multiples of 128 (MXU lane width).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import flax.struct
import jax
import jax.numpy as jnp
import optax


@dataclasses.dataclass(frozen=True)
class AEConfig:
    input_dim: int = 4096        # matches entropy sketch width (2^12)
    hidden_dim: int = 512
    latent_dim: int = 128
    learning_rate: float = 1e-3
    compute_dtype: Any = jnp.bfloat16


@flax.struct.dataclass
class AnomalyScorer:
    params: dict
    opt_state: Any
    steps: jnp.ndarray
    config: AEConfig = flax.struct.field(pytree_node=False)


def _optimizer(cfg: AEConfig):
    return optax.adam(cfg.learning_rate)


def ae_init(cfg: AEConfig = AEConfig(), seed: int = 0) -> AnomalyScorer:
    k = jax.random.PRNGKey(seed)
    ks = jax.random.split(k, 6)

    def dense(key, fan_in, fan_out):
        scale = jnp.sqrt(2.0 / fan_in)
        return {
            "w": jax.random.normal(key, (fan_in, fan_out), jnp.float32) * scale,
            "b": jnp.zeros((fan_out,), jnp.float32),
        }

    params = {
        "enc1": dense(ks[0], cfg.input_dim, cfg.hidden_dim),
        "enc2": dense(ks[1], cfg.hidden_dim, cfg.latent_dim),
        "dec1": dense(ks[2], cfg.latent_dim, cfg.hidden_dim),
        "dec2": dense(ks[3], cfg.hidden_dim, cfg.input_dim),
    }
    opt_state = _optimizer(cfg).init(params)
    return AnomalyScorer(params=params, opt_state=opt_state,
                         steps=jnp.zeros((), jnp.int32), config=cfg)


def _layer(x, p, dtype):
    return x.astype(dtype) @ p["w"].astype(dtype) + p["b"].astype(dtype)


def ae_apply(params: dict, x: jnp.ndarray, cfg: AEConfig) -> jnp.ndarray:
    dt = cfg.compute_dtype
    h = jax.nn.gelu(_layer(x, params["enc1"], dt))
    z = jax.nn.gelu(_layer(h, params["enc2"], dt))
    h = jax.nn.gelu(_layer(z, params["dec1"], dt))
    out = _layer(h, params["dec2"], dt)
    return out.astype(jnp.float32)


def normalize_counts(counts: jnp.ndarray) -> jnp.ndarray:
    """log1p + L1 normalize a (batch, dim) count matrix."""
    x = jnp.log1p(counts.astype(jnp.float32))
    return x / jnp.maximum(x.sum(axis=-1, keepdims=True), 1e-6)


def ae_loss(params: dict, x: jnp.ndarray, cfg: AEConfig) -> jnp.ndarray:
    recon = ae_apply(params, x, cfg)
    return jnp.mean((recon - x) ** 2)


def ae_score(scorer: AnomalyScorer, x: jnp.ndarray) -> jnp.ndarray:
    """Per-row anomaly score: reconstruction MSE, scaled for display."""
    recon = ae_apply(scorer.params, x, scorer.config)
    return jnp.mean((recon - x) ** 2, axis=-1) * x.shape[-1]


def ae_train_step(
    scorer: AnomalyScorer, x: jnp.ndarray, axis_name: str | None = None
) -> tuple[AnomalyScorer, jnp.ndarray]:
    """One Adam step; grads psum'd over `axis_name` when run under shard_map
    (data-parallel over the node axis of the mesh)."""
    loss, grads = jax.value_and_grad(ae_loss)(scorer.params, x, scorer.config)
    if axis_name is not None:
        grads = jax.lax.pmean(grads, axis_name)
        loss = jax.lax.pmean(loss, axis_name)
    updates, opt_state = _optimizer(scorer.config).update(grads, scorer.opt_state, scorer.params)
    params = optax.apply_updates(scorer.params, updates)
    return scorer.replace(params=params, opt_state=opt_state, steps=scorer.steps + 1), loss


# ---------------------------------------------------------------------------
# Tensor-parallel variant (Megatron MLP pattern over the mesh 'model' axis):
# enc1/dec1 column-parallel (hidden sharded, no collective), enc2/dec2
# row-parallel (contract over the sharded hidden → one psum each). Two
# psums per forward; activations stay sharded through the gelu.
# ---------------------------------------------------------------------------


def ae_param_pspecs(model_axis: str = "model"):
    """PartitionSpec tree for tensor-parallel autoencoder params."""
    from jax.sharding import PartitionSpec as P

    col = {"w": P(None, model_axis), "b": P(model_axis)}   # column-parallel
    row = {"w": P(model_axis, None), "b": P()}             # row-parallel
    return {"enc1": col, "enc2": row, "dec1": col, "dec2": row}


def ae_apply_tp(params: dict, x: jnp.ndarray, cfg: AEConfig,
                model_axis: str = "model") -> jnp.ndarray:
    dt = cfg.compute_dtype
    h = jax.nn.gelu(_layer(x, params["enc1"], dt))          # (b, hidden/m)
    z = jax.lax.psum(
        (h.astype(dt) @ params["enc2"]["w"].astype(dt)), model_axis
    ) + params["enc2"]["b"].astype(dt)
    z = jax.nn.gelu(z)                                      # (b, latent) repl
    h2 = jax.nn.gelu(_layer(z, params["dec1"], dt))         # (b, hidden/m)
    out = jax.lax.psum(
        (h2.astype(dt) @ params["dec2"]["w"].astype(dt)), model_axis
    ) + params["dec2"]["b"].astype(dt)
    return out.astype(jnp.float32)


def ae_loss_tp(params: dict, x: jnp.ndarray, cfg: AEConfig,
               model_axis: str = "model") -> jnp.ndarray:
    recon = ae_apply_tp(params, x, cfg, model_axis)
    return jnp.mean((recon - x) ** 2)


def ae_score_tp(scorer: AnomalyScorer, x: jnp.ndarray,
                model_axis: str = "model") -> jnp.ndarray:
    recon = ae_apply_tp(scorer.params, x, scorer.config, model_axis)
    return jnp.mean((recon - x) ** 2, axis=-1) * x.shape[-1]


def ae_train_step_tp(
    scorer: AnomalyScorer, x: jnp.ndarray, *, dp_axis: str | None = "node",
    model_axis: str = "model",
) -> tuple[AnomalyScorer, jnp.ndarray]:
    """DP×TP step under shard_map: forward/backward with model-axis psums
    (autodiff transposes them correctly), grads pmean'd over the data axis,
    per-shard Adam update (optimizer state shards like the params)."""
    loss, grads = jax.value_and_grad(ae_loss_tp)(
        scorer.params, x, scorer.config, model_axis)
    if dp_axis is not None:
        grads = jax.lax.pmean(grads, dp_axis)
        loss = jax.lax.pmean(loss, dp_axis)
    updates, opt_state = _optimizer(scorer.config).update(
        grads, scorer.opt_state, scorer.params)
    params = optax.apply_updates(scorer.params, updates)
    return scorer.replace(params=params, opt_state=opt_state,
                          steps=scorer.steps + 1), loss
