"""Analytics models: the autoencoder anomaly scorer and its online trainer.

The reference's closest analogue is advise/seccomp-profile (record per-
container syscall sets, synthesize a policy; pkg/gadgets/advise/seccomp +
pkg/gadget-collection/gadgets/advise/seccomp/gadget.go). Here the per-
container syscall *distribution* (from the entropy sketch's hashed count
vector) feeds a small autoencoder; reconstruction error is the anomaly
score, trained online with optax — batched bf16 matmuls on the MXU.
"""

from .vae import (
    VAEScorer, VAEConfig, vae_init, vae_score, vae_train_step,
)
from .autoencoder import (
    AnomalyScorer,
    AEConfig,
    ae_init,
    ae_apply,
    ae_loss,
    ae_train_step,
    ae_score,
)

__all__ = [
    "AnomalyScorer", "AEConfig", "ae_init", "ae_apply", "ae_loss",
    "ae_train_step", "ae_score",
    "VAEScorer", "VAEConfig", "vae_init", "vae_score", "vae_train_step",
]
