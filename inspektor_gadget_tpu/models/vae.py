"""Variational autoencoder anomaly scorer — the probabilistic alternative
to the deterministic AE (models/autoencoder.py).

Score = negative ELBO (reconstruction NLL + KL to the unit Gaussian), which
separates "rare but in-distribution" from "structurally novel" better than
plain reconstruction error on skewed syscall/flow distributions. Same
interface as the AE scorer, so the tpusketch operator can swap
(`anomaly-model=vae`). bf16 matmuls on the MXU; reparameterization keeps
the step jittable with an explicit PRNG key threaded through the state.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import flax.struct
import jax
import jax.numpy as jnp
import optax


@dataclasses.dataclass(frozen=True)
class VAEConfig:
    input_dim: int = 4096
    hidden_dim: int = 512
    latent_dim: int = 64
    learning_rate: float = 1e-3
    kl_weight: float = 1e-2
    compute_dtype: Any = jnp.bfloat16


@flax.struct.dataclass
class VAEScorer:
    params: dict
    opt_state: Any
    rng: jnp.ndarray
    steps: jnp.ndarray
    config: VAEConfig = flax.struct.field(pytree_node=False)


def _optimizer(cfg: VAEConfig):
    return optax.adam(cfg.learning_rate)


def vae_init(cfg: VAEConfig = VAEConfig(), seed: int = 0) -> VAEScorer:
    k = jax.random.PRNGKey(seed)
    ks = jax.random.split(k, 8)

    def dense(key, fi, fo):
        return {
            "w": jax.random.normal(key, (fi, fo), jnp.float32) * jnp.sqrt(2.0 / fi),
            "b": jnp.zeros((fo,), jnp.float32),
        }

    params = {
        "enc": dense(ks[0], cfg.input_dim, cfg.hidden_dim),
        "mu": dense(ks[1], cfg.hidden_dim, cfg.latent_dim),
        "logvar": dense(ks[2], cfg.hidden_dim, cfg.latent_dim),
        "dec1": dense(ks[3], cfg.latent_dim, cfg.hidden_dim),
        "dec2": dense(ks[4], cfg.hidden_dim, cfg.input_dim),
    }
    return VAEScorer(params=params, opt_state=_optimizer(cfg).init(params),
                     rng=ks[5], steps=jnp.zeros((), jnp.int32), config=cfg)


def _layer(x, p, dt):
    return x.astype(dt) @ p["w"].astype(dt) + p["b"].astype(dt)


def vae_encode(params, x, cfg):
    h = jax.nn.gelu(_layer(x, params["enc"], cfg.compute_dtype))
    return (_layer(h, params["mu"], cfg.compute_dtype).astype(jnp.float32),
            _layer(h, params["logvar"], cfg.compute_dtype).astype(jnp.float32))


def vae_decode(params, z, cfg):
    h = jax.nn.gelu(_layer(z, params["dec1"], cfg.compute_dtype))
    return _layer(h, params["dec2"], cfg.compute_dtype).astype(jnp.float32)


def vae_elbo_terms(params, x, key, cfg):
    mu, logvar = vae_encode(params, x, cfg)
    logvar = jnp.clip(logvar, -8.0, 8.0)
    eps = jax.random.normal(key, mu.shape, jnp.float32)
    z = mu + jnp.exp(0.5 * logvar) * eps
    recon = vae_decode(params, z, cfg)
    rec_err = jnp.mean((recon - x) ** 2, axis=-1) * x.shape[-1]
    kl = -0.5 * jnp.sum(1 + logvar - mu**2 - jnp.exp(logvar), axis=-1)
    return rec_err, kl


def vae_loss(params, x, key, cfg):
    rec, kl = vae_elbo_terms(params, x, key, cfg)
    return jnp.mean(rec + cfg.kl_weight * kl)


def vae_score(scorer: VAEScorer, x: jnp.ndarray) -> jnp.ndarray:
    """Anomaly score = negative ELBO per row (deterministic: z = mu)."""
    cfg = scorer.config
    mu, logvar = vae_encode(scorer.params, x, cfg)
    logvar = jnp.clip(logvar, -8.0, 8.0)
    recon = vae_decode(scorer.params, mu, cfg)
    rec_err = jnp.mean((recon - x) ** 2, axis=-1) * x.shape[-1]
    kl = -0.5 * jnp.sum(1 + logvar - mu**2 - jnp.exp(logvar), axis=-1)
    return rec_err + cfg.kl_weight * kl


def vae_train_step(scorer: VAEScorer, x: jnp.ndarray,
                   axis_name: str | None = None) -> tuple[VAEScorer, jnp.ndarray]:
    key, next_rng = jax.random.split(scorer.rng)
    loss, grads = jax.value_and_grad(vae_loss)(scorer.params, x, key,
                                               scorer.config)
    if axis_name is not None:
        grads = jax.lax.pmean(grads, axis_name)
        loss = jax.lax.pmean(loss, axis_name)
    updates, opt_state = _optimizer(scorer.config).update(
        grads, scorer.opt_state, scorer.params)
    params = optax.apply_updates(scorer.params, updates)
    return scorer.replace(params=params, opt_state=opt_state, rng=next_rng,
                          steps=scorer.steps + 1), loss
