// Event capture sources.
//
// The reference's L0 is eBPF programs attached to tracepoints/kprobes
// (SURVEY §2.4); in this build the native capture layer is C++:
//  - SyntheticSource: deterministic zipf-distributed event generator — the
//    replayable test/bench backbone (the analogue of the reference's
//    namespace-unshare fake containers + event triggers,
//    internal/test/runner.go).
//  - ProcExecSource: real exec/exit capture via netlink proc connector
//    (PROC_EVENT_EXEC/EXIT) with /proc polling fallback — the non-eBPF
//    kernel boundary for trace/exec + trace/signal-ish lifecycles.
//  - ProcTcpSource: /proc/net/tcp{,6} diff scanner for connect/accept/close
//    (trace/tcp family without a socket filter).
//
// Every source owns an SPSC ring; a drop is counted, never blocks capture.

#include <fcntl.h>
#include <pthread.h>
#include <sys/stat.h>
#include <unistd.h>

#include <cerrno>
#include <cmath>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <ctime>
#include <map>
#include <memory>
#include <mutex>
#include <set>
#include <string>
#include <thread>
#include <unordered_map>
#include <unordered_set>
#include <vector>

#ifdef __linux__
#include <dirent.h>
#include <linux/cn_proc.h>
#include <linux/connector.h>
#include <linux/netlink.h>
#include <sys/socket.h>
#endif

#include "ringbuf.h"

namespace ig {

static uint64_t now_ns() {
  struct timespec ts;
  clock_gettime(CLOCK_REALTIME, &ts);
  return (uint64_t)ts.tv_sec * 1000000000ull + ts.tv_nsec;
}

// ---------------------------------------------------------------------------
// Vocab: hash -> string side table for un-hashing heavy hitters.
// ---------------------------------------------------------------------------

class Vocab {
 public:
  void put(uint64_t h, const char* s, size_t n) {
    std::lock_guard<std::mutex> g(mu_);
    if (map_.size() >= cap_) return;  // consumers fall back to hex keys
    auto it = map_.find(h);
    if (it == map_.end()) map_.emplace(h, std::string(s, n));
  }

  // Bound the side table for high-cardinality producers (per-call-unique
  // syscall lines would otherwise grow it for the life of the source).
  void set_capacity(size_t cap) {
    std::lock_guard<std::mutex> g(mu_);
    cap_ = cap;
  }
  // returns copied length, 0 if unknown
  size_t get(uint64_t h, char* out, size_t cap) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = map_.find(h);
    if (it == map_.end()) return 0;
    size_t n = it->second.size() < cap ? it->second.size() : cap;
    memcpy(out, it->second.data(), n);
    return n;
  }

 private:
  std::mutex mu_;
  std::unordered_map<uint64_t, std::string> map_;
  size_t cap_ = (size_t)-1;
};

// ---------------------------------------------------------------------------
// Source base
// ---------------------------------------------------------------------------

class Source {
 public:
  explicit Source(size_t ring_pow2) : ring_(ring_pow2) {}
  // Derived classes MUST stop() in their own destructor: the capture thread
  // runs derived run() and reads derived members, which are destroyed before
  // this base destructor joins the thread.
  virtual ~Source() { stop(); }

  virtual void start() {
    // cpu_mu_ guards every access to thread_ (assignment here, the final
    // sample in stop(), joinable()/native_handle() reads in
    // thread_cpu_ns()) — std::thread itself is not atomic
    std::lock_guard<std::mutex> g(cpu_mu_);
    running_.store(true);
    thread_ = std::thread([this] { run(); });
  }
  virtual void stop() {
    // Sample the CPU clock and move the handle out under cpu_mu_, then
    // join OUTSIDE the lock: a capture thread blocked in a long syscall
    // must not stall stats readers (ig_sources_stats holds g_mu while
    // waiting on cpu_mu_, so a held-across-join cpu_mu_ would freeze the
    // whole C API behind one slow shutdown).
    std::thread t;
    {
      std::lock_guard<std::mutex> g(cpu_mu_);
      bool was = running_.exchange(false);
      if (was && thread_.joinable()) {
        sample_cpu_locked();
        t = std::move(thread_);
      }
    }
    if (t.joinable()) t.join();
  }

  size_t pop(Event* out, size_t n) { return ring_.pop(out, n); }
  uint64_t drops() const { return ring_.drops(); }
  uint64_t produced() const { return ring_.produced(); }
  uint64_t filtered() const {
    return filtered_.load(std::memory_order_relaxed);
  }
  Vocab& vocab() { return vocab_; }

  // -- self-stats (the top/ebpf contract: per-program runtime via kernel
  //    stats, pkg/gadgets/top/ebpf/tracer.go:55-418 + pkg/bpfstats) -------
  void set_kind(uint32_t k) { kind_ = k; }
  uint32_t kind() const { return kind_; }
  uint64_t ring_len() const { return ring_.size(); }
  uint64_t ring_capacity() const { return ring_.capacity(); }
  uint64_t consumed() const { return ring_.consumed(); }
  // CPU time consumed by this source's capture thread (ns); the analogue
  // of BPF_ENABLE_STATS run_time_ns per program.
  uint64_t thread_cpu_ns() {
    std::lock_guard<std::mutex> g(cpu_mu_);
    if (running_.load(std::memory_order_relaxed) && thread_.joinable())
      sample_cpu_locked();
    return last_cpu_ns_;
  }

  // Capture-side container filter — the mntnsset-map analogue
  // (ref: pkg/tracer-collection/tracer-collection.go:100-134 keeps a per-
  // tracer BPF hash of allowed mntns ids so events are discarded *before*
  // they ever reach userspace). Here the set is swapped in atomically from
  // the tracer-collection pubsub; capture threads consult it pre-push, so a
  // filtered gadget does zero per-event Python work and every suppressed
  // event is accounted.
  void set_filter(const uint64_t* ids, size_t n) {
    std::shared_ptr<const std::unordered_set<uint64_t>> f;
    if (ids != nullptr)
      f = std::make_shared<const std::unordered_set<uint64_t>>(ids, ids + n);
    std::lock_guard<std::mutex> g(filter_mu_);
    filter_ = std::move(f);
  }

 protected:
  virtual void run() = 0;

  // Push through the filter; every event a capture thread emits goes here.
  bool emit(const Event& ev) {
    {
      std::shared_ptr<const std::unordered_set<uint64_t>> f;
      {
        std::lock_guard<std::mutex> g(filter_mu_);
        f = filter_;
      }
      if (f && !f->count(ev.mntns)) {
        filtered_.fetch_add(1, std::memory_order_relaxed);
        return false;
      }
    }
    return ring_.push(ev);
  }

  RingBuffer ring_;
  Vocab vocab_;
  std::atomic<bool> running_{false};
  std::thread thread_;
  std::mutex filter_mu_;
  std::shared_ptr<const std::unordered_set<uint64_t>> filter_;
  std::atomic<uint64_t> filtered_{0};

 private:
  void sample_cpu_locked() {
#ifdef __linux__
    clockid_t cid;
    if (pthread_getcpuclockid(thread_.native_handle(), &cid) == 0) {
      struct timespec ts;
      if (clock_gettime(cid, &ts) == 0)
        last_cpu_ns_ =
            (uint64_t)ts.tv_sec * 1000000000ull + (uint64_t)ts.tv_nsec;
    }
#endif
  }

  uint32_t kind_ = 0;
  std::mutex cpu_mu_;
  uint64_t last_cpu_ns_ = 0;
};

#ifdef __linux__
// Shared /proc identity fill: comm (hashed into the vocab) + mntns.
// Used by every procfs-adjacent source; the self-enrichment role of the
// reference's containers-map lookup inside BPF programs.
inline void fill_proc_identity(Event& ev, Vocab& vocab, uint32_t pid) {
  char path[64], buf[256];
  snprintf(path, sizeof(path), "/proc/%u/comm", pid);
  int fd = open(path, O_RDONLY);
  ssize_t n = 0;
  if (fd >= 0) {
    n = read(fd, buf, sizeof(buf) - 1);
    close(fd);
  }
  if (n > 0 && buf[n - 1] == '\n') n--;
  if (n > 0) {
    ev.key_hash = fnv1a64(buf, (size_t)n);
    vocab.put(ev.key_hash, buf, (size_t)n);
    size_t c = (size_t)n < sizeof(ev.comm) - 1 ? (size_t)n : sizeof(ev.comm) - 1;
    memcpy(ev.comm, buf, c);
  }
  snprintf(path, sizeof(path), "/proc/%u/ns/mnt", pid);
  char link[64];
  ssize_t ln = readlink(path, link, sizeof(link) - 1);
  if (ln > 0) {
    link[ln] = 0;
    const char* lb = strchr(link, '[');
    if (lb) ev.mntns = strtoull(lb + 1, nullptr, 10);
  }
}
#endif  // __linux__

// ---------------------------------------------------------------------------
// SyntheticSource — seeded zipf generator over a comm/addr vocabulary.
// ---------------------------------------------------------------------------

class SyntheticSource : public Source {
 public:
  SyntheticSource(size_t ring_pow2, uint32_t kind, uint64_t seed,
                  double rate_per_sec, uint32_t vocab_size, double zipf_s)
      : Source(ring_pow2),
        kind_(kind),
        rng_(seed ? seed : 0x9E3779B97F4A7C15ull),
        rate_(rate_per_sec),
        vocab_size_(vocab_size ? vocab_size : 1000),
        zipf_s_(zipf_s > 0 ? zipf_s : 1.2) {
    // Zipf sampling via Walker's alias method: O(1) per draw (one random,
    // one table probe) instead of a CDF binary search — keeps the host
    // generation path well above the device-feed requirement.
    std::vector<double> p(vocab_size_);
    double sum = 0;
    for (uint32_t i = 0; i < vocab_size_; i++) {
      p[i] = 1.0 / std::pow((double)(i + 1), zipf_s_);
      sum += p[i];
    }
    alias_prob_.resize(vocab_size_);
    alias_idx_.resize(vocab_size_);
    std::vector<uint32_t> small, large;
    std::vector<double> scaled(vocab_size_);
    for (uint32_t i = 0; i < vocab_size_; i++) {
      scaled[i] = p[i] / sum * vocab_size_;
      (scaled[i] < 1.0 ? small : large).push_back(i);
    }
    while (!small.empty() && !large.empty()) {
      uint32_t s = small.back(); small.pop_back();
      uint32_t l = large.back(); large.pop_back();
      alias_prob_[s] = scaled[s];
      alias_idx_[s] = l;
      scaled[l] = scaled[l] + scaled[s] - 1.0;
      (scaled[l] < 1.0 ? small : large).push_back(l);
    }
    for (uint32_t i : small) { alias_prob_[i] = 1.0; alias_idx_[i] = i; }
    for (uint32_t i : large) { alias_prob_[i] = 1.0; alias_idx_[i] = i; }
    names_.reserve(vocab_size_);
    for (uint32_t i = 0; i < vocab_size_; i++) {
      char buf[24];
      int n = snprintf(buf, sizeof(buf), "proc-%u", i);
      names_.emplace_back(buf, n);
      uint64_t h = fnv1a64(buf, n);
      hashes_.push_back(h);
      vocab_.put(h, buf, n);
    }
  }

  ~SyntheticSource() override { stop(); }

  // Fill a caller buffer directly — the zero-copy bench path (no thread).
  // One clock read per batch: the bridge stamps batch-level timestamps.
  size_t generate(Event* out, size_t n) {
    uint64_t ts = now_ns();
    for (size_t i = 0; i < n; i++) out[i] = make_event(ts);
    return n;
  }

  // Folded-uint32 fast path: the sketch plane consumes xor-folded uint32
  // keys, so fold once per vocab entry and emit draws straight into the
  // caller's H2D staging buffer — no 64-byte Event structs, no separate
  // numpy fold pass. One alias draw + one table load per event.
  size_t generate_folded(uint32_t* out, size_t n) {
    if (folded_.empty()) {
      folded_.reserve(hashes_.size());
      for (uint64_t h : hashes_)
        folded_.push_back((uint32_t)((h >> 32) ^ (h & 0xFFFFFFFFull)));
    }
    for (size_t i = 0; i < n; i++) out[i] = folded_[zipf_draw()];
    return n;
  }

 protected:
  void run() override {
    // Paced producer: emit in 1ms chunks at the requested rate.
    const double per_ms = rate_ / 1000.0;
    double carry = 0;
    while (running_.load(std::memory_order_relaxed)) {
      carry += per_ms;
      size_t n = (size_t)carry;
      carry -= (double)n;
      for (size_t i = 0; i < n; i++) emit(make_event());
      std::this_thread::sleep_for(std::chrono::milliseconds(1));
    }
  }

 private:
  uint64_t next_rand() {  // splitmix64
    uint64_t z = (rng_ += 0x9E3779B97F4A7C15ull);
    z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ull;
    z = (z ^ (z >> 27)) * 0x94D049BB133111EBull;
    return z ^ (z >> 31);
  }

  uint32_t zipf_draw() {
    uint64_t r = next_rand();
    uint32_t i = (uint32_t)((r >> 32) % vocab_size_);
    double u = (double)(r & 0xFFFFFFFF) * (1.0 / 4294967296.0);
    return u < alias_prob_[i] ? i : alias_idx_[i];
  }

  Event make_event(uint64_t ts = 0) {
    Event ev{};
    uint32_t idx = zipf_draw();
    ev.ts_ns = ts ? ts : now_ns();
    ev.key_hash = hashes_[idx];
    ev.pid = 1000 + (uint32_t)(next_rand() % 50000);
    ev.ppid = 1;
    ev.uid = (uint32_t)(next_rand() % 4);
    ev.kind = kind_;
    ev.mntns = 4026531840ull + idx % 64;  // 64 fake containers
    ev.aux1 = next_rand();                // e.g. addresses / bytes
    ev.aux2 = next_rand() & 0xFFFF;       // e.g. port / flags
    const std::string& nm = names_[idx];
    size_t n = nm.size() < sizeof(ev.comm) ? nm.size() : sizeof(ev.comm) - 1;
    memcpy(ev.comm, nm.data(), n);
    return ev;
  }

  uint32_t kind_;
  uint64_t rng_;
  double rate_;
  uint32_t vocab_size_;
  double zipf_s_;
  std::vector<double> alias_prob_;
  std::vector<uint32_t> alias_idx_;
  std::vector<std::string> names_;
  std::vector<uint64_t> hashes_;
  std::vector<uint32_t> folded_;
};

#ifdef __linux__

// ---------------------------------------------------------------------------
// ProcExecSource — netlink proc connector exec/exit events, /proc fallback.
// ---------------------------------------------------------------------------

class ProcExecSource : public Source {
 public:
  explicit ProcExecSource(size_t ring_pow2) : Source(ring_pow2) {}
  ~ProcExecSource() override { stop(); }

 protected:
  void run() override {
    if (!run_netlink()) run_procfs();
  }

 private:
  void fill_from_proc(Event& ev, uint32_t pid) {
    fill_proc_identity(ev, vocab_, pid);
    if (ev.key_hash == 0) {
      char buf[32];
      int n = snprintf(buf, sizeof(buf), "pid-%u", pid);
      ev.key_hash = fnv1a64(buf, (size_t)n);
      vocab_.put(ev.key_hash, buf, (size_t)n);
      memcpy(ev.comm, buf, (size_t)n < sizeof(ev.comm) - 1 ? (size_t)n
                                                           : sizeof(ev.comm) - 1);
    }
    // ppid + real uid: execsnoop's columns (the BPF event carries them
    // from task_struct; here one /proc/<pid>/status read — NOT the
    // /proc/<pid> inode owner, which the kernel forces to root for
    // non-dumpable processes, i.e. every setuid exec). Best effort — an
    // exec-and-exit racer may already be gone.
    char path[64];
    snprintf(path, sizeof(path), "/proc/%u/status", pid);
    int fd = open(path, O_RDONLY);
    if (fd >= 0) {
      char sb[1024];
      ssize_t n = read(fd, sb, sizeof(sb) - 1);
      close(fd);
      if (n > 0) {
        sb[n] = 0;
        const char* pp = strstr(sb, "\nPPid:");
        unsigned v = 0;
        if (pp && sscanf(pp + 6, " %u", &v) == 1) ev.ppid = v;
        const char* up = strstr(sb, "\nUid:");
        if (up && sscanf(up + 5, " %u", &v) == 1) ev.uid = v;  // real uid
      }
    }
    // argv: /proc/<pid>/cmdline, NUL-separated → spaces, vocab under aux1
    // (execsnoop's ARGS column; tracer.go:169-181 parses the same buffer,
    // itself capped in-kernel). A line beyond the buffer is marked "..."
    // so truncation is visible and distinct commands can't silently
    // collapse onto a shared prefix hash.
    snprintf(path, sizeof(path), "/proc/%u/cmdline", pid);
    fd = open(path, O_RDONLY);
    if (fd >= 0) {
      char ab[2048];
      // read 3 bytes short of the buffer so the marker ALWAYS fits — a
      // cap landing mid-argument is the common truncation case
      ssize_t n = read(fd, ab, sizeof(ab) - 4);
      close(fd);
      bool truncated = n == (ssize_t)sizeof(ab) - 4;
      while (n > 0 && ab[n - 1] == 0) n--;  // trailing NUL(s)
      if (n > 0) {
        for (ssize_t i = 0; i < n; i++)
          if (ab[i] == 0) ab[i] = ' ';
        if (truncated) {
          memcpy(ab + n, "...", 3);
          n += 3;
        }
        ev.aux1 = fnv1a64(ab, (size_t)n);
        vocab_.put(ev.aux1, ab, (size_t)n);
      }
    }
  }

  bool run_netlink() {
    int sock = socket(PF_NETLINK, SOCK_DGRAM | SOCK_NONBLOCK, NETLINK_CONNECTOR);
    if (sock < 0) return false;
    struct sockaddr_nl addr {};
    addr.nl_family = AF_NETLINK;
    addr.nl_groups = CN_IDX_PROC;
    addr.nl_pid = (uint32_t)getpid();
    if (bind(sock, (struct sockaddr*)&addr, sizeof(addr)) < 0) {
      close(sock);
      return false;
    }
    // subscribe: PROC_CN_MCAST_LISTEN. cn_msg ends in a flexible array
    // member, so the request is assembled in a flat buffer.
    char req[NLMSG_LENGTH(sizeof(struct cn_msg) + sizeof(enum proc_cn_mcast_op))];
    memset(req, 0, sizeof(req));
    struct nlmsghdr* hdr = (struct nlmsghdr*)req;
    hdr->nlmsg_len = sizeof(req);
    hdr->nlmsg_type = NLMSG_DONE;
    hdr->nlmsg_pid = (uint32_t)getpid();
    struct cn_msg* msg = (struct cn_msg*)NLMSG_DATA(hdr);
    msg->id.idx = CN_IDX_PROC;
    msg->id.val = CN_VAL_PROC;
    msg->len = sizeof(enum proc_cn_mcast_op);
    *(enum proc_cn_mcast_op*)msg->data = PROC_CN_MCAST_LISTEN;
    if (send(sock, req, sizeof(req), 0) < 0) {
      close(sock);
      return false;
    }
    char buf[4096];
    bool got_any = false;
    uint64_t start = now_ns();
    while (running_.load(std::memory_order_relaxed)) {
      ssize_t len = recv(sock, buf, sizeof(buf), 0);
      if (len <= 0) {
        if (errno == EAGAIN || errno == EWOULDBLOCK) {
          // If netlink stays silent for 2s with no permission, fall back.
          if (!got_any && now_ns() - start > 2000000000ull) {
            close(sock);
            return false;
          }
          std::this_thread::sleep_for(std::chrono::milliseconds(5));
          continue;
        }
        break;
      }
      for (struct nlmsghdr* h = (struct nlmsghdr*)buf; NLMSG_OK(h, (size_t)len);
           h = NLMSG_NEXT(h, len)) {
        struct cn_msg* cn = (struct cn_msg*)NLMSG_DATA(h);
        struct proc_event* pe = (struct proc_event*)cn->data;
        Event ev{};
        ev.ts_ns = now_ns();
        got_any = true;
        if (pe->what == proc_event::PROC_EVENT_EXEC) {
          ev.kind = EV_EXEC;
          ev.pid = (uint32_t)pe->event_data.exec.process_pid;
          fill_from_proc(ev, ev.pid);
          emit(ev);
        } else if (pe->what == proc_event::PROC_EVENT_EXIT) {
          ev.kind = EV_EXIT;
          ev.pid = (uint32_t)pe->event_data.exit.process_pid;
          ev.aux2 = (uint64_t)pe->event_data.exit.exit_code;
          emit(ev);
          // Termination by signal is kernel-real signal-delivery evidence:
          // exit_code follows wait(2) encoding, low 7 bits = fatal signal
          // (sigsnoop's system-wide window without eBPF; the ptrace source
          // covers full delivery for traced trees).
          uint32_t sig = (uint32_t)pe->event_data.exit.exit_code & 0x7f;
          if (sig != 0) {
            Event sv = ev;
            sv.kind = EV_SIGNAL;
            sv.ppid = ev.pid;  // receiver (tpid); sender unknown post-mortem
            sv.aux2 = sig;
            sv.aux1 = 1;  // delivered+fatal
            emit(sv);
          }
        }
      }
    }
    close(sock);
    return true;
  }

  void run_procfs() {
    // Poll /proc for new pids at 50Hz — the BCC-less fallback flavour
    // (role analogue of pkg/standardgadgets' subprocess fallback).
    std::set<uint32_t> seen;
    bool first = true;
    while (running_.load(std::memory_order_relaxed)) {
      DIR* d = opendir("/proc");
      if (!d) return;
      std::set<uint32_t> cur;
      struct dirent* de;
      while ((de = readdir(d))) {
        char* end;
        unsigned long pid = strtoul(de->d_name, &end, 10);
        if (*end || pid == 0) continue;
        cur.insert((uint32_t)pid);
      }
      closedir(d);
      if (!first) {
        for (uint32_t pid : cur) {
          if (!seen.count(pid)) {
            Event ev{};
            ev.ts_ns = now_ns();
            ev.kind = EV_EXEC;
            ev.pid = pid;
            fill_from_proc(ev, pid);
            emit(ev);
          }
        }
        for (uint32_t pid : seen) {
          if (!cur.count(pid)) {
            Event ev{};
            ev.ts_ns = now_ns();
            ev.kind = EV_EXIT;
            ev.pid = pid;
            emit(ev);
          }
        }
      }
      seen.swap(cur);
      first = false;
      std::this_thread::sleep_for(std::chrono::milliseconds(20));
    }
  }
};

// ---------------------------------------------------------------------------
// ProcTcpSource — /proc/net/tcp{,6} diff scanner.
// ---------------------------------------------------------------------------

class ProcTcpSource : public Source {
 public:
  explicit ProcTcpSource(size_t ring_pow2) : Source(ring_pow2) {}
  ~ProcTcpSource() override { stop(); }

 protected:
  void run() override {
    std::map<uint64_t, Event> known;  // inode -> last event
    bool first = true;
    uint64_t last_opens = 0;
    while (running_.load(std::memory_order_relaxed)) {
      std::map<uint64_t, Event> cur;
      scan("/proc/net/tcp", cur);
      scan("/proc/net/tcp6", cur);
      size_t new_seen = 0;
      if (!first) {
        for (auto& [inode, ev] : cur) {
          auto it = known.find(inode);
          if (it == known.end()) {
            Event e = ev;
            // state 0x0A = LISTEN → accept-side socket; else connect
            e.kind = (e.aux2 >> 32) == 0x0A ? EV_TCP_ACCEPT : EV_TCP_CONNECT;
            emit(e);
            new_seen++;
          }
        }
        for (auto& [inode, ev] : known) {
          if (!cur.count(inode)) {
            Event e = ev;
            e.kind = EV_TCP_CLOSE;
            e.ts_ns = now_ns();
            emit(e);
          }
        }
      }
      // Churn accounting: connections opened and closed entirely between
      // two 50ms scans are invisible to the diff (the reference's kprobe
      // path sees every connect — tcpconnect.bpf.c). The kernel's SNMP
      // ActiveOpens+PassiveOpens counters give ground truth; any excess
      // over sockets we actually observed is surfaced as a drop so the
      // loss stays auditable end-to-end.
      uint64_t opens = snmp_tcp_opens();
      if (last_opens != 0 && opens > last_opens) {
        uint64_t delta = opens - last_opens;
        if (delta > new_seen) ring_.count_external_drops(delta - new_seen);
      }
      if (opens != 0) last_opens = opens;
      known.swap(cur);
      first = false;
      std::this_thread::sleep_for(std::chrono::milliseconds(50));
    }
  }

 private:
  // Sum of TCP ActiveOpens + PassiveOpens from /proc/net/snmp.
  static uint64_t snmp_tcp_opens() {
    FILE* f = fopen("/proc/net/snmp", "r");
    if (!f) return 0;
    char line[1024];
    uint64_t active = 0, passive = 0;
    bool header_seen = false;
    while (fgets(line, sizeof(line), f)) {
      if (strncmp(line, "Tcp:", 4) != 0) continue;
      if (!header_seen) {
        header_seen = true;  // first Tcp: line is the field-name header
        continue;
      }
      // Tcp: RtoAlgorithm RtoMin RtoMax MaxConn ActiveOpens PassiveOpens ...
      sscanf(line, "Tcp: %*s %*s %*s %*s %llu %llu",
             (unsigned long long*)&active, (unsigned long long*)&passive);
      break;
    }
    fclose(f);
    return active + passive;
  }
  void scan(const char* path, std::map<uint64_t, Event>& out) {
    FILE* f = fopen(path, "r");
    if (!f) return;
    char line[512];
    if (!fgets(line, sizeof(line), f)) {  // header
      fclose(f);
      return;
    }
    while (fgets(line, sizeof(line), f)) {
      unsigned long sl;
      char local[128], remote[128];
      unsigned state;
      unsigned long long inode = 0;
      // sl local rem st tx:rx tr:tm retrnsmt uid timeout inode
      int n = sscanf(line, " %lu: %127s %127s %x %*s %*s %*s %*u %*u %llu", &sl,
                     local, remote, &state, &inode);
      if (n < 5 || inode == 0) continue;
      Event ev{};
      ev.ts_ns = now_ns();
      unsigned long long laddr = 0, raddr = 0;
      unsigned lport = 0, rport = 0;
      char* colon = strrchr(local, ':');
      if (colon) {
        lport = (unsigned)strtoul(colon + 1, nullptr, 16);
        laddr = strtoull(local, nullptr, 16);
      }
      colon = strrchr(remote, ':');
      if (colon) {
        rport = (unsigned)strtoul(colon + 1, nullptr, 16);
        raddr = strtoull(remote, nullptr, 16);
      }
      ev.aux1 = (laddr << 32) ^ raddr;
      ev.aux2 = ((uint64_t)state << 32) | (lport << 16) | rport;
      char key[64];
      int kn = snprintf(key, sizeof(key), "%llx:%x->%llx:%x", laddr, lport,
                        raddr, rport);
      ev.key_hash = fnv1a64(key, (size_t)kn);
      vocab_.put(ev.key_hash, key, (size_t)kn);
      ev.kind = EV_TCP_CONNECT;
      out[inode] = ev;
    }
    fclose(f);
  }
};

#endif  // __linux__

}  // namespace ig
