// TSAN/stress harness for the SPSC ring (SURVEY §5: the reference relies
// on the BPF verifier + Go runtime for safety; this build runs its C++
// concurrency under ThreadSanitizer instead — `make tsan`).
//
// One producer pushes 2M events flat out against a small ring while a
// consumer drains; asserts conservation: produced == consumed + dropped.

#include <cassert>
#include <cstdio>
#include <thread>

#include "ringbuf.h"

int main() {
  ig::RingBuffer ring(1 << 10);
  const uint64_t N = 2'000'000;
  std::thread producer([&] {
    ig::Event ev{};
    for (uint64_t i = 0; i < N; i++) {
      ev.ts_ns = i;
      ring.push(ev);
    }
  });
  uint64_t consumed = 0;
  ig::Event out[256];
  std::thread consumer([&] {
    while (consumed + ring.drops() < N) {
      size_t got = ring.pop(out, 256);
      consumed += got;
      if (!got) std::this_thread::yield();
    }
  });
  producer.join();
  consumer.join();
  // drain the tail
  for (size_t got; (got = ring.pop(out, 256)) > 0;) consumed += got;
  uint64_t dropped = ring.drops();
  printf("produced=%llu consumed=%llu dropped=%llu\n",
         (unsigned long long)ring.produced() + dropped,
         (unsigned long long)consumed, (unsigned long long)dropped);
  assert(ring.produced() == consumed);
  assert(consumed + dropped == N);
  printf("ring stress OK\n");
  return 0;
}
