// TSAN stress for the capture sources: concurrent create/start/pop/
// set_filter/stats/stop/destroy across threads — the cross-thread
// surfaces the Python bridge exercises (run loop pops, tracer-collection
// filter updates, top/self stats enumeration, teardown). Run via
// `make -C inspektor_gadget_tpu/native tsan-sources` (root: the real
// kernel windows open live sockets/marks). Complements ring_stress.cc,
// which hammers the SPSC ring contract itself.
#include "api.cc"
#include <atomic>
#include <chrono>
#include <thread>
#include <vector>
#include <cstdio>

int main() {
  // ig_source_start cannot report window failures (they happen inside
  // the capture thread), so gate on the one precondition every kernel
  // window shares — a green run without root would exercise nothing
  if (geteuid() != 0) {
    fprintf(stderr, "needs root: the kernel windows won't open and the "
                    "emit/pop races would never run\n");
    return 1;
  }
  const uint32_t kinds[] = {IG_SRC_TCP_BYTES,  IG_SRC_AUDIT,
                            IG_SRC_CAP_TRACE,  IG_SRC_FS_TRACE,
                            IG_SRC_SOCK_STATE, IG_SRC_SIG_TRACE,
                            IG_SRC_BLK_TRACE,  IG_SRC_FANOTIFY_OPEN};
  for (int round = 0; round < 3; round++) {
    std::vector<uint64_t> hs;
    int started = 0;
    for (uint32_t k : kinds) {
      uint64_t h = ig_source_create_cfg(k, "interval_ms=100\x1fmin_lat_us=1000", 14);
      if (!h) {
        fprintf(stderr, "kind %u: create failed\n", k);
        continue;
      }
      ig_source_start(h);
      started++;
      hs.push_back(h);
    }
    if (started < (int)(sizeof(kinds) / sizeof(kinds[0]))) {
      fprintf(stderr, "only %d/%zu sources created — races not fully "
                      "exercised\n",
              started, sizeof(kinds) / sizeof(kinds[0]));
      return 1;
    }
    std::atomic<bool> stop{false};
    // poller thread per source
    std::vector<std::thread> ts;
    for (uint64_t h : hs)
      ts.emplace_back([h, &stop] {
        uint64_t ts_[256], kh[256], a1[256], a2[256], mn[256];
        uint32_t pid[256], ppid[256], uid[256], kind[256];
        char comm[2048];
        while (!stop.load())
          ig_source_pop_batch(h, 256, ts_, kh, a1, a2, mn, pid, ppid, uid,
                              kind, comm);
      });
    // filter-churn thread (tracer-collection updates)
    ts.emplace_back([&hs, &stop] {
      uint64_t ids[4] = {1, 2, 3, 4};
      while (!stop.load())
        for (uint64_t h : hs) {
          ig_source_set_filter(h, ids, 4);
          ig_source_set_filter(h, nullptr, 0);
        }
    });
    // stats thread (top/self enumeration)
    ts.emplace_back([&stop] {
      uint64_t ids[64], prod[64], cons[64], drops[64], filt[64], rl[64],
          rc[64], cpu[64];
      uint32_t kk[64];
      while (!stop.load())
        ig_sources_stats(ids, kk, prod, cons, drops, filt, rl, rc, cpu, 64);
    });
    std::this_thread::sleep_for(std::chrono::milliseconds(1200));
    stop.store(true);
    for (auto& t : ts) t.join();
    for (uint64_t h : hs) { ig_source_stop(h); ig_source_destroy(h); }
    printf("round %d ok (%d sources live)\n", round, started);
  }
  printf("source stress OK\n");
  return 0;
}
