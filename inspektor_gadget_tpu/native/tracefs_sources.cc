// Tracefs-backed capture sources — block per-IO, host-wide fsslower, and
// the cap_capable tracepoint.
//
// Each source owns a PRIVATE tracing instance (instances/<name>: isolated
// ring buffers + event enables, never disturbs global tracing), reads its
// trace_pipe, and surfaces per-cpu ring overruns as drops. The shared
// lifecycle lives in TracefsInstanceSource; concrete sources supply the
// events to enable (with optional in-kernel filters) and a line parser.
//
// This file is included AFTER ptrace_source.cc (see api.cc) on purpose:
// FsTraceSource reuses its kSyscallNames (arch-native syscall numbers)
// and kSpecs fs_op classification so the per-target ptrace flavour and
// the host-wide tracepoint flavour can never disagree about which
// syscalls are fs ops.

#ifdef __linux__
#include <dirent.h>
#include <fcntl.h>
#include <poll.h>
#include <sys/stat.h>
#include <unistd.h>

#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

#include "ringbuf.h"

namespace ig {

// ---------------------------------------------------------------------------
// TracefsInstanceSource — shared private-instance lifecycle.
// ---------------------------------------------------------------------------

class TracefsInstanceSource : public Source {
 public:
  TracefsInstanceSource(size_t ring_pow2, const char* name_prefix,
                        const std::string& root = "")
      : Source(ring_pow2), root_(root) {
    if (root_.empty()) root_ = tracefs_root();
    static std::atomic<int> seq{0};
    char inst[64];
    snprintf(inst, sizeof(inst), "%s_%d_%d", name_prefix, (int)getpid(),
             seq.fetch_add(1));
    instance_ = inst;
  }
  ~TracefsInstanceSource() override { teardown_instance(); }

  // A usable tracefs needs WRITE access (instance creation + event
  // enables), not just readable event dirs — /sys is commonly mounted
  // read-only in containers and a read-only root must not be reported
  // as a working window.
  static bool root_usable(const std::string& root) {
    if (root.empty()) return false;
    return access((root + "/instances").c_str(), W_OK) == 0;
  }

 protected:
  // subclass contract -------------------------------------------------------
  // relative "events/..." paths to enable, with optional in-kernel filter
  struct EventEnable {
    std::string event;   // e.g. "events/block/block_rq_issue"
    std::string filter;  // "" = none
  };
  virtual std::vector<EventEnable> events() = 0;
  virtual void parse_line(const char* line, size_t len) = 0;
  // bound for per-source in-flight tables; called when the pipe drains
  virtual void prune() {}

  void run() override {
    if (root_.empty()) return;
    std::string inst = root_ + "/instances/" + instance_;
    mkdir(inst.c_str(), 0700);
    if (access(inst.c_str(), R_OK) != 0) return;
    made_instance_ = true;
    for (const EventEnable& e : events()) {
      if (!e.filter.empty() &&
          !write_file(inst + "/" + e.event + "/filter", e.filter.c_str()))
        return;
      if (!write_file(inst + "/" + e.event + "/enable", "1")) return;
      // recorded for teardown: the destructor must not dispatch to the
      // (already-destroyed) derived class's virtual events()
      enabled_events_.push_back(e.event);
    }
    int fd = open((inst + "/trace_pipe").c_str(),
                  O_RDONLY | O_NONBLOCK | O_CLOEXEC);
    if (fd < 0) return;
    struct pollfd pfd{fd, POLLIN, 0};
    std::string carry;
    uint64_t last_overrun_check = 0;
    while (running_.load(std::memory_order_relaxed)) {
      if (poll(&pfd, 1, 100) <= 0) continue;
      char buf[16384];
      ssize_t n = read(fd, buf, sizeof(buf));
      if (n <= 0) continue;
      carry.append(buf, (size_t)n);
      size_t pos = 0, nl;
      while ((nl = carry.find('\n', pos)) != std::string::npos) {
        parse_line(carry.data() + pos, nl - pos);
        pos = nl + 1;
      }
      carry.erase(0, pos);
      prune();
      uint64_t now = now_ns();
      if (now - last_overrun_check > 1000000000ull) {
        last_overrun_check = now;
        account_overruns(inst);
      }
    }
    close(fd);
  }

  // shared helpers ----------------------------------------------------------

  // leading "comm-pid" field of a trace_pipe line; runs up to the " [cpu]"
  // column, NOT the first space — comms may contain spaces. Returns pid
  // (0 on parse failure) and fills comm.
  static uint32_t parse_task(const std::string& s, std::string& comm) {
    size_t ns_ = s.find_first_not_of(' ');
    size_t br = s.find(" [", ns_);
    if (ns_ == std::string::npos || br == std::string::npos || br <= ns_)
      return 0;
    std::string task = s.substr(ns_, br - ns_);
    while (!task.empty() && task.back() == ' ') task.pop_back();
    size_t dash = task.rfind('-');
    if (dash == std::string::npos) return 0;
    comm = task.substr(0, dash);
    return (uint32_t)atoi(task.c_str() + dash + 1);
  }

  // "12345.678901:" timestamp token directly before the event name
  static double parse_ts(const std::string& s, size_t event_pos) {
    if (event_pos < 2) return 0.0;
    size_t ts_start = s.rfind(' ', event_pos - 2);
    if (ts_start == std::string::npos) return 0.0;
    return atof(s.c_str() + ts_start + 1);
  }

  void fill_task_identity(Event& ev, const std::string& comm) {
    if (!comm.empty()) {
      size_t c = comm.size() < sizeof(ev.comm) - 1 ? comm.size()
                                                   : sizeof(ev.comm) - 1;
      memcpy(ev.comm, comm.data(), c);
      if (ev.key_hash == 0) {
        ev.key_hash = fnv1a64(comm.data(), comm.size());
        vocab_.put(ev.key_hash, comm.data(), comm.size());
      }
    }
    if (ev.pid) {
      char path[64], link[64];
      snprintf(path, sizeof(path), "/proc/%u/ns/mnt", ev.pid);
      ssize_t ln = readlink(path, link, sizeof(link) - 1);
      if (ln > 0) {
        link[ln] = 0;
        const char* lb = strchr(link, '[');
        if (lb) ev.mntns = strtoull(lb + 1, nullptr, 10);
      }
    }
  }

  static bool write_file(const std::string& path, const char* val) {
    int fd = open(path.c_str(), O_WRONLY | O_CLOEXEC);
    if (fd < 0) return false;
    ssize_t n = write(fd, val, strlen(val));
    close(fd);
    return n > 0;
  }

  std::string root_;

 private:
  // per_cpu/*/stats "overrun: N" — events the ftrace ring discarded before
  // we read them; folded into the source's drop counter so loss stays
  // auditable end-to-end (ring_stress contract)
  void account_overruns(const std::string& inst) {
    uint64_t total = 0;
    DIR* d = opendir((inst + "/per_cpu").c_str());
    if (!d) return;
    struct dirent* de;
    while ((de = readdir(d))) {
      if (strncmp(de->d_name, "cpu", 3) != 0) continue;
      std::string sp = inst + "/per_cpu/" + de->d_name + "/stats";
      FILE* f = fopen(sp.c_str(), "r");
      if (!f) continue;
      char line[128];
      while (fgets(line, sizeof(line), f)) {
        unsigned long long v;
        if (sscanf(line, "overrun: %llu", &v) == 1) total += v;
      }
      fclose(f);
    }
    closedir(d);
    if (total > overrun_seen_) {
      ring_.count_external_drops(total - overrun_seen_);
      overrun_seen_ = total;
    }
  }

  void teardown_instance() {
    if (!made_instance_ || root_.empty()) return;
    std::string inst = root_ + "/instances/" + instance_;
    for (const std::string& e : enabled_events_)
      write_file(inst + "/" + e + "/enable", "0");
    rmdir(inst.c_str());  // removing the instance frees its buffers
  }

  std::string instance_;
  bool made_instance_ = false;
  uint64_t overrun_seen_ = 0;
  std::vector<std::string> enabled_events_;
};

// ---------------------------------------------------------------------------
// BlkTraceSource — profile/block-io via tracefs block events, PER-IO.
//
// The reference's biolatency.bpf.c (1-156) kprobes rq issue→complete and
// histograms each request's latency in-kernel. trace_pipe lines carry
// (dev, sector, rwbs, bytes) on issue and completion, so each IO's
// latency is the timestamp delta of its (dev,sector) pair. Events:
//   key_hash  dev "maj,min" (vocab)   aux1  latency_us
//   aux2      bytes<<8 | is_write     pid/comm  issuing task
// ---------------------------------------------------------------------------

class BlkTraceSource : public TracefsInstanceSource {
 public:
  BlkTraceSource(size_t ring_pow2, const std::string& cfg)
      : TracefsInstanceSource(ring_pow2, "igtpu_blk",
                              cfg_get(cfg, "tracefs", "")) {}
  ~BlkTraceSource() override { stop(); }

  static bool supported() {
    std::string root = tracefs_root();
    return root_usable(root) &&
           access((root + "/events/block").c_str(), R_OK) == 0;
  }

 protected:
  std::vector<EventEnable> events() override {
    return {{"events/block/block_rq_issue", ""},
            {"events/block/block_rq_complete", ""}};
  }

  void prune() override {
    // IOs whose completion we never see (requeues, remaps) must not leak
    if (inflight_.size() > 65536) inflight_.clear();
  }

  void parse_line(const char* line, size_t len) override {
    std::string s(line, len);
    // "  comm-pid  [cpu] flags ts.usec: block_rq_issue: maj,min RWBS bytes
    //  () sector + len [comm]"   (complete: no bytes field)
    size_t m_issue = s.find("block_rq_issue: ");
    size_t m_done = s.find("block_rq_complete: ");
    if (m_issue == std::string::npos && m_done == std::string::npos) return;
    double ts = parse_ts(
        s, m_issue != std::string::npos ? m_issue : m_done);
    if (m_issue != std::string::npos) {
      char dev[16] = "", rwbs[8] = "";
      unsigned long long bytes = 0, sector = 0;
      if (sscanf(s.c_str() + m_issue + 16, "%15s %7s %llu () %llu",
                 dev, rwbs, &bytes, &sector) != 4)
        return;
      Pending p{};
      p.ts = ts;
      p.bytes = bytes;
      p.is_write = strchr(rwbs, 'W') != nullptr;
      std::string comm;
      p.pid = parse_task(s, comm);
      size_t cn = comm.size() < sizeof(p.comm) - 1 ? comm.size()
                                                   : sizeof(p.comm) - 1;
      memcpy(p.comm, comm.data(), cn);
      p.comm[cn] = 0;
      inflight_[key(dev, sector)] = p;
    } else {
      char dev[16] = "";
      unsigned long long sector = 0;
      if (sscanf(s.c_str() + m_done + 19, "%15s %*s () %llu",
                 dev, &sector) != 2)
        return;
      auto it = inflight_.find(key(dev, sector));
      if (it == inflight_.end()) return;
      const Pending& p = it->second;
      double lat_us = (ts - p.ts) * 1e6;
      if (lat_us >= 0) {
        Event ev{};
        ev.ts_ns = now_ns();
        ev.kind = EV_BLOCK_IO;
        ev.aux1 = (uint64_t)lat_us;
        ev.aux2 = (p.bytes << 8) | (p.is_write ? 1 : 0);
        ev.pid = p.pid;
        size_t dn = strlen(dev);
        ev.key_hash = fnv1a64(dev, dn);
        vocab_.put(ev.key_hash, dev, dn);
        size_t cn = strlen(p.comm);
        memcpy(ev.comm, p.comm,
               cn < sizeof(ev.comm) - 1 ? cn : sizeof(ev.comm) - 1);
        emit(ev);
      }
      inflight_.erase(it);
    }
  }

 private:
  struct Pending {
    double ts;
    uint64_t bytes;
    uint32_t pid;
    char comm[16];
    bool is_write;
  };

  static std::string key(const char* dev, unsigned long long sector) {
    char k[48];
    snprintf(k, sizeof(k), "%s:%llu", dev, sector);
    return k;
  }

  std::unordered_map<std::string, Pending> inflight_;
};

// ---------------------------------------------------------------------------
// FsTraceSource — trace/fsslower HOST-WIDE via filtered raw_syscalls.
//
// The reference's fsslower.bpf.c (1-239) kprobes per-fs read/write/open/
// fsync entry+exit and reports ops slower than a threshold, system-wide.
// Here: events/raw_syscalls/{sys_enter,sys_exit} with an IN-KERNEL id
// filter (only fs syscalls reach the ring), entry/exit paired per
// (pid, nr):
//   sys_enter: NR 0 (fd_hex, buf, count, ...)     sys_exit: NR 0 = 4096
// Ops >= min_lat_us emit EV_FSSLOWER with
//   aux1 latency_us    aux2 op<<32 | bytes (ret of read/write)
//   key_hash           file path via /proc/<pid>/fd/<fd>, resolved only
//                      for the slow ops that get reported (cheap)
// The syscall set and op classes come from ptrace_source.cc's kSpecs
// (fs_op column) — one source of truth for both fsslower flavours.
// ---------------------------------------------------------------------------

class FsTraceSource : public TracefsInstanceSource {
 public:
  FsTraceSource(size_t ring_pow2, const std::string& cfg)
      : TracefsInstanceSource(ring_pow2, "igtpu_fs") {
    min_lat_us_ = strtoull(cfg_get(cfg, "min_lat_us", "10000").c_str(),
                           nullptr, 10);
    // arch-native nr → fs-op class, from the ptrace window's tables
    for (const SyscallName* s = kSyscallNames; s->name; s++) {
      for (const SysSpec* sp = kSpecs; sp->name; sp++) {
        if (strcmp(sp->name, s->name) == 0) {
          if (sp->fs_op > 0) op_by_nr_[s->nr] = sp->fs_op;
          break;
        }
      }
    }
  }
  ~FsTraceSource() override { stop(); }

  static bool supported() {
    std::string root = tracefs_root();
    return root_usable(root) &&
           access((root + "/events/raw_syscalls/sys_enter").c_str(),
                  R_OK) == 0;
  }

 protected:
  std::vector<EventEnable> events() override {
    std::string filter;
    for (auto& [nr, _op] : op_by_nr_) {
      if (!filter.empty()) filter += "||";
      filter += "id==" + std::to_string(nr);
    }
    return {{"events/raw_syscalls/sys_enter", filter},
            {"events/raw_syscalls/sys_exit", filter}};
  }

  void prune() override {
    if (inflight_.size() > 65536) inflight_.clear();
  }

  void parse_line(const char* line, size_t len) override {
    std::string s(line, len);
    size_t m_in = s.find("sys_enter: NR ");
    size_t m_out = s.find("sys_exit: NR ");
    if (m_in == std::string::npos && m_out == std::string::npos) return;
    std::string comm;
    uint32_t pid = parse_task(s, comm);
    if (!pid) return;
    double ts = parse_ts(s, m_in != std::string::npos ? m_in : m_out);
    if (m_in != std::string::npos) {
      long nr = 0;
      unsigned long long a0 = 0;
      if (sscanf(s.c_str() + m_in + 14, "%ld (%llx", &nr, &a0) < 1) return;
      if (!op_by_nr_.count((int)nr)) return;
      inflight_[((uint64_t)pid << 16) | (uint64_t)(nr & 0xFFFF)] =
          Pending{ts, a0};
    } else {
      long nr = 0;
      long long ret = 0;
      if (sscanf(s.c_str() + m_out + 13, "%ld = %lld", &nr, &ret) != 2)
        return;
      auto op_it = op_by_nr_.find((int)nr);
      if (op_it == op_by_nr_.end()) return;
      auto key = ((uint64_t)pid << 16) | (uint64_t)(nr & 0xFFFF);
      auto it = inflight_.find(key);
      if (it == inflight_.end()) return;
      double lat_us = (ts - it->second.ts) * 1e6;
      uint64_t fdnum = it->second.fd;
      inflight_.erase(it);
      if (lat_us < (double)min_lat_us_) return;
      Event ev{};
      ev.ts_ns = now_ns();
      ev.kind = EV_FSSLOWER;
      ev.pid = pid;
      ev.aux1 = (uint64_t)lat_us;
      uint64_t bytes =
          (op_it->second == 1 || op_it->second == 2) && ret > 0
              ? (uint64_t)ret : 0;
      ev.aux2 = ((uint64_t)op_it->second << 32) | (bytes & 0xFFFFFFFF);
      // only reported (slow) ops pay the fd→path resolve
      if (op_it->second != 3 && fdnum < 65536) {
        char link[64], path[512];
        snprintf(link, sizeof(link), "/proc/%u/fd/%llu", pid,
                 (unsigned long long)fdnum);
        ssize_t pn = readlink(link, path, sizeof(path) - 1);
        if (pn > 0) {
          ev.key_hash = fnv1a64(path, (size_t)pn);
          vocab_.put(ev.key_hash, path, (size_t)pn);
        }
      }
      fill_task_identity(ev, comm);
      emit(ev);
    }
  }

 private:
  struct Pending {
    double ts;
    uint64_t fd;
  };

  uint64_t min_lat_us_;
  std::unordered_map<int, int> op_by_nr_;
  std::unordered_map<uint64_t, Pending> inflight_;
};

// ---------------------------------------------------------------------------
// CapTraceSource — trace/capabilities via the cap_capable TRACEPOINT.
//
// The reference kprobes cap_capable (capable.bpf.c:1-250) to see every
// capability check on the host with its verdict. Kernels >= 6.7 expose
// the same function as a real tracepoint (events/capability/cap_capable
// with cap + ret fields) — the exact mechanism, no BPF:
//   comm-pid [cpu] flags ts: cap_capable: cred .., target_ns ..,
//   capable_ns .., cap 21, ret 0
// This window sees ALLOWS and DENIES system-wide, strictly stronger than
// the audit EPERM-rule flavour (denial-only). Events:
//   kind EV_CAPABILITY   aux1 = 1 allow / 0 deny   aux2 = capability nr
// ---------------------------------------------------------------------------

class CapTraceSource : public TracefsInstanceSource {
 public:
  CapTraceSource(size_t ring_pow2, const std::string& cfg)
      : TracefsInstanceSource(ring_pow2, "igtpu_cap") {
    (void)cfg;
  }
  ~CapTraceSource() override { stop(); }

  static bool supported() {
    std::string root = tracefs_root();
    return root_usable(root) &&
           access((root + "/events/capability/cap_capable").c_str(),
                  R_OK) == 0;
  }

 protected:
  std::vector<EventEnable> events() override {
    return {{"events/capability/cap_capable", ""}};
  }

  void parse_line(const char* line, size_t len) override {
    std::string s(line, len);
    size_t m = s.find("cap_capable: ");
    if (m == std::string::npos) return;
    int cap = -1, ret = 0;
    size_t cp = s.find("cap ", m);
    if (cp == std::string::npos ||
        sscanf(s.c_str() + cp, "cap %d, ret %d", &cap, &ret) != 2 || cap < 0)
      return;
    Event ev{};
    ev.ts_ns = now_ns();
    ev.kind = EV_CAPABILITY;
    ev.aux1 = ret == 0 ? 1 : 0;  // allow : deny (ret is -EPERM on denial)
    ev.aux2 = (uint64_t)cap;
    std::string comm;
    ev.pid = parse_task(s, comm);
    fill_task_identity(ev, comm);
    emit(ev);
  }
};

// ---------------------------------------------------------------------------
// SockStateSource — trace/tcp via the inet_sock_set_state TRACEPOINT.
//
// The reference kprobes tcp_v4/v6_connect, inet_csk_accept and tcp_close
// (tcptracer.bpf.c:1-375). The tracepoint window sees every TCP state
// transition host-wide, event-driven — no scan window, so short-lived
// connections can't slip between polls like the /proc/net diff scanner's:
//   inet_sock_set_state: family=AF_INET protocol=IPPROTO_TCP sport=N
//   dport=M saddr=a.b.c.d daddr=e.f.g.h ... oldstate=X newstate=Y
// Transition → event mapping (with honest pid attribution — state
// changes fire in softirq/timer context where the line's task is
// whatever got interrupted):
//   CLOSE→SYN_SENT          task context IS the connecting process; the
//                           tuple lacks sport, so identity is parked and
//                           EV_TCP_CONNECT emits on SYN_SENT→ESTABLISHED
//                           with the full tuple
//   SYN_RECV→ESTABLISHED    EV_TCP_ACCEPT; softirq context — identity is
//                           the LISTENER, resolved via the port→pid map
//   ESTABLISHED→FIN_WAIT1 / CLOSE_WAIT→LAST_ACK
//                           EV_TCP_CLOSE; both fire inside the closing
//                           process's close() — task context is right
// Event encoding matches the /proc scanner so the gadget decodes both:
//   aux1 = saddr_le<<32 | daddr_le     aux2 = sport<<16 | dport
// ---------------------------------------------------------------------------

class SockStateSource : public TracefsInstanceSource {
 public:
  SockStateSource(size_t ring_pow2, const std::string& cfg)
      : TracefsInstanceSource(ring_pow2, "igtpu_ss") {
    (void)cfg;
  }
  ~SockStateSource() override { stop(); }

  static bool supported() {
    std::string root = tracefs_root();
    return root_usable(root) &&
           access((root + "/events/sock/inet_sock_set_state").c_str(),
                  R_OK) == 0;
  }

 protected:
  std::vector<EventEnable> events() override {
    enricher_.refresh();  // listener map ready before the first accept
    last_refresh_ = now_ns();
    // TCP only; BOTH address families (the /proc fallback scans tcp6 too)
    return {{"events/sock/inet_sock_set_state", "protocol==6"}};
  }

  void prune() override {
    if (pending_connect_.size() > 16384) pending_connect_.clear();
    uint64_t now = now_ns();
    if (now - last_refresh_ > 500000000ull) {
      last_refresh_ = now;
      enricher_.refresh();
    }
  }

  void parse_line(const char* line, size_t len) override {
    std::string s(line, len);
    size_t m = s.find("inet_sock_set_state: ");
    if (m == std::string::npos) return;
    unsigned sport = 0, dport = 0;
    char fam[12] = "", saddr[48] = "", daddr[48] = "";
    char olds[20] = "", news[20] = "";
    const char* p = s.c_str() + m;
    if (sscanf(p, "inet_sock_set_state: family=%11s protocol=IPPROTO_TCP"
                  " sport=%u dport=%u saddr=%47s daddr=%47s",
               fam, &sport, &dport, saddr, daddr) != 5)
      return;
    bool v6 = strcmp(fam, "AF_INET6") == 0;
    if (v6) {
      // the dotted fields are mapped-v4 for v6 sockets; use the real ones
      size_t s6 = s.find("saddrv6=", m), d6 = s.find("daddrv6=", m);
      if (s6 == std::string::npos || d6 == std::string::npos) return;
      sscanf(s.c_str() + s6, "saddrv6=%47s", saddr);
      sscanf(s.c_str() + d6, "daddrv6=%47s", daddr);
    }
    size_t os_ = s.find("oldstate=", m);
    size_t ns2 = s.find("newstate=", m);
    if (os_ == std::string::npos || ns2 == std::string::npos) return;
    sscanf(s.c_str() + os_, "oldstate=%19s", olds);
    sscanf(s.c_str() + ns2, "newstate=%19s", news);
    std::string comm;
    uint32_t task_pid = parse_task(s, comm);
    uint32_t sa = v6 ? 0 : ip4_le(saddr), da = v6 ? 0 : ip4_le(daddr);
    uint64_t v6key = v6 ? put_v6(saddr, daddr) : 0;

    if (!strcmp(olds, "TCP_CLOSE") && !strcmp(news, "TCP_SYN_SENT")) {
      // Park the connecting task's identity; tuple completes on
      // ESTABLISHED. sport is 0 here, so concurrent connects to the same
      // target share a key — a collision from a DIFFERENT task makes the
      // slot ambiguous (pid 0 beats blaming the wrong process), and the
      // ambiguity must outlive the FIRST establishment (a refcount, not a
      // flag): with it erased early, a third connect re-parking would be
      // blamed for the second's connection.
      uint64_t key = conn_key(saddr, daddr, dport);
      auto it = pending_connect_.find(key);
      if (it == pending_connect_.end()) {
        pending_connect_[key] = {task_pid, comm, 1};
      } else {
        it->second.count++;
        if (it->second.pid != task_pid) it->second = {0, "", it->second.count};
      }
      return;
    }
    if (!strcmp(olds, "TCP_SYN_SENT")) {
      // honest attribution only: a miss means the parked identity is gone
      // (table pruned) — the line's task here is softirq-interrupted and
      // must NOT be blamed
      auto it = pending_connect_.find(conn_key(saddr, daddr, dport));
      uint32_t pid = 0;
      std::string who;
      if (it != pending_connect_.end()) {
        pid = it->second.pid;
        who = it->second.comm;
        if (--it->second.count <= 0) pending_connect_.erase(it);
      }
      if (strcmp(news, "TCP_ESTABLISHED") != 0) return;  // refused/reset
      push(EV_TCP_CONNECT, pid, who, sa, da, sport, dport, v6, v6key);
      return;
    }
    if (!strcmp(olds, "TCP_SYN_RECV") && !strcmp(news, "TCP_ESTABLISHED")) {
      uint32_t pid = 0;
      char owner[32] = "";
      bool hit = lookup_port_owner(sport, &pid, owner, sizeof(owner));
      push(EV_TCP_ACCEPT, hit ? pid : 0, hit ? owner : "", sa, da, sport,
           dport, v6, v6key);
      return;
    }
    // Closes. ESTABLISHED→FIN_WAIT1 and CLOSE_WAIT→LAST_ACK fire inside
    // the closing process's close() — task context is right. A direct
    // →TCP_CLOSE from a live state is an abort (RST received, SO_LINGER-0
    // close, tcp_abort), possibly in softirq — attribute via the port→pid
    // map instead of blaming the interrupted task.
    bool task_close =
        (!strcmp(olds, "TCP_ESTABLISHED") && !strcmp(news, "TCP_FIN_WAIT1"))
        || (!strcmp(olds, "TCP_CLOSE_WAIT") && !strcmp(news, "TCP_LAST_ACK"));
    bool abort_close =
        !strcmp(news, "TCP_CLOSE")
        && (!strcmp(olds, "TCP_ESTABLISHED")
            || !strcmp(olds, "TCP_CLOSE_WAIT"));
    if (task_close) {
      push(EV_TCP_CLOSE, task_pid, comm, sa, da, sport, dport, v6, v6key);
    } else if (abort_close) {
      uint32_t pid = 0;
      char owner[32] = "";
      bool hit = lookup_port_owner(sport, &pid, owner, sizeof(owner));
      push(EV_TCP_CLOSE, hit ? pid : 0, hit ? owner : "", sa, da, sport,
           dport, v6, v6key);
    }
  }

 private:
  struct PendingConnect {
    uint32_t pid;
    std::string comm;
    int count;  // concurrent connects sharing this key (sport is 0)
  };

  // keyed on the ADDRESS STRINGS (works for both families; sport is 0 at
  // SYN_SENT so it can't participate)
  static uint64_t conn_key(const char* saddr, const char* daddr,
                           unsigned dport) {
    uint64_t h = fnv1a64(saddr, strlen(saddr));
    h ^= fnv1a64(daddr, strlen(daddr)) * 0x100000001B3ull;
    return h ^ dport;
  }

  // dotted quad → the little-endian u32 the /proc scanner emits (the
  // gadget's decoder unpacks with "<I")
  static uint32_t ip4_le(const char* dotted) {
    unsigned a = 0, b = 0, c = 0, d = 0;
    if (sscanf(dotted, "%u.%u.%u.%u", &a, &b, &c, &d) != 4) return 0;
    return a | (b << 8) | (c << 16) | (d << 24);
  }

  // v6 address pair → vocab payload "saddr6\x1fdaddr6" keyed by hash
  uint64_t put_v6(const char* saddr, const char* daddr) {
    std::string payload = std::string(saddr) + '\x1f' + daddr;
    uint64_t h = fnv1a64(payload.data(), payload.size());
    vocab_.put(h, payload.data(), payload.size());
    return h;
  }

  // port → owning process, with a rate-limited refresh on miss (a miss
  // usually means the socket is younger than the last /proc scan)
  bool lookup_port_owner(unsigned port, uint32_t* pid, char* owner,
                         size_t cap) {
    bool hit = enricher_.lookup((uint16_t)port, pid, owner, cap);
    if (!hit) {
      uint64_t now = now_ns();
      if (now - last_refresh_ > 200000000ull) {
        last_refresh_ = now;
        enricher_.refresh();
        hit = enricher_.lookup((uint16_t)port, pid, owner, cap);
      }
    }
    return hit;
  }

  void push(uint32_t kind, uint32_t pid, const std::string& comm,
            uint32_t sa, uint32_t da, unsigned sport, unsigned dport,
            bool v6, uint64_t v6key) {
    Event ev{};
    ev.ts_ns = now_ns();
    ev.kind = kind;
    ev.pid = pid;
    ev.aux1 = v6 ? v6key : (((uint64_t)sa << 32) | da);
    ev.aux2 = ((uint64_t)(sport & 0xFFFF) << 16) | (dport & 0xFFFF);
    // ipversion flag for the decoder — bit 48, clear of the /proc
    // fallback's state field (sources.cc packs state<<32, values <= 12)
    if (v6) ev.aux2 |= 1ull << 48;
    fill_task_identity(ev, comm);
    emit(ev);
  }

  SocketEnricher enricher_;
  uint64_t last_refresh_ = 0;
  std::unordered_map<uint64_t, PendingConnect> pending_connect_;
};

// ---------------------------------------------------------------------------
// SignalTraceSource — trace/signal via the signal_generate TRACEPOINT.
//
// The reference's sigsnoop.bpf.c (1-175) hooks the signal_generate
// tracepoint; this is the same hook, host-wide, covering every signal —
// not just the fatal ones the netlink-exit window derives:
//   sig=9 errno=0 code=0 comm=target pid=123 grp=1 res=0
// The line's task is the SENDER; the record's comm/pid are the TARGET.
// Encoding matches the gadget: aux1=2 (sent), aux2=sig, pid=sender,
// ppid=target pid.
// ---------------------------------------------------------------------------

class SignalTraceSource : public TracefsInstanceSource {
 public:
  SignalTraceSource(size_t ring_pow2, const std::string& cfg)
      : TracefsInstanceSource(ring_pow2, "igtpu_sig") {
    (void)cfg;
  }
  ~SignalTraceSource() override { stop(); }

  static bool supported() {
    std::string root = tracefs_root();
    return root_usable(root) &&
           access((root + "/events/signal/signal_generate").c_str(),
                  R_OK) == 0;
  }

 protected:
  std::vector<EventEnable> events() override {
    return {{"events/signal/signal_generate", ""}};
  }

  void parse_line(const char* line, size_t len) override {
    std::string s(line, len);
    size_t m = s.find("signal_generate: ");
    if (m == std::string::npos) return;
    int sig = 0, res = 0;
    unsigned tpid = 0;
    if (sscanf(s.c_str() + m, "signal_generate: sig=%d", &sig) != 1)
      return;
    size_t pp = s.find(" pid=", m);
    if (pp != std::string::npos) sscanf(s.c_str() + pp, " pid=%u", &tpid);
    size_t rp = s.find(" res=", m);
    if (rp != std::string::npos) sscanf(s.c_str() + rp, " res=%d", &res);
    if (sig <= 0) return;
    std::string comm;
    uint32_t sender = parse_task(s, comm);
    Event ev{};
    ev.ts_ns = now_ns();
    ev.kind = EV_SIGNAL;
    ev.pid = sender;
    ev.ppid = tpid;  // target (the gadget's TPID column)
    ev.aux1 = 2;     // sent
    ev.aux2 = (uint64_t)(sig & 0x7F);
    fill_task_identity(ev, comm);
    emit(ev);
  }
};

}  // namespace ig
#endif  // __linux__
