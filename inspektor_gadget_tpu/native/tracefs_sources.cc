// Tracefs-backed capture sources — block per-IO, host-wide fsslower, and
// the cap_capable tracepoint.
//
// Each source owns a PRIVATE tracing instance (instances/<name>: isolated
// ring buffers + event enables, never disturbs global tracing), reads its
// trace_pipe, and surfaces per-cpu ring overruns as drops. The shared
// lifecycle lives in TracefsInstanceSource; concrete sources supply the
// events to enable (with optional in-kernel filters) and a line parser.
//
// This file is included AFTER ptrace_source.cc (see api.cc) on purpose:
// FsTraceSource reuses its kSyscallNames (arch-native syscall numbers)
// and kSpecs fs_op classification so the per-target ptrace flavour and
// the host-wide tracepoint flavour can never disagree about which
// syscalls are fs ops.

#ifdef __linux__
#include <dirent.h>
#include <fcntl.h>
#include <poll.h>
#include <sys/stat.h>
#include <unistd.h>

#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

#include "ringbuf.h"

namespace ig {

// ---------------------------------------------------------------------------
// TracefsInstanceSource — shared private-instance lifecycle.
// ---------------------------------------------------------------------------

class TracefsInstanceSource : public Source {
 public:
  TracefsInstanceSource(size_t ring_pow2, const char* name_prefix,
                        const std::string& root = "")
      : Source(ring_pow2), root_(root) {
    if (root_.empty()) root_ = tracefs_root();
    static std::atomic<int> seq{0};
    char inst[64];
    snprintf(inst, sizeof(inst), "%s_%d_%d", name_prefix, (int)getpid(),
             seq.fetch_add(1));
    instance_ = inst;
  }
  ~TracefsInstanceSource() override { teardown_instance(); }

  // A usable tracefs needs WRITE access (instance creation + event
  // enables), not just readable event dirs — /sys is commonly mounted
  // read-only in containers and a read-only root must not be reported
  // as a working window.
  static bool root_usable(const std::string& root) {
    if (root.empty()) return false;
    return access((root + "/instances").c_str(), W_OK) == 0;
  }

 protected:
  // subclass contract -------------------------------------------------------
  // relative "events/..." paths to enable, with optional in-kernel filter
  struct EventEnable {
    std::string event;   // e.g. "events/block/block_rq_issue"
    std::string filter;  // "" = none
  };
  virtual std::vector<EventEnable> events() = 0;
  virtual void parse_line(const char* line, size_t len) = 0;
  // bound for per-source in-flight tables; called when the pipe drains
  virtual void prune() {}

  void run() override {
    if (root_.empty()) return;
    std::string inst = root_ + "/instances/" + instance_;
    mkdir(inst.c_str(), 0700);
    if (access(inst.c_str(), R_OK) != 0) return;
    made_instance_ = true;
    for (const EventEnable& e : events()) {
      if (!e.filter.empty() &&
          !write_file(inst + "/" + e.event + "/filter", e.filter.c_str()))
        return;
      if (!write_file(inst + "/" + e.event + "/enable", "1")) return;
      // recorded for teardown: the destructor must not dispatch to the
      // (already-destroyed) derived class's virtual events()
      enabled_events_.push_back(e.event);
    }
    int fd = open((inst + "/trace_pipe").c_str(),
                  O_RDONLY | O_NONBLOCK | O_CLOEXEC);
    if (fd < 0) return;
    struct pollfd pfd{fd, POLLIN, 0};
    std::string carry;
    uint64_t last_overrun_check = 0;
    while (running_.load(std::memory_order_relaxed)) {
      if (poll(&pfd, 1, 100) <= 0) continue;
      char buf[16384];
      ssize_t n = read(fd, buf, sizeof(buf));
      if (n <= 0) continue;
      carry.append(buf, (size_t)n);
      size_t pos = 0, nl;
      while ((nl = carry.find('\n', pos)) != std::string::npos) {
        parse_line(carry.data() + pos, nl - pos);
        pos = nl + 1;
      }
      carry.erase(0, pos);
      prune();
      uint64_t now = now_ns();
      if (now - last_overrun_check > 1000000000ull) {
        last_overrun_check = now;
        account_overruns(inst);
      }
    }
    close(fd);
  }

  // shared helpers ----------------------------------------------------------

  // leading "comm-pid" field of a trace_pipe line; runs up to the " [cpu]"
  // column, NOT the first space — comms may contain spaces. Returns pid
  // (0 on parse failure) and fills comm.
  static uint32_t parse_task(const std::string& s, std::string& comm) {
    size_t ns_ = s.find_first_not_of(' ');
    size_t br = s.find(" [", ns_);
    if (ns_ == std::string::npos || br == std::string::npos || br <= ns_)
      return 0;
    std::string task = s.substr(ns_, br - ns_);
    while (!task.empty() && task.back() == ' ') task.pop_back();
    size_t dash = task.rfind('-');
    if (dash == std::string::npos) return 0;
    comm = task.substr(0, dash);
    return (uint32_t)atoi(task.c_str() + dash + 1);
  }

  // "12345.678901:" timestamp token directly before the event name
  static double parse_ts(const std::string& s, size_t event_pos) {
    if (event_pos < 2) return 0.0;
    size_t ts_start = s.rfind(' ', event_pos - 2);
    if (ts_start == std::string::npos) return 0.0;
    return atof(s.c_str() + ts_start + 1);
  }

  void fill_task_identity(Event& ev, const std::string& comm) {
    if (!comm.empty()) {
      size_t c = comm.size() < sizeof(ev.comm) - 1 ? comm.size()
                                                   : sizeof(ev.comm) - 1;
      memcpy(ev.comm, comm.data(), c);
      if (ev.key_hash == 0) {
        ev.key_hash = fnv1a64(comm.data(), comm.size());
        vocab_.put(ev.key_hash, comm.data(), comm.size());
      }
    }
    if (ev.pid) {
      char path[64], link[64];
      snprintf(path, sizeof(path), "/proc/%u/ns/mnt", ev.pid);
      ssize_t ln = readlink(path, link, sizeof(link) - 1);
      if (ln > 0) {
        link[ln] = 0;
        const char* lb = strchr(link, '[');
        if (lb) ev.mntns = strtoull(lb + 1, nullptr, 10);
      }
    }
  }

  static bool write_file(const std::string& path, const char* val) {
    int fd = open(path.c_str(), O_WRONLY | O_CLOEXEC);
    if (fd < 0) return false;
    ssize_t n = write(fd, val, strlen(val));
    close(fd);
    return n > 0;
  }

  std::string root_;

 private:
  // per_cpu/*/stats "overrun: N" — events the ftrace ring discarded before
  // we read them; folded into the source's drop counter so loss stays
  // auditable end-to-end (ring_stress contract)
  void account_overruns(const std::string& inst) {
    uint64_t total = 0;
    DIR* d = opendir((inst + "/per_cpu").c_str());
    if (!d) return;
    struct dirent* de;
    while ((de = readdir(d))) {
      if (strncmp(de->d_name, "cpu", 3) != 0) continue;
      std::string sp = inst + "/per_cpu/" + de->d_name + "/stats";
      FILE* f = fopen(sp.c_str(), "r");
      if (!f) continue;
      char line[128];
      while (fgets(line, sizeof(line), f)) {
        unsigned long long v;
        if (sscanf(line, "overrun: %llu", &v) == 1) total += v;
      }
      fclose(f);
    }
    closedir(d);
    if (total > overrun_seen_) {
      ring_.count_external_drops(total - overrun_seen_);
      overrun_seen_ = total;
    }
  }

  void teardown_instance() {
    if (!made_instance_ || root_.empty()) return;
    std::string inst = root_ + "/instances/" + instance_;
    for (const std::string& e : enabled_events_)
      write_file(inst + "/" + e + "/enable", "0");
    rmdir(inst.c_str());  // removing the instance frees its buffers
  }

  std::string instance_;
  bool made_instance_ = false;
  uint64_t overrun_seen_ = 0;
  std::vector<std::string> enabled_events_;
};

// ---------------------------------------------------------------------------
// BlkTraceSource — profile/block-io via tracefs block events, PER-IO.
//
// The reference's biolatency.bpf.c (1-156) kprobes rq issue→complete and
// histograms each request's latency in-kernel. trace_pipe lines carry
// (dev, sector, rwbs, bytes) on issue and completion, so each IO's
// latency is the timestamp delta of its (dev,sector) pair. Events:
//   key_hash  dev "maj,min" (vocab)   aux1  latency_us
//   aux2      bytes<<8 | is_write     pid/comm  issuing task
// ---------------------------------------------------------------------------

class BlkTraceSource : public TracefsInstanceSource {
 public:
  BlkTraceSource(size_t ring_pow2, const std::string& cfg)
      : TracefsInstanceSource(ring_pow2, "igtpu_blk",
                              cfg_get(cfg, "tracefs", "")) {}
  ~BlkTraceSource() override { stop(); }

  static bool supported() {
    std::string root = tracefs_root();
    return root_usable(root) &&
           access((root + "/events/block").c_str(), R_OK) == 0;
  }

 protected:
  std::vector<EventEnable> events() override {
    return {{"events/block/block_rq_issue", ""},
            {"events/block/block_rq_complete", ""}};
  }

  void prune() override {
    // IOs whose completion we never see (requeues, remaps) must not leak
    if (inflight_.size() > 65536) inflight_.clear();
  }

  void parse_line(const char* line, size_t len) override {
    std::string s(line, len);
    // "  comm-pid  [cpu] flags ts.usec: block_rq_issue: maj,min RWBS bytes
    //  () sector + len [comm]"   (complete: no bytes field)
    size_t m_issue = s.find("block_rq_issue: ");
    size_t m_done = s.find("block_rq_complete: ");
    if (m_issue == std::string::npos && m_done == std::string::npos) return;
    double ts = parse_ts(
        s, m_issue != std::string::npos ? m_issue : m_done);
    if (m_issue != std::string::npos) {
      char dev[16] = "", rwbs[8] = "";
      unsigned long long bytes = 0, sector = 0;
      if (sscanf(s.c_str() + m_issue + 16, "%15s %7s %llu () %llu",
                 dev, rwbs, &bytes, &sector) != 4)
        return;
      Pending p{};
      p.ts = ts;
      p.bytes = bytes;
      p.is_write = strchr(rwbs, 'W') != nullptr;
      std::string comm;
      p.pid = parse_task(s, comm);
      size_t cn = comm.size() < sizeof(p.comm) - 1 ? comm.size()
                                                   : sizeof(p.comm) - 1;
      memcpy(p.comm, comm.data(), cn);
      p.comm[cn] = 0;
      inflight_[key(dev, sector)] = p;
    } else {
      char dev[16] = "";
      unsigned long long sector = 0;
      if (sscanf(s.c_str() + m_done + 19, "%15s %*s () %llu",
                 dev, &sector) != 2)
        return;
      auto it = inflight_.find(key(dev, sector));
      if (it == inflight_.end()) return;
      const Pending& p = it->second;
      double lat_us = (ts - p.ts) * 1e6;
      if (lat_us >= 0) {
        Event ev{};
        ev.ts_ns = now_ns();
        ev.kind = EV_BLOCK_IO;
        ev.aux1 = (uint64_t)lat_us;
        ev.aux2 = (p.bytes << 8) | (p.is_write ? 1 : 0);
        ev.pid = p.pid;
        size_t dn = strlen(dev);
        ev.key_hash = fnv1a64(dev, dn);
        vocab_.put(ev.key_hash, dev, dn);
        size_t cn = strlen(p.comm);
        memcpy(ev.comm, p.comm,
               cn < sizeof(ev.comm) - 1 ? cn : sizeof(ev.comm) - 1);
        emit(ev);
      }
      inflight_.erase(it);
    }
  }

 private:
  struct Pending {
    double ts;
    uint64_t bytes;
    uint32_t pid;
    char comm[16];
    bool is_write;
  };

  static std::string key(const char* dev, unsigned long long sector) {
    char k[48];
    snprintf(k, sizeof(k), "%s:%llu", dev, sector);
    return k;
  }

  std::unordered_map<std::string, Pending> inflight_;
};

// ---------------------------------------------------------------------------
// FsTraceSource — trace/fsslower HOST-WIDE via filtered raw_syscalls.
//
// The reference's fsslower.bpf.c (1-239) kprobes per-fs read/write/open/
// fsync entry+exit and reports ops slower than a threshold, system-wide.
// Here: events/raw_syscalls/{sys_enter,sys_exit} with an IN-KERNEL id
// filter (only fs syscalls reach the ring), entry/exit paired per
// (pid, nr):
//   sys_enter: NR 0 (fd_hex, buf, count, ...)     sys_exit: NR 0 = 4096
// Ops >= min_lat_us emit EV_FSSLOWER with
//   aux1 latency_us    aux2 op<<32 | bytes (ret of read/write)
//   key_hash           file path via /proc/<pid>/fd/<fd>, resolved only
//                      for the slow ops that get reported (cheap)
// The syscall set and op classes come from ptrace_source.cc's kSpecs
// (fs_op column) — one source of truth for both fsslower flavours.
// ---------------------------------------------------------------------------

class FsTraceSource : public TracefsInstanceSource {
 public:
  FsTraceSource(size_t ring_pow2, const std::string& cfg)
      : TracefsInstanceSource(ring_pow2, "igtpu_fs") {
    min_lat_us_ = strtoull(cfg_get(cfg, "min_lat_us", "10000").c_str(),
                           nullptr, 10);
    // arch-native nr → fs-op class, from the ptrace window's tables
    for (const SyscallName* s = kSyscallNames; s->name; s++) {
      for (const SysSpec* sp = kSpecs; sp->name; sp++) {
        if (strcmp(sp->name, s->name) == 0) {
          if (sp->fs_op > 0) op_by_nr_[s->nr] = sp->fs_op;
          break;
        }
      }
    }
  }
  ~FsTraceSource() override { stop(); }

  static bool supported() {
    std::string root = tracefs_root();
    return root_usable(root) &&
           access((root + "/events/raw_syscalls/sys_enter").c_str(),
                  R_OK) == 0;
  }

 protected:
  std::vector<EventEnable> events() override {
    std::string filter;
    for (auto& [nr, _op] : op_by_nr_) {
      if (!filter.empty()) filter += "||";
      filter += "id==" + std::to_string(nr);
    }
    return {{"events/raw_syscalls/sys_enter", filter},
            {"events/raw_syscalls/sys_exit", filter}};
  }

  void prune() override {
    if (inflight_.size() > 65536) inflight_.clear();
  }

  void parse_line(const char* line, size_t len) override {
    std::string s(line, len);
    size_t m_in = s.find("sys_enter: NR ");
    size_t m_out = s.find("sys_exit: NR ");
    if (m_in == std::string::npos && m_out == std::string::npos) return;
    std::string comm;
    uint32_t pid = parse_task(s, comm);
    if (!pid) return;
    double ts = parse_ts(s, m_in != std::string::npos ? m_in : m_out);
    if (m_in != std::string::npos) {
      long nr = 0;
      unsigned long long a0 = 0;
      if (sscanf(s.c_str() + m_in + 14, "%ld (%llx", &nr, &a0) < 1) return;
      if (!op_by_nr_.count((int)nr)) return;
      inflight_[((uint64_t)pid << 16) | (uint64_t)(nr & 0xFFFF)] =
          Pending{ts, a0};
    } else {
      long nr = 0;
      long long ret = 0;
      if (sscanf(s.c_str() + m_out + 13, "%ld = %lld", &nr, &ret) != 2)
        return;
      auto op_it = op_by_nr_.find((int)nr);
      if (op_it == op_by_nr_.end()) return;
      auto key = ((uint64_t)pid << 16) | (uint64_t)(nr & 0xFFFF);
      auto it = inflight_.find(key);
      if (it == inflight_.end()) return;
      double lat_us = (ts - it->second.ts) * 1e6;
      uint64_t fdnum = it->second.fd;
      inflight_.erase(it);
      if (lat_us < (double)min_lat_us_) return;
      Event ev{};
      ev.ts_ns = now_ns();
      ev.kind = EV_FSSLOWER;
      ev.pid = pid;
      ev.aux1 = (uint64_t)lat_us;
      uint64_t bytes =
          (op_it->second == 1 || op_it->second == 2) && ret > 0
              ? (uint64_t)ret : 0;
      ev.aux2 = ((uint64_t)op_it->second << 32) | (bytes & 0xFFFFFFFF);
      // only reported (slow) ops pay the fd→path resolve
      if (op_it->second != 3 && fdnum < 65536) {
        char link[64], path[512];
        snprintf(link, sizeof(link), "/proc/%u/fd/%llu", pid,
                 (unsigned long long)fdnum);
        ssize_t pn = readlink(link, path, sizeof(path) - 1);
        if (pn > 0) {
          ev.key_hash = fnv1a64(path, (size_t)pn);
          vocab_.put(ev.key_hash, path, (size_t)pn);
        }
      }
      fill_task_identity(ev, comm);
      emit(ev);
    }
  }

 private:
  struct Pending {
    double ts;
    uint64_t fd;
  };

  uint64_t min_lat_us_;
  std::unordered_map<int, int> op_by_nr_;
  std::unordered_map<uint64_t, Pending> inflight_;
};

// ---------------------------------------------------------------------------
// CapTraceSource — trace/capabilities via the cap_capable TRACEPOINT.
//
// The reference kprobes cap_capable (capable.bpf.c:1-250) to see every
// capability check on the host with its verdict. Kernels >= 6.7 expose
// the same function as a real tracepoint (events/capability/cap_capable
// with cap + ret fields) — the exact mechanism, no BPF:
//   comm-pid [cpu] flags ts: cap_capable: cred .., target_ns ..,
//   capable_ns .., cap 21, ret 0
// This window sees ALLOWS and DENIES system-wide, strictly stronger than
// the audit EPERM-rule flavour (denial-only). Events:
//   kind EV_CAPABILITY   aux1 = 1 allow / 0 deny   aux2 = capability nr
// ---------------------------------------------------------------------------

class CapTraceSource : public TracefsInstanceSource {
 public:
  CapTraceSource(size_t ring_pow2, const std::string& cfg)
      : TracefsInstanceSource(ring_pow2, "igtpu_cap") {
    (void)cfg;
  }
  ~CapTraceSource() override { stop(); }

  static bool supported() {
    std::string root = tracefs_root();
    return root_usable(root) &&
           access((root + "/events/capability/cap_capable").c_str(),
                  R_OK) == 0;
  }

 protected:
  std::vector<EventEnable> events() override {
    return {{"events/capability/cap_capable", ""}};
  }

  void parse_line(const char* line, size_t len) override {
    std::string s(line, len);
    size_t m = s.find("cap_capable: ");
    if (m == std::string::npos) return;
    int cap = -1, ret = 0;
    size_t cp = s.find("cap ", m);
    if (cp == std::string::npos ||
        sscanf(s.c_str() + cp, "cap %d, ret %d", &cap, &ret) != 2 || cap < 0)
      return;
    Event ev{};
    ev.ts_ns = now_ns();
    ev.kind = EV_CAPABILITY;
    ev.aux1 = ret == 0 ? 1 : 0;  // allow : deny (ret is -EPERM on denial)
    ev.aux2 = (uint64_t)cap;
    std::string comm;
    ev.pid = parse_task(s, comm);
    fill_task_identity(ev, comm);
    emit(ev);
  }
};

}  // namespace ig
#endif  // __linux__
