// PacketSniffSource — AF_PACKET capture with protocol parsing in C++.
//
// Role parity with the reference's network gadget family:
//  - networktracer engine: one refcounted BPF socket-filter attachment per
//    netns (pkg/gadgets/internal/networktracer/tracer.go:54-220). Here: one
//    AF_PACKET sniffer per netns, entered via setns (the rawsock/netnsenter
//    analogue, pkg/rawsock/rawsock.go:40-76, pkg/netnsenter).
//  - dns.c (qname walker in BPF, pkg/gadgets/trace/dns/tracer/bpf/dns.c):
//    the DNS header/qname parse runs here in C++.
//  - snisnoop.c TLS ClientHello SNI walk.
//  - graph.c connection-edge dedup (trace/network).
//  - socketenricher (sockets-map.bpf.c): a periodic /proc/net + /proc/*/fd
//    scan maps local ports → pid/comm so packet events self-enrich.

#ifdef __linux__
#include <arpa/inet.h>
#include <dirent.h>
#include <fcntl.h>
#include <linux/if_ether.h>
#include <linux/if_packet.h>
#include <net/if.h>
#include <sched.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cstring>
#include <map>
#include <set>
#include <string>
#include <unordered_map>

#include "ringbuf.h"

namespace ig {

enum PacketKindFilter : uint32_t {
  PKT_DNS = 1,
  PKT_SNI = 2,
  PKT_FLOW = 3,
};

// ---------------------------------------------------------------------------
// SocketEnricher: local port -> (pid, comm), refreshed periodically.
// ---------------------------------------------------------------------------

class SocketEnricher {
 public:
  void refresh() {
    // inode -> port from the CALLING THREAD's netns view: /proc/net is a
    // symlink to /proc/self/net (the main process's netns), which would
    // read the HOST socket table from a capture thread that setns()'d
    // into a container — /proc/thread-self/net follows the thread
    std::unordered_map<uint64_t, uint16_t> inode_port;
    for (const char* path : {"/proc/thread-self/net/tcp",
                             "/proc/thread-self/net/udp",
                             "/proc/thread-self/net/tcp6",
                             "/proc/thread-self/net/udp6"}) {
      FILE* f = fopen(path, "r");
      if (!f) continue;
      char line[512];
      if (!fgets(line, sizeof(line), f)) { fclose(f); continue; }
      while (fgets(line, sizeof(line), f)) {
        char local[128];
        unsigned long long inode = 0;
        if (sscanf(line, " %*u: %127s %*s %*x %*s %*s %*s %*u %*u %llu",
                   local, &inode) < 2 || !inode)
          continue;
        char* colon = strrchr(local, ':');
        if (!colon) continue;
        inode_port[inode] = (uint16_t)strtoul(colon + 1, nullptr, 16);
      }
      fclose(f);
    }
    // pid -> inodes from /proc/*/fd
    std::unordered_map<uint16_t, std::pair<uint32_t, std::string>> fresh;
    DIR* proc = opendir("/proc");
    if (!proc) return;
    struct dirent* de;
    while ((de = readdir(proc))) {
      char* end;
      unsigned long pid = strtoul(de->d_name, &end, 10);
      if (*end || !pid) continue;
      char fdpath[64];
      snprintf(fdpath, sizeof(fdpath), "/proc/%lu/fd", pid);
      DIR* fds = opendir(fdpath);
      if (!fds) continue;
      std::string comm;
      struct dirent* fd;
      while ((fd = readdir(fds))) {
        char link[384], target[64];
        snprintf(link, sizeof(link), "%s/%s", fdpath, fd->d_name);
        ssize_t n = readlink(link, target, sizeof(target) - 1);
        if (n <= 9 || strncmp(target, "socket:[", 8) != 0) continue;
        target[n] = 0;
        uint64_t inode = strtoull(target + 8, nullptr, 10);
        auto it = inode_port.find(inode);
        if (it == inode_port.end()) continue;
        if (comm.empty()) {
          char cpath[64], cbuf[64];
          snprintf(cpath, sizeof(cpath), "/proc/%lu/comm", pid);
          int cfd = open(cpath, O_RDONLY);
          if (cfd >= 0) {
            ssize_t cn = read(cfd, cbuf, sizeof(cbuf) - 1);
            close(cfd);
            if (cn > 0 && cbuf[cn - 1] == '\n') cn--;
            if (cn > 0) comm.assign(cbuf, (size_t)cn);
          }
        }
        fresh[it->second] = {(uint32_t)pid, comm};
      }
      closedir(fds);
    }
    closedir(proc);
    std::lock_guard<std::mutex> g(mu_);
    by_port_.swap(fresh);
  }

  bool lookup(uint16_t port, uint32_t* pid, char* comm, size_t cap) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = by_port_.find(port);
    if (it == by_port_.end()) return false;
    *pid = it->second.first;
    size_t n = it->second.second.size() < cap - 1 ? it->second.second.size()
                                                  : cap - 1;
    memcpy(comm, it->second.second.data(), n);
    comm[n] = 0;
    return true;
  }

 private:
  std::mutex mu_;
  std::unordered_map<uint16_t, std::pair<uint32_t, std::string>> by_port_;
};

// ---------------------------------------------------------------------------
// PacketSniffSource
// ---------------------------------------------------------------------------

class PacketSniffSource : public Source {
 public:
  PacketSniffSource(size_t ring_pow2, uint32_t filter, int netns_fd)
      : Source(ring_pow2), filter_(filter), netns_fd_(netns_fd) {}
  ~PacketSniffSource() override {
    stop();
    if (netns_fd_ >= 0) close(netns_fd_);
  }

 protected:
  void run() override {
    // rawsock analogue: enter the target netns before opening the socket.
    // ETH_P_ALL (not ETH_P_IP) so the IPv6 plane is visible too; the
    // version-nibble dispatch drops non-IP frames (beats the reference:
    // dns.c:18 is v4-only)
    if (netns_fd_ >= 0) setns(netns_fd_, CLONE_NEWNET);
    int sock = socket(AF_PACKET, SOCK_DGRAM | SOCK_NONBLOCK,
                      htons(ETH_P_ALL));
    if (sock < 0) return;
    // loopback delivers every local packet twice under ETH_P_ALL (the
    // OUTGOING copy + the rx); dropping the OUTGOING copy on lo alone
    // keeps single delivery there while still seeing container-originated
    // traffic leaving on real interfaces
    const unsigned int lo_ifindex = if_nametoindex("lo");
    uint64_t last_refresh = 0;
    unsigned char buf[2048];
    while (running_.load(std::memory_order_relaxed)) {
      uint64_t now = now_ns();
      if (now - last_refresh > 1000000000ull) {
        enricher_.refresh();
        last_refresh = now;
      }
      struct sockaddr_ll sll{};
      socklen_t slen = sizeof(sll);
      ssize_t len = recvfrom(sock, buf, sizeof(buf), 0,
                             (struct sockaddr*)&sll, &slen);
      if (len <= 0) {
        std::this_thread::sleep_for(std::chrono::milliseconds(2));
        continue;
      }
      if (sll.sll_pkttype == PACKET_OUTGOING &&
          (unsigned int)sll.sll_ifindex == lo_ifindex)
        continue;
      parse_ip(buf, (size_t)len);
    }
    close(sock);
  }

 private:
  void emit(uint64_t key_hash, const char* name, size_t name_len,
            uint32_t saddr, uint32_t daddr, uint16_t sport, uint16_t dport,
            uint32_t kind, uint32_t flags) {
    Event ev{};
    ev.ts_ns = now_ns();
    ev.kind = kind;
    ev.key_hash = key_hash;
    if (name && name_len) vocab_.put(key_hash, name, name_len);
    if (name) {
      size_t c = name_len < sizeof(ev.comm) - 1 ? name_len : sizeof(ev.comm) - 1;
      memcpy(ev.comm, name, c);
    }
    ev.aux1 = ((uint64_t)saddr << 32) | daddr;
    ev.aux2 = ((uint64_t)flags << 32) | ((uint32_t)sport << 16) | dport;
    char comm[32];
    uint32_t pid = 0;
    // socketenricher: prefer the local (source) port, then dest
    if (enricher_.lookup(sport, &pid, comm, sizeof(comm)) ||
        enricher_.lookup(dport, &pid, comm, sizeof(comm))) {
      ev.pid = pid;
    }
    ring_.push(ev);
  }

  void parse_ip(const unsigned char* p, size_t len) {
    uint8_t ver = len ? (p[0] >> 4) : 0;
    if (ver == 6) {
      parse_ip6(p, len);
      return;
    }
    if (len < 20 || ver != 4) return;
    size_t ihl = (size_t)(p[0] & 0xF) * 4;
    if (ihl < 20 || len < ihl + 8) return;  // corrupt IHL nibble
    uint8_t proto = p[9];
    uint32_t saddr = ntohl(*(const uint32_t*)(p + 12));
    uint32_t daddr = ntohl(*(const uint32_t*)(p + 16));
    dispatch_l4(proto, p + ihl, len - ihl, saddr, daddr, p + 12, p + 16, 4);
  }

  // IPv6: fixed 40-byte header + a bounded extension-header walk; the
  // 128-bit addresses are xor-folded into the 32-bit aux fields (display
  // names carry the full address via the vocab).
  void parse_ip6(const unsigned char* p, size_t len) {
    if (len < 40) return;
    uint8_t next = p[6];
    size_t off = 40;
    for (int hops = 0; hops < 4; hops++) {
      if (next == 0 || next == 43 || next == 60) {  // hbh/routing/dstopts
        if (off + 8 > len) return;
        uint8_t nn = p[off];
        off += ((size_t)p[off + 1] + 1) * 8;
        next = nn;
      } else if (next == 44) {  // fragment (fixed 8 bytes)
        if (off + 8 > len) return;
        if (p[off + 2] || (p[off + 3] & 0xF8)) return;  // non-first frag
        next = p[off];
        off += 8;
      } else {
        break;
      }
    }
    // a chain longer than the walk bound leaves an unconsumed extension
    // header — its bytes must not be parsed as L4 ports
    if (next == 0 || next == 43 || next == 44 || next == 60) return;
    if (off + 8 > len) return;
    auto fold = [](const unsigned char* a) {
      uint32_t w = 0;
      for (int i = 0; i < 4; i++) w ^= ntohl(*(const uint32_t*)(a + 4 * i));
      return w;
    };
    dispatch_l4(next, p + off, len - off, fold(p + 8), fold(p + 24), p + 8,
                p + 24, 16);
  }

  // Family-independent L4 dispatch: addr16/alen key the flow dedup (full
  // 128-bit tuples for v6); display names are formatted lazily, only for
  // NEW flows (never on the per-packet hot path).
  void dispatch_l4(uint8_t proto, const unsigned char* l4, size_t l4len,
                   uint32_t saddr, uint32_t daddr,
                   const unsigned char* saddr_raw,
                   const unsigned char* daddr_raw, size_t alen) {
    if (l4len < 8) return;
    uint16_t sport = ((uint16_t)l4[0] << 8) | l4[1];
    uint16_t dport = ((uint16_t)l4[2] << 8) | l4[3];
    if (filter_ == PKT_FLOW) {
      unsigned char tuple[16 * 2 + 5];
      memcpy(tuple, saddr_raw, alen);
      memcpy(tuple + alen, daddr_raw, alen);
      tuple[2 * alen] = (unsigned char)(sport >> 8);
      tuple[2 * alen + 1] = (unsigned char)sport;
      tuple[2 * alen + 2] = (unsigned char)(dport >> 8);
      tuple[2 * alen + 3] = (unsigned char)dport;
      tuple[2 * alen + 4] = proto;
      uint64_t h = fnv1a64((const char*)tuple, 2 * alen + 5);
      if (seen_flows_.insert(h).second) {
        char name[96];
        int n;
        if (alen == 16) {
          char dst[INET6_ADDRSTRLEN] = {0};
          inet_ntop(AF_INET6, daddr_raw, dst, sizeof(dst));
          n = snprintf(name, sizeof(name), "[%s]:%u", dst, dport);
        } else {
          n = snprintf(name, sizeof(name), "%u.%u.%u.%u:%u", daddr >> 24,
                       (daddr >> 16) & 0xFF, (daddr >> 8) & 0xFF,
                       daddr & 0xFF, dport);
        }
        emit(h, name, (size_t)n, saddr, daddr, sport, dport, EV_NET_GRAPH,
             proto);
      }
      return;
    }
    if (filter_ == PKT_DNS && proto == 17 && l4len > 8 + 12 &&
        (dport == 53 || sport == 53)) {
      parse_dns(l4 + 8, l4len - 8, saddr, daddr, sport, dport);
    } else if (filter_ == PKT_SNI && proto == 6 && l4len >= 20) {
      size_t doff = (size_t)(l4[12] >> 4) * 4;
      if (l4len > doff) parse_sni(l4 + doff, l4len - doff, saddr, daddr,
                                  sport, dport);
    }
  }

  // DNS qname walker (ref contract: dns.c:1-242 walks labels in BPF)
  void parse_dns(const unsigned char* d, size_t len, uint32_t saddr,
                 uint32_t daddr, uint16_t sport, uint16_t dport) {
    if (len < 12) return;
    uint16_t flags = ((uint16_t)d[2] << 8) | d[3];
    uint16_t qdcount = ((uint16_t)d[4] << 8) | d[5];
    if (qdcount == 0) return;
    char name[256];
    size_t ni = 0, i = 12;
    while (i < len && d[i] != 0 && ni < sizeof(name) - 2) {
      size_t lab = d[i++];
      if (lab > 63 || i + lab > len) return;  // compression/verifier guard
      if (ni) name[ni++] = '.';
      for (size_t j = 0; j < lab && ni < sizeof(name) - 1; j++)
        name[ni++] = (char)d[i + j];
      i += lab;
    }
    if (ni == 0) return;
    uint16_t qtype = (i + 4 < len) ? (((uint16_t)d[i + 1] << 8) | d[i + 2]) : 1;
    uint64_t h = fnv1a64(name, ni);
    // flags word (32-bit): full 16-bit qtype<<16 | QR bit (0x80) | rcode
    // nibble (decoded by network_family.py's native branch)
    emit(h, name, ni, saddr, daddr, sport, dport, EV_DNS,
         ((uint32_t)qtype << 16) | (uint32_t)(flags >> 8 & 0x80) |
             (uint32_t)(flags & 0x0F));
  }

  // TLS ClientHello SNI walk (ref contract: snisnoop.c)
  void parse_sni(const unsigned char* d, size_t len, uint32_t saddr,
                 uint32_t daddr, uint16_t sport, uint16_t dport) {
    // TLS record: type 22 (handshake), version, len; handshake type 1
    if (len < 9 + 34 || d[0] != 22 || d[5] != 1) return;
    size_t i = 9 + 34;  // record hdr(5) + hs hdr(4) + version(2) + random(32)
    if (i >= len) return;
    size_t sid = d[i]; i += 1 + sid;                       // session id
    if (i + 2 > len) return;
    size_t cs = ((size_t)d[i] << 8) | d[i + 1]; i += 2 + cs;  // ciphers
    if (i + 1 > len) return;
    size_t comp = d[i]; i += 1 + comp;                     // compression
    if (i + 2 > len) return;
    size_t extlen = ((size_t)d[i] << 8) | d[i + 1]; i += 2;
    size_t end = i + extlen < len ? i + extlen : len;
    while (i + 4 <= end) {
      uint16_t etype = ((uint16_t)d[i] << 8) | d[i + 1];
      size_t elen = ((size_t)d[i + 2] << 8) | d[i + 3];
      i += 4;
      if (etype == 0 && i + 5 <= end) {  // server_name
        size_t nlen = ((size_t)d[i + 3] << 8) | d[i + 4];
        if (i + 5 + nlen <= end && nlen > 0 && nlen < 256) {
          uint64_t h = fnv1a64((const char*)(d + i + 5), nlen);
          emit(h, (const char*)(d + i + 5), nlen, saddr, daddr, sport,
               dport, EV_SNI, 0);
          return;
        }
      }
      i += elen;
    }
  }

  uint32_t filter_;
  int netns_fd_;
  SocketEnricher enricher_;
  std::set<uint64_t> seen_flows_;
};

}  // namespace ig
#endif  // __linux__
