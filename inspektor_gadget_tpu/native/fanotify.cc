// FanotifyExecSource — container-runtime detection via fanotify.
//
// Reference contract: pkg/runcfanotify/runcfanotify.go — watches runc
// binaries with FAN_OPEN_EXEC_PERM, reads the OCI bundle's config.json,
// and emits container add/remove without any runtime hook (:144-300).
// Here: FAN_OPEN_EXEC (non-permission flavour — observe, never gate) marks
// on the configured binaries; each exec of a watched binary emits an
// EV_EXEC event whose mntns/pid identify the new workload root. The
// ContainerCollection consumes these as container-start candidates.

#ifdef __linux__
#include <fcntl.h>
#include <sys/fanotify.h>
#include <unistd.h>

#include <cstring>
#include <string>
#include <vector>

#include "ringbuf.h"

namespace ig {

class FanotifyExecSource : public Source {
 public:
  FanotifyExecSource(size_t ring_pow2, std::vector<std::string> paths)
      : Source(ring_pow2), paths_(std::move(paths)) {
    if (paths_.empty())
      paths_ = {"/usr/bin/runc", "/usr/sbin/runc", "/usr/local/bin/runc"};
  }
  ~FanotifyExecSource() override { stop(); }

  static bool supported() {  // ref: runcfanotify.go Supported():144
    int fd = fanotify_init(FAN_CLASS_NOTIF | FAN_NONBLOCK,
                           O_RDONLY | O_CLOEXEC);
    if (fd < 0) return false;
    close(fd);
    return true;
  }

 protected:
  void run() override {
    int fan = fanotify_init(FAN_CLASS_NOTIF | FAN_NONBLOCK,
                            O_RDONLY | O_LARGEFILE | O_CLOEXEC);
    if (fan < 0) return;
    bool any = false;
    for (const auto& p : paths_) {
      if (fanotify_mark(fan, FAN_MARK_ADD, FAN_OPEN_EXEC, AT_FDCWD,
                        p.c_str()) == 0)
        any = true;
    }
    if (!any) {
      close(fan);
      return;
    }
    char buf[4096];
    while (running_.load(std::memory_order_relaxed)) {
      ssize_t len = read(fan, buf, sizeof(buf));
      if (len <= 0) {
        std::this_thread::sleep_for(std::chrono::milliseconds(10));
        continue;
      }
      auto* md = (struct fanotify_event_metadata*)buf;
      while (FAN_EVENT_OK(md, len)) {
        if (md->mask & FAN_OPEN_EXEC) {
          Event ev{};
          ev.ts_ns = now_ns();
          ev.kind = EV_EXEC;
          ev.pid = (uint32_t)md->pid;
          fill_identity(ev);
          emit(ev);
        }
        if (md->fd >= 0) close(md->fd);
        md = FAN_EVENT_NEXT(md, len);
      }
    }
    close(fan);
  }

 private:
  void fill_identity(Event& ev) {
    char path[64], buf[64];
    snprintf(path, sizeof(path), "/proc/%u/comm", ev.pid);
    int fd = open(path, O_RDONLY);
    ssize_t n = fd >= 0 ? read(fd, buf, sizeof(buf) - 1) : 0;
    if (fd >= 0) close(fd);
    if (n > 0 && buf[n - 1] == '\n') n--;
    if (n > 0) {
      ev.key_hash = fnv1a64(buf, (size_t)n);
      vocab_.put(ev.key_hash, buf, (size_t)n);
      size_t c = (size_t)n < sizeof(ev.comm) - 1 ? (size_t)n : sizeof(ev.comm) - 1;
      memcpy(ev.comm, buf, c);
    }
    snprintf(path, sizeof(path), "/proc/%u/ns/mnt", ev.pid);
    char link[64];
    ssize_t ln = readlink(path, link, sizeof(link) - 1);
    if (ln > 0) {
      link[ln] = 0;
      const char* lb = strchr(link, '[');
      if (lb) ev.mntns = strtoull(lb + 1, nullptr, 10);
    }
  }

  std::vector<std::string> paths_;
};

// ---------------------------------------------------------------------------
// FanotifyRuncSource — container identity from the runtime, hookless.
//
// Reference contract: pkg/runcfanotify/runcfanotify.go:160-300 — watch runc
// binaries, parse the command line for the OCI verb + --bundle + --pid-file
// + container id, then watch the pid file to learn the container init pid,
// and watch that pid for termination. The config.json itself is parsed by
// the Python rim (containers/options.py), which has a JSON parser; this
// source delivers the kernel-real detection chain:
//   EV_CONTAINER aux2=1 create / 2 start / 3 run / 4 delete  (runc exec seen)
//   EV_CONTAINER aux2=10 started  (pid file written; ev.pid = init pid)
//   EV_CONTAINER aux2=11 removed  (init pid vanished)
// vocab payload under key_hash: "<id>\x1f<bundle>\x1f<pidfile>".
// ---------------------------------------------------------------------------

class FanotifyRuncSource : public Source {
 public:
  FanotifyRuncSource(size_t ring_pow2, const std::string& cfg)
      : Source(ring_pow2) {
    std::string p = cfg_get(cfg, "paths");
    if (!p.empty()) paths_ = split_str(p, ':');
    if (paths_.empty())
      paths_ = {"/usr/bin/runc", "/usr/sbin/runc", "/usr/local/bin/runc",
                "/usr/local/sbin/runc"};
  }
  ~FanotifyRuncSource() override { stop(); }

 protected:
  struct PidWait {
    std::string pidfile;
    uint64_t key_hash;
    uint64_t deadline_ns;
  };
  struct TermWait {
    uint32_t pid;
    uint64_t key_hash;
  };

  void run() override {
    int fan = fanotify_init(FAN_CLASS_NOTIF | FAN_NONBLOCK,
                            O_RDONLY | O_LARGEFILE | O_CLOEXEC);
    if (fan < 0) return;
    bool any = false;
    for (const auto& p : paths_)
      if (fanotify_mark(fan, FAN_MARK_ADD, FAN_OPEN_EXEC, AT_FDCWD,
                        p.c_str()) == 0)
        any = true;
    if (!any) {
      close(fan);
      return;
    }
    char buf[4096];
    while (running_.load(std::memory_order_relaxed)) {
      ssize_t len = read(fan, buf, sizeof(buf));
      if (len > 0) {
        auto* md = (struct fanotify_event_metadata*)buf;
        while (FAN_EVENT_OK(md, len)) {
          if (md->mask & FAN_OPEN_EXEC) on_runc_exec((uint32_t)md->pid);
          if (md->fd >= 0) close(md->fd);
          md = FAN_EVENT_NEXT(md, len);
        }
      }
      poll_waiters();
      std::this_thread::sleep_for(std::chrono::milliseconds(10));
    }
    close(fan);
  }

 private:
  void on_runc_exec(uint32_t pid) {
    // /proc/<pid>/cmdline is NUL-separated argv
    char path[64];
    snprintf(path, sizeof(path), "/proc/%u/cmdline", pid);
    int fd = open(path, O_RDONLY);
    if (fd < 0) return;
    char raw[4096];
    ssize_t n = read(fd, raw, sizeof(raw) - 1);
    close(fd);
    if (n <= 0) return;
    raw[n] = 0;
    std::vector<std::string> argv;
    for (ssize_t i = 0; i < n;) {
      size_t l = strnlen(raw + i, (size_t)(n - i));
      argv.emplace_back(raw + i, l);
      i += (ssize_t)l + 1;
    }
    // parse: runc [global flags] <verb> [--bundle B] [--pid-file P] <id>
    int verb = 0;
    std::string bundle, pidfile, id;
    for (size_t i = 1; i < argv.size(); i++) {
      const std::string& a = argv[i];
      if (a == "create") verb = 1;
      else if (a == "start") verb = 2;
      else if (a == "run") verb = 3;
      else if (a == "delete") verb = 4;
      else if ((a == "--bundle" || a == "-b") && i + 1 < argv.size())
        bundle = argv[++i];
      else if (a == "--pid-file" && i + 1 < argv.size())
        pidfile = argv[++i];
      else if (verb && a[0] != '-')
        id = a;  // last non-flag arg after the verb
    }
    if (!verb || id.empty()) return;
    if (bundle.empty()) {
      // runc defaults the bundle to the invoking cwd (runc spec)
      char cwdlink[64], cwd[512];
      snprintf(cwdlink, sizeof(cwdlink), "/proc/%u/cwd", pid);
      ssize_t cn = readlink(cwdlink, cwd, sizeof(cwd) - 1);
      if (cn > 0) bundle.assign(cwd, (size_t)cn);
    }
    // One key per container id: create/run registers it; start/delete
    // reuse it so the whole lifecycle chain correlates by key_hash.
    uint64_t kh;
    auto known = id_keys_.find(id);
    if (known != id_keys_.end() && verb != 1 && verb != 3) {
      kh = known->second;
    } else {
      std::string payload = id + '\x1f' + bundle + '\x1f' + pidfile;
      kh = fnv1a64(payload.data(), payload.size());
      vocab_.put(kh, payload.data(), payload.size());
      id_keys_[id] = kh;
    }
    Event ev{};
    ev.ts_ns = now_ns();
    ev.kind = EV_CONTAINER;
    ev.pid = pid;
    ev.aux2 = (uint64_t)verb;
    ev.key_hash = kh;
    size_t c = id.size() < sizeof(ev.comm) - 1 ? id.size() : sizeof(ev.comm) - 1;
    memcpy(ev.comm, id.data(), c);
    emit(ev);
    if ((verb == 1 || verb == 3) && !pidfile.empty())
      pid_waits_.push_back(
          PidWait{pidfile, kh, now_ns() + 5000000000ull /*5s*/});
    if (verb == 4) {
      // delete verb: authoritative removal; drop any pending term watch so
      // the consumer does not see a duplicate removal for the same key
      for (size_t i = 0; i < term_waits_.size();) {
        if (term_waits_[i].key_hash == kh)
          term_waits_.erase(term_waits_.begin() + (long)i);
        else
          i++;
      }
      Event rv = ev;
      rv.aux2 = 11;
      rv.pid = 0;  // init pid unknown at delete time
      emit(rv);
      id_keys_.erase(id);
    }
  }

  void poll_waiters() {
    uint64_t now = now_ns();
    for (size_t i = 0; i < pid_waits_.size();) {
      PidWait& w = pid_waits_[i];
      FILE* f = fopen(w.pidfile.c_str(), "r");
      unsigned pid = 0;
      if (f) {
        if (fscanf(f, "%u", &pid) != 1) pid = 0;
        fclose(f);
      }
      if (pid) {
        Event ev{};
        ev.ts_ns = now;
        ev.kind = EV_CONTAINER;
        ev.pid = pid;
        ev.aux2 = 10;  // started
        ev.key_hash = w.key_hash;
        fill_mntns(ev, pid);
        emit(ev);
        term_waits_.push_back(TermWait{pid, w.key_hash});
        pid_waits_.erase(pid_waits_.begin() + (long)i);
      } else if (now > w.deadline_ns) {
        pid_waits_.erase(pid_waits_.begin() + (long)i);
      } else {
        i++;
      }
    }
    for (size_t i = 0; i < term_waits_.size();) {
      char p[64];
      snprintf(p, sizeof(p), "/proc/%u", term_waits_[i].pid);
      if (access(p, F_OK) != 0) {
        Event ev{};
        ev.ts_ns = now;
        ev.kind = EV_CONTAINER;
        ev.pid = term_waits_[i].pid;
        ev.aux2 = 11;  // removed
        ev.key_hash = term_waits_[i].key_hash;
        emit(ev);
        term_waits_.erase(term_waits_.begin() + (long)i);
      } else {
        i++;
      }
    }
  }

  static void fill_mntns(Event& ev, uint32_t pid) {
    char path[64], link[64];
    snprintf(path, sizeof(path), "/proc/%u/ns/mnt", pid);
    ssize_t ln = readlink(path, link, sizeof(link) - 1);
    if (ln > 0) {
      link[ln] = 0;
      const char* lb = strchr(link, '[');
      if (lb) ev.mntns = strtoull(lb + 1, nullptr, 10);
    }
  }

  std::vector<std::string> paths_;
  std::vector<PidWait> pid_waits_;
  std::vector<TermWait> term_waits_;
  std::unordered_map<std::string, uint64_t> id_keys_;
};

}  // namespace ig
#endif  // __linux__
