// FanotifyExecSource — container-runtime detection via fanotify.
//
// Reference contract: pkg/runcfanotify/runcfanotify.go — watches runc
// binaries with FAN_OPEN_EXEC_PERM, reads the OCI bundle's config.json,
// and emits container add/remove without any runtime hook (:144-300).
// Here: FAN_OPEN_EXEC (non-permission flavour — observe, never gate) marks
// on the configured binaries; each exec of a watched binary emits an
// EV_EXEC event whose mntns/pid identify the new workload root. The
// ContainerCollection consumes these as container-start candidates.

#ifdef __linux__
#include <fcntl.h>
#include <sys/fanotify.h>
#include <unistd.h>

#include <cstring>
#include <string>
#include <vector>

#include "ringbuf.h"

namespace ig {

class FanotifyExecSource : public Source {
 public:
  FanotifyExecSource(size_t ring_pow2, std::vector<std::string> paths)
      : Source(ring_pow2), paths_(std::move(paths)) {
    if (paths_.empty())
      paths_ = {"/usr/bin/runc", "/usr/sbin/runc", "/usr/local/bin/runc"};
  }
  ~FanotifyExecSource() override { stop(); }

  static bool supported() {  // ref: runcfanotify.go Supported():144
    int fd = fanotify_init(FAN_CLASS_NOTIF | FAN_NONBLOCK,
                           O_RDONLY | O_CLOEXEC);
    if (fd < 0) return false;
    close(fd);
    return true;
  }

 protected:
  void run() override {
    int fan = fanotify_init(FAN_CLASS_NOTIF | FAN_NONBLOCK,
                            O_RDONLY | O_LARGEFILE | O_CLOEXEC);
    if (fan < 0) return;
    bool any = false;
    for (const auto& p : paths_) {
      if (fanotify_mark(fan, FAN_MARK_ADD, FAN_OPEN_EXEC, AT_FDCWD,
                        p.c_str()) == 0)
        any = true;
    }
    if (!any) {
      close(fan);
      return;
    }
    char buf[4096];
    while (running_.load(std::memory_order_relaxed)) {
      ssize_t len = read(fan, buf, sizeof(buf));
      if (len <= 0) {
        std::this_thread::sleep_for(std::chrono::milliseconds(10));
        continue;
      }
      auto* md = (struct fanotify_event_metadata*)buf;
      while (FAN_EVENT_OK(md, len)) {
        if (md->mask & FAN_OPEN_EXEC) {
          Event ev{};
          ev.ts_ns = now_ns();
          ev.kind = EV_EXEC;
          ev.pid = (uint32_t)md->pid;
          fill_identity(ev);
          ring_.push(ev);
        }
        if (md->fd >= 0) close(md->fd);
        md = FAN_EVENT_NEXT(md, len);
      }
    }
    close(fan);
  }

 private:
  void fill_identity(Event& ev) {
    char path[64], buf[64];
    snprintf(path, sizeof(path), "/proc/%u/comm", ev.pid);
    int fd = open(path, O_RDONLY);
    ssize_t n = fd >= 0 ? read(fd, buf, sizeof(buf) - 1) : 0;
    if (fd >= 0) close(fd);
    if (n > 0 && buf[n - 1] == '\n') n--;
    if (n > 0) {
      ev.key_hash = fnv1a64(buf, (size_t)n);
      vocab_.put(ev.key_hash, buf, (size_t)n);
      size_t c = (size_t)n < sizeof(ev.comm) - 1 ? (size_t)n : sizeof(ev.comm) - 1;
      memcpy(ev.comm, buf, c);
    }
    snprintf(path, sizeof(path), "/proc/%u/ns/mnt", ev.pid);
    char link[64];
    ssize_t ln = readlink(path, link, sizeof(link) - 1);
    if (ln > 0) {
      link[ln] = 0;
      const char* lb = strchr(link, '[');
      if (lb) ev.mntns = strtoull(lb + 1, nullptr, 10);
    }
  }

  std::vector<std::string> paths_;
};

}  // namespace ig
#endif  // __linux__
