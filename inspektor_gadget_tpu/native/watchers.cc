// Real kernel-event watchers for the syscall-family trace gadgets.
//
// The reference implements these as eBPF programs; this build observes the
// same kernel facts through the non-BPF windows the kernel offers:
//  - FanotifyOpenSource  → trace/open   (ref: pkg/gadgets/trace/open/tracer/
//    bpf/opensnoop.bpf.c:1-163, openat tracepoints). fanotify mount marks
//    with FAN_OPEN|FAN_MODIFY deliver an fd whose /proc/self/fd link is the
//    opened path; pid identity comes with the event metadata.
//  - MountInfoSource     → trace/mount  (ref: mountsnoop.bpf.c:1-168).
//    /proc/self/mountinfo is pollable (POLLERR|POLLPRI on change); diffing
//    by mount id yields real mount/umount events with source/target/fstype.
//  - SockDiagBindSource  → trace/bind   (ref: bindsnoop.bpf.c:1-152).
//    NETLINK_SOCK_DIAG dumps of listening TCP + bound UDP sockets, diffed
//    by inode; pid resolved by a targeted /proc/*/fd socket-inode scan.
//  - KmsgOomSource       → trace/oomkill (ref: oomkill.bpf.c:1-51, kprobe
//    oom_kill_process). The OOM killer logs structured lines to the kernel
//    ring; /dev/kmsg streams them with no polling loss.
//
// All sources emit through Source::emit() so the capture-side mntns filter
// and filtered-event accounting apply uniformly.

#ifdef __linux__
#include <fcntl.h>
#include <poll.h>
#include <sched.h>
#include <sys/fanotify.h>
#include <sys/mount.h>
#include <sys/stat.h>
#include <unistd.h>

#include <mutex>

#include <dirent.h>
#include <linux/inet_diag.h>
#include <linux/netlink.h>
#include <linux/rtnetlink.h>
#include <linux/sock_diag.h>
#include <linux/tcp.h>
#include <netinet/in.h>
#include <sys/socket.h>

#include <cstring>
#include <map>
#include <string>
#include <unordered_map>
#include <unordered_set>
#include <vector>

#include "ringbuf.h"

namespace ig {

// "key=value\x1fkey=value" config-string access (the string-configured
// source analogue of the reference's RewriteConstants at BPF load time).
inline std::string cfg_get(const std::string& cfg, const char* key,
                           const char* dflt = "") {
  std::string needle = std::string(key) + "=";
  size_t pos = 0;
  while (pos < cfg.size()) {
    size_t end = cfg.find('\x1f', pos);
    if (end == std::string::npos) end = cfg.size();
    if (cfg.compare(pos, needle.size(), needle) == 0)
      return cfg.substr(pos + needle.size(), end - pos - needle.size());
    pos = end + 1;
  }
  return dflt;
}

inline std::vector<std::string> split_str(const std::string& s, char sep) {
  std::vector<std::string> out;
  size_t pos = 0;
  while (pos <= s.size()) {
    size_t end = s.find(sep, pos);
    if (end == std::string::npos) end = s.size();
    if (end > pos) out.push_back(s.substr(pos, end - pos));
    pos = end + 1;
  }
  return out;
}

// ---------------------------------------------------------------------------
// FanotifyOpenSource — trace/open via fanotify mount marks.
// ---------------------------------------------------------------------------

// mountinfo octal-escapes spaces/tabs/backslashes in path fields
inline std::string mountinfo_unescape(const std::string& s) {
  if (s.find('\\') == std::string::npos) return s;
  std::string out;
  out.reserve(s.size());
  for (size_t i = 0; i < s.size();) {
    if (s[i] == '\\' && i + 3 < s.size() && s[i + 1] >= '0' &&
        s[i + 1] <= '7' && s[i + 2] >= '0' && s[i + 2] <= '7' &&
        s[i + 3] >= '0' && s[i + 3] <= '7') {
      out.push_back((char)(((s[i + 1] - '0') << 6) | ((s[i + 2] - '0') << 3) |
                           (s[i + 3] - '0')));
      i += 4;
    } else {
      out.push_back(s[i++]);
    }
  }
  return out;
}

// One mountinfo parser for every consumer (the remark loop and
// MountInfoSource::scan must never disagree on escaping/fields).
struct MountInfoEnt {
  unsigned long id;
  std::string target, source, fstype;
};

// Read fd from offset 0 and parse every line (target/source unescaped).
// Returns false when nothing could be read — the watched pid is gone.
inline bool read_mountinfo(int fd, std::vector<MountInfoEnt>& out) {
  if (lseek(fd, 0, SEEK_SET) != 0) return false;
  std::string content;
  char buf[8192];
  ssize_t n;
  while ((n = read(fd, buf, sizeof(buf))) > 0) content.append(buf, (size_t)n);
  if (content.empty()) return false;
  // line: "36 35 98:0 /root /mnt rw,noatime master:1 - ext3 /dev/sda rw"
  for (const auto& line : split_str(content, '\n')) {
    size_t dash = line.find(" - ");
    if (dash == std::string::npos) continue;
    char root[256], target[256], fstype[64], source[256];
    unsigned long id = 0, parent = 0;
    if (sscanf(line.c_str(), "%lu %lu %*s %255s %255s", &id, &parent, root,
               target) != 4)
      continue;
    if (sscanf(line.c_str() + dash + 3, "%63s %255s", fstype, source) != 2)
      continue;
    out.push_back({id, mountinfo_unescape(target), mountinfo_unescape(source),
                   fstype});
  }
  return true;
}

// kernel pseudo-filesystems: no value marking them (mirror of the Python
// attach-time skip list, source_gadget.py _FANOTIFY_SKIP_FSTYPES)
inline bool fanotify_skip_fstype(const std::string& t) {
  static const std::unordered_set<std::string> kSkip = {
      "proc",       "sysfs",   "devpts", "devtmpfs", "cgroup",
      "cgroup2",    "securityfs", "debugfs", "tracefs", "mqueue",
      "bpf",        "fusectl", "configfs", "pstore",  "efivarfs"};
  return kSkip.count(t) != 0;
}

class FanotifyOpenSource : public Source {
 public:
  FanotifyOpenSource(size_t ring_pow2, const std::string& cfg)
      : Source(ring_pow2) {
    // list values arrive \x1e-separated (make_cfg's list contract) since
    // ':' is legal inside mount points; the user-facing CLI colon syntax
    // stays supported when no \x1e is present
    std::string raw = cfg_get(cfg, "paths", "/");
    paths_ = split_str(raw, raw.find('\x1e') != std::string::npos ? '\x1e'
                                                                  : ':');
    if (paths_.empty()) paths_ = {"/"};
    include_modify_ = cfg_get(cfg, "modify", "1") != "0";
    // live re-mark: watch this pid's mountinfo and mark mounts created
    // AFTER attach (closes the snapshot gap vs the reference's kprobes,
    // opensnoop.bpf.c full-coverage semantics)
    remark_pid_ = atoi(cfg_get(cfg, "remark_pid", "0").c_str());
  }
  ~FanotifyOpenSource() override { stop(); }

 protected:
  // Re-mark every markable mount in the watched pid's mount ns. Marks
  // are re-added idempotently each pass (FAN_MARK_ADD on a marked mount
  // merges masks, no duplicate events): a mount REPLACED at the same
  // target between polls gets a fresh mark instead of being skipped, and
  // dead mounts stop counting against the budget (their marks die with
  // the mount). Returns false when the target pid is gone.
  bool remark(int fan, uint64_t mask, int mi_fd, const std::string& root) {
    std::vector<MountInfoEnt> ents;
    if (!read_mountinfo(mi_fd, ents)) return false;  // pid exited
    size_t live = 0;
    for (const auto& e : ents) {
      if (e.target.empty() || e.target == "/") continue;
      if (fanotify_skip_fstype(e.fstype)) continue;
      if (live >= kMaxMarks) {
        if (!marks_capped_) {
          marks_capped_ = true;
          fprintf(stderr,
                  "ig: fanotify remark budget (%zu) exceeded for pid %d — "
                  "later mounts are NOT watched\n",
                  kMaxMarks, remark_pid_);
        }
        break;
      }
      std::string full = root + e.target;
      if (fanotify_mark(fan, FAN_MARK_ADD | FAN_MARK_MOUNT, mask, AT_FDCWD,
                        full.c_str()) == 0)
        live++;
    }
    return true;
  }

  void run() override {
    int fan = fanotify_init(FAN_CLASS_NOTIF | FAN_NONBLOCK,
                            O_RDONLY | O_LARGEFILE | O_CLOEXEC);
    if (fan < 0) return;
    uint64_t mask = FAN_OPEN;
    if (include_modify_) mask |= FAN_MODIFY;
    bool any = false;
    std::unordered_set<std::string> marked;
    for (const auto& p : paths_) {
      if (fanotify_mark(fan, FAN_MARK_ADD | FAN_MARK_MOUNT, mask, AT_FDCWD,
                        p.c_str()) == 0) {
        any = true;
        marked.insert(p);
      }
    }
    if (!any) {
      close(fan);
      return;
    }
    int mi_fd = -1;
    std::string root;
    if (remark_pid_ > 0) {
      char mp[64];
      snprintf(mp, sizeof(mp), "/proc/%d/mountinfo", remark_pid_);
      mi_fd = open(mp, O_RDONLY | O_CLOEXEC);
      snprintf(mp, sizeof(mp), "/proc/%d/root", remark_pid_);
      root = mp;
      // initial sweep: the poll baseline is set at open(), so a mount
      // created between the Python attach-time snapshot and this open
      // would otherwise never fire POLLPRI and never get marked
      if (mi_fd >= 0 && !remark(fan, mask, mi_fd, root)) {
        close(mi_fd);
        mi_fd = -1;
      }
    }
    const uint32_t self = (uint32_t)getpid();
    char buf[8192];
    struct pollfd pfds[2] = {{fan, POLLIN, 0},
                             {mi_fd, POLLERR | POLLPRI, 0}};
    while (running_.load(std::memory_order_relaxed)) {
      nfds_t nf = mi_fd >= 0 ? 2 : 1;
      if (poll(pfds, nf, 100) <= 0) continue;
      if (nf == 2 && (pfds[1].revents & (POLLERR | POLLPRI))) {
        if (!remark(fan, mask, mi_fd, root)) {
          close(mi_fd);
          mi_fd = -1;  // target gone; keep serving existing marks
        }
      }
      if (!(pfds[0].revents & POLLIN)) continue;
      ssize_t len = read(fan, buf, sizeof(buf));
      if (len <= 0) continue;
      auto* md = (struct fanotify_event_metadata*)buf;
      while (FAN_EVENT_OK(md, len)) {
        // Skip our own accesses (the identity fill below reads /proc, which
        // is a different mount, but the event fd close and any library IO
        // on a marked mount would feed back otherwise).
        if ((uint32_t)md->pid != self &&
            (md->mask & (FAN_OPEN | FAN_MODIFY))) {
          Event ev{};
          ev.ts_ns = now_ns();
          ev.kind = EV_OPEN;
          ev.pid = (uint32_t)md->pid;
          // aux2: bit0 = open, bit1 = modify (write) — the flags analogue
          ev.aux2 = ((md->mask & FAN_OPEN) ? 1u : 0u) |
                    ((md->mask & FAN_MODIFY) ? 2u : 0u);
          if (md->fd >= 0) {
            char fdp[64], path[512];
            snprintf(fdp, sizeof(fdp), "/proc/self/fd/%d", md->fd);
            ssize_t n = readlink(fdp, path, sizeof(path) - 1);
            if (n > 0) {
              ev.aux1 = fnv1a64(path, (size_t)n);
              vocab_.put(ev.aux1, path, (size_t)n);
            }
          }
          fill_proc_identity(ev, vocab_, ev.pid);
          emit(ev);
        }
        if (md->fd >= 0) close(md->fd);
        md = FAN_EVENT_NEXT(md, len);
      }
    }
    if (mi_fd >= 0) close(mi_fd);
    close(fan);
  }

 private:
  static constexpr size_t kMaxMarks = 64;
  std::vector<std::string> paths_;
  bool include_modify_ = true;
  int remark_pid_ = 0;
  bool marks_capped_ = false;
};

// ---------------------------------------------------------------------------
// MountInfoSource — trace/mount via pollable /proc/self/mountinfo diffs.
// ---------------------------------------------------------------------------

class MountInfoSource : public Source {
 public:
  MountInfoSource(size_t ring_pow2, const std::string& cfg = "")
      : Source(ring_pow2) {
    // a container's private mount ns is invisible in the host mountinfo;
    // the per-container attach passes its pid and we poll THAT process's
    // view (/proc/<pid>/mountinfo is pollable exactly like self's)
    pid_ = atoi(cfg_get(cfg, "pid", "0").c_str());
  }
  ~MountInfoSource() override { stop(); }

 protected:
  struct MountEnt {
    std::string target, source, fstype;
  };

  void run() override {
    char path[64];
    if (pid_ > 0)
      snprintf(path, sizeof(path), "/proc/%d/mountinfo", pid_);
    else
      snprintf(path, sizeof(path), "/proc/self/mountinfo");
    int fd = open(path, O_RDONLY);
    if (fd < 0) return;
    std::map<uint64_t, MountEnt> known;
    scan(fd, known);  // baseline: no events for pre-existing mounts
    struct pollfd pfd{fd, POLLERR | POLLPRI, 0};
    while (running_.load(std::memory_order_relaxed)) {
      int r = poll(&pfd, 1, 200);
      if (r <= 0) continue;
      std::map<uint64_t, MountEnt> cur;
      scan(fd, cur);
      // An EMPTY scan means the window died, not that every mount went
      // away: a per-container poller whose pid exited reads nothing (the
      // mount ns may live on in sibling containers) — ending quietly
      // beats emitting a spurious umount flood. A real mount ns always
      // has at least the root mount.
      if (cur.empty()) break;
      uint64_t ts = now_ns();
      for (auto& [id, m] : cur)
        if (!known.count(id)) push_mount(ts, m, /*umount=*/false);
      for (auto& [id, m] : known)
        if (!cur.count(id)) push_mount(ts, m, /*umount=*/true);
      known.swap(cur);
    }
    close(fd);
  }

 private:
  void push_mount(uint64_t ts, const MountEnt& m, bool umount) {
    Event ev{};
    ev.ts_ns = ts;
    ev.kind = EV_MOUNT;
    ev.aux2 = umount ? 1 : 0;
    // vocab payload: source \x1f target \x1f fstype (Python splits)
    std::string payload = m.source + '\x1f' + m.target + '\x1f' + m.fstype;
    ev.key_hash = fnv1a64(payload.data(), payload.size());
    vocab_.put(ev.key_hash, payload.data(), payload.size());
    size_t c = m.target.size() < sizeof(ev.comm) - 1 ? m.target.size()
                                                     : sizeof(ev.comm) - 1;
    memcpy(ev.comm, m.target.data(), c);
    emit(ev);
  }

  void scan(int fd, std::map<uint64_t, MountEnt>& out) {
    // shared parser (read_mountinfo) so every mountinfo consumer agrees
    // on fields + octal escaping
    std::vector<MountInfoEnt> ents;
    if (!read_mountinfo(fd, ents)) return;
    for (auto& e : ents) out[e.id] = MountEnt{e.target, e.source, e.fstype};
  }

  int pid_ = 0;
};

// One /proc pass resolving socket inodes to owning pids (shared by the
// sock_diag sources; the reference gets pid identity in-kernel from the
// calling task, a luxury the netlink window lacks).
inline void resolve_socket_inodes(const std::vector<uint64_t>& inodes,
                                  std::unordered_map<uint64_t, uint32_t>& owner) {
  std::unordered_set<uint64_t> want(inodes.begin(), inodes.end());
  DIR* proc = opendir("/proc");
  if (!proc) return;
  struct dirent* de;
  while ((de = readdir(proc)) && !want.empty()) {
    char* end;
    unsigned long pid = strtoul(de->d_name, &end, 10);
    if (*end || !pid) continue;
    char fdpath[64];
    snprintf(fdpath, sizeof(fdpath), "/proc/%lu/fd", pid);
    DIR* fds = opendir(fdpath);
    if (!fds) continue;
    struct dirent* fd;
    while ((fd = readdir(fds))) {
      char link[384], target[64];
      snprintf(link, sizeof(link), "%s/%s", fdpath, fd->d_name);
      ssize_t n = readlink(link, target, sizeof(target) - 1);
      if (n <= 9 || strncmp(target, "socket:[", 8) != 0) continue;
      target[n] = 0;
      uint64_t inode = strtoull(target + 8, nullptr, 10);
      if (want.count(inode)) {
        owner[inode] = (uint32_t)pid;
        want.erase(inode);
      }
    }
    closedir(fds);
  }
  closedir(proc);
}

// ---------------------------------------------------------------------------
// SockDiagBindSource — trace/bind via NETLINK_SOCK_DIAG dumps.
// ---------------------------------------------------------------------------

class SockDiagBindSource : public Source {
 public:
  SockDiagBindSource(size_t ring_pow2, const std::string& cfg)
      : Source(ring_pow2) {
    interval_ms_ = atoi(cfg_get(cfg, "interval_ms", "50").c_str());
    if (interval_ms_ <= 0) interval_ms_ = 50;
  }
  ~SockDiagBindSource() override { stop(); }

 protected:
  struct SockEnt {
    uint8_t family, proto;
    uint16_t port;      // host order
    uint64_t addr;      // v4: host-order u32; v6: first 8 bytes
    char addr_str[48];
  };

  void run() override {
    std::unordered_map<uint64_t, SockEnt> known;  // inode -> socket
    bool first = true;
    while (running_.load(std::memory_order_relaxed)) {
      std::unordered_map<uint64_t, SockEnt> cur;
      for (uint8_t fam : {AF_INET, AF_INET6}) {
        dump(fam, IPPROTO_TCP, 1u << 10 /*TCP_LISTEN*/, cur);
        dump(fam, IPPROTO_UDP, 0xffffffff, cur);
      }
      // Kernels without udp_diag return an empty dump; procfs covers UDP.
      scan_proc_udp("/proc/net/udp", AF_INET, cur);
      scan_proc_udp("/proc/net/udp6", AF_INET6, cur);
      if (!first) {
        std::vector<uint64_t> fresh;
        for (auto& [inode, s] : cur)
          if (!known.count(inode)) fresh.push_back(inode);
        if (!fresh.empty()) {
          // one targeted /proc pass resolves pids for all new binds
          std::unordered_map<uint64_t, uint32_t> owner;
          resolve_inodes(fresh, owner);
          uint64_t ts = now_ns();
          for (uint64_t inode : fresh) {
            const SockEnt& s = cur[inode];
            Event ev{};
            ev.ts_ns = ts;
            ev.kind = EV_BIND;
            ev.aux1 = s.addr;
            ev.aux2 = ((uint64_t)(s.family == AF_INET6 ? 1 : 0) << 24 |
                       (uint64_t)s.proto << 16 | s.port);
            auto it = owner.find(inode);
            if (it != owner.end()) {
              ev.pid = it->second;
              fill_proc_identity(ev, vocab_, ev.pid);
            }
            // aux-key: "addr:port" for display/sketch
            char key[64];
            int kn = snprintf(key, sizeof(key), "%s:%u", s.addr_str, s.port);
            uint64_t kh = fnv1a64(key, (size_t)kn);
            vocab_.put(kh, key, (size_t)kn);
            if (ev.key_hash == 0) ev.key_hash = kh;
            ev.aux1 = kh;  // addr string hash (addr itself derivable)
            emit(ev);
          }
        }
      }
      known.swap(cur);
      first = false;
      std::this_thread::sleep_for(std::chrono::milliseconds(interval_ms_));
    }
  }

 private:
  void dump(uint8_t family, uint8_t proto, uint32_t states,
            std::unordered_map<uint64_t, SockEnt>& out) {
    int sd = socket(AF_NETLINK, SOCK_RAW | SOCK_CLOEXEC, NETLINK_SOCK_DIAG);
    if (sd < 0) return;
    struct {
      struct nlmsghdr nlh;
      struct inet_diag_req_v2 req;
    } r{};
    r.nlh.nlmsg_len = sizeof(r);
    r.nlh.nlmsg_type = SOCK_DIAG_BY_FAMILY;
    r.nlh.nlmsg_flags = NLM_F_REQUEST | NLM_F_DUMP;
    r.req.sdiag_family = family;
    r.req.sdiag_protocol = proto;
    r.req.idiag_states = states;
    if (send(sd, &r, sizeof(r), 0) < 0) {
      close(sd);
      return;
    }
    char buf[32768];
    bool done = false;
    while (!done) {
      ssize_t len = recv(sd, buf, sizeof(buf), 0);
      if (len <= 0) break;
      for (struct nlmsghdr* h = (struct nlmsghdr*)buf; NLMSG_OK(h, (size_t)len);
           h = NLMSG_NEXT(h, len)) {
        if (h->nlmsg_type == NLMSG_DONE || h->nlmsg_type == NLMSG_ERROR) {
          done = true;
          break;
        }
        auto* msg = (struct inet_diag_msg*)NLMSG_DATA(h);
        SockEnt s{};
        s.family = family;
        s.proto = proto;
        s.port = ntohs(msg->id.idiag_sport);
        if (family == AF_INET) {
          uint32_t a = ntohl(msg->id.idiag_src[0]);
          s.addr = a;
          snprintf(s.addr_str, sizeof(s.addr_str), "%u.%u.%u.%u", a >> 24,
                   (a >> 16) & 0xff, (a >> 8) & 0xff, a & 0xff);
        } else {
          memcpy(&s.addr, msg->id.idiag_src, 8);
          snprintf(s.addr_str, sizeof(s.addr_str), "[%08x:%08x:%08x:%08x]",
                   ntohl(msg->id.idiag_src[0]), ntohl(msg->id.idiag_src[1]),
                   ntohl(msg->id.idiag_src[2]), ntohl(msg->id.idiag_src[3]));
        }
        out[(uint64_t)msg->idiag_inode] = s;
      }
    }
    close(sd);
  }

  void scan_proc_udp(const char* path, uint8_t family,
                     std::unordered_map<uint64_t, SockEnt>& out) {
    FILE* f = fopen(path, "r");
    if (!f) return;
    char line[512];
    if (!fgets(line, sizeof(line), f)) {  // header
      fclose(f);
      return;
    }
    while (fgets(line, sizeof(line), f)) {
      char local[128];
      unsigned long long inode = 0;
      if (sscanf(line, " %*u: %127s %*s %*x %*s %*s %*s %*u %*u %llu", local,
                 &inode) < 2 || !inode)
        continue;
      char* colon = strrchr(local, ':');
      if (!colon) continue;
      SockEnt s{};
      s.family = family;
      s.proto = IPPROTO_UDP;
      s.port = (uint16_t)strtoul(colon + 1, nullptr, 16);
      if (family == AF_INET) {
        uint32_t a = (uint32_t)strtoul(local, nullptr, 16);  // little-endian
        a = __builtin_bswap32(a);
        s.addr = a;
        snprintf(s.addr_str, sizeof(s.addr_str), "%u.%u.%u.%u", a >> 24,
                 (a >> 16) & 0xff, (a >> 8) & 0xff, a & 0xff);
      } else {
        snprintf(s.addr_str, sizeof(s.addr_str), "[%.32s]", local);
      }
      out[inode] = s;
    }
    fclose(f);
  }

  void resolve_inodes(const std::vector<uint64_t>& inodes,
                      std::unordered_map<uint64_t, uint32_t>& owner) {
    resolve_socket_inodes(inodes, owner);
  }

  int interval_ms_;
};

// ---------------------------------------------------------------------------
// TcpBytesSource — top/tcp via sock_diag INET_DIAG_INFO byte counters.
//
// The reference's tcptop.bpf.c (1-133) kprobes tcp_sendmsg/tcp_cleanup_rbuf
// and sums bytes per connection in a BPF map drained each interval
// (tracer.go:222-314). The kernel exports the same per-socket totals with
// no probes: sock_diag with ext INET_DIAG_INFO returns struct tcp_info per
// socket, whose tcpi_bytes_acked (RFC4898 tcpEStatsAppHCThruOctetsAcked ≈
// bytes sent and acked) and tcpi_bytes_received are cumulative since
// connection start (kernel >= 4.1). Dumping every interval and diffing per
// socket inode yields real SENT/RECV deltas per connection. Events:
//   key_hash  "saddr:sport->daddr:dport" (vocab)   kind EV_TCP_BYTES
//   aux1 sent-bytes delta     aux2 recv-bytes delta
//   pid/comm/mntns  socket owner, resolved once per socket via /proc
// Sockets that existed before the first dump contribute deltas only (their
// pre-existing totals are the baseline); sockets born later contribute
// everything — i.e. bytes are counted "since gadget start", the reference's
// semantics. Two limits vs the kprobe window, both documented to users:
// a connection opening AND closing within one poll tick is never seen, and
// the dump is scoped to this process's network namespace (kprobes are
// system-wide) — containers with private netns need the per-netns path.
// ---------------------------------------------------------------------------

class TcpBytesSource : public Source {
 public:
  TcpBytesSource(size_t ring_pow2, const std::string& cfg)
      : Source(ring_pow2) {
    interval_ms_ = atoi(cfg_get(cfg, "interval_ms", "500").c_str());
    if (interval_ms_ <= 0) interval_ms_ = 500;
    // The sock_diag dump is netns-scoped; a container with a private
    // netns needs its own source whose capture THREAD enters that netns
    // (setns is per-thread, the rawsock/netnsenter contract) before
    // dumping — the per-container Attacher path passes the init pid here.
    netns_pid_ = atoi(cfg_get(cfg, "netns_pid", "0").c_str());
  }
  ~TcpBytesSource() override { stop(); }

  // The window exists only when a dumped socket actually carries the byte
  // counters: a dump can answer fine on kernels whose tcp_info is shorter
  // than tcpi_bytes_received (< 4.1), and then the source would emit
  // nothing forever while claiming to be real. A loopback listen socket
  // guarantees at least one dumpable socket to length-check even on an
  // otherwise idle host.
  static bool supported() {
    int probe = socket(AF_INET, SOCK_STREAM | SOCK_CLOEXEC, 0);
    if (probe >= 0) {
      struct sockaddr_in a{};
      a.sin_family = AF_INET;
      a.sin_addr.s_addr = htonl(0x7f000001);
      if (bind(probe, (struct sockaddr*)&a, sizeof(a)) != 0 ||
          listen(probe, 1) != 0) {
        close(probe);
        probe = -1;
      }
    }
    int sd = socket(AF_NETLINK, SOCK_RAW | SOCK_CLOEXEC, NETLINK_SOCK_DIAG);
    if (sd < 0) {
      if (probe >= 0) close(probe);
      return false;
    }
    struct {
      struct nlmsghdr nlh;
      struct inet_diag_req_v2 req;
    } r{};
    r.nlh.nlmsg_len = sizeof(r);
    r.nlh.nlmsg_type = SOCK_DIAG_BY_FAMILY;
    r.nlh.nlmsg_flags = NLM_F_REQUEST | NLM_F_DUMP;
    r.req.sdiag_family = AF_INET;
    r.req.sdiag_protocol = IPPROTO_TCP;
    r.req.idiag_states = 0xffffffff;
    r.req.idiag_ext = 1u << (INET_DIAG_INFO - 1);
    bool ok = false;
    if (send(sd, &r, sizeof(r), 0) == (ssize_t)sizeof(r)) {
      char buf[65536];
      bool done = false;
      while (!done) {
        ssize_t len = recv(sd, buf, sizeof(buf), 0);
        if (len <= 0) break;
        for (struct nlmsghdr* h = (struct nlmsghdr*)buf;
             NLMSG_OK(h, (size_t)len); h = NLMSG_NEXT(h, len)) {
          if (h->nlmsg_type == NLMSG_DONE || h->nlmsg_type == NLMSG_ERROR) {
            done = true;
            break;
          }
          auto* msg = (struct inet_diag_msg*)NLMSG_DATA(h);
          int rem = (int)(h->nlmsg_len - NLMSG_LENGTH(sizeof(*msg)));
          auto* rta =
              (struct rtattr*)((char*)msg + NLMSG_ALIGN(sizeof(*msg)));
          for (; RTA_OK(rta, rem); rta = RTA_NEXT(rta, rem)) {
            if (rta->rta_type == INET_DIAG_INFO &&
                RTA_PAYLOAD(rta) >=
                    offsetof(struct tcp_info, tcpi_bytes_received) +
                        sizeof(uint64_t))
              ok = true;
          }
        }
      }
    }
    close(sd);
    if (probe >= 0) close(probe);
    return ok;
  }

 protected:
  struct ConnState {
    uint64_t acked = 0, received = 0;
    uint64_t conn_hash = 0;
    uint32_t pid = 0;
    uint8_t family = 0;
    bool seen = false;  // present in the current scan
  };

  void run() override {
    if (netns_pid_ > 0) {
      char path[64];
      snprintf(path, sizeof(path), "/proc/%d/ns/net", netns_pid_);
      int nfd = open(path, O_RDONLY | O_CLOEXEC);
      if (nfd < 0) {
        // distinguishable in agent logs: EPERM is a capability problem,
        // ENOENT means the container is simply gone
        fprintf(stderr, "igcapture: tcp-bytes netns open %s failed: %s\n",
                path, strerror(errno));
        return;
      }
      int rc = setns(nfd, CLONE_NEWNET);
      close(nfd);
      if (rc != 0) {
        fprintf(stderr,
                "igcapture: tcp-bytes setns(pid %d) failed: %s "
                "(needs CAP_SYS_ADMIN)\n", netns_pid_, strerror(errno));
        return;
      }
    }
    bool first = true;
    while (running_.load(std::memory_order_relaxed)) {
      for (auto& [inode, c] : conns_) c.seen = false;
      std::vector<uint64_t> fresh;
      bool v4_ok = dump_family(AF_INET, first, fresh);
      bool v6_ok = dump_family(AF_INET6, first, fresh);
      if (!fresh.empty()) {
        std::unordered_map<uint64_t, uint32_t> owner;
        resolve_socket_inodes(fresh, owner);
        for (uint64_t ino : fresh) {
          auto it = owner.find(ino);
          if (it != owner.end()) conns_[ino].pid = it->second;
        }
        // newborn sockets' whole history belongs to this window: emit it
        // now that the pid is known (deltas were parked in pending_)
        for (auto& [ino, delta] : pending_) {
          auto ct = conns_.find(ino);
          if (ct != conns_.end())
            push(ct->second, delta.first, delta.second);
        }
      }
      pending_.clear();
      // Closed sockets disappear from the dump; drop their state — but
      // only for families whose dump ran to NLMSG_DONE. A transiently
      // failed dump (fd exhaustion, ENOBUFS) must keep state: erasing
      // would make every live connection look newborn next tick and
      // re-emit its whole cumulative history as one interval's delta.
      // Per-family so a host whose v6 dump always errors still reaps v4.
      for (auto it = conns_.begin(); it != conns_.end();) {
        bool dumped = it->second.family == AF_INET6 ? v6_ok : v4_ok;
        it = (!it->second.seen && dumped) ? conns_.erase(it) : std::next(it);
      }
      first = false;
      int waited = 0;
      while (waited < interval_ms_ &&
             running_.load(std::memory_order_relaxed)) {
        std::this_thread::sleep_for(std::chrono::milliseconds(20));
        waited += 20;
      }
    }
  }

 private:
  // Returns true only when the dump ran to NLMSG_DONE (a partial or failed
  // dump must not be mistaken for "those sockets closed").
  bool dump_family(uint8_t family, bool first, std::vector<uint64_t>& fresh) {
    int sd = socket(AF_NETLINK, SOCK_RAW | SOCK_CLOEXEC, NETLINK_SOCK_DIAG);
    if (sd < 0) return false;
    struct {
      struct nlmsghdr nlh;
      struct inet_diag_req_v2 req;
    } r{};
    r.nlh.nlmsg_len = sizeof(r);
    r.nlh.nlmsg_type = SOCK_DIAG_BY_FAMILY;
    r.nlh.nlmsg_flags = NLM_F_REQUEST | NLM_F_DUMP;
    r.req.sdiag_family = family;
    r.req.sdiag_protocol = IPPROTO_TCP;
    r.req.idiag_states = 0xffffffff;  // every state; LISTEN skipped in parse
    r.req.idiag_ext = 1u << (INET_DIAG_INFO - 1);
    if (send(sd, &r, sizeof(r), 0) < 0) {
      close(sd);
      return false;
    }
    char buf[65536];
    bool done = false, clean = false;
    while (!done) {
      ssize_t len = recv(sd, buf, sizeof(buf), 0);
      if (len <= 0) break;
      for (struct nlmsghdr* h = (struct nlmsghdr*)buf; NLMSG_OK(h, (size_t)len);
           h = NLMSG_NEXT(h, len)) {
        if (h->nlmsg_type == NLMSG_DONE || h->nlmsg_type == NLMSG_ERROR) {
          done = true;
          clean = h->nlmsg_type == NLMSG_DONE;
          break;
        }
        parse_sock(h, family, first, fresh);
      }
    }
    close(sd);
    return clean;
  }

  void parse_sock(struct nlmsghdr* h, uint8_t family, bool first,
                  std::vector<uint64_t>& fresh) {
    auto* msg = (struct inet_diag_msg*)NLMSG_DATA(h);
    if (msg->idiag_state == 10 /*TCP_LISTEN*/ || msg->idiag_inode == 0)
      return;
    // walk the attribute list for INET_DIAG_INFO (struct tcp_info; may be
    // truncated on old kernels — require the byte counters to be present)
    int rem = (int)(h->nlmsg_len - NLMSG_LENGTH(sizeof(*msg)));
    auto* rta = (struct rtattr*)((char*)msg + NLMSG_ALIGN(sizeof(*msg)));
    const struct tcp_info* ti = nullptr;
    for (; RTA_OK(rta, rem); rta = RTA_NEXT(rta, rem)) {
      if (rta->rta_type == INET_DIAG_INFO &&
          RTA_PAYLOAD(rta) >= offsetof(struct tcp_info, tcpi_bytes_received) +
                                  sizeof(uint64_t)) {
        ti = (const struct tcp_info*)RTA_DATA(rta);
        break;
      }
    }
    if (!ti) return;
    uint64_t inode = msg->idiag_inode;
    auto it = conns_.find(inode);
    if (it == conns_.end()) {
      ConnState c;
      c.conn_hash = put_conn_key(msg, family);
      c.family = family;
      c.seen = true;
      if (first) {
        // pre-existing connection: its history is the baseline, but the
        // owner still needs resolving for later deltas
        fresh.push_back(inode);
        c.acked = ti->tcpi_bytes_acked;
        c.received = ti->tcpi_bytes_received;
      } else {
        // born inside the window: everything counts; emit after the pid
        // resolve pass (one /proc scan for all newborns, not one each)
        fresh.push_back(inode);
        if (ti->tcpi_bytes_acked || ti->tcpi_bytes_received)
          pending_[inode] = {ti->tcpi_bytes_acked, ti->tcpi_bytes_received};
        c.acked = ti->tcpi_bytes_acked;
        c.received = ti->tcpi_bytes_received;
      }
      conns_.emplace(inode, c);
      return;
    }
    ConnState& c = it->second;
    c.seen = true;
    uint64_t ds = ti->tcpi_bytes_acked >= c.acked
                      ? ti->tcpi_bytes_acked - c.acked : 0;
    uint64_t dr = ti->tcpi_bytes_received >= c.received
                      ? ti->tcpi_bytes_received - c.received : 0;
    c.acked = ti->tcpi_bytes_acked;
    c.received = ti->tcpi_bytes_received;
    if (ds || dr) push(c, ds, dr);
  }

  uint64_t put_conn_key(const struct inet_diag_msg* msg, uint8_t family) {
    char key[128];
    int kn;
    uint16_t sport = ntohs(msg->id.idiag_sport);
    uint16_t dport = ntohs(msg->id.idiag_dport);
    if (family == AF_INET) {
      uint32_t s = ntohl(msg->id.idiag_src[0]);
      uint32_t d = ntohl(msg->id.idiag_dst[0]);
      kn = snprintf(key, sizeof(key), "%u.%u.%u.%u:%u->%u.%u.%u.%u:%u",
                    s >> 24, (s >> 16) & 0xff, (s >> 8) & 0xff, s & 0xff,
                    sport, d >> 24, (d >> 16) & 0xff, (d >> 8) & 0xff,
                    d & 0xff, dport);
    } else {
      kn = snprintf(key, sizeof(key),
                    "[%08x:%08x:%08x:%08x]:%u->[%08x:%08x:%08x:%08x]:%u",
                    ntohl(msg->id.idiag_src[0]), ntohl(msg->id.idiag_src[1]),
                    ntohl(msg->id.idiag_src[2]), ntohl(msg->id.idiag_src[3]),
                    sport,
                    ntohl(msg->id.idiag_dst[0]), ntohl(msg->id.idiag_dst[1]),
                    ntohl(msg->id.idiag_dst[2]), ntohl(msg->id.idiag_dst[3]),
                    dport);
    }
    uint64_t h = fnv1a64(key, (size_t)kn);
    vocab_.put(h, key, (size_t)kn);
    return h;
  }

  void push(const ConnState& c, uint64_t sent, uint64_t received) {
    Event ev{};
    ev.ts_ns = now_ns();
    ev.kind = EV_TCP_BYTES;
    ev.aux1 = sent;
    ev.aux2 = received;
    if (c.pid) {
      ev.pid = c.pid;
      fill_proc_identity(ev, vocab_, c.pid);
    }
    ev.key_hash = c.conn_hash;  // after identity fill: the conn is the key
    emit(ev);
  }

  int interval_ms_;
  int netns_pid_ = 0;
  std::unordered_map<uint64_t, ConnState> conns_;
  std::unordered_map<uint64_t, std::pair<uint64_t, uint64_t>> pending_;
};

// ---------------------------------------------------------------------------
// KmsgOomSource — trace/oomkill via the kernel log stream.
// ---------------------------------------------------------------------------

class KmsgOomSource : public Source {
 public:
  explicit KmsgOomSource(size_t ring_pow2) : Source(ring_pow2) {}
  ~KmsgOomSource() override { stop(); }

 protected:
  void run() override {
    int fd = open("/dev/kmsg", O_RDONLY | O_NONBLOCK | O_CLOEXEC);
    if (fd < 0) return;
    lseek(fd, 0, SEEK_END);  // live events only, skip history
    struct pollfd pfd{fd, POLLIN, 0};
    // The trigger's pid is not present in any kmsg line the OOM killer
    // emits (only its comm, in "<comm> invoked oom-killer"); ppid stays 0.
    char killer_comm[32] = "";
    while (running_.load(std::memory_order_relaxed)) {
      if (poll(&pfd, 1, 100) <= 0) continue;
      char buf[2048];
      ssize_t n;
      while ((n = read(fd, buf, sizeof(buf) - 1)) > 0) {
        buf[n] = 0;
        // kmsg record: "pri,seq,ts,-;message"
        char* msg = strchr(buf, ';');
        msg = msg ? msg + 1 : buf;
        // "<comm> invoked oom-killer:" — remember the trigger
        char* inv = strstr(msg, " invoked oom-killer");
        if (inv) {
          size_t cl = (size_t)(inv - msg);
          if (cl >= sizeof(killer_comm)) cl = sizeof(killer_comm) - 1;
          memcpy(killer_comm, msg, cl);
          killer_comm[cl] = 0;
        }
        // "Out of memory: Killed process 123 (comm) total-vm:456kB, ..."
        // (also "Memory cgroup out of memory: Killed process ...")
        char* kp = strstr(msg, "Killed process ");
        if (kp) {
          unsigned pid = 0;
          char comm[64] = "";
          unsigned long long vm_kb = 0;
          sscanf(kp, "Killed process %u (%63[^)])", &pid, comm);
          char* tv = strstr(kp, "total-vm:");
          if (tv) sscanf(tv, "total-vm:%llukB", &vm_kb);
          Event ev{};
          ev.ts_ns = now_ns();
          ev.kind = EV_OOMKILL;
          ev.pid = pid;         // victim
          ev.aux1 = vm_kb / 4;  // pages (4k)
          size_t cn = strlen(comm);
          if (cn) {
            ev.key_hash = fnv1a64(comm, cn);
            vocab_.put(ev.key_hash, comm, cn);
            memcpy(ev.comm, comm,
                   cn < sizeof(ev.comm) - 1 ? cn : sizeof(ev.comm) - 1);
          }
          // aux2: trigger comm hash (vocab-resolvable)
          size_t kn = strlen(killer_comm);
          if (kn) {
            ev.aux2 = fnv1a64(killer_comm, kn);
            vocab_.put(ev.aux2, killer_comm, kn);
          }
          // victim may already be gone; mntns best-effort
          fill_mntns(ev);
          emit(ev);
        }
      }
    }
    close(fd);
  }

 private:
  static void fill_mntns(Event& ev) {
    char path[64], link[64];
    snprintf(path, sizeof(path), "/proc/%u/ns/mnt", ev.pid);
    ssize_t ln = readlink(path, link, sizeof(link) - 1);
    if (ln > 0) {
      link[ln] = 0;
      const char* lb = strchr(link, '[');
      if (lb) ev.mntns = strtoull(lb + 1, nullptr, 10);
    }
  }
};


// Shared tracefs root discovery with auto-mount. The reference's
// entrypoint remounts kernel filesystems the capture layer needs
// (entrypoint.sh bpffs remount); the tracefs analogue: when neither
// standard mount point exists, mount a private tracefs instance under
// /run — requires CAP_SYS_ADMIN, degrades to "" without it. The mount is
// left in place (like the entrypoint's bpffs) — it is a kernel view, not
// per-process state, and repeated mounts are satisfied by the cache.
inline std::string tracefs_root() {
  static std::mutex mu;
  static std::string cached;
  static bool resolved = false;
  std::lock_guard<std::mutex> g(mu);
  if (resolved) return cached;
  for (const char* p : {"/sys/kernel/tracing", "/sys/kernel/debug/tracing"}) {
    std::string ev = std::string(p) + "/events";
    if (access(ev.c_str(), R_OK) == 0) {
      cached = p;
      resolved = true;
      return cached;
    }
  }
  const char* priv = "/run/igtpu_tracefs";
  mkdir(priv, 0700);
  std::string ev = std::string(priv) + "/events";
  if (access(ev.c_str(), R_OK) == 0 ||
      mount("tracefs", priv, "tracefs", 0, nullptr) == 0) {
    if (access(ev.c_str(), R_OK) == 0) cached = priv;
  }
  resolved = true;
  return cached;
}

}  // namespace ig
#endif  // __linux__
