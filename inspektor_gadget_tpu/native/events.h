// Fixed-width wire event — the slot format of the capture ring buffer.
//
// Reference contract being replaced: per-gadget eBPF structs shipped through
// perf ring buffers (e.g. trace/exec's event struct,
// pkg/gadgets/trace/exec/tracer/bpf/execsnoop.bpf.c:41-167) and read by
// perf.Reader in Go (tracer.go:134-188). Here capture shims fill one
// fixed 64-byte slot per event; string identity (comm, filenames, qnames)
// is FNV-1a-hashed at capture time so the analytics plane works on fixed
// width keys, with a side vocab for un-hashing heavy hitters.

#pragma once
#include <cstdint>
#include <cstring>

namespace ig {

// Event kinds — one per gadget source family.
enum EventKind : uint32_t {
  EV_EXEC = 1,
  EV_EXIT = 2,
  EV_OPEN = 3,
  EV_TCP_CONNECT = 4,
  EV_TCP_ACCEPT = 5,
  EV_TCP_CLOSE = 6,
  EV_DNS = 7,
  EV_BIND = 8,
  EV_SIGNAL = 9,
  EV_MOUNT = 10,
  EV_OOMKILL = 11,
  EV_CAPABILITY = 12,
  EV_FSSLOWER = 13,
  EV_FILE_RW = 14,
  EV_BLOCK_IO = 15,
  EV_SNI = 16,
  EV_NET_GRAPH = 17,
  EV_SYSCALL = 18,  // traceloop/seccomp-style raw syscall stream
  EV_PERF_SAMPLE = 19,  // CPU sampling profiler hit (profile/cpu)
  EV_CONTAINER = 20,    // container lifecycle from the runc fanotify watch
  EV_TCP_BYTES = 21,    // per-connection interval byte deltas (top/tcp)
  EV_AUDIT = 22,        // kernel audit record (host-wide capability/seccomp)
};

// 64-byte POD slot; layout is the ring-buffer ABI shared with Python.
struct Event {
  uint64_t ts_ns;     // capture timestamp
  uint64_t key_hash;  // FNV-1a64 of the primary string key (comm/qname/path)
  uint64_t aux1;      // per-kind: saddr<<32|daddr, bytes, latency_ns, ...
  uint64_t aux2;      // per-kind: sport<<16|dport, flags, ret, signal, ...
  uint64_t mntns;     // mount-namespace id (container filter key)
  uint32_t pid;
  uint32_t ppid;
  uint32_t uid;
  uint32_t kind;      // EventKind
  char comm[8];       // key-string prefix (display fast-path; vocab has full)
};
static_assert(sizeof(Event) == 64, "Event must stay one cache line");

inline uint64_t fnv1a64(const char* s, size_t n) {
  uint64_t h = 0xCBF29CE484222325ull;
  for (size_t i = 0; i < n; i++) {
    h ^= (unsigned char)s[i];
    h *= 0x100000001B3ull;
  }
  return h;
}

}  // namespace ig
