// PerfCpuSampler — a real sampling CPU profiler via perf_event_open.
//
// Reference contract: profile/cpu attaches a perf-event sampler at 49 Hz
// with a BPF program pushing stacks into a stack map, then symbolizes
// kernel frames from /proc/kallsyms in userspace
// (pkg/gadgets/profile/cpu/tracer/tracer.go:57-58,139-200,293-402,
// profile.bpf.c:1-116). Here the same perf_event_open window is used
// directly: software CPU-clock events per CPU, PERF_SAMPLE_CALLCHAIN for
// stacks, mmap ring buffers drained by the capture thread, kernel frames
// symbolized from kallsyms, user frames attributed to their mapping via
// /proc/<pid>/maps. One EV_PERF_SAMPLE per hit; the vocab payload is the
// folded stack ("comm;frameN;...;frame0") the flamegraph output consumes.

#ifdef __linux__
#include <fcntl.h>
#include <linux/perf_event.h>
#include <poll.h>
#include <sys/ioctl.h>
#include <sys/mman.h>
#include <sys/syscall.h>
#include <unistd.h>

#include <algorithm>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

#include "ringbuf.h"

namespace ig {

class KallsymsTable {
 public:
  void load() {
    FILE* f = fopen("/proc/kallsyms", "r");
    if (!f) return;
    char line[512];
    while (fgets(line, sizeof(line), f)) {
      unsigned long long addr;
      char type;
      char name[256];
      if (sscanf(line, "%llx %c %255s", &addr, &type, name) != 3) continue;
      if (addr == 0) continue;
      syms_.push_back({addr, name});
    }
    fclose(f);
    std::sort(syms_.begin(), syms_.end(),
              [](const Sym& a, const Sym& b) { return a.addr < b.addr; });
  }

  const char* resolve(uint64_t ip) const {
    if (syms_.empty()) return nullptr;
    // last symbol with addr <= ip
    size_t lo = 0, hi = syms_.size();
    while (lo < hi) {
      size_t mid = (lo + hi) / 2;
      if (syms_[mid].addr <= ip)
        lo = mid + 1;
      else
        hi = mid;
    }
    if (lo == 0) return nullptr;
    return syms_[lo - 1].name.c_str();
  }

  bool empty() const { return syms_.empty(); }

 private:
  struct Sym {
    uint64_t addr;
    std::string name;
  };
  std::vector<Sym> syms_;
};

class PerfCpuSampler : public Source {
 public:
  PerfCpuSampler(size_t ring_pow2, const std::string& cfg) : Source(ring_pow2) {
    freq_ = atoi(cfg_get(cfg, "freq", "49").c_str());
    if (freq_ <= 0) freq_ = 49;
    target_pid_ = atoi(cfg_get(cfg, "pid", "0").c_str());
    user_only_ = cfg_get(cfg, "user", "0") == "1";
    kernel_only_ = cfg_get(cfg, "kernel", "0") == "1";
  }
  ~PerfCpuSampler() override { stop(); }

  static bool supported() {
    struct perf_event_attr pe {};
    pe.type = PERF_TYPE_SOFTWARE;
    pe.size = sizeof(pe);
    pe.config = PERF_COUNT_SW_CPU_CLOCK;
    pe.disabled = 1;
    int fd = (int)syscall(SYS_perf_event_open, &pe, 0, -1, -1, 0);
    if (fd < 0) return false;
    close(fd);
    return true;
  }

 protected:
  static constexpr size_t kPages = 16;  // data pages per CPU (ref: 64/tracer)

  struct CpuBuf {
    int fd = -1;
    void* base = nullptr;
    size_t map_len = 0;
    uint64_t tail = 0;
  };

  void run() override {
    kallsyms_.load();
    int ncpu = (int)sysconf(_SC_NPROCESSORS_ONLN);
    if (ncpu <= 0) ncpu = 1;
    long page = sysconf(_SC_PAGESIZE);
    std::vector<CpuBuf> bufs;
    std::vector<struct pollfd> pfds;
    for (int cpu = 0; cpu < ncpu; cpu++) {
      struct perf_event_attr pe {};
      pe.type = PERF_TYPE_SOFTWARE;
      pe.size = sizeof(pe);
      pe.config = PERF_COUNT_SW_CPU_CLOCK;
      pe.freq = 1;
      pe.sample_freq = (uint64_t)freq_;
      pe.sample_type = PERF_SAMPLE_IP | PERF_SAMPLE_TID | PERF_SAMPLE_TIME |
                       PERF_SAMPLE_CPU | PERF_SAMPLE_CALLCHAIN;
      pe.disabled = 1;
      pe.exclude_kernel = user_only_ ? 1 : 0;
      pe.exclude_user = kernel_only_ ? 1 : 0;
      pe.wakeup_events = 1;
      int fd = (int)syscall(SYS_perf_event_open, &pe,
                            target_pid_ > 0 ? target_pid_ : -1, cpu, -1,
                            PERF_FLAG_FD_CLOEXEC);
      if (fd < 0) continue;
      size_t len = (size_t)page * (1 + kPages);
      void* base = mmap(nullptr, len, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
      if (base == MAP_FAILED) {
        close(fd);
        continue;
      }
      ioctl(fd, PERF_EVENT_IOC_ENABLE, 0);
      bufs.push_back(CpuBuf{fd, base, len, 0});
      pfds.push_back({fd, POLLIN, 0});
    }
    if (bufs.empty()) return;
    while (running_.load(std::memory_order_relaxed)) {
      poll(pfds.data(), (nfds_t)pfds.size(), 50);
      for (auto& b : bufs) drain(b, (size_t)page);
    }
    for (auto& b : bufs) {
      ioctl(b.fd, PERF_EVENT_IOC_DISABLE, 0);
      munmap(b.base, b.map_len);
      close(b.fd);
    }
  }

 private:
  void drain(CpuBuf& b, size_t page) {
    auto* meta = (struct perf_event_mmap_page*)b.base;
    uint64_t head = __atomic_load_n(&meta->data_head, __ATOMIC_ACQUIRE);
    uint64_t tail = b.tail;
    char* data = (char*)b.base + page;
    size_t mask = page * kPages - 1;
    while (tail < head) {
      auto* hdr = (struct perf_event_header*)(data + (tail & mask));
      // copy out (records can wrap the ring edge)
      std::vector<char> rec(hdr->size);
      for (size_t i = 0; i < hdr->size; i++)
        rec[i] = data[(tail + i) & mask];
      auto* rh = (struct perf_event_header*)rec.data();
      if (rh->type == PERF_RECORD_SAMPLE) parse_sample(rec.data(), rec.size());
      if (rh->type == PERF_RECORD_LOST) {
        // struct { header; u64 id; u64 lost; }
        if (rec.size() >= sizeof(*rh) + 16)
          ring_.count_external_drops(*(uint64_t*)(rec.data() + sizeof(*rh) + 8));
      }
      tail += hdr->size;
    }
    b.tail = tail;
    __atomic_store_n(&meta->data_tail, tail, __ATOMIC_RELEASE);
  }

  void parse_sample(const char* rec, size_t len) {
    // layout per sample_type order: IP, TID(pid,tid), TIME, CPU(cpu,res),
    // CALLCHAIN(nr, ips[])
    const char* p = rec + sizeof(struct perf_event_header);
    const char* end = rec + len;
    if (p + 8 * 4 + 8 > end) return;
    uint64_t ip = *(const uint64_t*)p; p += 8;
    uint32_t pid = *(const uint32_t*)p; p += 4;
    uint32_t tid = *(const uint32_t*)p; p += 4;
    uint64_t t = *(const uint64_t*)p; p += 8;
    uint32_t cpu = *(const uint32_t*)p; p += 8;  // cpu + res
    uint64_t nr = *(const uint64_t*)p; p += 8;
    if (p + nr * 8 > end) nr = (uint64_t)(end - p) / 8;

    Event ev{};
    ev.ts_ns = t;
    ev.kind = EV_PERF_SAMPLE;
    ev.pid = pid;
    ev.ppid = tid;
    ev.aux1 = ip;
    ev.aux2 = cpu;
    fill_proc_identity(ev, vocab_, pid);
    std::string comm = ev.key_hash ? vocab_lookup_comm(ev) : "unknown";

    // fold root-first: comm;outermost;...;leaf (reference folded format,
    // tracer.go collectResult), skipping perf context markers
    std::vector<std::string> frames;
    frames.reserve(nr);
    for (uint64_t i = 0; i < nr; i++) {
      uint64_t a = ((const uint64_t*)p)[i];
      if (a >= (uint64_t)PERF_CONTEXT_MAX) continue;  // context marker
      if (a >= 0xffff000000000000ull) {
        const char* s = kallsyms_.resolve(a);
        frames.emplace_back(s ? s : "[k]?");
      } else {
        frames.push_back(user_frame(pid, a));
      }
    }
    std::string folded = comm;
    for (auto it = frames.rbegin(); it != frames.rend(); ++it) {
      folded += ';';
      folded += *it;
    }
    ev.key_hash = fnv1a64(folded.data(), folded.size());
    vocab_.put(ev.key_hash, folded.data(), folded.size());
    emit(ev);
  }

  std::string vocab_lookup_comm(const Event& ev) {
    char buf[64];
    size_t n = vocab_.get(ev.key_hash, buf, sizeof(buf));
    return std::string(buf, n);
  }

  // Attribute a user-space address to its mapping ("module+0xoff"),
  // with a per-pid cache of /proc/<pid>/maps. On a miss the maps are
  // reloaded once (exec/dlopen invalidates old ranges); the cache is
  // bounded so system-wide sampling over many pids cannot grow unbounded.
  std::string user_frame(uint32_t pid, uint64_t addr) {
    if (maps_cache_.size() > 256) maps_cache_.clear();
    auto& maps = maps_cache_[pid];
    for (int attempt = 0; attempt < 2; attempt++) {
      if (maps.empty() || attempt == 1) {
        maps.clear();
        load_maps(pid, maps);
      }
      for (const auto& m : maps) {
        if (addr >= m.lo && addr < m.hi) {
          char buf[320];
          snprintf(buf, sizeof(buf), "%s+0x%llx", m.name.c_str(),
                   (unsigned long long)(addr - m.lo));
          return buf;
        }
      }
    }
    char buf[32];
    snprintf(buf, sizeof(buf), "[u]0x%llx", (unsigned long long)addr);
    return buf;
  }

  struct MapEnt {
    uint64_t lo, hi;
    std::string name;
  };

  void load_maps(uint32_t pid, std::vector<MapEnt>& out) {
    char path[64];
    snprintf(path, sizeof(path), "/proc/%u/maps", pid);
    FILE* f = fopen(path, "r");
    if (!f) return;
    char line[512];
    while (fgets(line, sizeof(line), f)) {
      unsigned long long lo, hi;
      char perms[8], name[256] = "";
      if (sscanf(line, "%llx-%llx %7s %*s %*s %*s %255s", &lo, &hi, perms,
                 name) < 3)
        continue;
      if (perms[2] != 'x') continue;  // executable mappings only
      const char* base = strrchr(name, '/');
      out.push_back(MapEnt{lo, hi, base ? base + 1 : (name[0] ? name : "anon")});
    }
    fclose(f);
  }

  int freq_;
  int target_pid_;
  bool user_only_ = false;
  bool kernel_only_ = false;
  KallsymsTable kallsyms_;
  std::unordered_map<uint32_t, std::vector<MapEnt>> maps_cache_;
};

}  // namespace ig
#endif  // __linux__
