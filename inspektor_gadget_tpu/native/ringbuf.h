// Single-producer single-consumer lock-free ring buffer with drop-on-full
// loss accounting.
//
// Behavioral contract from the reference's transport chain: perf ring
// buffers report LostSamples (pkg/gadgets/trace/exec/tracer/tracer.go:148-151),
// the gadget service drops on a full 1024-slot buffer
// (pkg/gadget-service/service.go:160-167), and streams carry an EventLost
// marker (pkg/gadgettracermanager/stream). Same semantics here: producers
// never block; every drop is counted; the consumer sees a monotone sequence
// number so gaps are auditable end-to-end (grpc-runtime.go:312-314's seq-gap
// check is reproduced at the Python rim).

#pragma once
#include <atomic>
#include <cstdint>
#include <vector>

#include "events.h"

namespace ig {

class RingBuffer {
 public:
  explicit RingBuffer(size_t capacity_pow2)
      : cap_(capacity_pow2), mask_(capacity_pow2 - 1), slots_(capacity_pow2) {
    // capacity must be a power of two
  }

  // Producer side. Returns false (and counts a drop) when full.
  bool push(const Event& ev) {
    uint64_t head = head_.load(std::memory_order_relaxed);
    uint64_t tail = tail_.load(std::memory_order_acquire);
    if (head - tail >= cap_) {
      drops_.fetch_add(1, std::memory_order_relaxed);
      return false;
    }
    slots_[head & mask_] = ev;
    head_.store(head + 1, std::memory_order_release);
    return true;
  }

  // Consumer side: pop up to n events into out; returns count.
  size_t pop(Event* out, size_t n) {
    uint64_t tail = tail_.load(std::memory_order_relaxed);
    uint64_t head = head_.load(std::memory_order_acquire);
    size_t avail = static_cast<size_t>(head - tail);
    size_t take = avail < n ? avail : n;
    for (size_t i = 0; i < take; i++) out[i] = slots_[(tail + i) & mask_];
    tail_.store(tail + take, std::memory_order_release);
    return take;
  }

  uint64_t drops() const { return drops_.load(std::memory_order_relaxed); }

  // Account losses that happened before the ring (e.g. poll-window churn a
  // scanner provably missed) so downstream gap auditing sees them too.
  void count_external_drops(uint64_t n) {
    drops_.fetch_add(n, std::memory_order_relaxed);
  }
  uint64_t produced() const { return head_.load(std::memory_order_relaxed); }
  uint64_t consumed() const { return tail_.load(std::memory_order_relaxed); }
  size_t size() const {
    return static_cast<size_t>(head_.load(std::memory_order_acquire) -
                               tail_.load(std::memory_order_acquire));
  }
  size_t capacity() const { return cap_; }

 private:
  const size_t cap_;
  const size_t mask_;
  std::vector<Event> slots_;
  alignas(64) std::atomic<uint64_t> head_{0};
  alignas(64) std::atomic<uint64_t> tail_{0};
  alignas(64) std::atomic<uint64_t> drops_{0};
};

}  // namespace ig
