// PtraceSyscallSource — a real per-process syscall stream.
//
// Reference contract: traceloop's raw tracepoints on sys_enter/sys_exit
// feeding per-container ring buffers (pkg/gadgets/traceloop/tracer/bpf/
// traceloop.bpf.c:1-470) with userspace arg-decode tables
// (pkg/gadgets/traceloop/tracer/tracer.go:246-632). Here the kernel window
// is ptrace: PTRACE_SYSCALL stops deliver every entry/exit of the traced
// tree (children auto-attached via TRACECLONE/FORK/VFORK), registers carry
// nr/args/ret, and process_vm_readv reads string arguments. Each completed
// syscall is one EV_SYSCALL event whose vocab payload is the decoded
// "name(arg, "str", ...) = ret" line.
//
// The same stream derives three more gadget families the reference covers
// with dedicated BPF programs, because the syscalls themselves are the
// ground truth being traced:
//  - EV_SIGNAL: ptrace signal-delivery-stops (receiver side, sigsnoop's
//    exact semantics for the traced tree) + kill/tkill/tgkill exits
//    (sender side).
//  - EV_CAPABILITY: syscalls that imply a capability check (mount →
//    CAP_SYS_ADMIN, setuid → CAP_SETUID, bind(<1024) →
//    CAP_NET_BIND_SERVICE, ...) with the verdict inferred from the
//    observed outcome (-EPERM/-EACCES = deny). Ref: capable.bpf.c's
//    kprobe on cap_capable; here the check's *result* is observed.
//  - EV_FSSLOWER: read/write/openat/fsync latency measured between the
//    entry and exit stops, fd resolved to a path via /proc/<tid>/fd while
//    the tracee is stopped. Ref: fsslower.bpf.c's kprobe pairs.
//
// Tracing is opt-in per target (cmd= spawns, pid= attaches) — matching the
// reference's traceloop, which also attaches per-container rather than
// system-wide.

#ifdef __linux__
#include <elf.h>
#include <fcntl.h>
#include <signal.h>
#include <sys/ptrace.h>
#include <sys/socket.h>
#include <sys/uio.h>
#include <sys/user.h>
#include <sys/wait.h>
#include <unistd.h>

#include <cerrno>

#include <cstring>
#include <string>
#include <unordered_map>
#include <unordered_set>
#include <vector>

#include "ringbuf.h"

namespace ig {

// Generated from <asm/unistd.h> by the Makefile (arch-correct nr → name).
struct SyscallName {
  long nr;
  const char* name;
};
static const SyscallName kSyscallNames[] = {
#include "syscall_names.inc"
    {-1, nullptr},
};

// Arg decode spec, keyed by syscall name (arch-independent).
//  i=int f=fd x=hex s=tracee string o=octal S=signal a=sockaddr(fd-style)
//  p=pointer -=end
struct SysSpec {
  const char* name;
  const char* args;   // up to 6 type chars
  int8_t cap;         // implied Linux capability number, -1 = none
  int8_t fs_op;       // 0=none 1=read 2=write 3=open 4=fsync
  int8_t path_arg;    // arg index holding a path string, -1 = none
  int8_t sig_arg;     // arg index holding a signal number, -1 = none
};
static const SysSpec kSpecs[] = {
    {"read", "fpi", -1, 1, -1, -1},
    {"pread64", "fpii", -1, 1, -1, -1},
    {"readv", "fpi", -1, 1, -1, -1},
    {"write", "fpi", -1, 2, -1, -1},
    {"pwrite64", "fpii", -1, 2, -1, -1},
    {"writev", "fpi", -1, 2, -1, -1},
    {"open", "sxo", -1, 3, 0, -1},
    {"openat", "fsxo", -1, 3, 1, -1},
    {"creat", "so", -1, 3, 0, -1},
    {"close", "f", -1, 0, -1, -1},
    {"fsync", "f", -1, 4, -1, -1},
    {"fdatasync", "f", -1, 4, -1, -1},
    {"stat", "sp", -1, 0, 0, -1},
    {"lstat", "sp", -1, 0, 0, -1},
    {"fstat", "fp", -1, 0, -1, -1},
    {"newfstatat", "fspi", -1, 0, 1, -1},
    {"statx", "fsxxp", -1, 0, 1, -1},
    {"access", "si", -1, 0, 0, -1},
    {"faccessat", "fsi", -1, 0, 1, -1},
    {"faccessat2", "fsii", -1, 0, 1, -1},
    {"execve", "spp", -1, 0, 0, -1},
    {"execveat", "fspp", -1, 0, 1, -1},
    {"readlink", "spi", -1, 0, 0, -1},
    {"readlinkat", "fspi", -1, 0, 1, -1},
    {"unlink", "s", -1, 0, 0, -1},
    {"unlinkat", "fsi", -1, 0, 1, -1},
    {"mkdir", "so", -1, 0, 0, -1},
    {"mkdirat", "fso", -1, 0, 1, -1},
    {"rmdir", "s", -1, 0, 0, -1},
    {"rename", "ss", -1, 0, 0, -1},
    {"renameat2", "fsfsx", -1, 0, 1, -1},
    {"getdents64", "fpi", -1, 0, -1, -1},
    {"chdir", "s", -1, 0, 0, -1},
    {"mmap", "piiifi", -1, 0, -1, -1},
    {"munmap", "pi", -1, 0, -1, -1},
    {"mprotect", "pix", -1, 0, -1, -1},
    {"brk", "p", -1, 0, -1, -1},
    {"ioctl", "fxx", -1, 0, -1, -1},
    {"fcntl", "fix", -1, 0, -1, -1},
    {"dup", "f", -1, 0, -1, -1},
    {"dup2", "ff", -1, 0, -1, -1},
    {"dup3", "ffx", -1, 0, -1, -1},
    {"pipe2", "px", -1, 0, -1, -1},
    {"socket", "iii", -1, 0, -1, -1},
    {"bind", "fai", 10 /*NET_BIND_SERVICE, port-gated*/, 0, -1, -1},
    {"connect", "fai", -1, 0, -1, -1},
    {"accept", "fpp", -1, 0, -1, -1},
    {"accept4", "fppx", -1, 0, -1, -1},
    {"listen", "fi", -1, 0, -1, -1},
    {"sendto", "fpixai", -1, 2, -1, -1},
    {"recvfrom", "fpixpp", -1, 1, -1, -1},
    {"sendmsg", "fpx", -1, 2, -1, -1},
    {"recvmsg", "fpx", -1, 1, -1, -1},
    {"setsockopt", "fiipx", -1, 0, -1, -1},
    {"getsockopt", "fiipp", -1, 0, -1, -1},
    {"kill", "iS", 5 /*KILL*/, 0, -1, 1},
    {"tkill", "iS", 5, 0, -1, 1},
    {"tgkill", "iiS", 5, 0, -1, 2},
    {"rt_sigaction", "Spp", -1, 0, -1, -1},
    {"rt_sigprocmask", "ipp", -1, 0, -1, -1},
    {"rt_sigreturn", "", -1, 0, -1, -1},
    {"clone", "xppp", -1, 0, -1, -1},
    {"clone3", "pi", -1, 0, -1, -1},
    {"fork", "", -1, 0, -1, -1},
    {"vfork", "", -1, 0, -1, -1},
    {"wait4", "ipip", -1, 0, -1, -1},
    {"exit", "i", -1, 0, -1, -1},
    {"exit_group", "i", -1, 0, -1, -1},
    {"mount", "sssxp", 21 /*SYS_ADMIN*/, 0, 1, -1},
    {"umount2", "si", 21, 0, 0, -1},
    {"pivot_root", "ss", 21, 0, 0, -1},
    {"sethostname", "pi", 21, 0, -1, -1},
    {"setns", "fi", 21, 0, -1, -1},
    {"unshare", "x", 21, 0, -1, -1},
    {"init_module", "pis", 16 /*SYS_MODULE*/, 0, -1, -1},
    {"finit_module", "fsx", 16, 0, -1, -1},
    {"setuid", "i", 7 /*SETUID*/, 0, -1, -1},
    {"setgid", "i", 6 /*SETGID*/, 0, -1, -1},
    {"setreuid", "ii", 7, 0, -1, -1},
    {"setregid", "ii", 6, 0, -1, -1},
    {"setresuid", "iii", 7, 0, -1, -1},
    {"setresgid", "iii", 6, 0, -1, -1},
    {"chown", "sii", 0 /*CHOWN*/, 0, 0, -1},
    {"lchown", "sii", 0, 0, 0, -1},
    {"fchown", "fii", 0, 0, -1, -1},
    {"fchownat", "fsiii", 0, 0, 1, -1},
    {"chmod", "so", 3 /*FOWNER-ish; keep DAC*/, 0, 0, -1},
    {"fchmod", "fo", -1, 0, -1, -1},
    {"fchmodat", "fso", -1, 0, 1, -1},
    {"chroot", "s", 18 /*SYS_CHROOT*/, 0, 0, -1},
    {"mknod", "soi", 27 /*MKNOD*/, 0, 0, -1},
    {"mknodat", "fsoi", 27, 0, 1, -1},
    {"ptrace", "iipp", 19 /*SYS_PTRACE*/, 0, -1, -1},
    {"process_vm_readv", "ipipii", 19, 0, -1, -1},
    {"reboot", "xxxp", 22 /*SYS_BOOT*/, 0, -1, -1},
    {"swapon", "sx", 21, 0, 0, -1},
    {"setpriority", "iii", 23 /*SYS_NICE*/, 0, -1, -1},
    {"sched_setaffinity", "iip", 23, 0, -1, -1},
    {"prctl", "ixxxx", -1, 0, -1, -1},
    {"capset", "pp", 8 /*SETPCAP*/, 0, -1, -1},
    {"futex", "pixppi", -1, 0, -1, -1},
    {"nanosleep", "pp", -1, 0, -1, -1},
    {"clock_nanosleep", "iipp", -1, 0, -1, -1},
    {"getpid", "", -1, 0, -1, -1},
    {"gettid", "", -1, 0, -1, -1},
    {"getuid", "", -1, 0, -1, -1},
    {"geteuid", "", -1, 0, -1, -1},
    {"getcwd", "pi", -1, 0, -1, -1},
    {"uname", "p", -1, 0, -1, -1},
    {nullptr, nullptr, -1, 0, -1, -1},
};

static const char* kSigNames[] = {
    "0",       "SIGHUP",  "SIGINT",    "SIGQUIT", "SIGILL",  "SIGTRAP",
    "SIGABRT", "SIGBUS",  "SIGFPE",    "SIGKILL", "SIGUSR1", "SIGSEGV",
    "SIGUSR2", "SIGPIPE", "SIGALRM",   "SIGTERM", "SIGSTKFLT", "SIGCHLD",
    "SIGCONT", "SIGSTOP", "SIGTSTP",   "SIGTTIN", "SIGTTOU", "SIGURG",
    "SIGXCPU", "SIGXFSZ", "SIGVTALRM", "SIGPROF", "SIGWINCH", "SIGIO",
    "SIGPWR",  "SIGSYS"};

class PtraceSyscallSource : public Source {
 public:
  PtraceSyscallSource(size_t ring_pow2, const std::string& cfg)
      : Source(ring_pow2) {
    std::string cmd = cfg_get(cfg, "cmd");
    for (auto& a : split_str(cmd, '\x1e')) argv_.push_back(a);
    attach_pid_ = atoi(cfg_get(cfg, "pid", "0").c_str());
    min_lat_us_ = strtoull(cfg_get(cfg, "min_lat_us", "0").c_str(), nullptr, 10);
    for (const SyscallName* n = kSyscallNames; n->name; n++)
      names_[n->nr] = n->name;
    for (const SysSpec* s = kSpecs; s->name; s++) spec_by_name_[s->name] = s;
    // Decoded call lines are near-unique per call (pointers, rets); bound
    // the side table so long traces cannot grow memory without limit.
    vocab_.set_capacity(1u << 18);
  }
  ~PtraceSyscallSource() override { stop(); }

  // Exit status of the spawned command (cmd mode), -1 while running.
  int exit_status() const { return exit_status_.load(); }

 protected:
  struct TaskState {
    bool in_syscall = false;
    uint64_t entry_ts = 0;
    long nr = 0;
    uint64_t args[6] = {0};
    bool attached = false;   // first stop handled
    std::string call_prefix; // "name(decoded args" — built at ENTRY, while
                             // the argument memory is still live (execve
                             // wipes it before the exit stop)
    std::string fs_path;     // path arg decoded at entry (fsslower)
    uint16_t sock_port = 0;  // sockaddr port decoded at entry (bind)
    const SysSpec* spec = nullptr;
    const char* name = nullptr;
    char namebuf[24];
  };

#if defined(__x86_64__)
  using Regs = struct user_regs_struct;
  static long regs_nr(const Regs& r) { return (long)r.orig_rax; }
  static uint64_t regs_ret(const Regs& r) { return r.rax; }
  static void regs_args(const Regs& r, uint64_t* a) {
    a[0] = r.rdi; a[1] = r.rsi; a[2] = r.rdx;
    a[3] = r.r10; a[4] = r.r8; a[5] = r.r9;
  }
#elif defined(__aarch64__)
  using Regs = struct user_regs_struct;
  static long regs_nr(const Regs& r) { return (long)r.regs[8]; }
  static uint64_t regs_ret(const Regs& r) { return r.regs[0]; }
  static void regs_args(const Regs& r, uint64_t* a) {
    for (int i = 0; i < 6; i++) a[i] = r.regs[i];
  }
#else
#error "unsupported arch for ptrace source"
#endif

  bool get_regs(pid_t tid, Regs* r) {
    struct iovec iov{r, sizeof(*r)};
    return ptrace(PTRACE_GETREGSET, tid, (void*)NT_PRSTATUS, &iov) == 0;
  }

  void run() override {
    const long opts = PTRACE_O_TRACESYSGOOD | PTRACE_O_TRACECLONE |
                      PTRACE_O_TRACEFORK | PTRACE_O_TRACEVFORK |
                      PTRACE_O_TRACEEXEC;
    pid_t root = 0;
    if (!argv_.empty()) {
      std::vector<char*> cargv;
      for (auto& a : argv_) cargv.push_back(const_cast<char*>(a.c_str()));
      cargv.push_back(nullptr);
      root = fork();
      if (root == 0) {
        ptrace(PTRACE_TRACEME, 0, 0, 0);
        raise(SIGSTOP);
        execvp(cargv[0], cargv.data());
        _exit(127);
      }
      if (root < 0) return;
      child_ = root;
    } else if (attach_pid_ > 0) {
      root = attach_pid_;
      if (ptrace(PTRACE_ATTACH, root, 0, 0) < 0) return;
    } else {
      return;
    }
    tasks_[root] = TaskState{};
    // First stop: set inheritable options, then enter the syscall loop.
    int st;
    if (waitpid(root, &st, __WALL) < 0) return;
    ptrace(PTRACE_SETOPTIONS, root, 0, (void*)opts);
    ptrace(PTRACE_SYSCALL, root, 0, 0);

    while (running_.load(std::memory_order_relaxed)) {
      bool saw_any = false;
      // Only wait on known tracees — waitpid(-1) would steal exit statuses
      // of unrelated children of this (Python host) process. New tracees
      // are learned from PTRACE_EVENT_{CLONE,FORK,VFORK} before they run.
      std::vector<pid_t> tids;
      tids.reserve(tasks_.size());
      for (auto& [tid, _] : tasks_) tids.push_back(tid);
      for (pid_t tid : tids) {
        pid_t p = waitpid(tid, &st, __WALL | WNOHANG);
        if (p <= 0) continue;
        saw_any = true;
        handle_stop(p, st);
      }
      if (tasks_.empty()) {
        // traced tree fully exited; idle until stop()
        std::this_thread::sleep_for(std::chrono::milliseconds(20));
        continue;
      }
      if (!saw_any)
        std::this_thread::sleep_for(std::chrono::microseconds(200));
    }
    // Teardown: kill the spawned tree / detach from attached tracees.
    if (child_ > 0) {
      kill(child_, SIGKILL);
      for (auto& [tid, _] : tasks_) kill(tid, SIGKILL);
      int st2;
      waitpid(child_, &st2, __WALL | WNOHANG);
    } else {
      for (auto& [tid, _] : tasks_) {
        kill(tid, SIGSTOP);
        int st2;
        waitpid(tid, &st2, __WALL | WNOHANG);
        ptrace(PTRACE_DETACH, tid, 0, SIGCONT);
      }
    }
  }

 private:
  void handle_stop(pid_t tid, int st) {
    auto it = tasks_.find(tid);
    if (it == tasks_.end()) return;
    TaskState& t = it->second;
    if (WIFEXITED(st) || WIFSIGNALED(st)) {
      tasks_.erase(it);
      if (tid == child_)
        exit_status_.store(WIFEXITED(st) ? WEXITSTATUS(st) : 128 + WTERMSIG(st));
      return;
    }
    if (!WIFSTOPPED(st)) return;
    int sig = WSTOPSIG(st);
    int event = st >> 16;
    long cont_sig = 0;
    if (sig == (SIGTRAP | 0x80)) {
      on_syscall_stop(tid, t);
    } else if (sig == SIGTRAP && event != 0) {
      if (event == PTRACE_EVENT_CLONE || event == PTRACE_EVENT_FORK ||
          event == PTRACE_EVENT_VFORK) {
        unsigned long newtid = 0;
        if (ptrace(PTRACE_GETEVENTMSG, tid, 0, &newtid) == 0 && newtid)
          tasks_[(pid_t)newtid] = TaskState{};
      }
      // EXEC event fires BETWEEN execve's entry and exit stops — the
      // in-flight syscall state (recorded at entry, pre-wipe) must be
      // preserved so the following syscall stop is treated as the exit.
    } else if (sig == SIGSTOP && !t.attached) {
      // initial stop of an auto-attached child
    } else {
      // Genuine signal-delivery-stop → sigsnoop semantics (receiver side).
      Event ev{};
      ev.ts_ns = now_ns();
      ev.kind = EV_SIGNAL;
      ev.pid = (uint32_t)tid;
      ev.ppid = (uint32_t)tid;  // receiver
      ev.aux1 = 0;              // delivery observed
      ev.aux2 = (uint64_t)sig;
      fill_identity(ev, tid);
      emit(ev);
      cont_sig = sig;  // re-inject: observe, never swallow
    }
    t.attached = true;
    if (tasks_.count(tid))
      ptrace(PTRACE_SYSCALL, tid, 0, (void*)cont_sig);
  }

  void on_syscall_stop(pid_t tid, TaskState& t) {
    Regs regs;
    if (!get_regs(tid, &regs)) return;
    if (!t.in_syscall) {
      // ---- entry stop: record + decode everything argument-dependent ------
      t.in_syscall = true;
      t.entry_ts = now_ns();
      t.nr = regs_nr(regs);
      regs_args(regs, t.args);
      auto nit = names_.find(t.nr);
      t.name = nit != names_.end() ? nit->second : nullptr;
      if (!t.name) {
        snprintf(t.namebuf, sizeof(t.namebuf), "sys_%ld", t.nr);
        t.name = t.namebuf;
      }
      t.spec = nullptr;
      auto sit = spec_by_name_.find(t.name);
      if (sit != spec_by_name_.end()) t.spec = sit->second;
      t.call_prefix = format_args(tid, t.name, t.spec, t.args);
      t.fs_path.clear();
      t.sock_port = 0;
      if (t.spec) {
        if (t.spec->path_arg >= 0)
          t.fs_path = read_str(tid, t.args[t.spec->path_arg]);
        const char* types = t.spec->args;
        for (size_t i = 0; types[i]; i++)
          if (types[i] == 'a') t.sock_port = sockaddr_port(tid, t.args[i]);
      }
      return;
    }
    // ---- exit stop: emit --------------------------------------------------
    t.in_syscall = false;
    uint64_t ts = now_ns();
    uint64_t lat_ns = ts - t.entry_ts;
    int64_t ret = (int64_t)regs_ret(regs);
    long nr = t.nr;
    const char* name = t.name;
    const SysSpec* spec = t.spec;

    Event ev{};
    ev.ts_ns = ts;
    ev.kind = EV_SYSCALL;
    ev.pid = (uint32_t)tid;
    ev.aux1 = lat_ns;
    ev.aux2 = ((uint64_t)(uint32_t)nr << 32) | (uint32_t)(int32_t)ret;
    char retbuf[32];
    snprintf(retbuf, sizeof(retbuf), ") = %lld", (long long)ret);
    std::string line = t.call_prefix + retbuf;
    ev.key_hash = fnv1a64(line.data(), line.size());
    vocab_.put(ev.key_hash, line.data(), line.size());
    size_t cn = strlen(name);
    memcpy(ev.comm, name, cn < sizeof(ev.comm) - 1 ? cn : sizeof(ev.comm) - 1);
    ev.mntns = mntns_of(tid);
    emit(ev);

    if (!spec) return;

    // ---- derived: sender-side signals --------------------------------------
    if (spec->sig_arg >= 0) {
      Event sv{};
      sv.ts_ns = ts;
      sv.kind = EV_SIGNAL;
      sv.pid = (uint32_t)tid;                       // sender
      sv.ppid = (uint32_t)t.args[0];                // target pid
      sv.aux1 = 2;                                  // sent
      sv.aux2 = t.args[spec->sig_arg] & 0x7f;
      sv.mntns = ev.mntns;
      fill_identity(sv, tid);
      emit(sv);
    }

    // ---- derived: capability checks ----------------------------------------
    if (spec->cap >= 0) {
      bool applies = true;
      if (strcmp(spec->name, "bind") == 0)
        applies = t.sock_port != 0 && t.sock_port < 1024;
      if (applies) {
        Event cv{};
        cv.ts_ns = ts;
        cv.kind = EV_CAPABILITY;
        cv.pid = (uint32_t)tid;
        cv.aux2 = (uint64_t)spec->cap;
        cv.aux1 = (ret == -EPERM || ret == -EACCES) ? 0 : 1;  // deny : allow
        cv.mntns = ev.mntns;
        fill_identity(cv, tid);
        emit(cv);
      }
    }

    // ---- derived: slow fs ops ----------------------------------------------
    if (spec->fs_op != 0 && lat_ns / 1000 >= min_lat_us_) {
      Event fv{};
      fv.ts_ns = ts;
      fv.kind = EV_FSSLOWER;
      fv.pid = (uint32_t)tid;
      fv.aux1 = lat_ns / 1000;  // latency us
      uint64_t bytes = (spec->fs_op == 1 || spec->fs_op == 2) && ret > 0
                           ? (uint64_t)ret
                           : 0;
      fv.aux2 = ((uint64_t)spec->fs_op << 32) | (bytes & 0xffffffff);
      fv.mntns = ev.mntns;
      // file identity: path arg decoded at entry, or the fd resolved now
      // (the fd table is intact while the tracee sits in the exit stop)
      std::string path = t.fs_path;
      if (path.empty() && spec->args[0] == 'f')
        path = fd_path(tid, (int)t.args[0]);
      if (!path.empty()) {
        fv.key_hash = fnv1a64(path.data(), path.size());
        vocab_.put(fv.key_hash, path.data(), path.size());
        memcpy(fv.comm, path.data(),
               path.size() < sizeof(fv.comm) - 1 ? path.size()
                                                 : sizeof(fv.comm) - 1);
      }
      emit(fv);
    }
  }

  std::string format_args(pid_t tid, const char* name, const SysSpec* spec,
                          const uint64_t* args) {
    char buf[512];
    size_t off = (size_t)snprintf(buf, sizeof(buf), "%s(", name);
    const char* types = spec ? spec->args : "xxx";
    for (size_t i = 0; types[i] && off < sizeof(buf) - 96; i++) {
      if (i) off += (size_t)snprintf(buf + off, sizeof(buf) - off, ", ");
      uint64_t a = args[i];
      switch (types[i]) {
        case 'i':
          off += (size_t)snprintf(buf + off, sizeof(buf) - off, "%lld",
                                  (long long)(int64_t)a);
          break;
        case 'f':
          off += (size_t)snprintf(buf + off, sizeof(buf) - off, "%d", (int)a);
          break;
        case 'o':
          off += (size_t)snprintf(buf + off, sizeof(buf) - off, "0%llo",
                                  (unsigned long long)a);
          break;
        case 'S': {
          unsigned s = (unsigned)a & 0x7f;
          if (s < sizeof(kSigNames) / sizeof(kSigNames[0]))
            off += (size_t)snprintf(buf + off, sizeof(buf) - off, "%s",
                                    kSigNames[s]);
          else
            off += (size_t)snprintf(buf + off, sizeof(buf) - off, "%u", s);
          break;
        }
        case 's': {
          std::string sv = read_str(tid, a);
          off += (size_t)snprintf(buf + off, sizeof(buf) - off, "\"%s\"",
                                  sv.c_str());
          break;
        }
        case 'a': {
          uint16_t port = sockaddr_port(tid, a);
          off += (size_t)snprintf(buf + off, sizeof(buf) - off, "{port=%u}",
                                  port);
          break;
        }
        case 'p':
        case 'x':
        default:
          off += (size_t)snprintf(buf + off, sizeof(buf) - off, "0x%llx",
                                  (unsigned long long)a);
          break;
      }
    }
    return std::string(buf, off);
  }

  std::string read_str(pid_t tid, uint64_t addr) {
    if (!addr) return "NULL";
    // process_vm_readv fails the whole iovec if any byte is unmapped, and
    // argv/env strings commonly end right at a page boundary — read in
    // page-clamped chunks so a short valid string near unmapped memory
    // still decodes.
    char buf[96];
    size_t total = 0;
    while (total < sizeof(buf)) {
      uint64_t a = addr + total;
      size_t page_left = 4096 - (a & 4095);
      size_t want = sizeof(buf) - total;
      if (want > page_left) want = page_left;
      struct iovec local{buf + total, want};
      struct iovec remote{(void*)a, want};
      ssize_t n = process_vm_readv(tid, &local, 1, &remote, 1, 0);
      if (n <= 0) break;
      total += (size_t)n;
      if (memchr(buf + total - n, 0, (size_t)n)) break;  // NUL found
      if ((size_t)n < want) break;
    }
    if (total == 0) return "?";
    size_t len = strnlen(buf, total);
    std::string out;
    out.reserve(len);
    for (size_t i = 0; i < len; i++)
      out.push_back((buf[i] >= 0x20 && buf[i] < 0x7f) ? buf[i] : '.');
    if (len == total && total == sizeof(buf)) out += "...";
    return out;
  }

  uint16_t sockaddr_port(pid_t tid, uint64_t addr) {
    // sockaddr_in/in6 both keep the port in bytes 2-3, network order
    unsigned char sa[4];
    struct iovec local{sa, sizeof(sa)};
    struct iovec remote{(void*)addr, sizeof(sa)};
    if (process_vm_readv(tid, &local, 1, &remote, 1, 0) != sizeof(sa)) return 0;
    uint16_t fam = (uint16_t)(sa[0] | sa[1] << 8);
    if (fam != AF_INET && fam != AF_INET6) return 0;
    return (uint16_t)(sa[2] << 8 | sa[3]);
  }

  std::string fd_path(pid_t tid, int fd) {
    char link[64], target[256];
    snprintf(link, sizeof(link), "/proc/%d/fd/%d", tid, fd);
    ssize_t n = readlink(link, target, sizeof(target) - 1);
    if (n <= 0) return "";
    return std::string(target, (size_t)n);
  }

  uint64_t mntns_of(pid_t tid) {
    auto it = mntns_cache_.find(tid);
    if (it != mntns_cache_.end()) return it->second;
    char path[64], link[64];
    snprintf(path, sizeof(path), "/proc/%d/ns/mnt", tid);
    uint64_t ns = 0;
    ssize_t ln = readlink(path, link, sizeof(link) - 1);
    if (ln > 0) {
      link[ln] = 0;
      const char* lb = strchr(link, '[');
      if (lb) ns = strtoull(lb + 1, nullptr, 10);
    }
    mntns_cache_[tid] = ns;
    return ns;
  }

  void fill_identity(Event& ev, pid_t tid) {
    uint64_t saved = ev.key_hash;
    fill_proc_identity(ev, vocab_, (uint32_t)tid);
    if (saved) ev.key_hash = saved;
    if (!ev.mntns) ev.mntns = mntns_of(tid);
  }

  std::vector<std::string> argv_;
  pid_t attach_pid_ = 0;
  pid_t child_ = 0;
  uint64_t min_lat_us_ = 0;
  std::atomic<int> exit_status_{-1};
  std::unordered_map<pid_t, TaskState> tasks_;
  std::unordered_map<long, const char*> names_;
  std::unordered_map<std::string, const SysSpec*> spec_by_name_;
  std::unordered_map<pid_t, uint64_t> mntns_cache_;
};

}  // namespace ig
#endif  // __linux__
