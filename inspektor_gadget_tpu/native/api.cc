// C ABI for the capture layer — the cgo-bridge analogue.
//
// The reference ships events Go→client via gRPC streams
// (pkg/gadget-service/service.go RunGadget) after a cgo-free in-process hop
// from cilium/ebpf's perf reader. Here the in-process hop is this C ABI:
// Python (ctypes) owns preallocated struct-of-arrays numpy buffers and calls
// ig_source_pop_batch, which transposes ring slots directly into them —
// columnar at the boundary, zero Python-side per-event work.

#include <cstdint>
#include <cstring>
#include <mutex>
#include <unordered_map>

#include "sources.cc"
#include "packet.cc"
#include "watchers.cc"
#include "fanotify.cc"
#include "ptrace_source.cc"
#include "perf_sampler.cc"
#include "audit_source.cc"
// after ptrace_source.cc: tracefs sources share its syscall/fs-op tables
#include "tracefs_sources.cc"

using namespace ig;

namespace {

std::mutex g_mu;
std::unordered_map<uint64_t, Source*> g_sources;
uint64_t g_next_id = 1;

Source* lookup(uint64_t h) {
  std::lock_guard<std::mutex> g(g_mu);
  auto it = g_sources.find(h);
  return it == g_sources.end() ? nullptr : it->second;
}

}  // namespace

extern "C" {

// Source kinds for ig_source_create / ig_source_create_cfg.
enum {
  IG_SRC_SYNTH_EXEC = 1,
  IG_SRC_SYNTH_TCP = 2,
  IG_SRC_SYNTH_DNS = 3,
  IG_SRC_PROC_EXEC = 100,
  IG_SRC_PROC_TCP = 101,
  IG_SRC_FANOTIFY_EXEC = 102,
  IG_SRC_FANOTIFY_OPEN = 103,
  IG_SRC_MOUNTINFO = 104,
  IG_SRC_SOCK_DIAG = 105,
  IG_SRC_KMSG_OOM = 106,
  IG_SRC_PTRACE = 108,
  IG_SRC_FANOTIFY_RUNC = 109,
  IG_SRC_PERF_CPU = 110,
  IG_SRC_BLK_TRACE = 111,
  IG_SRC_TCP_BYTES = 112,
  IG_SRC_AUDIT = 113,
  IG_SRC_CAP_TRACE = 114,
  IG_SRC_FS_TRACE = 115,
  IG_SRC_SOCK_STATE = 116,
  IG_SRC_SIG_TRACE = 117,
  IG_SRC_PKT_DNS = 200,
  IG_SRC_PKT_SNI = 201,
  IG_SRC_PKT_FLOW = 202,
};

uint64_t ig_source_create(uint32_t kind, uint64_t seed, double rate,
                          uint32_t vocab, double zipf_s, uint32_t ring_pow2) {
  size_t cap = 1ull << (ring_pow2 ? ring_pow2 : 20);
  Source* s = nullptr;
  switch (kind) {
    case IG_SRC_SYNTH_EXEC:
      s = new SyntheticSource(cap, EV_EXEC, seed, rate, vocab, zipf_s);
      break;
    case IG_SRC_SYNTH_TCP:
      s = new SyntheticSource(cap, EV_TCP_CONNECT, seed, rate, vocab, zipf_s);
      break;
    case IG_SRC_SYNTH_DNS:
      s = new SyntheticSource(cap, EV_DNS, seed, rate, vocab, zipf_s);
      break;
#ifdef __linux__
    case IG_SRC_PROC_EXEC:
      s = new ProcExecSource(cap);
      break;
    case IG_SRC_PROC_TCP:
      s = new ProcTcpSource(cap);
      break;
    case IG_SRC_FANOTIFY_EXEC: {
      // watched binaries from IG_FANOTIFY_PATHS (colon-separated); defaults
      // to the usual runc locations (ref: runcfanotify runc watch)
      std::vector<std::string> paths;
      if (const char* env = getenv("IG_FANOTIFY_PATHS")) {
        std::string all(env);
        size_t pos = 0;
        while (pos != std::string::npos) {
          size_t next = all.find(':', pos);
          std::string p = all.substr(
              pos, next == std::string::npos ? next : next - pos);
          if (!p.empty()) paths.push_back(p);
          pos = next == std::string::npos ? next : next + 1;
        }
      }
      s = new FanotifyExecSource(cap, std::move(paths));
      break;
    }
    case IG_SRC_PKT_DNS:
      // seed doubles as an optional netns fd (0 = current netns) — the
      // rawsock "open in target namespace" contract
      s = new PacketSniffSource(cap, PKT_DNS, seed ? (int)seed : -1);
      break;
    case IG_SRC_PKT_SNI:
      s = new PacketSniffSource(cap, PKT_SNI, seed ? (int)seed : -1);
      break;
    case IG_SRC_PKT_FLOW:
      s = new PacketSniffSource(cap, PKT_FLOW, seed ? (int)seed : -1);
      break;
#endif
    default:
      return 0;
  }
  s->set_kind(kind);
  std::lock_guard<std::mutex> g(g_mu);
  uint64_t id = g_next_id++;
  g_sources[id] = s;
  return id;
}

// String-configured sources ("key=value\x1fkey=value" — the RewriteConstants
// analogue for sources whose config is not numeric).
uint64_t ig_source_create_cfg(uint32_t kind, const char* cfg,
                              uint32_t ring_pow2) {
  size_t cap = 1ull << (ring_pow2 ? ring_pow2 : 20);
  std::string c = cfg ? cfg : "";
  Source* s = nullptr;
#ifdef __linux__
  switch (kind) {
    case IG_SRC_FANOTIFY_OPEN:
      s = new FanotifyOpenSource(cap, c);
      break;
    case IG_SRC_MOUNTINFO:
      s = new MountInfoSource(cap, c);
      break;
    case IG_SRC_SOCK_DIAG:
      s = new SockDiagBindSource(cap, c);
      break;
    case IG_SRC_KMSG_OOM:
      s = new KmsgOomSource(cap);
      break;
    case IG_SRC_PTRACE:
      s = new PtraceSyscallSource(cap, c);
      break;
    case IG_SRC_FANOTIFY_RUNC:
      s = new FanotifyRuncSource(cap, c);
      break;
    case IG_SRC_PERF_CPU:
      s = new PerfCpuSampler(cap, c);
      break;
    case IG_SRC_BLK_TRACE:
      s = new BlkTraceSource(cap, c);
      break;
    case IG_SRC_TCP_BYTES:
      s = new TcpBytesSource(cap, c);
      break;
    case IG_SRC_AUDIT:
      s = new AuditSource(cap, c);
      break;
    case IG_SRC_CAP_TRACE:
      s = new CapTraceSource(cap, c);
      break;
    case IG_SRC_FS_TRACE:
      s = new FsTraceSource(cap, c);
      break;
    case IG_SRC_SOCK_STATE:
      s = new SockStateSource(cap, c);
      break;
    case IG_SRC_SIG_TRACE:
      s = new SignalTraceSource(cap, c);
      break;
    default:
      return 0;
  }
#else
  (void)cap;
  return 0;
#endif
  s->set_kind(kind);
  std::lock_guard<std::mutex> g(g_mu);
  uint64_t id = g_next_id++;
  g_sources[id] = s;
  return id;
}

// Enumerate all live sources with self-stats — the top/ebpf contract
// (reference pkg/gadgets/top/ebpf/tracer.go:55-418 iterates every loaded
// BPF program with runtime/run-count from kernel stats; here every live
// capture source reports thread CPU time, ring occupancy and loss
// counters). Any output pointer may be null. Returns entries written.
int64_t ig_sources_stats(uint64_t* ids, uint32_t* kinds, uint64_t* produced,
                         uint64_t* consumed, uint64_t* drops,
                         uint64_t* filtered, uint64_t* ring_len,
                         uint64_t* ring_cap, uint64_t* cpu_ns, int64_t cap) {
  if (cap <= 0) return -1;
  std::lock_guard<std::mutex> g(g_mu);  // also blocks concurrent destroy
  int64_t n = 0;
  for (auto& kv : g_sources) {
    if (n >= cap) break;
    Source* s = kv.second;
    if (ids) ids[n] = kv.first;
    if (kinds) kinds[n] = s->kind();
    if (produced) produced[n] = s->produced();
    // the ring's own tail counter — deriving it as produced-ring_len from
    // two separate loads can underflow when the producer advances between
    // the reads
    if (consumed) consumed[n] = s->consumed();
    if (drops) drops[n] = s->drops();
    if (filtered) filtered[n] = s->filtered();
    if (ring_len) ring_len[n] = s->ring_len();
    if (ring_cap) ring_cap[n] = s->ring_capacity();
    if (cpu_ns) cpu_ns[n] = s->thread_cpu_ns();
    n++;
  }
  return n;
}

// Capture-side container filter (ref: tracer-collection.go:100-134 mntns
// map). ids=null clears; n=0 with non-null ids blocks everything.
int ig_source_set_filter(uint64_t h, const uint64_t* ids, int64_t n) {
  Source* s = lookup(h);
  if (!s || n < 0) return -1;
  s->set_filter(ids, ids ? (size_t)n : 0);
  return 0;
}

uint64_t ig_source_filtered(uint64_t h) {
  Source* s = lookup(h);
  return s ? s->filtered() : 0;
}

// Exit status of a ptrace-spawned command (-1 while running, -2 not ptrace).
int ig_ptrace_exit_status(uint64_t h) {
#ifdef __linux__
  Source* s = lookup(h);
  auto* p = dynamic_cast<PtraceSyscallSource*>(s);
  return p ? p->exit_status() : -2;
#else
  return -2;
#endif
}

int ig_perf_supported() {
#ifdef __linux__
  return PerfCpuSampler::supported() ? 1 : 0;
#else
  return 0;
#endif
}

// Per-IO block window available? (tracefs block events readable)
int ig_blktrace_supported() {
#ifdef __linux__
  return BlkTraceSource::supported() ? 1 : 0;
#else
  return 0;
#endif
}

// Per-connection TCP byte counters available? (sock_diag INET_DIAG_INFO)
int ig_tcpinfo_supported() {
#ifdef __linux__
  return TcpBytesSource::supported() ? 1 : 0;
#else
  return 0;
#endif
}

// Host-wide audit window available? (NETLINK_AUDIT + READLOG multicast)
int ig_audit_supported() {
#ifdef __linux__
  return AuditSource::supported() ? 1 : 0;
#else
  return 0;
#endif
}

// cap_capable tracepoint window available? (tracefs, kernel >= 6.7)
int ig_captrace_supported() {
#ifdef __linux__
  return CapTraceSource::supported() ? 1 : 0;
#else
  return 0;
#endif
}

// raw_syscalls tracepoint window available? (host-wide fsslower)
int ig_fstrace_supported() {
#ifdef __linux__
  return FsTraceSource::supported() ? 1 : 0;
#else
  return 0;
#endif
}

// inet_sock_set_state tracepoint window available? (event-driven trace/tcp)
int ig_sockstate_supported() {
#ifdef __linux__
  return SockStateSource::supported() ? 1 : 0;
#else
  return 0;
#endif
}

// signal_generate tracepoint window available? (full sigsnoop parity)
int ig_sigtrace_supported() {
#ifdef __linux__
  return SignalTraceSource::supported() ? 1 : 0;
#else
  return 0;
#endif
}

int ig_source_start(uint64_t h) {
  Source* s = lookup(h);
  if (!s) return -1;
  s->start();
  return 0;
}

int ig_source_stop(uint64_t h) {
  Source* s = lookup(h);
  if (!s) return -1;
  s->stop();
  return 0;
}

int ig_source_destroy(uint64_t h) {
  Source* s;
  {
    std::lock_guard<std::mutex> g(g_mu);
    auto it = g_sources.find(h);
    if (it == g_sources.end()) return -1;
    s = it->second;
    g_sources.erase(it);
  }
  delete s;
  return 0;
}

// Pop up to n events as struct-of-arrays into caller buffers. Any pointer
// may be null to skip that column. Returns count popped.
int64_t ig_source_pop_batch(uint64_t h, int64_t n, uint64_t* ts,
                            uint64_t* key_hash, uint64_t* aux1, uint64_t* aux2,
                            uint64_t* mntns, uint32_t* pid, uint32_t* ppid,
                            uint32_t* uid, uint32_t* kind, char* comm /*8n*/) {
  Source* s = lookup(h);
  if (!s || n <= 0) return -1;
  static thread_local std::vector<Event> tmp;
  tmp.resize((size_t)n);
  size_t got = s->pop(tmp.data(), (size_t)n);
  for (size_t i = 0; i < got; i++) {
    const Event& e = tmp[i];
    if (ts) ts[i] = e.ts_ns;
    if (key_hash) key_hash[i] = e.key_hash;
    if (aux1) aux1[i] = e.aux1;
    if (aux2) aux2[i] = e.aux2;
    if (mntns) mntns[i] = e.mntns;
    if (pid) pid[i] = e.pid;
    if (ppid) ppid[i] = e.ppid;
    if (uid) uid[i] = e.uid;
    if (kind) kind[i] = e.kind;
    if (comm) memcpy(comm + i * 8, e.comm, 8);
  }
  return (int64_t)got;
}

// Folded SoA batch exporter — the zero-copy sketch-ingest hot path.
//
// The classic pop (ig_source_pop_batch) hands Python nine 64/32-bit
// columns which the sketch plane then folds to uint32 and re-copies into
// a staging buffer: at 100M+ ev/s the fold + copy + per-column ctypes
// bookkeeping IS the pipeline wall (BENCH_r04: host plane ~130M vs
// device plane 2.6B ev/s). This call drains the ring straight into the
// caller's pre-folded uint32 lanes — keys (xor-folded key_hash, the
// sketch key width), weights (per-event weight, 1 today; the lane exists
// so a capture shim may pre-aggregate runs of equal keys), and mntns
// (xor-folded, exact for real mount-ns inode numbers < 2^32) — so Python
// does ZERO per-event work and the lanes land directly in the pinned H2D
// staging buffer. weights/mntns may be null to skip those lanes.
int64_t ig_source_pop_folded(uint64_t h, int64_t n, uint32_t* keys,
                             uint32_t* weights, uint32_t* mntns) {
  Source* s = lookup(h);
  if (!s || n <= 0 || !keys) return -1;
  static thread_local std::vector<Event> tmp;
  tmp.resize((size_t)n);
  size_t got = s->pop(tmp.data(), (size_t)n);
  for (size_t i = 0; i < got; i++) {
    const Event& e = tmp[i];
    keys[i] = (uint32_t)((e.key_hash >> 32) ^ (e.key_hash & 0xFFFFFFFFull));
    if (weights) weights[i] = 1u;
    if (mntns)
      mntns[i] = (uint32_t)((e.mntns >> 32) ^ (e.mntns & 0xFFFFFFFFull));
  }
  return (int64_t)got;
}

// Value-lane variant of ig_source_pop_folded (quantile plane): one more
// uint32 out column carrying the per-event magnitude — latency ns or byte
// count, whatever the kind keeps in aux1 (fsslower/file-rw latency,
// block-io latency, tcp interval bytes). Kinds without a magnitude write
// 0, which the DDSketch accounts in its zero bucket instead of a
// positive latency bin. Saturating cast: aux1 past 2^32-1 (a ~4.3 s
// latency) clamps to UINT32_MAX — still inside the sketch's top bucket
// span, so the quantile read degrades gracefully instead of wrapping.
int64_t ig_source_pop_folded2(uint64_t h, int64_t n, uint32_t* keys,
                              uint32_t* weights, uint32_t* mntns,
                              uint32_t* values) {
  Source* s = lookup(h);
  if (!s || n <= 0 || !keys) return -1;
  static thread_local std::vector<Event> tmp;
  tmp.resize((size_t)n);
  size_t got = s->pop(tmp.data(), (size_t)n);
  for (size_t i = 0; i < got; i++) {
    const Event& e = tmp[i];
    keys[i] = (uint32_t)((e.key_hash >> 32) ^ (e.key_hash & 0xFFFFFFFFull));
    if (weights) weights[i] = 1u;
    if (mntns)
      mntns[i] = (uint32_t)((e.mntns >> 32) ^ (e.mntns & 0xFFFFFFFFull));
    if (values) {
      switch (e.kind) {
        case EV_FSSLOWER:
        case EV_FILE_RW:
        case EV_BLOCK_IO:
        case EV_TCP_BYTES:
          values[i] = (e.aux1 > 0xFFFFFFFFull) ? 0xFFFFFFFFu
                                               : (uint32_t)e.aux1;
          break;
        default:
          values[i] = 0u;
      }
    }
  }
  return (int64_t)got;
}

uint64_t ig_source_drops(uint64_t h) {
  Source* s = lookup(h);
  return s ? s->drops() : 0;
}

uint64_t ig_source_produced(uint64_t h) {
  Source* s = lookup(h);
  return s ? s->produced() : 0;
}

// Synchronous generation into caller buffers (bench path, synthetic only).
int64_t ig_synth_generate(uint64_t h, int64_t n, uint64_t* key_hash,
                          uint64_t* mntns, uint32_t* pid, uint32_t* uid) {
  Source* s = lookup(h);
  auto* syn = dynamic_cast<SyntheticSource*>(s);
  if (!syn || n <= 0) return -1;
  static thread_local std::vector<Event> tmp;
  tmp.resize((size_t)n);
  syn->generate(tmp.data(), (size_t)n);
  for (int64_t i = 0; i < n; i++) {
    const Event& e = tmp[i];
    if (key_hash) key_hash[i] = e.key_hash;
    if (mntns) mntns[i] = e.mntns;
    if (pid) pid[i] = e.pid;
    if (uid) uid[i] = e.uid;
  }
  return n;
}

// Folded fast path: zipf draws land as xor-folded uint32 keys directly in
// the caller's staging buffer (the sketch plane's native key width).
int64_t ig_synth_generate_folded(uint64_t h, int64_t n, uint32_t* out) {
  Source* s = lookup(h);
  auto* syn = dynamic_cast<SyntheticSource*>(s);
  if (!syn || n <= 0 || !out) return -1;
  return (int64_t)syn->generate_folded(out, (size_t)n);
}

int64_t ig_vocab_lookup(uint64_t h, uint64_t key, char* out, int64_t cap) {
  Source* s = lookup(h);
  if (!s || cap <= 0) return -1;
  return (int64_t)s->vocab().get(key, out, (size_t)cap);
}

// Batch un-hash for the display decode loop: one ctypes crossing per
// batch instead of one per row. out is n*stride bytes; lens[i] receives
// the copied length (0 = unknown key).
int64_t ig_vocab_lookup_batch(uint64_t h, const uint64_t* keys, int64_t n,
                              char* out, int64_t stride, int32_t* lens) {
  Source* s = lookup(h);
  if (!s || n <= 0 || stride <= 0 || !keys || !out || !lens) return -1;
  for (int64_t i = 0; i < n; i++) {
    lens[i] = (int32_t)s->vocab().get(keys[i], out + i * stride,
                                      (size_t)stride);
  }
  return n;
}

uint64_t ig_fnv1a64(const char* s, int64_t n) {
  return fnv1a64(s, (size_t)n);
}

}  // extern "C"

extern "C" int ig_fanotify_supported() {
#ifdef __linux__
  return ig::FanotifyExecSource::supported() ? 1 : 0;
#else
  return 0;
#endif
}

// ---------------------------------------------------------------------------
// Containers map — shared mntns → container-name table.
//
// Reference contract: pkg/gadgettracermanager/containers-map (a BPF hash
// map pinned at /sys/fs/bpf/gadget/containers mapping mntns → container
// identity so BPF programs self-enrich, containers-map/tracer.go:66,119).
// Here the table lives in the capture library; Python mirrors the
// ContainerCollection into it and capture threads or the display path
// resolve identity without crossing back into Python.
// ---------------------------------------------------------------------------

namespace {
std::mutex g_cmap_mu;
std::unordered_map<uint64_t, std::string> g_cmap;
}  // namespace

extern "C" void ig_containers_set(uint64_t mntns, const char* name,
                                  int64_t len) {
  std::lock_guard<std::mutex> g(g_cmap_mu);
  g_cmap[mntns] = std::string(name, (size_t)len);
}

extern "C" void ig_containers_remove(uint64_t mntns) {
  std::lock_guard<std::mutex> g(g_cmap_mu);
  g_cmap.erase(mntns);
}

extern "C" int64_t ig_containers_lookup(uint64_t mntns, char* out,
                                        int64_t cap) {
  std::lock_guard<std::mutex> g(g_cmap_mu);
  auto it = g_cmap.find(mntns);
  if (it == g_cmap.end() || cap <= 0) return 0;
  int64_t n = (int64_t)it->second.size() < cap ? (int64_t)it->second.size() : cap;
  memcpy(out, it->second.data(), (size_t)n);
  return n;
}

extern "C" int64_t ig_containers_count() {
  std::lock_guard<std::mutex> g(g_cmap_mu);
  return (int64_t)g_cmap.size();
}
